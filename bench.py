"""Benchmarks — headline + the BASELINE.md measurement suite.

Default (no args): prints ONE JSON line, the driver contract —
batched threshold-share verification throughput on the device backend:

  {"metric": "share_verify_throughput", "value": <shares/sec>,
   "unit": "shares/s", "vs_baseline": <speedup over per-share CPU path>}

The reference's per-epoch hot loop is N² BLS share verifications
(``honey_badger.rs:422-444``: N proposers × N senders) plus combines —
each a 2-pairing check in the ``threshold_crypto`` crate.  The headline
measures our replacement at the epoch shape (N=1024 senders × k/1024
ciphertext groups): the product-form fused check of
``harness/batching.py``, whose k-point G1 MSM runs on the windowed
Pallas device kernel (``ops/pallas_ec.py``) with one G2 MSM per sender
set and two pairings per *flush* (host-side, native C++).  vs_baseline
compares against the sequential per-share path (2 pairings each on the
native C++ host backend — the faithful stand-in for the reference's
Rust crate loop), measured on a sample in the same process.

``--suite`` additionally runs the BASELINE.md measurement configs
(SURVEY §6), one JSON line each:

  1. sim_default   — reference simulation defaults (n=10, 1000 txs)
  2. sim_batched   — same with the batched-prefetch façade
  3. coin64        — 64-node CommonCoin flip, real BLS, batched
  4. broadcast_1mb — 1 MB reliable broadcast (RS + Merkle hot path)
  5. decshares     — batched decryption-share verify throughput
  6. qhb_scale     — QueueingHoneyBadger co-simulation scaling
"""

from __future__ import annotations

import argparse
import json
import random
import time


def _emit(metric, value, unit, vs_baseline=None, **extra):
    row = {"metric": metric, "value": round(value, 3), "unit": unit}
    if vs_baseline is not None:
        row["vs_baseline"] = round(vs_baseline, 3)
    row.update(extra)
    print(json.dumps(row), flush=True)
    return row


# ---------------------------------------------------------------------------
# Headline: batched share verification on the device backend
# ---------------------------------------------------------------------------


def bench_headline(k: int = 65536, iters: int = 5):
    """The epoch-shaped product-form verification flush, BOTH paths
    measured every round (VERDICT r2 item 2 follow-through: the old
    K=1024 headline measured host Pippenger *by accident*; now the
    device leg runs explicitly with the routing band forced open, the
    shipping leg runs the measured default policy, and the JSON
    records both — so kernel improvements and routing changes are
    visible round-over-round).

    N=1024 senders × G=k/1024 ciphertext groups of REAL BLS12-381
    decryption shares — the HoneyBadger N² hot surface
    (``honey_badger.rs:422-444``) at BASELINE config-5 scale — settled
    by ONE fused product-form check (``harness/batching.py``): one
    k-point G1 MSM (windowed Pallas kernel on the device leg, native
    Pippenger on the shipping leg — host wins end-to-end on this
    tunneled host, see ``ops/backend_tpu.py``), one G2 MSM per sender
    set + 2 pairings.  Every iteration flushes a FRESH share set over
    fresh ciphertexts, so per-flush marshalling/serialization is paid
    exactly as a real epoch pays it.
    """
    from hbbft_tpu import native as NT
    from hbbft_tpu.crypto import threshold as T
    from hbbft_tpu.crypto.curve import G2_GEN
    from hbbft_tpu.harness.batching import BatchingBackend, DecObligation
    from hbbft_tpu.obs import recorder as obsrec
    from hbbft_tpu.ops import limbs as LB
    from hbbft_tpu.ops.backend_tpu import TpuBackend

    # Leg timings ride the obs recorder: spans land in the JSONL trace
    # when --trace is set, and the span's .dur replaces the ad-hoc
    # perf_counter pairs either way (a local unsinked recorder when
    # tracing is off — identical timing source, no file)
    rec = obsrec.active() or obsrec.Recorder()

    rng = random.Random(0xBEEF)
    n_nodes = min(1024, k)
    groups = max(1, k // n_nodes)
    k = n_nodes * groups
    xs = [rng.randrange(1, LB.R) for _ in range(n_nodes)]
    pk_shares = [T.PublicKeyShare(G2_GEN * x) for x in xs]
    master_pk = T.SecretKey.random(rng).public_key()

    def make_obs(tag: bytes):
        """n_nodes × groups fresh obligations (fresh ciphertexts)."""
        cts = [
            master_pk.encrypt(tag + b"-%d" % g, rng) for g in range(groups)
        ]
        obs = []
        for ct in cts:
            if NT.available():
                wires = NT.g1_mul_many(NT.g1_wire(ct.u), xs)
                shares = [
                    T.DecryptionShare(NT.g1_unwire(w, type(ct.u)))
                    for w in wires
                ]
            else:
                shares = [T.DecryptionShare(ct.u * x) for x in xs]
            obs.extend(
                DecObligation(pk_shares[i], shares[i], ct)
                for i in range(n_nodes)
            )
        return obs

    import os

    from hbbft_tpu.ops import packed_msm

    os.environ.setdefault("HBBFT_TPU_WARM", "1")  # bench may compile

    # Persistent warm-start first: a fresh process with a populated
    # disk cache deserializes the recorded shapes' executables on the
    # prewarm thread (production kicks it from TpuBackend() and hides
    # it under DKG/setup); joining it HERE keeps the cold-flush row
    # measuring the flush itself rather than the load race.
    t0 = time.perf_counter()
    _pw = packed_msm.start_background_prewarm()
    if _pw is not None:
        _pw.join()
    prewarm_s = time.perf_counter() - t0

    # Leg order (r5): the two forced single-engine legs run FIRST and
    # their medians are fed into the adaptive controller
    # (packed_msm.seed_rates) before the shipping leg runs — the r4
    # capture measured exactly the rates the controller needed and
    # threw them away (VERDICT r4 missing #1), so the shipping flush
    # started each round at a stale split.  The warm-up flush is now
    # TIMED as the capture's cold row (``flush_cold_s``): it pays
    # whatever the prewarm could not hide — compiles on a virgin
    # cache, nothing on a warm-started one — so cold vs warm startup
    # is a measured pair instead of a footnote.
    with rec.span("bench.flush", leg="cold", k=k) as sp:
        cold_be = BatchingBackend(inner=TpuBackend())
        cold_be.prefetch(make_obs(b"warm"))
    flush_cold_s = sp.dur
    cold_phases = {
        name: round(v, 3)
        for name, v in (
            getattr(cold_be, "last_flush_phases", None) or {}
        ).items()
    }

    # host leg: band forced shut so native host Pippenger runs the
    # same flushes — the r3 shipping configuration, kept measured so
    # the routing decision stays evidence-backed round over round
    host_inner = TpuBackend()
    host_inner.G1_DEVICE_MIN = 1 << 62
    host_inner.G1_DEVICE_MAX = 1 << 62
    host_dts = []
    for i in range(iters):
        obs = make_obs(b"host-%d" % i)
        be = BatchingBackend(inner=host_inner)
        with rec.span("bench.flush", leg="host", i=i, k=k) as sp:
            be.prefetch(obs)
        host_dts.append(sp.dur)
        assert be.stats.fallback_items == 0
        assert all(
            be.verify_dec_share(o.pk_share, o.share, o.ciphertext)
            for o in obs
        )

    # device-only leg: fraction forced to 1.0 so the pure device path
    # is measured every round (the shipping leg is a hybrid; this row
    # is the one that validates the routing-band decision)
    dev_dts = []
    prev_frac = os.environ.get("HBBFT_TPU_DEVICE_FRACTION")
    os.environ["HBBFT_TPU_DEVICE_FRACTION"] = "1"
    try:
        for i in range(iters):
            obs = make_obs(b"dev-%d" % i)
            be = BatchingBackend(inner=TpuBackend())
            with rec.span("bench.flush", leg="device", i=i, k=k) as sp:
                be.prefetch(obs)
            dev_dts.append(sp.dur)
            assert be.stats.fallback_items == 0
            assert all(
                be.verify_dec_share(o.pk_share, o.share, o.ciphertext)
                for o in obs
            )
    finally:
        if prev_frac is None:
            os.environ.pop("HBBFT_TPU_DEVICE_FRACTION", None)
        else:
            os.environ["HBBFT_TPU_DEVICE_FRACTION"] = prev_frac
    # the shared tunnel host shows ~1.5x run-to-run variance; the
    # median flush is the robust captured value, min/max recorded
    import statistics

    host_dt = statistics.median(host_dts)
    dev_dt = statistics.median(dev_dts)

    # feed the forced-leg medians into the adaptive controller: these
    # are exact single-engine rates at exactly the shipping shape
    packed_msm.seed_rates(n_nodes, groups, d=k / dev_dt, h=k / host_dt)

    # shipping leg LAST: the default routing policy — the adaptive
    # hybrid split (packed_msm._split_plan / _adapt), starting from
    # the engine rates measured seconds ago and re-solving from the
    # waiter-thread device-wall stamp every flush
    ship_inner = TpuBackend()
    ship_dts = []
    phase_samples = []
    for i in range(iters):
        obs = make_obs(b"ship-%d" % i)
        be = BatchingBackend(inner=ship_inner)
        with rec.span("bench.flush", leg="ship", i=i, k=k) as sp:
            be.prefetch(obs)
        ship_dts.append(sp.dur)
        assert be.stats.fallback_items == 0
        assert all(
            be.verify_dec_share(o.pk_share, o.share, o.ciphertext)
            for o in obs
        )
        ph = getattr(be, "last_flush_phases", None)
        if ph:
            phase_samples.append(dict(ph))
    ship_dt = statistics.median(ship_dts)

    # per-phase p50/p95 across ALL warm iterations (the r05 capture
    # kept only the final flush's walls, so a one-off straggler phase
    # was indistinguishable from a systematic wall); every sample is
    # also on the trace's flush events when --trace is set
    def _pct(vals, q):
        vals = sorted(vals)
        return vals[min(len(vals) - 1, int(round(q * (len(vals) - 1))))]

    ship_phases = {
        name: {
            "p50": round(statistics.median(vals), 3),
            "p95": round(_pct(vals, 0.95), 3),
        }
        for name in sorted({n for ph in phase_samples for n in ph})
        for vals in ([ph[name] for ph in phase_samples if name in ph],)
    }

    # vs_baseline denominator: the sequential per-share path over a
    # pinned ≥64-share sample (the r4 8-share sample on a loaded core
    # swung the ratio 124–197× across captures — VERDICT r4 next-6)
    sample = min(64, len(obs))
    ob0 = obs[:sample]
    with rec.span("bench.cpu_sample", n=sample) as sp:
        for o in ob0:
            assert o.pk_share.verify_decryption_share(o.share, o.ciphertext)
    cpu_rate = sample / sp.dur
    rate = k / ship_dt
    st = packed_msm._rho_state().get("%d:%d" % (n_nodes, groups))
    ctl = st if isinstance(st, dict) else {}

    return _emit(
        "share_verify_throughput",
        rate,
        "shares/s",
        vs_baseline=rate / cpu_rate,
        nodes=n_nodes,
        groups=groups,
        ship_rho=round(packed_msm.learned_fraction(n_nodes, groups), 3),
        flush_s=round(ship_dt, 2),
        flush_min_s=round(min(ship_dts), 2),
        flush_max_s=round(max(ship_dts), 2),
        flush_cold_s=round(flush_cold_s, 2),
        # cold÷warm: the startup tax in flush units.  With a primed
        # ``.palexe`` cache this should sit near 1 (the acceptance band
        # is ≤3×); a virgin cache pays the compiles here instead of in
        # an epoch.  ``cold_phases`` localizes whatever tax remains.
        cold_warm_ratio=round(flush_cold_s / ship_dt, 2),
        cold_phases=cold_phases,
        prewarm_s=round(prewarm_s, 2),
        device_flush_s=round(dev_dt, 2),
        device_rate=round(k / dev_dt, 1),
        host_flush_s=round(host_dt, 2),
        host_rate=round(k / host_dt, 1),
        cpu_rate=round(cpu_rate, 1),
        ship_phases=ship_phases,
        # controller state in force at capture end: engine-rate EMAs
        # (d = uncompressed wire, dc = compressed wire, h = host) —
        # the compressed-transfer flip ships whichever of d/dc
        # measures faster (VERDICT r4 next-8)
        ctl_d=round(ctl.get("d") or 0.0, 1),
        ctl_dc=round(ctl.get("dc") or 0.0, 1),
        ctl_h=round(ctl.get("h") or 0.0, 1),
    )


# ---------------------------------------------------------------------------
# Cold-start probe (--cold): one fresh-process first flush, traced
# ---------------------------------------------------------------------------


def bench_cold(k: int = 4096):
    """The FIRST flush of THIS process, timed under a compile-event
    trace — the row ``scripts/bench_cold.sh`` captures twice against
    one ``HBBFT_TPU_EXEC_CACHE`` dir: once virgin (pays the compiles,
    writes every ``.palexe``) and once primed (the prewarm plan
    preloads them all and the flush must log ZERO ``compile`` events).
    Emits one JSON row: total flush wall, per-phase walls, the prewarm
    join time, and the compile-event count + total compile seconds.

    The device leg is forced (``G1_DEVICE_MIN = 1``; pair with
    ``HBBFT_TPU_DEVICE_FRACTION=1`` to suppress the host split) so the
    row measures the device path's cold wall, not the routing guard's
    host fallback.  Obligation generation runs outside the timed span.
    """
    from hbbft_tpu import native as NT
    from hbbft_tpu.crypto import threshold as T
    from hbbft_tpu.crypto.curve import G2_GEN
    from hbbft_tpu.harness.batching import BatchingBackend, DecObligation
    from hbbft_tpu.obs import recorder as obsrec
    from hbbft_tpu.ops import limbs as LB
    from hbbft_tpu.ops import packed_msm
    from hbbft_tpu.ops.backend_tpu import TpuBackend

    rec = obsrec.active() or obsrec.enable()

    rng = random.Random(0xC01D)
    n_nodes = min(1024, k)
    groups = max(1, k // n_nodes)
    k = n_nodes * groups
    xs = [rng.randrange(1, LB.R) for _ in range(n_nodes)]
    pk_shares = [T.PublicKeyShare(G2_GEN * x) for x in xs]
    master_pk = T.SecretKey.random(rng).public_key()
    t_gen = time.perf_counter()
    cts = [master_pk.encrypt(b"cold-%d" % g, rng) for g in range(groups)]
    obs = []
    for ct in cts:
        if NT.available():
            wires = NT.g1_mul_many(NT.g1_wire(ct.u), xs)
            shares = [
                T.DecryptionShare(NT.g1_unwire(w, type(ct.u))) for w in wires
            ]
        else:
            shares = [T.DecryptionShare(ct.u * x) for x in xs]
        obs.extend(
            DecObligation(pk_shares[i], shares[i], ct)
            for i in range(n_nodes)
        )
    gen_s = time.perf_counter() - t_gen

    # join the persistent-cache prewarm BEFORE the timed flush, exactly
    # as production hides it under DKG/setup — on a primed cache this
    # is where every planned executable deserializes
    t0 = time.perf_counter()
    pw = packed_msm.start_background_prewarm()
    if pw is not None:
        pw.join()
    prewarm_s = time.perf_counter() - t0

    inner = TpuBackend()
    inner.G1_DEVICE_MIN = 1
    be = BatchingBackend(inner=inner)
    with rec.span("bench.flush", leg="cold", k=k) as sp:
        be.prefetch(obs)
    sample = obs[:: max(1, len(obs) // 64)]
    assert all(
        be.verify_dec_share(o.pk_share, o.share, o.ciphertext)
        for o in sample
    )
    compiles = [e for e in rec.events if e.get("ev") == "compile"]
    return _emit(
        "cold_flush",
        sp.dur,
        "s",
        k=k,
        engine=packed_msm._product_engine(),
        prewarm_s=round(prewarm_s, 3),
        gen_s=round(gen_s, 3),
        compile_events=len(compiles),
        compile_s=round(sum(e.get("wall") or 0.0 for e in compiles), 3),
        phases={
            name: round(v, 3)
            for name, v in (
                getattr(be, "last_flush_phases", None) or {}
            ).items()
        },
    )


# ---------------------------------------------------------------------------
# Multi-chip mesh headline (--mesh): per-device-count scaling rows
# ---------------------------------------------------------------------------


def bench_mesh_child(n_devices: int, k: int = 512, iters: int = 3):
    """ONE per-device-count row, measured on the REAL flush path (not
    the ``__graft_entry__`` dryrun): ``BatchingBackend.prefetch`` over
    fresh BLS decryption obligations, with the product-MSM sharded
    across ``n_devices`` by the mesh engine (``parallel/mesh.py``) for
    ``n_devices > 1`` and the default single-device routing at 1.

    Runs inside a child process whose device count was fixed before
    jax came up (``bench_mesh`` sets the env; same pattern as
    ``__graft_entry__._dryrun_child``).  The flush's ``device_op``
    events are captured to a trace and the engines that ACTUALLY ran
    are reported in the row — a mesh row that silently fell back to
    host would be a lie the trajectory files can't detect."""
    import os
    import statistics
    import tempfile

    import jax

    if os.environ.get("JAX_PLATFORMS") == "cpu":
        # re-assert in config: a sitecustomize TPU plugin can outrank
        # the env var (see __graft_entry__._dryrun_child)
        jax.config.update("jax_platforms", "cpu")
        try:
            jax.config.update("jax_num_cpu_devices", int(n_devices))
        except Exception:
            pass
    if len(jax.devices()) < n_devices:
        raise RuntimeError(
            f"mesh child: need {n_devices} devices, have "
            f"{len(jax.devices())} ({jax.default_backend()!r})"
        )

    from hbbft_tpu import native as NT
    from hbbft_tpu.crypto import threshold as T
    from hbbft_tpu.crypto.curve import G2_GEN
    from hbbft_tpu.harness.batching import BatchingBackend, DecObligation
    from hbbft_tpu.obs import recorder as obsrec
    from hbbft_tpu.ops import limbs as LB
    from hbbft_tpu.ops.backend_tpu import TpuBackend

    rng = random.Random(0x3E5A)
    n_nodes = min(1024, k)
    groups = max(1, k // n_nodes)
    k = n_nodes * groups
    xs = [rng.randrange(1, LB.R) for _ in range(n_nodes)]
    pk_shares = [T.PublicKeyShare(G2_GEN * x) for x in xs]
    master_pk = T.SecretKey.random(rng).public_key()

    def make_obs(tag: bytes):
        cts = [
            master_pk.encrypt(tag + b"-%d" % g, rng) for g in range(groups)
        ]
        obs = []
        for ct in cts:
            if NT.available():
                wires = NT.g1_mul_many(NT.g1_wire(ct.u), xs)
                shares = [
                    T.DecryptionShare(NT.g1_unwire(w, type(ct.u)))
                    for w in wires
                ]
            else:
                shares = [T.DecryptionShare(ct.u * x) for x in xs]
            obs.extend(
                DecObligation(pk_shares[i], shares[i], ct)
                for i in range(n_nodes)
            )
        return obs

    inner = TpuBackend()  # mesh resolved from HBBFT_TPU_MESH
    inner.G1_MESH_MIN = k  # open the gate for exactly the flush shape
    if n_devices == 1:
        # open the single-device gate too: the scaling baseline must
        # be the same engine family as the mesh rows (device bit-scan
        # MSM), not the host-arithmetic fallback the small-k routing
        # band would pick
        inner.G1_DEVICE_MIN = min(inner.G1_DEVICE_MIN, k)
    mesh_on = inner._mesh_flush_active()
    if n_devices > 1 and not mesh_on:
        raise RuntimeError(
            "mesh child: %d devices requested but the mesh engine is "
            "inactive (HBBFT_TPU_MESH / HBBFT_TPU_MESH_CPU unset?)"
            % n_devices
        )

    trace = tempfile.NamedTemporaryFile(
        suffix=".jsonl", delete=False, mode="w"
    )
    trace.close()
    obsrec.enable(trace.name)
    flush_dts, phase_samples = [], []
    try:
        # one untimed warmup flush: the first iteration pays the XLA
        # compile (~minutes cold on the CPU bit-scan engine) and would
        # swamp the warm steady state the scaling row is about; its
        # wall is reported separately as warm_s
        t0 = time.perf_counter()
        BatchingBackend(inner=inner).prefetch(make_obs(b"mesh-warm"))
        warm_s = time.perf_counter() - t0
        for i in range(iters):
            obs_l = make_obs(b"mesh-%d" % i)
            be = BatchingBackend(inner=inner)
            t0 = time.perf_counter()
            be.prefetch(obs_l)
            flush_dts.append(time.perf_counter() - t0)
            assert be.stats.fallback_items == 0
            assert all(
                be.verify_dec_share(o.pk_share, o.share, o.ciphertext)
                for o in obs_l
            )
            ph = getattr(be, "last_flush_phases", None)
            if ph:
                phase_samples.append(
                    {kk: round(vv, 4) for kk, vv in ph.items()}
                )
    finally:
        obsrec.disable()
    engines = set()
    with open(trace.name) as fh:
        for line in fh:
            try:
                row = json.loads(line)
            except ValueError:
                continue
            if row.get("ev") == "device_op" and row.get(
                "op", ""
            ).startswith("g1_msm"):
                # collect per-group g1_msm engines too: at 1 device the
                # fused product wrapper is host but the MSMs themselves
                # run on the device engine — the row must say so
                engines.add(row.get("engine"))
    os.unlink(trace.name)
    if n_devices > 1 and "mesh" not in engines:
        raise RuntimeError(
            "mesh child: flush never routed to the mesh engine "
            f"(saw {sorted(engines)}) — the row would be a lie"
        )

    flush_s = statistics.median(flush_dts)
    return _emit(
        "share_verify_throughput",
        k / flush_s,
        "shares/s",
        mesh_devices=n_devices,
        engines=sorted(e for e in engines if e),
        nodes=n_nodes,
        groups=groups,
        flush_s=round(flush_s, 3),
        flush_min_s=round(min(flush_dts), 3),
        flush_max_s=round(max(flush_dts), 3),
        warm_s=round(warm_s, 3),
        phases=phase_samples[-1] if phase_samples else None,
    )


def bench_mesh(k: int = 512, iters: int = 3, devices=(1, 2, 4, 8)):
    """The MULTICHIP-style headline: ``share_verify_throughput`` per
    device count from the REAL flush path, plus one scaling-summary
    row.  Each count runs in its own child process (a JAX backend's
    device count is fixed once initialized, so only a fresh interpreter
    can host each mesh width — the ``__graft_entry__`` respawn
    pattern); on a host without that many real chips the children run
    a virtual CPU mesh (``HBBFT_TPU_MESH_CPU=1``), which validates the
    sharded program and transfers but SERIALIZES shard compute on one
    core — per-device speedup there is measured, not assumed, and the
    summary row carries the host context so trajectory readers can
    tell the regimes apart."""
    import os
    import re
    import subprocess
    import sys

    import jax

    here = os.path.dirname(os.path.abspath(__file__))
    rows = {}
    virtual = False
    for d in devices:
        env = dict(os.environ)
        env["HBBFT_TPU_MESH"] = str(d) if d > 1 else "0"
        # force the full device share: the scaling row measures the
        # mesh engine itself, not the host/device hybrid split
        env["HBBFT_TPU_DEVICE_FRACTION"] = "1"
        use_cpu = jax.default_backend() != "tpu" or len(jax.devices()) < d
        if use_cpu:
            virtual = True
            env["JAX_PLATFORMS"] = "cpu"
            env["HBBFT_TPU_MESH_CPU"] = "1"
            flags = re.sub(
                r"--xla_force_host_platform_device_count=\d+",
                "",
                env.get("XLA_FLAGS", ""),
            )
            env["XLA_FLAGS"] = (
                flags + f" --xla_force_host_platform_device_count={d}"
            ).strip()
        res = subprocess.run(
            [
                sys.executable,
                os.path.join(here, "bench.py"),
                "--mesh-child",
                str(d),
                "--k",
                str(k),
                "--iters",
                str(iters),
            ],
            cwd=here,
            env=env,
            capture_output=True,
            text=True,
        )
        sys.stdout.write(res.stdout)
        sys.stdout.flush()
        if res.returncode != 0:
            sys.stderr.write(res.stderr)
            raise RuntimeError(
                f"mesh child (devices={d}) failed rc={res.returncode}"
            )
        last = [
            ln for ln in res.stdout.splitlines() if ln.startswith("{")
        ][-1]
        rows[d] = json.loads(last)
    d0, d1 = min(rows), max(rows)
    speedup = rows[d1]["value"] / rows[d0]["value"]
    return _emit(
        "mesh_share_verify_scaling",
        speedup,
        "x",
        devices=sorted(rows),
        rates={str(d): rows[d]["value"] for d in sorted(rows)},
        flush_s={str(d): rows[d].get("flush_s") for d in sorted(rows)},
        k=k,
        virtual_cpu_mesh=virtual,
        host_cores=os.cpu_count(),
    )


# ---------------------------------------------------------------------------
# Suite configs (BASELINE.md / SURVEY §6)
# ---------------------------------------------------------------------------


def bench_sim_default(batched: bool = False):
    """Config 1: the reference simulation defaults
    (``examples/simulation.rs:33-52``)."""
    from hbbft_tpu.harness.batching import BatchingBackend
    from hbbft_tpu.harness.simulation import simulate_queueing_honey_badger

    ops = BatchingBackend() if batched else None
    stats, wall, sim = simulate_queueing_honey_badger(
        num_nodes=10,
        num_txs=1000,
        batch_size=100,
        rng=random.Random(0),
        ops=ops,
    )
    epochs = len(stats.rows)
    return _emit(
        "sim_batched_epochs_per_s" if batched else "sim_default_epochs_per_s",
        epochs / wall,
        "epochs/s",
        epochs=epochs,
        wall_s=round(wall, 2),
        sim_s=round(sim, 2),
    )


def bench_sim_real_pair(nodes: int = 4, txs: int = 24, batch: int = 12):
    """The batching façade on the virtual-time simulator with REAL
    BLS12-381 (VERDICT r1 weak #3 follow-up): under mock crypto the
    façade now steps aside entirely (``SimNetwork._collect_obs``), and
    with real crypto the prefetch flush must win — this config measures
    both paths in one process and reports the batched rate with
    vs_baseline = batched/sequential."""
    from hbbft_tpu.harness.batching import BatchingBackend
    from hbbft_tpu.harness.simulation import simulate_queueing_honey_badger

    def run(ops):
        stats, wall, _ = simulate_queueing_honey_badger(
            num_nodes=nodes,
            num_txs=txs,
            batch_size=batch,
            rng=random.Random(0),
            mock_crypto=False,
            ops=ops,
        )
        return len(stats.rows) / wall

    seq = run(None)
    batched = run(BatchingBackend())
    return _emit(
        "sim_real_batched_epochs_per_s",
        batched,
        "epochs/s",
        vs_baseline=batched / seq,
        seq_epochs_per_s=round(seq, 2),
        nodes=nodes,
    )


def bench_coin64(flips: int = 3, nodes: int = 64):
    """Config 2: 64-node common coin on real BLS12-381.  The batched
    path amortizes the network-wide N² share verifies into prefetch
    flushes; the baseline is the same run without the façade."""
    from hbbft_tpu.harness.batching import BatchingBackend
    from hbbft_tpu.harness.network import (
        MessageScheduler,
        SilentAdversary,
        TestNetwork,
    )
    from hbbft_tpu.protocols.common_coin import CommonCoin

    def one_flip(nonce, ops):
        """Returns seconds for the flip itself (network construction /
        key dealing excluded — it is identical for both paths)."""
        rng = random.Random(nonce)
        net = TestNetwork(
            nodes,
            0,
            lambda adv: SilentAdversary(
                MessageScheduler(MessageScheduler.RANDOM, rng)
            ),
            lambda ni: CommonCoin(ni, nonce),
            rng,
            mock_crypto=False,
            ops=ops,
        )
        t0 = time.perf_counter()
        net.input_all(None)
        net.step_until(
            lambda: all(n.terminated() for n in net.nodes.values())
        )
        dt = time.perf_counter() - t0
        vals = {n.outputs[0] for n in net.nodes.values()}
        assert len(vals) == 1, "coin values diverged"
        return dt

    be = BatchingBackend()
    batched_dt = sum(
        one_flip(b"bench-flip-%d" % i, be) for i in range(flips)
    ) / flips
    base_dt = one_flip(b"bench-flip-base", None)
    return _emit(
        "coin64_flips_per_s",
        1.0 / batched_dt,
        "flips/s",
        vs_baseline=base_dt / batched_dt,
        seq_s_per_flip=round(base_dt, 2),
    )


def bench_coin1024(nodes: int = 1024, flips: int = 2):
    """North-star scale (BASELINE target: N=1024 validators): the
    vectorized co-simulation flips a real-BLS common coin across 1024
    validators with ONE batched verification flush per flip — the
    sequential path would need N² ≈ 1M pairing checks per flip
    (~1 hour network-wide; extrapolated below from a measured sample)."""
    import random as _r

    from hbbft_tpu.crypto.threshold import PublicKeyShare, SignatureShare
    from hbbft_tpu.harness.vectorized import VectorizedCoinSim

    rng = _r.Random(0xC01)
    t0 = time.perf_counter()
    sim = VectorizedCoinSim(nodes, rng, mock=False)
    # warm the per-index public-key-share cache (setup, not flip cost)
    for nid in range(nodes):
        sim.netinfos[0].public_key_share(nid)
    setup_s = time.perf_counter() - t0

    sim.flip(b"warm")  # compile/warm whatever the backend uses
    t0 = time.perf_counter()
    for i in range(flips):
        r = sim.flip(b"bench-%d" % i)
        assert len(r.outputs) == nodes
    dt = (time.perf_counter() - t0) / flips

    # sequential extrapolation from a measured per-share sample
    ni = sim.netinfos[0]
    share = ni.secret_key_share.sign(b"sample")
    pk = ni.public_key_share(0)
    t0 = time.perf_counter()
    for _ in range(8):
        assert pk.verify_signature_share(share, b"sample")
    per_verify = (time.perf_counter() - t0) / 8
    seq_est = nodes * nodes * per_verify
    return _emit(
        "coin1024_flips_per_s",
        1.0 / dt,
        "flips/s",
        vs_baseline=seq_est / dt,
        seq_extrapolated_s_per_flip=round(seq_est, 1),
        setup_s=round(setup_s, 1),
        nodes=nodes,
    )


def bench_broadcast_vec(nodes: int = 256):
    """Broadcast through the vectorized round at N=256 — the GF(2⁸)
    erasure-coding design maximum (the reference's RS crate has the
    same 256-shard cap) — one encode, N proof checks, one decode, vs
    the measured sequential network run at the same size."""
    import random as _r

    from hbbft_tpu.harness.vectorized import VectorizedBroadcastRound

    rng = _r.Random(0xBC)
    payload = rng.randbytes(1 << 20)
    sim = VectorizedBroadcastRound(nodes, rng)
    r = sim.broadcast(payload)  # warm (table builds etc.)
    t0 = time.perf_counter()
    r = sim.broadcast(payload)
    dt = time.perf_counter() - t0
    assert r.value == payload
    # baseline: the sequential network run at the same size, measured
    # in-process on this host/backend
    seq = bench_broadcast_1mb(nodes=nodes)
    return _emit(
        "broadcast_vec_s",
        dt,
        "s",
        vs_baseline=seq["value"] / dt,
        seq_measured_s=seq["value"],
        nodes=nodes,
    )


def bench_hb_dec_round(nodes: int = 1024, proposers: int = 256):
    """BASELINE config 4 at the real epoch shape (VERDICT r2 item 7):
    one HoneyBadger decryption phase, N=1024 senders × P=256 proposers
    on real BLS12-381 — N·P = 262k shares settled by the product-form
    fused check (one device G1 MSM + ONE host G2 MSM + 2 pairings,
    ``harness/batching.py``) and P cached-Lagrange native combines.

    Share *generation* (each node's local signing work — N·P here but
    P-per-node, embarrassingly parallel, in a real deployment) is
    staged outside the timed phase and reported as ``gen_s``."""
    import random as _r

    from hbbft_tpu.harness.vectorized import (
        VectorizedHoneyBadgerRound,
        decrypt_round,
    )

    from hbbft_tpu.ops.backend_tpu import TpuBackend

    rng = _r.Random(0x4B)
    t0 = time.perf_counter()
    sim = VectorizedHoneyBadgerRound(nodes, rng, ops=TpuBackend())
    for nid in range(nodes):
        sim.netinfos[0].public_key_share(nid)
    setup_s = time.perf_counter() - t0
    contribs = {p: b"payload-%04d" % p for p in range(proposers)}
    cts = sim.encrypt_contributions(contribs)
    t0 = time.perf_counter()
    from hbbft_tpu.harness.vectorized import _stage_real_shares

    staged = _stage_real_shares(
        sim.netinfos, sorted(cts.items()), set(), {}, None
    )
    if staged is None:  # no native library: stage per-call so the timed
        # phase still measures verification, not generation
        staged = {
            nid: {
                pid: sim.netinfos[
                    nid
                ].secret_key_share.decrypt_share_no_verify(ct)
                for pid, ct in cts.items()
            }
            for nid in sim.netinfos
        }
    gen_s = time.perf_counter() - t0
    # warm the per-process compiles at the same flush shape (the Mosaic
    # executable comes from the disk cache; the XLA reduction still
    # compiles once per process) — steady-state is what the epochs/sec
    # story repeats every epoch
    decrypt_round(sim.netinfos, cts, shares=staged)
    t0 = time.perf_counter()
    r = decrypt_round(sim.netinfos, cts, shares=staged)
    dt = time.perf_counter() - t0
    assert r.contributions == contribs

    # sequential extrapolation: per-share verify sample
    ni = sim.netinfos[0]
    ct0 = next(iter(cts.values()))
    share = ni.secret_key_share.decrypt_share_no_verify(ct0)
    pk = ni.public_key_share(0)
    t0s = time.perf_counter()
    for _ in range(8):
        assert pk.verify_decryption_share(share, ct0)
    per_verify = (time.perf_counter() - t0s) / 8
    # conservative baseline: *deduplicated* sequential verification
    # (one check per distinct share); a sequential network verifies at
    # every receiver, i.e. `nodes`× this — reported as network_wide_x
    seq_est = r.shares_verified * per_verify
    return _emit(
        "hb_dec_round_shares_per_s",
        r.shares_verified / dt,
        "shares/s",
        vs_baseline=seq_est / dt,
        network_wide_x=round(seq_est / dt * nodes, 1),
        nodes=nodes,
        proposers=proposers,
        round_s=round(dt, 2),
        gen_s=round(gen_s, 1),
        setup_s=round(setup_s, 1),
    )


def bench_broadcast_1mb(nodes: int = 64, device: bool = False):
    """Config 3: 1 MB payload reliable broadcast (RS encode/decode +
    Merkle build/verify dominate; reference ``broadcast.rs:332-404``).
    ``device=True`` routes the RS/Merkle work through the TPU kernels."""
    from hbbft_tpu.harness.network import (
        MessageScheduler,
        SilentAdversary,
        TestNetwork,
    )
    from hbbft_tpu.protocols.broadcast import Broadcast

    ops = None
    if device:
        from hbbft_tpu.ops.backend_tpu import TpuBackend

        ops = TpuBackend()
    rng = random.Random(0xB0)
    payload = rng.randbytes(1 << 20)
    net = TestNetwork(
        nodes - (nodes - 1) // 3,
        (nodes - 1) // 3,
        lambda adv: SilentAdversary(
            MessageScheduler(MessageScheduler.RANDOM, rng)
        ),
        lambda ni: Broadcast(ni, 0),
        rng,
        ops=ops,
    )
    t0 = time.perf_counter()
    net.input(0, payload)
    net.step_until(lambda: all(n.terminated() for n in net.nodes.values()))
    dt = time.perf_counter() - t0
    assert all(n.outputs == [payload] for n in net.nodes.values())
    return _emit(
        "broadcast_1mb_s", dt, "s", nodes=nodes, backend="tpu" if device else "native",
    )


def bench_decshares(k: int = 1024):
    """Config 4 (crypto plane): batched decryption-share verification —
    the single hottest surface (N² per HoneyBadger epoch).  One
    BatchingBackend flush of k real shares vs the per-share path."""
    from hbbft_tpu.crypto import threshold as T
    from hbbft_tpu.harness.batching import BatchingBackend, DecObligation
    from hbbft_tpu.ops.backend_tpu import TpuBackend

    rng = random.Random(0xD5)
    t = 3
    sks = T.SecretKeySet.random(t, rng)
    pks = sks.public_keys()
    n_nodes = 64

    def make_obs(tag):
        """k obligations over distinct ciphertexts (distinct groups
        stress the multi-pairing path the way real epochs do)."""
        cts = [
            pks.public_key().encrypt(tag + b"%d" % g, rng)
            for g in range(k // n_nodes)
        ]
        return [
            DecObligation(
                pks.public_key_share(i),
                sks.secret_key_share(i).decrypt_share_no_verify(c),
                c,
            )
            for c in cts
            for i in range(n_nodes)
        ]

    be = BatchingBackend(inner=TpuBackend())
    be.prefetch(make_obs(b"warm"))  # same shapes as the timed flush
    obs = make_obs(b"c")
    be2 = BatchingBackend(inner=TpuBackend())
    t0 = time.perf_counter()
    be2.prefetch(obs)
    dt = time.perf_counter() - t0
    assert all(
        be2.verify_dec_share(o.pk_share, o.share, o.ciphertext) for o in obs
    )

    sample = 8
    t0 = time.perf_counter()
    for o in obs[:sample]:
        assert o.pk_share.verify_decryption_share(o.share, o.ciphertext)
    cpu_rate = sample / (time.perf_counter() - t0)
    rate = len(obs) / dt
    return _emit(
        "decshare_verify_throughput",
        rate,
        "shares/s",
        vs_baseline=rate / cpu_rate,
        batch=len(obs),
        groups=k // n_nodes,
    )


def bench_qhb_1024(nodes: int = 1024, epochs: int = 3, n_dead: int = 50):
    """BASELINE config 5 **protocol plane** — MOCK crypto: the
    queueing layer over the vectorized epoch driver
    (``harness/epoch.py``) at N=1024 with an adversarial (silent-node)
    schedule: batched RBC matmuls, array-form agreement rounds,
    grouped decryption flushes — with hash-mock threshold crypto and
    honest-share verification elided (``verify_honest=False,
    emit_minimal=True``).  For the real-BLS epoch number see
    ``hb_1024_real``.  The sequential path is 'not measurable' at this
    size (BASELINE.md row 5); vs_baseline extrapolates the measured
    n=16 sequential rate (same mock settings) quadratically
    (charitable — observed sequential scaling between n=16 and n=32 is
    worse than N²)."""
    import random as _r

    from hbbft_tpu.harness.epoch import VectorizedQueueingSim
    from hbbft_tpu.harness.simulation import simulate_queueing_honey_badger

    rng = _r.Random(0x409)
    t0 = time.perf_counter()
    qsim = VectorizedQueueingSim(
        nodes,
        rng,
        batch_size=nodes,
        mock=True,
        verify_honest=False,
        emit_minimal=True,
    )
    qsim.input_all([b"tx-%06d" % i for i in range(4 * nodes)])
    setup_s = time.perf_counter() - t0
    dead = set(range(nodes - n_dead, nodes))
    qsim.run_epoch(dead=dead)  # warm table/matrix caches
    t0 = time.perf_counter()
    committed = 0
    for _ in range(epochs):
        res = qsim.run_epoch(dead=dead)
        committed += len(res.batch)
    dt = (time.perf_counter() - t0) / epochs

    # sequential anchor at n=16 (seconds), extrapolated quadratically
    stats, wall, _ = simulate_queueing_honey_badger(
        num_nodes=16, num_txs=64, batch_size=16, rng=_r.Random(1)
    )
    seq16 = len(stats.rows) / wall  # epochs/s at n=16
    seq_est = seq16 * (16.0 / nodes) ** 2
    return _emit(
        "qhb_1024_epochs_per_s",
        1.0 / dt,
        "epochs/s",
        vs_baseline=(1.0 / dt) / seq_est,
        nodes=nodes,
        dead=n_dead,
        txs_per_epoch=committed // epochs,
        s_per_epoch=round(dt, 2),
        setup_s=round(setup_s, 1),
        seq16_epochs_per_s=round(seq16, 3),
        crypto="mock",
        verify_honest=False,
        emit_minimal=True,
    )


def bench_hb_epoch64_real(nodes: int = 64, epochs: int = 2):
    """Full HoneyBadger epochs on REAL BLS12-381 at n=64 through the
    vectorized epoch driver — threshold encryption, batched RBC,
    array-form agreement, product-form decryption flush, Lagrange
    combines, batch assembly, end to end.  The sequential real-BLS
    path at this size is ~0.2 epochs/min (extrapolated from the n=4
    sim_real measurements; N² share work)."""
    import random as _r

    from hbbft_tpu.harness.epoch import VectorizedHoneyBadgerSim

    rng = _r.Random(0x64)
    t0 = time.perf_counter()
    sim = VectorizedHoneyBadgerSim(
        nodes, rng, mock=False, verify_honest=False, emit_minimal=True
    )
    setup_s = time.perf_counter() - t0
    contribs = {i: [b"e64-%d" % i] for i in range(nodes)}
    sim.run_epoch(contribs)  # warm
    t0 = time.perf_counter()
    for _ in range(epochs):
        res = sim.run_epoch(contribs)
        assert res.batch.contributions == contribs
    dt = (time.perf_counter() - t0) / epochs
    return _emit(
        "hb_epoch64_real_epochs_per_s",
        1.0 / dt,
        "epochs/s",
        nodes=nodes,
        s_per_epoch=round(dt, 2),
        setup_s=round(setup_s, 1),
    )


def bench_hb_1024_real(nodes: int = 1024, epochs: int = 3, n_dead: int = 50):
    """The north-star sentence, measured (VERDICT r2 item 1): full
    HoneyBadger epochs on REAL BLS12-381 at N=1024 through the
    vectorized epoch driver — threshold encryption, batched RBC
    matmuls, array-form agreement, comb-staged decryption-share
    generation, product-form N² share verification on the windowed
    Pallas device kernel, cached-Lagrange combines, batch assembly.

    No mock and no elision: ``verify_honest=True, emit_minimal=False``
    — every live sender's share of every accepted ciphertext is
    generated and verified (the reference's N² surface,
    ``honey_badger.rs:422-444``, deduplicated network-wide per the
    co-simulation semantics).  Note the co-simulation also pays the
    share-*generation* work every real node does locally (N scalar
    muls each, N² total) centrally via the fixed-base comb.

    vs_baseline extrapolates the measured sequential real-BLS n=4
    rate quadratically (charitable to the sequential path)."""
    import random as _r

    from hbbft_tpu.harness.epoch import VectorizedHoneyBadgerSim
    from hbbft_tpu.harness.simulation import (
        HwQuality,
        simulate_queueing_honey_badger,
    )
    from hbbft_tpu.ops.backend_tpu import TpuBackend

    import statistics as _st

    rng = _r.Random(0x1024)
    t0 = time.perf_counter()
    sim = VectorizedHoneyBadgerSim(
        nodes,
        rng,
        mock=False,
        ops=TpuBackend(),
    )
    setup_s = time.perf_counter() - t0
    dead = set(range(nodes - n_dead, nodes))
    contribs = {
        i: [b"real-%04d" % i] for i in range(nodes) if i not in dead
    }
    # cold first epoch: compile loads, comb tables, allocator warm-up
    # are REAL deployment costs — reported separately, never averaged
    # into the steady state (VERDICT r3 item 5)
    t0 = time.perf_counter()
    res = sim.run_epoch(contribs, dead=dead)
    cold_s = time.perf_counter() - t0
    assert res.batch.contributions == contribs

    # warm steady state, sequential epochs — per-phase walls collected
    # (VERDICT r4 weak #3: the dominant epoch cost was unattributed)
    seq_dts = []
    shares = 0
    phase_rows = []
    for _ in range(epochs):
        t0 = time.perf_counter()
        res = sim.run_epoch(contribs, dead=dead)
        seq_dts.append(time.perf_counter() - t0)
        assert res.batch.contributions == contribs
        shares += res.shares_verified
        phase_rows.append(res.phases or {})
    warm_dt = _st.median(seq_dts)
    phases = {
        k: round(_st.median([r.get(k, 0.0) for r in phase_rows]), 2)
        for k in sorted({k for r in phase_rows for k in r})
    }

    # pipelined epochs: two in flight (run_epochs — epoch e+1's
    # broadcast under epoch e's decryption flush; VERDICT r3 item 7)
    t0 = time.perf_counter()
    ress = sim.run_epochs([contribs] * epochs, dead=dead)
    pipe_dt = (time.perf_counter() - t0) / epochs
    assert all(r.batch.contributions == contribs for r in ress)
    # the fused flush must not have silently degraded to the per-group
    # fallback (a device failure would otherwise masquerade as a
    # measurement — the round-3 OOM lesson)
    assert sim.be.stats.fallback_groups == 0, sim.be.stats

    # virtual-time account: one epoch on an hw-profiled sim over the
    # SAME keys (reference simulator default profile — what this
    # real-crypto epoch costs on a 2 Mbit/s network)
    vsim = VectorizedHoneyBadgerSim.from_netinfos(
        sim.netinfos,
        _r.Random(0x1025),
        mock=False,
        hw=HwQuality.from_flags(lag_ms=100, bw_kbit_s=2000, cpu_pct=100),
    )
    v = vsim.run_epoch(contribs, dead=dead).virtual

    # sequential anchor: real-BLS n=4 virtual-time sim, quadratic
    stats, wall, _ = simulate_queueing_honey_badger(
        num_nodes=4, num_txs=16, batch_size=8, rng=_r.Random(2),
        mock_crypto=False,
    )
    seq4 = len(stats.rows) / wall
    seq_est = seq4 * (4.0 / nodes) ** 2
    best_dt = min(warm_dt, pipe_dt)
    return _emit(
        "hb_1024_real_s_per_epoch",
        best_dt,
        "s",
        vs_baseline=(1.0 / best_dt) / seq_est,
        nodes=nodes,
        dead=n_dead,
        epochs=epochs,
        cold_s=round(cold_s, 1),
        warm_median_s=round(warm_dt, 1),
        warm_min_s=round(min(seq_dts), 1),
        warm_max_s=round(max(seq_dts), 1),
        pipelined_s=round(pipe_dt, 1),
        shares_per_epoch=shares // epochs,
        setup_s=round(setup_s, 1),
        seq4_epochs_per_s=round(seq4, 3),
        crypto="real",
        verify_honest=True,
        emit_minimal=False,
        virtual_s=round(v.total_s, 1),
        virtual_network_s=round(v.network_s, 1),
        virtual_cpu_s=round(v.cpu_s, 1),
        phases=phases,
    )


def bench_hb_1024_observer(nodes: int = 1024, n_dead: int = 50):
    """VERDICT r4 next-9: the shared-flush observer lane at north-star
    N.  One warm epoch plain and one with ``observe=True`` on the same
    sim (both fully verified): the observer — a non-validator with no
    key share, reference ``tests/network/mod.rs:402-420`` — derives
    its batch from the network-visible share traffic alone, riding the
    SAME cache-filling flush (r3 design, tested at small n in
    ``test_epoch_vec.py``), so the epoch-cost delta should be ~0."""
    import random as _r

    from hbbft_tpu.harness.epoch import VectorizedHoneyBadgerSim
    from hbbft_tpu.ops.backend_tpu import TpuBackend

    rng = _r.Random(0x0B5)
    sim = VectorizedHoneyBadgerSim(nodes, rng, mock=False, ops=TpuBackend())
    dead = set(range(nodes - n_dead, nodes))
    contribs = {
        i: [b"obs-%04d" % i] for i in range(nodes) if i not in dead
    }
    sim.run_epoch(contribs, dead=dead)  # warm-up (compiles, combs)
    t0 = time.perf_counter()
    plain = sim.run_epoch(contribs, dead=dead)
    plain_dt = time.perf_counter() - t0
    t0 = time.perf_counter()
    obs = sim.run_epoch(contribs, dead=dead, observe=True)
    obs_dt = time.perf_counter() - t0
    assert obs.observer_batch is not None
    assert obs.observer_batch.contributions == obs.batch.contributions
    assert plain.batch.contributions == contribs
    # a device failure must not masquerade as a measurement
    assert sim.be.stats.fallback_groups == 0, sim.be.stats
    return _emit(
        "hb_1024_observer_delta_pct",
        100.0 * (obs_dt - plain_dt) / plain_dt,
        "%",
        nodes=nodes,
        plain_epoch_s=round(plain_dt, 1),
        observed_epoch_s=round(obs_dt, 1),
        observer_equal=True,
        crypto="real",
        plain_phases={
            k: round(v, 1) for k, v in (plain.phases or {}).items()
        },
        observed_phases={
            k: round(v, 1) for k, v in (obs.phases or {}).items()
        },
    )


def bench_qhb_1024_txrate(nodes: int = 1024, batch: int = 65536, n_dead: int = 50):
    """BASELINE north-star throughput metric: tx/sec at N=1024.  Same
    full stack as ``qhb_1024`` with the reference's batch-size knob
    turned up (B txs/epoch, each proposer sampling B/N — throughput
    scales with B while the epoch cost is dominated by the fixed N²
    bookkeeping, ``queueing_honey_badger.rs:13-23``)."""
    import random as _r

    from hbbft_tpu.harness.epoch import VectorizedQueueingSim

    rng = _r.Random(0x7A)
    qsim = VectorizedQueueingSim(
        nodes,
        rng,
        batch_size=batch,
        mock=True,
        verify_honest=False,
        emit_minimal=True,
    )
    qsim.input_all([b"tx-%07d" % i for i in range(2 * batch)])
    dead = set(range(nodes - n_dead, nodes))
    qsim.run_epoch(dead=dead)  # warm
    t0 = time.perf_counter()
    res = qsim.run_epoch(dead=dead)
    dt = time.perf_counter() - t0
    return _emit(
        "qhb_1024_tx_per_s",
        len(res.batch) / dt,
        "tx/s",
        nodes=nodes,
        batch_size=batch,
        txs_per_epoch=len(res.batch),
        s_per_epoch=round(dt, 2),
        crypto="mock",
        verify_honest=False,
        emit_minimal=True,
    )


def bench_broadcast_vec_1024(nodes: int = 1024):
    """1 MB reliable broadcast at N=1024 — past the reference crate's
    256-shard cap via the GF(2^16) codec (``crypto/rs.py``).  Baseline:
    the measured sequential n=256 network run extrapolated quadratically
    (N² proof validations dominate it)."""
    import random as _r

    from hbbft_tpu.harness.vectorized import VectorizedBroadcastRound

    rng = _r.Random(0xBD)
    payload = rng.randbytes(1 << 20)
    sim = VectorizedBroadcastRound(nodes, rng)
    r = sim.broadcast(payload)  # warm (GF(2^16) tables, matrices)
    t0 = time.perf_counter()
    r = sim.broadcast(payload)
    dt = time.perf_counter() - t0
    assert r.value == payload
    seq256 = bench_broadcast_1mb(nodes=256)
    seq_est = seq256["value"] * (nodes / 256.0) ** 2
    return _emit(
        "broadcast_vec_1024_s",
        dt,
        "s",
        vs_baseline=seq_est / dt,
        seq256_measured_s=seq256["value"],
        nodes=nodes,
    )


def bench_hb_1024_latency(nodes: int = 1024, n_dead: int = 50):
    """Simulated epoch LATENCY at north-star scale (VERDICT r2 weak
    #6): the vectorized engine's virtual-time account under the
    reference simulator's default hardware profile
    (``examples/simulation.rs:33-52``: lag 100 ms, bw 2000 kbit/s,
    cpu 100%) — the Min/MaxTime statistic of the reference's epoch
    table, produced at a size the event-driven simulator cannot reach.
    Protocol-plane run (mock crypto, annotated); the cpu term feeds the
    measured batched-phase wall times back into virtual time (SURVEY
    §5.8)."""
    import random as _r

    from hbbft_tpu.harness.epoch import VectorizedHoneyBadgerSim
    from hbbft_tpu.harness.simulation import HwQuality

    rng = _r.Random(0x11A)
    hw = HwQuality.from_flags(lag_ms=100, bw_kbit_s=2000, cpu_pct=100)
    sim = VectorizedHoneyBadgerSim(
        nodes, rng, mock=True, verify_honest=False, emit_minimal=True, hw=hw
    )
    dead = set(range(nodes - n_dead, nodes))
    contribs = {
        i: [b"lat-%04d" % i] for i in range(nodes) if i not in dead
    }
    sim.run_epoch(contribs, dead=dead)  # warm
    res = sim.run_epoch(contribs, dead=dead)
    v = res.virtual
    # fold the virtual round/cpu breakdown into the three commit-path
    # phases the latency arc optimizes: RBC, the agreement+coin rounds
    # (cross-instance coin batching's target), and decryption (the
    # speculative combine's target)
    rbc_s = coin_s = agree_s = dec_s = 0.0
    for label, secs in v.breakdown.items():
        if label.startswith("coin-"):
            coin_s += secs
        elif label.startswith(("bval-", "aux-", "conf-")) or label == (
            "cpu:agreement"
        ):
            agree_s += secs
        elif label in ("decshares", "cpu:decrypt", "cpu:assembly"):
            dec_s += secs
        else:  # value/echo/ready + cpu:propose/cpu:rbc
            rbc_s += secs
    return _emit(
        "hb_1024_epoch_latency_s",
        v.total_s,
        "simulated s",
        nodes=nodes,
        dead=n_dead,
        rounds=v.rounds,
        per_node_msgs=v.per_node_msgs,
        per_node_mb=round(v.per_node_bytes / 1e6, 2),
        network_s=round(v.network_s, 2),
        cpu_s=round(v.cpu_s, 2),
        rbc_s=round(rbc_s, 2),
        acs_vote_s=round(agree_s, 2),
        coin_s=round(coin_s, 2),
        decrypt_s=round(dec_s, 2),
        lag_ms=100,
        bw_kbit_s=2000,
        crypto="mock",
    )


def bench_latency(
    nodes: int = 13,
    epochs: int = 5,
    vec_nodes: int = 64,
    reveal_mode: str = "both",
):
    """Commit-latency A-B matrix (PR 10 arc) on the per-node protocol
    stack (``protocols/honey_badger.py`` over the TestNetwork message
    scheduler, REAL BLS): {eager, speculative} decryption × {serial,
    pipelined} epoch driving.  Eager is the protocol-prescribed
    verify-before-combine path — every received decryption share
    costs a pairing check before the combine, the latency price of
    arXiv:2407.12172; speculative combines the lowest f+1 shares
    unverified and pays one combined ciphertext check.  Serial
    barriers every epoch (commit latency = epoch wall); pipelined
    lets each node propose epoch e+1 the moment its own epoch e
    commits (``max_future_epochs`` in flight; commit latency =
    inter-commit gap).  Same seed everywhere; within each driving
    mode the A-B asserts byte-identical batches, then one p50/p99
    row per leg lands plus the headline speedup row
    (speculative+pipelined vs eager+serial — the ≥1.5× gate).  A
    second section reports the vectorized epoch driver
    (``harness/epoch.py``) serial vs deep-staged inter-commit gap —
    tentpole (c)'s staging-FIFO overlap, which needs spare cores to
    hide epoch e+1's propose/RBC wall inside epoch e's decrypt.

    A third section (PR 19, order-then-reveal) re-runs the pipelined
    driving as {eager, spec} × {inline, ordered}: under
    ``reveal_mode="ordered"`` the commit instant is the
    :class:`OrderedBatch` (ACS output + digest, no decryption on the
    path) and the plaintext follows asynchronously.  Each ordered leg
    emits its commit p50/p99, the ``acs_only_wall`` floor (gaps
    between ``acs_done`` events — the irreducible agreement wall),
    the ratio against that floor (the ≤1.2× acceptance gate), and the
    observed ``reveal_lag`` p50/p99; the post-reveal plaintext is
    asserted byte-identical across all four legs.  ``reveal_mode``
    selects the legs: ``"both"`` (default), ``"inline"``, or
    ``"ordered"``."""
    import hashlib as _hl
    import random as _r

    from hbbft_tpu.harness.network import (
        MessageScheduler,
        SilentAdversary,
        TestNetwork,
    )
    from hbbft_tpu.protocols.honey_badger import HoneyBadger

    f = (nodes - 1) // 3

    def run(speculative, pipelined):
        rng = _r.Random(0x1A7)
        net = TestNetwork(
            nodes - f,
            f,
            lambda adv: SilentAdversary(
                MessageScheduler(MessageScheduler.FIRST, rng)
            ),
            lambda ni: HoneyBadger(
                ni,
                rng=_r.Random(f"{ni.our_id}-lat"),
                speculative=speculative,
            ),
            rng,
            mock_crypto=False,
        )

        def commits():
            return min(len(n.outputs) for n in net.nodes.values())

        proposed = {nid: 0 for nid in net.nodes}
        lats = []
        guard = 0
        t0 = time.perf_counter()
        while commits() < epochs:
            guard += 1
            assert guard < 500_000, "latency bench failed to commit"
            before = commits()
            for nid in sorted(net.nodes):
                node = net.nodes[nid]
                if proposed[nid] >= epochs or node.instance.has_input():
                    continue
                # serial: epoch e+1 proposals wait for the global
                # commit barrier; pipelined: a node re-proposes the
                # moment its own epoch commits
                if pipelined or proposed[nid] <= before:
                    node.handle_input(
                        [b"lat-%02d-%02d" % (proposed[nid], nid)]
                    )
                    msgs = list(node.messages)
                    node.messages.clear()
                    net.dispatch_messages(nid, msgs)
                    proposed[nid] += 1
            if net.any_busy():
                net.step()
            after = commits()
            if after > before:
                now = time.perf_counter()
                lats.extend(
                    (now - t0) / (after - before)
                    for _ in range(after - before)
                )
                t0 = now
        digest = _hl.sha256()
        for nid in sorted(net.nodes):
            for b in net.nodes[nid].outputs:
                for k in sorted(b.contributions):
                    digest.update(b"%d:" % k)
                    for tx in b.contributions[k]:
                        digest.update(tx)
        return sorted(lats[1:]), digest.hexdigest()  # epoch 0: warmup

    def pct(lats, q):
        return lats[min(len(lats) - 1, int(q * len(lats)))]

    legs = [
        ("eager/serial", False, False),
        ("eager/pipelined", False, True),
        ("spec/serial", True, False),
        ("spec/pipelined", True, True),
    ]
    p50 = {}
    digests = {}
    for label, spec, pipelined in legs:
        lats, digest = run(spec, pipelined)
        digests[label] = digest
        p50[label] = pct(lats, 0.50)
        _emit(
            "commit_latency_p50_s",
            p50[label],
            "s",
            mode=label,
            p99_s=round(pct(lats, 0.99), 3),
            epochs=epochs,
            nodes=nodes,
            crypto="real",
        )
    # honest-node batches byte-identical across the speculative A-B
    # (same seed + same scheduler ⇒ same message order per mode)
    assert digests["eager/serial"] == digests["spec/serial"]
    assert digests["eager/pipelined"] == digests["spec/pipelined"]
    _emit(
        "commit_latency_speedup",
        p50["eager/serial"] / p50["spec/pipelined"],
        "x",
        vs_baseline=p50["eager/serial"] / p50["spec/pipelined"],
        baseline="eager/serial p50",
        nodes=nodes,
        batches_identical=True,
    )

    # -- order-then-reveal: {eager, spec} × {inline, ordered} ------------
    from hbbft_tpu.obs import recorder as _obsrec
    from hbbft_tpu.protocols.honey_badger import Batch, OrderedBatch

    def run_reveal(speculative, mode):
        """Pipelined driving (re-propose the moment our epoch
        advances); the commit instant is the OrderedBatch under
        ``mode="ordered"``, the plaintext Batch under ``"inline"``."""
        rng = _r.Random(0x1A7)
        rec = _obsrec.enable()
        try:
            net = TestNetwork(
                nodes - f,
                f,
                lambda adv: SilentAdversary(
                    MessageScheduler(MessageScheduler.FIRST, rng)
                ),
                lambda ni: HoneyBadger(
                    ni,
                    rng=_r.Random(f"{ni.our_id}-lat"),
                    speculative=speculative,
                    reveal_mode=mode,
                ),
                rng,
                mock_crypto=False,
            )
            proposed = {nid: 0 for nid in net.nodes}
            seen = {nid: 0 for nid in net.nodes}
            commit_t = {nid: {} for nid in net.nodes}
            reveal_t = {nid: {} for nid in net.nodes}

            def scan():
                now = time.perf_counter()
                for nid, node in net.nodes.items():
                    for o in node.outputs[seen[nid]:]:
                        if isinstance(o, OrderedBatch):
                            commit_t[nid][o.epoch] = now
                        elif isinstance(o, Batch):
                            reveal_t[nid][o.epoch] = now
                            if mode == "inline":
                                commit_t[nid][o.epoch] = now
                    seen[nid] = len(node.outputs)

            def revealed():
                return min(len(reveal_t[nid]) for nid in net.nodes)

            guard = 0
            while revealed() < epochs:
                guard += 1
                assert guard < 500_000, "reveal bench failed to commit"
                for nid in sorted(net.nodes):
                    node = net.nodes[nid]
                    if proposed[nid] >= epochs or node.instance.has_input():
                        continue
                    node.handle_input(
                        [b"lat-%02d-%02d" % (proposed[nid], nid)]
                    )
                    msgs = list(node.messages)
                    node.messages.clear()
                    net.dispatch_messages(nid, msgs)
                    proposed[nid] += 1
                    scan()
                if net.any_busy():
                    net.step()
                    scan()
            # per-node inter-commit gaps (epoch 0: warmup) + the
            # acs_done gaps — the agreement-only wall
            gaps, lags = [], []
            for nid in net.nodes:
                ts = [commit_t[nid][e] for e in sorted(commit_t[nid])]
                gaps.extend(b - a for a, b in zip(ts[1:], ts[2:]))
                lags.extend(
                    reveal_t[nid][e] - commit_t[nid][e]
                    for e in sorted(reveal_t[nid])
                    if e > 0
                )
            acs_ts = {}
            for row in rec.events:
                if row["ev"] == "acs_done":
                    acs_ts.setdefault(row["node"], {})[row["epoch"]] = (
                        row["t"]
                    )
            acs_gaps = []
            for per in acs_ts.values():
                ts = [per[e] for e in sorted(per)]
                acs_gaps.extend(b - a for a, b in zip(ts[1:], ts[2:]))
            digest = _hl.sha256()
            for nid in sorted(net.nodes):
                for b in net.nodes[nid].outputs:
                    if not isinstance(b, Batch):
                        continue
                    for k in sorted(b.contributions):
                        digest.update(b"%d:" % k)
                        for tx in b.contributions[k]:
                            digest.update(tx)
            return (
                sorted(gaps),
                sorted(lags),
                sorted(acs_gaps),
                digest.hexdigest(),
            )
        finally:
            _obsrec.disable()

    reveal_legs = [
        (dec, rm)
        for dec in ("eager", "spec")
        for rm in ("inline", "ordered")
        if reveal_mode in ("both", rm)
    ]
    rp50 = {}
    racs = {}
    rdigests = {}
    for dec, rm in reveal_legs:
        gaps, lags, acs_gaps, digest = run_reveal(dec == "spec", rm)
        label = f"{dec}/{rm}"
        rdigests[label] = digest
        rp50[label] = pct(gaps, 0.50)
        acs_p50 = racs[label] = pct(acs_gaps, 0.50)
        extra = {}
        if rm == "ordered":
            extra = dict(
                vs_acs_only_wall=round(rp50[label] / acs_p50, 3),
            )
        _emit(
            "commit_latency_p50_s",
            rp50[label],
            "s",
            mode=label,
            p99_s=round(pct(gaps, 0.99), 3),
            acs_only_wall_p50_s=round(acs_p50, 6),
            epochs=epochs,
            nodes=nodes,
            crypto="real",
            **extra,
        )
        if rm == "ordered":
            _emit(
                "reveal_lag_p50_s",
                pct(lags, 0.50),
                "s",
                mode=label,
                p99_s=round(pct(lags, 0.99), 3),
                epochs=epochs,
                nodes=nodes,
            )
    # the ordered pipeline reorders nothing: post-reveal plaintext is
    # byte-identical across every leg that ran
    assert len(set(rdigests.values())) == 1, "reveal legs diverged"
    if reveal_mode in ("both", "ordered"):
        # the PR-19 acceptance gate: the ordered commit instant sits
        # within 1.2x of the irreducible agreement wall — decryption
        # is off the commit critical path (its cost shows up only as
        # reveal_lag).  Inter-commit gaps can't shrink in this
        # single-threaded scheduler, so the floor ratio IS the
        # headline, not a gap speedup.
        _emit(
            "ordered_commit_vs_acs_wall",
            rp50["spec/ordered"] / racs["spec/ordered"],
            "x",
            baseline="acs_only_wall p50 (spec/ordered leg)",
            eager_x=round(
                rp50["eager/ordered"] / racs["eager/ordered"], 3
            ),
            gate="<= 1.2",
            nodes=nodes,
            batches_identical=True,
        )

    # -- vectorized epoch driver: serial wall vs deep-staged gap ---------
    from hbbft_tpu.harness.epoch import VectorizedHoneyBadgerSim

    def vec(mode):
        rng = _r.Random(0x1A7)
        sim = VectorizedHoneyBadgerSim(
            vec_nodes,
            rng,
            mock=False,
            verify_honest=True,
            emit_minimal=True,
            speculative=True,
        )
        seq = [
            {i: [b"lat-%02d-%04d" % (e, i)] for i in range(vec_nodes)}
            for e in range(epochs)
        ]
        results = sim.run_epochs(seq, pipeline=mode)
        lats = sorted(r.phases["commit_latency"] for r in results[1:])
        return results, lats

    vec_batches = None
    for label, mode in (("serial", False), ("staged", "deep")):
        results, lats = vec(mode)
        batches = [r.batch for r in results]
        if vec_batches is None:
            vec_batches = batches
        else:
            assert batches == vec_batches, "staged epochs diverged"
        _emit(
            "vec_commit_gap_p50_s",
            pct(lats, 0.50),
            "s",
            mode=label,
            p99_s=round(pct(lats, 0.99), 3),
            epochs=epochs,
            nodes=vec_nodes,
            spec_hits=sum(
                int(r.phases.get("spec_hits", 0)) for r in results
            ),
            crypto="real",
        )


def bench_qhb_dyn_1024(nodes: int = 1024, n_dead: int = 50):
    """BASELINE config 5, now with the TRUE reference stack shape:
    QueueingHoneyBadger = **DynamicHoneyBadger** + queue
    (``queueing_honey_badger.rs:161-176``) — votes, on-chain DKG and an
    era switch run mid-measurement at N=1024 (the round-2 driver's
    'QHB' wrapped the static HB sim; VERDICT r2 missing #1).  Same
    protocol-plane settings as qhb_1024 (mock crypto, honest checks
    elided — annotated in the JSON)."""
    import random as _r

    from hbbft_tpu.harness.dynamic import VectorizedDynamicQueueingSim
    from hbbft_tpu.protocols.change import Complete, Remove

    rng = _r.Random(0x5D1)
    t0 = time.perf_counter()
    qsim = VectorizedDynamicQueueingSim(
        nodes,
        rng,
        batch_size=nodes,
        mock=True,
        verify_honest=False,
        emit_minimal=True,
    )
    qsim.input_all([b"tx-%06d" % i for i in range(4 * nodes)])
    setup_s = time.perf_counter() - t0
    # n_dead silent nodes, keeping the churn target (the highest id) live
    dead = set(range(nodes - n_dead - 1, nodes - 1))
    qsim.run_epoch(dead=dead)  # warm
    f = (nodes - 1) // 3
    for v in qsim.validators[: f + 1]:
        qsim.vote_for(v, Remove(nodes - 1))
    t0 = time.perf_counter()
    committed = 0
    churn_epoch = None
    epochs = 3
    for e in range(epochs):
        res = qsim.run_epoch(dead=dead)
        committed += len(res.batch)
        if isinstance(res.change, Complete):
            churn_epoch = e
    dt = (time.perf_counter() - t0) / epochs
    assert churn_epoch is not None and qsim.era == 1
    assert (nodes - 1) not in qsim.validators
    return _emit(
        "qhb_dyn_1024_epochs_per_s",
        1.0 / dt,
        "epochs/s",
        nodes=nodes,
        dead=n_dead,
        txs_per_epoch=committed // epochs,
        s_per_epoch=round(dt, 2),
        setup_s=round(setup_s, 1),
        churn_at_epoch=churn_epoch,
        eras=qsim.era + 1,
        crypto="mock",
        verify_honest=False,
        emit_minimal=True,
    )


def bench_dkg_verified(nodes: int = 64):
    """Dynamic layer at scale, verification plane (VERDICT r2 item 3):
    a full dealerless DKG at N with EVERY row check (N² cells) and
    EVERY ack value check (N³ cells) settled by ONE fused product-form
    G2 MSM over the N·(t+1)² commitment entries
    (``harness/dkg.py``).  vs_baseline extrapolates from measured
    sequential ``SyncKeyGen.handle_part``/``handle_ack`` samples at the
    same size (network-wide: N nodes × (N parts + N² acks))."""
    import random as _r

    from hbbft_tpu.crypto import threshold as T
    from hbbft_tpu.harness.dkg import VectorizedDkg
    from hbbft_tpu.protocols.sync_key_gen import SyncKeyGen

    rng = _r.Random(0xD6)
    t = (nodes - 1) // 3
    dkg = VectorizedDkg(list(range(nodes)), t, rng, mock=False)
    t0 = time.perf_counter()
    res = dkg.run(verify_honest=True)
    dt = time.perf_counter() - t0
    assert res.fault_log.is_empty() and len(res.complete) == nodes

    # sequential samples (one dealing node + one receiving node)
    sec_keys = {i: T.SecretKey.random(_r.Random(2000 + i)) for i in range(nodes)}
    pub_keys = {i: sec_keys[i].public_key() for i in range(nodes)}
    t0 = time.perf_counter()
    dealer = SyncKeyGen(0, sec_keys[0], pub_keys, t, _r.Random(1))
    deal_s = time.perf_counter() - t0
    receiver = SyncKeyGen(1, sec_keys[1], pub_keys, t, _r.Random(2))
    t0 = time.perf_counter()
    ack, faults = receiver.handle_part(0, dealer.our_part, rng=_r.Random(3))
    part_s = time.perf_counter() - t0
    assert ack is not None and faults.is_empty()
    receiver.parts[0].acks.discard(1)
    t0 = time.perf_counter()
    assert receiver.handle_ack(1, ack).is_empty()
    ack_s = time.perf_counter() - t0
    # network-wide sequential cost: every node handles N parts + N² acks
    seq_est = nodes * (nodes * part_s + nodes * nodes * ack_s)
    checks = res.row_checks + res.value_checks
    return _emit(
        "dkg_verified_s",
        dt,
        "s",
        vs_baseline=seq_est / dt,
        nodes=nodes,
        checks=checks,
        msm_points=res.msm_points,
        seq_est_s=round(seq_est, 1),
        seq_part_ms=round(part_s * 1e3, 1),
        seq_ack_ms=round(ack_s * 1e3, 1),
    )


def bench_dkg_256(nodes: int = 256):
    """Dynamic layer at north-star scale: a full dealerless DKG at
    N=256 (degree-85 bivariate dealing, native Fr matrix algebra +
    shared-base G2 comb, generation with cached Lagrange weights).
    Honest-share checks are ELIDED (``verify_honest=False`` — the
    ``decrypt_round`` equivalence argument; adversarial injections
    would still be checked exactly), so this row measures the
    co-simulation protocol plane: dealing + value grids + key
    generation.  The verification plane is measured by
    ``dkg_verified``."""
    import random as _r

    from hbbft_tpu.harness.dkg import VectorizedDkg

    rng = _r.Random(0xD7)
    t = (nodes - 1) // 3
    dkg = VectorizedDkg(list(range(nodes)), t, rng, mock=False)
    t0 = time.perf_counter()
    res = dkg.run(verify_honest=False)
    dt = time.perf_counter() - t0
    assert len(res.complete) == nodes and len(res.shares) == nodes
    # the generated keys work: sign + combine round-trip
    shares = {i: res.shares[i].sign(b"dkg256") for i in range(t + 1)}
    sig = res.pk_set.combine_signatures(shares)
    assert res.pk_set.verify_signature(sig, b"dkg256")
    return _emit(
        "dkg_256_s",
        dt,
        "s",
        nodes=nodes,
        threshold=t,
        elided=True,
        engine=res.engine,
        crypto="real",
    )


def bench_dkg_verified_256(nodes: int = 256):
    """VERDICT r3 item 6: the FULLY-VERIFIED fused DKG at the scale
    the elided row ships — every row check (N² cells) and every ack
    value check (N³ cells) settled by the single trilinear-RLC G2 MSM,
    at N=256 (degree-85 bivariate polynomials).  Also asserts the
    elided and verified runs produce byte-identical keys (same seed),
    closing the 'argued equivalent' → 'measured equivalent' gap at the
    quoted scale."""
    import random as _r

    from hbbft_tpu.harness.dkg import VectorizedDkg

    t = (nodes - 1) // 3
    dkg = VectorizedDkg(list(range(nodes)), t, _r.Random(0xD8), mock=False)
    t0 = time.perf_counter()
    res = dkg.run(verify_honest=True)
    dt = time.perf_counter() - t0
    assert res.fault_log.is_empty() and len(res.complete) == nodes

    # elided twin over the same seed: identical outputs (host engine —
    # the equality being asserted is elided-vs-verified, so both runs
    # must draw the same dealer polynomial streams)
    dkg2 = VectorizedDkg(list(range(nodes)), t, _r.Random(0xD8), mock=False)
    t0 = time.perf_counter()
    res2 = dkg2.run(verify_honest=False, engine="host")
    elided_dt = time.perf_counter() - t0
    assert res.pk_set.public_key().to_bytes() == res2.pk_set.public_key().to_bytes()
    assert all(
        res.shares[i].scalar == res2.shares[i].scalar for i in range(nodes)
    )
    return _emit(
        "dkg_verified_%d_s" % nodes,
        dt,
        "s",
        nodes=nodes,
        threshold=t,
        checks=res.row_checks + res.value_checks,
        msm_points=res.msm_points,
        elided_twin_s=round(elided_dt, 1),
        elided_equal=True,
        crypto="real",
    )


def bench_dkg_verified_512():
    """VERDICT r4 next-3: one fully-verified fused DKG PAST the N=256
    scale — N=512 (degree-170 bivariate), every row/value check in the
    fused trilinear-RLC G2 MSM, elided-twin byte-identity asserted at
    this scale.  Long-running by nature; captured once per round."""
    return bench_dkg_verified_256(nodes=512)


def bench_dkg_1024(nodes: int = 1024):
    """VERDICT r3 item 2: the dealerless DKG at the north-star N —
    degree-341 bivariate dealing (the ``BivarPoly``/commitment work of
    ``sync_key_gen.rs:268-299`` at SURVEY §7 scale), value grids and
    key generation on real BLS12-381.  Honest checks elided
    (annotated; the verification plane is measured at N=256 by
    ``dkg_verified_256``), with a vs-sequential extrapolation from the
    measured per-part/per-ack sequential costs at N=64."""
    import random as _r

    from hbbft_tpu.crypto import threshold as T
    from hbbft_tpu.harness.dkg import VectorizedDkg
    from hbbft_tpu.protocols.sync_key_gen import SyncKeyGen

    t = (nodes - 1) // 3
    dkg = VectorizedDkg(list(range(nodes)), t, _r.Random(0xDA), mock=False)
    t0 = time.perf_counter()
    res = dkg.run(verify_honest=False)
    dt = time.perf_counter() - t0
    assert len(res.complete) == nodes and len(res.shares) == nodes
    # the generated keys work: sign + combine round-trip
    shares = {i: res.shares[i].sign(b"dkg1024") for i in range(t + 1)}
    sig = res.pk_set.combine_signatures(shares)
    assert res.pk_set.verify_signature(sig, b"dkg1024")

    # sequential anchor at a measurable size: one part + one ack at
    # n=64, scaled by the reference's cost model (handle_part ~ n·t
    # commitment evaluations; handle_ack ~ t field ops; network-wide
    # N nodes × (N parts + N² acks), all ~quadratic in N on top)
    small = 64
    ts = (small - 1) // 3
    sec = {i: T.SecretKey.random(_r.Random(3000 + i)) for i in range(small)}
    pub = {i: sec[i].public_key() for i in range(small)}
    dealer = SyncKeyGen(0, sec[0], pub, ts, _r.Random(5))
    receiver = SyncKeyGen(1, sec[1], pub, ts, _r.Random(6))
    t0 = time.perf_counter()
    ack, faults = receiver.handle_part(0, dealer.our_part, rng=_r.Random(7))
    part_s = time.perf_counter() - t0
    assert ack is not None and faults.is_empty()
    receiver.parts[0].acks.discard(1)
    t0 = time.perf_counter()
    assert receiver.handle_ack(1, ack).is_empty()
    ack_s = time.perf_counter() - t0
    scale = (nodes / small) ** 2  # per-op cost grows ~N² (t ~ N rows × N cols)
    seq_est = nodes * (
        nodes * part_s * scale + nodes * nodes * ack_s * (nodes / small)
    )
    return _emit(
        "dkg_1024_s",
        dt,
        "s",
        vs_baseline=seq_est / dt,
        nodes=nodes,
        threshold=t,
        elided=True,
        engine=res.engine,
        seq_est_s=round(seq_est, 1),
        crypto="real",
    )


def bench_churn_1024(nodes: int = 1024):
    """VERDICT r3 item 2: the full membership-change cycle at the
    north-star N on real BLS12-381 — f+1 signed votes on-chain →
    Remove wins → degree-341 dealerless DKG over the new set → era
    restart → one epoch committed under the NEW keys
    (``dynamic_honey_badger.rs:300-338`` at SURVEY §7 scale).  DKG
    honest checks elided; epoch crypto ``verify_honest=False,
    emit_minimal=True`` (annotated)."""
    import random as _r

    from hbbft_tpu.harness.dynamic import VectorizedDynamicSim
    from hbbft_tpu.protocols.change import Complete, Remove

    rng = _r.Random(0xC5)
    t0 = time.perf_counter()
    sim = VectorizedDynamicSim(
        nodes,
        rng,
        mock=False,
        verify_honest=False,
        emit_minimal=True,
    )
    setup_s = time.perf_counter() - t0
    f = (nodes - 1) // 3
    for v in range(f + 1):
        sim.vote_for(v, Remove(nodes - 1))
    t0 = time.perf_counter()
    r1 = sim.run_epoch({i: [b"c-%d" % i] for i in range(nodes)})
    era_switch_s = time.perf_counter() - t0
    assert isinstance(r1.change, Complete) and sim.era == 1
    t0 = time.perf_counter()
    r2 = sim.run_epoch({i: [b"d-%d" % i] for i in sim.validators})
    next_epoch_s = time.perf_counter() - t0
    assert len(r2.batch) == nodes - 1
    return _emit(
        "churn_1024_s",
        era_switch_s + next_epoch_s,
        "s",
        nodes=nodes,
        era_switch_s=round(era_switch_s, 1),
        next_epoch_s=round(next_epoch_s, 1),
        setup_s=round(setup_s, 1),
        crypto="real",
        dkg_elided=True,
        verify_honest=False,
        emit_minimal=True,
    )


def bench_qhb_dyn_1024_real(nodes: int = 1024, n_dead: int = 50):
    """VERDICT r3 item 2: the dynamic queueing stack at N=1024 on REAL
    BLS12-381 (the mock-crypto ``qhb_dyn_1024`` row's missing real
    twin): votes, on-chain DKG and an era switch run mid-measurement
    with genuine threshold decryption per epoch.  Protocol-plane
    elisions annotated (``verify_honest=False, emit_minimal=True``)."""
    import random as _r

    from hbbft_tpu.harness.dynamic import VectorizedDynamicQueueingSim
    from hbbft_tpu.protocols.change import Complete, Remove

    rng = _r.Random(0x5D2)
    t0 = time.perf_counter()
    qsim = VectorizedDynamicQueueingSim(
        nodes,
        rng,
        batch_size=nodes,
        mock=False,
        verify_honest=False,
        emit_minimal=True,
    )
    qsim.input_all([b"tx-%06d" % i for i in range(4 * nodes)])
    setup_s = time.perf_counter() - t0
    dead = set(range(nodes - n_dead - 1, nodes - 1))
    qsim.run_epoch(dead=dead)  # warm
    f = (nodes - 1) // 3
    for v in qsim.validators[: f + 1]:
        qsim.vote_for(v, Remove(nodes - 1))
    t0 = time.perf_counter()
    committed = 0
    churn_epoch = None
    epochs = 3
    for e in range(epochs):
        res = qsim.run_epoch(dead=dead)
        committed += len(res.batch)
        if isinstance(res.change, Complete):
            churn_epoch = e
    dt = (time.perf_counter() - t0) / epochs
    assert churn_epoch is not None and qsim.era == 1
    assert (nodes - 1) not in qsim.validators
    return _emit(
        "qhb_dyn_1024_real_s_per_epoch",
        dt,
        "s",
        nodes=nodes,
        dead=n_dead,
        txs_per_epoch=committed // epochs,
        churn_at_epoch=churn_epoch,
        eras=qsim.era + 1,
        setup_s=round(setup_s, 1),
        crypto="real",
        verify_honest=False,
        emit_minimal=True,
    )


def bench_churn_256(nodes: int = 256):
    """A full membership-change cycle at N=256 on real BLS12-381
    through the vectorized dynamic layer (``harness/dynamic.py``):
    f+1 signed votes ride on-chain → Remove wins → dealerless DKG over
    the new set → era restart → one epoch committed under the NEW
    keys.  DKG honest checks elided (see ``dkg_256``); epoch crypto
    runs ``verify_honest=False, emit_minimal=True`` (the qhb_1024
    protocol-plane settings, annotated)."""
    import random as _r

    from hbbft_tpu.harness.dynamic import VectorizedDynamicSim
    from hbbft_tpu.protocols.change import Complete, Remove

    rng = _r.Random(0xC4)
    t0 = time.perf_counter()
    sim = VectorizedDynamicSim(
        nodes,
        rng,
        mock=False,
        verify_honest=False,
        emit_minimal=True,
    )
    setup_s = time.perf_counter() - t0
    f = (nodes - 1) // 3
    for v in range(f + 1):
        sim.vote_for(v, Remove(nodes - 1))
    t0 = time.perf_counter()
    r1 = sim.run_epoch({i: [b"c-%d" % i] for i in range(nodes)})
    assert isinstance(r1.change, Complete) and sim.era == 1
    r2 = sim.run_epoch({i: [b"d-%d" % i] for i in sim.validators})
    assert len(r2.batch) == nodes - 1
    dt = time.perf_counter() - t0
    return _emit(
        "churn_256_s",
        dt,
        "s",
        nodes=nodes,
        setup_s=round(setup_s, 1),
        crypto="real",
        dkg_elided=True,
        verify_honest=False,
        emit_minimal=True,
    )


def bench_qhb_scale(nodes: int = 32, txs: int = 320, batch: int = 64):
    """Config 5 proxy: QueueingHoneyBadger co-simulation throughput at
    growing N (the full-stack protocol-plane cost, mock crypto)."""
    from hbbft_tpu.harness.batching import BatchingBackend
    from hbbft_tpu.harness.simulation import simulate_queueing_honey_badger

    stats, wall, _ = simulate_queueing_honey_badger(
        num_nodes=nodes,
        num_txs=txs,
        batch_size=batch,
        rng=random.Random(3),
        ops=BatchingBackend(),
    )
    return _emit(
        "qhb_scale_epochs_per_s",
        len(stats.rows) / wall,
        "epochs/s",
        nodes=nodes,
        epochs=len(stats.rows),
        wall_s=round(wall, 2),
    )


def bench_serve(
    duration: float = 5.0,
    clients: int = 2,
    tenants: int = 2,
    rate_hz: float = 60.0,
    nodes: int = 4,
):
    """The serving headline: concurrent clients over the real TCP mesh
    through the gateway — sustained committed tx/s with exactly-once
    acks, plus the client-observed commit-latency percentiles."""
    from hbbft_tpu.serve.loadgen import default_tenants, run_tcp

    summary = run_tcp(
        default_tenants(tenants, clients, rate_hz, mean_payload=256),
        n_validators=nodes,
        duration_s=duration,
        seed=0x5EB0,
    )
    _emit(
        "serve_tx_per_s",
        summary["tx_per_s"],
        "tx/s",
        nodes=nodes,
        tenants=summary["tenants"],
        clients=summary["clients"],
        submitted=summary["submitted"],
        committed=summary["committed"],
        reject_rate=summary["reject_rate"],
        unacked=summary["unacked"],
        duration_s=summary["duration_s"],
    )
    return _emit(
        "serve_commit_latency",
        summary["commit_p50_s"],
        "s",
        p50_s=summary["commit_p50_s"],
        p99_s=summary["commit_p99_s"],
        nodes=nodes,
    )


def bench_serve_vector(epochs: int = 100, nodes: int = 1024):
    """BASELINE config #5 behind the gateway: n=1024 adversarial
    (f crashed), 100 epochs, fed by superposed million-client tenant
    arrival processes through the real frame/decode/admission path."""
    from hbbft_tpu.serve.loadgen import default_tenants, run_vector

    summary = run_vector(
        default_tenants(4, 2, 50.0, mean_payload=256),
        n=nodes,
        epochs=epochs,
        seed=0x5EB1,
    )
    _emit(
        "serve_vector_tx_per_s",
        summary["tx_per_s"],
        "tx/s",
        nodes=nodes,
        epochs=epochs,
        dead=summary["dead"],
        tenants=summary["tenants"],
        clients_simulated=summary["clients_simulated"],
        submitted=summary["submitted"],
        committed=summary["committed"],
        reject_rate=summary["reject_rate"],
        duration_s=summary["duration_s"],
    )
    for hop, dist in sorted(summary.get("hop_walls_s", {}).items()):
        _emit(
            "serve_vector_hop_wall",
            dist["p50"],
            "s",
            hop=hop,
            p50_s=dist["p50"],
            p90_s=dist["p90"],
            max_s=dist["max"],
            nodes=nodes,
        )
    return _emit(
        "serve_vector_commit_latency",
        summary["commit_p50_s"],
        "s",
        p50_s=summary["commit_p50_s"],
        p99_s=summary["commit_p99_s"],
        nodes=nodes,
    )


# ---------------------------------------------------------------------------
# Recorder overhead with the export plane live (--obs-bench)
# ---------------------------------------------------------------------------


def bench_obs_overhead(events: int = 200_000, reps: int = 3):
    """A/B overhead of the fleet telemetry plane on the recorder hot
    path.  Leg A is the PR-1 recorder: JSONL sink, counters, hists —
    nothing else.  Leg B is the same workload with export enabled:
    every row mirrored into the flight ring AND the Prometheus
    exposition rendered every 10k events (a scrape rate well above any
    real fleet poller).  Best-of-``reps`` wall per leg; the acceptance
    bar is B within 5%% of A."""
    import os
    import tempfile

    from hbbft_tpu.obs.flight import FlightRecorder
    from hbbft_tpu.obs.metrics import MetricsCore
    from hbbft_tpu.obs.recorder import Recorder

    def drive(rec, core=None, every=10_000):
        t0 = time.perf_counter()
        for i in range(events):
            rec.event(
                "wire_send",
                kind="SeqData",
                peer="127.0.0.1:1",
                size=i & 1023,
                node="127.0.0.1:2",
                seq=i,
            )
            if i & 7 == 0:
                rec.count("wire.frames")
            if i & 1023 == 0:
                rec.observe("gateway.commit_latency_s", 0.001 * (i & 63))
            if core is not None and i % every == 0:
                core.render()
        return time.perf_counter() - t0

    with tempfile.TemporaryDirectory() as td:
        base_walls, export_walls = [], []
        for r in range(reps):
            rec_a = Recorder(os.path.join(td, f"a{r}.jsonl"), node="bench")
            base_walls.append(drive(rec_a))
            rec_a.close()

            rec_b = Recorder(os.path.join(td, f"b{r}.jsonl"), node="bench")
            flight = FlightRecorder(
                os.path.join(td, f"flight{r}.jsonl"), capacity=512,
                node="bench",
            )
            rec_b.attach_flight(flight)
            core = MetricsCore(node="bench", recorder=rec_b)
            export_walls.append(drive(rec_b, core=core))
            rec_b.close()
            flight.close()

    base, export = min(base_walls), min(export_walls)
    overhead = export / base - 1.0
    _emit(
        "obs_recorder_events_per_s",
        events / base,
        "events/s",
        events=events,
        reps=reps,
        wall_s=round(base, 4),
    )
    return _emit(
        "obs_export_overhead",
        100.0 * overhead,
        "%",
        vs_baseline=export / base,
        events=events,
        base_wall_s=round(base, 4),
        export_wall_s=round(export, 4),
        within_5pct=bool(overhead <= 0.05),
    )


# ---------------------------------------------------------------------------
# 100k-validator co-simulation sweep (--cosim)
# ---------------------------------------------------------------------------


def bench_cosim(ns=None, epochs: int = 3, out: str = None):
    """The packed co-simulation scale sweep (``scripts/bench_cosim.sh``):
    struct-of-arrays epochs at n ∈ {1k, 4k, 16k, 65k, 100k} under a
    WAN-real delay model (5 continental zones, lognormal tails, 2%%
    crashed), one fused device launch per epoch, O(1) Python objects.

    Two legs, all rows also collected into ``BENCH_COSIM_r0.json``:

    1. scale — per-n rows from ``run_epoch_packed``: cold (compile)
       epoch, warm epochs/s (median of ``epochs``), peak RSS, packed
       device bytes per validator, mesh device count.
    2. equivalence — the packed queueing co-sim vs the dict-based
       ``VectorizedQueueingSim`` from equal-seeded rngs at n=1024:
       committed batches must be byte-identical every epoch (the same
       gate ``tests/test_cosim.py`` holds at small n; the sweep's
       numbers are only meaningful because this row is exact).

    Sweep sizes come from ``HBBFT_TPU_COSIM_NS`` (comma-separated)
    when set.  Mock-crypto protocol plane throughout — the co-sim's
    own contract (real BLS belongs to the dict-based sims).
    """
    import os
    import random as _r
    import statistics as _st

    from hbbft_tpu.harness.cosim import (
        PackedHoneyBadgerCosim,
        PackedQueueingCosim,
    )
    from hbbft_tpu.harness.epoch import VectorizedQueueingSim
    from hbbft_tpu.harness.wan import (
        DEFAULT_TOPOLOGY,
        LatencyModel,
        WanModel,
    )

    env_ns = os.environ.get("HBBFT_TPU_COSIM_NS")
    if ns is None and env_ns:
        ns = [int(x) for x in env_ns.split(",") if x]
    ns = list(ns or (1000, 4096, 16384, 65536, 100000))
    rows = []

    # -- leg 1: the scale sweep under the WAN model --------------------
    wan = WanModel(
        seed=0xC052,
        topology=DEFAULT_TOPOLOGY,
        latency=LatencyModel("lognormal"),
        deadline_ms=400.0,
    )
    for n in ns:
        f = (n - 1) // 3
        n_dead = min(n // 50, f)  # 2% crashed, inside the f bound
        dead = set(range(n - n_dead, n))
        t0 = time.perf_counter()
        sim = PackedHoneyBadgerCosim(n, _r.Random(0xC053), wan=wan)
        init_s = time.perf_counter() - t0
        cold = sim.run_epoch_packed(dead=dead)  # pays the compile
        warm = [sim.run_epoch_packed(dead=dead) for _ in range(epochs)]
        rate = _st.median(s.epochs_per_s for s in warm)
        last = warm[-1]
        rows.append(
            _emit(
                "cosim_epochs_per_s",
                rate,
                "epochs/s",
                nodes=n,
                dead=n_dead,
                epochs=epochs,
                init_s=round(init_s, 2),
                cold_epoch_s=round(cold.wall_s, 3),
                warm_epoch_s=round(1.0 / rate, 4),
                accepted=last.accepted,
                coin_flips=last.coin_flips,
                peak_rss_mb=round(last.peak_rss_bytes / 2**20, 1),
                bytes_per_validator=round(last.bytes_per_validator, 1),
                mesh_devices=last.mesh_devices,
                wan_zones=len(DEFAULT_TOPOLOGY.zones),
                wan_distribution="lognormal",
            )
        )

    # -- leg 2: byte-identity vs the dict plane at n=1024 -------------
    # (AFTER the sweep: the dict plane's ~1.7 GB of per-node Python
    # objects would otherwise pollute every sweep row's RSS high-water)
    n_twin, twin_epochs = 1024, 2
    dead = set(range(n_twin - 30, n_twin))
    legacy = VectorizedQueueingSim(
        n_twin, _r.Random(0xC051), batch_size=n_twin, mock=True
    )
    packed = PackedQueueingCosim(
        n_twin, _r.Random(0xC051), batch_size=n_twin
    )
    txs = [b"cosim-%06d" % i for i in range(2 * n_twin)]
    legacy.input_all(txs)
    packed.input_all(txs)
    t0 = time.perf_counter()
    for _ in range(twin_epochs):
        res_l = legacy.run_epoch(dead=dead)
        res_p = packed.run_epoch(dead=dead)
        assert res_l.batch == res_p.batch, "packed plane diverged"
        assert res_l.accepted == res_p.accepted
        assert [(f.node_id, f.kind) for f in res_l.fault_log] == [
            (f.node_id, f.kind) for f in res_p.fault_log
        ]
    rows.append(
        _emit(
            "cosim_twin_identity",
            1.0,
            "bool",
            nodes=n_twin,
            epochs=twin_epochs,
            dead=len(dead),
            wall_s=round(time.perf_counter() - t0, 2),
        )
    )

    sweep = [r for r in rows if r["metric"] == "cosim_epochs_per_s"]
    rows.append(
        _emit(
            "cosim_sweep",
            max(r["nodes"] for r in sweep),
            "validators",
            rates={str(r["nodes"]): r["value"] for r in sweep},
            peak_rss_mb={
                str(r["nodes"]): r["peak_rss_mb"] for r in sweep
            },
            host_cores=os.cpu_count(),
        )
    )
    if out:
        path = os.path.join(
            os.path.dirname(os.path.abspath(__file__)), out
        )
        with open(path, "w") as fh:
            json.dump(rows, fh, indent=2)
            fh.write("\n")
        print("wrote %d rows to %s" % (len(rows), path), flush=True)
    return rows


SUITE = {
    "sim_default": lambda: bench_sim_default(batched=False),
    "sim_batched": lambda: bench_sim_default(batched=True),
    "sim_real_pair": bench_sim_real_pair,
    "coin64": bench_coin64,
    "coin1024": bench_coin1024,
    "hb_dec_round": bench_hb_dec_round,
    "broadcast_1mb": bench_broadcast_1mb,
    "broadcast_vec": bench_broadcast_vec,
    "decshares": bench_decshares,
    "qhb_scale": bench_qhb_scale,
    "qhb_1024": bench_qhb_1024,
    "qhb_1024_txrate": bench_qhb_1024_txrate,
    "hb_1024_real": bench_hb_1024_real,
    "hb_1024_observer": bench_hb_1024_observer,
    "qhb_dyn_1024": bench_qhb_dyn_1024,
    "hb_1024_latency": bench_hb_1024_latency,
    "latency": bench_latency,
    "dkg_verified": bench_dkg_verified,
    "dkg_256": bench_dkg_256,
    "dkg_verified_256": bench_dkg_verified_256,
    "dkg_verified_512": bench_dkg_verified_512,
    "dkg_1024": bench_dkg_1024,
    "churn_256": bench_churn_256,
    "churn_1024": bench_churn_1024,
    "qhb_dyn_1024_real": bench_qhb_dyn_1024_real,
    "broadcast_vec_1024": bench_broadcast_vec_1024,
    "hb_epoch64_real": bench_hb_epoch64_real,
    "serve": bench_serve,
    "serve_vector": bench_serve_vector,
}


def main() -> None:
    # the EC scan kernels are large XLA programs; cache compilations so
    # repeated bench runs skip the multi-minute cold compile
    import os

    import jax

    # the bench is a warming entry point: new device shapes MAY pay
    # their one-time compile here (production routing never does —
    # ops/backend_tpu._device_g1_msm falls back to host when cold)
    os.environ.setdefault("HBBFT_TPU_WARM", "1")

    # --cold measures the ``.palexe`` mechanism in isolation, so it
    # must NOT get a lift from jax's own persistent compilation cache
    cold_mode = "--cold" in __import__("sys").argv
    if not cold_mode:
        cache = os.path.join(
            os.path.dirname(os.path.abspath(__file__)), ".xla_cache"
        )
        os.makedirs(cache, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", cache)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--suite", action="store_true", help="run all configs")
    p.add_argument("--config", choices=sorted(SUITE), help="run one config")
    p.add_argument(
        "--k",
        type=int,
        default=None,
        help="batch size (default: 65536 headline, 512 --mesh)",
    )
    p.add_argument(
        "--mesh",
        action="store_true",
        help="per-device-count mesh scaling rows from the real flush "
        "path (spawns one child per device count; see scripts/"
        "bench_mesh.sh)",
    )
    p.add_argument(
        "--mesh-devices",
        default="1,2,4,8",
        help="comma-separated device counts for --mesh",
    )
    p.add_argument(
        "--mesh-child", type=int, default=None, help=argparse.SUPPRESS
    )
    p.add_argument(
        "--iters", type=int, default=3, help="flush iterations (--mesh)"
    )
    p.add_argument(
        "--latency",
        action="store_true",
        help="commit-latency A-B matrix: {eager, speculative} decryption "
        "× {serial, pipelined} epochs on the protocol stack, real BLS, "
        "plus the {inline, ordered} order-then-reveal legs "
        "(see scripts/bench_latency.sh)",
    )
    p.add_argument(
        "--epochs", type=int, default=5, help="epochs per leg (--latency)"
    )
    p.add_argument(
        "--reveal-mode",
        choices=("both", "inline", "ordered"),
        default="both",
        help="which order-then-reveal legs the --latency matrix runs",
    )
    p.add_argument(
        "--cold",
        action="store_true",
        help="one fresh-process first flush under a compile-event "
        "trace (see scripts/bench_cold.sh for the virgin/primed pair)",
    )
    p.add_argument(
        "--cosim",
        action="store_true",
        help="100k-validator packed co-simulation sweep under a WAN "
        "delay model + the n=1024 dict-plane byte-identity leg "
        "(see scripts/bench_cosim.sh); sizes via HBBFT_TPU_COSIM_NS",
    )
    p.add_argument(
        "--cosim-out",
        default="BENCH_COSIM_r0.json",
        help="JSON file for the --cosim rows (relative to the repo "
        "root; empty string disables the file)",
    )
    p.add_argument(
        "--serve",
        action="store_true",
        help="serving-gateway headline: concurrent clients over the real "
        "TCP mesh, tx/s + commit p50/p99 (see scripts/bench_serve.sh)",
    )
    p.add_argument(
        "--serve-vector",
        action="store_true",
        help="BASELINE config #5 (n=1024, adversarial, 100 epochs) "
        "behind the gateway with synthetic million-client tenants",
    )
    p.add_argument(
        "--obs-bench",
        action="store_true",
        help="A/B recorder overhead with the export plane live (flight "
        "ring mirror + periodic exposition render) vs the bare "
        "recorder; the acceptance bar is within 5%%",
    )
    p.add_argument(
        "--duration", type=float, default=5.0, help="seconds (--serve)"
    )
    p.add_argument(
        "--trace",
        metavar="PATH",
        default=None,
        help="write a JSONL observability trace (hbbft_tpu.obs) to PATH; "
        "summarize with `python -m hbbft_tpu.obs.report PATH`",
    )
    args = p.parse_args()
    if args.trace:
        from hbbft_tpu.obs import recorder as obsrec

        obsrec.enable(args.trace)
    try:
        if args.cosim:
            bench_cosim(
                epochs=args.epochs if args.epochs != 5 else 3,
                out=args.cosim_out or None,
            )
        elif args.serve:
            bench_serve(duration=args.duration)
        elif args.serve_vector:
            bench_serve_vector(epochs=args.epochs if args.epochs != 5 else 100)
        elif args.obs_bench:
            bench_obs_overhead()
        elif args.latency:
            bench_latency(
                nodes=args.k or 13,
                epochs=args.epochs,
                reveal_mode=args.reveal_mode,
            )
        elif args.cold:
            bench_cold(k=args.k or 4096)
        elif args.mesh_child:
            bench_mesh_child(
                args.mesh_child, k=args.k or 512, iters=args.iters
            )
        elif args.mesh:
            bench_mesh(
                k=args.k or 512,
                iters=args.iters,
                devices=tuple(
                    int(x) for x in args.mesh_devices.split(",") if x
                ),
            )
        elif args.config:
            SUITE[args.config]()
        elif args.suite:
            for name in SUITE:
                SUITE[name]()
        else:
            bench_headline(k=args.k or 65536)
    finally:
        if args.trace:
            obsrec.disable()


if __name__ == "__main__":
    main()
