"""Headline benchmark: batched threshold-share verification throughput.

The reference's per-epoch hot loop is N² BLS share verifications
(``honey_badger.rs:422-444``: N proposers × N senders) plus combines —
each a 2-pairing check in the ``threshold_crypto`` crate.  This bench
measures our replacement: the random-linear-combination batch verify
whose MSMs run as device kernels (``ops/ec_jax.py``) with exactly two
pairings per *batch* (host-side).

Prints ONE JSON line:
  {"metric": "share_verify_throughput", "value": <shares/sec>,
   "unit": "shares/s", "vs_baseline": <speedup over per-share CPU path>}

vs_baseline compares against the sequential CPU reference path
(per-share 2-pairing checks, the faithful stand-in for the reference's
crate loop) measured on a sample in the same process.
"""

from __future__ import annotations

import json
import random
import time


def main() -> None:
    import jax
    import jax.numpy as jnp

    from hbbft_tpu.crypto.curve import G1_GEN, G2_GEN
    from hbbft_tpu.crypto.hashing import hash_to_g1
    from hbbft_tpu.crypto import threshold as T
    from hbbft_tpu.ops import ec_jax, limbs as LB
    from hbbft_tpu.ops.backend_tpu import TpuBackend

    rng = random.Random(0xBEEF)
    K = 128  # shares per batch (≈ one 128-validator epoch row)

    base = hash_to_g1(b"bench-epoch-nonce")
    sks = [rng.randrange(1, LB.R) for _ in range(K)]
    shares = [base * sk for sk in sks]
    pks = [G2_GEN * sk for sk in sks]

    be = TpuBackend()

    # -- device path: RLC batch verify (2 pairings total) -----------------
    ok = be.batch_verify_shares(shares, pks, base, b"warmup")  # compile
    assert ok
    iters = 3
    t0 = time.perf_counter()
    for i in range(iters):
        assert be.batch_verify_shares(shares, pks, base, b"ctx%d" % i)
    dt = (time.perf_counter() - t0) / iters
    device_rate = K / dt

    # -- baseline: per-share pairing checks (CPU reference path) ----------
    sample = 4
    t0 = time.perf_counter()
    from hbbft_tpu.crypto.threshold import PublicKeyShare, SignatureShare

    for i in range(sample):
        assert PublicKeyShare(pks[i]).verify_signature_share_g1(
            SignatureShare(shares[i]), base
        )
    cpu_per_share = (time.perf_counter() - t0) / sample
    cpu_rate = 1.0 / cpu_per_share

    print(
        json.dumps(
            {
                "metric": "share_verify_throughput",
                "value": round(device_rate, 2),
                "unit": "shares/s",
                "vs_baseline": round(device_rate / cpu_rate, 2),
            }
        )
    )


if __name__ == "__main__":
    main()
