"""Packed-wire device MSM path (``ops/packed_msm.py``).

The on-device unpack must be bit-identical to the host marshalling
(``ec_jax.g1_to_limbs`` + ``scalars_to_bits``/``bits_to_digits``), and
the end-to-end packed MSM must equal the host MSM — including infinity
encodings, bucket padding, and the chunked multi-partial path.
"""

import random

import numpy as np
import pytest

from hbbft_tpu.crypto.curve import G1, G1_GEN
from hbbft_tpu.ops import ec_jax, limbs as LB, packed_msm, pallas_ec


def _random_points(rng, n, with_inf=True):
    pts = [G1_GEN * rng.randrange(1, 1 << 64) for _ in range(n)]
    if with_inf and n >= 3:
        pts[1] = G1.infinity()
    return pts


def test_g1_wires_batch_matches_native_wire():
    from hbbft_tpu import native as NT

    rng = random.Random(7)
    pts = _random_points(rng, 9)
    # strip any memoized wire so the slow path is exercised too
    jacs = [p.jac for p in pts]
    fresh = [G1(j) for j in jacs]
    wires = packed_msm.g1_wires_batch(fresh)
    assert wires.shape == (9, 96)
    for i, p in enumerate(pts):
        assert wires[i].tobytes() == NT.g1_wire(p)
    # memoized round: identical result through the fast path
    again = packed_msm.g1_wires_batch(fresh)
    assert np.array_equal(wires, again)


def test_g1_wires_batch_jacobian_batch_inversion():
    rng = random.Random(11)
    # points built by repeated addition carry Z != 1 Jacobian coords
    pts = []
    for _ in range(6):
        p = G1_GEN * rng.randrange(1, 1 << 40)
        q = p + G1_GEN  # Jacobian add → Z != 1, no memoized wire
        pts.append(G1(q.jac))
    from hbbft_tpu import native as NT

    wires = packed_msm.g1_wires_batch(pts)
    for i, p in enumerate(pts):
        assert wires[i].tobytes() == NT.g1_wire(p)


def test_unpack_matches_host_marshalling():
    rng = random.Random(23)
    pts = _random_points(rng, 7)
    scalars = [rng.randrange(0, 1 << 128) for _ in range(7)]
    nb = 16

    kp = packed_msm._bucket_rows(len(pts))
    wires = packed_msm.g1_wires_batch(pts)
    sc = packed_msm.scalar_bytes_batch(scalars, nb)
    wires = np.concatenate(
        [wires, np.zeros((kp - 7, 96), dtype=np.uint8)]
    )
    sc = np.concatenate([sc, np.zeros((kp - 7, nb), dtype=np.uint8)])

    pts_t, dig_t = packed_msm._unpack_fn(wires, sc)

    # host reference: limb marshalling + tile transpose
    host_pts = ec_jax.g1_to_limbs(pts)
    host_dig = pallas_ec.bits_to_digits(LB.scalars_to_bits(scalars, 128))
    ref_pts_t, ref_dig_t, _, _ = pallas_ec._tile_transpose(
        host_pts, host_dig
    )
    assert np.array_equal(np.asarray(pts_t), np.asarray(ref_pts_t))
    assert np.array_equal(np.asarray(dig_t), np.asarray(ref_dig_t))


def _host_windowed_tiles(pts_t, dig_t, interpret):
    """Host reference stand-in for the Pallas windowed kernel: per-lane
    scalar-mul through the (independently tested) host curve ops.  Lets
    the end-to-end glue — bucket padding, chunk split, untile, tree
    reduction, finalizer combine — run fast on CPU; the real kernel is
    covered by ``test_pallas_ec.py`` and the hardware smoke gate."""
    pts_t = np.asarray(pts_t)
    dig_t = np.asarray(dig_t)
    G, _, L, T = pts_t.shape
    out = np.zeros_like(pts_t)
    for g in range(G):
        for t in range(T):
            pt = ec_jax.g1_from_limbs(pts_t[g, :, :, t])
            k = 0
            for d in dig_t[g, :, t]:
                k = (k << 4) | int(d)
            out[g, :, :, t] = ec_jax.g1_to_limbs([pt * k])[0]
    import jax.numpy as jnp

    return jnp.asarray(out)


@pytest.fixture
def host_kernel(monkeypatch):
    monkeypatch.setattr(pallas_ec, "_windowed_tiles", _host_windowed_tiles)


def _host_msm(pts, scalars):
    from hbbft_tpu.crypto.backend import CpuBackend

    return CpuBackend().g1_msm(pts, scalars)


def test_packed_msm_matches_host_small(host_kernel):
    rng = random.Random(5)
    pts = _random_points(rng, 5)
    scalars = [rng.randrange(0, 1 << 16) for _ in range(5)]
    got = packed_msm.g1_msm_packed(pts, scalars, nbits=16, interpret=True)
    assert got == _host_msm(pts, scalars)


def test_packed_msm_chunked(host_kernel, monkeypatch):
    monkeypatch.setattr(packed_msm, "_MAX_CHUNK", 256)
    rng = random.Random(9)
    n = 300  # spans two chunks: 256 + 44 (bucket-padded to 128)
    pts = _random_points(rng, n)
    scalars = [rng.randrange(0, 1 << 16) for _ in range(n)]
    got = packed_msm.g1_msm_packed(pts, scalars, nbits=16, interpret=True)
    assert got == _host_msm(pts, scalars)


def test_packed_msm_empty_and_zero_scalars(host_kernel):
    assert packed_msm.g1_msm_packed([], []) == G1.infinity()
    rng = random.Random(3)
    pts = _random_points(rng, 3, with_inf=False)
    got = packed_msm.g1_msm_packed(pts, [0, 0, 0], nbits=16, interpret=True)
    assert got == G1.infinity()


def test_compressed_unpack_matches_uncompressed():
    # 48-byte-x + parity/infinity bits must reconstruct exactly the
    # limb layout of the 96-byte path (device sqrt + sign correction)
    rng = random.Random(61)
    k = 128
    pts = _random_points(rng, k)  # includes one infinity
    scalars = [rng.getrandbits(16) for _ in range(k)]
    wires = packed_msm.g1_wires_batch(pts)
    sc = packed_msm.scalar_bytes_batch(scalars, 2)
    x, meta = packed_msm.compress_rows(wires, k)
    ref_pts_t, ref_dig_t = packed_msm._unpack_fn(wires, sc)
    got_pts_t, got_dig_t = packed_msm._unpack_fn_compressed(x, meta, sc)
    # limb forms may differ (canonical vs redundant); compare points
    from hbbft_tpu.ops import ec_jax

    ref = np.asarray(ref_pts_t)
    got = np.asarray(got_pts_t)
    assert np.array_equal(np.asarray(got_dig_t), np.asarray(ref_dig_t))
    G, _, L, T = ref.shape
    for g in range(G):
        for t in range(0, T, 17):  # sample lanes
            a = ec_jax.g1_from_limbs(ref[g, :, :, t])
            b = ec_jax.g1_from_limbs(got[g, :, :, t])
            assert a == b, (g, t)


def test_product_async_default_matches_flat():
    from hbbft_tpu.crypto.backend import CpuBackend
    from hbbft_tpu.crypto import fields as F

    rng = random.Random(41)
    be = CpuBackend()
    pts = _random_points(rng, 6, with_inf=False)
    s = [rng.getrandbits(96) | 1 for _ in range(6)]
    ts = [rng.getrandbits(96) | 1 for _ in range(2)]
    fin = be.g1_msm_product_async(pts, s, ts, [3, 3])
    flat = [
        (s[i] * ts[g]) % F.R for g in range(2) for i in (3 * g, 3 * g + 1, 3 * g + 2)
    ]
    assert fin() == be.g1_msm(pts, flat)


def test_packed_product_shape_fallbacks(monkeypatch):
    rng = random.Random(43)
    pts = _random_points(rng, 6, with_inf=False)
    s = [1] * 6
    # non-uniform group sizes → None
    assert packed_msm.g1_msm_product_async(pts, s, [1, 1], [2, 4]) is None
    assert packed_msm.g1_msm_product_async([], [], [], []) is None
    # a single group past the proven per-group-tree scale → None
    # (fraction 1 so the want>0 path actually reaches the guard)
    monkeypatch.setattr(packed_msm, "_MAX_GTREE", 4)
    monkeypatch.setenv("HBBFT_TPU_DEVICE_FRACTION", "1")
    assert (
        packed_msm.g1_msm_product_async(pts, s, [1], [6]) is None
    )
    # device fraction 0 → all-host, no device share
    monkeypatch.setenv("HBBFT_TPU_DEVICE_FRACTION", "0")
    assert (
        packed_msm.g1_msm_product_async(pts, s, [1, 1, 1], [2, 2, 2])
        is None
    )


def test_ready_predicates_mirror_cached_keys(monkeypatch):
    """``_flat_ready``/``_product_ready`` must probe EXACTLY the
    executable keys the device paths build — any drift (a renamed
    kernel, a changed digit width, a different tree chunking) makes
    ``exec_available`` probe keys that are never written, and on
    production hosts (no ``HBBFT_TPU_WARM``) the device path then
    silently falls back to host Pippenger forever."""
    import jax

    built = []

    def rec_cc(name, fn, *args, key_parts=None, donate=()):
        if key_parts is None:
            key_parts = tuple(
                (tuple(a.shape), str(getattr(a, "dtype", "")))
                for a in args
            )
        built.append(pallas_ec._exec_key(name, key_parts))
        return jax.jit(fn)(*args)

    def rec_tiles(name, kernel, pts_t, aux_t):
        built.append(
            pallas_ec._exec_key(
                name, (tuple(pts_t.shape), tuple(aux_t.shape))
            )
        )
        return _host_windowed_tiles(pts_t, aux_t, True)

    monkeypatch.setattr(pallas_ec, "cached_compiled", rec_cc)
    monkeypatch.setattr(pallas_ec, "_cached_tiles", rec_tiles)
    monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
    monkeypatch.setenv("HBBFT_TPU_WARM", "1")
    monkeypatch.setenv("HBBFT_TPU_DEVICE_FRACTION", "0.5")

    rng = random.Random(67)
    from hbbft_tpu.crypto.backend import CpuBackend
    from hbbft_tpu.crypto import fields as F

    # flat path, k=5 → kp=128, nb=2
    pts = _random_points(rng, 5)
    scalars = [rng.getrandbits(16) for _ in range(5)]
    got = packed_msm.g1_msm_packed(pts, scalars, nbits=16)
    assert got == CpuBackend().g1_msm(pts, scalars)

    # product path, 4 groups of 3 → plan [2] (one ladder chunk of two
    # quanta), kd=6 padded to kp=128
    k, G = 12, 4
    ppts = _random_points(rng, k, with_inf=False)
    s = [rng.getrandbits(16) | 1 for _ in range(k)]
    ts = [rng.getrandbits(16) | 1 for _ in range(G)]
    fin = packed_msm.g1_msm_product_async(ppts, s, ts, [3] * G)
    assert fin is not None
    flat = [(s[g * 3 + i] * ts[g]) % F.R for g in range(G) for i in range(3)]
    assert fin() == CpuBackend().g1_msm(ppts, flat)

    # the predicates must probe exactly the keys the paths built
    probes = []
    monkeypatch.setattr(
        pallas_ec,
        "exec_available",
        lambda name, kp: probes.append(pallas_ec._exec_key(name, kp))
        or True,
    )
    assert packed_msm._flat_ready(128, 2)
    assert packed_msm._product_ready(6, 2, False)
    assert set(built) == set(probes), (
        sorted(set(built) - set(probes)),
        sorted(set(probes) - set(built)),
    )


def test_split_plan_shapes(monkeypatch):
    monkeypatch.setenv("HBBFT_TPU_DEVICE_FRACTION", "0.5")
    # headline flush 64×1024: the quantum is shape-only (4 groups —
    # 16 steps of resolution since r5), and the chosen quanta pack
    # into the FEWEST ladder chunks (each chunk pays a tunnel RPC
    # floor — the r5 A/B: 16×4-group chunks 2.24 s vs 2×32 0.6-1.2 s)
    assert packed_msm._split_plan(65536, 64) == [32]
    # hb_1024_real flush 974×974: uniform padded chunks within the
    # per-group-tree scale (the 2q/8q rungs exceed the 67-group cap)
    assert packed_msm._split_plan(948676, 974) == [60] * 8
    assert all(
        g * 974 <= packed_msm._MAX_GTREE
        for g in packed_msm._split_plan(948676, 974)
    )
    # full device fraction takes (nearly) everything
    monkeypatch.setenv("HBBFT_TPU_DEVICE_FRACTION", "1")
    plan = packed_msm._split_plan(948676, 974)
    assert sum(plan) == 960 and len(set(plan)) == 1
    assert packed_msm._split_plan(65536, 64) == [32, 32]
    # a non-ladder quantum count decomposes largest-first
    monkeypatch.setenv("HBBFT_TPU_DEVICE_FRACTION", "0.82")
    assert packed_msm._split_plan(65536, 64) == [32, 8, 8, 4]
    # ragged totals (not divisible by the group count) → no share
    assert packed_msm._split_plan(7, 3) == []


def test_split_plan_warm_filtering(monkeypatch):
    """On a real TPU outside warming mode, ladder sizes without warm
    executables are skipped — smaller warm chunks take their place —
    so production never pays a cold multi-minute Mosaic compile."""
    import jax

    monkeypatch.setenv("HBBFT_TPU_DEVICE_FRACTION", "1")
    monkeypatch.delenv("HBBFT_TPU_WARM", raising=False)
    monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
    # only the 8-group (2q) chunk shape is warm: the 32-group rung is
    # filtered out and the plan decomposes with what remains
    monkeypatch.setattr(
        packed_msm,
        "_product_ready",
        lambda kd, g, compressed, engine="pallas": g == 8,
    )
    assert packed_msm._split_plan(65536, 64) == [8] * 8
    # nothing warm at all: the quantum survives as the last resort and
    # the caller's own readiness check routes the flush host-side
    monkeypatch.setattr(
        packed_msm,
        "_product_ready",
        lambda kd, g, compressed, engine="pallas": False,
    )
    assert packed_msm._split_plan(65536, 64) == [4] * 16
    # warming mode uses the full ladder regardless of cache state
    monkeypatch.setenv("HBBFT_TPU_WARM", "1")
    assert packed_msm._split_plan(65536, 64) == [32, 32]


def test_rho_state_file_roundtrip(tmp_path, monkeypatch):
    """The persisted controller state (rho/d/h/hage/dc/cage/dage)
    survives a save/load cycle, tolerates legacy bare-rho entries, and
    drops malformed rows without losing the rest."""
    import json

    path = tmp_path / "device_fraction.json"
    monkeypatch.setattr(packed_msm, "_rho_path", lambda: str(path))
    monkeypatch.setattr(packed_msm, "_RHO_STATE", None)
    state = packed_msm._rho_state()
    state["1024:64"] = {
        "rho": 0.75, "d": 93061.4, "h": 38141.1, "hage": 2,
        "dc": 2038.7, "cage": 5, "dage": 1,
    }
    packed_msm._save_rho()
    raw = json.loads(path.read_text())
    raw["974:974"] = 0.25  # legacy bare-rho entry
    raw["bad"] = {"rho": "soup"}  # malformed: must not drop the rest
    path.write_text(json.dumps(raw))
    monkeypatch.setattr(packed_msm, "_RHO_STATE", None)
    st = packed_msm._rho_state()
    assert st["1024:64"] == {
        "rho": 0.75, "d": 93061.4, "h": 38141.1, "hage": 2,
        "dc": 2038.7, "cage": 5, "dage": 1,
    }
    assert st["974:974"]["rho"] == 0.25
    assert "bad" not in st


def test_adaptive_fraction_controller(monkeypatch):
    """The r5 rate-balance controller: EXACT device- and host-rate
    samples every flush (the waiter thread stamps the device wall, so
    no straggle-gating, no probe ratchet), EMA smoothing with a 3×
    slew clip, and a split that may cover the whole flush."""
    monkeypatch.delenv("HBBFT_TPU_DEVICE_FRACTION", raising=False)
    monkeypatch.setattr(packed_msm, "_RHO_STATE", {})
    monkeypatch.setattr(packed_msm, "_save_rho", lambda: None)
    n, g = 1024, 64
    K = 65536
    assert packed_msm.learned_fraction(n, g) == 0.5
    # equal halves: device wall 2.5 s (the waiter's stamp, launch →
    # group sums on host), host 1.0 s, caller overlap 0.5 s →
    # d = K/2 / 2.5, h = K/2 / 1.0 →
    # rho* = (0.5 + K/h)/(K/d + K/h) = 2.5/7
    packed_msm._adapt(n, g, K // 2, K // 2, 0.5, 1.0, 2.5)
    rho1 = packed_msm.learned_fraction(n, g)
    assert abs(rho1 - 2.5 / 7.0) < 1e-6
    # a faster device wall is an exact sample DOWNWARD too — the EMA
    # moves and the share climbs (r4 could only raise `d` on straggle)
    packed_msm._adapt(n, g, K // 2, K // 2, 0.5, 1.0, 0.5)
    assert packed_msm.learned_fraction(n, g) > rho1
    # ceiling is 1.0 now: a decisively faster device takes everything
    packed_msm._rho_state()["%d:%d" % (n, g)] = {
        "rho": 0.5, "d": 1e9, "h": 100.0, "hage": 0
    }
    packed_msm._adapt(n, g, K // 2, K // 2, 0.0, 300.0, 0.01)
    assert packed_msm.learned_fraction(n, g) > 0.999
    # floor: a collapsed device rate clamps at 0.02, not 0 — and the
    # slew-rate clip bounds one pathological flush's damage to 3×
    packed_msm._rho_state()["%d:%d" % (n, g)] = {
        "rho": 0.5, "d": 30000.0, "h": 30000.0, "hage": 0
    }
    packed_msm._adapt(n, g, K // 2, K // 2, 0.0, 1.0, 46.0)
    st = packed_msm._rho_state()["%d:%d" % (n, g)]
    assert st["d"] == 0.5 * 30000 + 0.5 * 10000  # clipped at d/3
    packed_msm._rho_state()["%d:%d" % (n, g)] = {
        "rho": 0.5, "d": 100.0, "h": 1e9, "hage": 0
    }
    packed_msm._adapt(n, g, K // 2, K // 2, 0.0, 0.001, 10.0)
    assert packed_msm.learned_fraction(n, g) == 0.02
    # an all-device flush (k_host = 0) cannot sample the host rate:
    # hage counts the staleness, a host flush resets it
    packed_msm._rho_state()["%d:%d" % (n, g)] = {
        "rho": 1.0, "d": 30000.0, "h": 30000.0, "hage": 0
    }
    for i in range(3):
        packed_msm._adapt(n, g, K, 0, 0.1, 0.0, 2.0)
    st = packed_msm._rho_state()["%d:%d" % (n, g)]
    assert st["hage"] == 3
    packed_msm._adapt(n, g, K - 4096, 4096, 0.1, 0.15, 2.0)
    assert st["hage"] == 0
    # seed_rates: the bench's forced-leg medians land as exact rates
    # and re-solve the split (r4 threw them away)
    packed_msm.seed_rates(n, g, d=34640.0, h=29472.0)
    st = packed_msm._rho_state()["%d:%d" % (n, g)]
    assert st["d"] == 34640.0 and st["h"] == 29472.0
    assert abs(st["rho"] - 34640.0 / (34640.0 + 29472.0)) < 1e-9
    # adaptive plans keep one device chunk at the floor (an all-host
    # plan never reaches the finalizer's measurement), and may cover
    # EVERYTHING at the ceiling — until the host rate goes stale, at
    # which point one quantum is handed back as a host probe
    packed_msm._rho_state()["1024:64"] = 0.10
    assert packed_msm._split_plan(65536, 64) == [8]
    packed_msm._rho_state()["1024:64"] = {
        "rho": 1.0, "d": 34640.0, "h": 29472.0, "hage": 0
    }
    assert packed_msm._split_plan(65536, 64) == [32, 32]  # full device
    packed_msm._rho_state()["1024:64"]["hage"] = packed_msm._HOST_PROBE_IV
    # host probe: one quantum handed back, rest packed largest-first
    assert packed_msm._split_plan(65536, 64) == [32, 8, 8, 8, 4]
    # a single-group flush cannot be balanced (no host tail possible):
    # adaptive mode keeps it host-side rather than freezing at 100%
    assert packed_msm._split_plan(2048, 1) == []
    # env override pins every shape, bypasses the learned state, and
    # may take the whole flush (the bench's device-only leg)
    monkeypatch.setenv("HBBFT_TPU_DEVICE_FRACTION", "0.75")
    assert packed_msm.learned_fraction(n, g) == 0.75
    assert packed_msm.learned_fraction(7, 7) == 0.75
    monkeypatch.setenv("HBBFT_TPU_DEVICE_FRACTION", "1")
    assert packed_msm._split_plan(65536, 64) == [32, 32]
    # malformed override: fall back to the learned state, not 0.5-pin
    packed_msm._rho_state()["1024:64"] = 0.10
    monkeypatch.setenv("HBBFT_TPU_DEVICE_FRACTION", "half")
    assert packed_msm.learned_fraction(n, g) == 0.10
    monkeypatch.setenv("HBBFT_TPU_DEVICE_FRACTION", "nan")
    assert packed_msm.learned_fraction(n, g) == 0.10


def _host_windowed_g2_tiles(pts_t, dig_t, interpret):
    """Host reference stand-in for the Fq2 windowed kernel (see
    ``_host_windowed_tiles``) — the real kernel is covered by
    ``test_pallas_ec.py`` and the hardware smoke gate; interpret mode
    at G2 cost is minutes even for one tile."""
    pts_t = np.asarray(pts_t)
    dig_t = np.asarray(dig_t)
    G, _, _, L, T = pts_t.shape
    out = np.zeros_like(pts_t)
    for g in range(G):
        for t in range(T):
            pt = ec_jax.g2_from_limbs(pts_t[g, :, :, :, t])
            k = 0
            for d in dig_t[g, :, t]:
                k = (k << 4) | int(d)
            out[g, :, :, :, t] = ec_jax.g2_to_limbs([pt * k])[0]
    import jax.numpy as jnp

    return jnp.asarray(out)


def test_g2_packed_wires_matches_host(monkeypatch):
    """The packed-wire flat G2 MSM (192-byte wires in, wire out) —
    the DKG verification plane's shape — equals the host MSM,
    including an infinity row and chunk padding."""
    from hbbft_tpu import native as NT
    from hbbft_tpu.crypto.backend import CpuBackend
    from hbbft_tpu.crypto.curve import G2, G2_GEN

    monkeypatch.setattr(
        pallas_ec, "_windowed_g2_tiles", _host_windowed_g2_tiles
    )
    rng = random.Random(71)
    k = 9
    pts = [G2_GEN * rng.randrange(1, 1 << 40) for _ in range(k)]
    pts[4] = G2.infinity()
    scalars = [rng.getrandbits(16) for _ in range(k)]
    wires = [NT.g2_wire(p) for p in pts]
    fin = packed_msm.g2_msm_packed_wires_async(
        wires, scalars, interpret=True, nbits=16
    )
    got = fin()
    expect = CpuBackend().g2_msm(pts, scalars)
    assert got == NT.g2_wire(expect)
    assert packed_msm.g2_msm_packed_wires_async([], [])() == b"\x00" * 192


def test_compressed_mode_controller(monkeypatch):
    """The compressed-transfer flip is MEASURED per shape (VERDICT r4
    next-8): separate device-rate EMAs for the 96-byte and 48-byte
    wires, a periodic trial flush, and the faster mode ships."""
    import jax

    monkeypatch.delenv("HBBFT_TPU_COMPRESS", raising=False)
    monkeypatch.delenv("HBBFT_TPU_DEVICE_FRACTION", raising=False)
    monkeypatch.setattr(packed_msm, "_RHO_STATE", {})
    monkeypatch.setattr(packed_msm, "_save_rho", lambda: None)
    monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
    monkeypatch.setattr(packed_msm, "_product_ready", lambda *a: True)
    n, g = 1024, 64
    K = 65536
    plan = [32, 32]
    # no measured state yet → uncompressed default
    assert not packed_msm._choose_compressed(n, g, plan)
    # after the first uncompressed sample, dc is unknown → trial fires
    packed_msm._adapt(n, g, K, 0, 0.1, 0.0, 1.0)
    assert packed_msm._choose_compressed(n, g, plan)
    # trial measured SLOWER → ship uncompressed between probes
    packed_msm._adapt(n, g, K, 0, 0.1, 0.0, 2.0, compressed=True)
    assert not packed_msm._choose_compressed(n, g, plan)
    # the probe interval elapses → another trial
    for _ in range(packed_msm._COMPRESS_PROBE_IV):
        packed_msm._adapt(n, g, K, 0, 0.1, 0.0, 1.0)
    assert packed_msm._choose_compressed(n, g, plan)
    # a compressed-wins regime (link-bound tunnel) ships compressed
    st = packed_msm._rho_state()["%d:%d" % (n, g)]
    st["dc"] = st["d"] * 2
    st["cage"] = 0
    assert packed_msm._choose_compressed(n, g, plan)
    # symmetric staleness: a compressed-winning streak must still
    # re-probe the UNCOMPRESSED wire (the tunnel idling again would
    # otherwise never be detected)
    for _ in range(packed_msm._COMPRESS_PROBE_IV):
        packed_msm._adapt(n, g, K, 0, 0.1, 0.0, 1.0, compressed=True)
    assert not packed_msm._choose_compressed(n, g, plan)
    packed_msm._adapt(n, g, K, 0, 0.1, 0.0, 1.0)  # uncompressed sample
    st = packed_msm._rho_state()["%d:%d" % (n, g)]
    assert st["dage"] == 0
    # seeding never degrades a converged (higher) engine estimate:
    # leg medians are end-to-end lower bounds
    st["d"], st["h"] = 77000.0, 31000.0
    packed_msm.seed_rates(n, g, d=34640.0, h=29472.0)
    assert st["d"] == 77000.0 and st["h"] == 31000.0
    packed_msm.seed_rates(n, g, d=90000.0, h=40000.0)
    assert st["d"] == 90000.0 and st["h"] == 40000.0
    # operator pin overrides measurement both ways
    monkeypatch.setenv("HBBFT_TPU_COMPRESS", "0")
    assert not packed_msm._choose_compressed(n, g, plan)
    monkeypatch.setenv("HBBFT_TPU_COMPRESS", "1")
    assert packed_msm._choose_compressed(n, g, plan)


def test_packed_product_padded_groups(host_kernel):
    # group sizes that never land on a tile bucket (the hb_1024_real
    # shape family): the device chunk is bucket-padded and the padding
    # sliced off before the per-group tree — results must still equal
    # the flat host MSM, with the trailing groups on host Pippenger
    from hbbft_tpu.crypto.backend import CpuBackend
    from hbbft_tpu.crypto import fields as F

    rng = random.Random(59)
    G, n = 4, 3  # k = 12; plan takes 2 leading groups (kd=6 → kp=128)
    k = G * n
    pts = _random_points(rng, k, with_inf=True)
    s = [rng.getrandbits(16) | 1 for _ in range(k)]
    ts = [rng.getrandbits(16) | 1 for _ in range(G)]
    fin = packed_msm.g1_msm_product_async(
        pts, s, ts, [n] * G, interpret=True
    )
    assert fin is not None
    flat = [
        (s[g * n + i] * ts[g]) % F.R for g in range(G) for i in range(n)
    ]
    assert fin() == CpuBackend().g1_msm(pts, flat)


def test_packed_product_matches_flat(host_kernel):
    # uniform 2×128 groups: k = 256 lands exactly on the tile bucket,
    # so the factored device layout applies (group trees + host t-MSM)
    from hbbft_tpu.crypto.backend import CpuBackend
    from hbbft_tpu.crypto import fields as F

    rng = random.Random(47)
    k, G = 256, 2
    base_pts = _random_points(rng, k, with_inf=True)
    s = [rng.getrandbits(16) | 1 for _ in range(k)]
    ts = [rng.getrandbits(16) | 1 for _ in range(G)]
    sizes = [k // G] * G
    fin = packed_msm.g1_msm_product_async(
        base_pts, s, ts, sizes, interpret=True
    )
    assert fin is not None
    n = k // G
    flat = [
        (s[g * n + i] * ts[g]) % F.R for g in range(G) for i in range(n)
    ]
    assert fin() == CpuBackend().g1_msm(base_pts, flat)


def test_shipped_points_passthrough_cpu():
    # on CPU g1_ship returns the plain list; the TpuBackend product
    # seam still routes through the flat default and stays correct
    from hbbft_tpu.ops.backend_tpu import TpuBackend
    from hbbft_tpu.crypto.backend import CpuBackend
    from hbbft_tpu.crypto import fields as F

    rng = random.Random(53)
    be = TpuBackend()
    be.G1_DEVICE_MIN = 0
    be.G1_DEVICE_MAX = 1 << 62
    pts = _random_points(rng, 4, with_inf=False)
    shipped = be.g1_ship(pts)
    assert shipped == pts  # no device in CPU tests
    s = [3, 5, 7, 9]
    ts = [11, 13]
    fin = be.g1_msm_product_async(shipped, s, ts, [2, 2])
    flat = [(s[0] * 11) % F.R, (s[1] * 11) % F.R, (s[2] * 13) % F.R, (s[3] * 13) % F.R]
    assert fin() == CpuBackend().g1_msm(pts, flat)


def test_backend_async_finalizer_cpu_route():
    """On CPU the TpuBackend async seam must fall back to the XLA limb
    path and still return correct results through the finalizer."""
    from hbbft_tpu.ops.backend_tpu import TpuBackend

    rng = random.Random(31)
    be = TpuBackend()
    be.G1_DEVICE_MIN = 0
    be.G1_DEVICE_MAX = 1 << 62
    pts = _random_points(rng, 4, with_inf=False)
    scalars = [rng.randrange(1, 1 << 64) for _ in range(4)]
    fin = be.g1_msm_async(pts, scalars)
    from hbbft_tpu.crypto.backend import CpuBackend

    assert fin() == CpuBackend().g1_msm(pts, scalars)
