"""Threshold crypto, RS, Merkle, mock-equivalence, serialization tests."""

import random

import pytest

from hbbft_tpu.core.serialize import dumps, loads
from hbbft_tpu.crypto import mock as M
from hbbft_tpu.crypto import threshold as T
from hbbft_tpu.crypto.curve import G1, G2_GEN
from hbbft_tpu.crypto.hashing import hash_to_g1
from hbbft_tpu.crypto.merkle import MerkleProof, MerkleTree
from hbbft_tpu.crypto.poly import (
    BivarPoly,
    Poly,
    interpolate_at_zero,
    lagrange_coefficients_at_zero,
)
from hbbft_tpu.crypto.rs import ReedSolomon
from hbbft_tpu.crypto import fields as F


@pytest.fixture(params=["real", "mock"], ids=["bls", "mock"])
def keyset(request):
    rng = random.Random(33)
    if request.param == "real":
        sks = T.SecretKeySet.random(1, rng)
    else:
        sks = M.MockSecretKeySet.random(1, rng)
    return sks, sks.public_keys(), rng


class TestThresholdSignatures:
    def test_sign_verify_combine_subset_independent(self, keyset):
        sks, pkset, rng = keyset
        msg = b"epoch-7-coin"
        shares = {i: sks.secret_key_share(i).sign(msg) for i in range(4)}
        for i, s in shares.items():
            assert pkset.public_key_share(i).verify_signature_share(s, msg)
            assert not pkset.public_key_share(i).verify_signature_share(
                s, msg + b"!"
            )
        sig_a = pkset.combine_signatures({i: shares[i] for i in (0, 1)})
        sig_b = pkset.combine_signatures({i: shares[i] for i in (2, 3)})
        assert sig_a == sig_b
        assert pkset.verify_signature(sig_a, msg)
        assert not pkset.verify_signature(sig_a, b"other")
        assert isinstance(sig_a.parity(), bool)

    def test_combine_requires_threshold(self, keyset):
        sks, pkset, rng = keyset
        share = {0: sks.secret_key_share(0).sign(b"m")}
        with pytest.raises(ValueError):
            pkset.combine_signatures(share)

    def test_wrong_index_share_rejected(self, keyset):
        sks, pkset, rng = keyset
        msg = b"m"
        s0 = sks.secret_key_share(0).sign(msg)
        assert not pkset.public_key_share(1).verify_signature_share(s0, msg)


def test_combine_decryption_shares_many_matches_per_row():
    """The batched combine (one native call per shared valid-index
    subset) is bit-identical to per-row combines — including rows
    whose subset differs (the Byzantine knock-out case), which take
    the fallback path."""
    rng = random.Random(0xC01)
    sks = T.SecretKeySet.random(2, rng)
    pkset = sks.public_keys()
    pk = pkset.public_key()
    cts, rows = [], []
    for p in range(9):
        ct = pk.encrypt(b"many-%d" % p, rng)
        cts.append(ct)
        senders = (
            range(3) if p != 4 else (1, 2, 3)  # row 4: different subset
        )
        rows.append(
            {
                i: sks.secret_key_share(i).decrypt_share_no_verify(ct)
                for i in senders
            }
        )
    got = pkset.combine_decryption_shares_many(rows, cts)
    for p in range(9):
        assert got[p] == pkset.combine_decryption_shares(rows[p], cts[p])
        assert got[p] == b"many-%d" % p
    with pytest.raises(ValueError, match="not enough"):
        pkset.combine_decryption_shares_many(
            [{0: rows[0][0]}], [cts[0]]
        )


class TestThresholdEncryption:
    def test_roundtrip_and_validity(self, keyset):
        sks, pkset, rng = keyset
        pk = pkset.public_key()
        ct = pk.encrypt(b"contribution", rng)
        assert ct.verify()
        shares = {
            i: sks.secret_key_share(i).decrypt_share_no_verify(ct)
            for i in range(4)
        }
        for i, d in shares.items():
            assert pkset.public_key_share(i).verify_decryption_share(d, ct)
        m1 = pkset.combine_decryption_shares(
            {i: shares[i] for i in (0, 3)}, ct
        )
        m2 = pkset.combine_decryption_shares(
            {i: shares[i] for i in (1, 2)}, ct
        )
        assert m1 == m2 == b"contribution"

    def test_tampered_ciphertext_fails_verify(self, keyset):
        sks, pkset, rng = keyset
        ct = pkset.public_key().encrypt(b"data", rng)
        if isinstance(ct, T.Ciphertext):
            bad = T.Ciphertext(ct.u, ct.v + b"x", ct.c, ct.z)
        else:
            bad = M.MockCiphertext(ct.seed_id, ct.nonce, ct.v + b"x", ct.mac)
        assert not bad.verify()

    def test_faulty_share_detected(self, keyset):
        sks, pkset, rng = keyset
        ct = pkset.public_key().encrypt(b"data", rng)
        good = sks.secret_key_share(0).decrypt_share_no_verify(ct)
        # share from wrong index presented as index 1
        assert not pkset.public_key_share(1).verify_decryption_share(good, ct)


class TestIndividualKeys:
    def test_sign_verify(self, keyset):
        sks, pkset, rng = keyset
        cls = T.SecretKey if isinstance(sks, T.SecretKeySet) else M.MockSecretKey
        sk = cls.random(rng)
        sig = sk.sign(b"vote:Remove(2)")
        assert sk.public_key().verify(sig, b"vote:Remove(2)")
        assert not sk.public_key().verify(sig, b"vote:Remove(3)")

    def test_encrypt_decrypt(self, keyset):
        sks, pkset, rng = keyset
        cls = T.SecretKey if isinstance(sks, T.SecretKeySet) else M.MockSecretKey
        sk = cls.random(rng)
        ct = sk.public_key().encrypt(b"dkg row bytes", rng)
        assert sk.decrypt(ct) == b"dkg row bytes"


class TestBatchVerification:
    def test_batch_accepts_good_rejects_bad(self):
        rng = random.Random(5)
        sks = T.SecretKeySet.random(1, rng)
        pkset = sks.public_keys()
        msg = b"batched"
        h = hash_to_g1(msg)
        shares = [sks.secret_key_share(i).sign(msg) for i in range(4)]
        pks = [pkset.public_key_share(i).point for i in range(4)]
        pts = [s.point for s in shares]
        assert T.batch_verify_shares(pts, pks, h)
        bad = list(pts)
        bad[1] = pts[0]
        assert not T.batch_verify_shares(bad, pks, h)
        assert T.batch_verify_shares([], [], h)


class TestPolynomials:
    def test_interpolation_recovers_secret(self):
        rng = random.Random(9)
        p = Poly.random(3, rng)
        pts = [(x, p.evaluate(x)) for x in (1, 5, 7, 9)]
        assert interpolate_at_zero(pts) == p.coeffs[0]

    def test_lagrange_coefficients_sum_property(self):
        lams = lagrange_coefficients_at_zero([1, 2, 3])
        # interpolating the constant-1 polynomial gives 1
        assert sum(lams) % F.R == 1

    def test_commitment_matches_evaluation(self):
        rng = random.Random(10)
        p = Poly.random(2, rng)
        c = p.commitment()
        for x in (0, 1, 4):
            assert c.evaluate(x) == G2_GEN * p.evaluate(x)

    def test_bivar_symmetry_and_rows(self):
        rng = random.Random(11)
        bp = BivarPoly.random(2, rng)
        for (x, y) in [(1, 2), (3, 5), (0, 4)]:
            assert bp.evaluate(x, y) == bp.evaluate(y, x)
        row3 = bp.row(3)
        for y in (0, 1, 2, 6):
            assert row3.evaluate(y) == bp.evaluate(3, y)

    def test_bivar_commitment_consistency(self):
        rng = random.Random(12)
        bp = BivarPoly.random(1, rng)
        bc = bp.commitment()
        assert bc.is_symmetric()
        assert bc.evaluate(2, 3) == G2_GEN * bp.evaluate(2, 3)
        assert bc.row(2).evaluate(3) == G2_GEN * bp.evaluate(2, 3)


class TestReedSolomon:
    @pytest.mark.parametrize("k,m", [(1, 2), (4, 6), (8, 4), (3, 0)])
    def test_roundtrip(self, k, m):
        rng = random.Random(k * 100 + m)
        rs = ReedSolomon(k, m)
        data = [bytes(rng.randrange(256) for _ in range(24)) for _ in range(k)]
        shards = rs.encode(data)
        assert shards[:k] == data
        for _ in range(5):
            erased: list = list(shards)
            for i in rng.sample(range(k + m), m):
                erased[i] = None
            assert rs.reconstruct(erased) == shards

    def test_insufficient_shards(self):
        rs = ReedSolomon(4, 2)
        shards = rs.encode([b"aaaa"] * 4)
        lost = [None, None, None] + list(shards[3:])
        with pytest.raises(ValueError):
            rs.reconstruct(lost)

    def test_all_equal_leaves(self):
        # reference edge case tests/broadcast.rs:141-149
        rs = ReedSolomon(2, 4)
        shards = rs.encode([b"\x2a" * 8, b"\x2a" * 8])
        erased: list = [None, None, None, None] + list(shards[4:])
        assert rs.reconstruct(erased) == shards


class TestMerkle:
    @pytest.mark.parametrize("n", [1, 2, 3, 5, 8, 13])
    def test_proofs_validate(self, n):
        vals = [bytes([i]) * 7 for i in range(n)]
        t = MerkleTree(vals)
        for i in range(n):
            p = t.proof(i)
            assert p.validate(n)
            assert not MerkleProof(
                p.value + b"z", p.index, p.lemma, p.root_hash
            ).validate(n)

    def test_duplicate_leaves_distinct(self):
        # the reference needed an index-byte workaround
        # (broadcast.rs:371-377); our leaf hash binds the index directly.
        t = MerkleTree([b"same"] * 4)
        assert t.proof(0).validate(4) and t.proof(3).validate(4)
        p0 = t.proof(0)
        moved = MerkleProof(p0.value, 1, p0.lemma, p0.root_hash)
        assert not moved.validate(4)


class TestSerialization:
    def test_roundtrip_primitives(self):
        obj = {
            "a": [1, -5, 2**200, b"\x00bytes", "str", True, None],
            "b": (1, 2),
        }
        assert loads(dumps(obj)) == obj

    def test_deterministic_dict_order(self):
        assert dumps({"x": 1, "y": 2}) == dumps({"y": 2, "x": 1})

    def test_crypto_objects_roundtrip(self):
        rng = random.Random(3)
        sks = T.SecretKeySet.random(1, rng)
        pkset = sks.public_keys()
        ct = pkset.public_key().encrypt(b"m", rng)
        assert loads(dumps(ct)) == ct
        sig = sks.secret_key_share(0).sign(b"m")
        assert loads(dumps(sig)) == sig
        assert loads(dumps(pkset)) == pkset


class TestCiphertextAttacks:
    """Active attacks on the Schnorr-PoK ciphertext validity check —
    the consensus-critical deviation from the reference's Baek–Zheng
    W element (VERDICT r2 item 9).  Every manipulation must be
    rejected by ``Ciphertext.verify`` so HoneyBadger attributes
    INVALID_CIPHERTEXT to the proposer (``honey_badger.py``)."""

    def _ct(self, seed=0xCCA):
        import dataclasses as dc
        import random

        rng = random.Random(seed)
        sks = T.SecretKeySet.random(1, rng)
        pks = sks.public_keys()
        ct = pks.public_key().encrypt(b"attack at dawn", rng)
        assert ct.verify()
        return rng, sks, pks, ct, dc

    def test_mauled_v_rejected(self):
        rng, sks, pks, ct, dc = self._ct()
        # classic ElGamal XOR malleability: flip one plaintext bit
        v = bytearray(ct.v)
        v[0] ^= 1
        assert not dc.replace(ct, v=bytes(v)).verify()

    def test_mauled_u_rejected(self):
        rng, sks, pks, ct, dc = self._ct()
        from hbbft_tpu.crypto.curve import G1_GEN

        assert not dc.replace(ct, u=ct.u + G1_GEN).verify()

    def test_pok_transplant_rejected(self):
        rng, sks, pks, ct, dc = self._ct()
        ct2 = pks.public_key().encrypt(b"another message", rng)
        assert ct2.verify()
        # graft ct2's proof onto ct's payload and vice versa
        assert not dc.replace(ct, c=ct2.c, z=ct2.z).verify()
        assert not dc.replace(ct2, c=ct.c, z=ct.z).verify()

    def test_rerandomization_rejected(self):
        rng, sks, pks, ct, dc = self._ct()
        from hbbft_tpu.crypto.curve import G1_GEN

        # adversary knows s, shifts U by s·P1 and tries the natural
        # z adjustments; all lack the unknown c'·r term
        s = 12345
        u2 = ct.u + G1_GEN * s
        for z2 in (ct.z, (ct.z + ct.c * s) % T.R, (ct.z + s) % T.R):
            assert not dc.replace(ct, u=u2, z=z2).verify()

    def test_identity_u_rejected(self):
        rng, sks, pks, ct, dc = self._ct()
        from hbbft_tpu.crypto.curve import G1

        assert not dc.replace(ct, u=G1.infinity()).verify()

    def test_out_of_range_proof_rejected(self):
        rng, sks, pks, ct, dc = self._ct()
        assert not dc.replace(ct, c=ct.c + T.R).verify()
        assert not dc.replace(ct, z=ct.z + T.R).verify()

    def test_mauled_ciphertext_shares_rejected_end_to_end(self):
        """A mauled ciphertext must also never decrypt: shares made
        for it are rejected against the original (and vice versa) by
        the pairing check — the TDH2 share-consistency half."""
        rng, sks, pks, ct, dc = self._ct()
        v = bytearray(ct.v)
        v[-1] ^= 0x80
        bad = dc.replace(ct, v=bytes(v))
        share = sks.secret_key_share(0).decrypt_share_no_verify(bad)
        # same U → share verifies against either; but the mauled
        # ciphertext itself is invalid, so HB never requests shares
        assert not bad.verify()
        # share verification is U-bound, not V-bound — the validity
        # check is what stops V-mauling (documented in Ciphertext)
        assert pks.public_key_share(0).verify_decryption_share(share, ct)


def test_seed_share_cache_from_scalars_matches_eval():
    # the co-simulation's seeded cache must hold byte-identical points
    # to the commitment evaluation a real node performs
    import random

    from hbbft_tpu.crypto import threshold as T

    rng = random.Random(77)
    sk_set = T.SecretKeySet.random(2, rng)
    pk = sk_set.public_keys()
    seeded = T.PublicKeySet(pk.commitment, pk.master_g1)
    n = 5
    seeded.seed_share_cache_from_scalars(
        {i: sk_set.secret_key_share(i).scalar for i in range(n)}
    )
    for i in range(n):
        assert (
            seeded.public_key_share(i).point.to_bytes()
            == pk.public_key_share(i).point.to_bytes()
        )
