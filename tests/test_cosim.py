"""Packed co-simulation equivalence (harness/cosim.py, harness/wan.py).

The packed struct-of-arrays co-sim is only trustworthy because it is
byte-identical to the dict-based vectorized sims — same rng draw
sequence, same batches, same fault attribution, same agreement-epoch
accounting — at every size the dict plane can still run.  These tests
hold that plane-equivalence gate at small n (the 100k sweep in
``bench.py --cosim`` rides on it), pin the WAN model's determinism,
and pin the legacy ``SeededDelaySchedule`` draw sequence that the
WAN sampler seam must not disturb.
"""

import random

import numpy as np
import pytest

from hbbft_tpu.crypto.mock import MockDecryptionShare
from hbbft_tpu.harness import wan as W
from hbbft_tpu.harness.cosim import (
    PackedHoneyBadgerCosim,
    PackedQueueingCosim,
)
from hbbft_tpu.harness.epoch import (
    VectorizedHoneyBadgerSim,
    VectorizedQueueingSim,
)
from hbbft_tpu.harness.network import SeededDelaySchedule

FORGED = MockDecryptionShare(b"\x00" * 32, b"\x00" * 32)


def _contribs(n, e):
    return {i: [f"tx-{e}-{i}-{j}" for j in range(3)] for i in range(n)}


def _assert_epoch_equal(a, b, ctx):
    assert a.batch == b.batch, ctx
    assert a.accepted == b.accepted, ctx
    assert [x.compact() for x in a.fault_log] == [
        x.compact() for x in b.fault_log
    ], ctx
    assert a.coin_flips == b.coin_flips, ctx
    assert a.shares_verified == b.shares_verified, ctx
    assert a.agreement_epochs == b.agreement_epochs, ctx


def _wan_model(seed=11, alpha=1.5):
    topo = W.GeoTopology(
        zones=("a", "b", "c"),
        delay_ms=((2, 80, 250), (80, 2, 120), (250, 120, 2)),
        weights=(6, 4, 3),
    )
    return W.WanModel(
        seed=seed,
        topology=topo,
        latency=W.LatencyModel("pareto", alpha=alpha),
        deadline_ms=200.0,
        partitions=(W.PartitionWindow(1, 2, ((0, 1), (2,))),),
        failures=(W.CorrelatedFailure(2, 3, 2),),
        flash_crowds=(W.FlashCrowd(1, 2, 4.0),),
    )


class TestPackedEquivalence:
    """packed co-sim ≡ dict-based sim, epoch by epoch, from equal seeds."""

    @pytest.mark.parametrize("n", [4, 13, 64])
    @pytest.mark.parametrize("seed", [42, 7])
    def test_matches_dict_plane(self, n, seed):
        r1, r2 = random.Random(seed), random.Random(seed)
        legacy = VectorizedHoneyBadgerSim(n, r1, mock=True)
        packed = PackedHoneyBadgerCosim(n, r2)
        f = legacy.num_faulty
        for e in range(4):
            kw = {}
            if e == 1 and f >= 1:
                kw = dict(dead={0}, forged_dec={n - 1: {1: FORGED}})
            if e == 2 and n >= 13:
                kw = dict(late_subset={2: set(range(6))})
            if e == 3 and f >= 2:
                # enough forgers to push a proposer below t+1 valid
                kw = dict(forged_dec={s: {1: FORGED} for s in range(f, n)})
            a = legacy.run_epoch(_contribs(n, e), **kw)
            b = packed.run_epoch(_contribs(n, e), **kw)
            _assert_epoch_equal(a, b, (n, seed, e))
        # rng lockstep held across all epochs — every draw matched
        assert r1.random() == r2.random()

    def test_nondef_coin_path(self):
        # n=13, f=4: a 6-live late_subset gives c1=6 >= f+1 and
        # c0=7 >= f+1 — the non-definite branch that flips a real coin
        n = 13
        r1, r2 = random.Random(7), random.Random(7)
        legacy = VectorizedHoneyBadgerSim(n, r1, mock=True)
        packed = PackedHoneyBadgerCosim(n, r2)
        kw = dict(late_subset={2: set(range(6))})
        a = legacy.run_epoch(_contribs(n, 0), **kw)
        b = packed.run_epoch(_contribs(n, 0), **kw)
        assert a.coin_flips == b.coin_flips == 1
        assert a.agreement_epochs[2] in (2, 3)
        _assert_epoch_equal(a, b, "nondef")

    def test_decryption_collapse(self):
        # 9 of 13 senders forge their share of proposer 1: valid =
        # 13-9 = 4 <= f, so decryption fails and pid 1 leaves the batch
        n = 13
        r1, r2 = random.Random(9), random.Random(9)
        legacy = VectorizedHoneyBadgerSim(n, r1, mock=True)
        packed = PackedHoneyBadgerCosim(n, r2)
        forgers = {s: {1: FORGED} for s in range(4, n)}
        a = legacy.run_epoch(_contribs(n, 0), forged_dec=forgers)
        b = packed.run_epoch(_contribs(n, 0), forged_dec=forgers)
        fa = [x.compact() for x in a.fault_log]
        assert any("SHARE_DECRYPTION_FAILED" in x for x in fa)
        assert 1 not in a.batch.contributions
        assert 1 not in b.batch.contributions
        _assert_epoch_equal(a, b, "collapse")

    def test_unsupported_adversaries_raise(self):
        packed = PackedHoneyBadgerCosim(4, random.Random(0))
        with pytest.raises(ValueError):
            packed.run_epoch(_contribs(4, 0), corrupt_shards={0: {1}})
        with pytest.raises(TypeError):
            packed.run_epoch(_contribs(4, 0), bogus_adversary=1)
        with pytest.raises(ValueError):
            PackedHoneyBadgerCosim(4, random.Random(0), mock=False)


class TestWanModels:
    def test_wan_twin_byte_identity(self):
        """The same WanModel drives both planes — partition window,
        correlated zone failure and pareto tails included — and every
        epoch row stays byte-identical."""
        n = 13
        model = _wan_model()
        r1, r2 = random.Random(5), random.Random(5)
        legacy = VectorizedHoneyBadgerSim(n, r1, mock=True)
        packed = PackedHoneyBadgerCosim(n, r2, wan=model)
        for e in range(4):
            a = legacy.run_epoch(_contribs(n, e), wan=model)
            b = packed.run_epoch(_contribs(n, e))
            _assert_epoch_equal(a, b, ("wan", e))
        assert r1.random() == r2.random()

    def test_wan_bind_deterministic(self):
        model = _wan_model()
        s1, s2 = model.bind(13), model.bind(13)
        for e in range(5):
            v1, v2 = s1.epoch_view(e), s2.epoch_view(e)
            assert (v1.reach == v2.reach).all()
            assert (v1.crashed == v2.crashed).all()
            assert (v1.src_ok == v2.src_ok).all()
            assert (v1.dst_ok == v2.dst_ok).all()
            assert v1.arrival_factor == v2.arrival_factor

    def test_zone_assignment_largest_remainder(self):
        topo = W.GeoTopology(
            zones=("a", "b", "c"), delay_ms=((2.0,) * 3,) * 3,
            weights=(4.0, 3.0, 3.0),
        )
        zone = topo.assign(10)
        counts = np.bincount(zone, minlength=3)
        assert counts.tolist() == [4, 3, 3]
        assert (np.sort(zone) == zone).all()  # contiguous blocks

    def test_latency_late_prob_closed_forms(self):
        lm = W.LatencyModel("pareto", alpha=2.0)
        assert lm.late_prob(100.0, 200.0) == pytest.approx(0.25)
        assert lm.late_prob(100.0, 50.0) == 1.0
        lg = W.LatencyModel("lognormal", sigma=0.6)
        assert lg.late_prob(100.0, 100.0) == pytest.approx(0.5)
        un = W.LatencyModel("uniform")
        assert un.late_prob(100.0, 400.0) == 0.0


class TestShardedAndQueueing:
    def test_sharded_matches_single_device(self):
        """Mesh-sharded packed state ≡ single-device packed state,
        including the persistent commit counters (conftest forces 8
        virtual CPU devices, so a 4-way mesh is available)."""
        from hbbft_tpu.parallel import mesh as M

        n = 64
        r1, r2 = random.Random(3), random.Random(3)
        single = PackedHoneyBadgerCosim(n, r1)
        shard = PackedHoneyBadgerCosim(n, r2, mesh=M.make_mesh(4))
        assert shard.mesh_devices == 4
        for e in range(3):
            kw = dict(late_subset={5: set(range(40))}) if e == 1 else {}
            a = single.run_epoch(_contribs(n, e), **kw)
            b = shard.run_epoch(_contribs(n, e), **kw)
            _assert_epoch_equal(a, b, ("mesh", e))
        assert (single.commit_counts() == shard.commit_counts()).all()

    def test_queueing_lockstep(self):
        n = 13
        r1, r2 = random.Random(21), random.Random(21)
        lq = VectorizedQueueingSim(n, r1, batch_size=20, mock=True)
        pq = PackedQueueingCosim(n, r2, batch_size=20)
        txs = [b"t%03d" % i for i in range(200)]
        lq.input_all(txs)
        pq.input_all(txs)
        for e in range(4):
            kw = dict(dead={0}) if e == 2 else {}
            a = lq.run_epoch(**kw)
            b = pq.run_epoch(**kw)
            _assert_epoch_equal(a, b, ("queue", e))
            assert len(lq.queue) == len(pq.queue)
        assert r1.random() == r2.random()

    def test_queueing_wan_twin(self):
        n = 13
        model = _wan_model(seed=23)
        r1, r2 = random.Random(31), random.Random(31)
        lq = VectorizedQueueingSim(n, r1, batch_size=20, mock=True)
        pq = PackedQueueingCosim(n, r2, batch_size=20, wan=model)
        txs = [b"w%03d" % i for i in range(200)]
        lq.input_all(txs)
        pq.input_all(txs)
        for e in range(4):
            a = lq.run_epoch(wan=model)
            b = pq.run_epoch()
            _assert_epoch_equal(a, b, ("qwan", e))
            assert len(lq.queue) == len(pq.queue)
        assert r1.random() == r2.random()


class TestDelaySchedulePin:
    """The sampler seam must not disturb the legacy draw sequence."""

    def test_default_draws_pinned_byte_for_byte(self):
        # the default sampler consumes exactly ONE flat rng.random()
        # per decision — the distribution every pre-seam scenario and
        # checkpoint was recorded under
        sched = SeededDelaySchedule(random.Random(0xDE1A), p_delay=0.25)
        ref = random.Random(0xDE1A)
        decisions = [
            sched(s, r, ("msg", i))
            for i, (s, r) in enumerate((a, b) for a in range(5) for b in range(5))
        ]
        expected = [not (ref.random() < 0.25) for _ in range(25)]
        assert decisions == expected
        assert sched.held_count == expected.count(False)
        # and the rngs are in lockstep afterwards
        assert sched.rng.random() == ref.random()

    def test_wan_sampler_one_draw_per_decision(self):
        model = W.WanModel(
            seed=3,
            latency=W.LatencyModel("lognormal", sigma=0.8),
            deadline_ms=150.0,
        )
        sampler = model.bind(10).delay_sampler()
        sched = SeededDelaySchedule(
            random.Random(4), p_delay=0.25, sampler=sampler
        )
        ref = random.Random(4)
        for s in range(5):
            for r in range(5):
                sched(s, r, None)
                ref.random()
        assert sched.rng.random() == ref.random()


class TestScaleMode:
    def test_packed_stats_small(self):
        sim = PackedHoneyBadgerCosim(64, random.Random(0))
        s = sim.run_epoch_packed()
        assert s.n == 64 and s.accepted == 64 and s.coin_flips == 0
        assert s.bytes_per_validator > 0 and s.mesh_devices >= 1
        s2 = sim.run_epoch_packed(dead={0})
        assert s2.epoch == 1 and s2.accepted == 63
        counts = sim.commit_counts()
        assert counts[1] == 2 and counts[0] == 1

    @pytest.mark.slow
    def test_packed_smoke_16384(self):
        model = W.WanModel(
            seed=3,
            latency=W.LatencyModel("lognormal", sigma=0.8),
            deadline_ms=150.0,
        )
        sim = PackedHoneyBadgerCosim(16384, random.Random(0), wan=model)
        for _ in range(3):
            s = sim.run_epoch_packed()
            assert 0 < s.accepted <= 16384
            assert s.peak_rss_bytes > 0
        assert int(sim.commit_counts().max()) <= 3
