"""The shipped examples stay runnable (the reference exercises its
examples in CI; here they run as subprocess integration tests)."""

import subprocess
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent


def _free_ports(n):
    import socket

    socks, ports = [], []
    for _ in range(n):
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        socks.append(s)
        ports.append(s.getsockname()[1])
    for s in socks:
        s.close()
    return ports


def test_simulation_cli():
    out = subprocess.run(
        [
            sys.executable,
            str(REPO / "examples" / "simulation.py"),
            "-n", "5", "-f", "1", "-t", "40", "-b", "20",
        ],
        capture_output=True,
        text=True,
        timeout=120,
        cwd=REPO,
    )
    assert out.returncode == 0, out.stderr
    assert "epochs/s" in out.stdout
    assert "Epoch" in out.stdout  # the per-epoch stats table header


def test_consensus_node_cli_three_processes():
    ports = _free_ports(3)
    addrs = sorted(f"127.0.0.1:{p}" for p in ports)
    procs = []
    try:
        for addr in addrs:
            remotes = [a for a in addrs if a != addr]
            cmd = [
                sys.executable,
                str(REPO / "examples" / "consensus_node.py"),
                f"--bind-address={addr}",
            ] + [f"--remote-address={r}" for r in remotes]
            if addr == addrs[0]:
                cmd.append("--value=example-test")
            procs.append(
                subprocess.Popen(
                    cmd,
                    stdout=subprocess.PIPE,
                    stderr=subprocess.STDOUT,
                    text=True,
                    cwd=REPO,
                )
            )
        outs = [p.communicate(timeout=60)[0] for p in procs]
        for out in outs:
            assert "agreed value: b'example-test'" in out, out
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
