"""QueueingHoneyBadger tests (mirrors ``tests/queueing_honey_badger.rs``):
the built-in queue drives proposals automatically; a Remove(0)→Add(0)
churn happens mid-stream with the second half of transactions input only
after the removal completes."""

import random

from hbbft_tpu.harness.network import (
    MessageScheduler,
    SilentAdversary,
    TestNetwork,
)
from hbbft_tpu.protocols import change as C
from hbbft_tpu.protocols.dynamic_honey_badger import ChangeInput, DynamicHoneyBadger
from hbbft_tpu.protocols.queueing_honey_badger import QueueingHoneyBadger


def new_qhb(netinfo):
    rng = random.Random(f"qhb-{netinfo.our_id}")
    dhb = DynamicHoneyBadger(netinfo, rng=rng)
    qhb = QueueingHoneyBadger(dhb, batch_size=8, rng=rng)
    return qhb


def _run_qhb_churn(seed, mock=True, ops=None, txs=8):
    """Remove(0) → Add(0) mid-stream at the QHB level, second half of
    the transactions input only after the removal completes (reference
    ``tests/queueing_honey_badger.rs:38-87``); parameterized to also
    run on real BLS12-381 (VERDICT r2 item 5)."""
    rng = random.Random(seed)
    size = 4
    net = TestNetwork(
        size,
        0,
        lambda adv: SilentAdversary(
            MessageScheduler(MessageScheduler.RANDOM, rng)
        ),
        new_qhb,
        rng,
        mock_crypto=mock,
        ops=ops,
    )
    first_half = [b"tx-a-%d" % i for i in range(txs)]
    second_half = [b"tx-b-%d" % i for i in range(txs)]
    node0_pk = net.nodes[0].instance.dyn_hb.netinfo.public_key(0)

    # queue the first half everywhere and vote to remove node 0
    for nid in sorted(net.nodes):
        for tx in first_half:
            net.input(nid, tx)
    for nid in sorted(net.nodes):
        net.input(nid, ChangeInput(C.Remove(0)))

    def committed(node):
        return {tx for b in node.outputs for tx in b.tx_iter()}

    def has_complete(node, change_cls):
        return any(
            isinstance(b.change, C.Complete)
            and isinstance(b.change.change, change_cls)
            for b in node.outputs
        )

    state = {"removed": False, "added": False}
    guard = 0
    while True:
        guard += 1
        assert guard < 200_000, f"QHB churn stalled: {state}"
        if not state["removed"] and all(
            has_complete(n, C.Remove) for n in net.nodes.values()
        ):
            state["removed"] = True
            # now input the second half and vote node 0 back in
            for nid in sorted(net.nodes):
                inst = net.nodes[nid].instance
                if inst.dyn_hb.netinfo.is_validator:
                    for tx in second_half:
                        net.input(nid, tx)
                    net.input(nid, ChangeInput(C.Add(0, node0_pk)))
        if not state["added"] and all(
            has_complete(n, C.Add) for n in net.nodes.values()
        ):
            state["added"] = True
        if state["added"] and all(
            committed(n) >= set(first_half) | set(second_half)
            for n in net.nodes.values()
        ):
            break
        if net.any_busy():
            net.step()
        else:
            # kick any idle validator that can propose
            progressed = False
            for nid in sorted(net.nodes):
                node = net.nodes[nid]
                step = node.instance.propose()
                if not step.is_empty():
                    node._absorb(step)
                    msgs = list(node.messages)
                    node.messages.clear()
                    net.dispatch_messages(nid, msgs)
                    progressed = True
            assert progressed or net.any_busy(), "network wedged"

    # batch sequences have equal prefixes
    def key(b):
        return (
            b.epoch,
            tuple(sorted((str(k), tuple(v)) for k, v in b.contributions.items())),
            repr(b.change),
        )

    seqs = [[key(b) for b in n.outputs] for n in net.nodes.values()]
    min_len = min(len(s) for s in seqs)
    for s in seqs[1:]:
        assert s[:min_len] == seqs[0][:min_len]
    assert state["removed"] and state["added"]


def test_queueing_honey_badger_txs_and_churn():
    _run_qhb_churn(90, mock=True)


def test_qhb_churn_real_bls():
    """The full stack — queue sampling, DHB votes, on-chain DKG, era
    switch, re-keyed threshold decryption — on real BLS12-381 with the
    batching façade keeping the share verifications fused."""
    from hbbft_tpu.harness.batching import BatchingBackend

    _run_qhb_churn(94, mock=False, ops=BatchingBackend(), txs=4)


def test_qhb_builder_and_auto_propose():
    rng = random.Random(91)
    net = TestNetwork(
        4,
        0,
        lambda adv: SilentAdversary(
            MessageScheduler(MessageScheduler.RANDOM, rng)
        ),
        new_qhb,
        rng,
        mock_crypto=True,
    )
    txs = [b"solo-%d" % i for i in range(4)]
    for nid in sorted(net.nodes):
        for tx in txs:
            net.input(nid, tx)
    net.step_until(
        lambda: all(
            {t for b in n.outputs for t in b.tx_iter()} >= set(txs)
            for n in net.nodes.values()
        ),
        max_steps=100_000,
    )


def test_qhb_random_adversary_fuzz():
    """RandomAdversary (replay + garbage injection, reference
    ``tests/network/mod.rs:221-344``) over the FULL QHB stack: one
    corrupted node replays unicasts to wrong recipients and injects
    generator-built garbage at every layer of the message nesting; the
    good nodes must still commit every transaction and agree on batch
    prefixes (VERDICT r2 item 8)."""
    from hbbft_tpu.harness.network import RandomAdversary
    from hbbft_tpu.core.step import Target, TargetedMessage
    from hbbft_tpu.protocols import agreement as A
    from hbbft_tpu.protocols import broadcast as B
    from hbbft_tpu.protocols.common_subset import CsAgreement, CsBroadcast
    from hbbft_tpu.protocols.dynamic_honey_badger import DhbHoneyBadger
    from hbbft_tpu.protocols.honey_badger import (
        HbCommonSubset,
        HoneyBadgerMessage,
    )

    rng = random.Random(95)

    def garbage():
        pid = rng.randrange(4)
        if rng.randrange(2):
            inner = CsBroadcast(pid, B.random_message(rng, 4))
        else:
            inner = CsAgreement(pid, A.random_message(rng))
        msg = DhbHoneyBadger(
            0, HoneyBadgerMessage(rng.randrange(3), HbCommonSubset(inner))
        )
        target = Target.all() if rng.randrange(2) else Target.to(
            rng.randrange(4)
        )
        return TargetedMessage(target, msg)

    net = TestNetwork(
        3,
        1,
        lambda adv: RandomAdversary(0.2, 0.4, garbage, rng),
        new_qhb,
        rng,
        mock_crypto=True,
    )
    txs = [b"fuzz-%d" % i for i in range(6)]
    for nid in sorted(net.nodes):
        for tx in txs:
            net.input(nid, tx)

    def committed(node):
        return {tx for b in node.outputs for tx in b.tx_iter()}

    guard = 0
    while not all(committed(n) >= set(txs) for n in net.nodes.values()):
        guard += 1
        assert guard < 200_000, "QHB under fuzz stalled"
        if net.any_busy():
            net.step()
        else:
            progressed = False
            for nid in sorted(net.nodes):
                node = net.nodes[nid]
                step = node.instance.propose()
                if not step.is_empty():
                    node._absorb(step)
                    msgs = list(node.messages)
                    node.messages.clear()
                    net.dispatch_messages(nid, msgs)
                    progressed = True
            assert progressed or net.any_busy(), "network wedged"

    def key(b):
        return (
            b.epoch,
            tuple(
                sorted(
                    (str(k), tuple(v)) for k, v in b.contributions.items()
                )
            ),
            repr(b.change),
        )

    seqs = [[key(b) for b in n.outputs] for n in net.nodes.values()]
    min_len = min(len(s) for s in seqs)
    for s in seqs[1:]:
        assert s[:min_len] == seqs[0][:min_len]
