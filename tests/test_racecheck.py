"""The runtime lockset checker (``hbbft_tpu/analysis/racecheck.py``).

Three layers, mirroring the ISSUE 5 acceptance criteria:

- a deliberate-race fixture is flagged by BOTH the static
  ``thread-shared-state`` pass and the runtime Eraser checker, and the
  locked variant is clean under both;
- the enable/disable shims install over the real shared-state surface
  (``pallas_ec._EXEC_MEM``, ``packed_msm._WARM_SEEN``, the module
  locks) and restore plain builtins afterwards with contents intact;
- a stress run drives the staging worker, the background prewarmer and
  an epoch-style executor concurrently with the main path under the
  checker: zero candidate races, and the persisted flush caches
  (``warm_shapes.json`` / ``device_fraction.json``) are byte-identical
  with staging on and off.
"""

import functools
import json
import os
import subprocess
import sys
import threading
import textwrap

import pytest

from hbbft_tpu.analysis import all_rules, lint_source
from hbbft_tpu.analysis import racecheck
from hbbft_tpu.analysis.racecheck import RaceChecker
from hbbft_tpu.crypto import rs
from hbbft_tpu.ops import packed_msm, pallas_ec, staging
from hbbft_tpu.parallel import mesh as parallel_mesh

# ---------------------------------------------------------------------------
# The deliberate-race fixture: one source, caught twice
# ---------------------------------------------------------------------------

DELIBERATE_RACE_SRC = textwrap.dedent(
    """
    import threading

    CACHE = {}

    def _worker():
        CACHE["w"] = 1

    def start():
        t = threading.Thread(target=_worker, name="hbbft-racer", daemon=True)
        t.start()
        return t

    def main_write(key):
        CACHE[key] = 2
"""
)


def test_deliberate_race_flagged_by_static_pass():
    rules = [r for r in all_rules() if r.name == "thread-shared-state"]
    vs = lint_source(DELIBERATE_RACE_SRC, "ops/fixture.py", rules)
    assert len(vs) == 2
    assert all("unguarded write to 'ops/fixture.CACHE'" in v.message for v in vs)


def test_deliberate_race_flagged_by_runtime_checker():
    # the same shape, executed: two threads write a dict, no lock
    chk = RaceChecker()
    cache = chk.track_dict({}, "ops/fixture.CACHE")

    def worker():
        cache["w"] = 1

    t = threading.Thread(target=worker, name="hbbft-racer")
    t.start()
    t.join()
    cache["m"] = 2  # main thread, no common lock → candidate race

    assert len(chk.reports) == 1
    r = chk.reports[0]
    assert r.var == "ops/fixture.CACHE"
    assert r.write
    assert "hbbft-racer" in r.threads and "MainThread" in r.threads
    assert "candidate race" in r.message()
    # reuses the structured Violation machinery (human/JSON/SARIF)
    v = r.as_violation()
    assert v.rule == "racecheck"
    assert v.render()  # renders like any lint violation
    assert json.loads(json.dumps(r.as_dict()))["var"] == "ops/fixture.CACHE"


def test_locked_variant_is_clean_at_runtime():
    chk = RaceChecker()
    lock = chk.track_lock(threading.Lock(), "ops/fixture._LOCK")
    cache = chk.track_dict({}, "ops/fixture.CACHE")

    def worker():
        for i in range(50):
            with lock:
                cache[("w", i)] = i

    t = threading.Thread(target=worker, name="hbbft-racer")
    t.start()
    for i in range(50):
        with lock:
            cache[("m", i)] = i
    t.join()
    assert chk.reports == []


def test_lockset_refinement_empties_across_different_locks():
    # classic Eraser: each access IS locked, but never by a COMMON lock
    chk = RaceChecker()
    a = chk.track_lock(threading.Lock(), "fixture.A_LOCK")
    b = chk.track_lock(threading.Lock(), "fixture.B_LOCK")
    d = chk.track_dict({}, "fixture.STATE")

    d["x"] = 0  # main: Virgin → Exclusive

    def worker():
        with a:
            d["x"] = 1  # cross-thread: C(v) = {A}

    t = threading.Thread(target=worker, name="hbbft-a-side")
    t.start()
    t.join()
    with b:
        d["x"] = 2  # C(v) = {A} ∩ {B} = ∅ → report
    assert len(chk.reports) == 1
    assert "share no common lock" in chk.reports[0].message()


def test_tracked_rlock_reentrancy_keeps_held_set():
    chk = RaceChecker()
    rl = chk.track_lock(threading.RLock(), "fixture.RLOCK")
    d = chk.track_dict({}, "fixture.STATE")

    def worker():
        with rl:
            with rl:  # reentrant acquire
                d["x"] = 1
            d["y"] = 2  # still held after inner release

    t = threading.Thread(target=worker, name="hbbft-r")
    t.start()
    t.join()
    with rl:
        d["x"] = 3
    assert chk.reports == []


# ---------------------------------------------------------------------------
# enable()/disable(): the process-wide shims
# ---------------------------------------------------------------------------


def test_enable_shims_known_globals_and_disable_restores(request):
    if request.config.getoption("--racecheck"):
        pytest.skip("manages the global checker itself")
    mem_before = pallas_ec._EXEC_MEM
    racecheck.enable()
    try:
        assert isinstance(pallas_ec._EXEC_MEM, racecheck.TrackedDict)
        assert isinstance(pallas_ec._EXEC_LOCK, racecheck.TrackedLock)
        assert isinstance(packed_msm._WARM_SEEN, racecheck.TrackedSet)
        assert isinstance(packed_msm._STATE_LOCK, racecheck.TrackedLock)
        assert isinstance(staging._STAGER_LOCK, racecheck.TrackedLock)
        assert isinstance(staging._BUFFERS._free, racecheck.TrackedDict)
        assert isinstance(parallel_mesh._RUNNERS, racecheck.TrackedDict)
        assert isinstance(
            parallel_mesh._RUNNERS_LOCK, racecheck.TrackedLock
        )
        # nested enable shares the active checker (refcounted)
        assert racecheck.enable() is racecheck.active()
        racecheck.disable()
        pallas_ec._EXEC_MEM["__racecheck_test__"] = "kept"
    finally:
        reports = racecheck.disable()
    assert racecheck.active() is None
    assert type(pallas_ec._EXEC_MEM) is dict
    assert type(packed_msm._WARM_SEEN) is set
    assert type(parallel_mesh._RUNNERS) is dict
    # contents loaded during the instrumented window survive
    assert pallas_ec._EXEC_MEM.pop("__racecheck_test__") == "kept"
    assert mem_before is not pallas_ec._EXEC_MEM or not mem_before
    assert isinstance(reports, list)


def test_reports_append_to_out_file(tmp_path, monkeypatch, request):
    if request.config.getoption("--racecheck"):
        pytest.skip("manages the global checker itself")
    out = tmp_path / "races.jsonl"
    monkeypatch.setenv(racecheck.OUT_ENV, str(out))
    monkeypatch.setattr(pallas_ec, "_EXEC_MEM", {})
    racecheck.enable()
    try:
        mem = pallas_ec._EXEC_MEM

        def worker():
            mem["w"] = 1  # no lock, worker thread

        t = threading.Thread(target=worker, name="hbbft-racer")
        t.start()
        t.join()
        mem["m"] = 2  # no lock, main thread → candidate race
    finally:
        reports = racecheck.disable()
    assert len(reports) == 1
    assert reports[0].var == "ops/pallas_ec._EXEC_MEM"
    loaded = racecheck.load_reports(str(out))
    assert len(loaded) == 1
    assert loaded[0].var == "ops/pallas_ec._EXEC_MEM"
    assert loaded[0].message() == reports[0].message()


# ---------------------------------------------------------------------------
# The stress test: stager + prewarm + epoch-style overlap, zero races,
# byte-identical flush caches with staging on and off
# ---------------------------------------------------------------------------

_SHAPES = [(64, 4, False), (64, 4, True), (128, 8, False), (974, 16, False)]


def _drive_flush_state(cache_dir, staged, monkeypatch):
    """Replay the flush pipeline's persistent-state traffic —
    ``record_warm_shape`` + ``seed_rates`` for each shape — through the
    staging worker (staged) or inline (sequential), and return the
    bytes of the two persisted caches."""
    monkeypatch.setenv("HBBFT_TPU_EXEC_CACHE", str(cache_dir))
    monkeypatch.setenv("HBBFT_TPU_STAGING", "1" if staged else "0")
    # reset IN PLACE so the racecheck shim installed over _WARM_SEEN
    # keeps tracking it (rebinding the global would escape the shim)
    with packed_msm._STATE_LOCK:
        packed_msm._WARM_SEEN.clear()
    monkeypatch.setattr(packed_msm, "_RHO_STATE", None)
    st = staging.stager()
    tasks = []
    for n, g, comp in _SHAPES:
        tasks.append(
            st.submit(functools.partial(packed_msm.record_warm_shape, n, g, comp))
        )
        tasks.append(
            st.submit(
                functools.partial(packed_msm.seed_rates, n, g, 1e6, 5e5)
            )
        )
    for t in tasks:
        t.result()
    warm = (cache_dir / "warm_shapes.json").read_bytes()
    rho = (cache_dir / "device_fraction.json").read_bytes()
    return warm, rho


def test_stress_concurrent_pipeline_zero_races_and_byte_identity(
    tmp_path, monkeypatch
):
    seq_dir = tmp_path / "seq"
    staged_dir = tmp_path / "staged"
    seq_dir.mkdir()
    staged_dir.mkdir()
    # fresh state BEFORE enable(): the shims install over these exact
    # objects, so the stress traffic below runs fully tracked
    monkeypatch.setattr(packed_msm, "_PREWARM", None)
    monkeypatch.setattr(packed_msm, "_WARM_SEEN", set())
    monkeypatch.setattr(packed_msm, "_RHO_STATE", None)

    racecheck.enable()
    try:
        # sequential leg first: staging off, everything inline
        warm_seq, rho_seq = _drive_flush_state(seq_dir, False, monkeypatch)

        # staged leg: the stager worker replays the same traffic while
        # the prewarm daemon, an epoch-style stage executor and the
        # main path all hammer the same module state
        stop = threading.Event()

        def prewarm_leg():
            while not stop.is_set():
                packed_msm.prewarm_shapes()

        from concurrent.futures import ThreadPoolExecutor

        def epoch_unit(i):
            # what the epoch stage worker actually exercises: RS table
            # math + the controller's read path
            packed_msm.learned_fraction(64, 4)
            rs.gf16_mul(3, i % 65535 + 1)
            pallas_ec.exec_available("fixture", ((i % 7, 2),))
            return i

        aux = threading.Thread(
            target=prewarm_leg, name="hbbft-test-prewarm", daemon=True
        )
        aux.start()
        packed_msm.start_background_prewarm()
        with ThreadPoolExecutor(
            max_workers=2, thread_name_prefix="hbbft-epoch-stage"
        ) as ex:
            futs = [ex.submit(epoch_unit, i) for i in range(64)]
            warm_staged, rho_staged = _drive_flush_state(
                staged_dir, True, monkeypatch
            )
            # main path reads race the legs above
            for i in range(64):
                packed_msm.learned_fraction(64, 4)
                packed_msm.record_warm_shape(64, 4, False)
            assert [f.result() for f in futs] == list(range(64))
        stop.set()
        aux.join(timeout=10)
    finally:
        reports = racecheck.disable()

    assert reports == [], "\n".join(r.message() for r in reports)
    assert warm_staged == warm_seq
    assert rho_staged == rho_seq
    # sanity: the caches really did record the driven shapes (v2
    # schema: the per-shape dict lives in the "shapes" plane)
    recorded = json.loads(warm_seq)["shapes"]
    assert set(recorded) == {"%d:%d" % (n, g) for n, g, _ in _SHAPES}
    assert recorded["64:4"]["compressed"] is True  # sticky sighting


def test_mesh_runner_cache_concurrent_build_zero_races():
    """The mesh flush's shared surface: the prewarm daemon, the epoch
    stage executor and the flush path can all miss ``mesh._RUNNERS`` at
    once and build the same sharded runner.  Hammer the cache from five
    worker threads plus the main thread under the checker — zero
    candidate races, and first-builder-wins means every leg observes
    the same runner object per key."""
    mesh = parallel_mesh.make_mesh(4)
    keys = [(2, 8), (4, 8), (2, 16)]
    with parallel_mesh._RUNNERS_LOCK:
        parallel_mesh._RUNNERS.clear()

    racecheck.enable()
    try:
        assert isinstance(parallel_mesh._RUNNERS, racecheck.TrackedDict)
        results = [[] for _ in range(6)]

        def leg(out):
            for g, kd in keys:
                out.append(
                    parallel_mesh.sharded_product_msm_fn(
                        mesh, g, kd, 12, "xla"
                    )
                )
                # the flush path's readback between builds
                parallel_mesh.product_runner_key(mesh, g, kd, 12, "xla")

        threads = [
            threading.Thread(
                target=leg,
                args=(results[i],),
                name="hbbft-mesh-warm-%d" % i,
            )
            for i in range(5)
        ]
        for t in threads:
            t.start()
        leg(results[5])  # main thread races the warm legs
        for t in threads:
            t.join()
    finally:
        reports = racecheck.disable()

    assert reports == [], "\n".join(r.message() for r in reports)
    # first builder wins: one runner object per key, shared by all legs
    for per_key in zip(*results):
        assert len({id(r) for r in per_key}) == 1


# ---------------------------------------------------------------------------
# The CLI driver: python -m hbbft_tpu.analysis --racecheck <test-expr>
# ---------------------------------------------------------------------------


def test_tcp_node_containers_tracked_and_restored(request):
    if request.config.getoption("--racecheck"):
        pytest.skip("manages the global checker itself")
    from hbbft_tpu.transport import tcp

    assert tcp._TRACK_NODE is None
    racecheck.enable()
    try:
        node = tcp.TcpNode(
            "127.0.0.1:7001",
            ["127.0.0.1:7001", "127.0.0.1:7002"],
            lambda ni: object(),
        )
        # per-connection shared containers are shimmed at construction
        assert isinstance(node._writers, racecheck.TrackedDict)
        assert isinstance(node.outputs, racecheck.TrackedList)
        assert isinstance(node.faults, racecheck.TrackedList)
        assert callable(tcp._TRACK_NODE)
    finally:
        racecheck.disable()
    # the constructor hook is restored to None, and new nodes get
    # plain builtins again
    assert tcp._TRACK_NODE is None
    after = tcp.TcpNode(
        "127.0.0.1:7001",
        ["127.0.0.1:7001", "127.0.0.1:7002"],
        lambda ni: object(),
    )
    assert type(after._writers) is dict
    assert type(after.outputs) is list


def test_wal_writer_sync_thread_tracked_and_race_free(request, tmp_path):
    """The durable WAL's thread shape under the lockset checker:
    concurrent appenders race the ``hbbft-wal-sync`` daemon over the
    shared file handle — all accesses go through ``_lock``, so the
    checker must stay silent and the log must stay intact."""
    if request.config.getoption("--racecheck"):
        pytest.skip("manages the global checker itself")
    from hbbft_tpu.recover import wal as wal_mod

    assert wal_mod._TRACK_WAL is None
    path = str(tmp_path / "rc.wal")
    racecheck.enable()
    try:
        w = wal_mod.WalWriter(
            path, fsync="interval", fsync_interval_s=0.001
        )
        assert isinstance(w._lock, racecheck.TrackedLock)
        assert callable(wal_mod._TRACK_WAL)

        def burst():
            for i in range(50):
                w.append_input(i)

        threads = [threading.Thread(target=burst) for _ in range(3)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        w.sync()
        w.close()
    finally:
        reports = racecheck.disable()
    assert wal_mod._TRACK_WAL is None
    assert reports == []
    records, clean = wal_mod.read_records(path)
    assert clean and len(records) == 150


@pytest.mark.slow
def test_cli_racecheck_driver_runs_clean():
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    proc = subprocess.run(
        [
            sys.executable,
            "-m",
            "hbbft_tpu.analysis",
            "--racecheck",
            "tests/test_racecheck.py::test_locked_variant_is_clean_at_runtime",
        ],
        cwd=repo,
        capture_output=True,
        text=True,
        timeout=600,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "racecheck clean" in proc.stdout
