"""The event-loop stall sanitizer (``hbbft_tpu/analysis/stallcheck.py``).

Four layers, mirroring the racecheck suite:

- a deliberately blocking coroutine is caught with Task attribution,
  elapsed/budget accounting and a mid-stall stack sample, and the
  sanctioned ``run_in_executor`` form is clean under the same budget;
- the budget knob works through both the argument and
  ``$HBBFT_TPU_STALLCHECK_BUDGET``;
- reports round-trip through ``$HBBFT_TPU_STALLCHECK_OUT`` (JSONL) and
  the refcounted enable/disable pair restores ``Handle._run``;
- the fix this PR landed in ``recover.driver.prime_replay`` — the
  periodic cooperative yield — is pinned by a regression test that
  counts how often a concurrent task gets the loop during replay.
"""

import asyncio
import asyncio.events
import os
import subprocess
import sys
import time

import pytest

from hbbft_tpu.analysis import stallcheck


async def _stall(duration):
    time.sleep(duration)  # lint: ok(async-blocking)  # noqa — deliberate


async def _offloaded(duration):
    loop = asyncio.get_event_loop()
    await loop.run_in_executor(None, time.sleep, duration)


# ---------------------------------------------------------------------------
# catch / don't-catch
# ---------------------------------------------------------------------------


def test_blocking_coroutine_caught(request):
    if request.config.getoption("--stallcheck"):
        pytest.skip("manages the global checker itself")
    stallcheck.enable(0.05)
    try:
        asyncio.run(_stall(0.25))
    finally:
        reports = stallcheck.disable()
    assert len(reports) == 1
    r = reports[0]
    assert "Task step" in r.callback and "_stall" in r.callback
    assert r.elapsed_ms >= 50.0
    assert r.budget_ms == pytest.approx(50.0)
    assert "blocked the loop" in r.message()
    assert "run_in_executor" in r.message()
    # the watchdog sampled the stack mid-stall: the hops name the
    # blocked coroutine, like a lint rule's source→sink flow
    assert r.stack, "watchdog never sampled a 250 ms stall at 12.5 ms cadence"
    assert any(qual == "_stall" for _, _, qual in r.stack)
    # reuses the structured Violation machinery (human/JSON/SARIF)
    v = r.as_violation()
    assert v.rule == "stallcheck"
    assert v.render()
    assert any("in _stall()" in note for _, _, note in v.flow)


def test_executor_offload_is_clean(request):
    # the sanctioned form: the same sleep, parked on a worker thread —
    # the loop keeps running and no callback crosses the budget
    if request.config.getoption("--stallcheck"):
        pytest.skip("manages the global checker itself")
    stallcheck.enable(0.05)
    try:
        asyncio.run(_offloaded(0.25))
    finally:
        reports = stallcheck.disable()
    assert reports == []


# ---------------------------------------------------------------------------
# the budget knob
# ---------------------------------------------------------------------------


def test_budget_knob_tolerates_slow_callback(request):
    if request.config.getoption("--stallcheck"):
        pytest.skip("manages the global checker itself")
    stallcheck.enable(5.0)
    try:
        asyncio.run(_stall(0.05))
    finally:
        reports = stallcheck.disable()
    assert reports == []


def test_budget_env_and_argument(monkeypatch):
    monkeypatch.setenv(stallcheck.BUDGET_ENV, "1.5")
    assert stallcheck.StallChecker().budget_s == 1.5
    # an explicit argument outranks the environment
    assert stallcheck.StallChecker(0.01).budget_s == 0.01
    monkeypatch.delenv(stallcheck.BUDGET_ENV)
    assert stallcheck.StallChecker().budget_s == stallcheck.DEFAULT_BUDGET_S


# ---------------------------------------------------------------------------
# OUT-file roundtrip + the refcounted switchboard
# ---------------------------------------------------------------------------


def test_reports_append_to_out_file(tmp_path, monkeypatch, request):
    if request.config.getoption("--stallcheck"):
        pytest.skip("manages the global checker itself")
    out = tmp_path / "stalls.jsonl"
    monkeypatch.setenv(stallcheck.OUT_ENV, str(out))
    stallcheck.enable(0.05)
    try:
        asyncio.run(_stall(0.25))
    finally:
        reports = stallcheck.disable()
    assert len(reports) == 1
    loaded = stallcheck.load_reports(str(out))
    assert len(loaded) == 1
    assert loaded[0].message() == reports[0].message()
    assert loaded[0].stack == reports[0].stack
    assert loaded[0].as_violation().flow == reports[0].as_violation().flow
    # missing file is an empty report set, not an error
    assert stallcheck.load_reports(str(tmp_path / "nope.jsonl")) == []


def test_nested_enable_shares_checker_and_restores(request):
    if request.config.getoption("--stallcheck"):
        pytest.skip("manages the global checker itself")
    orig = asyncio.events.Handle._run
    chk = stallcheck.enable(0.5)
    try:
        assert asyncio.events.Handle._run is not orig
        # nested enable shares the active checker (refcounted); the
        # first enable's budget wins
        assert stallcheck.enable(0.001) is chk
        assert stallcheck.active() is chk
        assert chk.budget_s == 0.5
        stallcheck.disable()
        assert stallcheck.active() is chk  # one reference still out
    finally:
        stallcheck.disable()
    assert stallcheck.active() is None
    assert asyncio.events.Handle._run is orig


# ---------------------------------------------------------------------------
# the prime_replay regression: a long WAL tail must not monopolize the
# loop (the fix this PR landed after async-blocking/stallcheck flagged it)
# ---------------------------------------------------------------------------


def test_prime_replay_yields_to_concurrent_tasks():
    from hbbft_tpu.recover.driver import prime_replay

    class FakeNode:
        def __init__(self):
            self.routed = 0

        async def _route(self, step):
            # like the real _route with no link up: never actually
            # awaits, so only prime_replay's own yields share the loop
            self.routed += 1

    ticks = 0

    async def main():
        nonlocal ticks
        node = FakeNode()
        done = False

        async def ticker():
            nonlocal ticks
            while not done:
                ticks += 1
                await asyncio.sleep(0)

        t = asyncio.get_event_loop().create_task(ticker())
        await prime_replay(node, list(range(300)))
        done = True
        await t
        return node

    node = asyncio.run(main())
    assert node.routed == 300
    # 300 steps yield at i = 63, 127, 191, 255 — a concurrent server
    # (metrics exporter, peer pump) breathes at least that often.
    # Before the fix the ticker never ran until the replay finished.
    assert ticks >= 4


# ---------------------------------------------------------------------------
# the CLI driver: python -m hbbft_tpu.analysis --stallcheck <test-expr>
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_cli_stallcheck_driver_runs_clean():
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    proc = subprocess.run(
        [
            sys.executable,
            "-m",
            "hbbft_tpu.analysis",
            "--stallcheck",
            "tests/test_stallcheck.py::test_prime_replay_yields_to_concurrent_tasks",
            "--stall-budget",
            "0.5",
        ],
        cwd=repo,
        capture_output=True,
        text=True,
        timeout=600,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "stallcheck clean" in proc.stdout
