"""Commit-latency arc correctness gates (PR 10).

Pins the three mechanisms of the arc:

- speculative combine-first decryption is *outcome-invisible*: batches
  byte-identical to eager on fault-free epochs (mock and real BLS), a
  bad share inside the f+1 combine window forces the per-share
  fallback with identical fault attribution, a forger past the window
  is flagged by the leftover audit exactly as eagerly;
- pipelined epoch driving (thread-overlap and deep-staged) is
  bit-identical to serial;
- the signature-scheme seam resolves BLS and rejects the EdDSA stub;
- the ``spec_combine`` / ``commit_latency`` observability rows land.
"""

import random

import pytest

from hbbft_tpu.crypto import scheme as scheme_mod
from hbbft_tpu.crypto.mock import MockDecryptionShare
from hbbft_tpu.harness.epoch import VectorizedHoneyBadgerSim
from hbbft_tpu.harness.network import (
    BadShareAdversary,
    MessageScheduler,
    SilentAdversary,
    TestNetwork,
)
from hbbft_tpu.obs import recorder as obs
from hbbft_tpu.protocols.honey_badger import HoneyBadger


def _contribs(n, tag):
    return {i: [b"%s-%d" % (tag, i)] for i in range(n)}


def _bogus(rng):
    return MockDecryptionShare(
        rng.randrange(2**256).to_bytes(32, "big"),
        rng.randrange(2**256).to_bytes(32, "big"),
    )


# -- vectorized: speculative vs eager, fault-free ---------------------------


@pytest.mark.parametrize("seed", [0xA1, 0xB2, 0xC3])
def test_spec_byte_identical_fault_free(seed):
    n = 7
    eager = VectorizedHoneyBadgerSim(n, random.Random(seed), mock=True)
    spec = VectorizedHoneyBadgerSim(
        n, random.Random(seed), mock=True, speculative=True
    )
    for e in range(3):
        contribs = _contribs(n, b"s%x-%d" % (seed, e))
        r_e = eager.run_epoch(contribs)
        r_s = spec.run_epoch(contribs)
        assert r_s.batch.contributions == r_e.batch.contributions
        assert r_e.fault_log.is_empty() and r_s.fault_log.is_empty()
        assert r_s.phases["spec_hits"] == n
        assert r_s.phases["spec_misses"] == 0
        assert "spec_hits" not in r_e.phases


def test_spec_byte_identical_real_bls():
    n = 4
    eager = VectorizedHoneyBadgerSim(n, random.Random(7), mock=False)
    spec = VectorizedHoneyBadgerSim(
        n, random.Random(7), mock=False, speculative=True
    )
    contribs = _contribs(n, b"real")
    r_e = eager.run_epoch(contribs)
    r_s = spec.run_epoch(contribs)
    assert r_s.batch.contributions == r_e.batch.contributions
    assert r_s.phases["spec_hits"] == n
    assert r_s.phases["spec_misses"] == 0


# -- vectorized: bad shares, fallback and leftover audit --------------------


def test_bad_share_in_window_falls_back_same_attribution():
    n = 7
    rng = random.Random(0xBAD)
    forged = {0: {p: _bogus(rng) for p in range(n)}}
    eager = VectorizedHoneyBadgerSim(n, random.Random(11), mock=True)
    spec = VectorizedHoneyBadgerSim(
        n, random.Random(11), mock=True, speculative=True
    )
    contribs = _contribs(n, b"win")
    r_e = eager.run_epoch(contribs, forged_dec=forged)
    r_s = spec.run_epoch(contribs, forged_dec=forged)
    assert r_s.batch.contributions == r_e.batch.contributions
    assert {f.node_id for f in r_e.fault_log} == {0}
    assert {f.node_id for f in r_s.fault_log} == {0}
    # index 0 sits in every proposer's lowest-f+1 window: every
    # combined check must miss and fall back to per-share verification
    assert r_s.phases["spec_misses"] == n
    assert r_s.phases["spec_hits"] == 0


def test_bad_share_out_of_window_audited_by_flush():
    n = 7
    rng = random.Random(0xBAE)
    forger = n - 1
    forged = {forger: {p: _bogus(rng) for p in range(n)}}
    eager = VectorizedHoneyBadgerSim(n, random.Random(12), mock=True)
    spec = VectorizedHoneyBadgerSim(
        n, random.Random(12), mock=True, speculative=True
    )
    contribs = _contribs(n, b"out")
    r_e = eager.run_epoch(contribs, forged_dec=forged)
    r_s = spec.run_epoch(contribs, forged_dec=forged)
    assert r_s.batch.contributions == r_e.batch.contributions
    # the forged shares sit past the combine window: the speculative
    # check hits AND the leftover audit still attributes the forger
    assert r_s.phases["spec_hits"] == n
    assert r_s.phases["spec_misses"] == 0
    assert {f.node_id for f in r_e.fault_log} == {forger}
    assert {f.node_id for f in r_s.fault_log} == {forger}


# -- pipelined epoch driving ------------------------------------------------


@pytest.mark.parametrize("speculative", [False, True])
def test_pipelined_epochs_bit_identical_to_serial(speculative):
    n, epochs = 5, 4
    seq = [_contribs(n, b"p%d" % e) for e in range(epochs)]
    runs = {}
    for mode in (False, True, "deep"):
        sim = VectorizedHoneyBadgerSim(
            n, random.Random(0xEE), mock=True, speculative=speculative
        )
        res = sim.run_epochs(seq, pipeline=mode)
        assert all(r.phases["commit_latency"] > 0 for r in res)
        runs[mode] = [r.batch.contributions for r in res]
    assert runs[True] == runs[False]
    assert runs["deep"] == runs[False]


# -- sequential protocol stack ----------------------------------------------


def _run_protocol_net(speculative, adversary_factory=None, n=7, epochs=2):
    f = (n - 1) // 3
    rng = random.Random(0x51E)
    factory = adversary_factory or (
        lambda adv: SilentAdversary(
            MessageScheduler(MessageScheduler.FIRST, rng)
        )
    )
    rec = obs.enable()
    try:
        net = TestNetwork(
            n - f,
            f,
            factory,
            lambda ni: HoneyBadger(
                ni,
                rng=random.Random(f"{ni.our_id}-cl"),
                speculative=speculative,
            ),
            rng,
            mock_crypto=True,
        )

        def commits():
            return min(len(node.outputs) for node in net.nodes.values())

        proposed = {nid: 0 for nid in net.nodes}
        guard = 0
        while commits() < epochs:
            guard += 1
            assert guard < 100_000, "protocol net failed to commit"
            barrier = commits()
            for nid in sorted(net.nodes):
                node = net.nodes[nid]
                if proposed[nid] >= epochs or node.instance.has_input():
                    continue
                if proposed[nid] <= barrier:
                    node.handle_input([b"cl-%d-%d" % (proposed[nid], nid)])
                    msgs = list(node.messages)
                    node.messages.clear()
                    net.dispatch_messages(nid, msgs)
                    proposed[nid] += 1
            if net.any_busy():
                net.step()
    finally:
        obs.disable()
    spec_rows = [e for e in rec.events if e["ev"] == "spec_combine"]
    batches = {
        nid: [
            sorted(
                (k, tuple(v)) for k, v in b.contributions.items()
            )
            for b in net.nodes[nid].outputs
        ]
        for nid in net.nodes
    }
    faults = {
        nid: {(fl.node_id, fl.kind) for fl in net.nodes[nid].faults}
        for nid in net.nodes
    }
    hits = sum(e["hits"] for e in spec_rows)
    misses = sum(e["misses"] for e in spec_rows)
    return batches, faults, hits, misses


def test_sequential_spec_byte_identical():
    eager_b, eager_f, _, _ = _run_protocol_net(False)
    spec_b, spec_f, hits, misses = _run_protocol_net(True)
    assert spec_b == eager_b
    assert eager_f == spec_f == {nid: set() for nid in spec_f}
    # the speculative path actually ran: combined checks hit, no
    # fallback on a fault-free net
    assert hits > 0
    assert misses == 0


def test_sequential_spec_bad_share_fallback():
    def factory(adv):
        return BadShareAdversary(
            MessageScheduler(MessageScheduler.FIRST, random.Random(0xF)),
            random.Random(0xF0),
            epochs=2,
        )

    eager_b, eager_f, _, _ = _run_protocol_net(False, factory)
    spec_b, spec_f, hits, misses = _run_protocol_net(True, factory)
    assert spec_b == eager_b
    assert hits + misses > 0
    # shares arriving after a node already decrypted are never
    # verified, so per-node attribution is timing-dependent — but a
    # speculative node only ever verifies a subset of what its eager
    # twin verifies (module doc: spec-flagged subset of eager-flagged)
    for nid in eager_f:
        assert spec_f[nid] <= eager_f[nid]
    assert any(eager_f.values())


# -- signature-scheme seam --------------------------------------------------


def test_scheme_bls_round_trip():
    from hbbft_tpu.crypto import threshold as T

    scheme = scheme_mod.get_scheme()
    assert scheme.name == scheme_mod.DEFAULT_SCHEME == "bls381"
    sks = T.SecretKeySet.random(1, random.Random(5))
    pk_set = sks.public_keys()
    msg = b"scheme seam"
    shares = {
        i: scheme.sign_share(sks.secret_key_share(i), msg) for i in range(2)
    }
    for i, share in shares.items():
        assert scheme.verify_share(pk_set.public_key_share(i), share, msg)
    sig = scheme.combine(pk_set, shares)
    assert scheme.verify(pk_set, sig, msg)
    assert scheme.combine_and_check is not None


def test_scheme_eddsa_stub_and_unknown():
    assert set(scheme_mod.available_schemes()) == {"bls381", "eddsa"}
    eddsa = scheme_mod.get_scheme("eddsa")
    with pytest.raises(NotImplementedError):
        eddsa.sign_share(None, b"x")
    with pytest.raises(ValueError, match="unknown signature scheme"):
        scheme_mod.get_scheme("rsa")


# -- observability rows -----------------------------------------------------


def test_commit_latency_and_spec_obs_events():
    n, epochs = 5, 2
    rec = obs.enable()
    try:
        sim = VectorizedHoneyBadgerSim(
            n, random.Random(3), mock=True, speculative=True
        )
        seq = [_contribs(n, b"o%d" % e) for e in range(epochs)]
        sim.run_epochs(seq, pipeline=False)
    finally:
        obs.disable()
    spec_rows = [e for e in rec.events if e["ev"] == "spec_combine"]
    assert len(spec_rows) == epochs
    assert all(e["hits"] == n and e["misses"] == 0 for e in spec_rows)
    lat_rows = [e for e in rec.events if e["ev"] == "commit_latency"]
    assert len(lat_rows) == epochs
    assert all(e["latency_s"] > 0 and e["mode"] == "serial" for e in lat_rows)
    assert [e["epoch"] for e in lat_rows] == list(range(epochs))
