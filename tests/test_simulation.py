"""Virtual-time simulator tests (mirrors ``examples/simulation.rs``
semantics: virtual clocks, bandwidth serialization delay, epoch table)."""

import random

from hbbft_tpu.harness.simulation import (
    EpochStats,
    HwQuality,
    SimNetwork,
    simulate_queueing_honey_badger,
)


def test_hw_quality_flags():
    hw = HwQuality.from_flags(lag_ms=100, bw_kbit_s=2000, cpu_pct=50)
    assert abs(hw.latency - 0.1) < 1e-9
    assert abs(hw.inv_bw - 8.0 / 2_000_000) < 1e-12
    assert hw.cpu_factor == 50


def test_simulation_commits_all_txs():
    stats, wall, sim = simulate_queueing_honey_badger(
        num_nodes=5,
        num_txs=40,
        batch_size=20,
        rng=random.Random(2),
    )
    assert stats.rows, "no epochs completed"
    assert all(r.min_time <= r.max_time for r in stats.rows)
    # virtual time advances monotonically across epochs
    times = [r.max_time for r in stats.rows]
    assert times == sorted(times)
    # messages were accounted
    assert stats.rows[-1].msgs_per_node > 0
    assert stats.rows[-1].bytes_per_node > 0


def test_simulation_with_dead_nodes():
    # f dead nodes: the remaining N-f must still commit everything
    stats, wall, sim = simulate_queueing_honey_badger(
        num_nodes=4,
        num_dead=1,
        num_txs=20,
        batch_size=10,
        rng=random.Random(3),
    )
    assert stats.rows


def test_latency_dominates_virtual_time():
    # with 1s lag and tiny payloads, one epoch takes at least ~2 lags
    stats, _, sim = simulate_queueing_honey_badger(
        num_nodes=4,
        num_txs=4,
        batch_size=4,
        lag_ms=1000.0,
        rng=random.Random(4),
    )
    assert sim >= 2.0
