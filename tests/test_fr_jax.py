"""Device Fr matmul (``ops/fr_jax.py``) — exactness against the
native host path and against plain Python big-int arithmetic,
including adversarial-magnitude limb inputs (the redundant 33-limb
representation's worst case)."""

import random

import numpy as np
import pytest

from hbbft_tpu.crypto import fields as F
from hbbft_tpu.ops import fr_jax

R = F.R


def _rand_fr(rng, n):
    return [rng.randrange(R) for _ in range(n)]


def test_limb_roundtrip():
    rng = random.Random(1)
    vals = [0, 1, R - 1] + _rand_fr(rng, 5)
    limbs = fr_jax.fr_to_limbs(vals)
    assert limbs.shape == (8, fr_jax.FR_LIMBS)
    assert fr_jax.limbs_to_fr(limbs) == vals


def test_be32_roundtrip():
    rng = random.Random(2)
    vals = _rand_fr(rng, 6)
    be = np.frombuffer(
        b"".join(v.to_bytes(32, "big") for v in vals), dtype=np.uint8
    )
    limbs = fr_jax.be32_to_limbs(be)
    assert fr_jax.limbs_to_fr(limbs) == vals
    assert np.array_equal(fr_jax.limbs_to_be32(limbs), be)


def test_matmul_matches_bigint():
    rng = random.Random(3)
    m, k, p = 3, 5, 4
    A = [_rand_fr(rng, k) for _ in range(m)]
    B = [_rand_fr(rng, p) for _ in range(k)]
    a = fr_jax.fr_to_limbs([x for row in A for x in row]).reshape(
        m, k, fr_jax.FR_LIMBS
    )
    b = fr_jax.fr_to_limbs([x for row in B for x in row]).reshape(
        k, p, fr_jax.FR_LIMBS
    )
    got = fr_jax.limbs_to_fr(np.asarray(fr_jax.fr_matmul_device(a, b)))
    want = [
        sum(A[i][t] * B[t][j] for t in range(k)) % R
        for i in range(m)
        for j in range(p)
    ]
    assert got == want


def test_matmul_matches_native():
    from hbbft_tpu import native as NT

    if not NT.available():
        pytest.skip("native library unavailable")
    rng = random.Random(4)
    m, k, p = 4, 7, 6
    A = _rand_fr(rng, m * k)
    B = _rand_fr(rng, k * p)
    abuf = np.frombuffer(
        b"".join(v.to_bytes(32, "big") for v in A), dtype=np.uint8
    ).copy()
    bbuf = np.frombuffer(
        b"".join(v.to_bytes(32, "big") for v in B), dtype=np.uint8
    ).copy()
    want = NT.fr_matmul(abuf, bbuf, m, k, p)
    a = fr_jax.be32_to_limbs(abuf).reshape(m, k, fr_jax.FR_LIMBS)
    b = fr_jax.be32_to_limbs(bbuf).reshape(k, p, fr_jax.FR_LIMBS)
    got = fr_jax.limbs_to_be32(np.asarray(fr_jax.fr_matmul_device(a, b)))
    assert np.array_equal(got, np.asarray(want))


def test_matmul_redundant_worst_case():
    # all-0xFF limb inputs (value 2^264-1, far above r) through the
    # matmul: the fold bound must hold and results stay exact mod r
    m, k, p = 2, 3, 2
    a = np.full((m, k, fr_jax.FR_LIMBS), 0xFF, dtype=np.uint8)
    b = np.full((k, p, fr_jax.FR_LIMBS), 0xFF, dtype=np.uint8)
    out = np.asarray(fr_jax.fr_matmul_device(a, b))
    assert out.shape == (m, p, fr_jax.FR_LIMBS)
    v = (2**264 - 1) % R
    want = (k * v * v) % R
    assert fr_jax.limbs_to_fr(out) == [want] * (m * p)


def test_matmul_contraction_bound():
    a = np.zeros((1, fr_jax._MAX_K + 1, fr_jax.FR_LIMBS), dtype=np.uint8)
    b = np.zeros((fr_jax._MAX_K + 1, 1, fr_jax.FR_LIMBS), dtype=np.uint8)
    with pytest.raises(ValueError):
        fr_jax.fr_matmul_device(a, b)


def test_add_device():
    rng = random.Random(5)
    xs = _rand_fr(rng, 4)
    ys = _rand_fr(rng, 4)
    a = fr_jax.fr_to_limbs(xs)
    b = fr_jax.fr_to_limbs(ys)
    got = fr_jax.limbs_to_fr(np.asarray(fr_jax.fr_add_device(a, b)))
    assert got == [(x + y) % R for x, y in zip(xs, ys)]


def test_sample_shape_and_range():
    import jax

    key = jax.random.PRNGKey(7)
    s = np.asarray(fr_jax.sample_fr_device(key, (3, 2)))
    assert s.shape == (3, 2, fr_jax.FR_LIMBS)
    vals = fr_jax.limbs_to_fr(s)
    assert all(0 <= v < R for v in vals)
    assert len(set(vals)) == len(vals)  # overwhelmingly distinct
