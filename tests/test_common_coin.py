"""Common-coin tests (mirrors ``tests/common_coin.rs``): every good node
and the observer get the same value; repeated fresh-nonce flips approach
a fair distribution."""

import random

import pytest

from hbbft_tpu.harness.network import (
    MessageScheduler,
    SilentAdversary,
    TestNetwork,
)
from hbbft_tpu.protocols.common_coin import CommonCoin


def flip(rng, size: int, nonce: bytes, mock: bool, scheduler_kind) -> bool:
    f = (size - 1) // 3
    good = size - f
    net = TestNetwork(
        good,
        f,
        lambda adv: SilentAdversary(MessageScheduler(scheduler_kind, rng)),
        lambda ni: CommonCoin(ni, nonce),
        rng,
        mock_crypto=mock,
    )
    net.input_all(None)
    # the observer wants the coin too (it cannot contribute a share)
    net.observer.handle_input(None)
    assert not net.observer.messages
    net.step_until(
        lambda: all(n.outputs for n in net.nodes.values())
    )
    values = {tuple(n.outputs) for n in net.nodes.values()}
    assert len(values) == 1, "coin values diverged"
    (out,) = values
    assert len(out) == 1
    # observer cannot sign but must still learn the coin
    assert net.observer.outputs == list(out)
    return out[0]


@pytest.mark.parametrize("kind", [MessageScheduler.RANDOM, MessageScheduler.FIRST])
def test_coin_mock_distribution(kind):
    rng = random.Random(10)
    results = [
        flip(rng, 4, b"flip-%d" % i, True, kind) for i in range(64)
    ]
    trues = sum(results)
    # binomial(64, 0.5): P(<16 or >48) < 1e-4
    assert 16 <= trues <= 48, trues


def test_coin_mock_sizes():
    rng = random.Random(11)
    for size in (1, 2, 4, 7, 10, 13):
        flip(rng, size, b"size-%d" % size, True, MessageScheduler.RANDOM)


def test_coin_real_bls_consistency():
    rng = random.Random(12)
    seen = {flip(rng, 4, b"real-%d" % i, False, MessageScheduler.RANDOM)
            for i in range(4)}
    assert seen <= {True, False}


def test_coin_mock_distribution_200_samples():
    """200-flip fairness suite mirroring the reference's statistical
    check (``tests/common_coin.rs:59-73``, 200-sample suite with an
    explicit bound): for fair flips the count of each outcome must
    clear a large-deviation lower bound — here Chernoff at 5σ:
    P(|trues − 100| > 35) < 2·exp(−2·35²/200) ≈ 9·10⁻⁶."""
    rng = random.Random(11)
    n = 200
    trues = sum(
        flip(rng, 4, b"fair-%d" % i, True, MessageScheduler.RANDOM)
        for i in range(n)
    )
    lo, hi = 100 - 35, 100 + 35
    assert lo <= trues <= hi, trues


def test_coin_mock_distribution_multi_size():
    """Fairness holds across network sizes (50 samples each, looser
    5σ-equivalent bound for the smaller suite)."""
    rng = random.Random(12)
    for size in (1, 7, 10):
        trues = sum(
            flip(rng, size, b"ms-%d-%d" % (size, i), True,
                 MessageScheduler.RANDOM)
            for i in range(50)
        )
        assert 7 <= trues <= 43, (size, trues)
