"""TCP transport integration tests — real sockets on localhost.

Mirrors the reference's ``examples/consensus-node.rs`` scenario: N
processes' worth of nodes (here: N tasks on one loop, real TCP in
between) run Reliable Broadcast and must all output the proposed
value.  Also runs full HoneyBadger over TCP — beyond the reference
example's single-Broadcast scope.
"""

import asyncio
import random

import pytest

from hbbft_tpu.protocols.broadcast import Broadcast
from hbbft_tpu.protocols.honey_badger import HoneyBadger
from hbbft_tpu.transport.tcp import TcpNode


def _free_ports(n):
    import socket

    socks, ports = [], []
    for _ in range(n):
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        socks.append(s)
        ports.append(s.getsockname()[1])
    for s in socks:
        s.close()
    return ports


def _addrs(n):
    return sorted(f"127.0.0.1:{p}" for p in _free_ports(n))


async def _run_broadcast(n=4):
    addrs = _addrs(n)
    proposer = addrs[0]
    nodes = [
        TcpNode(a, [x for x in addrs if x != a], lambda ni: Broadcast(ni, proposer))
        for a in addrs
    ]
    await asyncio.gather(*(node.start() for node in nodes))
    await nodes[0].input(b"tcp-payload")
    results = await asyncio.gather(
        *(node.run(timeout=30.0) for node in nodes)
    )
    await asyncio.gather(*(node.close() for node in nodes))
    return results


def test_broadcast_over_tcp():
    results = asyncio.run(_run_broadcast(4))
    assert all(r == [b"tcp-payload"] for r in results), results


def test_start_fails_fast_when_peer_unreachable():
    """A dead peer must surface a ConnectionError from start(), not
    hang the mesh-up wait forever."""

    async def run():
        addrs = _addrs(2)  # second address is never bound
        node = TcpNode(
            addrs[0],
            [addrs[1]],
            lambda ni: Broadcast(ni, addrs[0]),
            dial_retries=3,
        )
        with pytest.raises(ConnectionError):
            await asyncio.wait_for(node.start(), timeout=10.0)
        await node.close()

    asyncio.run(run())


def test_malformed_frame_dropped_stream_survives():
    """A well-framed but undecodable payload is dropped; later frames
    on the same connection still arrive (length-prefix resync)."""
    from hbbft_tpu.core.serialize import dumps
    from hbbft_tpu.transport.tcp import _frame

    async def run():
        addrs = _addrs(2)
        node = TcpNode(
            addrs[0], [addrs[1]], lambda ni: Broadcast(ni, addrs[0])
        )
        reader = asyncio.StreamReader()
        garbage = b"\xff\xfe\xfd"  # no valid wire tag
        reader.feed_data(len(garbage).to_bytes(4, "big") + garbage)
        reader.feed_data(_frame(b"still-alive"))
        reader.feed_eof()
        await node._recv_loop(addrs[1], reader)
        assert node._inbox.qsize() == 1
        sender, msg = node._inbox.get_nowait()
        assert (sender, msg) == (addrs[1], b"still-alive")

    asyncio.run(run())


def test_honey_badger_over_tcp():
    """One full HoneyBadger epoch across real sockets: every node
    proposes, every node commits the same batch."""

    async def run():
        addrs = _addrs(4)
        nodes = [
            TcpNode(
                a,
                [x for x in addrs if x != a],
                lambda ni: HoneyBadger(
                    ni, rng=random.Random(f"tcp-{ni.our_id}")
                ),
            )
            for a in addrs
        ]
        await asyncio.gather(*(node.start() for node in nodes))
        for i, node in enumerate(nodes):
            await node.input([b"tx-%d" % i])
        results = await asyncio.gather(
            *(
                node.run(until=lambda nd: len(nd.outputs) >= 1, timeout=30.0)
                for node in nodes
            )
        )
        await asyncio.gather(*(node.close() for node in nodes))
        return results

    results = asyncio.run(run())
    batches = [
        (b.epoch, tuple(sorted((k, tuple(v)) for k, v in b.contributions.items())))
        for r in results
        for b in r[:1]
    ]
    assert len(set(batches)) == 1, batches
    # all four contributions made it into the batch
    assert len(batches[0][1]) == 4
