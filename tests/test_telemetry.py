"""Tests for the fleet telemetry plane: trace-context stamping, the
``ObTrace`` wire piggyback (valid → ``trace_link``, malformed →
attributed fault), the Prometheus exporter + fleet poller, the flight
recorder (ring bounds, forced dumps, crash-safe persist mode, a real
SIGKILL mid-run), the post-mortem timeline (joins, chains, hop walls,
declarative SLO rules), and the real-TCP n=4 acceptance run — fleet
scrape + ≥99% complete admit→ack chains + health rules green."""

import asyncio
import json
import os
import signal
import socket
import subprocess
import sys
import time

import pytest

from hbbft_tpu.obs import fleet as fleet_mod
from hbbft_tpu.obs import flight as flight_mod
from hbbft_tpu.obs import metrics as metrics_mod
from hbbft_tpu.obs import recorder as obs
from hbbft_tpu.obs import report, timeline
from hbbft_tpu.recover.wal import read_records


@pytest.fixture(autouse=True)
def _no_leaked_recorder():
    obs.disable()
    yield
    obs.disable()


# ---------------------------------------------------------------------------
# trace-context stamping
# ---------------------------------------------------------------------------


def test_trace_context_stamped_on_every_row():
    rec = obs.enable(node="n0")
    rec.event("epoch_start", epoch=0, vt=0.1)
    rec.set_epoch(3)
    rec.event("epoch_decide", epoch=3, node=1, vt=0.9)
    rows = rec.events
    assert all(r["tn"] == "n0" for r in rows)
    seqs = [r["ts"] for r in rows]
    assert seqs == sorted(seqs) and len(set(seqs)) == len(seqs)
    assert "te" not in rows[1]  # before set_epoch
    assert rows[-1]["te"] == 3
    obs.disable()


def test_trace_context_absent_without_node():
    rec = obs.enable()
    rec.event("epoch_start", epoch=0, vt=0.1)
    assert "tn" not in rec.events[-1] and "ts" not in rec.events[-1]
    rec.set_node("late")
    rec.event("epoch_start", epoch=1, vt=0.2)
    assert rec.events[-1]["tn"] == "late"
    obs.disable()


def test_set_epoch_rejects_non_int():
    rec = obs.enable(node="n0")
    rec.set_epoch(True)
    rec.set_epoch("7")
    rec.event("epoch_start", epoch=0, vt=0.1)
    assert "te" not in rec.events[-1]
    obs.disable()


# ---------------------------------------------------------------------------
# ObTrace piggyback over the real recv loop
# ---------------------------------------------------------------------------


def _frame(payload: bytes) -> bytes:
    from hbbft_tpu.transport import tcp as _tcp

    return len(payload).to_bytes(_tcp._LEN_BYTES, "big") + payload


def _pump(node, *messages):
    from hbbft_tpu.core.serialize import dumps

    async def run():
        reader = asyncio.StreamReader()
        for m in messages:
            reader.feed_data(_frame(dumps(m)))
        reader.feed_eof()
        await node._recv_loop("peer-under-test", reader)

    asyncio.run(run())


def test_obtrace_valid_emits_trace_link():
    from hbbft_tpu.transport import tcp as _tcp

    rec = obs.enable(node="b")
    node = _tcp.TcpNode("127.0.0.1:2", ["127.0.0.1:1"], lambda ni: None)
    _pump(node, _tcp.ObTrace("127.0.0.1:1", 7, 3), _tcp.ObTrace("127.0.0.1:1", 8, None))
    links = [e for e in rec.events if e["ev"] == "trace_link"]
    assert len(links) == 2
    assert links[0]["node"] == "127.0.0.1:2"
    assert links[0]["peer"] == "127.0.0.1:1"
    assert links[0]["seq"] == 7 and links[0]["epoch"] == 3
    assert "epoch" not in links[1]
    assert rec.counters.get("wire.obtrace") == 2
    assert node.faults == []
    obs.disable()


def test_obtrace_malformed_attributed_never_fatal():
    from hbbft_tpu.core.fault import FaultKind
    from hbbft_tpu.transport import tcp as _tcp

    rec = obs.enable(node="b")
    node = _tcp.TcpNode("127.0.0.1:2", ["127.0.0.1:1"], lambda ni: None)
    bad = [
        _tcp.ObTrace(True, 1, None),  # bool node id
        _tcp.ObTrace(None, 1, None),  # missing node id
        _tcp.ObTrace("n", -1, None),  # negative seq
        _tcp.ObTrace("n", 1, "x"),  # non-int epoch
        _tcp.ObTrace("n", 2**80, None),  # seq out of range
    ]
    _pump(node, *bad, _tcp.ObTrace("n", 5, 0))
    assert rec.counters.get("wire.bad_obtrace") == len(bad)
    assert len(node.faults) == len(bad)
    assert all(f.kind is FaultKind.INVALID_MESSAGE for f in node.faults)
    # the pump survived all of them and still linked the valid one
    assert rec.counters.get("wire.obtrace") == 1
    obs.disable()


# ---------------------------------------------------------------------------
# metrics: render/parse, exporter, fleet poller
# ---------------------------------------------------------------------------


def test_metrics_render_parse_roundtrip():
    rec = obs.enable(node="n3")
    rec.count("wire.seq_gap", 2)
    rec.count("gateway.admitted", 41)
    for v in (0.1, 0.2, 0.3, 0.4):
        rec.observe("gateway.commit_latency_s", v)
    body = metrics_mod.MetricsCore().render()
    series = metrics_mod.parse(body)
    assert series['hbbft_wire_seq_gap_total{node="n3"}'] == 2.0
    assert series['hbbft_gateway_admitted_total{node="n3"}'] == 41.0
    assert series['hbbft_gateway_commit_latency_s{node="n3",stat="count"}'] == 4.0
    assert series['hbbft_gateway_commit_latency_s{node="n3",stat="max"}'] == pytest.approx(0.4)
    assert series['hbbft_obs_events_total{node="n3"}'] >= 1.0
    obs.disable()


def test_metrics_render_with_tracing_off_is_valid():
    body = metrics_mod.MetricsCore(node="nx").render()
    assert body.endswith("\n")
    assert metrics_mod.parse(body) == {}


def test_parse_drops_malformed_lines():
    got = metrics_mod.parse("a 1\nbroken\n# c 2\nd nan-ish-not\ne 2.5\n")
    assert got == {"a": 1.0, "e": 2.5}


def test_exporter_and_fleet_poller(tmp_path):
    rec = obs.enable(node="n0")
    rec.count("gateway.admitted", 5)
    out = tmp_path / "fleet.jsonl"

    async def run():
        exp_a = metrics_mod.MetricsExporter(metrics_mod.MetricsCore(node="n0"))
        exp_b = metrics_mod.MetricsExporter(metrics_mod.MetricsCore(node="n1"))
        await exp_a.start()
        await exp_b.start()
        # a dead target: bind a port and close it so nothing listens
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        dead_port = s.getsockname()[1]
        s.close()
        poller = fleet_mod.FleetPoller(
            {
                "n0": exp_a.addr,
                "n1": exp_b.addr,
                "dead": ("127.0.0.1", dead_port),
            },
            str(out),
            timeout_s=2.0,
        )
        rows = await poller.poll_once()
        await exp_a.stop()
        await exp_b.stop()
        return rows

    rows = asyncio.run(run())
    by_node = {r["node"]: r for r in rows}
    assert by_node["n0"]["up"] and by_node["n1"]["up"]
    assert not by_node["dead"]["up"]
    agg = fleet_mod.aggregate(rows)
    assert agg["up"] == 2 and agg["nodes"] == 3
    # both live nodes exported the shared counter: the sum sees 2x
    assert agg["totals"]["hbbft_gateway_admitted_total"] == 10.0
    # the JSONL artifact round-trips through the report loader
    disk = report.load_events(str(out))
    assert len(disk) == 3 and all(r["ev"] == "metrics_scrape" for r in disk)
    # live metrics_scrape rows were emitted into the active trace too
    scraped = [e for e in rec.events if e["ev"] == "metrics_scrape"]
    assert len(scraped) == 3
    obs.disable()


# ---------------------------------------------------------------------------
# flight recorder
# ---------------------------------------------------------------------------


def test_flight_ring_bounds_and_dump(tmp_path):
    path = tmp_path / "flight.jsonl"
    fl = flight_mod.FlightRecorder(str(path), capacity=8, node="n0")
    for i in range(30):
        fl.record({"ev": "x", "t": i * 0.1, "i": i})
    fl.dump("test")
    rows, meta = flight_mod.load(str(path))
    assert meta["reason"] == "test"
    assert meta["events"] == 8 and meta["dropped"] == 22
    assert [r["i"] for r in rows] == list(range(22, 30))
    fl.close()


def test_fault_event_triggers_flight_dump(tmp_path):
    path = tmp_path / "flight.jsonl"
    rec = obs.enable(node="n0")
    fl = flight_mod.FlightRecorder(str(path), capacity=16, node="n0")
    rec.attach_flight(fl)
    rec.event("epoch_start", epoch=0, vt=0.1)
    assert not path.exists()
    rec.event("fault", fault="1:INVALID_MESSAGE", node=1, kind="INVALID_MESSAGE")
    assert path.exists()
    rows, meta = flight_mod.load(str(path))
    assert meta["reason"] == "fault"
    assert any(r["ev"] == "fault" for r in rows)
    # the dump itself is announced in the live trace
    assert any(e["ev"] == "flight_dump" for e in rec.events)
    fl.close()
    obs.disable()


def test_flight_persist_write_through(tmp_path):
    dump = tmp_path / "flight.jsonl"
    persist = tmp_path / "flight.persist.jsonl"
    fl = flight_mod.FlightRecorder(
        str(dump), capacity=8, node="n0", persist=str(persist)
    )
    for i in range(5):
        fl.record({"ev": "x", "t": float(i), "i": i})
    # NO dump, NO close — the persist file must already hold every row
    rows, meta = flight_mod.load(str(persist))
    assert meta is None
    assert [r["i"] for r in rows] == list(range(5))
    fl.close()


def test_flight_persist_compacts_to_ring_bound(tmp_path):
    persist = tmp_path / "p.jsonl"
    fl = flight_mod.FlightRecorder(
        str(tmp_path / "d.jsonl"), capacity=10, node="n0", persist=str(persist)
    )
    for i in range(200):
        fl.record({"ev": "x", "t": float(i), "i": i})
    rows, _ = flight_mod.load(str(persist))
    # bounded: compaction keeps the file within 4x the ring capacity
    assert len(rows) <= 40
    assert rows[-1]["i"] == 199
    fl.close()


_SIGKILL_CHILD = r"""
import asyncio, random, sys
from hbbft_tpu.obs import flight as flight_mod
from hbbft_tpu.obs import recorder as obs
from hbbft_tpu.protocols.honey_badger import HoneyBadger
from hbbft_tpu.recover.driver import durable_tcp_node

our, wal_path, persist_path = sys.argv[1], sys.argv[2], sys.argv[3]
peers = sys.argv[4:]
rec = obs.enable(node=our)
fl = flight_mod.FlightRecorder(
    persist_path + ".dump", capacity=256, node=our, persist=persist_path
)
rec.attach_flight(fl)
node = durable_tcp_node(
    our, peers, lambda ni: HoneyBadger(ni, rng=random.Random("sk-%s" % ni.our_id)),
    wal_path, fsync="off",
)

async def main():
    await node.start(mesh_timeout=15)
    await node.input([b"victim-e0"])
    await node.run(until=lambda nd: len(nd.outputs) >= 1, timeout=60)
    print("EPOCH0-COMMITTED", flush=True)
    # keep serving until the parent SIGKILLs us
    await node.run(until=lambda nd: len(nd.outputs) >= 10**6, timeout=600)

asyncio.run(main())
"""


def _free_addrs(k):
    socks = []
    for _ in range(k):
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        socks.append(s)
    addrs = sorted("127.0.0.1:%d" % s.getsockname()[1] for s in socks)
    for s in socks:
        s.close()
    return addrs


def test_flight_survives_sigkill(tmp_path):
    """A real-TCP node in a separate process is SIGKILLed mid-run; its
    write-through flight file must be complete and parseable, and its
    last ``wal_append`` row must match the WAL's on-disk high-water
    mark — the flight recorder is trustworthy evidence after a crash
    the process never saw coming."""
    import random

    from hbbft_tpu.protocols.honey_badger import HoneyBadger
    from hbbft_tpu.transport.tcp import TcpNode

    addrs = _free_addrs(4)
    victim = addrs[0]  # smallest addr dials every peer itself
    peers = [a for a in addrs if a != victim]
    wal_path = str(tmp_path / "victim.wal")
    persist_path = str(tmp_path / "victim.flight.jsonl")

    env = dict(os.environ, JAX_PLATFORMS="cpu")
    child = subprocess.Popen(
        [sys.executable, "-c", _SIGKILL_CHILD, victim, wal_path, persist_path]
        + peers,
        stdout=subprocess.PIPE,
        stderr=subprocess.PIPE,
        env=env,
        text=True,
    )

    def new_algo(ni):
        return HoneyBadger(ni, rng=random.Random("sk-%s" % ni.our_id))

    async def run():
        nodes = {
            a: TcpNode(a, [x for x in addrs if x != a], new_algo)
            for a in peers
        }
        await asyncio.gather(
            *(nd.start(mesh_timeout=30) for nd in nodes.values())
        )
        for i, a in enumerate(peers):
            await nodes[a].input([b"peer-e0-%d" % i])
        await asyncio.gather(
            *(
                nodes[a].run(until=lambda nd: len(nd.outputs) >= 1, timeout=120)
                for a in peers
            )
        )
        await asyncio.gather(*(nd.close() for nd in nodes.values()))

    try:
        asyncio.run(run())
        # wait for the victim to report its commit, let its tail settle,
        # then kill it with no warning whatsoever
        line = child.stdout.readline()
        assert "EPOCH0-COMMITTED" in line, (
            line + (child.stderr.read() or "")
        )
        time.sleep(0.5)
        os.kill(child.pid, signal.SIGKILL)
        child.wait(timeout=30)
    finally:
        if child.poll() is None:
            child.kill()
            child.wait(timeout=30)
    assert child.returncode == -signal.SIGKILL

    rows, _meta = flight_mod.load(persist_path)
    assert rows, "flight persist file is empty"
    # complete & parseable: every row is a dict with the stamp
    assert all(r.get("tn") == victim for r in rows)
    seqs = [r["ts"] for r in rows]
    assert seqs == sorted(seqs)
    wal_rows = [r for r in rows if r.get("ev") == "wal_append"]
    assert wal_rows, "no wal_append rows reached the flight recorder"
    on_disk, _clean = read_records(wal_path)
    assert wal_rows[-1]["records"] == len(on_disk)
    # the victim's trace rows include real wire traffic with the causal
    # join fields — the post-mortem can splice this node back in
    sends = [r for r in rows if r.get("ev") == "wire_send"]
    assert sends and all("seq" in r and r["node"] == victim for r in sends)


# ---------------------------------------------------------------------------
# timeline: joins, chains, rules
# ---------------------------------------------------------------------------


def _write_jsonl(path, rows):
    with open(path, "w") as fh:
        for r in rows:
            fh.write(json.dumps(r) + "\n")
    return str(path)


def test_timeline_wire_joins_and_chains(tmp_path):
    a = _write_jsonl(
        tmp_path / "a.jsonl",
        [
            {"ev": "trace_start", "t": 0.0, "wall_unix": 100.0, "schema": 2},
            {"ev": "gateway_admit", "t": 0.01, "client": "c0", "seq": 1,
             "tenant": "t", "depth": 1},
            {"ev": "wire_send", "t": 0.02, "node": "A", "peer": "B", "seq": 1,
             "size": 10, "kind": "SeqData"},
            {"ev": "wire_send", "t": 0.03, "node": "A", "peer": "B", "seq": 2,
             "size": 10, "kind": "SeqData"},
            {"ev": "gossip_relay", "t": 0.025, "txs": 1},
            {"ev": "client_commit_latency", "t": 0.30, "latency_s": 0.29,
             "client": "c0", "seq": 1, "epoch": 0, "tenant": "t"},
            {"ev": "client_commit_latency", "t": 0.31, "latency_s": 0.30,
             "client": "c9", "seq": 4, "epoch": 0, "tenant": "t"},
        ],
    )
    b = _write_jsonl(
        tmp_path / "b.jsonl",
        [
            {"ev": "trace_start", "t": 0.0, "wall_unix": 100.05, "schema": 2},
            {"ev": "wire_recv", "t": 0.0, "node": "B", "peer": "A", "seq": 1,
             "size": 10},
            {"ev": "acs_done", "t": 0.1, "node": "B", "epoch": 0},
            {"ev": "node_commit", "t": 0.2, "node": "B", "epoch": 0, "txs": 2},
        ],
    )
    tl = timeline.build([a, b])
    assert tl["joins"]["sends"] == 2 and tl["joins"]["joined"] == 1
    # chain c0/1 is complete; c9/4 has no admit → incomplete
    assert tl["chains"]["committed"] == 2 and tl["chains"]["complete"] == 1
    assert tl["chains"]["incomplete_sample"][0]["client"] == "c9"
    assert tl["nodes"] == ["B"]
    (epoch,) = tl["epochs"]
    assert epoch["epoch"] == 0 and epoch["commit_nodes"] == 1
    assert epoch["txs"] == 2
    # hop walls exist and respect the wall-clock anchors
    assert epoch["hops"]["admit_to_gossip"] == pytest.approx(0.015)
    assert "gossip_to_acs" in epoch["hops"]
    assert "acs_to_commit" in epoch["hops"]
    assert "commit_to_ack" in epoch["hops"]
    # the 50% join rate and 50% chain rate trip the default rules
    failed = {r["rule"] for r in tl["health"] if r["status"] == "FAIL"}
    assert {"chain-complete", "trace-joins"} <= failed
    assert not tl["ok"]


def test_timeline_flight_dump_borrows_anchor_and_dedupes(tmp_path):
    # A flight dump has no trace_start row: its rows reuse the live
    # recorder's relative t.  The merge must borrow the trace file's
    # wall anchor via the shared (tn, ts) identity and collapse the
    # mirrored copies — otherwise a hop pairing a raw-clock row with an
    # anchored one puts ~the unix epoch into the wall diff.
    trace = _write_jsonl(
        tmp_path / "trace.jsonl",
        [
            {"ev": "trace_start", "t": 0.0, "wall_unix": 1.7e9, "schema": 2},
            {"ev": "acs_done", "t": 5.0, "node": "n0", "epoch": 0,
             "tn": "n0", "ts": 1, "te": 0},
            {"ev": "node_commit", "t": 5.5, "node": "n0", "epoch": 0,
             "txs": 1, "tn": "n0", "ts": 2, "te": 0},
        ],
    )
    flight = _write_jsonl(
        tmp_path / "flight.jsonl",
        [
            # mirrored copy of ts=2 plus a ring-only row the trace lacks
            {"ev": "node_commit", "t": 5.5, "node": "n0", "epoch": 1,
             "txs": 1, "tn": "n0", "ts": 2, "te": 0},
            {"ev": "acs_done", "t": 6.0, "node": "n0", "epoch": 1,
             "tn": "n0", "ts": 3, "te": 1},
        ],
    )
    rows = timeline.merge([trace, flight])
    commits = [r for r in rows if r["ev"] == "node_commit"]
    assert len(commits) == 1  # mirrored copy deduped by (tn, ts)
    by_ts = {r["ts"]: r for r in rows if "ts" in r}
    # flight-only row sits on the borrowed anchor, not raw t
    assert by_ts[3]["_wall"] == pytest.approx(1.7e9 + 6.0)
    tl = timeline.build([trace, flight])
    (epoch0,) = [e for e in tl["epochs"] if e["epoch"] == 0]
    assert epoch0["hops"]["acs_to_commit"] == pytest.approx(0.5)


def test_timeline_rules_counters_and_absent(tmp_path):
    p = _write_jsonl(
        tmp_path / "t.jsonl",
        [
            {"ev": "trace_start", "t": 0.0, "wall_unix": 1.0, "schema": 2},
            {"ev": "counter", "t": 1.0, "name": "wire.seq_gap", "value": 3},
            {"ev": "hist", "t": 1.0, "name": "reveal.lag_s", "count": 2,
             "min": 0.1, "p50": 0.5, "p90": 2.0, "max": 2.0, "sum": 2.1},
        ],
    )
    tl = timeline.build([p])
    by_rule = {r["rule"]: r for r in tl["health"]}
    assert by_rule["wire-seq-gap"]["status"] == "FAIL"
    assert by_rule["wire-seq-gap"]["value"] == 3.0
    assert by_rule["reveal-lag-p90"]["status"] == "FAIL"  # p90=2.0 > 1.0
    assert by_rule["wire-replay-evicted"]["status"] == "absent"
    assert by_rule["spec-combine-misses"]["status"] == "absent"
    assert not tl["ok"]


def test_timeline_custom_rules_and_cli(tmp_path):
    p = _write_jsonl(
        tmp_path / "t.jsonl",
        [
            {"ev": "trace_start", "t": 0.0, "wall_unix": 1.0, "schema": 2},
            {"ev": "counter", "t": 1.0, "name": "gateway.admitted", "value": 7},
        ],
    )
    rules = tmp_path / "slo.rules"
    rules.write_text(
        "# comment\n"
        "admitted counter:gateway.admitted >= 5\n"
        "scrapes event_count:metrics_scrape <= 0\n"
    )
    parsed = timeline.parse_rules(str(rules))
    assert parsed == [
        ("admitted", "counter:gateway.admitted", ">=", 5.0),
        ("scrapes", "event_count:metrics_scrape", "<=", 0.0),
    ]
    assert timeline.main([p, "--rules", str(rules)]) == 0
    # default rules also pass on this quiet trace...
    assert timeline.main([p]) == 0
    # ...but --min-join fails it: there are no joinable sends at all
    assert timeline.main([p, "--min-join", "0.99"]) == 1
    bad = tmp_path / "bad.rules"
    bad.write_text("just two\n")
    with pytest.raises(ValueError):
        timeline.parse_rules(str(bad))


# ---------------------------------------------------------------------------
# report: multi-file + unknown event tolerance (schema minors)
# ---------------------------------------------------------------------------


def test_report_merges_multiple_traces_and_tolerates_unknown(tmp_path):
    a = _write_jsonl(
        tmp_path / "a.jsonl",
        [
            {"ev": "trace_start", "t": 0.0, "wall_unix": 1.0, "schema": 2},
            {"ev": "epoch", "t": 1.0, "epoch": 0, "min_time": 0.1,
             "max_time": 0.2, "txs": 4, "msgs_per_node": 2,
             "bytes_per_node": 64},
        ],
    )
    b = _write_jsonl(
        tmp_path / "b.jsonl",
        [
            {"ev": "trace_start", "t": 0.0, "wall_unix": 2.0, "schema": 2},
            {"ev": "from_the_future", "t": 0.5, "payload": 1},
            {"ev": "epoch", "t": 1.0, "epoch": 1, "min_time": 0.1,
             "max_time": 0.3, "txs": 2, "msgs_per_node": 2,
             "bytes_per_node": 32},
        ],
    )
    events = report.load_many([a, b])
    s = report.summarize(events)
    assert s["epochs"]["count"] == 2
    assert s["unknown_events"] == {"from_the_future": 1}
    text = report.render(s)
    assert "from_the_future" in text
    # the CLI accepts multiple positional traces
    assert report.main([a, b]) == 0


# ---------------------------------------------------------------------------
# acceptance: the fleet-telemetry scenario (real TCP, n=4, under load)
# ---------------------------------------------------------------------------


def test_fleet_telemetry_scenario_end_to_end(tmp_path, monkeypatch):
    """The acceptance gate: a real-TCP n=4 run under client load must
    produce a scraped fleet metrics snapshot, a merged timeline where
    ≥99% of committed txs have a complete admit→ack hop chain, and a
    flight artifact — all re-verified here over the on-disk files."""
    from hbbft_tpu.harness.scenarios import ScenarioConfig, run_scenario

    out = tmp_path / "fleet"
    monkeypatch.setenv("HBBFT_FLEET_DIR", str(out))
    res = run_scenario("fleet-telemetry", ScenarioConfig(seed=0xFEE7))
    assert res.ok, res.detail

    trace = str(out / "trace.jsonl")
    fleet = str(out / "fleet.jsonl")
    flight = str(out / "flight.jsonl")
    for p in (trace, fleet, flight):
        assert os.path.exists(p), p

    scrapes = report.load_events(fleet)
    assert len(scrapes) == 4 and all(r["up"] for r in scrapes)

    _rows, meta = flight_mod.load(flight)
    assert meta is not None and meta["reason"] == "scenario-end"

    tl = timeline.build([trace, fleet, flight])
    assert tl["ok"], [r for r in tl["health"] if r["status"] == "FAIL"]
    assert tl["chains"]["complete_frac"] >= 0.99
    assert tl["joins"]["frac"] >= 0.99
    assert tl["chains"]["committed"] > 0
    assert tl["epochs"], "no committed epochs in the timeline"
    # every epoch entry carries at least one established hop wall
    assert any(e["hops"] for e in tl["epochs"])
