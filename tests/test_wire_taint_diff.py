"""Fuzzer↔lint differential suite for the ``wire-taint`` rule.

PR 6's wire fuzzer cracked a set of handler paths and each got a
guard.  These tests pin that the *static* pass would have caught every
one of them: each test copies the in-scope tree into a fixture,
reverts exactly one PR-6 hardening guard by text substitution, runs
the whole-project wire-taint pass over the reverted tree, and asserts
the rule reports that precise source→sink flow — file, function, and
sink class.

The unreverted copy is asserted clean once up front, so a failure
here means the revert (and only the revert) re-opened the hole.
"""

import os
import shutil

import pytest

from hbbft_tpu.analysis import lint_paths
from hbbft_tpu.analysis.rules.wire_taint import WireTaintRule

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PKG = os.path.join(REPO, "hbbft_tpu")

# everything in the wire-taint scope
_SCOPE_DIRS = ("protocols", "transport", "harness")
_SCOPE_FILES = ("core/serialize.py", "crypto/merkle.py")


def _copy_scope(tmp_path):
    dst = tmp_path / "hbbft_tpu"
    for d in _SCOPE_DIRS:
        shutil.copytree(
            os.path.join(PKG, d),
            dst / d,
            ignore=shutil.ignore_patterns("__pycache__"),
        )
    for f in _SCOPE_FILES:
        target = dst / f
        target.parent.mkdir(parents=True, exist_ok=True)
        shutil.copy(os.path.join(PKG, f), target)
    return dst


def _revert_and_lint(tmp_path, relpath, old, new):
    """Apply one textual guard-revert and run wire-taint over the tree."""
    root = _copy_scope(tmp_path)
    target = root / relpath
    text = target.read_text()
    assert old in text, (
        f"guard text not found in {relpath} — the differential revert "
        "needs updating alongside the guard"
    )
    target.write_text(text.replace(old, new))
    violations, errors = lint_paths([str(root)], [WireTaintRule()])
    assert not errors
    return violations


def _flows(violations, path):
    return [v for v in violations if v.path == path]


def test_unreverted_scope_copy_is_clean(tmp_path):
    root = _copy_scope(tmp_path)
    violations, errors = lint_paths([str(root)], [WireTaintRule()])
    assert not errors
    assert violations == []


def test_codec_depth_cap_revert_redetects_recursion(tmp_path):
    # PR 6: `_decode` got a depth cap after the fuzzer's nesting bomb
    violations = _revert_and_lint(
        tmp_path,
        "core/serialize.py",
        'if depth > _MAX_DECODE_DEPTH:\n        raise SerializationError("nesting too deep")\n    ',
        "",
    )
    hits = [
        v
        for v in _flows(violations, "core/serialize.py")
        if "recursion" in v.message and "_decode" in v.message
    ]
    assert hits, violations
    # the flow names the byte source and the recursive sink
    flow_notes = " | ".join(note for _, _, note in hits[0].flow)
    assert "recursion" in flow_notes


def test_honey_badger_epoch_guard_revert_redetects(tmp_path):
    # PR 6: non-int epochs faulted before comparison / queue keying
    violations = _revert_and_lint(
        tmp_path,
        "protocols/honey_badger.py",
        "        if not isinstance(epoch, int) or isinstance(epoch, bool):\n"
        "            return Step.from_fault(sender_id, FaultKind.INVALID_MESSAGE)\n",
        "",
    )
    hits = _flows(violations, "protocols/honey_badger.py")
    assert any("handle_message" in v.message for v in hits), violations
    # both hazards the guard closed: the ordering comparison and the
    # incoming_queue keying
    assert any("ordering comparison" in v.message for v in hits)
    assert any("key" in v.message for v in hits)
    flagged = next(v for v in hits if "ordering comparison" in v.message)
    assert any("handle_message" in note for _, _, note in flagged.flow)


def test_agreement_epoch_guard_revert_redetects(tmp_path):
    violations = _revert_and_lint(
        tmp_path,
        "protocols/agreement.py",
        "        if not isinstance(message.epoch, int) or isinstance(message.epoch, bool):\n"
        "            return Step.from_fault(sender_id, FaultKind.INVALID_MESSAGE)\n",
        "",
    )
    hits = _flows(violations, "protocols/agreement.py")
    assert any(
        "ordering comparison" in v.message and "handle_message" in v.message
        for v in hits
    ), violations


def test_honey_badger_proposer_guard_revert_redetects(tmp_path):
    # PR 6: unhashable proposer_id faulted via try/except TypeError
    # around the validator probe.  Reverted, the unresolvable,
    # unguarded probe earns no sanitization credit and the proposer
    # reaches dict keying tainted.
    violations = _revert_and_lint(
        tmp_path,
        "protocols/honey_badger.py",
        "        try:\n"
        "            known = self.netinfo.is_node_validator(proposer_id)\n"
        "        except TypeError:\n"
        "            return Step.from_fault(sender_id, FaultKind.INVALID_MESSAGE)\n"
        "        if not known:",
        "        known = self.netinfo.is_node_validator(proposer_id)\n"
        "        if not known:",
    )
    hits = [
        v
        for v in _flows(violations, "protocols/honey_badger.py")
        if "key" in v.message
    ]
    assert hits, violations


def test_common_subset_proposer_guard_revert_redetects(tmp_path):
    # PR 6: the unhashable-proposer membership test went under
    # try/except TypeError
    violations = _revert_and_lint(
        tmp_path,
        "protocols/common_subset.py",
        "            try:\n"
        "                known = message.proposer_id in self.broadcast_instances\n"
        "            except TypeError:\n"
        "                return Step.from_fault(sender_id, FaultKind.INVALID_MESSAGE)\n",
        "            known = message.proposer_id in self.broadcast_instances\n",
    )
    hits = [
        v
        for v in _flows(violations, "protocols/common_subset.py")
        if "membership" in v.message and "handle_message" in v.message
    ]
    assert hits, violations
    assert any("proposer" in note or "message" in note for _, _, note in hits[0].flow)


def test_merkle_type_guard_revert_redetects(tmp_path):
    # PR 6: MerkleProof.validate got the isinstance block after the
    # fuzzer's type-confusion frames
    violations = _revert_and_lint(
        tmp_path,
        "crypto/merkle.py",
        "        if (\n"
        "            not isinstance(self.index, int)\n"
        "            or isinstance(self.index, bool)\n"
        "            or not isinstance(self.value, bytes)\n"
        "            or not isinstance(self.lemma, (tuple, list))\n"
        "            or not isinstance(self.root_hash, bytes)\n"
        "        ):\n"
        "            return False\n",
        "",
    )
    hits = [
        v
        for v in _flows(violations, "crypto/merkle.py")
        if "validate" in v.message
    ]
    assert hits, violations
    assert any(
        "MerkleProof" in note for v in hits for _, _, note in v.flow
    )


def test_tcp_handler_catch_revert_redetects_dispatch(tmp_path):
    # PR 6: the TcpNode pump stopped crashing on handler exceptions —
    # malformed-but-deserializable messages become attributed faults
    # (the handler call is offloaded through run_in_executor — the
    # taint engine unwraps the hop, so the guard credit still comes
    # from the try/except around it)
    violations = _revert_and_lint(
        tmp_path,
        "transport/tcp.py",
        "                try:\n"
        "                    step = await loop.run_in_executor(\n"
        "                        None, self.algo.handle_message, sender, message\n"
        "                    )\n"
        "                except Exception:",
        "                if True:\n"
        "                    step = await loop.run_in_executor(\n"
        "                        None, self.algo.handle_message, sender, message\n"
        "                    )\n"
        "                if False:",
    )
    hits = [
        v
        for v in _flows(violations, "transport/tcp.py")
        if "dispatched" in v.message and "run" in v.message
    ]
    assert hits, violations
    assert any("inbox" in note for _, _, note in hits[0].flow)


def test_tcp_frame_bound_revert_redetects_alloc(tmp_path):
    # the huge-length DoS dual: dropping the _MAX_FRAME bound leaves an
    # attacker-magnitude length sizing readexactly()
    violations = _revert_and_lint(
        tmp_path,
        "transport/tcp.py",
        "    if length > _MAX_FRAME:\n"
        '        raise ConnectionError(f"oversized frame: {length} bytes")\n',
        "",
    )
    hits = [
        v
        for v in _flows(violations, "transport/tcp.py")
        if "size reaches readexactly()" in v.message
    ]
    assert hits, violations


def test_sync_key_gen_proposer_idx_guard_revert_redetects(tmp_path):
    # the guard this PR itself added after wire-taint flagged the
    # unvalidated Ack.proposer_idx dict key
    violations = _revert_and_lint(
        tmp_path,
        "protocols/sync_key_gen.py",
        "        if not isinstance(ack.proposer_idx, int) or isinstance(\n"
        "            ack.proposer_idx, bool\n"
        "        ):\n",
        "        if False:\n",
    )
    hits = [
        v
        for v in _flows(violations, "protocols/sync_key_gen.py")
        if ".get() key" in v.message
    ]
    assert hits, violations
