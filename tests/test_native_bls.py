"""Bit-identity tests: native C++ BLS12-381 vs the pure-Python oracle.

The native library (``native/bls12_381.cpp``) must be byte-identical to
``hbbft_tpu/crypto/{fields,curve,pairing,hashing}.py`` — including the
pairing *value* (the projective Miller loop's line scalings lie in Fq2*
and are killed by the final exponentiation).  These tests toggle
``HBBFT_TPU_NO_NATIVE`` to compute both sides.
"""

import random

import pytest

from hbbft_tpu import native as NT
from hbbft_tpu.crypto import fields as F
from hbbft_tpu.crypto.curve import (
    G1,
    G1_GEN,
    G2,
    G2_GEN,
    g1_multi_exp,
    g2_multi_exp,
)

pytestmark = pytest.mark.skipif(
    not NT.available(), reason="native library unavailable"
)


@pytest.fixture
def no_native(monkeypatch):
    monkeypatch.setenv("HBBFT_TPU_NO_NATIVE", "1")


def _rand_scalar(rng):
    return rng.randrange(1, F.R)


class TestGroupOps:
    def test_g1_mul_matches_python(self, rng, monkeypatch):
        p = G1_GEN * _rand_scalar(rng)
        for _ in range(4):
            k = _rand_scalar(rng)
            nat = p * k
            monkeypatch.setenv("HBBFT_TPU_NO_NATIVE", "1")
            ref = p * k
            monkeypatch.delenv("HBBFT_TPU_NO_NATIVE")
            assert nat == ref
            assert nat.to_bytes() == ref.to_bytes()

    def test_g2_mul_matches_python(self, rng, monkeypatch):
        p = G2_GEN * _rand_scalar(rng)
        k = _rand_scalar(rng)
        nat = p * k
        monkeypatch.setenv("HBBFT_TPU_NO_NATIVE", "1")
        ref = p * k
        monkeypatch.delenv("HBBFT_TPU_NO_NATIVE")
        assert nat == ref

    def test_mul_edge_cases(self, rng):
        p = G1_GEN * _rand_scalar(rng)
        assert (p * 0).is_infinity()
        assert p * 1 == p
        assert p * (F.R - 1) == -p
        assert (G1.infinity() * 5).is_infinity()
        q = G2_GEN * 3
        assert q * (F.R + 2) == q * 2  # scalar reduced mod r

    def test_g1_msm_matches_naive(self, rng, monkeypatch):
        pts = [G1_GEN * _rand_scalar(rng) for _ in range(17)]
        ks = [rng.randrange(F.R) for _ in range(17)]
        nat = g1_multi_exp(pts, ks)
        monkeypatch.setenv("HBBFT_TPU_NO_NATIVE", "1")
        ref = g1_multi_exp(pts, ks)
        monkeypatch.delenv("HBBFT_TPU_NO_NATIVE")
        assert nat == ref

    def test_g2_msm_matches_naive(self, rng, monkeypatch):
        pts = [G2_GEN * _rand_scalar(rng) for _ in range(9)]
        ks = [rng.randrange(F.R) for _ in range(9)]
        nat = g2_multi_exp(pts, ks)
        monkeypatch.setenv("HBBFT_TPU_NO_NATIVE", "1")
        ref = g2_multi_exp(pts, ks)
        monkeypatch.delenv("HBBFT_TPU_NO_NATIVE")
        assert nat == ref

    def test_msm_with_infinity_and_zero_scalars(self, rng):
        pts = [G1_GEN * 5, G1.infinity(), G1_GEN * 7]
        ks = [3, 9, 0]
        assert g1_multi_exp(pts, ks) == G1_GEN * 15

    def test_msm_empty(self):
        assert g1_multi_exp([], []).is_infinity()

    def test_in_subgroup_via_native(self, rng):
        assert (G1_GEN * _rand_scalar(rng)).in_subgroup()
        # (0, 2) is on the curve but not in the r-torsion subgroup
        assert not G1.from_affine((0, 2)).in_subgroup()


class TestPairing:
    def test_pairing_value_byte_identical(self, rng, monkeypatch):
        from hbbft_tpu.crypto.pairing import pairing

        p = G1_GEN * 5
        q = G2_GEN * 7
        nat = pairing(p, q)
        monkeypatch.setenv("HBBFT_TPU_NO_NATIVE", "1")
        ref = pairing(p, q)
        monkeypatch.delenv("HBBFT_TPU_NO_NATIVE")
        assert nat == ref

    def test_bilinearity(self):
        from hbbft_tpu.crypto.pairing import pairing

        assert pairing(G1_GEN * 6, G2_GEN) == pairing(G1_GEN * 2, G2_GEN * 3)

    def test_pairing_check_share_verify(self, rng):
        from hbbft_tpu.crypto.hashing import hash_to_g1
        from hbbft_tpu.crypto.pairing import pairing_check

        sk = _rand_scalar(rng)
        h = hash_to_g1(b"some message")
        sig = h * sk
        pk = G2_GEN * sk
        assert pairing_check([(sig, G2_GEN), (-h, pk)])
        assert not pairing_check([(h * (sk + 1), G2_GEN), (-h, pk)])

    def test_pairing_check_empty_and_infinity(self):
        from hbbft_tpu.crypto.pairing import pairing_check

        assert pairing_check([])
        assert pairing_check([(G1.infinity(), G2_GEN)])


class TestHashToG1:
    def test_matches_python(self, monkeypatch):
        from hbbft_tpu.crypto import hashing as H

        for msg in [b"", b"a", b"hello world", bytes(range(100))]:
            nat = H.hash_to_g1(msg)
            monkeypatch.setenv("HBBFT_TPU_NO_NATIVE", "1")
            ref = H.hash_to_g1(msg)
            monkeypatch.delenv("HBBFT_TPU_NO_NATIVE")
            assert nat == ref, msg

    def test_dst_separation(self):
        from hbbft_tpu.crypto import hashing as H

        assert H.hash_to_g1(b"m", H.DST_SIG) != H.hash_to_g1(b"m", H.DST_ENC)

    def test_output_in_subgroup(self):
        from hbbft_tpu.crypto import hashing as H

        assert H.hash_to_g1(b"subgroup test").in_subgroup()


class TestThresholdEndToEnd:
    def test_sign_combine_verify_native(self, rng):
        from hbbft_tpu.crypto.threshold import SecretKeySet, batch_verify_shares
        from hbbft_tpu.crypto.hashing import hash_to_g1

        sks = SecretKeySet.random(2, rng)
        pks = sks.public_keys()
        h = hash_to_g1(b"coin nonce")
        shares = {i: sks.secret_key_share(i).sign_g1(h) for i in range(7)}
        for i in range(7):
            assert pks.public_key_share(i).verify_signature_share_g1(
                shares[i], h
            )
        sig = pks.combine_signatures(shares)
        assert pks.verify_signature(sig, b"coin nonce")
        assert batch_verify_shares(
            [shares[i].point for i in range(7)],
            [pks.public_key_share(i).point for i in range(7)],
            h,
            b"ctx",
        )

    def test_combine_matches_pure_python(self, rng, monkeypatch):
        from hbbft_tpu.crypto.threshold import SecretKeySet
        from hbbft_tpu.crypto.hashing import hash_to_g1

        sks = SecretKeySet.random(1, rng)
        pks = sks.public_keys()
        h = hash_to_g1(b"m")
        shares = {i: sks.secret_key_share(i).sign_g1(h) for i in range(4)}
        nat = pks.combine_signatures(shares)
        monkeypatch.setenv("HBBFT_TPU_NO_NATIVE", "1")
        ref = pks.combine_signatures(shares)
        monkeypatch.delenv("HBBFT_TPU_NO_NATIVE")
        assert nat.to_bytes() == ref.to_bytes()


def test_g1_mul_many_comb_paths():
    """Shared-base batch scalar-mul: the fixed-base comb (n ≥ 8) and
    the direct loop (n < 8) agree with per-call muls, including the
    zero scalar and the infinity base."""
    import random

    from hbbft_tpu import native as NT
    from hbbft_tpu.crypto.curve import G1, G1_GEN

    if not NT.available():
        import pytest

        pytest.skip("native library unavailable")
    rng = random.Random(0xC0B)
    base = G1_GEN * 31337
    bw = NT.g1_wire(base)
    for n in (1, 7, 8, 33):  # straddle the comb threshold
        ks = [rng.randrange(0, 1 << 255) for _ in range(n - 1)] + [0]
        outs = NT.g1_mul_many(bw, ks)
        for k, w in zip(ks, outs):
            assert w == NT.g1_wire(base * k), (n, k)
    inf = NT.g1_wire(G1.infinity())
    for w in NT.g1_mul_many(inf, [5, 0, 123456789, 1 << 254]):
        assert w == inf


def test_g1_mul_outer_matches_per_base():
    """The one-call staging matrix: out[b][s] = ks[s]·base_b equals
    the per-base g1_mul_many results byte-for-byte."""
    import random

    import numpy as np

    from hbbft_tpu import native as NT
    from hbbft_tpu.crypto.curve import G1_GEN

    if not NT.available():
        import pytest

        pytest.skip("native library unavailable")
    rng = random.Random(0xC0C)
    bases = [G1_GEN * rng.randrange(1, 1 << 60) for _ in range(3)]
    ks = [rng.randrange(0, 1 << 255) for _ in range(20)]
    kbuf = np.frombuffer(
        b"".join(int(k).to_bytes(32, "big") for k in ks), dtype=np.uint8
    )
    raw = NT.g1_mul_outer_raw(
        b"".join(NT.g1_wire(b) for b in bases), kbuf
    ).tobytes()
    for b, base in enumerate(bases):
        expect = NT.g1_mul_many(NT.g1_wire(base), ks)
        for s in range(len(ks)):
            off = (b * len(ks) + s) * 96
            assert raw[off : off + 96] == expect[s], (b, s)


def test_g1_msm_many_matches_per_msm():
    """Many MSMs over one shared scalar vector: each row equals the
    single-MSM result byte-for-byte."""
    import random

    import numpy as np

    from hbbft_tpu import native as NT
    from hbbft_tpu.crypto.curve import G1_GEN

    if not NT.available():
        import pytest

        pytest.skip("native library unavailable")
    rng = random.Random(0xC0D)
    n_msms, n_pts = 5, 7
    rows = [
        [G1_GEN * rng.randrange(1, 1 << 60) for _ in range(n_pts)]
        for _ in range(n_msms)
    ]
    ks = [rng.randrange(1, 1 << 255) for _ in range(n_pts)]
    kbuf = np.frombuffer(
        b"".join(int(k).to_bytes(32, "big") for k in ks), dtype=np.uint8
    )
    pts = np.frombuffer(
        b"".join(NT.g1_wire(p) for row in rows for p in row),
        dtype=np.uint8,
    )
    raw = NT.g1_msm_many_raw(n_msms, n_pts, pts, kbuf).tobytes()
    for m, row in enumerate(rows):
        expect = NT.g1_msm([NT.g1_wire(p) for p in row], ks)
        assert raw[m * 96 : (m + 1) * 96] == expect, m


def test_g2_poly_eval_range_matches_per_index():
    """Forward-difference range evaluation at the kernel boundary:
    every shape class — n > ncoeffs (difference path), n <= ncoeffs
    (pure seeding), degree 0 — must be bit-identical to per-index
    commitment evaluation, and the partial-cache / no-native fallbacks
    must produce the same shares."""
    import random

    import pytest

    from hbbft_tpu import native as NT
    from hbbft_tpu.crypto import threshold as T

    if not NT.available():
        pytest.skip("native library unavailable")
    rng = random.Random(0xD1F)
    for t, n in ((3, 12), (7, 8), (7, 3), (0, 6)):
        sks = T.SecretKeySet.random(t, rng)
        ref = sks.public_keys()
        # raw kernel vs Commitment.evaluate
        wires = NT.g2_poly_eval_range(
            [NT.g2_wire(c) for c in ref.commitment.coeffs], n, T.R
        )
        for i in range(n):
            assert wires[i] == NT.g2_wire(ref.commitment.evaluate(i + 1)), (
                t,
                n,
                i,
            )
        # precompute with a partially warm cache keeps the fast path
        warm = sks.public_keys()
        expected0 = warm.public_key_share(0)  # pre-cache one entry
        warm.precompute_shares(n)
        for i in range(n):
            assert warm.public_key_share(i) == ref.public_key_share(i)
        assert warm.public_key_share(0) == expected0


def test_precompute_shares_pure_python_fallback(monkeypatch):
    import random

    from hbbft_tpu.crypto import threshold as T

    monkeypatch.setenv("HBBFT_TPU_NO_NATIVE", "1")
    rng = random.Random(0xD20)
    sks = T.SecretKeySet.random(2, rng)
    a = sks.public_keys()
    a.precompute_shares(7)
    b = sks.public_keys()
    for i in range(7):
        assert a.public_key_share(i) == b.public_key_share(i)
