"""Vectorized co-simulation tests (``harness/vectorized.py``).

Key contract: the vectorized round produces exactly the coin value and
fault attribution a sequential adversarial network run would — the
combined threshold signature is unique regardless of which > f valid
shares each node happens to combine.
"""

import random

import pytest

from hbbft_tpu.harness.network import (
    MessageScheduler,
    SilentAdversary,
    TestNetwork,
)
from hbbft_tpu.harness.vectorized import VectorizedCoinSim
from hbbft_tpu.protocols.common_coin import CommonCoin


def _sequential_coin(seed, n, f_silent, nonce, mock):
    """Reference result: a TestNetwork run with silent Byzantine nodes
    under a random scheduler."""
    rng = random.Random(seed)
    net = TestNetwork(
        n - f_silent,
        f_silent,
        lambda adv: SilentAdversary(
            MessageScheduler(MessageScheduler.RANDOM, rng)
        ),
        lambda ni: CommonCoin(ni, nonce),
        rng,
        mock_crypto=mock,
    )
    net.input_all(None)
    net.step_until(lambda: all(n_.terminated() for n_ in net.nodes.values()))
    vals = {n_.outputs[0] for n_ in net.nodes.values()}
    assert len(vals) == 1
    return vals.pop()


@pytest.mark.parametrize("mock", [True, False])
def test_matches_sequential_network(mock):
    """Same keys (same rng seed) → the vectorized flip equals the
    sequential adversarial network's coin, for several nonces."""
    n, f = 7, 2
    for i, nonce in enumerate([b"vec-a", b"vec-b", b"vec-c"]):
        seq = _sequential_coin(1000 + i, n, f, nonce, mock)
        vec = VectorizedCoinSim(
            n, random.Random(1000 + i), mock=mock
        ).flip(nonce, dead={n - 2, n - 1})
        assert vec.value == seq
        assert all(v == seq for v in vec.outputs.values())
        assert vec.fault_log.is_empty()


def test_forged_share_attribution():
    """A well-formed but wrong share is rejected and attributed, and
    the coin still completes from the honest shares."""
    rng = random.Random(77)
    sim = VectorizedCoinSim(7, rng, mock=False)
    forged_share = sim.netinfos[3].secret_key_share.sign(b"WRONG-NONCE")
    r = sim.flip(b"the-nonce", forged={3: forged_share})
    assert 3 not in r.valid_senders
    assert [(f.node_id, f.kind.name) for f in r.fault_log] == [
        (3, "INVALID_SIGNATURE_SHARE")
    ]
    # and matches a clean flip's value (same keys, same honest shares
    # are a superset of any t+1)
    clean = sim.flip(b"the-nonce")
    assert r.value == clean.value


def test_garbage_share_rejected():
    rng = random.Random(78)
    sim = VectorizedCoinSim(4, rng, mock=False)
    r = sim.flip(b"n", forged={2: b"not-a-share"})
    assert 2 not in r.valid_senders
    assert len(r.fault_log) == 1


def test_mock_scale_distribution():
    """Mock-crypto co-simulation at n=256: flips are produced and not
    constant (distribution sanity, reference ``tests/common_coin.rs``
    statistical check in spirit)."""
    rng = random.Random(79)
    sim = VectorizedCoinSim(256, rng, mock=True)
    vals = [sim.flip(b"flip-%d" % i).value for i in range(20)]
    assert 0 < sum(vals) < 20


def test_too_few_live_nodes():
    rng = random.Random(80)
    sim = VectorizedCoinSim(4, rng, mock=True)
    with pytest.raises(ValueError):
        sim.flip(b"x", dead={1, 2, 3})


def test_broadcast_round_roundtrip():
    """N=16, 1 KB payload round-trips; equals the sequential TestNetwork
    broadcast output for the same payload."""
    from hbbft_tpu.harness.vectorized import VectorizedBroadcastRound
    from hbbft_tpu.protocols.broadcast import Broadcast

    payload = bytes(range(256)) * 4
    rng = random.Random(83)
    vec = VectorizedBroadcastRound(16, rng).broadcast(payload)
    assert vec.value == payload
    assert vec.fault_log.is_empty()
    assert len(vec.valid_shard_holders) == 16

    net_rng = random.Random(83)
    net = TestNetwork(
        11, 5,
        lambda adv: SilentAdversary(
            MessageScheduler(MessageScheduler.RANDOM, net_rng)
        ),
        lambda ni: Broadcast(ni, 0), net_rng,
    )
    net.input(0, payload)
    net.step_until(lambda: all(n.terminated() for n in net.nodes.values()))
    assert all(n.outputs == [vec.value] for n in net.nodes.values())


def test_broadcast_round_byzantine():
    """Dead nodes + tampered echo shards: tamperers attributed, payload
    still reconstructs from the honest ≥ N−2f shards."""
    from hbbft_tpu.harness.vectorized import VectorizedBroadcastRound

    rng = random.Random(84)
    sim = VectorizedBroadcastRound(16, rng)  # f=5, data=6, parity=10
    payload = b"tamper-resistant-payload" * 20
    r = sim.broadcast(
        payload, dead={14, 15}, corrupt={7: b"\x00" * 8, 9: b"junk"}
    )
    assert r.value == payload
    assert sorted(f.node_id for f in r.fault_log) == [7, 9]
    assert 7 not in r.valid_shard_holders


def test_broadcast_round_too_few_shards():
    from hbbft_tpu.harness.vectorized import VectorizedBroadcastRound

    rng = random.Random(85)
    sim = VectorizedBroadcastRound(4, rng)  # f=1, data=2
    with pytest.raises(ValueError):
        sim.broadcast(b"x", dead={1, 2, 3})


def test_hb_decryption_round_roundtrip():
    """Full decryption phase: N=7 validators, 3 proposers; every
    contribution round-trips through encrypt → shares → grouped
    verification → combine."""
    from hbbft_tpu.harness.vectorized import VectorizedHoneyBadgerRound

    rng = random.Random(81)
    sim = VectorizedHoneyBadgerRound(7, rng)
    contribs = {p: b"contrib-%d" % p for p in (0, 2, 5)}
    cts = sim.encrypt_contributions(contribs)
    r = sim.decrypt_round(cts)
    assert r.contributions == contribs
    assert r.fault_log.is_empty()
    assert r.shares_verified == 7 * 3


def test_hb_decryption_round_byzantine():
    """Dead nodes and forged shares: contributions still decrypt
    (> f honest shares remain) and forgers are attributed."""
    from hbbft_tpu.harness.vectorized import VectorizedHoneyBadgerRound

    rng = random.Random(82)
    sim = VectorizedHoneyBadgerRound(7, rng)
    contribs = {p: b"data-%d" % p for p in (1, 4)}
    cts = sim.encrypt_contributions(contribs)
    # node 6 silent; node 3 sends a share for the wrong ciphertext
    wrong = sim.netinfos[3].secret_key_share.decrypt_share_no_verify(
        cts[4]
    )
    r = sim.decrypt_round(cts, dead={6}, forged={3: {1: wrong}})
    assert r.contributions == contribs
    assert [(f.node_id, f.kind.name) for f in r.fault_log] == [
        (3, "INVALID_DECRYPTION_SHARE")
    ]
