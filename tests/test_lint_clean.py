"""Tier-1 gate: the checked-in tree passes badgerlint.

This is the test the CI actually leans on — every invariant the rule
suite encodes (protocol determinism, ordered emission, jit sync
discipline, limb dtype bounds, layer map, event schema) holds over
``hbbft_tpu/`` itself, modulo the reviewed baseline.  A PR that
introduces a violation fails here with the exact ``path:line:
[rule] message`` rendering in the assertion.
"""

import os

from hbbft_tpu.analysis import Baseline, all_rules, lint_paths
from hbbft_tpu.analysis.cli import DEFAULT_BASELINE

PACKAGE_DIR = os.path.dirname(DEFAULT_BASELINE).rsplit(os.sep, 1)[0]


def test_package_tree_lints_clean():
    violations, errors = lint_paths([PACKAGE_DIR], all_rules())
    assert errors == [], "\n".join(errors)
    baseline = Baseline.load(DEFAULT_BASELINE)
    new, _baselined = baseline.split(violations)
    assert new == [], "\n".join(v.render() for v in new)


def test_baseline_entries_still_fire():
    """Every baseline entry must still match a live violation —
    otherwise the fix landed and the entry is stale cover for the next
    regression."""
    violations, _ = lint_paths([PACKAGE_DIR], all_rules())
    live = {v.key() for v in violations}
    stale = [
        e
        for e in Baseline.load(DEFAULT_BASELINE).entries
        if (e["rule"], e["path"], e["message"]) not in live
    ]
    assert stale == [], f"stale baseline entries: {stale}"
