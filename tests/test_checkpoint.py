"""Checkpoint/resume tests (``harness/checkpoint.py``).

Contract: a restored snapshot is a bit-identical continuation — same
future batches, same fault attribution — and restores across backend
boundaries (snapshot taken with one ops backend, resumed with another).
"""

import random

from hbbft_tpu.harness import checkpoint as CK
from hbbft_tpu.harness.batching import BatchingBackend
from hbbft_tpu.harness.network import (
    MessageScheduler,
    SilentAdversary,
    TestNetwork,
)
from hbbft_tpu.harness.simulation import simulate_queueing_honey_badger
from hbbft_tpu.protocols.broadcast import Broadcast
from hbbft_tpu.protocols.honey_badger import HoneyBadger


def _mk_hb_net(seed, ops=None):
    rng = random.Random(seed)
    net = TestNetwork(
        5,
        1,
        lambda adv: SilentAdversary(
            MessageScheduler(MessageScheduler.RANDOM, rng)
        ),
        lambda ni: HoneyBadger(ni, rng=random.Random(f"{ni.our_id}-ck")),
        rng,
        ops=ops,
    )
    return net


def _outputs(net):
    return {
        nid: [
            (b.epoch, tuple(sorted((k, tuple(v)) for k, v in b.contributions.items())))
            for b in node.outputs
        ]
        for nid, node in net.nodes.items()
    }


def test_fork_mid_run_identical_continuation():
    """Run HoneyBadger halfway, snapshot the whole network, continue
    the original and the restored copy — identical batch sequences."""
    net = _mk_hb_net(90)
    for nid in sorted(net.nodes):
        net.input(nid, [b"ck-%d" % nid])
    for _ in range(40):
        if net.any_busy():
            net.step()
    forked = CK.clone(net)

    def finish(n):
        guard = 0
        while n.any_busy() and guard < 20_000:
            n.step()
            guard += 1
        return _outputs(n)

    out_a = finish(net)
    out_b = finish(forked)
    assert out_a == out_b
    assert any(len(s) > 0 for s in out_a.values())


def test_restore_rebinds_backend():
    """A snapshot never carries an ops backend; restore injects the
    caller's choice."""
    be = BatchingBackend()
    net = _mk_hb_net(91, ops=be)
    for nid in sorted(net.nodes):
        net.input(nid, [b"x-%d" % nid])
    for _ in range(10):
        if net.any_busy():
            net.step()
    data = CK.save(net)
    be2 = BatchingBackend()
    restored = CK.load(data, ops=be2)
    ni = restored.nodes[0].algo.netinfo
    assert ni.ops is be2
    # sub-instances share the rebound NetworkInfo
    for cs in restored.nodes[0].algo.common_subsets.values():
        assert cs.netinfo is ni
    # default restore falls back to the CPU backend
    restored_cpu = CK.load(data)
    assert restored_cpu.nodes[0].algo.netinfo.ops.name == "cpu"


def test_single_node_roundtrip_broadcast(rng):
    """Node-level snapshot: a Broadcast instance mid-protocol restores
    and finishes with the same output."""
    net_rng = random.Random(92)
    net = TestNetwork(
        6,
        2,
        lambda adv: SilentAdversary(
            MessageScheduler(MessageScheduler.RANDOM, net_rng)
        ),
        lambda ni: Broadcast(ni, 0),
        net_rng,
    )
    payload = bytes(rng.randrange(256) for _ in range(512))
    net.input(0, payload)
    for _ in range(15):
        if net.any_busy():
            net.step()
    # snapshot node 3's algorithm alone and swap it into the live network
    node = net.nodes[3]
    node.algo = CK.load(CK.save(node.algo))
    net.step_until(lambda: all(n.terminated() for n in net.nodes.values()))
    assert node.outputs == [payload]


def test_device_codec_stripped_from_snapshot():
    """A Broadcast running on the TPU ops backend snapshots without the
    device codec and restores onto the CPU backend (cross-host
    portability of checkpoints)."""
    from hbbft_tpu.ops.backend_tpu import TpuBackend
    from hbbft_tpu.crypto.rs import ReedSolomon

    net_rng = random.Random(94)
    be = TpuBackend()
    be._native_host = lambda: False  # force the device codec path
    net = TestNetwork(
        6,
        2,
        lambda adv: SilentAdversary(
            MessageScheduler(MessageScheduler.RANDOM, net_rng)
        ),
        lambda ni: Broadcast(ni, 0),
        net_rng,
        ops=be,
    )
    payload = bytes(random.Random(95).randrange(256) for _ in range(2048))
    net.input(0, payload)
    for _ in range(10):
        if net.any_busy():
            net.step()
    restored = CK.load(CK.save(net))  # default restore: CPU backend
    for node in restored.nodes.values():
        assert isinstance(node.algo.coding, ReedSolomon)
    guard = 0
    while restored.any_busy() and guard < 20_000:
        restored.step()
        guard += 1
    assert all(n.outputs == [payload] for n in restored.nodes.values())


def test_simulation_network_roundtrip():
    """A virtual-time SimNetwork snapshots and resumes to completion
    (timing statistics are measured, so only protocol results are
    asserted — all transactions commit on every live node)."""
    import hbbft_tpu.harness.simulation as S

    rng = random.Random(93)
    txs = [b"sim-tx-%02d" % i for i in range(20)]

    from hbbft_tpu.protocols.dynamic_honey_badger import DynamicHoneyBadger
    from hbbft_tpu.protocols.queueing_honey_badger import QueueingHoneyBadger

    def new_algo(netinfo):
        node_rng = random.Random(f"ckpt-{netinfo.our_id}")
        dhb = DynamicHoneyBadger(netinfo, rng=node_rng)
        return (
            QueueingHoneyBadger.builder(dhb)
            .batch_size(10)
            .rng(node_rng)
            .build_with_transactions(list(txs))
        )

    net = S.SimNetwork(4, 0, new_algo, S.HwQuality(), rng, mock_crypto=True)
    for _ in range(60):
        if net.step() is None:
            break
    net = CK.load(CK.save(net))  # mid-run snapshot + restore
    guard = 0
    while net.step() is not None and guard < 200_000:
        guard += 1
    want = set(txs)
    for node in net.live_nodes():
        got = {tx for _, b in node.outputs for tx in b.tx_iter()}
        assert got >= want


def test_vectorized_epoch_sim_checkpoint_resume():
    """The vectorized full-epoch co-simulation snapshots mid-run and
    the restored continuation produces identical batches (the long-run
    save/resume property, SURVEY §5.4, extended to the round-2 epoch
    driver)."""
    import random

    from hbbft_tpu.harness import checkpoint as CP
    from hbbft_tpu.harness.epoch import VectorizedQueueingSim

    rng = random.Random(0x5A7E)
    qsim = VectorizedQueueingSim(7, rng, batch_size=8, mock=True)
    txs = [b"ck-%d" % i for i in range(16)]
    qsim.input_all(txs)
    first = qsim.run_epoch()
    assert first.batch.epoch == 0

    fork = CP.clone(qsim)
    # the driver's rng is shared state; to compare continuations, give
    # both the same fresh seed (snapshots of random.Random pickle fine,
    # but qsim.rng is the *caller's* rng object here)
    qsim.rng = random.Random(1)
    qsim.sim.rng = qsim.rng
    fork.rng = random.Random(1)
    fork.sim.rng = fork.rng
    a = qsim.run_epoch()
    b = fork.run_epoch()
    assert a.batch.epoch == b.batch.epoch == 1
    assert a.batch.contributions == b.batch.contributions
    assert a.accepted == b.accepted
