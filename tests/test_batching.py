"""Batched crypto façade tests (``harness/batching.py``).

The contract under test: prefetching share verifications in one fused
batch yields *bit-identical* protocol outcomes to the sequential
per-item path — same batches, same fault attribution — while the
pairing count collapses from 2-per-share to 2-per-batch (the TPU
co-simulation north star, SURVEY §5.8)."""

import random

from hbbft_tpu.crypto import threshold as T
from hbbft_tpu.crypto.hashing import DST_SIG, hash_to_g1
from hbbft_tpu.harness.batching import (
    BatchingBackend,
    DecObligation,
    SigObligation,
)


def deal(n=4, t=1, seed=7):
    rng = random.Random(seed)
    sks = T.SecretKeySet.random(t, rng)
    pks = sks.public_keys()
    return rng, sks, pks


def test_prefetch_sig_shares_real_all_good():
    rng, sks, pks = deal()
    msgs = [b"nonce-A", b"nonce-B"]
    obs = []
    for m in msgs:
        for i in range(4):
            share = sks.secret_key_share(i).sign(m)
            obs.append(SigObligation(pks.public_key_share(i), share, m))
    be = BatchingBackend()
    be.prefetch(obs)
    assert be.stats.prefetched == 8
    assert be.stats.fallback_items == 0  # one fused check settled all
    for ob in obs:
        assert be.verify_sig_share(ob.pk_share, ob.share, ob.msg) is True
    assert be.stats.cache_hits == 8  # no re-verification happened


def test_prefetch_sig_shares_real_with_forgery():
    rng, sks, pks = deal()
    m = b"nonce-C"
    obs = []
    for i in range(4):
        share = sks.secret_key_share(i).sign(m)
        obs.append(SigObligation(pks.public_key_share(i), share, m))
    # forge node 2's share (wrong message)
    forged = sks.secret_key_share(2).sign(b"other")
    obs[2] = SigObligation(pks.public_key_share(2), forged, m)
    be = BatchingBackend()
    be.prefetch(obs)
    results = [
        be.verify_sig_share(ob.pk_share, ob.share, ob.msg) for ob in obs
    ]
    assert results == [True, True, False, True]
    assert be.stats.fallback_groups >= 1  # the fused check had to bisect


def test_prefetch_dec_shares_real_mixed_groups():
    rng, sks, pks = deal()
    ct1 = pks.public_key().encrypt(b"payload-1", rng)
    ct2 = pks.public_key().encrypt(b"payload-2", rng)
    obs = []
    expected = []
    for ct in (ct1, ct2):
        for i in range(4):
            share = sks.secret_key_share(i).decrypt_share_no_verify(ct)
            obs.append(DecObligation(pks.public_key_share(i), share, ct))
            expected.append(True)
    # one wrong share: decryption share for the *other* ciphertext
    wrong = sks.secret_key_share(0).decrypt_share_no_verify(ct2)
    obs.append(DecObligation(pks.public_key_share(0), wrong, ct1))
    expected.append(False)
    # plus a signature obligation in the same flush (3 groups total)
    sig = sks.secret_key_share(1).sign(b"coin")
    obs.append(SigObligation(pks.public_key_share(1), sig, b"coin"))
    expected.append(True)
    be = BatchingBackend()
    be.prefetch(obs)
    for ob, want in zip(obs, expected):
        if isinstance(ob, SigObligation):
            got = be.verify_sig_share(ob.pk_share, ob.share, ob.msg)
        else:
            got = be.verify_dec_share(ob.pk_share, ob.share, ob.ciphertext)
        assert got is want


def test_mock_prefetch_matches_inline():
    from hbbft_tpu.crypto.mock import MockSecretKeySet

    rng = random.Random(11)
    sks = MockSecretKeySet.random(1, rng)
    pks = sks.public_keys()
    m = b"mock-nonce"
    good = sks.secret_key_share(0).sign(m)
    bad = sks.secret_key_share(1).sign(b"other")
    obs = [
        SigObligation(pks.public_key_share(0), good, m),
        SigObligation(pks.public_key_share(1), bad, m),
    ]
    be = BatchingBackend()
    be.prefetch(obs)
    assert be.verify_sig_share(pks.public_key_share(0), good, m) is True
    assert be.verify_sig_share(pks.public_key_share(1), bad, m) is False
    assert be.stats.cache_hits == 2


def _batch_seq(node):
    return [
        (b.epoch, tuple(sorted((k, tuple(v)) for k, v in b.contributions.items())))
        for b in node.outputs
    ]


def test_honey_badger_batched_bit_identity_mock():
    """Same seed, with and without the batching façade → identical
    batch sequences and identical fault attribution at every node."""
    from test_honey_badger import run_honey_badger

    be = BatchingBackend()
    net_plain = run_honey_badger(random.Random(77), 7, txs_per_node=3)
    net_batched = run_honey_badger(
        random.Random(77), 7, txs_per_node=3, ops=be
    )
    assert be.stats.prefetched > 0, "prefetch never extracted obligations"
    assert be.stats.cache_hits > 0, "inline path never hit the cache"
    for nid in net_plain.nodes:
        assert _batch_seq(net_plain.nodes[nid]) == _batch_seq(
            net_batched.nodes[nid]
        )
        assert [
            (f.node_id, f.kind) for f in net_plain.nodes[nid].faults
        ] == [(f.node_id, f.kind) for f in net_batched.nodes[nid].faults]


def test_honey_badger_batched_real_bls():
    """Full HoneyBadger run on real BLS12-381 with batched prefetch —
    the end-to-end proof that fused verification preserves consensus."""
    from test_honey_badger import run_honey_badger

    be = BatchingBackend()
    run_honey_badger(
        random.Random(43), 4, txs_per_node=2, batch_contrib=2,
        mock=False, ops=be,
    )
    assert be.stats.prefetched > 0
    assert be.stats.cache_hits > 0


def test_duplicate_cell_cancellation_attack_rejected():
    """Two bogus shares for ONE (sender, message) cell whose deviations
    cancel (σ+D and σ−D): under product-form coefficients both items in
    the cell share one coefficient, so their sum telescopes to a valid
    aggregate — the fused check MUST detect the duplicate cell and use
    independent per-item coefficients (``_fused_check`` guard), marking
    both forgeries invalid."""
    rng, sks, pks = deal()
    m = b"attack-nonce"
    base = hash_to_g1(m, DST_SIG)
    good = sks.secret_key_share(0).sign(m)
    delta = base * 12345
    forged_plus = T.SignatureShare(good.point + delta)
    forged_minus = T.SignatureShare(good.point + (-delta))
    pk0 = pks.public_key_share(0)
    obs = [
        SigObligation(pk0, forged_plus, m),
        SigObligation(pk0, forged_minus, m),
        # honest context from the other validators
        *(
            SigObligation(
                pks.public_key_share(i), sks.secret_key_share(i).sign(m), m
            )
            for i in range(1, 4)
        ),
    ]
    be = BatchingBackend()
    be.prefetch(obs)
    assert be.verify_sig_share(pk0, forged_plus, m) is False
    assert be.verify_sig_share(pk0, forged_minus, m) is False
    for i in range(1, 4):
        share = sks.secret_key_share(i).sign(m)
        assert be.verify_sig_share(pks.public_key_share(i), share, m) is True


def test_product_form_multi_group_epoch_shape():
    """The epoch shape the product form collapses: N senders × P
    ciphertexts with one shared sender set — all honest plus one forged
    share; the forgery must be attributed and every honest share must
    verify (fallback cascade preserves per-item outcomes)."""
    rng, sks, pks = deal(seed=21)
    master = pks.public_key()
    cts = [master.encrypt(b"payload-%d" % g, rng) for g in range(5)]
    obs = []
    for ct in cts:
        for i in range(4):
            share = sks.secret_key_share(i).decrypt_share_no_verify(ct)
            obs.append(DecObligation(pks.public_key_share(i), share, ct))
    # corrupt one share in group 3
    bad = T.DecryptionShare(obs[0].share.point * 7)
    obs[3 * 4 + 2] = DecObligation(
        pks.public_key_share(2), bad, cts[3]
    )
    be = BatchingBackend()
    be.prefetch(obs)
    for ob in obs:
        expect = ob.share is not bad
        assert (
            be.verify_dec_share(ob.pk_share, ob.share, ob.ciphertext)
            is expect
        )
