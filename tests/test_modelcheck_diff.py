"""Differential validation of badgermc: revert a safety guard in a
fixture copy of the package and the model checker must (a) find a
violation, (b) shrink it to a ≤12-action counterexample, and (c) write
a repro file that replays deterministically inside the fixture — while
the unreverted tree stays clean at the same pinned configs and fails
to reproduce the fixture's counterexample.

The fixture subprocesses run with ``cwd`` INSIDE the fixture root:
``python -m`` prepends the cwd to ``sys.path``, which shadows any
installed/parent copy of the package — ``PYTHONPATH`` alone does not
(the launch directory wins), which silently re-runs the clean tree."""

import json
import os
import shutil
import subprocess
import sys
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parent.parent

# Each entry reverts one guard.  ``old`` must match exactly once — a
# drifted anchor fails loudly instead of silently testing nothing.
MUTATIONS = {
    "ag-nonbool-term-guard": dict(
        path="hbbft_tpu/protocols/agreement.py",
        old="if not isinstance(content.value, bool):",
        new="if False and not isinstance(content.value, bool):",
        mc=["--mc-config", "agreement", "--mc-depth", "3",
            "--mc-corrupt", "1", "--mc-probes", "2"],
        kind="crash",  # forged non-bool Term indexes BoolMultimap
    ),
    "hb-missing-ciphertext-guard": dict(
        path="hbbft_tpu/protocols/honey_badger.py",
        old=(
            "cts = self.ciphertexts.get(self.epoch)\n"
            "        if cts is None:\n"
            "            return None\n"
        ),
        new="cts = self.ciphertexts.get(self.epoch) or {}\n",
        mc=["--mc-config", "honey_badger", "--mc-depth", "2",
            "--mc-corrupt", "1", "--mc-probes", "2"],
        kind="crash",  # forged share with no ciphertext to audit
    ),
    "ba-coin-match-guard": dict(
        path="hbbft_tpu/protocols/agreement.py",
        old="if def_bin is not None and def_bin == coin:",
        new="if def_bin is not None:",
        mc=["--mc-config", "agreement", "--mc-depth", "2",
            "--mc-probes", "6"],
        kind="agreement",  # honest nodes decide opposite values — needs
        # the partition-biased liveness probes, not the DFS frontier
    ),
}


def _env():
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    return env


def _mc(cwd, args, repro=None):
    cmd = [sys.executable, "-m", "hbbft_tpu.analysis", "--mc",
           "--format", "json", *args]
    if repro is not None:
        cmd += ["--mc-repro", str(repro)]
    return subprocess.run(
        cmd, cwd=str(cwd), env=_env(),
        capture_output=True, text=True, timeout=600,
    )


def _replay(cwd, path):
    return subprocess.run(
        [sys.executable, "-m", "hbbft_tpu.harness.scenarios",
         "--replay-trace", str(path)],
        cwd=str(cwd), env=_env(),
        capture_output=True, text=True, timeout=300,
    )


def _fixture(tmp_path, name):
    root = tmp_path / name
    shutil.copytree(
        REPO / "hbbft_tpu", root / "hbbft_tpu",
        ignore=shutil.ignore_patterns("__pycache__"),
    )
    m = MUTATIONS[name]
    target = root / m["path"]
    src = target.read_text()
    assert src.count(m["old"]) == 1, f"mutation anchor drifted in {m['path']}"
    target.write_text(src.replace(m["old"], m["new"]))
    return root


@pytest.mark.parametrize("name", sorted(MUTATIONS))
def test_revert_is_caught_shrunk_and_replayable(tmp_path, name):
    m = MUTATIONS[name]
    root = _fixture(tmp_path, name)
    repro = root / "repro.json"

    p = _mc(root, m["mc"], repro=repro)
    assert p.returncode == 1, f"revert not caught:\n{p.stdout}\n{p.stderr}"
    doc = json.loads(p.stdout)
    assert not doc["ok"]
    v = doc["mc"]["violation"]
    assert v is not None and v["kind"] == m["kind"], v
    assert len(v["trace"]) <= 12, "counterexample not shrunk"
    assert repro.exists()

    # the counterexample replays deterministically inside the fixture
    r = _replay(root, repro)
    assert r.returncode == 0, f"repro did not replay:\n{r.stdout}\n{r.stderr}"
    assert "REPRODUCED" in r.stdout

    # ... and does NOT reproduce on the unreverted tree
    r = _replay(REPO, repro)
    assert r.returncode == 1, r.stdout
    assert "NOT REPRODUCED" in r.stdout


@pytest.mark.parametrize("name", sorted(MUTATIONS))
def test_unreverted_tree_is_clean_at_the_pinned_configs(name):
    p = _mc(REPO, MUTATIONS[name]["mc"])
    assert p.returncode == 0, f"clean tree flagged:\n{p.stdout}\n{p.stderr}"
    doc = json.loads(p.stdout)
    assert doc["ok"] and doc["mc"]["violation"] is None
