"""The multi-chip mesh flush (ISSUE 7 tentpole): the product-MSM
verify plane sharded over the device mesh must be byte-identical to
the single-device path — EC addition is exact under complete formulas,
so resharding and ring-reducing the partial sums may not change a
single output byte, with the staging pipeline on or off.

Runs on the conftest-forced virtual 8-device CPU mesh
(``HBBFT_TPU_MESH_CPU=1`` opts the CPU backend into the mesh engine;
the XLA bit-scan engine keeps compiles tractable — the Pallas windowed
engine under ``shard_map`` is real-TPU only).
"""

import random

import numpy as np
import pytest

from hbbft_tpu.crypto import fields as F
from hbbft_tpu.crypto.backend import CpuBackend
from hbbft_tpu.crypto.curve import G1, G1_GEN, G2_GEN, g2_multi_exp
from hbbft_tpu.ops import ec_jax as EC, packed_msm as pm
from hbbft_tpu.parallel import mesh as M


@pytest.fixture(autouse=True)
def _mesh_env(monkeypatch):
    # CPU virtual meshes + full device share: the deterministic shapes
    # the byte-identity claim is made over
    monkeypatch.setenv("HBBFT_TPU_MESH_CPU", "1")
    monkeypatch.setenv("HBBFT_TPU_DEVICE_FRACTION", "1")


@pytest.fixture(scope="module")
def batch():
    rng = random.Random(0x7E57)
    n_groups, n = 2, 4
    pts = [G1_GEN * rng.randrange(1, F.R) for _ in range(n_groups * n)]
    pts[3] = G1.infinity()  # the wire's all-zero encoding, absorbing
    s = [rng.randrange(1, 1 << 96) for _ in range(n_groups * n)]
    t = [rng.randrange(1, F.R) for _ in range(n_groups)]
    sizes = [n] * n_groups
    ref = CpuBackend().g1_msm_product_async(pts, s, t, sizes)()
    return pts, s, t, sizes, ref


class TestG1ProductByteIdentity:
    # one mesh width in tier-1: every extra width is a fresh multi-minute
    # XLA compile of the sharded program on this CPU host.  The staged
    # and inline legs share the compiled runner (same cache key), so
    # the staging toggle itself costs nothing.
    @pytest.mark.parametrize("staged", [True, False], ids=["staged", "inline"])
    def test_mesh_matches_single_device(self, batch, monkeypatch, staged):
        pts, s, t, sizes, ref = batch
        monkeypatch.setenv("HBBFT_TPU_STAGING", "1" if staged else "0")
        fin = pm.g1_msm_product_async(pts, s, t, sizes, mesh=M.make_mesh(4))
        assert fin is not None, "mesh path declined the batch"
        assert fin().to_bytes() == ref.to_bytes()

    @pytest.mark.slow
    @pytest.mark.parametrize("n_dev", [2, 8])
    def test_other_mesh_widths(self, batch, n_dev):
        pts, s, t, sizes, ref = batch
        fin = pm.g1_msm_product_async(
            pts, s, t, sizes, mesh=M.make_mesh(n_dev)
        )
        assert fin is not None
        assert fin().to_bytes() == ref.to_bytes()

    @pytest.mark.parametrize("staged", [True, False], ids=["staged", "inline"])
    def test_shipped_points_route(self, batch, monkeypatch, staged):
        """The prefetch route: ``ship_points`` marshals the per-shard
        blocks (through the staging FIFO when on), the flush then
        consumes the shipped mesh plan."""
        pts, s, t, sizes, ref = batch
        monkeypatch.setenv("HBBFT_TPU_STAGING", "1" if staged else "0")
        sp = pm.ship_points(pts, sizes, mesh=M.make_mesh(4))
        assert sp.mesh is not None, "ship_points did not take the mesh plan"
        fin = pm.g1_msm_product_async(sp, s, t, sizes)
        assert fin is not None
        assert fin().to_bytes() == ref.to_bytes()

    def test_backend_routing(self, batch):
        """A mesh-configured TpuBackend routes g1_ship +
        g1_msm_product_async through the sharded engine end to end."""
        from hbbft_tpu.ops.backend_tpu import TpuBackend

        pts, s, t, sizes, ref = batch
        be = TpuBackend(mesh=M.make_mesh(4))
        assert be._mesh_flush_active()
        be.G1_MESH_MIN = len(pts)  # force the mesh path at test size
        sp = be.g1_ship(pts, sizes)
        assert isinstance(sp, pm.ShippedPoints) and sp.mesh is not None
        fin = be.g1_msm_product_async(sp, s, t, sizes)
        assert fin().to_bytes() == ref.to_bytes()


class TestG2ByteIdentity:
    @staticmethod
    def _batch(rng, k=8, nbits=16):
        import jax.numpy as jnp

        pts = [G2_GEN * rng.randrange(1, F.R) for _ in range(k)]
        scalars = [rng.randrange(1, 1 << nbits) for _ in range(k)]
        bits = np.stack(
            [
                [(s >> (nbits - 1 - i)) & 1 for i in range(nbits)]
                for s in scalars
            ]
        ).astype(np.int32)
        return pts, scalars, jnp.asarray(EC.g2_to_limbs(pts)), jnp.asarray(bits)

    def test_mesh_matches_single_device(self, rng):
        """The G2 side of the verify plane: the sharded MSM's wire
        bytes equal the single-device (host) MSM's.  Byte-identity is a
        WIRE property — Jacobian limbs are a redundant representation
        (reduction order changes (X,Y,Z) but not the point), so
        serialization normalizes to affine before comparing."""
        pts, scalars, limbs, bits = self._batch(rng)
        out4 = M.sharded_msm_fn(M.make_mesh(4), g2=True)(limbs, bits)
        ref = g2_multi_exp(pts, scalars)
        assert EC.g2_from_limbs(out4).to_bytes() == ref.to_bytes()

    @pytest.mark.slow
    def test_mesh_matches_one_device_mesh(self, rng):
        # the 1-device mesh leg costs a second full trace of the
        # sharded program on this host — slow-tier only
        pts, scalars, limbs, bits = self._batch(rng)
        out1 = M.sharded_msm_fn(M.make_mesh(1), g2=True)(limbs, bits)
        out4 = M.sharded_msm_fn(M.make_mesh(4), g2=True)(limbs, bits)
        p1, p4 = EC.g2_from_limbs(out1), EC.g2_from_limbs(out4)
        assert p4.to_bytes() == p1.to_bytes()
        assert p4 == g2_multi_exp(pts, scalars)


class TestOneDeviceCollapse:
    def test_ship_points_collapses(self, batch):
        pts, _, _, sizes, _ = batch
        sp = pm.ship_points(pts, sizes, mesh=M.make_mesh(1))
        assert sp.mesh is None, "1-device mesh must collapse to the standard path"

    def test_backend_collapses(self, batch):
        from hbbft_tpu.ops.backend_tpu import TpuBackend

        pts, s, t, sizes, ref = batch
        be = TpuBackend(mesh=M.make_mesh(1))
        assert not be._mesh_flush_active()
        # the flush still works — through the standard single-device path
        fin = be.g1_msm_product_async(pts, s, t, sizes)
        got = fin() if fin is not None else be.g1_msm_product(pts, s, t, sizes)
        assert got.to_bytes() == ref.to_bytes()

    def test_direct_call_collapses(self, batch, monkeypatch):
        """mesh=1 must behave exactly like mesh=None — here with the
        device share forced to zero so BOTH legs decline (compiling the
        full single-device chunk path just to watch it agree costs
        minutes on this host and proves nothing about the mesh)."""
        pts, s, t, sizes, _ = batch
        monkeypatch.setenv("HBBFT_TPU_DEVICE_FRACTION", "0")
        fin1 = pm.g1_msm_product_async(pts, s, t, sizes, mesh=M.make_mesh(1))
        fin0 = pm.g1_msm_product_async(pts, s, t, sizes)
        assert fin1 is None and fin0 is None
