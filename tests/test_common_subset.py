"""Asynchronous Common Subset tests (mirrors ``tests/common_subset.rs``):
the output map is identical at all correct nodes, contains ≥ N−f
proposals, and every entry matches what its proposer actually input."""

import random

import pytest

from hbbft_tpu.harness.network import (
    MessageScheduler,
    SilentAdversary,
    TestNetwork,
)
from hbbft_tpu.protocols.common_subset import CommonSubset


def run_common_subset(rng, size, proposals, mock=True):
    """proposals: {node_id: bytes} — only these nodes propose."""
    f = (size - 1) // 3
    good = size - f
    net = TestNetwork(
        good,
        f,
        lambda adv: SilentAdversary(
            MessageScheduler(MessageScheduler.RANDOM, rng)
        ),
        lambda ni: CommonSubset(ni, 0),
        rng,
        mock_crypto=mock,
    )
    for nid, value in sorted(proposals.items()):
        if nid in net.nodes:
            net.input(nid, value)
    net.step_until(
        lambda: all(n.outputs for n in net.nodes.values())
    )
    outs = [n.outputs for n in net.nodes.values()]
    assert all(len(o) == 1 for o in outs)
    first = outs[0][0]
    for o in outs[1:]:
        assert o[0] == first, "common subsets diverged"
    assert net.observer.outputs and net.observer.outputs[0] == first
    # every entry matches the proposer's actual input
    for pid, value in first.items():
        assert proposals.get(pid) == value
    # at least N - f entries
    assert len(first) >= size - f
    return first


def test_common_subset_all_propose():
    rng = random.Random(30)
    for size in (1, 2, 4, 7):
        proposals = {
            i: b"value-%d" % i for i in range(size)
        }
        run_common_subset(rng, size, proposals)


def test_common_subset_3_out_of_4():
    # reference: tests/common_subset.rs — 3 of 4 nodes propose
    rng = random.Random(31)
    result = run_common_subset(
        rng, 4, {0: b"A", 1: b"B", 2: b"C"}
    )
    assert set(result) <= {0, 1, 2}
    assert len(result) >= 3


def test_common_subset_5_distinct_values():
    rng = random.Random(32)
    run_common_subset(
        rng,
        5,
        {i: bytes([65 + i]) * (i + 1) for i in range(5)},
    )


def test_common_subset_single_node():
    rng = random.Random(33)
    result = run_common_subset(rng, 1, {0: b"solo"})
    assert result == {0: b"solo"}


def test_common_subset_real_bls():
    rng = random.Random(34)
    run_common_subset(
        rng, 4, {i: b"real-%d" % i for i in range(4)}, mock=False
    )


def test_completion_output_order_is_arrival_independent():
    """badgermc regression: the decided-subset dict must list proposers
    in canonical order regardless of the order agreement/broadcast
    results arrived in (``_try_agreement_completion``)."""
    from hbbft_tpu.core.network_info import NetworkInfo

    ni = NetworkInfo.generate_map(
        list(range(4)), random.Random(7), mock=True
    )[0]
    outs = []
    for order in ([0, 1, 2, 3], [3, 1, 0, 2]):
        cs = CommonSubset(ni, 0)
        for pid in order:  # insertion order == arrival order
            cs.broadcast_results[pid] = bytes([pid])
            cs.agreement_results[pid] = True
        result = cs._try_agreement_completion()
        assert result is not None
        outs.append(result)
    assert outs[0] == outs[1]
    assert list(outs[0]) == list(outs[1]) == [0, 1, 2, 3]
