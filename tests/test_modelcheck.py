"""badgermc — bounded schedule-space model checking
(``analysis/modelcheck.py`` + ``harness/mc_net.py``).

The pinned honest sbv stack is explored *exhaustively* here (the
acceptance gate: zero violations, untruncated, ≥10× state reduction
from dedup + DPOR), plus unit coverage of the moving parts: the
independence predicate, ddmin, schedule replay determinism, the
partition-biased probe cut, and the repro file round-trip."""

import json
import random
import subprocess
import sys

import pytest

from hbbft_tpu.analysis.modelcheck import ddmin, independent, run_modelcheck
from hbbft_tpu.harness.mc_net import (
    MCConfig,
    MCNet,
    live_done,
    partition_lag,
    random_schedule,
    run_actions,
    save_repro,
    replay_repro,
    state_key,
)

# ---------------------------------------------------------------------------
# unit pieces
# ---------------------------------------------------------------------------


def test_independence_predicate():
    d = lambda s, r, q: ("deliver", s, r, q)
    # different links, different recipients: commute
    assert independent(d(0, 1, 0), d(2, 3, 0))
    # same recipient: handler order matters
    assert not independent(d(0, 1, 0), d(2, 1, 0))
    # same link: FIFO order is state
    assert not independent(d(0, 1, 0), d(0, 1, 1))
    # forges race with anything at the same recipient
    assert not independent(("forge", 3, 1, "bval-true"), d(0, 1, 0))
    assert independent(("forge", 3, 2, "bval-true"), d(0, 1, 0))


def test_ddmin_finds_minimal_core():
    # the failure needs {3, 7} together; everything else is noise
    calls = []

    def fails(seq):
        calls.append(list(seq))
        return 3 in seq and 7 in seq

    out = ddmin(list(range(10)), fails)
    assert sorted(out) == [3, 7]
    assert len(calls) < 80  # ddmin, not brute force


def test_mcconfig_validation():
    with pytest.raises(ValueError):
        MCConfig(protocol="nope")
    with pytest.raises(ValueError):
        MCConfig(corrupt=2)  # f=1 at n=4
    with pytest.raises(ValueError):
        MCConfig(reveal_mode="sideways")
    rt = MCConfig.from_dict(MCConfig(protocol="agreement").to_dict())
    assert rt.protocol == "agreement"


def test_partition_lag_is_deterministic_cut():
    a = partition_lag(random.Random(5), 4)
    b = partition_lag(random.Random(5), 4)
    assert a == b
    # every lagged link crosses the cut, and both sides are non-empty
    nodes = {s for s, _ in a} | {r for _, r in a}
    assert nodes == {0, 1, 2, 3}
    for s, r in a:
        assert s != r
        assert (r, s) in a  # the cut is symmetric
    assert len(a) == 8  # 2x2 split -> 2*2*2 directed cross links


# ---------------------------------------------------------------------------
# replay determinism
# ---------------------------------------------------------------------------


def test_seeded_schedule_replays_bit_identically():
    cfg = MCConfig(protocol="sbv_broadcast")
    runs = []
    for _ in range(2):
        mc = MCNet(cfg)
        trace, viols = random_schedule(mc, random.Random(99), 4000)
        assert viols == []
        runs.append((trace, state_key(mc).hex(), live_done(mc)))
    assert runs[0] == runs[1]
    assert runs[0][2], "full random delivery must reach the liveness goal"
    # the recorded trace replays through run_actions to the same digest
    mc = MCNet(cfg)
    res = run_actions(mc, runs[0][0])
    assert res.feasible and not res.violations
    assert res.digest == runs[0][1]


def test_repro_file_roundtrip(tmp_path):
    cfg = MCConfig(protocol="sbv_broadcast")
    mc = MCNet(cfg)
    trace, _ = random_schedule(mc, random.Random(3), 4000)
    digest = state_key(mc).hex()
    path = tmp_path / "repro.json"
    save_repro(str(path), cfg, [], trace, {"kind": "liveness-probe"}, digest)
    res = replay_repro(str(path))
    assert res["reproduced"] and res["applied"] == len(trace)
    # a tampered end-state digest must fail the replay
    data = json.loads(path.read_text())
    data["final_digest"] = "00" * 32
    path.write_text(json.dumps(data))
    assert not replay_repro(str(path))["reproduced"]


# ---------------------------------------------------------------------------
# the pinned exhaustive exploration (acceptance gate)
# ---------------------------------------------------------------------------


def test_sbv_exhaustive_honest_is_clean_with_real_reduction():
    r = run_modelcheck(MCConfig(protocol="sbv_broadcast", depth=5))
    d = r.to_dict()
    assert d["violation"] is None
    assert not d["truncated"], "state budget must cover the depth bound"
    assert d["explored"] > 1000
    assert d["deduped"] > 0 and d["dpor_pruned"] > 0
    assert d["reduction"] >= 10.0, d["reduction"]
    assert d["probe_runs"] == 3  # bounded-liveness probes all ran


def test_byzantine_choice_points_stay_clean():
    r = run_modelcheck(
        MCConfig(
            protocol="sbv_broadcast",
            depth=2,
            corrupt=1,
            probes=2,
            probe_steps=800,
        )
    )
    d = r.to_dict()
    assert d["violation"] is None and not d["truncated"]
    assert d["explored"] > 100


# ---------------------------------------------------------------------------
# CLI surface
# ---------------------------------------------------------------------------


def _mc_cli(*args):
    return subprocess.run(
        [sys.executable, "-m", "hbbft_tpu.analysis", "--mc", *args],
        capture_output=True,
        text=True,
        timeout=300,
        env={"JAX_PLATFORMS": "cpu", "PATH": "/usr/bin:/bin"},
        cwd="/root/repo",
    )


def test_cli_mc_json_and_exit_codes():
    p = _mc_cli(
        "--mc-config", "sbv_broadcast", "--mc-depth", "2", "--format", "json"
    )
    assert p.returncode == 0, p.stderr
    doc = json.loads(p.stdout)
    assert doc["ok"] and doc["violations"] == []
    assert doc["mc"]["explored"] > 0
    # unknown stack is a usage error
    assert _mc_cli("--mc-config", "nope").returncode == 2
    # a clean-but-degenerate search fails the state floor
    p = _mc_cli(
        "--mc-config", "sbv_broadcast", "--mc-depth", "1",
        "--mc-min-states", "1000000",
    )
    assert p.returncode == 1
    assert "min-states" in p.stderr or "state floor" in p.stderr
