"""Prewarm-plan completeness — the AOT gate's tier-1 contract.

The first real flush of a fresh process must never compile; that holds
only while :func:`packed_msm.prewarm_plan` covers EVERY executable the
epoch driver can route to.  These tests enumerate the driver's shape
families — G1 product chunks in both transfer modes (uncompressed and
compressed), the flat G1 band, the DKG plane's G2 flat MSM, the
per-chunk gtree fused-check reductions, and the per-device-count mesh
exec keys — and assert each appears in the plan, so a future shape
addition that skips the plan fails HERE instead of silently
reintroducing a multi-second (CPU) or multi-minute (TPU) cold compile.

The ``.palexe`` loadability half runs a real tiny flush under
``HBBFT_TPU_AOT=1`` and proves every planned executable round-trips
disk → memory through ``preload_exec`` WITHOUT compiling; the GC half
proves :func:`packed_msm._gc_palexe` prunes exactly the plan-owned
stale files and nothing else.
"""

import json
import os
import random

import pytest

jax = pytest.importorskip("jax")

from hbbft_tpu.ops import packed_msm, pallas_ec


@pytest.fixture
def warm_env(monkeypatch, tmp_path):
    """Isolated warm-state world: tmp exec cache + fresh seen/rho."""
    monkeypatch.setenv("HBBFT_TPU_EXEC_CACHE", str(tmp_path))
    monkeypatch.setattr(packed_msm, "_WARM_SEEN", set())
    monkeypatch.setattr(packed_msm, "_RHO_STATE", None)
    return tmp_path


def _plan_names(plan):
    return {name for name, _ in plan}


def test_prewarm_plan_covers_epoch_driver_shapes(warm_env, monkeypatch):
    """Every shape family the epoch driver can emit has a plan entry.

    Records the families exactly the way production records them
    (``record_warm_shape`` / ``record_flat_shape``) and asserts the
    plan contains, per family, the executables routing will demand —
    by the shared key builders, so the assertion can't drift from the
    cache it guards."""
    monkeypatch.setattr(jax, "default_backend", lambda: "tpu")

    # product shapes: both transfer modes (sticky compressed), plus a
    # recorded 8-device mesh deployment of the same shape
    packed_msm.record_warm_shape(3, 4, False)
    packed_msm.record_warm_shape(3, 4, True)
    packed_msm.record_warm_shape(3, 4, False, mesh_dev=8)
    # flat band: a G1 chunk and the DKG fused-check plane's G2 chunk
    packed_msm.record_flat_shape(128, 12, g2=False)
    packed_msm.record_flat_shape(128, 32, g2=True)

    plan = packed_msm.prewarm_plan()
    names = _plan_names(plan)

    # G1 product, uncompressed + compressed wire (v2 device unpack)
    assert "unpack_g1_v2" in names
    assert "unpack_g1c_v2" in names
    # gtree fused-check reductions ride every product chunk
    assert any(n.startswith("gtree_g1_") for n in names)
    # flat G1 and the G2 plane
    assert "unpack_g1_v1" in names  # flat keeps the v1 host padding
    assert "unpack_g2_v1" in names
    # per-device-count mesh exec keys (PR 7 format)
    assert any(
        n.startswith("mesh_prod_g1_") and n.endswith("_8d") for n in names
    )

    # completeness against the shared key builders: every executable
    # the recorded product shape routes to is planned
    plan_set = set(plan)
    for g in packed_msm._split_plan(12, 4):
        for compressed in (False, True):
            for key in packed_msm._product_exec_keys(
                g * 3, g, compressed, "pallas"
            ):
                assert key in plan_set, key
    for key in packed_msm._flat_exec_keys(128, 32, True, "pallas"):
        assert key in plan_set, key


def test_prewarm_plan_follows_engine(warm_env, monkeypatch):
    """The plan enumerates for the CURRENT engine: on a CPU AOT host
    (``HBBFT_TPU_AOT=1``) the product chunks are the fused XLA
    programs, never Pallas tile kernels; with the cache inactive the
    plan is empty (plain-CPU interp never compiles through it)."""
    packed_msm.record_warm_shape(3, 4, False)
    packed_msm.record_flat_shape(128, 12, g2=False)

    monkeypatch.delenv("HBBFT_TPU_AOT", raising=False)
    assert packed_msm.prewarm_plan() == []

    monkeypatch.setenv("HBBFT_TPU_AOT", "1")
    names = _plan_names(packed_msm.prewarm_plan())
    assert any(n.startswith("prod_g1_xla_") for n in names)
    assert "flat_g1_xla" in names
    assert not any("unpack" in n or n.startswith("win_") for n in names)


@pytest.mark.slow  # pays one real XLA compile (~2 min on a CPU host)
def test_plan_entries_preload_loadable_and_first_flush_compile_free(
    warm_env, monkeypatch
):
    """The zero-compile contract, end to end on this host: a warming
    flush populates ``.palexe``; a simulated fresh process (cleared
    in-memory cache) preloads every planned executable from disk and
    re-runs the same flush with ZERO compile events in the obs trace."""
    from hbbft_tpu.crypto.backend import CpuBackend
    from hbbft_tpu.crypto.curve import G1_GEN
    from hbbft_tpu.obs import recorder as obs

    monkeypatch.setenv("HBBFT_TPU_AOT", "1")
    monkeypatch.setenv("HBBFT_TPU_WARM", "1")

    rng = random.Random(11)
    pts = [G1_GEN * rng.randrange(1, 997) for _ in range(5)]
    scalars = [rng.getrandbits(16) for _ in range(5)]
    ref = CpuBackend().g1_msm(pts, scalars)

    assert packed_msm.g1_msm_packed(pts, scalars, nbits=16) == ref

    plan = packed_msm.prewarm_plan()
    assert ("flat_g1_xla" in _plan_names(plan)) and plan
    # the warming run wrote every planned executable to disk
    for name, parts in plan:
        fname = pallas_ec._exec_fname(pallas_ec._exec_key(name, parts))
        assert os.path.exists(os.path.join(str(warm_env), fname)), name

    # simulated fresh process: drop the in-memory executables, then
    # prewarm (disk → memory, no compiling) and re-flush under a trace
    monkeypatch.setattr(pallas_ec, "_EXEC_MEM", {})
    monkeypatch.delenv("HBBFT_TPU_WARM", raising=False)
    assert packed_msm.prewarm_shapes() == len(plan)

    rec = obs.Recorder()
    monkeypatch.setattr(obs, "ACTIVE", rec)
    assert packed_msm.g1_msm_packed(pts, scalars, nbits=16) == ref
    compiles = [e for e in rec.events if e.get("ev") == "compile"]
    assert compiles == []  # the first timed flush never compiles


def test_gc_palexe_prunes_only_stale_owned_files(warm_env, monkeypatch):
    """GC removes exactly: plan-owned families, this process's key
    suffix, not reachable from the plan.  Foreign-backend files and
    shared kernel families survive."""
    monkeypatch.setenv("HBBFT_TPU_AOT", "1")
    packed_msm.record_flat_shape(128, 12, g2=False)
    plan = packed_msm.prewarm_plan()
    reachable = [
        pallas_ec._exec_fname(pallas_ec._exec_key(name, parts))
        for name, parts in plan
    ]
    tail = "-".join(
        str(p)
        for p in (jax.__version__, jax.devices()[0].device_kind)
    ).replace(" ", "").replace("/", "_") + ".palexe"

    live = os.path.join(str(warm_env), reachable[0])
    stale = os.path.join(str(warm_env), "flat_g1_xla-((64,96),'uint8')-" + tail)
    shared = os.path.join(str(warm_env), "win_g1-(1,3,12,128)-" + tail)
    foreign = os.path.join(
        str(warm_env), "flat_g1_xla-((64,96),'uint8')-0.0.0-OtherChip.palexe"
    )
    for p in (live, stale, shared, foreign):
        with open(p, "wb") as f:
            f.write(b"x")

    removed = packed_msm._gc_palexe(reachable)
    assert removed == 1
    assert not os.path.exists(stale)  # owned + stale: pruned
    assert os.path.exists(live)  # reachable: kept
    assert os.path.exists(shared)  # shared kernel family: never touched
    assert os.path.exists(foreign)  # other backend's cache: not ours


def test_warm_file_v2_schema_and_legacy_pruning(warm_env):
    """``warm_shapes.json`` hygiene: the v2 document carries a
    ``version`` field and a ``flat`` plane; legacy v1 bare-dict files
    load tolerantly; garbage entries (pre-PR-7 key formats, malformed
    rows) are pruned on load and disappear on the next write."""
    path = os.path.join(str(warm_env), "warm_shapes.json")

    # legacy v1 bare dict with stale/garbage entries
    with open(path, "w") as f:
        json.dump(
            {
                "64:2": {"compressed": False},
                "64:2:mesh8": {"compressed": False},  # pre-PR-7 junk key
                "bogus": 1,
                "0:3": {},
            },
            f,
        )
    doc = packed_msm._load_warm_file()
    assert doc["version"] == 2
    assert doc["shapes"] == {"64:2": {"compressed": False}}
    assert doc["flat"] == []

    # a write round-trips to the v2 schema and drops the junk for good
    packed_msm.record_flat_shape(256, 12, g2=True)
    raw = json.load(open(path))
    assert raw["version"] == 2
    assert raw["shapes"] == {"64:2": {"compressed": False}}
    assert raw["flat"] == [[256, 12, "g2"]]
