"""Device-kernel tests: limb field, EC, SHA-256, GF(2^8) — every kernel
checked bit-exact against the host CPU reference path.

These run on the virtual CPU mesh (conftest forces ``jax_platforms=cpu``)
so they validate XLA-traceable semantics without TPU hardware; the same
compiled programs run unchanged on a real chip.

Scalar-length note: kernels are shape-polymorphic in the scalar bit
length, so most tests use short scalars to keep XLA compile times in CI
seconds; one full-width (255-bit) G1 test pins the production shape.
"""

import hashlib
import random

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from hbbft_tpu.crypto.curve import G1, G2, G1_GEN, G2_GEN, g1_multi_exp, g2_multi_exp
from hbbft_tpu.crypto.rs import ReedSolomon
from hbbft_tpu.crypto.merkle import MerkleTree
from hbbft_tpu.ops import limbs as LB
from hbbft_tpu.ops import ec_jax as EC
from hbbft_tpu.ops import gf256_jax as GF
from hbbft_tpu.ops import sha256_jax as SH
from hbbft_tpu.ops.backend_tpu import TpuBackend


@pytest.fixture(scope="module")
def fq():
    return LB.fq()


# ---------------------------------------------------------------------------
# Limb field
# ---------------------------------------------------------------------------


class TestLimbField:
    def test_roundtrip(self, fq, rng):
        for _ in range(20):
            x = rng.randrange(LB.P)
            assert fq.from_limbs(fq.to_limbs(x)) == x

    def test_ops_match_python_ints(self, fq, rng):
        xs = [rng.randrange(LB.P) for _ in range(32)]
        ys = [rng.randrange(LB.P) for _ in range(32)]
        a = jnp.asarray(fq.to_limbs_batch(xs))
        b = jnp.asarray(fq.to_limbs_batch(ys))
        mul = jax.jit(fq.mul)(a, b)
        add = jax.jit(fq.add)(a, b)
        sub = jax.jit(fq.sub)(a, b)
        neg = jax.jit(fq.neg)(a)
        for i in range(32):
            assert fq.from_limbs(mul[i]) == xs[i] * ys[i] % LB.P
            assert fq.from_limbs(add[i]) == (xs[i] + ys[i]) % LB.P
            assert fq.from_limbs(sub[i]) == (xs[i] - ys[i]) % LB.P
            assert fq.from_limbs(neg[i]) == -xs[i] % LB.P

    def test_edge_values(self, fq):
        edge = [0, 1, 2, LB.P - 1, LB.P - 2]
        rev = list(reversed(edge))
        a = jnp.asarray(fq.to_limbs_batch(edge))
        b = jnp.asarray(fq.to_limbs_batch(rev))
        mul = jax.jit(fq.mul)(a, b)
        sub = jax.jit(fq.sub)(a, b)
        for i, (x, y) in enumerate(zip(edge, rev)):
            assert fq.from_limbs(mul[i]) == x * y % LB.P
            assert fq.from_limbs(sub[i]) == (x - y) % LB.P

    def test_lazy_chain_stays_correct(self, fq, rng):
        """Long chains of unreduced ops must preserve congruence and
        the redundancy invariant (the lazy-reduction soundness test)."""
        xs = [rng.randrange(LB.P) for _ in range(16)]
        ys = [rng.randrange(LB.P) for _ in range(16)]
        a = jnp.asarray(fq.to_limbs_batch(xs))
        b = jnp.asarray(fq.to_limbs_batch(ys))

        @jax.jit
        def chain(a, b):
            acc = a
            for _ in range(15):
                acc = fq.mul(acc, b)
                acc = fq.add(acc, a)
                acc = fq.sub(acc, b)
                acc = fq.mul(acc, acc)
            return acc

        acc = chain(a, b)
        val = list(xs)
        for _ in range(15):
            val = [
                pow((v * y % LB.P + x - y) % LB.P, 2, LB.P)
                for v, x, y in zip(val, xs, ys)
            ]
        for i in range(16):
            assert fq.from_limbs(acc[i]) == val[i]
        assert int(jnp.max(acc)) < 1 << 12  # redundancy invariant

    def test_canon_eq_is_zero(self, fq, rng):
        xs = [rng.randrange(LB.P) for _ in range(8)]
        a = jnp.asarray(fq.to_limbs_batch(xs))
        b = jnp.asarray(fq.to_limbs_batch(xs))
        prod = jax.jit(fq.mul)(a, a)
        want = jnp.asarray(fq.to_limbs_batch([x * x % LB.P for x in xs]))
        assert bool(jax.jit(fq.eq)(prod, want).all())
        assert bool(jax.jit(fq.is_zero)(jax.jit(fq.sub)(a, b)).all())
        canon = jax.jit(fq.canon)(prod)
        for i, x in enumerate(xs):
            assert LB.limbs_to_int(np.asarray(canon[i])) == x * x % LB.P


# ---------------------------------------------------------------------------
# EC kernels
# ---------------------------------------------------------------------------


def _rand_g1(rng, n):
    return [G1_GEN * rng.randrange(1, LB.R) for _ in range(n)]


def _rand_g2(rng, n):
    return [G2_GEN * rng.randrange(1, LB.R) for _ in range(n)]


class TestEcKernels:
    def test_g1_roundtrip(self, rng):
        pts = _rand_g1(rng, 4) + [G1.infinity()]
        arr = EC.g1_to_limbs(pts)
        for i, p in enumerate(pts):
            assert EC.g1_from_limbs(arr[i]) == p

    def test_g2_roundtrip(self, rng):
        pts = _rand_g2(rng, 3) + [G2.infinity()]
        arr = EC.g2_to_limbs(pts)
        for i, p in enumerate(pts):
            assert EC.g2_from_limbs(arr[i]) == p

    def test_complete_add_all_cases(self, rng):
        """One formula must cover: generic add, doubling, ±identity,
        inverse pairs — the completeness property the kernels rely on."""
        k = EC.g1_kernel()
        pts = _rand_g1(rng, 4)
        p, q = pts[0], pts[1]
        cases = [
            (p, q, p + q),
            (p, p, p.double()),
            (p, G1.infinity(), p),
            (G1.infinity(), q, q),
            (G1.infinity(), G1.infinity(), G1.infinity()),
            (p, -p, G1.infinity()),
        ]
        a = jnp.asarray(EC.g1_to_limbs([c[0] for c in cases]))
        b = jnp.asarray(EC.g1_to_limbs([c[1] for c in cases]))
        out = jax.jit(k.add)(a, b)
        for i, (_, _, want) in enumerate(cases):
            assert EC.g1_from_limbs(out[i]) == want, f"case {i}"

    def test_g2_complete_add(self, rng):
        k = EC.g2_kernel()
        pts = _rand_g2(rng, 2)
        p, q = pts
        cases = [(p, q, p + q), (p, p, p.double()), (p, G2.infinity(), p)]
        a = jnp.asarray(EC.g2_to_limbs([c[0] for c in cases]))
        b = jnp.asarray(EC.g2_to_limbs([c[1] for c in cases]))
        out = jax.jit(k.add)(a, b)
        for i, (_, _, want) in enumerate(cases):
            assert EC.g2_from_limbs(out[i]) == want, f"case {i}"

    def test_scalar_mul_short_bits(self, rng):
        """24-bit scalars keep the scan short (compile seconds)."""
        k = EC.g1_kernel()
        pts = _rand_g1(rng, 6)
        scalars = [rng.randrange(1 << 24) for _ in range(4)] + [0, 1]
        bits = np.stack(
            [
                [(s >> (23 - i)) & 1 for i in range(24)]
                for s in scalars
            ]
        ).astype(np.int32)
        arr = jnp.asarray(EC.g1_to_limbs(pts))
        out = jax.jit(k.scalar_mul)(arr, jnp.asarray(bits))
        for i, (p, s) in enumerate(zip(pts, scalars)):
            assert EC.g1_from_limbs(out[i]) == p * s, f"scalar {i}"

    def test_g2_scalar_mul_short_bits(self, rng):
        k = EC.g2_kernel()
        pts = _rand_g2(rng, 2)
        scalars = [rng.randrange(1 << 16) for _ in range(2)]
        bits = np.stack(
            [[(s >> (15 - i)) & 1 for i in range(16)] for s in scalars]
        ).astype(np.int32)
        arr = jnp.asarray(EC.g2_to_limbs(pts))
        out = jax.jit(k.scalar_mul)(arr, jnp.asarray(bits))
        for i, (p, s) in enumerate(zip(pts, scalars)):
            assert EC.g2_from_limbs(out[i]) == p * s

    def test_g1_msm_full_width(self, rng):
        """Production shape: 255-bit scalars, non-power-of-two count."""
        pts = _rand_g1(rng, 5)
        scalars = [rng.randrange(LB.R) for _ in range(5)]
        assert EC.g1_msm(pts, scalars) == g1_multi_exp(pts, scalars)

    def test_msm_empty(self):
        assert EC.g1_msm([], []).is_infinity()


# ---------------------------------------------------------------------------
# SHA-256 kernel
# ---------------------------------------------------------------------------


class TestSha256:
    @pytest.mark.parametrize("msg_len", [0, 1, 32, 55, 56, 64, 100, 200])
    def test_matches_hashlib(self, msg_len, rng):
        msgs = [bytes(rng.randrange(256) for _ in range(msg_len)) for _ in range(9)]
        got = SH.sha256_many(msgs)
        want = [hashlib.sha256(m).digest() for m in msgs]
        assert got == want

    def test_merkle_levels_match_host_tree(self, rng):
        values = [bytes([i]) * 40 for i in range(11)]
        host = MerkleTree(values)
        dev = SH.merkle_levels_device(values)
        assert dev == host.levels


# ---------------------------------------------------------------------------
# GF(2^8) / Reed-Solomon kernel
# ---------------------------------------------------------------------------


class TestGf256:
    def test_matmul_matches_host(self, rng):
        from hbbft_tpu.crypto.rs import gf_matmul

        m = np.array(
            [[rng.randrange(256) for _ in range(6)] for _ in range(4)],
            dtype=np.uint8,
        )
        d = np.array(
            [[rng.randrange(256) for _ in range(50)] for _ in range(6)],
            dtype=np.uint8,
        )
        got = np.asarray(GF.gf_matmul_device(m, jnp.asarray(d)))
        assert (got == gf_matmul(m, d)).all()

    @pytest.mark.parametrize("k,m", [(4, 2), (6, 3), (10, 2)])
    def test_rs_encode_matches_host(self, k, m, rng):
        host = ReedSolomon(k, m)
        dev = GF.ReedSolomonDevice(k, m)
        data = [bytes(rng.randrange(256) for _ in range(64)) for _ in range(k)]
        assert dev.encode(data) == host.encode(data)

    def test_rs_reconstruct(self, rng):
        k, m = 5, 3
        dev = GF.ReedSolomonDevice(k, m)
        data = [bytes(rng.randrange(256) for _ in range(48)) for _ in range(k)]
        full = dev.encode(data)
        # erase m arbitrary shards (max tolerable)
        lost = [1, 4, 6]
        holey = [None if i in lost else s for i, s in enumerate(full)]
        assert dev.reconstruct(holey) == full


# ---------------------------------------------------------------------------
# TpuBackend: bit-identity through the CryptoBackend seam
# ---------------------------------------------------------------------------


class TestTpuBackend:
    def test_merkle_same_root_and_proofs(self, rng):
        be = TpuBackend()
        values = [bytes([i]) * 33 for i in range(9)]
        dev_tree = be.merkle_tree(values)
        host_tree = MerkleTree(values)
        assert dev_tree.root_hash == host_tree.root_hash
        for i in range(9):
            assert dev_tree.proof(i) == host_tree.proof(i)
            assert dev_tree.proof(i).validate(9)

    def test_rs_same_shards(self, rng):
        be = TpuBackend()
        codec = be.rs_codec(6, 2)
        data = [bytes(rng.randrange(256) for _ in range(32)) for _ in range(6)]
        assert codec.encode(data) == ReedSolomon(6, 2).encode(data)

    def test_batch_verify_shares(self, rng):
        """The hot N² verification path: device MSM + 2 host pairings."""
        from hbbft_tpu.crypto import threshold as T
        from hbbft_tpu.crypto.hashing import hash_to_g1

        be = TpuBackend()
        base = hash_to_g1(b"epoch-nonce")
        sks = [rng.randrange(1, LB.R) for _ in range(4)]
        shares = [base * sk for sk in sks]
        pks = [G2_GEN * sk for sk in sks]
        assert be.batch_verify_shares(shares, pks, base, b"ctx")
        # a single corrupted share must fail the whole batch
        bad = list(shares)
        bad[2] = shares[2] + G1_GEN
        assert not be.batch_verify_shares(bad, pks, base, b"ctx")
        # and must agree with the CPU reference on both outcomes
        assert T.batch_verify_shares(shares, pks, base, b"ctx")
        assert not T.batch_verify_shares(bad, pks, base, b"ctx")


class TestMarshallingBatch:
    """Vectorized host↔device marshalling (round-2: the per-element
    Python loops dominated large flushes)."""

    def test_scalars_to_bits_matches_single(self):
        import numpy as np
        import random

        from hbbft_tpu.ops import limbs as LB

        rng = random.Random(0xB17)
        for nbits in (128, 192, 255):
            ks = [rng.randrange(0, 1 << nbits) for _ in range(40)] + [0, 1]
            ref = np.stack([LB.scalar_to_bits(k, nbits) for k in ks])
            assert np.array_equal(LB.scalars_to_bits(ks, nbits), ref)

    def test_scalars_to_bits_overwidth_raises(self):
        import pytest

        from hbbft_tpu.ops import limbs as LB

        with pytest.raises(OverflowError):
            LB.scalars_to_bits([1 << 200], 192)

    def test_ints_to_limbs_batch_matches_single(self):
        import numpy as np
        import random

        from hbbft_tpu.ops import limbs as LB

        rng = random.Random(0xB18)
        f = LB.fq()
        vals = [rng.randrange(0, f.p) for _ in range(32)] + [0, 1, f.p - 1]
        ref = np.stack([LB.int_to_limbs(v, f.L) for v in vals])
        assert np.array_equal(LB.ints_to_limbs_batch(vals, f.L), ref)

    def test_g1_to_limbs_mixed_reps(self):
        import random

        from hbbft_tpu.crypto.curve import G1, G1_GEN
        from hbbft_tpu.ops import ec_jax as EC, limbs as LB

        rng = random.Random(0xB19)
        f = LB.fq()
        pts = [G1.infinity()]
        for _ in range(8):
            a = G1_GEN * rng.randrange(1, LB.R)
            b = G1_GEN * rng.randrange(1, LB.R)
            pts += [a, a + b]  # affine-built and Jacobian (Z≠1) mixes
        out = EC.g1_to_limbs(pts)
        for i, pt in enumerate(pts):
            aff = pt.affine()
            if aff is None:
                assert f.from_limbs(out[i, 1]) == 1
                assert out[i, 0].sum() == 0 and out[i, 2].sum() == 0
            else:
                assert f.from_limbs(out[i, 0]) == aff[0]
                assert f.from_limbs(out[i, 1]) == aff[1]
                assert f.from_limbs(out[i, 2]) == 1
