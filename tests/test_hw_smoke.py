"""Real-hardware smoke gate (VERDICT r2 item 7).

Run with ``HBBFT_TPU_HW=1 python -m pytest tests/test_hw_smoke.py -q``
— the whole suite is skipped otherwise (the regular CI forces the
virtual CPU mesh; full-width Pallas on a real chip is what this file
guards round-over-round, replacing bench-time assertions).

~2-3 min warm: the windowed Mosaic executables load from the
``.xla_cache/pallas_exec`` disk cache (~1 s each); only the small XLA
reductions compile per process.  Run it before each BENCH capture.
"""

import random

import numpy as np
import pytest

try:
    import jax

    _ON_TPU = jax.default_backend() == "tpu"
except Exception:  # pragma: no cover - no jax
    _ON_TPU = False

pytestmark = pytest.mark.skipif(
    not _ON_TPU,
    reason="hardware smoke suite needs the real TPU "
    "(HBBFT_TPU_HW=1, outside the CPU-forced CI)",
)

if _ON_TPU:  # the smoke gate is a warming entry point (backend_tpu.py)
    import os

    os.environ.setdefault("HBBFT_TPU_WARM", "1")


def _fr_scalars(rng, k):
    from hbbft_tpu.ops import limbs as LB

    return [rng.randrange(1, LB.R) for _ in range(k)]


class TestWindowedKernelsHw:
    """Full-width (255-bit) windowed Pallas correctness on the chip."""

    def test_g1_windowed_full_width(self):
        from hbbft_tpu.crypto.curve import G1_GEN, g1_multi_exp
        from hbbft_tpu.ops import pallas_ec

        rng = random.Random(0x51)
        k = 256  # buckets to a cached tile grid
        pts = [G1_GEN * rng.randrange(1, 1 << 64) for _ in range(k)]
        scalars = _fr_scalars(rng, k)
        got = pallas_ec.g1_msm_pallas(pts, scalars, nbits=255, interpret=False)
        assert got == g1_multi_exp(pts, scalars)

    def test_g2_windowed_full_width(self):
        from hbbft_tpu.crypto.curve import G2_GEN, g2_multi_exp
        from hbbft_tpu.ops import pallas_ec

        rng = random.Random(0x52)
        k = 64
        pts = [G2_GEN * rng.randrange(1, 1 << 64) for _ in range(k)]
        scalars = _fr_scalars(rng, k)
        got = pallas_ec.g2_msm_pallas(pts, scalars, nbits=255, interpret=False)
        assert got == g2_multi_exp(pts, scalars)

    def test_g1_windowed_epoch_shape_192bit(self):
        # the product-form flush width (192-bit coefficients) at a
        # cached epoch-scale bucket
        from hbbft_tpu.crypto.curve import G1_GEN, g1_multi_exp
        from hbbft_tpu.ops import pallas_ec

        rng = random.Random(0x53)
        k = 200  # buckets to the 2-tile 192-bit shape (exec-cached)
        pts = [G1_GEN * rng.randrange(1, 1 << 64) for _ in range(k)]
        scalars = [rng.randrange(1, 1 << 192) for _ in range(k)]
        got = pallas_ec.g1_msm_pallas(pts, scalars, nbits=192, interpret=False)
        assert got == g1_multi_exp(pts, scalars)

    def test_edge_scalars(self):
        # 0, 1, r-1 and duplicate points through the windowed path
        from hbbft_tpu.crypto.curve import G1_GEN, g1_multi_exp
        from hbbft_tpu.ops import limbs as LB
        from hbbft_tpu.ops import pallas_ec

        pts = [G1_GEN * 7] * 4 + [G1_GEN * 11] * 4
        scalars = [0, 1, LB.R - 1, 2, 0, 1, LB.R - 1, 3]
        got = pallas_ec.g1_msm_pallas(pts, scalars, nbits=255, interpret=False)
        assert got == g1_multi_exp(pts, scalars)


class TestPackedHw:
    """Round-4 shipping paths: packed-wire transfer + on-device unpack
    (flat and compressed) and the hybrid factored product split."""

    def test_packed_flat_matches_host(self):
        from hbbft_tpu import native as NT
        from hbbft_tpu.crypto.backend import CpuBackend
        from hbbft_tpu.crypto.curve import G1, G1_GEN
        from hbbft_tpu.ops import limbs as LB, packed_msm

        rng = random.Random(0x55)
        k = 65536  # the headline bucket (warm executables)
        base = G1_GEN * rng.randrange(1, LB.R)
        xs = [rng.randrange(1, LB.R) for _ in range(k)]
        pts = [
            NT.g1_unwire(w, G1)
            for w in NT.g1_mul_many(NT.g1_wire(base), xs)
        ]
        scalars = [rng.getrandbits(192) % LB.R for _ in range(k)]
        got = packed_msm.g1_msm_packed(pts, scalars, nbits=192)
        assert got == CpuBackend().g1_msm(pts, scalars)

    def test_hybrid_product_split_matches_host(self):
        from hbbft_tpu import native as NT
        from hbbft_tpu.crypto import fields as F
        from hbbft_tpu.crypto.backend import CpuBackend
        from hbbft_tpu.crypto.curve import G1, G1_GEN
        from hbbft_tpu.ops import limbs as LB, packed_msm

        rng = random.Random(0x56)
        G, n = 16, 4096  # 2-group chunks, kd = 2·4096 = 8192 each
        k = G * n
        base = G1_GEN * rng.randrange(1, LB.R)
        xs = [rng.randrange(1, LB.R) for _ in range(k)]
        pts = [
            NT.g1_unwire(w, G1)
            for w in NT.g1_mul_many(NT.g1_wire(base), xs)
        ]
        s = [rng.getrandbits(96) | 1 for _ in range(k)]
        ts = [rng.getrandbits(96) | 1 for _ in range(G)]
        fin = packed_msm.g1_msm_product_async(pts, s, ts, [n] * G)
        assert fin is not None  # a device share must exist on hw
        flat = [
            (s[g * n + i] * ts[g]) % F.R for g in range(G) for i in range(n)
        ]
        assert fin() == CpuBackend().g1_msm(pts, flat)

    def test_compressed_unpack_on_device(self):
        # 48-byte x + device sqrt reconstructs the same points as the
        # 96-byte path (sign + infinity handling) on the real chip
        import jax

        from hbbft_tpu.crypto.curve import G1, G1_GEN
        from hbbft_tpu.ops import ec_jax, packed_msm

        rng = random.Random(0x57)
        k = 128
        pts = [G1_GEN * rng.randrange(1, 1 << 64) for _ in range(k)]
        pts[3] = G1.infinity()
        scalars = [rng.getrandbits(96) | 1 for _ in range(k)]
        wires = packed_msm.g1_wires_batch(pts)
        sc = packed_msm.scalar_bytes_batch(scalars, 12)
        x, meta = packed_msm.compress_rows(wires, k)
        ref_t, ref_d = packed_msm._unpack_device(
            jax.device_put(wires), jax.device_put(sc)
        )
        got_t, got_d = packed_msm._unpack_compressed_device(
            jax.device_put(x), jax.device_put(meta), jax.device_put(sc)
        )
        assert np.array_equal(np.asarray(got_d), np.asarray(ref_d))
        ref = np.asarray(ref_t)
        got = np.asarray(got_t)
        for t in range(0, 128, 13):
            a = ec_jax.g1_from_limbs(ref[0, :, :, t])
            b = ec_jax.g1_from_limbs(got[0, :, :, t])
            assert a == b, t


class TestBackendRoutingHw:
    def test_backend_batch_verify_on_device(self):
        """The TpuBackend's fused share verification with the G1
        routing band forced open below the shipping threshold agrees
        with ground truth, so a marshalling/kernel regression in the
        device leg cannot hide behind host routing."""
        from hbbft_tpu.crypto.curve import G2_GEN
        from hbbft_tpu.crypto.hashing import hash_to_g1
        from hbbft_tpu.ops import limbs as LB
        from hbbft_tpu.ops.backend_tpu import TpuBackend

        rng = random.Random(0x54)
        k = 8192  # a cached device tile bucket
        base = hash_to_g1(b"hw-smoke")
        sks = [rng.randrange(1, LB.R) for _ in range(1024)]
        shares = [base * sk for sk in sks] * (k // 1024)
        pks = [G2_GEN * sk for sk in sks] * (k // 1024)
        be = TpuBackend()
        be.G1_DEVICE_MIN = 0
        be.G1_DEVICE_MAX = 1 << 62
        assert be.batch_verify_shares(shares, pks, base, b"hw")
        # one corrupted share must fail the fused equation
        bad = list(shares)
        bad[5] = base * (sks[5] + 1)
        assert not be.batch_verify_shares(bad, pks, base, b"hw")
