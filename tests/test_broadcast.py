"""Reliable-broadcast integration tests (mirrors ``tests/broadcast.rs``).

Correctness: every good node and the observer output the proposed value
exactly once, under silent, proposing-equivocator, and random-fuzz
adversaries across network sizes with f = (N−1)/3 corrupted nodes.
"""

import random

import pytest

from hbbft_tpu.harness.network import (
    Adversary,
    MessageScheduler,
    MessageWithSender,
    RandomAdversary,
    SilentAdversary,
    TestNetwork,
)
from hbbft_tpu.core.network_info import NetworkInfo
from hbbft_tpu.protocols.broadcast import Broadcast, random_message


def new_broadcast(netinfo):
    return Broadcast(netinfo, 0)


class ProposeAdversary(Adversary):
    """A corrupt node injects a conflicting broadcast mid-protocol
    (reference ``tests/broadcast.rs:31-91``)."""

    def __init__(self, scheduler, rng):
        self.scheduler = scheduler
        self.rng = rng
        self.has_sent = False
        self.adv_netinfos = {}

    def init(self, all_nodes, adv_netinfos):
        self.adv_netinfos = adv_netinfos

    def pick_node(self, nodes):
        return self.scheduler.pick_node(nodes)

    def push_message(self, sender_id, tm):
        pass

    def step(self):
        if self.has_sent or not self.adv_netinfos:
            return []
        self.has_sent = True
        adv_id = sorted(self.adv_netinfos)[0]
        # the corrupt node runs its own broadcast instance claiming to
        # propose, and leaks those messages into the network
        bc = Broadcast(self.adv_netinfos[adv_id], adv_id)
        step = bc.handle_input(b"Fake news")
        return [MessageWithSender(adv_id, tm) for tm in step.messages]


def run_broadcast(network: TestNetwork, proposed: bytes):
    network.input(0, proposed)
    network.step_until(
        lambda: all(n.terminated() for n in network.nodes.values())
    )
    for node in network.nodes.values():
        assert node.outputs == [proposed], node.id
    assert network.observer.outputs == [proposed]


def sweep_sizes(new_adversary, proposed: bytes, seed: int, sizes=None):
    rng = random.Random(seed)
    if sizes is None:
        sizes = list(range(1, 7)) + [rng.randrange(8, 16)]
    for size in sizes:
        f = (size - 1) // 3
        good = size - f
        net = TestNetwork(
            good,
            f,
            lambda adv_nis: new_adversary(good, f, rng),
            new_broadcast,
            rng,
            mock_crypto=True,
        )
        run_broadcast(net, proposed)


def test_broadcast_random_delivery_silent():
    sweep_sizes(
        lambda g, f, rng: SilentAdversary(
            MessageScheduler(MessageScheduler.RANDOM, rng)
        ),
        b"Foo",
        seed=1,
    )


def test_broadcast_first_delivery_silent():
    sweep_sizes(
        lambda g, f, rng: SilentAdversary(
            MessageScheduler(MessageScheduler.FIRST, rng)
        ),
        b"Foo",
        seed=2,
    )


def test_broadcast_random_delivery_adv_propose():
    sweep_sizes(
        lambda g, f, rng: ProposeAdversary(
            MessageScheduler(MessageScheduler.RANDOM, rng), rng
        ),
        b"Foo",
        seed=3,
    )


def test_broadcast_random_adversary():
    rng = random.Random(4)

    def gen():
        from hbbft_tpu.core.step import Target

        msg = random_message(rng)
        target = (
            Target.all()
            if rng.random() < 0.5
            else Target.to(rng.randrange(4))
        )
        return Target.all().message(msg) if target.is_all else target.message(msg)

    sweep_sizes(
        lambda g, f, rng_: RandomAdversary(0.2, 0.2, gen, rng_),
        b"RandomFoo",
        seed=5,
        sizes=[4, 7],
    )


def test_broadcast_equal_leaves():
    # 32 spaces -> all shards equal; the index-bound leaf hashes must
    # still produce valid distinct proofs (reference
    # ``test_8_broadcast_equal_leaves_silent``).
    rng = random.Random(6)
    net = TestNetwork(
        8,
        0,
        lambda adv: SilentAdversary(
            MessageScheduler(MessageScheduler.RANDOM, rng)
        ),
        new_broadcast,
        rng,
    )
    run_broadcast(net, b" " * 32)


def test_broadcast_large_value_medium_network():
    rng = random.Random(7)
    net = TestNetwork(
        9,
        4,
        lambda adv: SilentAdversary(
            MessageScheduler(MessageScheduler.RANDOM, rng)
        ),
        new_broadcast,
        rng,
    )
    run_broadcast(net, bytes(rng.randrange(256) for _ in range(10_000)))


def test_non_proposer_cannot_input():
    rng = random.Random(8)
    nis = NetworkInfo.generate_map(range(4), rng, mock=True)
    bc = Broadcast(nis[1], 0)
    with pytest.raises(Exception):
        bc.handle_input(b"nope")


def test_faulty_proof_attributed():
    rng = random.Random(9)
    nis = NetworkInfo.generate_map(range(4), rng, mock=True)
    bc = Broadcast(nis[1], 0)
    garbage = random_message(rng, 4)
    step = bc.handle_message(2, garbage)
    # whatever the message type, node 2 is either ignored or flagged;
    # flagged faults must name node 2
    for fault in step.fault_log:
        assert fault.node_id == 2


def test_broadcast_silent_reference_scale():
    """The reference additionally sweeps rand(30..50) nodes
    (``tests/broadcast.rs:124-127``) — f = (N−1)/3 silent Byzantine."""
    rng = random.Random(0x30)
    sweep_sizes(
        lambda g, f, r: SilentAdversary(
            MessageScheduler(MessageScheduler.RANDOM, r)
        ),
        b"payload at reference scale",
        0x30,
        sizes=[rng.randrange(30, 50)],
    )
