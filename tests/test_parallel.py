"""Multi-device sharding tests on the virtual 8-device CPU mesh.

Validates that the sharded crypto plane (``parallel/mesh.py``) compiles
and executes with real collectives and returns bit-identical results to
the single-device path — the property the driver's multi-chip dry-run
checks at scale.
"""

import random

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from hbbft_tpu.crypto.curve import G1_GEN, g1_multi_exp
from hbbft_tpu.ops import ec_jax as EC, limbs as LB
from hbbft_tpu.parallel import mesh as M


@pytest.fixture(scope="module")
def mesh8():
    assert len(jax.devices()) >= 8, "conftest must force 8 CPU devices"
    return M.make_mesh(8)


def _short_bits(scalars, nbits):
    return np.stack(
        [[(s >> (nbits - 1 - i)) & 1 for i in range(nbits)] for s in scalars]
    ).astype(np.int32)


class TestShardedMsm:
    def test_matches_host_short_scalars(self, mesh8, rng):
        pts = [G1_GEN * rng.randrange(1, LB.R) for _ in range(16)]
        scalars = [rng.randrange(1 << 16) for _ in range(16)]
        run = M.sharded_msm_fn(mesh8)
        out = run(
            jnp.asarray(EC.g1_to_limbs(pts)),
            jnp.asarray(_short_bits(scalars, 16)),
        )
        assert EC.g1_from_limbs(out) == g1_multi_exp(pts, scalars)

    def test_uneven_batch_pads_with_identity(self, mesh8, rng):
        pts = [G1_GEN * rng.randrange(1, LB.R) for _ in range(11)]
        scalars = [rng.randrange(1 << 12) for _ in range(11)]
        run = M.sharded_msm_fn(mesh8)
        out = run(
            jnp.asarray(EC.g1_to_limbs(pts)),
            jnp.asarray(_short_bits(scalars, 12)),
        )
        assert EC.g1_from_limbs(out) == g1_multi_exp(pts, scalars)

    def test_single_device_mesh(self, rng):
        mesh1 = M.make_mesh(1)
        pts = [G1_GEN * rng.randrange(1, LB.R) for _ in range(4)]
        scalars = [rng.randrange(1 << 12) for _ in range(4)]
        run = M.sharded_msm_fn(mesh1)
        out = run(
            jnp.asarray(EC.g1_to_limbs(pts)),
            jnp.asarray(_short_bits(scalars, 12)),
        )
        assert EC.g1_from_limbs(out) == g1_multi_exp(pts, scalars)


class TestShardedEpochStep:
    def test_epoch_step_compiles_and_matches(self, mesh8, rng):
        """The multi-chip 'training step' on tiny shapes: G1+G2 MSM
        aggregates + hash lanes, sharded 8 ways."""
        from hbbft_tpu.crypto.curve import G2_GEN, g2_multi_exp
        from hbbft_tpu.ops import sha256_jax as SH

        k, nbits = 8, 8
        sks = [rng.randrange(1, LB.R) for _ in range(k)]
        base = G1_GEN * 7
        shares = [base * s for s in sks]
        pks = [G2_GEN * s for s in sks]
        coeffs = [rng.randrange(1 << nbits) for _ in range(k)]
        step = M.sharded_epoch_crypto_fn(mesh8)
        msgs = [bytes([i]) * 20 for i in range(k)]
        blocks = SH.pad_messages(msgs)  # [k, 1, 16]
        agg1, agg2, digests = step(
            jnp.asarray(EC.g1_to_limbs(shares)),
            jnp.asarray(_short_bits(coeffs, nbits)),
            jnp.asarray(EC.g2_to_limbs(pks)),
            jnp.asarray(blocks[:, 0, :]),
        )
        assert EC.g1_from_limbs(agg1) == g1_multi_exp(shares, coeffs)
        assert EC.g2_from_limbs(agg2) == g2_multi_exp(pks, coeffs)
        assert SH.digests_to_bytes(digests) == SH.sha256_many(msgs)


class TestMeshBackend:
    def test_tpu_backend_mesh_routing(self, rng):
        """A mesh-configured TpuBackend routes big G1 MSMs through the
        sharded all-gather path and matches the host result."""
        import random

        from hbbft_tpu.crypto.curve import G1_GEN, g1_multi_exp
        from hbbft_tpu.ops.backend_tpu import TpuBackend
        from hbbft_tpu.parallel import mesh as M

        r = random.Random(0x3E5)
        mesh = M.make_mesh(8)
        be = TpuBackend(mesh=mesh)
        be.G1_MESH_MIN = 4  # force the mesh path at test size
        pts = [G1_GEN * r.randrange(1, 1 << 40) for _ in range(10)]
        ks = [r.randrange(1, 1 << 96) for _ in range(10)]
        assert be.g1_msm(pts, ks) == g1_multi_exp(pts, ks)


class TestShardedWindowedMsm:
    """The 4-bit windowed Pallas kernel under shard_map (VERDICT r2
    item 5): tile grid sharded over the mesh, per-device windowed
    scalar-mul + local reduction, one all_gather of [3, L] partials.
    Narrow scalar width keeps CPU interpret mode tractable; full-width
    correctness on real silicon is the hardware smoke suite's job
    (tests/test_hw_smoke.py)."""

    def test_windowed_matches_host(self, mesh8, rng):
        pts = [G1_GEN * rng.randrange(1, 1 << 30) for _ in range(24)]
        scalars = [rng.randrange(1, 1 << 16) for _ in range(24)]
        got = M.sharded_windowed_g1_msm(
            pts, scalars, mesh=mesh8, nbits=16, interpret=True
        )
        assert got == g1_multi_exp(pts, scalars)

    def test_packed_wire_matches_host(self, mesh8, rng):
        """The r5 packed-wire mesh transfer (96 B wire + scalar bytes,
        per-shard on-device unpack): ragged batch padded with the
        infinity encoding, result equal to the host MSM."""
        from hbbft_tpu.crypto.curve import G1
        from hbbft_tpu.ops import ec_jax as EC2, packed_msm

        pts = [G1_GEN * rng.randrange(1, 1 << 30) for _ in range(13)]
        pts[5] = G1.infinity()
        scalars = [rng.randrange(1, 1 << 16) for _ in range(13)]
        run = M.sharded_packed_msm_fn(mesh8, interpret=True)
        wires = packed_msm.g1_wires_batch(pts)
        sc = packed_msm.scalar_bytes_batch(scalars, 2)
        got = EC2.g1_from_limbs(run(wires, sc))
        assert got == g1_multi_exp(pts, scalars)
