"""Differential suite for limbprove (:mod:`hbbft_tpu.analysis.rangecheck`).

Each test copies the package tree into a fixture, textually reverts one
of the arithmetic safeguards the pinned ``range_manifest.json`` bounds
depend on, re-analyzes only the affected kernels in a subprocess (the
fixture's ``hbbft_tpu`` on ``PYTHONPATH``), and asserts limbprove
re-detects the exact obligation — right key, right direction (unproved
vs loosened pin), and a SARIF-able flow path through the right
function.  The analysis is targeted (``limbs.mul`` + ``fr.matmul``
re-prove in well under a second) so the whole suite stays tier-1.

The perturbations mirror real editing accidents:

- drop one carry round in ``Limb.normalize``       → every obligation
  still *proves*, but the ``limbs.mul:out-invariant`` peak grows past
  its pinned value — the manifest diff is the only thing that notices;
- ``LIMB_BITS`` 11 → 12                            → the ``_conv``
  convolution peak exceeds int32 (``limbs.mul:cap-int32`` unproved);
- ``_MAX_K`` 971 → 2000                            → the fr matmul
  accumulator exceeds int32 (``fr.matmul:cap-int32`` unproved);
- fr fold ``range(3)`` → ``range(1)``              → digits survive
  above the canonical slice (``fr.matmul:slice-exact`` unproved, flow
  through ``_fold_once``).
"""

import json
import os
import shutil
import subprocess
import sys

import pytest

import hbbft_tpu
from hbbft_tpu.analysis import rangecheck as rc

PACKAGE_DIR = os.path.dirname(os.path.abspath(hbbft_tpu.__file__))

KERNELS = ("limbs.mul", "fr.matmul")

# Subprocess driver: analyze only the named kernels against whatever
# ``hbbft_tpu`` resolves first on PYTHONPATH, dump obligations as JSON.
_DRIVER = """\
import json, sys
import hbbft_tpu
import hbbft_tpu.analysis.rangecheck as rc
names = set(sys.argv[1:])
out = {"pkg": hbbft_tpu.__file__, "obs": []}
for _module, rs in rc.iter_range_specs():
    for spec in rs["specs"](rc):
        if spec.name in names:
            rep = rc.analyze_spec(spec)
            for o in rep.obligations:
                out["obs"].append({
                    "kernel": o.kernel, "kind": o.kind, "key": o.key,
                    "proved": o.proved, "peak": str(o.peak),
                    "capacity": str(o.capacity),
                    "site": list(o.site) if o.site else None,
                    "flow": [list(f) for f in (o.flow or [])],
                })
print(json.dumps(out))
"""


def _copy_pkg(tmp_path):
    """Copy the package tree into an importable fixture root."""
    root = tmp_path / "fixture"
    shutil.copytree(
        PACKAGE_DIR,
        root / "hbbft_tpu",
        ignore=shutil.ignore_patterns("__pycache__", "*.pyc"),
    )
    return root


def _perturb(root, relpath, old, new):
    path = root / "hbbft_tpu" / relpath
    text = path.read_text()
    assert old in text, (
        f"perturbation anchor {old!r} vanished from {relpath} — "
        "update the differential suite alongside the kernel edit"
    )
    path.write_text(text.replace(old, new))


def _analyze(root, *kernels):
    """Run the targeted driver against the fixture; key → entry dict."""
    driver = root / "rc_driver.py"
    driver.write_text(_DRIVER)
    env = dict(os.environ)
    env["PYTHONPATH"] = str(root)
    env["JAX_PLATFORMS"] = "cpu"
    proc = subprocess.run(
        [sys.executable, str(driver), *kernels],
        capture_output=True,
        text=True,
        env=env,
        timeout=300,
    )
    assert proc.returncode == 0, proc.stderr[-4000:]
    out = json.loads(proc.stdout.strip().splitlines()[-1])
    # The subprocess must have analyzed the *fixture*, not the repo.
    assert out["pkg"].startswith(str(root)), out["pkg"]
    entries = {e["key"]: e for e in out["obs"]}
    # Targeted analysis still yields the full obligation set per kernel.
    for kernel in kernels:
        assert any(e["kernel"] == kernel for e in entries.values())
    return entries


def _pinned():
    manifest = rc.load_manifest()
    assert manifest is not None
    return {e["key"]: e for e in manifest["obligations"]}


def _flow_functions(entry):
    return {fn for (_path, _line, fn) in map(tuple, entry["flow"] or [])}


def _as_result(entries):
    """Rebuild a RunResult from driver JSON so diff_manifest (the exact
    code path behind the ``limb-range`` rule) renders the findings."""
    by_kernel = {}
    for e in entries.values():
        by_kernel.setdefault(e["kernel"], []).append(
            rc.Obligation(
                kernel=e["kernel"],
                kind=e["kind"],
                peak=int(e["peak"]),
                capacity=int(e["capacity"]),
                proved=e["proved"],
                site=tuple(e["site"]) if e["site"] else None,
                flow=tuple(tuple(f) for f in e["flow"]) or None,
            )
        )
    reports = [
        rc.KernelReport(kernel=k, obligations=obs)
        for k, obs in sorted(by_kernel.items())
    ]
    return rc.RunResult(reports=reports, plan=[], wall=0.0)


def _restricted_manifest(keys):
    """Pinned manifest cut down to the analyzed keys, so diff_manifest
    does not report every unanalyzed kernel as vanished."""
    pinned = _pinned()
    return {
        "version": 1,
        "obligations": [pinned[k] for k in sorted(keys) if k in pinned],
    }


@pytest.fixture
def fixture_root(tmp_path):
    return _copy_pkg(tmp_path)


def test_unperturbed_fixture_matches_manifest(fixture_root):
    """The copy machinery itself introduces no drift: every obligation
    proves and every peak equals its pinned value."""
    entries = _analyze(fixture_root, *KERNELS)
    pinned = _pinned()
    for key, entry in entries.items():
        assert entry["proved"], key
        assert key in pinned, key
        assert entry["peak"] == pinned[key]["peak"], key
    assert not rc.diff_manifest(
        _restricted_manifest(entries), _as_result(entries)
    )


def test_dropped_carry_round_loosens_pinned_bound(fixture_root):
    """One fewer carry round still proves (peak 4056 ≤ 4095) — only the
    manifest pin catches the silently loosened bound."""
    _perturb(
        fixture_root,
        "ops/limbs.py",
        "        x = _carry_round(_carry_round(x))\n"
        "        return x[..., : self.L]",
        "        x = _carry_round(x)\n"
        "        return x[..., : self.L]",
    )
    entries = _analyze(fixture_root, *KERNELS)
    entry = entries["limbs.mul:out-invariant"]
    assert entry["proved"]  # within ±4095 — capacity alone can't see it
    pinned_peak = int(_pinned()["limbs.mul:out-invariant"]["peak"])
    assert int(entry["peak"]) > pinned_peak
    diffs = rc.diff_manifest(
        _restricted_manifest(entries), _as_result(entries)
    )
    weakened = [
        msg
        for msg, ob in diffs
        if ob is not None and ob.key == "limbs.mul:out-invariant"
    ]
    assert len(weakened) == 1
    assert "weakened" in weakened[0]
    assert f"{pinned_peak} -> {entry['peak']}" in weakened[0]


def test_limb_bits_overflows_int32_conv(fixture_root):
    """Widening the limb radix breaks the 38·(2¹²−1)² < 2³¹ headroom:
    the convolution obligation must go unproved with a flow into
    ``_conv``."""
    _perturb(fixture_root, "ops/limbs.py", "LIMB_BITS = 11", "LIMB_BITS = 12")
    entries = _analyze(fixture_root, "limbs.mul")
    entry = entries["limbs.mul:cap-int32"]
    assert not entry["proved"]
    assert int(entry["peak"]) > 2**31 - 1
    assert entry["site"][0] == "ops/limbs.py"
    assert entry["site"][2] == "_conv"
    assert "_conv" in _flow_functions(entry)
    diffs = rc.diff_manifest(
        _restricted_manifest(entries), _as_result(entries)
    )
    assert any(
        msg.startswith("unproved obligation limbs.mul:cap-int32")
        for msg, _ob in diffs
    )


def test_max_k_overflows_fr_accumulator(fixture_root):
    """Raising the batched-matmul K cap past the proved 255²·k·33 < 2³¹
    budget must surface as an unproved fr accumulator obligation."""
    _perturb(fixture_root, "ops/fr_jax.py", "_MAX_K = 971", "_MAX_K = 2000")
    entries = _analyze(fixture_root, "fr.matmul")
    entry = entries["fr.matmul:cap-int32"]
    assert not entry["proved"]
    assert int(entry["peak"]) > 2**31 - 1
    assert entry["site"][0] == "ops/fr_jax.py"
    assert "_matmul_limbs" in _flow_functions(entry)


def test_fewer_folds_breaks_canonical_slice(fixture_root):
    """Shrinking the fold loop leaves nonzero digits above the canonical
    width: the slice-exact obligation fails with a flow through
    ``_fold_once``."""
    _perturb(
        fixture_root,
        "ops/fr_jax.py",
        "    for _ in range(3):\n        d = _fold_once(d)",
        "    for _ in range(1):\n        d = _fold_once(d)",
    )
    entries = _analyze(fixture_root, "fr.matmul")
    entry = entries["fr.matmul:slice-exact"]
    assert not entry["proved"]
    assert int(entry["peak"]) > 0
    assert entry["site"][0] == "ops/fr_jax.py"
    assert "_fold_once" in _flow_functions(entry)
    # The untouched limb kernel must not start failing collaterally.
    limb_entries = _analyze(fixture_root, "limbs.mul")
    assert all(e["proved"] for e in limb_entries.values())
