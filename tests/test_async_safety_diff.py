"""Fix↔lint differential suite for the badgerlint v4 async rules.

This PR's async-safety pass flagged every event-loop hazard on the
serving planes and each got a fix (executor offloads in the TCP pump
and input path, the fleet poller and the load generator, a cooperative
yield in replay, a narrowed catch in the metrics exporter).  These
tests pin that the *static* pass keeps covering every one of them:
each test copies the serving planes into a fixture, reverts exactly
one fix by text substitution, runs the async rules over the reverted
tree, and asserts the right rule reports the right root→sink chain —
file, coroutine, and sink class.

The unreverted copy is asserted clean once up front, so a failure
here means the revert (and only the revert) re-opened the hole.
"""

import json
import os
import shutil

import pytest

from hbbft_tpu.analysis import all_rules, lint_paths
from hbbft_tpu.analysis.cli import main as cli_main

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PKG = os.path.join(REPO, "hbbft_tpu")

# the serving planes plus everything their coroutine chains reach: the
# WAL/checkpoint bodies (recover/) are the blocking sinks the rules
# must see, and obs/ carries the poller and the exporter
_SCOPE_DIRS = ("transport", "serve", "obs", "recover")

ASYNC_RULES = (
    "async-blocking",
    "task-leak",
    "await-holding-lock",
    "cancellation-safety",
)


def _rules():
    return [r for r in all_rules() if r.name in ASYNC_RULES]


def _copy_scope(tmp_path):
    dst = tmp_path / "hbbft_tpu"
    for d in _SCOPE_DIRS:
        shutil.copytree(
            os.path.join(PKG, d),
            dst / d,
            ignore=shutil.ignore_patterns("__pycache__"),
        )
    return dst


def _revert_and_lint(tmp_path, relpath, old, new):
    """Apply one textual fix-revert and run the async rules over the
    tree."""
    root = _copy_scope(tmp_path)
    target = root / relpath
    text = target.read_text()
    assert old in text, (
        f"fix text not found in {relpath} — the differential revert "
        "needs updating alongside the fix"
    )
    target.write_text(text.replace(old, new))
    violations, errors = lint_paths([str(root)], _rules())
    assert not errors
    return violations


def test_unreverted_scope_copy_is_clean(tmp_path):
    root = _copy_scope(tmp_path)
    violations, errors = lint_paths([str(root)], _rules())
    assert not errors
    assert violations == []


# ---------------------------------------------------------------------------
# async-blocking: the executor offloads
# ---------------------------------------------------------------------------

_TCP_INPUT_FIXED = """\
        loop = asyncio.get_event_loop()
        async with self._algo_lock:
            step = await loop.run_in_executor(
                None, self.algo.handle_input, value
            )
            await self._route(step)
"""

_TCP_INPUT_REVERTED = """\
        async with self._algo_lock:
            step = self.algo.handle_input(value)
            await self._route(step)
"""


def test_tcp_input_offload_revert_redetects(tmp_path):
    # pre-fix: handle_input (threshold encryption + WAL fsync) ran
    # inline on the loop
    violations = _revert_and_lint(
        tmp_path, "transport/tcp.py", _TCP_INPUT_FIXED, _TCP_INPUT_REVERTED
    )
    hits = [
        v
        for v in violations
        if v.rule == "async-blocking"
        and v.path == "transport/tcp.py"
        and "input()" in v.message
    ]
    assert hits, violations
    # the seam bridges self.algo.handle_input to the WAL body and the
    # flow walks root → seam → sink
    assert any("append_input" in v.message for v in hits)
    flagged = next(v for v in hits if "append_input" in v.message)
    notes = " | ".join(note for _, _, note in flagged.flow)
    assert "event loop" in notes
    assert "handle_input" in notes
    assert "blocking" in notes


def test_tcp_pump_offload_revert_redetects(tmp_path):
    # pre-fix: the pump dispatched handle_message (combine/verify
    # crypto + WAL append) inline
    violations = _revert_and_lint(
        tmp_path,
        "transport/tcp.py",
        "                try:\n"
        "                    step = await loop.run_in_executor(\n"
        "                        None, self.algo.handle_message, sender, message\n"
        "                    )\n"
        "                except Exception:",
        "                try:\n"
        "                    step = self.algo.handle_message(sender, message)\n"
        "                except Exception:",
    )
    hits = [
        v
        for v in violations
        if v.rule == "async-blocking"
        and v.path == "transport/tcp.py"
        and "run()" in v.message
        and "handle_message" in v.message
    ]
    assert hits, violations
    assert any("append_message" in v.message for v in hits)


def test_fleet_poller_offload_revert_redetects(tmp_path):
    # pre-fix: poll_once appended JSONL rows with a sync open() on the
    # loop it shares with the nodes it scrapes
    violations = _revert_and_lint(
        tmp_path,
        "obs/fleet.py",
        "        if self.out_path is not None:\n"
        "            loop = asyncio.get_event_loop()\n"
        "            await loop.run_in_executor(None, self._append_rows, rows)\n",
        "        if self.out_path is not None:\n"
        "            self._append_rows(rows)\n",
    )
    hits = [
        v
        for v in violations
        if v.rule == "async-blocking"
        and v.path == "obs/fleet.py"
        and "poll_once()" in v.message
    ]
    assert hits, violations
    assert any("open()" in v.message for v in hits)
    assert any(
        "_append_rows" in note for v in hits for _, _, note in v.flow
    )


def test_loadgen_free_addrs_offload_revert_redetects(tmp_path):
    # pre-fix: the TCP load generator bound real sockets inline
    violations = _revert_and_lint(
        tmp_path,
        "serve/loadgen.py",
        "    # _free_addrs binds real sockets — sync syscalls, off the loop\n"
        "    loop = asyncio.get_event_loop()\n"
        "    addrs = await loop.run_in_executor(None, _free_addrs, n_validators + 1)\n",
        "    addrs = _free_addrs(n_validators + 1)\n",
    )
    hits = [
        v
        for v in violations
        if v.rule == "async-blocking"
        and v.path == "serve/loadgen.py"
        and "socket.socket" in v.message
    ]
    assert hits, violations
    assert any("via _free_addrs()" in v.message for v in hits)


def test_transfer_install_offload_revert_redetects(tmp_path):
    # pre-fix: the snapshot installer ran install_snapshot (WAL
    # checkpoint + fsync) inline.  The coroutine lives in
    # recover/transfer.py but the *root* is the transport recv loop —
    # every state-transfer control frame funnels through it — so the
    # finding anchors in transport/tcp.py with an interprocedural flow.
    violations = _revert_and_lint(
        tmp_path,
        "recover/transfer.py",
        "            if self._install_fn is not None:\n"
        "                step = await loop.run_in_executor(\n"
        "                    None, self._install_fn, self._target, batches\n"
        "                )\n"
        "            else:\n"
        "                step = await loop.run_in_executor(\n"
        "                    None, self.node.algo.install_snapshot, self._target, batches\n"
        "                )\n",
        "            if self._install_fn is not None:\n"
        "                step = self._install_fn(self._target, batches)\n"
        "            else:\n"
        "                step = self.node.algo.install_snapshot(self._target, batches)\n",
    )
    hits = [
        v
        for v in violations
        if v.rule == "async-blocking"
        and v.path == "transport/tcp.py"
        and "append_checkpoint" in v.message
    ]
    assert hits, violations
    notes = [note for v in hits for _, _, note in v.flow]
    assert any("_install" in n for n in notes)
    assert any("install_snapshot" in n for n in notes)


# ---------------------------------------------------------------------------
# task-leak: the dial tasks stay retained
# ---------------------------------------------------------------------------


def test_tcp_dial_retention_revert_redetects(tmp_path):
    violations = _revert_and_lint(
        tmp_path,
        "transport/tcp.py",
        "                self._tasks.append(\n"
        "                    asyncio.ensure_future(self._dial(peer))\n"
        "                )\n",
        "                asyncio.ensure_future(self._dial(peer))\n",
    )
    hits = [
        v
        for v in violations
        if v.rule == "task-leak" and v.path == "transport/tcp.py"
    ]
    assert hits, violations
    assert "fire-and-forget ensure_future()" in hits[0].message


# ---------------------------------------------------------------------------
# await-holding-lock: the hazard the _algo_lock design explicitly avoids
# ---------------------------------------------------------------------------


def test_blocking_under_algo_lock_redetects(tmp_path):
    # not a revert of a shipped fix but of the design rule the fix
    # established: the lock may be held across the executor hop, never
    # across an inline WAL append
    violations = _revert_and_lint(
        tmp_path,
        "transport/tcp.py",
        "        async with self._algo_lock:\n"
        "            step = await loop.run_in_executor(\n"
        "                None, self.algo.handle_input, value\n"
        "            )\n",
        "        async with self._algo_lock:\n"
        "            self.algo.wal.append_input(value)\n"
        "            step = await loop.run_in_executor(\n"
        "                None, self.algo.handle_input, value\n"
        "            )\n",
    )
    hits = [
        v for v in violations if v.rule == "await-holding-lock"
    ]
    assert hits, violations
    assert "append_input" in hits[0].message
    assert "asyncio lock 'self._algo_lock'" in hits[0].message


# ---------------------------------------------------------------------------
# cancellation-safety: the metrics exporter's narrowed catch
# ---------------------------------------------------------------------------


def test_metrics_cancelled_catch_revert_redetects(tmp_path):
    # pre-fix: the handler caught CancelledError alongside
    # ConnectionError, turning close()'s task cancellation into a no-op
    violations = _revert_and_lint(
        tmp_path,
        "obs/metrics.py",
        "        except ConnectionError:\n",
        "        except (ConnectionError, asyncio.CancelledError):\n",
    )
    hits = [
        v
        for v in violations
        if v.rule == "cancellation-safety" and v.path == "obs/metrics.py"
    ]
    assert hits, violations
    assert "swallows" in hits[0].message
    assert "_handle()" in hits[0].message


# ---------------------------------------------------------------------------
# the CLI surface: a reverted chain renders as SARIF codeFlows
# ---------------------------------------------------------------------------


def test_reverted_chain_renders_sarif_code_flows(tmp_path, capsys):
    root = _copy_scope(tmp_path)
    target = root / "transport/tcp.py"
    text = target.read_text()
    assert _TCP_INPUT_FIXED in text
    target.write_text(text.replace(_TCP_INPUT_FIXED, _TCP_INPUT_REVERTED))
    rc = cli_main(
        ["--format", "sarif", "--no-baseline", "--select", "async-blocking",
         str(root)]
    )
    sarif = json.loads(capsys.readouterr().out)
    assert rc == 1
    results = sarif["runs"][0]["results"]
    flagged = [
        r
        for r in results
        if r["locations"][0]["physicalLocation"]["artifactLocation"]["uri"]
        == "transport/tcp.py"
        and "append_input" in r["message"]["text"]
    ]
    assert flagged, results
    (thread_flow,) = flagged[0]["codeFlows"][0]["threadFlows"]
    locs = thread_flow["locations"]
    assert len(locs) >= 2
    notes = " | ".join(l["location"]["message"]["text"] for l in locs)
    assert "event loop" in notes
    assert "blocking" in notes
