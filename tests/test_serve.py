"""Serving gateway tests — protocol, admission, core, consensus, TCP.

Covers the acceptance surface of the serving front door: total
validators and pre-allocation frame bounds, weighted-fair admission
with explicit backpressure, the exactly-once commit-ack ledger,
hostile-client attribution, bit-identity of the client path against a
direct-input twin, and the real-TCP load test (4 clients x 2 tenants
over an n=4 validator mesh).
"""

import asyncio
import random

import pytest

from hbbft_tpu.core.fault import FaultKind
from hbbft_tpu.core.network_info import NetworkInfo
from hbbft_tpu.core.serialize import SerializationError, dumps, loads
from hbbft_tpu.core.step import Step
from hbbft_tpu.protocols.transaction_queue import TransactionQueue
from hbbft_tpu.serve.gateway import AdmissionQueues, GatewayAlgo, GatewayCore
from hbbft_tpu.serve.protocol import (
    CLIENT_MAX_FRAME,
    LEN_BYTES,
    MAX_PAYLOAD,
    PROTO_VERSION,
    ClientHello,
    CommitAck,
    HelloAck,
    ProtocolError,
    SubmitAck,
    SubmitTx,
    TxGossip,
    decode_tx,
    encode_tx,
    frame,
    read_frame,
    validate_commit_ack,
    validate_gossip,
    validate_hello,
    validate_hello_ack,
    validate_submit,
    validate_submit_ack,
)


def _tx(tenant, n):
    return encode_tx(tenant, "c0", n, b"p%d" % n)


# ---------------------------------------------------------------------------
# admission: weighted fairness + explicit backpressure
# ---------------------------------------------------------------------------


def test_weighted_fair_drain_respects_weights():
    adm = AdmissionQueues(weights={"heavy": 2, "light": 1})
    for i in range(6):
        assert adm.offer("heavy", _tx("heavy", i))[0]
        assert adm.offer("light", _tx("light", i))[0]
    out = adm.take(6)
    by_tenant = [decode_tx(tx)[0] for tx in out]
    # sorted tenants, cursor 0: heavy x2, light x1 per pass
    assert by_tenant == ["heavy", "heavy", "light", "heavy", "heavy", "light"]
    assert adm.total_depth() == 6


def test_drain_cursor_rotates_lead_tenant():
    adm = AdmissionQueues()
    for i in range(4):
        adm.offer("a", _tx("a", i))
        adm.offer("b", _tx("b", i))
    first = decode_tx(adm.take(1)[0])[0]
    second = decode_tx(adm.take(1)[0])[0]
    assert {first, second} == {"a", "b"}  # equal weights alternate lead


def test_tenant_full_is_explicit_backpressure_not_silent_drop():
    adm = AdmissionQueues(per_tenant_limit=2, retry_after_ms=50)
    assert adm.offer("t", _tx("t", 0)) == (True, 0, "ok")
    assert adm.offer("t", _tx("t", 1)) == (True, 0, "ok")
    ok, retry, detail = adm.offer("t", _tx("t", 2))
    assert (ok, retry, detail) == (False, 50, "tenant-full")
    # the other tenant still has headroom
    assert adm.offer("u", _tx("u", 0))[0]


def test_gateway_full_backs_off_harder():
    adm = AdmissionQueues(per_tenant_limit=10, global_limit=2, retry_after_ms=50)
    adm.offer("a", _tx("a", 0))
    adm.offer("b", _tx("b", 0))
    ok, retry, detail = adm.offer("c", _tx("c", 0))
    assert (ok, retry, detail) == (False, 100, "gateway-full")


def test_drain_empties_queues_and_depth_tracks():
    adm = AdmissionQueues()
    for i in range(5):
        adm.offer("t", _tx("t", i))
    got = adm.take(100)
    assert len(got) == 5
    assert adm.total_depth() == 0
    assert adm.take(10) == []


# ---------------------------------------------------------------------------
# framing: bounds enforced before allocation, clean exception taxonomy
# ---------------------------------------------------------------------------


def _fed_reader(stream: bytes) -> asyncio.StreamReader:
    reader = asyncio.StreamReader()
    reader.feed_data(stream)
    reader.feed_eof()
    return reader


def test_read_frame_round_trip():
    async def run():
        msg = SubmitTx(7, b"payload")
        got, size = await read_frame(_fed_reader(frame(msg)))
        assert got == msg and size == len(dumps(msg))

    asyncio.run(run())


def test_read_frame_rejects_oversized_header_before_allocation():
    async def run():
        lying = (CLIENT_MAX_FRAME + 1).to_bytes(LEN_BYTES, "big")
        with pytest.raises(ProtocolError):
            await read_frame(_fed_reader(lying + b"\x00"))

    asyncio.run(run())


def test_read_frame_raises_serialization_error_on_garbage():
    async def run():
        garbage = b"\xff\xfe\xfd\xfc"
        stream = len(garbage).to_bytes(LEN_BYTES, "big") + garbage
        with pytest.raises(SerializationError):
            await read_frame(_fed_reader(stream))

    asyncio.run(run())


def test_read_frame_raises_incomplete_on_truncation():
    async def run():
        full = frame(SubmitTx(0, b"xxxx"))
        with pytest.raises(asyncio.IncompleteReadError):
            await read_frame(_fed_reader(full[:-2]))

    asyncio.run(run())


def test_frame_refuses_oversized_outbound():
    with pytest.raises(ProtocolError):
        frame(SubmitTx(0, bytes(CLIENT_MAX_FRAME + 1)))


def test_validators_are_total():
    hostile = [
        None, True, 0, -1, 2**80, b"", b"\x00" * 8, "", "x" * 200,
        (), (1, 2), [], {}, object(),
        ClientHello("1", None, b"x"), SubmitTx(True, "not-bytes"),
        SubmitAck(-1, "yes", None, 0), CommitAck(None, "e"),
        HelloAck(1, None, -5), TxGossip([b"list-not-tuple"]),
        TxGossip(()), TxGossip((b"",)),
    ]
    for v in (
        validate_hello, validate_submit, validate_gossip,
        validate_hello_ack, validate_submit_ack, validate_commit_ack,
    ):
        for msg in hostile:
            assert v(msg) is False, (v.__name__, msg)
    assert validate_hello(ClientHello(PROTO_VERSION, "t", "c"))
    assert validate_submit(SubmitTx(0, b""))
    assert validate_gossip(TxGossip((b"x",)))
    assert validate_hello_ack(HelloAck(True, "ok", MAX_PAYLOAD))
    assert validate_submit_ack(SubmitAck(0, False, 50, "tenant-full"))
    assert validate_commit_ack(CommitAck(0, 0))
    # a bool is an int subclass but not a sequence number
    assert not validate_submit(SubmitTx(False, b""))


def test_envelope_round_trip_and_totality():
    tx = encode_tx("tenant", "client", 9, b"payload")
    assert decode_tx(tx) == ("tenant", "client", 9, b"payload")
    assert decode_tx(b"\xff\xfe") is None
    assert decode_tx("not-bytes") is None
    assert decode_tx(dumps((1, 2))) is None
    assert decode_tx(dumps(("t", "c", True, b""))) is None


# ---------------------------------------------------------------------------
# the sans-IO core: sessions, exactly-once ledger, attribution
# ---------------------------------------------------------------------------


def test_core_happy_path_exactly_once_ack():
    core = GatewayCore()
    replies, drop = core.on_hello("conn", ClientHello(1, "t", "c"))
    assert not drop and replies[0].ok
    replies, drop = core.on_submit("conn", SubmitTx(3, b"pay"), 1.0)
    assert not drop and replies[0].admitted
    (tx,) = core.drain(10)
    assert decode_tx(tx) == ("t", "c", 3, b"pay")
    got = core.on_committed(tx, 5, 2.5)
    assert got == ("conn", CommitAck(3, 5), 1.5)
    # duplicates across proposer samples: acked exactly once
    assert core.on_committed(tx, 5, 2.5) is None
    # foreign transactions from other proposers: ignored
    assert core.on_committed(b"foreign", 5, 2.5) is None
    assert core.on_committed(None, 5, 2.5) is None
    assert core.commits == 1 and core.drops == []


def test_core_duplicate_submit_is_idempotent():
    core = GatewayCore()
    core.on_hello("conn", ClientHello(1, "t", "c"))
    core.on_submit("conn", SubmitTx(0, b"p"), 0.0)
    replies, drop = core.on_submit("conn", SubmitTx(0, b"p"), 0.1)
    assert not drop and replies[0].admitted and replies[0].detail == "duplicate"
    assert core.admitted == 1
    assert len(core.drain(10)) == 1  # queued once


def test_core_attributes_every_hostile_class():
    core = GatewayCore()
    _, drop = core.on_hello("lie", ClientHello(2, "t", "c"))
    assert drop
    _, drop = core.on_submit("early", SubmitTx(0, b"p"), 0.0)
    assert drop
    core.on_hello("big", ClientHello(1, "t", "c"))
    _, drop = core.on_submit("big", SubmitTx(0, bytes(MAX_PAYLOAD + 1)), 0.0)
    assert drop
    core.on_bad_frame("garbage")
    core.on_timeout("loris")
    core.on_hello("twice", ClientHello(1, "t", "c"))
    _, drop = core.on_hello("twice", ClientHello(1, "t", "c"))
    assert drop
    assert core.drops == [
        ("lie", "bad-hello"),
        ("early", "submit-before-hello"),
        ("big", "bad-submit"),
        ("garbage", "malformed-frame"),
        ("loris", "slow-loris"),
        ("twice", "double-hello"),
    ]
    # dropped sessions are gone: the next submit is submit-before-hello
    _, drop = core.on_submit("big", SubmitTx(1, b"p"), 0.0)
    assert drop and core.drops[-1] == ("big", "submit-before-hello")


def test_core_reject_carries_retry_after():
    core = GatewayCore(AdmissionQueues(per_tenant_limit=1, retry_after_ms=75))
    core.on_hello("conn", ClientHello(1, "t", "c"))
    core.on_submit("conn", SubmitTx(0, b"a"), 0.0)
    replies, drop = core.on_submit("conn", SubmitTx(1, b"b"), 0.0)
    assert not drop  # backpressure is not an offence
    assert replies[0] == SubmitAck(1, False, 75, "tenant-full")
    assert core.rejected == 1


def test_core_emits_registered_obs_events(tmp_path):
    from hbbft_tpu.obs import recorder as _obs
    from hbbft_tpu.obs.schema import EVENTS

    rec = _obs.enable(str(tmp_path / "trace.jsonl"))
    try:
        core = GatewayCore(AdmissionQueues(per_tenant_limit=1))
        core.on_hello("conn", ClientHello(1, "t", "c"))
        core.on_submit("conn", SubmitTx(0, b"a"), 0.0)
        core.on_submit("conn", SubmitTx(1, b"b"), 0.0)
        (tx,) = core.drain(10)
        core.on_committed(tx, 0, 1.0)
        events = [e for e in rec.events if isinstance(e, dict)]
    finally:
        _obs.disable()
    seen = {e.get("ev") for e in events}
    for ev in ("gateway_admit", "gateway_reject", "client_commit_latency", "queue_depth"):
        assert ev in seen, f"missing {ev} in {seen}"
    for e in events:
        spec = EVENTS.get(e.get("ev"))
        if spec is None:
            continue
        fields = set(e) - {"ev", "t"}
        assert spec.required <= fields, (e.get("ev"), fields)
        if not spec.open:
            assert fields <= spec.allowed, (e.get("ev"), fields)


# ---------------------------------------------------------------------------
# TransactionQueue.remove_all: set fast path + unhashable fallback
# ---------------------------------------------------------------------------


def test_remove_all_set_fast_path():
    q = TransactionQueue([b"a", b"b", b"c", b"b"])
    q.remove_all(tx for tx in [b"b"])  # generator: must materialize once
    assert list(q.queue) == [b"a", b"c"]


def test_remove_all_unhashable_batch_does_not_crash():
    q = TransactionQueue([b"a", b"b", b"c"])
    q.remove_all([b"b", [1, 2]])  # unhashable committed tx from a peer
    assert list(q.queue) == [b"a", b"c"]


def test_remove_all_unhashable_queue_entry():
    marker = [1]
    q = TransactionQueue([b"a", marker, b"b"])
    q.remove_all([b"a", marker])
    assert list(q.queue) == [b"b"]


# ---------------------------------------------------------------------------
# GatewayAlgo: gossip intercept + attribution
# ---------------------------------------------------------------------------


def _new_algo_map(n=4, seed=0x6A7E):
    from hbbft_tpu.protocols.dynamic_honey_badger import DynamicHoneyBadger
    from hbbft_tpu.protocols.queueing_honey_badger import QueueingHoneyBadger

    rng = random.Random(seed)
    netinfos = NetworkInfo.generate_map(list(range(n)), rng, mock=True)
    algos = {}
    for nid, ni in netinfos.items():
        arng = random.Random(f"ga-{nid}")
        algos[nid] = GatewayAlgo(
            QueueingHoneyBadger(DynamicHoneyBadger(ni, rng=arng), batch_size=8, rng=arng)
        )
    return algos


def test_gateway_algo_attributes_invalid_gossip():
    algo = _new_algo_map()[0]
    for bad in (TxGossip(b"not-a-tuple"), TxGossip(()), TxGossip(("str",))):
        step = algo.handle_message(1, bad)
        assert isinstance(step, Step)
        faults = list(step.fault_log)
        assert len(faults) == 1
        assert faults[0].node_id == 1
        assert faults[0].kind == FaultKind.INVALID_MESSAGE
    assert len(algo.qhb.queue) == 0  # nothing hostile was queued


def test_gateway_algo_queues_valid_gossip_and_relays_input():
    algos = _new_algo_map()
    batch = (encode_tx("t", "c", 0, b"x"), encode_tx("t", "c", 1, b"y"))
    step = algos[0].handle_input(TxGossip(batch))
    assert isinstance(step, Step)
    assert len(algos[0].qhb.queue) == 2
    relayed = [tm for tm in step.messages if isinstance(tm.message, TxGossip)]
    assert len(relayed) == 1 and relayed[0].target.is_all
    step = algos[1].handle_message(0, TxGossip(batch))
    assert isinstance(step, Step) and not list(step.fault_log)
    assert len(algos[1].qhb.queue) == 2


def test_gateway_algo_rejects_invalid_local_input():
    algo = _new_algo_map()[0]
    with pytest.raises(ValueError):
        algo.handle_input(TxGossip(b"nope"))


# ---------------------------------------------------------------------------
# bit-identity: the client path against a direct-input twin
# ---------------------------------------------------------------------------


def _run_gossip_consensus(batch, n=4, seed=0x71D3):
    from hbbft_tpu.harness.network import (
        MessageScheduler,
        SilentAdversary,
        TestNetwork,
    )
    from hbbft_tpu.protocols.dynamic_honey_badger import DynamicHoneyBadger
    from hbbft_tpu.protocols.queueing_honey_badger import QueueingHoneyBadger

    rng = random.Random(seed)

    def new_algo(ni):
        arng = random.Random(f"twin-{ni.our_id}")
        return GatewayAlgo(
            QueueingHoneyBadger(DynamicHoneyBadger(ni, rng=arng), batch_size=8, rng=arng)
        )

    net = TestNetwork(
        n,
        0,
        lambda adv: SilentAdversary(MessageScheduler(MessageScheduler.RANDOM, rng)),
        new_algo,
        rng,
        mock_crypto=True,
    )
    net.input(0, TxGossip(batch))
    for _ in range(200_000):
        if all(nd.outputs for nd in net.nodes.values()):
            break
        if net.any_busy():
            net.step()
            continue
        for nid, nd in net.nodes.items():
            step = nd.instance.propose()
            if not step.is_empty():
                nd._absorb(step)
                msgs = list(nd.messages)
                nd.messages.clear()
                net.dispatch_messages(nid, msgs)
        if not net.any_busy():
            break
    assert all(nd.outputs for nd in net.nodes.values()), "consensus stalled"

    def key(b):
        return (
            b.epoch,
            tuple(sorted((str(k), tuple(v)) for k, v in b.contributions.items())),
            repr(b.change),
        )

    keys = [key(nd.outputs[0]) for _, nd in sorted(net.nodes.items())]
    assert len(set(keys)) == 1, "validators disagree"
    return keys[0]


def test_client_path_bit_identical_to_direct_input_twin():
    # leg 1: transactions enter through the full client path — framed
    # bytes, the codec, the validators, admission, weighted drain
    core = GatewayCore(AdmissionQueues(weights={"alpha": 2, "beta": 1}))
    plan = [
        ("alpha", "a0", 0, b"pay-a0"),
        ("beta", "b0", 0, b"pay-b0"),
        ("alpha", "a1", 0, b"pay-a1"),
        ("alpha", "a0", 1, b"pay-a2"),
        ("beta", "b0", 1, b"pay-b1"),
    ]
    for tenant, cid, _, _ in plan:
        conn = f"{tenant}/{cid}"
        if conn not in core.sessions:
            buf = frame(ClientHello(PROTO_VERSION, tenant, cid))
            core.on_hello(conn, loads(buf[LEN_BYTES:]))
    for i, (tenant, cid, seq, payload) in enumerate(plan):
        buf = frame(SubmitTx(seq, payload))
        replies, drop = core.on_submit(f"{tenant}/{cid}", loads(buf[LEN_BYTES:]), float(i))
        assert not drop and replies[0].admitted
    gateway_batch = tuple(core.drain(64))

    # leg 2: the direct-input twin — the same envelopes, no gateway
    adm = AdmissionQueues(weights={"alpha": 2, "beta": 1})
    for tenant, cid, seq, payload in plan:
        adm.offer(tenant, encode_tx(tenant, cid, seq, payload))
    direct_batch = tuple(adm.take(64))

    assert gateway_batch == direct_batch  # byte-identical before consensus
    assert len(gateway_batch) == len(plan)

    # both batches drive identically-seeded networks: committed batches
    # must be bit-identical
    assert _run_gossip_consensus(gateway_batch) == _run_gossip_consensus(direct_batch)


def test_hostile_clients_scenario_is_green():
    from hbbft_tpu.harness.scenarios import ScenarioConfig, run_scenario

    res = run_scenario("hostile-clients", ScenarioConfig(n=5, epochs=1, seed=0xBAD0))
    assert res.ok, res.detail
    assert res.faults >= 7  # 6 hostile clients + the invalid gossiper


def test_fuzz_gateway_surface_pinned_seed():
    from hbbft_tpu.harness.fuzz import fuzz_gateway

    rep = fuzz_gateway(0xF0227 + 3, 120)
    assert rep.ok, rep.failures[:3]
    assert rep.cases == 120
    assert rep.rejected > 0 and rep.decoded > 0


# ---------------------------------------------------------------------------
# the real thing: concurrent clients, real TCP mesh, exactly-once
# ---------------------------------------------------------------------------


def test_tcp_load_exactly_once_across_tenants():
    """Acceptance load test: 4 concurrent clients x 2 tenants through a
    real n=4 TCP mesh; every admitted transaction is committed exactly
    once and acked, hostile-free run attributes nobody."""
    from hbbft_tpu.serve.loadgen import TenantSpec, run_tcp

    tenants = [
        TenantSpec("alpha", weight=2, clients=2, rate_hz=40.0, mean_payload=96),
        TenantSpec("beta", weight=1, clients=2, rate_hz=40.0, arrival="bursty", mean_payload=96),
    ]
    summary = run_tcp(tenants, n_validators=4, duration_s=1.5, seed=0xACCE)
    assert summary["errors"] == []
    assert summary["committed"] > 0
    assert summary["unacked"] == 0, summary
    assert summary["duplicate_acks"] == 0
    assert summary["gateway_drops"] == []
    assert summary["admitted"] == summary["committed"]
    assert summary["commit_p99_s"] >= summary["commit_p50_s"] > 0


def test_gateway_shell_attributes_hostile_sockets():
    """Real sockets, hostile clients only: malformed handshake and an
    oversized header must be attributed and disconnected without
    touching the mesh or crashing the listener."""
    from hbbft_tpu.serve.loadgen import _free_addrs, _new_algo_factory
    from hbbft_tpu.serve.gateway import Gateway
    from hbbft_tpu.transport.tcp import TcpNode

    async def run():
        addrs = _free_addrs(5)
        client_addr, mesh = addrs[0], addrs[1:]
        new_algo = _new_algo_factory(8)
        nodes = [TcpNode(a, [x for x in mesh if x != a], new_algo) for a in mesh]
        core = GatewayCore()
        gw = Gateway(nodes[0], client_addr, core=core, handshake_timeout=0.4)
        await asyncio.gather(*(n.start() for n in nodes))
        await gw.start()
        run_tasks = [asyncio.ensure_future(n.run(until=lambda nd: False)) for n in nodes]
        host, port = client_addr.rsplit(":", 1)

        # malformed handshake bytes
        r, w = await asyncio.open_connection(host, int(port))
        garbage = b"\xde\xad\xbe\xef"
        w.write(len(garbage).to_bytes(LEN_BYTES, "big") + garbage)
        await w.drain()
        assert await r.read(64) == b""  # disconnected
        w.close()

        # oversized header
        r, w = await asyncio.open_connection(host, int(port))
        w.write((CLIENT_MAX_FRAME + 1).to_bytes(LEN_BYTES, "big"))
        await w.drain()
        assert await r.read(64) == b""
        w.close()

        # slow-loris: connect and send nothing past the deadline
        r, w = await asyncio.open_connection(host, int(port))
        assert await asyncio.wait_for(r.read(64), 5.0) == b""
        w.close()

        # an honest client still gets served after all that
        r, w = await asyncio.open_connection(host, int(port))
        w.write(frame(ClientHello(PROTO_VERSION, "t", "c")))
        await w.drain()
        ack, _ = await asyncio.wait_for(read_frame(r), 5.0)
        assert validate_hello_ack(ack) and ack.ok
        w.close()

        for t in run_tasks:
            t.cancel()
        await asyncio.gather(*run_tasks, return_exceptions=True)
        await gw.close()
        await asyncio.gather(*(n.close() for n in nodes[1:]))
        return core

    core = asyncio.run(run())
    reasons = sorted(reason for _, reason in core.drops)
    assert reasons == ["bad-handshake", "bad-handshake", "slow-loris"], core.drops
