"""Core L1 runtime tests: Step combinators, FaultLog, NetworkInfo."""

import random

import pytest

from hbbft_tpu import (
    Fault,
    FaultKind,
    FaultLog,
    NetworkInfo,
    Step,
    Target,
    TargetedMessage,
)


class TestTarget:
    def test_all_vs_node(self):
        assert Target.all().is_all
        assert not Target.to(3).is_all
        assert Target.to(3) == Target.to(3)
        assert Target.to(3) != Target.to(4)
        with pytest.raises(ValueError):
            Target.to(None)

    def test_message_map(self):
        tm = Target.to(1).message(("Echo", b"x"))
        tm2 = tm.map(lambda m: ("Wrapped", m))
        assert tm2.target == Target.to(1)
        assert tm2.message == ("Wrapped", ("Echo", b"x"))


class TestStep:
    def test_extend_with_wraps_messages(self):
        child = Step(output=["out"])
        child.send_all("inner")
        child.add_fault(9, FaultKind.INVALID_PROOF)
        parent: Step = Step()
        outputs = parent.extend_with(child, lambda m: ("wrap", m))
        assert outputs == ["out"]
        assert parent.messages[0].message == ("wrap", "inner")
        assert len(parent.fault_log) == 1

    def test_extend_merges(self):
        a = Step(output=[1])
        b = Step(output=[2])
        b.send_to(5, "m")
        a.extend(b)
        assert a.output == [1, 2]
        assert len(a.messages) == 1

    def test_is_empty(self):
        assert Step().is_empty()
        assert not Step.with_output(1).is_empty()
        assert not Step.from_fault(1, FaultKind.MULTIPLE_ECHOS).is_empty()


class TestFaultLog:
    def test_merge(self):
        a = FaultLog.init(1, FaultKind.DUPLICATE_BVAL)
        b = FaultLog.init(2, FaultKind.DUPLICATE_AUX)
        a.merge(b)
        assert len(a) == 2
        assert {f.node_id for f in a} == {1, 2}


class TestNetworkInfo:
    def test_basic_topology(self):
        rng = random.Random(1)
        nis = NetworkInfo.generate_map(range(7), rng, mock=True)
        ni = nis[3]
        assert ni.num_nodes == 7
        assert ni.num_faulty == 2
        assert ni.num_correct == 5
        assert ni.node_index(0) == 0 and ni.node_index(6) == 6
        assert ni.is_validator
        assert ni.invocation_id() == nis[0].invocation_id()

    def test_observer(self):
        rng = random.Random(2)
        nis = NetworkInfo.generate_map(range(4), rng, mock=True)
        obs = nis[0].observer_view("observer")
        assert not obs.is_validator
        assert obs.our_index is None
        assert obs.num_nodes == 4
        assert obs.public_key_share(2) is not None

    def test_f_bound_small_networks(self):
        rng = random.Random(3)
        for n, f in [(1, 0), (2, 0), (3, 0), (4, 1), (7, 2), (10, 3)]:
            nis = NetworkInfo.generate_map(range(n), rng, mock=True)
            assert nis[0].num_faulty == f
