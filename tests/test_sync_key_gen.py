"""DKG tests (mirrors ``tests/sync_key_gen.rs``): run the dealerless
key generation fully in memory — handling only t+1 Parts and 2t+1 Acks
per part — then verify the generated threshold keys actually work
(sign/combine/verify and encrypt/decrypt round-trips)."""

import random

import pytest

from hbbft_tpu.crypto import mock as M
from hbbft_tpu.crypto import threshold as T
from hbbft_tpu.protocols.sync_key_gen import Ack, Part, SyncKeyGen


def run_dkg(n: int, mock: bool, rng, handle_parts=None):
    threshold = (n - 1) // 3
    key_cls = M.MockSecretKey if mock else T.SecretKey
    sec_keys = {i: key_cls.random(rng) for i in range(n)}
    pub_keys = {i: sk.public_key() for i, sk in sec_keys.items()}
    nodes = {
        i: SyncKeyGen(i, sec_keys[i], pub_keys, threshold, rng)
        for i in range(n)
    }
    # handle only the first `handle_parts` parts (default: t+1 — the
    # minimum for security), mirroring the reference test
    k = handle_parts if handle_parts is not None else threshold + 1
    proposers = list(range(k))
    acks = []  # (acker, ack)
    for proposer in proposers:
        part = nodes[proposer].our_part
        for i in range(n):
            ack, faults = nodes[i].handle_part(proposer, part, rng)
            assert faults.is_empty()
            if i < 2 * threshold + 1:  # only 2t+1 nodes ack
                assert ack is not None
                acks.append((i, ack))
    for acker, ack in acks:
        for i in range(n):
            faults = nodes[i].handle_ack(acker, ack)
            assert faults.is_empty()
    for i in range(n):
        assert nodes[i].is_ready()
    results = {i: nodes[i].generate() for i in range(n)}
    return results, nodes


@pytest.mark.parametrize("n", [1, 2, 4, 7])
def test_dkg_mock(n):
    rng = random.Random(50 + n)
    results, _ = run_dkg(n, True, rng)
    # everyone derives the same public key set
    pk_sets = {id(None): None}
    first_pk = results[0][0]
    for i, (pk_set, sks) in results.items():
        assert pk_set == first_pk
        assert sks is not None
    # and the keys work
    shares = {i: results[i][1].sign(b"msg") for i in range(n)}
    sig = first_pk.combine_signatures(shares)
    assert first_pk.public_key().verify(sig, b"msg")


@pytest.mark.parametrize("n", [1, 4])
def test_dkg_real_bls(n):
    rng = random.Random(60 + n)
    results, _ = run_dkg(n, False, rng)
    threshold = (n - 1) // 3
    first_pk = results[0][0]
    for i, (pk_set, sks) in results.items():
        assert pk_set.commitment == first_pk.commitment
        assert pk_set.master_g1 == first_pk.master_g1
    # threshold signature round trip (reference tests/sync_key_gen.rs:37-81)
    msg = b"Test message!"
    shares = {i: results[i][1].sign(msg) for i in range(n)}
    for i in range(n):
        assert first_pk.public_key_share(i).verify_signature_share(
            shares[i], msg
        ), i
    sig = first_pk.combine_signatures(
        {i: shares[i] for i in list(range(n))[: threshold + 1]}
    )
    assert first_pk.verify_signature(sig, msg)
    # threshold encryption round trip against the DKG'd master key
    ct = first_pk.public_key().encrypt(b"post-dkg secret", rng)
    assert ct.verify()
    dec = {
        i: results[i][1].decrypt_share_no_verify(ct)
        for i in range(threshold + 1)
    }
    for i, d in dec.items():
        assert first_pk.public_key_share(i).verify_decryption_share(d, ct)
    assert (
        first_pk.combine_decryption_shares(dec, ct) == b"post-dkg secret"
    )


def test_dkg_observer_gets_public_keys():
    rng = random.Random(70)
    n, threshold = 4, 1
    sec_keys = {i: T.SecretKey.random(rng) for i in range(n)}
    pub_keys = {i: sk.public_key() for i, sk in sec_keys.items()}
    nodes = {
        i: SyncKeyGen(i, sec_keys[i], pub_keys, threshold, rng)
        for i in range(n)
    }
    obs = SyncKeyGen("observer", T.SecretKey.random(rng), pub_keys, threshold, rng)
    assert obs.our_part is None
    acks = []
    for proposer in range(threshold + 1):
        part = nodes[proposer].our_part
        o_ack, faults = obs.handle_part(proposer, part, rng)
        assert o_ack is None and faults.is_empty()
        for i in range(n):
            ack, _ = nodes[i].handle_part(proposer, part, rng)
            acks.append((i, ack))
    for acker, ack in acks:
        obs.handle_ack(acker, ack)
        for i in range(n):
            nodes[i].handle_ack(acker, ack)
    assert obs.is_ready()
    pk_obs, sks_obs = obs.generate()
    pk_0, _ = nodes[0].generate()
    assert sks_obs is None
    assert pk_obs.commitment == pk_0.commitment


def test_dkg_faulty_dealer_detected():
    rng = random.Random(71)
    n, threshold = 4, 1
    sec_keys = {i: T.SecretKey.random(rng) for i in range(n)}
    pub_keys = {i: sk.public_key() for i, sk in sec_keys.items()}
    node = SyncKeyGen(1, sec_keys[1], pub_keys, threshold, rng)
    good = SyncKeyGen(0, sec_keys[0], pub_keys, threshold, rng)
    part = good.our_part
    # tamper: swap two encrypted rows so node 1 decrypts the wrong row
    rows = list(part.rows)
    rows[1], rows[2] = rows[2], rows[1]
    bad = Part(part.commit, tuple(rows), part.master_g1)
    ack, faults = node.handle_part(0, bad, rng)
    assert ack is None and not faults.is_empty()
