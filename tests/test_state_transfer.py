"""State-transfer tests — replay-bound eviction, quorum catch-up,
WAL compaction, and the bounded-memory GC paths.

The contract under test, plane by plane:

- **Replay bounds** (``transport/tcp.py``): the outbound replay buffer
  is capped by frames *and* bytes; eviction is counted loudly
  (``wire.replay_evicted``) because it severs resume-exactness.
- **Escalation** (``recover/transfer.py``): a receive-side seq gap —
  the signature of eviction on the peer — escalates into a probe →
  quorum → fetch → verify → install state transfer instead of a
  permanently severed stream; inbound data frames are parked during
  the transfer and flushed after install, and the per-link applied
  seq is renumbered so acks/checkpoints continue contiguously.
- **WAL compaction** (``recover/wal.py``): dropping everything before
  the last checkpoint is invisible to recovery — the compacted log
  replays to a structurally identical state with identical resume
  seqs — and the ``HBBFT_TPU_WAL_COMPACT`` trigger + offline CLI both
  drive it.
- **Bounded memory** (``serve/gateway.py``, ``protocols/
  honey_badger.py``): the gateway's exactly-once ack ledger is aged by
  epoch GC without reopening the dedup window, and HoneyBadger's
  future-epoch queue is bounded per sender with drops counted and
  repeat offenders attributed.
"""

import asyncio
import random
import shutil

import pytest

from hbbft_tpu.harness.network import (
    MessageScheduler,
    SilentAdversary,
    TestNetwork,
)
from hbbft_tpu.harness.scenarios import _state_eq
from hbbft_tpu.obs import recorder as obs
from hbbft_tpu.protocols.honey_badger import (
    Batch,
    HoneyBadger,
    HoneyBadgerMessage,
)
from hbbft_tpu.recover import WalWriter, recover
from hbbft_tpu.recover import wal as wal_mod
from hbbft_tpu.recover.node import DurableAlgo
from hbbft_tpu.recover.transfer import (
    CatchupManager,
    SnapshotStore,
    encode_snapshot,
    snapshot_digest,
)
from hbbft_tpu.transport.tcp import SnapChunk, SnapDone, SnapMeta, TcpNode


class _NullAlgo:
    """Minimal sans-IO algorithm: absorbs everything, never outputs."""

    def __init__(self, ni):
        pass

    def handle_input(self, value):
        from hbbft_tpu.core.step import Step

        return Step()

    def handle_message(self, sender, message):
        from hbbft_tpu.core.step import Step

        return Step()

    def terminated(self):
        return False


class _CaptureWriter:
    def __init__(self):
        self.buf = b""

    def write(self, data):
        self.buf += data


def _route_n(node, n, size=64):
    """Route ``n`` broadcast frames of ``size``-byte payloads."""
    from hbbft_tpu.core.step import Step, Target

    async def run():
        for i in range(n):
            await node._route(
                Step(messages=[Target.all().message(b"%03d" % i + b"x" * size)])
            )

    asyncio.run(run())


# -- replay-buffer bounds ------------------------------------------------


def test_replay_byte_cap_evicts_and_counts():
    """The byte cap bounds the replay buffer independently of the frame
    cap, evicts oldest-first keeping a contiguous tail, and counts the
    evictions both globally and per-peer."""
    a, b = "127.0.0.1:1", "127.0.0.1:2"
    cap = 600
    sender = TcpNode(a, [b], _NullAlgo, replay_max_bytes=cap)
    rec = obs.enable()
    try:
        _route_n(sender, 20)
        evicted = rec.counters.get("wire.replay_evicted", 0)
        assert evicted == rec.counters.get(f"wire.replay_evicted.{b}", 0)
    finally:
        obs.disable()
    buf = sender._replay[b]
    assert sender._replay_bytes[b] <= cap
    assert sender._replay_bytes[b] == sum(len(f) for _, f in buf)
    # oldest-first eviction: what survives is the contiguous tail
    assert [s for s, _ in buf] == list(range(21 - len(buf), 21))
    assert evicted == 20 - len(buf) > 0


def test_replay_frame_cap_still_applies():
    a, b = "127.0.0.1:1", "127.0.0.1:2"
    sender = TcpNode(a, [b], _NullAlgo, replay_max_frames=4)
    _route_n(sender, 20)
    assert [s for s, _ in sender._replay[b]] == [17, 18, 19, 20]


# -- eviction escalates into a state transfer ----------------------------


def test_seq_gap_escalates_into_transfer_and_flushes_held():
    """A resume replay that starts past the receiver's high-water mark
    (the peer evicted the frames between) must escalate into a state
    transfer: the gap starts a probe, data frames delivered meanwhile
    are parked, a quorum-verified snapshot installs, the applied seq is
    renumbered under the first parked frame, and the parked frames are
    flushed to the inbox in arrival order."""
    a, b = "127.0.0.1:1", "127.0.0.1:2"
    installed = []

    async def run():
        sender = TcpNode(a, [b], _NullAlgo, replay_max_frames=4)
        receiver = TcpNode(b, [a], _NullAlgo)
        mgr = CatchupManager(
            receiver,
            0,  # n=2 toy mesh: f=0, a single offer is a quorum
            install_fn=lambda upto, batches: installed.append(
                (upto, list(batches))
            )
            or None,
            epoch_fn=lambda: 0,
        )
        receiver.transfer = mgr
        from hbbft_tpu.core.step import Step, Target

        payloads = [b"live-%02d" % i for i in range(20)]
        for p in payloads:
            await sender._route(Step(messages=[Target.all().message(p)]))
        # the receiver was dark for all 20; only 17..20 survive eviction
        w = _CaptureWriter()
        sender._resume_link(b, 0, w)
        reader = asyncio.StreamReader()
        reader.feed_data(w.buf)
        reader.feed_eof()
        await receiver._recv_loop(a, reader)
        # gap detected → probe in flight, every delivered frame parked
        assert mgr.state == mgr.PROBE
        assert receiver._inbox.empty()
        assert [m for _, m in mgr._held] == payloads[16:]
        # a peer answers the probe with a 2-epoch snapshot
        batches = [Batch(e, {0: [b"snap-%d" % e]}) for e in (0, 1)]
        payload = encode_snapshot(batches)
        digest = snapshot_digest(payload)
        await mgr.on_control(a, SnapMeta(0, 1, digest, len(payload), 1))
        assert mgr.state == mgr.FETCH
        await mgr.on_control(a, SnapChunk(0, 0, payload))
        await mgr.on_control(a, SnapDone(1, digest))
        assert mgr.state == mgr.IDLE
        # applied seq renumbered to just under the first parked frame:
        # everything below is covered by the snapshot, so acks and
        # checkpoints continue contiguously from the parked stream
        assert receiver._applied_seq[a] == 16
        flushed = []
        while not receiver._inbox.empty():
            flushed.append(receiver._inbox.get_nowait())
        assert flushed == [(a, m) for m in payloads[16:]]
        assert not receiver.faults

    rec = obs.enable()
    try:
        asyncio.run(run())
        assert rec.counters.get("wire.seq_gap", 0) >= 1
        assert rec.counters.get("wire.replay_evicted", 0) == 16
        assert rec.counters.get("st.installed", 0) == 1
    finally:
        obs.disable()
    assert len(installed) == 1
    upto, batches = installed[0]
    assert upto == 1 and [bt.epoch for bt in batches] == [0, 1]


def test_empty_offer_quorum_stands_down():
    """f+1 explicit "nothing newer" votes resolve a probe without a
    snapshot: the manager returns to idle and releases the parked
    frames instead of holding the inbox hostage."""

    async def run():
        a, b = "127.0.0.1:1", "127.0.0.1:2"
        receiver = TcpNode(b, [a], _NullAlgo)
        mgr = CatchupManager(receiver, 0, epoch_fn=lambda: 5)
        receiver.transfer = mgr
        await mgr.on_gap(a, 0, 40)
        assert mgr.state == mgr.PROBE
        mgr.hold(a, b"parked")
        await mgr.on_control(a, SnapMeta(5, 5, b"", 0, 0))
        assert mgr.state == mgr.IDLE
        assert receiver._inbox.get_nowait() == (a, b"parked")
        assert not receiver.faults

    rec = obs.enable()
    try:
        asyncio.run(run())
        assert rec.counters.get("st.noop", 0) == 1
    finally:
        obs.disable()


def test_snapshot_store_retention_bound():
    store = SnapshotStore(retain=3)
    for e in range(10):
        store.record(Batch(e, {0: [b"b%d" % e]}))
    assert len(store) == 3 and store.high() == 9
    assert store.slice(7, 9) is not None
    assert store.slice(5, 9) is None  # evicted epoch ⇒ refuse the range


# -- WAL compaction ------------------------------------------------------


def _durable_epoch_run(wal_path, seed):
    """One HoneyBadger epoch in TestNetwork with node 1 durable
    (checkpoint_every=1), so its WAL holds records both before and
    after the final checkpoint."""
    victim = 1
    rng = random.Random(seed)

    def new_algo(ni):
        algo = HoneyBadger(ni, rng=random.Random(f"cw-{ni.our_id}-{seed}"))
        if ni.our_id == victim:
            return DurableAlgo(
                algo, WalWriter(wal_path, fsync="off"), checkpoint_every=1
            )
        return algo

    net = TestNetwork(
        4,
        0,
        lambda adv: SilentAdversary(
            MessageScheduler(MessageScheduler.RANDOM, rng)
        ),
        new_algo,
        rng,
        mock_crypto=True,
    )
    for nid in sorted(net.nodes):
        node = net.nodes[nid]
        node.handle_input([b"cw-%03d" % nid])
        msgs = list(node.messages)
        node.messages.clear()
        net.dispatch_messages(nid, msgs)
    steps = 0
    while not all(nd.outputs for nd in net.nodes.values()):
        assert net.any_busy(), "network quiesced before batches"
        net.step()
        steps += 1
        assert steps < 400_000, "epoch stalled"
    net.nodes[victim].algo.wal.close()


def test_compacted_wal_replay_equals_full_replay(tmp_path, monkeypatch):
    """Satellite invariant: recovery from a compacted WAL reaches a
    state structurally equal to full-log replay, with identical resume
    receive seqs (compaction injects the dropped-prefix message counts
    into the surviving snapshot's meta)."""
    monkeypatch.delenv(wal_mod._COMPACT_ENV, raising=False)
    full = str(tmp_path / "full.wal")
    _durable_epoch_run(full, seed=4242)
    compacted = str(tmp_path / "compacted.wal")
    shutil.copyfile(full, compacted)
    dropped, reclaimed = wal_mod.compact_wal(compacted)
    assert dropped > 0 and reclaimed > 0
    a = recover(full)
    b = recover(compacted)
    assert _state_eq(a.algo, b.algo), "compacted replay diverges"
    assert a.recv_seqs == b.recv_seqs
    assert a.meta.get("send_seqs") == b.meta.get("send_seqs")
    # compaction is idempotent: nothing left before the snapshot
    assert wal_mod.compact_wal(compacted) == (0, 0)


def test_wal_compaction_trigger_env(tmp_path, monkeypatch):
    """``HBBFT_TPU_WAL_COMPACT`` arms the checkpoint-time trigger: a
    1-byte threshold compacts on every checkpoint append, ``off``
    disables the trigger entirely."""
    monkeypatch.setenv(wal_mod._COMPACT_ENV, "1")
    p = str(tmp_path / "auto.wal")
    rec = obs.enable()
    try:
        with WalWriter(p, fsync="off") as w:
            for i in range(3):
                w.append_input(i)
            w.append_checkpoint(b"state", {"send_seqs": {}})
        assert rec.counters.get("wal.compacted", 0) == 1
    finally:
        obs.disable()
    records, clean = wal_mod.read_records(p)
    assert clean and [r.kind for r in records] == [wal_mod.CHECKPOINT]

    monkeypatch.setenv(wal_mod._COMPACT_ENV, "off")
    p2 = str(tmp_path / "manual.wal")
    with WalWriter(p2, fsync="off") as w:
        for i in range(3):
            w.append_input(i)
        w.append_checkpoint(b"state", {})
    records, clean = wal_mod.read_records(p2)
    assert clean and len(records) == 4  # trigger disarmed


def test_wal_compaction_preserves_tail_records(tmp_path, monkeypatch):
    """Records *after* the last checkpoint survive compaction byte-for-
    byte — they are exactly what recovery replays."""
    monkeypatch.delenv(wal_mod._COMPACT_ENV, raising=False)
    p = str(tmp_path / "tail.wal")
    with WalWriter(p, fsync="off") as w:
        w.append_message("p0", ("pre", 1))
        w.append_checkpoint(b"s", {})
        w.append_message("p1", ("post", 2))
        w.append_input([b"post-input"])
    dropped, _ = wal_mod.compact_wal(p)
    assert dropped == 1
    records, clean = wal_mod.read_records(p)
    assert clean
    assert [r.kind for r in records] == [
        wal_mod.CHECKPOINT,
        wal_mod.MESSAGE,
        wal_mod.INPUT,
    ]
    assert wal_mod.decode_message(records[1].payload) == ("p1", ("post", 2))
    # the dropped prefix's per-sender counts moved into the meta
    _, meta = wal_mod.decode_checkpoint(records[0].payload)
    assert meta["recv_seqs"] == {"p0": 1}


def test_compact_cli(tmp_path, capsys):
    from hbbft_tpu.recover.__main__ import main

    p = str(tmp_path / "cli.wal")
    with WalWriter(p, fsync="off") as w:
        w.append_input(1)
        w.append_checkpoint(b"s", {})
    assert main(["--compact", p]) == 0
    out = capsys.readouterr().out
    assert "compacted" in out and "dropped 1 record" in out
    assert main(["--compact", str(tmp_path / "missing.wal")]) == 1


# -- bounded memory: gateway ack-ledger GC -------------------------------


def test_gateway_gc_ages_ack_ledger_without_reopening_dedup():
    from hbbft_tpu.serve.gateway import GatewayCore
    from hbbft_tpu.serve.protocol import ClientHello, SubmitTx

    core = GatewayCore()
    _, dropped = core.on_hello("c0", ClientHello(1, "alpha", "c0"))
    assert not dropped
    for s in range(5):
        replies, dropped = core.on_submit(
            "c0", SubmitTx(s, b"gc-tx-%d" % s), float(s)
        )
        assert not dropped and replies[0].admitted
    txs = core.drain(16)
    assert len(txs) == 5
    for ep, tx in enumerate(txs):
        assert core.on_committed(tx, ep, 10.0) is not None
    assert len(core.acked) == 5
    # a resubmission inside the keep window is deduped, not re-admitted
    replies, _ = core.on_submit("c0", SubmitTx(4, b"gc-tx-4"), 11.0)
    assert replies[0].admitted and not core.pending
    rec = obs.enable()
    try:
        assert core.gc_epochs(4, keep=2) == 3  # epochs 0..2 aged out
        assert rec.counters.get("gateway.gc_acked", 0) == 3
    finally:
        obs.disable()
    assert len(core.acked) == 2  # epochs 3, 4 still inside the window
    # past the window the tx is re-admitted (its old ack is long dead)
    replies, _ = core.on_submit("c0", SubmitTx(0, b"gc-tx-0"), 12.0)
    assert replies[0].admitted and len(core.pending) == 1
    assert core.gc_epochs("nonsense") == 0  # total on junk input


# -- bounded memory: HoneyBadger future-epoch queue ----------------------


def test_hb_future_drops_counted_and_attributed():
    """Messages beyond the queueing horizon are dropped with a counter
    and a schema row, and a flood from one sender is attributed on the
    32nd drop — memory stays bounded no matter what arrives."""
    from hbbft_tpu.core.fault import FaultKind
    from hbbft_tpu.core.network_info import NetworkInfo
    from hbbft_tpu.protocols import honey_badger as hb_mod

    nis = NetworkInfo.generate_map(
        list(range(4)), random.Random(7), mock=True
    )
    hb = HoneyBadger(nis[0])
    horizon = hb.max_future_epochs + hb_mod._FUTURE_HORIZON  # 3 + 64
    rec = obs.enable()
    try:
        faults = []
        for i in range(hb_mod._FUTURE_FAULT_EVERY):
            step = hb.handle_message(
                1, HoneyBadgerMessage(horizon + 1 + i, None)
            )
            faults.extend(step.fault_log)
        assert rec.counters.get("hb.future_dropped", 0) == 32
        rows = [e for e in rec.events if e["ev"] == "hb_future_drop"]
        assert len(rows) == 32 and rows[0]["node"] == "0"
        assert rows[-1]["drops"] == 32
    finally:
        obs.disable()
    # one drop is clock skew; the 32nd is a flood — exactly one fault
    assert [f.kind for f in faults] == [FaultKind.EPOCH_OUT_OF_RANGE]
    assert all(f.node_id == 1 for f in faults)
    assert not hb.incoming_queue  # nothing beyond the horizon queued


def test_hb_future_queue_bounded_per_sender():
    from hbbft_tpu.core.network_info import NetworkInfo
    from hbbft_tpu.protocols import honey_badger as hb_mod

    nis = NetworkInfo.generate_map(
        list(range(4)), random.Random(8), mock=True
    )
    hb = HoneyBadger(nis[0])
    cap = hb_mod._FUTURE_MAX_PER_SENDER
    rec = obs.enable()
    try:
        for i in range(cap + 5):
            hb.handle_message(2, HoneyBadgerMessage(10, ("q", i)))
        assert rec.counters.get("hb.future_dropped", 0) == 5
    finally:
        obs.disable()
    # exactly `cap` queued for the sender, the overflow dropped
    assert hb._future_queued[2] == cap
    assert len(hb.incoming_queue[10]) == cap
