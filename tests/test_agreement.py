"""Binary Agreement tests (mirrors ``tests/agreement.rs``).

Properties asserted (reference header ``tests/agreement.rs:7-13``):
- Agreement: all correct nodes output the same value;
- Termination: every correct node terminates;
- Validity: if all correct nodes input v, every correct node outputs v.
"""

import random

import pytest

from hbbft_tpu.harness.network import (
    MessageScheduler,
    SilentAdversary,
    TestNetwork,
)
from hbbft_tpu.protocols.agreement import Agreement, InputNotAccepted


def run_agreement(rng, size, inputs, mock=True, scheduler=MessageScheduler.RANDOM):
    """inputs: per-node bool, or None for random per node."""
    f = (size - 1) // 3
    good = size - f
    net = TestNetwork(
        good,
        f,
        lambda adv: SilentAdversary(MessageScheduler(scheduler, rng)),
        lambda ni: Agreement(ni, 0, 0),
        rng,
        mock_crypto=mock,
    )
    for nid in sorted(net.nodes):
        v = inputs if inputs is not None else bool(rng.randrange(2))
        net.input(nid, v)
    net.step_until(
        lambda: all(n.terminated() for n in net.nodes.values())
    )
    outputs = {tuple(n.outputs) for n in net.nodes.values()}
    assert len(outputs) == 1, f"outputs diverged: {outputs}"
    (decided,) = outputs
    assert len(decided) == 1
    # observer agrees
    assert net.observer.outputs == list(decided)
    return decided[0]


@pytest.mark.parametrize("inputs", [True, False, None], ids=["true", "false", "random"])
def test_agreement_sizes_mock(inputs):
    rng = random.Random(20)
    for size in (1, 2, 3, 4, 7, 10):
        decided = run_agreement(rng, size, inputs)
        if inputs is not None:
            assert decided == inputs, "validity violated"


def test_agreement_first_scheduler():
    rng = random.Random(21)
    for size in (4, 7):
        run_agreement(rng, size, None, scheduler=MessageScheduler.FIRST)


def test_agreement_real_bls_small():
    # real threshold coin path: adversarial random inputs force real
    # coin flips in epochs ≡ 2 mod 3
    rng = random.Random(22)
    for trial in range(3):
        run_agreement(rng, 4, None, mock=False)


def test_agreement_rejects_late_input():
    rng = random.Random(23)
    from hbbft_tpu.core.network_info import NetworkInfo

    nis = NetworkInfo.generate_map(range(4), rng, mock=True)
    ag = Agreement(nis[0], 0, 0)
    ag.handle_input(True)
    with pytest.raises(InputNotAccepted):
        ag.handle_input(False)
