"""Vectorized full-epoch co-simulation tests (VERDICT round-2 item 2).

The key gate: batches produced by the array-based epoch driver
(``harness/epoch.py``) are **bit-identical** to the sequential
event-driven harness at small N — the same invariant the reference
asserts across its own nodes (``tests/honey_badger.rs:163-186``),
extended across *execution engines*.
"""

import dataclasses
import random

import pytest

from hbbft_tpu.harness.epoch import (
    VectorizedAgreement,
    VectorizedHoneyBadgerSim,
    VectorizedQueueingSim,
)
from hbbft_tpu.harness.network import (
    MessageScheduler,
    SilentAdversary,
    TestNetwork,
)
from hbbft_tpu.protocols.honey_badger import HoneyBadger


def sequential_first_batch(rng, size, n_dead, contributions, mock=True):
    """Run the sequential ``TestNetwork`` HoneyBadger with every live
    node proposing up-front; return the first batch (identical at every
    correct node — asserted)."""
    net = TestNetwork(
        size - n_dead,
        n_dead,
        lambda adv: SilentAdversary(
            MessageScheduler(MessageScheduler.RANDOM, rng)
        ),
        lambda ni: HoneyBadger(
            ni, rng=random.Random(f"{ni.our_id}-seq")
        ),
        rng,
        mock_crypto=mock,
    )
    for nid in sorted(net.nodes):
        node = net.nodes[nid]
        node.handle_input(contributions[nid])
        msgs = list(node.messages)
        node.messages.clear()
        net.dispatch_messages(nid, msgs)
    guard = 0
    while not all(n.outputs for n in net.nodes.values()):
        guard += 1
        assert guard < 200_000 and net.any_busy(), "sequential run stalled"
        net.step()
    batches = [n.outputs[0] for n in net.nodes.values()]
    first = batches[0]
    for b in batches[1:]:
        assert b.epoch == first.epoch
        assert b.contributions == first.contributions
    return first


class TestEpochEquivalence:
    def test_matches_sequential_all_live(self):
        contributions = {i: [b"tx-%d" % i] for i in range(7)}
        seq = sequential_first_batch(random.Random(71), 7, 0, contributions)
        sim = VectorizedHoneyBadgerSim(7, random.Random(72), mock=True)
        vec = sim.run_epoch(contributions)
        assert vec.batch.epoch == seq.epoch == 0
        assert vec.batch.contributions == seq.contributions
        assert vec.accepted == sorted(contributions)

    def test_matches_sequential_f_dead(self):
        # exactly f dead nodes: the accepted set is deterministic (the
        # N−f live proposers), so both engines must agree exactly
        n, f = 10, 3
        dead = {7, 8, 9}  # TestNetwork corrupts the last f ids
        contributions = {i: [b"c%d" % i] for i in range(n)}
        seq = sequential_first_batch(random.Random(73), n, f, contributions)
        sim = VectorizedHoneyBadgerSim(n, random.Random(74), mock=True)
        vec = sim.run_epoch(
            {i: c for i, c in contributions.items() if i not in dead},
            dead=dead,
        )
        assert vec.batch.contributions == seq.contributions
        assert set(vec.accepted) == set(range(n)) - dead

    def test_two_epochs_advance(self):
        sim = VectorizedHoneyBadgerSim(4, random.Random(75), mock=True)
        b0 = sim.run_epoch({i: [0, i] for i in range(4)})
        b1 = sim.run_epoch({i: [1, i] for i in range(4)})
        assert (b0.batch.epoch, b1.batch.epoch) == (0, 1)
        assert b1.batch.contributions == {i: [1, i] for i in range(4)}


class TestVectorizedAgreement:
    def _netinfos(self, n, seed=0x5EED):
        from hbbft_tpu.core.network_info import NetworkInfo

        return NetworkInfo.generate_map(
            list(range(n)), random.Random(seed), mock=True
        )

    def test_unanimous_true_decides_epoch0(self):
        ag = VectorizedAgreement(self._netinfos(8), 0, list(range(8)))
        res = ag.run({p: True for p in range(8)})
        assert all(res.decisions.values())
        assert all(e == 0 for e in res.epochs_used.values())
        assert res.coin_flips == 0

    def test_unanimous_false_decides_epoch1(self):
        # epoch 0 coin is fixed true ≠ false → carry to epoch 1 (coin
        # false) — reference schedule ``agreement.rs:314-328``
        ag = VectorizedAgreement(self._netinfos(8), 0, list(range(8)))
        res = ag.run({p: False for p in range(8)})
        assert not any(res.decisions.values())
        assert all(e == 1 for e in res.epochs_used.values())
        assert res.coin_flips == 0

    def test_split_inputs_reach_real_coin_and_terminate(self):
        ag = VectorizedAgreement(self._netinfos(8), 1, list(range(8)))
        est0 = {p: {n: (n % 2 == 0) for n in range(8)} for p in range(8)}
        res = ag.run(est0)
        assert set(res.decisions.values()) <= {True, False}
        # both values were input by correct nodes → validity holds
        # regardless of outcome; with both in vals the estimate follows
        # the coin, so epoch ≥ 2 instances flip the real coin
        assert res.coin_flips > 0

    def test_split_inputs_real_bls_batched_coin(self):
        from hbbft_tpu.core.network_info import NetworkInfo

        netinfos = NetworkInfo.generate_map(
            list(range(4)), random.Random(0xB15), mock=False
        )
        ag = VectorizedAgreement(netinfos, 2, list(range(4)))
        est0 = {p: {n: (n < 2) for n in range(4)} for p in range(4)}
        res = ag.run(est0)
        assert res.coin_flips > 0
        assert res.crypto_flushes > 0  # the grouped RLC pairing ran
        assert not list(res.fault_log)

    def test_dead_nodes_within_bound(self):
        ag = VectorizedAgreement(
            self._netinfos(10), 0, list(range(10)), dead={8, 9}
        )
        res = ag.run({p: True for p in range(10)})
        assert all(res.decisions.values())

    def test_too_many_dead_rejected(self):
        with pytest.raises(ValueError):
            VectorizedAgreement(
                self._netinfos(4), 0, list(range(4)), dead={1, 2}
            )

    def test_byzantine_vote_injection_widens_vals(self):
        # f Byzantine BVal+Aux votes for the minority value force both
        # values into play; instances still terminate and agree
        n = 7
        ag = VectorizedAgreement(self._netinfos(n), 3, list(range(n)))
        res = ag.run(
            {p: True for p in range(n)},
            adv_bval={p: (2, 0) for p in range(n)},
            adv_aux={p: (2, 0) for p in range(n)},
        )
        assert set(res.decisions.values()) <= {True, False}


class TestEpochAdversaries:
    def test_forged_decryption_shares_attributed(self):
        sim = VectorizedHoneyBadgerSim(7, random.Random(76), mock=True)
        from hbbft_tpu.crypto.mock import MockDecryptionShare

        bogus = MockDecryptionShare(b"\x00" * 32, b"\x01" * 32)
        res = sim.run_epoch(
            {i: [i] for i in range(7)},
            forged_dec={6: {p: bogus for p in range(7)}},
        )
        # batch still complete; node 6 attributed
        assert res.batch.contributions == {i: [i] for i in range(7)}
        flagged = {f.node_id for f in res.fault_log}
        assert 6 in flagged

    def test_corrupt_echo_shards_attributed(self):
        sim = VectorizedHoneyBadgerSim(7, random.Random(77), mock=True)
        res = sim.run_epoch(
            {i: [i] for i in range(7)},
            corrupt_shards={0: {5: b"\xff\xff"}},
        )
        assert res.batch.contributions == {i: [i] for i in range(7)}
        flagged = {f.node_id for f in res.fault_log}
        assert 5 in flagged

    def test_forged_coin_share_fallback_attributes_and_lands(self):
        # A live Byzantine sender forges its threshold-coin signature
        # share on every real flip: the grouped-RLC check fails, the
        # per-share fallback must attribute INVALID_SIGNATURE_SHARE to
        # exactly the forger, and every coin still lands from the ≥ f+1
        # honest shares (epoch.py fallback branch — VERDICT r3 item 8).
        from hbbft_tpu.core.fault import FaultKind
        from hbbft_tpu.core.network_info import NetworkInfo
        from hbbft_tpu.harness.epoch import VectorizedAgreement

        netinfos = NetworkInfo.generate_map(
            list(range(4)), random.Random(0xF06), mock=False
        )
        ag = VectorizedAgreement(netinfos, 2, list(range(4)))
        est0 = {p: {n: (n < 2) for n in range(4)} for p in range(4)}
        res = ag.run(est0, forged_coin={3})
        assert res.coin_flips > 0  # split inputs force the real coin
        assert set(res.decisions.values()) <= {True, False}
        flagged = {
            f.node_id
            for f in res.fault_log
            if f.kind == FaultKind.INVALID_SIGNATURE_SHARE
        }
        assert flagged == {3}
        # honest outcome check: the same run without the forger's
        # interference decides identically (a bad share changes nothing)
        netinfos2 = NetworkInfo.generate_map(
            list(range(4)), random.Random(0xF06), mock=False
        )
        ag2 = VectorizedAgreement(netinfos2, 2, list(range(4)))
        res2 = ag2.run(est0)
        assert res.decisions == res2.decisions
        assert res.epochs_used == res2.epochs_used

    def test_forged_coin_validation(self):
        from hbbft_tpu.core.network_info import NetworkInfo
        from hbbft_tpu.harness.epoch import VectorizedAgreement

        netinfos = NetworkInfo.generate_map(
            list(range(4)), random.Random(0xF07), mock=True
        )
        ag = VectorizedAgreement(netinfos, 0, list(range(4)))
        with pytest.raises(ValueError, match="real BLS"):
            ag.run({p: True for p in range(4)}, forged_coin={0})
        netinfos = NetworkInfo.generate_map(
            list(range(4)), random.Random(0xF08), mock=False
        )
        ag = VectorizedAgreement(netinfos, 0, list(range(4)))
        with pytest.raises(ValueError, match="exceed"):
            ag.run({p: True for p in range(4)}, forged_coin={0, 1})
        with pytest.raises(ValueError, match="live"):
            VectorizedAgreement(
                netinfos, 0, list(range(4)), dead={3}
            ).run({p: True for p in range(4)}, forged_coin={3})

    def test_verify_honest_elision_same_outcome(self):
        contributions = {i: [b"z%d" % i] for i in range(7)}
        a = VectorizedHoneyBadgerSim(
            7, random.Random(78), mock=True, verify_honest=True
        ).run_epoch(contributions)
        b = VectorizedHoneyBadgerSim(
            7, random.Random(78), mock=True, verify_honest=False
        ).run_epoch(contributions)
        assert a.batch.contributions == b.batch.contributions
        assert a.accepted == b.accepted


class TestEpochRealBls:
    def test_full_epoch_real_crypto(self):
        sim = VectorizedHoneyBadgerSim(4, random.Random(79), mock=False)
        res = sim.run_epoch({i: [b"real-%d" % i] for i in range(4)})
        assert res.batch.contributions == {
            i: [b"real-%d" % i] for i in range(4)
        }
        assert res.shares_verified == 16  # N × N accepted proposers
        assert not list(res.fault_log)


class TestQueueingSim:
    def test_txs_commit_and_drain(self):
        rng = random.Random(80)
        qsim = VectorizedQueueingSim(7, rng, batch_size=8, mock=True)
        txs = [b"qtx-%d" % i for i in range(24)]
        qsim.input_all(txs)
        committed = set()
        for _ in range(40):
            res = qsim.run_epoch()
            committed.update(res.batch.tx_iter())
            if committed >= set(txs):
                break
        assert committed >= set(txs)
        assert all(len(q) == 0 for q in qsim.queues.values())

    def test_adversarial_epochs(self):
        rng = random.Random(81)
        qsim = VectorizedQueueingSim(10, rng, batch_size=10, mock=True)
        txs = [b"a-%d" % i for i in range(20)]
        qsim.input_all(txs)
        committed = set()
        for _ in range(60):
            res = qsim.run_epoch(dead={7, 8, 9})
            committed.update(res.batch.tx_iter())
            if committed >= set(txs):
                break
        assert committed >= set(txs)


def test_matches_sequential_two_epochs():
    """Bit-identical batches across TWO epochs: the sequential network
    proposes each node's epoch-1 contribution as soon as that node
    advances, and both engines must produce the same two batches."""
    n = 4
    rng = random.Random(85)
    contribs = {
        e: {i: [b"e%d-%d" % (e, i)] for i in range(n)} for e in (0, 1)
    }
    net = TestNetwork(
        n,
        0,
        lambda adv: SilentAdversary(
            MessageScheduler(MessageScheduler.RANDOM, rng)
        ),
        lambda ni: HoneyBadger(ni, rng=random.Random(f"{ni.our_id}-2e")),
        rng,
        mock_crypto=True,
    )
    for nid in sorted(net.nodes):
        node = net.nodes[nid]
        node.handle_input(contribs[0][nid])
        msgs = list(node.messages)
        node.messages.clear()
        net.dispatch_messages(nid, msgs)
    guard = 0
    while not all(len(nd.outputs) >= 2 for nd in net.nodes.values()):
        guard += 1
        assert guard < 400_000, "two-epoch sequential run stalled"
        for nid in sorted(net.nodes):
            node = net.nodes[nid]
            inst = node.instance
            if inst.epoch == 1 and not inst.has_input():
                node.handle_input(contribs[1][nid])
                msgs = list(node.messages)
                node.messages.clear()
                net.dispatch_messages(nid, msgs)
        if net.any_busy():
            net.step()
    seq_batches = [net.nodes[0].outputs[e] for e in (0, 1)]
    for nd in net.nodes.values():
        for e in (0, 1):
            assert nd.outputs[e].contributions == seq_batches[e].contributions

    sim = VectorizedHoneyBadgerSim(n, random.Random(86), mock=True)
    for e in (0, 1):
        vec = sim.run_epoch(contribs[e])
        assert vec.batch.epoch == e == seq_batches[e].epoch
        assert vec.batch.contributions == seq_batches[e].contributions


def test_matches_sequential_n13_f_dead():
    """A wider odd size (n=13, f=4): both engines agree exactly with
    exactly f silent Byzantine nodes."""
    n, f = 13, 4
    dead = {9, 10, 11, 12}
    contributions = {i: [b"w%d" % i] for i in range(n)}
    seq = sequential_first_batch(random.Random(87), n, f, contributions)
    sim = VectorizedHoneyBadgerSim(n, random.Random(88), mock=True)
    vec = sim.run_epoch(
        {i: c for i, c in contributions.items() if i not in dead}, dead=dead
    )
    assert vec.batch.contributions == seq.contributions
    assert set(vec.accepted) == set(range(n)) - dead


def test_rbc_phase_singular_decode_retries_subsets():
    """ADVICE r2 follow-up: a custom codec whose coding matrix has a
    singular k-row submatrix (impossible for the shipped Vandermonde-
    derived matrices, possible for exotic ops backends) must not abort
    the epoch — the batched wave slides to a different k-subset of the
    present rows.  The patched decode_matrix raises *deterministically*
    for the first subset tried, exactly as a real singular submatrix
    would."""
    n = 7
    sim = VectorizedHoneyBadgerSim(n, random.Random(90), mock=True)
    contribs = {i: [b"fb-%d" % i] for i in range(n)}
    orig = sim.codec.decode_matrix
    refused = {"key": None}

    def singular_subset(use):
        if refused["key"] is None:
            refused["key"] = tuple(use)
        if tuple(use) == refused["key"]:
            raise ValueError("singular submatrix")
        return orig(use)

    sim.codec.decode_matrix = singular_subset
    res = sim.run_epoch(contribs, dead={6})
    assert refused["key"] is not None, "batched decode was not exercised"
    assert res.batch.contributions == {
        i: contribs[i] for i in range(n - 1)
    }
    assert res.fault_log.is_empty()


def test_rbc_phase_no_invertible_subset_fails_closed():
    """If NO k-subset decodes (every sliding window singular — a
    backend defect, not proposer misbehavior), the wave delivers
    nothing and the epoch aborts, with no honest proposer blamed
    (matching the per-instance path's reconstruct-failure semantics)."""
    n = 7
    sim = VectorizedHoneyBadgerSim(n, random.Random(91), mock=True)
    contribs = {i: [b"fb-%d" % i] for i in range(n)}

    def always_singular(use):
        raise ValueError("singular submatrix")

    sim.codec.decode_matrix = always_singular
    with pytest.raises(RuntimeError, match="coding-matrix defect"):
        sim.run_epoch(contribs)


def sequential_first_batch_late(rng, size, late_pid, contributions, mock=True):
    """Sequential HoneyBadger where the adversary delays ALL broadcast
    traffic of instance ``late_pid`` (a live, proposing node) past the
    epoch: its agreement gets false from every node via the N−f rule
    (``common_subset.rs:271-289``) and the batch excludes it."""
    from hbbft_tpu.protocols.common_subset import CsBroadcast
    from hbbft_tpu.protocols.honey_badger import (
        HbCommonSubset,
        HoneyBadgerMessage,
    )

    def not_late_broadcast(sender, recipient, message):
        if isinstance(message, HoneyBadgerMessage) and isinstance(
            message.content, HbCommonSubset
        ):
            inner = message.content.msg
            if isinstance(inner, CsBroadcast) and inner.proposer_id == late_pid:
                return False
        return True

    net = TestNetwork(
        size,
        0,
        lambda adv: SilentAdversary(
            MessageScheduler(MessageScheduler.RANDOM, rng)
        ),
        lambda ni: HoneyBadger(ni, rng=random.Random(f"{ni.our_id}-late")),
        rng,
        mock_crypto=mock,
        message_filter=not_late_broadcast,
    )
    for nid in sorted(net.nodes):
        node = net.nodes[nid]
        node.handle_input(contributions[nid])
        msgs = list(node.messages)
        node.messages.clear()
        net.dispatch_messages(nid, msgs)
    guard = 0
    while not all(n.outputs for n in net.nodes.values()):
        guard += 1
        assert guard < 400_000 and net.any_busy(), "late-schedule run stalled"
        net.step()
    assert net.held_messages, "the delay filter never held anything"
    batches = [n.outputs[0] for n in net.nodes.values()]
    first = batches[0]
    for b in batches[1:]:
        assert b.contributions == first.contributions
    # the delayed messages eventually arrive (finite delays) — too late
    # to change anything
    net.release_held()
    while net.any_busy():
        net.step()
    for nd in net.nodes.values():
        assert nd.outputs[0].contributions == first.contributions
    return first


def test_matches_sequential_late_proposer():
    """THE async-schedule gate (VERDICT r2 item 4): a live proposer
    whose broadcast the adversary withholds decides false — accepted ⊊
    live proposers — and the two engines produce bit-identical
    batches."""
    n, late_pid = 7, 3
    contributions = {i: [b"late-%d" % i] for i in range(n)}
    seq = sequential_first_batch_late(
        random.Random(92), n, late_pid, contributions
    )
    assert late_pid not in seq.contributions  # late proposer excluded
    assert set(seq.contributions) == set(range(n)) - {late_pid}

    sim = VectorizedHoneyBadgerSim(n, random.Random(93), mock=True)
    vec = sim.run_epoch(contributions, late={late_pid})
    assert vec.batch.contributions == seq.contributions
    assert set(vec.accepted) == set(range(n)) - {late_pid}


def test_late_and_dead_combined():
    """late + dead together, within the f bound: accepted excludes
    both; the batch carries exactly the timely live proposers."""
    n = 10  # f = 3
    contributions = {i: [b"c%d" % i] for i in range(n)}
    sim = VectorizedHoneyBadgerSim(n, random.Random(94), mock=True)
    res = sim.run_epoch(contributions, dead={9}, late={0, 5})
    assert set(res.accepted) == set(range(n)) - {0, 5, 9}
    assert res.batch.contributions == {
        i: contributions[i] for i in sorted(set(range(n)) - {0, 5, 9})
    }


def test_too_many_late_rejected():
    """More than f withheld broadcasts: common subset cannot terminate
    on that schedule — the engine refuses rather than fabricating an
    impossible epoch."""
    n = 7  # f = 2, N−f = 5
    sim = VectorizedHoneyBadgerSim(n, random.Random(95), mock=True)
    with pytest.raises(RuntimeError, match="cannot terminate"):
        sim.run_epoch(
            {i: [i] for i in range(n)}, late={0, 1, 2}
        )


class TestObserverLane:
    """VERDICT r2 item 6: the non-validator observer consumer
    (reference ``tests/network/mod.rs:402-420``) in the vectorized
    engine."""

    def test_observer_matches_validators_mock(self):
        sim = VectorizedHoneyBadgerSim(7, random.Random(96), mock=True)
        contribs = {i: [b"ob-%d" % i] for i in range(7)}
        res = sim.run_epoch(contribs, observe=True)
        assert res.observer_batch is not None
        assert res.observer_batch.epoch == res.batch.epoch
        assert res.observer_batch.contributions == res.batch.contributions

    def test_observer_matches_with_dead_and_late(self):
        n = 10
        sim = VectorizedHoneyBadgerSim(n, random.Random(97), mock=True)
        contribs = {i: [b"ob%d" % i] for i in range(n)}
        res = sim.run_epoch(contribs, dead={9}, late={2}, observe=True)
        assert set(res.accepted) == set(range(n)) - {2, 9}
        assert res.observer_batch.contributions == res.batch.contributions

    def test_observer_rejects_forged_shares(self):
        # forged shares are invalid to the observer's public checks
        # exactly as to validators; the batch still matches
        from hbbft_tpu.crypto.mock import MockDecryptionShare

        sim = VectorizedHoneyBadgerSim(7, random.Random(98), mock=True)
        bogus = MockDecryptionShare(b"\x00" * 32, b"\x02" * 32)
        res = sim.run_epoch(
            {i: [i] for i in range(7)},
            forged_dec={6: {p: bogus for p in range(7)}},
            observe=True,
        )
        assert res.observer_batch.contributions == res.batch.contributions

    def test_observer_real_bls_elided_validators(self):
        # validators elide honest-share verification; the observer
        # cannot (it holds no secret) and still derives the same batch
        # through real public verification
        n = 4
        sim = VectorizedHoneyBadgerSim(
            n, random.Random(99), mock=False,
            verify_honest=False, emit_minimal=True,
        )
        contribs = {i: [b"rob-%d" % i] for i in range(n)}
        res = sim.run_epoch(contribs, observe=True)
        assert res.observer_batch.contributions == res.batch.contributions
        assert res.observer_batch.contributions == contribs

    def test_observer_shares_the_main_flush(self):
        # VERDICT r3 item 9: with an observer attached, the epoch's
        # main decryption round verifies every emitted share through
        # the cache-filling batched path, and the observer lane is pure
        # cache hits — NO additional obligations are prefetched and no
        # second flush runs for the observer.
        n = 4
        sim = VectorizedHoneyBadgerSim(
            n, random.Random(111), mock=False,
            verify_honest=False, emit_minimal=True,
        )
        contribs = {i: [b"sf-%d" % i] for i in range(n)}
        res = sim.run_epoch(contribs, observe=True)
        assert res.observer_batch.contributions == res.batch.contributions
        # exactly one decryption flush served both lanes: the observer
        # added zero new prefetched obligations (all were cached), so
        # prefetched == the shares the main round verified
        assert sim.be.stats.flushes == 1
        assert sim.be.stats.prefetched == res.shares_verified
        assert sim.be.stats.fallback_groups == 0


class TestDivergentViews:
    """VERDICT r3 item 4: a two-class asynchronous schedule where
    correct nodes hold DIFFERENT bin_values mid-agreement, expressed in
    the vectorized engine (``DivergentEpoch0``) and cross-checked
    against the sequential ``TestNetwork`` driven by a matching
    partition adversary (equivocating epoch-0 BVals + staged delivery
    waves — the reference adversary's delivery power,
    ``tests/network/mod.rs:151-173``)."""

    # scenario: n=7, f=2; honest 0-4 (est: 0-3 → True, 4 → False);
    # Byzantine 5,6 send BVal(True) to class A={0,1} and BVal(False)
    # to class B={2,3,4}, then stay silent.
    CLASS_A = frozenset({0, 1})
    CLASS_B = frozenset({2, 3, 4})

    def _sequential(self, mock, seed):
        from hbbft_tpu.core.step import Target, TargetedMessage
        from hbbft_tpu.harness.network import (
            Adversary,
            MessageScheduler,
            MessageWithSender,
            TestNetwork,
        )
        from hbbft_tpu.protocols.agreement import (
            Agreement,
            AgreementMessage,
            SbvContent,
        )
        from hbbft_tpu.protocols.sbv_broadcast import Aux, BVal
        from hbbft_tpu.protocols.bool_set import BoolSet

        A, B = self.CLASS_A, self.CLASS_B

        class EquivocatingAdversary(Adversary):
            """Epoch-0 BVal equivocation (True→A, False→B), silent
            after."""

            def __init__(self, rng):
                self.scheduler = MessageScheduler(
                    MessageScheduler.FIRST, rng
                )
                self.sent = False
                self.adv_ids = []

            def init(self, all_nodes, adv_netinfos):
                self.adv_ids = sorted(adv_netinfos)

            def pick_node(self, nodes):
                return self.scheduler.pick_node(nodes)

            def push_message(self, sender_id, tm):
                pass

            def step(self):
                if self.sent:
                    return []
                self.sent = True
                out = []
                for adv in self.adv_ids:
                    for r in sorted(A):
                        out.append(
                            MessageWithSender(
                                adv,
                                TargetedMessage(
                                    Target.to(r),
                                    AgreementMessage(
                                        0, SbvContent(BVal(True))
                                    ),
                                ),
                            )
                        )
                    for r in sorted(B):
                        out.append(
                            MessageWithSender(
                                adv,
                                TargetedMessage(
                                    Target.to(r),
                                    AgreementMessage(
                                        0, SbvContent(BVal(False))
                                    ),
                                ),
                            )
                        )
                return out

        def bval_msg(m, val):
            return (
                isinstance(m, AgreementMessage)
                and m.epoch == 0
                and isinstance(m.content, SbvContent)
                and isinstance(m.content.msg, BVal)
                and m.content.msg.value is val
            )

        def aux_msg(m):
            return (
                isinstance(m, AgreementMessage)
                and m.epoch == 0
                and isinstance(m.content, SbvContent)
                and isinstance(m.content.msg, Aux)
            )

        phase = {"n": 1}

        def filt(sender, recipient, m):
            # the staged wave schedule: W1 holds True-BVals from B and
            # relayed False-BVals from A, and every epoch-0 Aux; later
            # phases release wave by wave (release_held below)
            if recipient == TestNetwork.OBSERVER_ID:
                return True
            if phase["n"] <= 1 and bval_msg(m, True) and recipient in B:
                return False
            if (
                phase["n"] <= 2
                and bval_msg(m, False)
                and recipient in A
                and sender in {2, 3}  # relays; est-0 sender 4 passes
            ):
                return False
            if phase["n"] <= 3 and aux_msg(m):
                return False
            return True

        rng = random.Random(seed)
        net = TestNetwork(
            5,
            2,
            lambda advs: EquivocatingAdversary(random.Random(seed + 1)),
            lambda ni: Agreement(ni, 0, 0),
            rng,
            mock_crypto=mock,
            message_filter=filt,
        )
        for nid in range(4):
            net.input(nid, True)
        net.input(4, False)

        def drain():
            while net.any_busy():
                net.step()

        drain()
        # W1/W2 complete: the two classes hold DIFFERENT bin_values —
        # the mid-agreement state the uniform engine cannot represent
        bins = {
            nid: net.nodes[nid].algo.sbv_broadcast.bin_values
            for nid in range(5)
        }
        for nid in A:
            assert bins[nid] == BoolSet.single(True), bins
        for nid in B:
            assert bins[nid] == BoolSet.single(False), bins

        phase["n"] = 2  # release the True wave to B
        net.release_held(
            lambda s, r, m: bval_msg(m, True) and r in B
        )
        drain()
        for nid in B:
            assert net.nodes[nid].algo.sbv_broadcast.bin_values == BoolSet.both()

        phase["n"] = 3  # release the relayed False wave to A
        net.release_held(
            lambda s, r, m: bval_msg(m, False) and r in A
        )
        drain()
        for nid in A:
            assert net.nodes[nid].algo.sbv_broadcast.bin_values == BoolSet.both()

        phase["n"] = 4  # release the Aux wave; epochs proceed freely
        net.release_held()
        net.step_until(
            lambda: all(n.terminated() for n in net.nodes.values())
        )
        decisions = {nid: net.nodes[nid].algo.decision for nid in range(5)}
        epochs = {nid: net.nodes[nid].algo.epoch for nid in range(5)}
        assert len(set(decisions.values())) == 1
        return decisions[0], epochs

    def _vectorized(self, mock, seed):
        from hbbft_tpu.core.network_info import NetworkInfo
        from hbbft_tpu.harness.epoch import (
            DivergentEpoch0,
            VectorizedAgreement,
        )

        netinfos = NetworkInfo.generate_map(
            list(range(7)), random.Random(seed), mock=mock
        )
        ag = VectorizedAgreement(netinfos, 0, [0])
        res = ag.run(
            {0: {0: True, 1: True, 2: True, 3: True, 4: False}},
            divergent=DivergentEpoch0(
                class_a=self.CLASS_A,
                equiv={5: (True, False), 6: (True, False)},
                instances=frozenset({0}),
            ),
        )
        assert res.diverged
        return res.decisions[0], res.epochs_used[0]

    def test_divergent_cross_engine_mock(self):
        seq_dec, seq_epochs = self._sequential(mock=True, seed=0xD1)
        vec_dec, vec_epoch = self._vectorized(mock=True, seed=0xD1)
        assert vec_dec == seq_dec
        assert set(seq_epochs.values()) == {vec_epoch}

    def test_divergent_cross_engine_real_bls(self):
        seq_dec, seq_epochs = self._sequential(mock=False, seed=0xD2)
        vec_dec, vec_epoch = self._vectorized(mock=False, seed=0xD2)
        assert vec_dec == seq_dec
        assert set(seq_epochs.values()) == {vec_epoch}

    def test_epoch_divergent_batches_match_uniform(self):
        # A FULL epoch under the divergent schedule: proposer 4's
        # broadcast reaches only {0,1,2,3} before agreement
        # (late_subset) and the equivocators split the epoch-0 views;
        # the divergent run's batch must be bit-identical to the
        # uniform engine's run over the same schedule skeleton
        # (validity pins instance 4's decision to true in both).
        from hbbft_tpu.harness.epoch import DivergentEpoch0

        n = 7
        contribs = {i: [b"dv-%d" % i] for i in range(5)}
        div = DivergentEpoch0(
            class_a=self.CLASS_A,
            equiv={5: (True, False), 6: (True, False)},
            instances=frozenset({4}),
        )
        sim = VectorizedHoneyBadgerSim(n, random.Random(0xE7), mock=True)
        res = sim.run_epoch(
            contribs,
            late_subset={4: {0, 1, 2, 3}},
            divergent=div,
        )
        twin = VectorizedHoneyBadgerSim(n, random.Random(0xE7), mock=True)
        res2 = twin.run_epoch(
            contribs, dead={5, 6}, late_subset={4: {0, 1, 2, 3}}
        )
        assert res.accepted == res2.accepted
        assert 4 in res.accepted  # the late-subset proposer made it in
        assert res.batch.contributions == res2.batch.contributions
        assert res.shares_verified == res2.shares_verified

    @pytest.mark.parametrize("mock", [True, False])
    def test_divergent_at_scale_256(self, mock):
        # VERDICT r3 missing #3 (scale × adversarial scheduling never
        # intersected): the divergent two-class schedule at n=256 with
        # the FULL Byzantine budget — f=85 equivocators splitting the
        # epoch-0 views of a late-subset instance — mock and REAL BLS.
        # |B| = f+1 and one B member inside the late subset is exactly
        # the wave-threshold geometry: est-False count f stays under
        # class A's W1 relay guard while B's cascade reaches 2f+1.
        from hbbft_tpu.harness.epoch import DivergentEpoch0

        n = 256
        f = (n - 1) // 3
        equiv = {n - 1 - i: (True, False) for i in range(f)}
        live = [i for i in range(n) if i not in equiv]
        B = live[: f + 1]
        class_a = frozenset(live[f + 1 :])
        p = B[-1]
        late = set(class_a) | {B[0]}
        contribs = {i: [b"dv-%03d" % i] for i in live}
        sim = VectorizedHoneyBadgerSim(
            n,
            random.Random(0xE7),
            mock=mock,
            verify_honest=False,
            emit_minimal=True,
        )
        res = sim.run_epoch(
            contribs,
            late_subset={p: late},
            divergent=DivergentEpoch0(
                class_a=class_a, equiv=equiv, instances=frozenset({p})
            ),
        )
        twin = VectorizedHoneyBadgerSim(
            n,
            random.Random(0xE7),
            mock=mock,
            verify_honest=False,
            emit_minimal=True,
        )
        res2 = twin.run_epoch(
            contribs, dead=set(equiv), late_subset={p: late}
        )
        assert p in res.accepted and len(res.accepted) == len(live)
        assert res.batch.contributions == res2.batch.contributions
        assert res.shares_verified == res2.shares_verified

    def test_epoch_late_subset_excluded_when_minority(self):
        # delivered to fewer than the relay threshold: every correct
        # node inputs false for that instance and it is excluded even
        # though the payload (eventually) arrived
        n = 7
        contribs = {i: [b"ls-%d" % i] for i in range(n)}
        sim = VectorizedHoneyBadgerSim(n, random.Random(0xE8), mock=True)
        res = sim.run_epoch(contribs, late_subset={3: {3, 5}})
        assert 3 not in res.accepted
        assert set(res.accepted) == set(range(n)) - {3}

    def test_divergent_schedule_validation(self):
        from hbbft_tpu.core.network_info import NetworkInfo
        from hbbft_tpu.harness.epoch import (
            DivergentEpoch0,
            VectorizedAgreement,
        )

        netinfos = NetworkInfo.generate_map(
            list(range(7)), random.Random(3), mock=True
        )
        # too many Byzantine: 2 equivocators + 1 dead > f = 2
        with pytest.raises(ValueError, match="exceed"):
            VectorizedAgreement(netinfos, 0, [0], dead={4}).run(
                {0: True},
                divergent=DivergentEpoch0(
                    class_a=frozenset({0, 1}),
                    equiv={5: (True, False), 6: (True, False)},
                    instances=frozenset({0}),
                ),
            )
        # non-divergent schedule: unanimous est, equivocators alone
        # cannot push class B's cascade past f+1
        with pytest.raises(ValueError, match="non-divergent"):
            VectorizedAgreement(netinfos, 0, [0]).run(
                {0: True},
                divergent=DivergentEpoch0(
                    class_a=frozenset({0, 1}),
                    equiv={5: (True, False), 6: (True, False)},
                    instances=frozenset({0}),
                ),
            )


class TestMultiEpochDivergence:
    """VERDICT r4 next-4: divergence as CARRIED engine state — view
    classes with their own bin_values/Aux counts persisting across
    agreement epochs (``DivergentSchedule``), deciding the same
    instance at DIFFERENT epochs, cross-checked against the sequential
    ``TestNetwork`` driven by a matching partition adversary.

    Scenario (n=11, f=3): honest 0–7 (ests 0–3 True, 4–7 False),
    Byzantine 8–10 equivocate epoch-0 BVal AND Aux per class.  Class
    A = {0..4} sees the prompt true wave, counts an 8-true Aux prefix
    (5 honest + 3 Byzantine) and decides true at epoch 0; class
    B = {5,6,7} sees the false cascade first, counts a {6 false,
    2 true} prefix, continues with est = coin, and decides true at
    epoch 1 via f+1 Terms from A (expedited termination,
    ``agreement.rs:213-228``)."""

    A = frozenset({0, 1, 2, 3, 4})
    B = frozenset({5, 6, 7})
    EQUIV = (8, 9, 10)

    def _schedule(self):
        from hbbft_tpu.harness.epoch import (
            ClassDirective,
            DivergentSchedule,
        )

        return DivergentSchedule(
            classes=(self.A, self.B),
            equiv={e: (True, False) for e in self.EQUIV},
            equiv_aux=True,
            directives={
                0: (
                    ClassDirective(
                        withhold=False, aux_counted=((True, 8),)
                    ),
                    ClassDirective(
                        withhold=True,
                        aux_counted=((False, 6), (True, 2)),
                    ),
                )
            },
            instances=frozenset({0}),
        )

    def _est0(self):
        return {0: {nid: nid < 4 for nid in range(8)}}

    def _vectorized(self, mock, seed):
        from hbbft_tpu.core.network_info import NetworkInfo
        from hbbft_tpu.harness.epoch import VectorizedAgreement

        netinfos = NetworkInfo.generate_map(
            list(range(11)), random.Random(seed), mock=mock
        )
        ag = VectorizedAgreement(netinfos, 0, [0])
        res = ag.run(self._est0(), div_schedule=self._schedule())
        assert res.diverged
        return res

    def _sequential(self, mock, seed):
        from hbbft_tpu.core.step import Target, TargetedMessage
        from hbbft_tpu.harness.network import (
            Adversary,
            MessageScheduler,
            MessageWithSender,
            TestNetwork,
        )
        from hbbft_tpu.protocols.agreement import (
            Agreement,
            AgreementMessage,
            SbvContent,
        )
        from hbbft_tpu.protocols.sbv_broadcast import Aux, BVal
        from hbbft_tpu.protocols.bool_set import BoolSet

        A, B = self.A, self.B

        class EquivAdversary(Adversary):
            """Per-class epoch-0 BVal AND Aux equivocation (True wave
            to class A, False wave to class B), silent after."""

            def __init__(self, rng):
                self.scheduler = MessageScheduler(
                    MessageScheduler.FIRST, rng
                )
                self.sent = False
                self.adv_ids = []

            def init(self, all_nodes, adv_netinfos):
                self.adv_ids = sorted(adv_netinfos)

            def pick_node(self, nodes):
                return self.scheduler.pick_node(nodes)

            def push_message(self, sender_id, tm):
                pass

            def step(self):
                if self.sent:
                    return []
                self.sent = True
                out = []
                for adv in self.adv_ids:
                    for members, val in ((A, True), (B, False)):
                        for r in sorted(members):
                            for inner in (BVal(val), Aux(val)):
                                out.append(
                                    MessageWithSender(
                                        adv,
                                        TargetedMessage(
                                            Target.to(r),
                                            AgreementMessage(
                                                0, SbvContent(inner)
                                            ),
                                        ),
                                    )
                                )
                return out

        def bval_msg(m, val):
            return (
                isinstance(m, AgreementMessage)
                and m.epoch == 0
                and isinstance(m.content, SbvContent)
                and isinstance(m.content.msg, BVal)
                and m.content.msg.value is val
            )

        def aux_msg(m, val=None):
            return (
                isinstance(m, AgreementMessage)
                and m.epoch == 0
                and isinstance(m.content, SbvContent)
                and isinstance(m.content.msg, Aux)
                and (val is None or m.content.msg.value is val)
            )

        phase = {"n": 1}

        def filt(sender, recipient, m):
            # W-early: each class sees only its wave — the opposite
            # BVal value is withheld (from every sender but self), and
            # cross-class Auxes are withheld so each class counts
            # exactly the adversary's chosen prefix
            if recipient == TestNetwork.OBSERVER_ID:
                return True
            if phase["n"] <= 1 and bval_msg(m, False) and recipient in A:
                return False
            if phase["n"] <= 1 and bval_msg(m, True) and recipient in B:
                return False
            if (
                phase["n"] <= 2
                and aux_msg(m)
                and sender not in self.EQUIV
                and (sender in A) != (recipient in A)
            ):
                return False
            return True

        rng = random.Random(seed)
        net = TestNetwork(
            8,
            3,
            lambda advs: EquivAdversary(random.Random(seed + 1)),
            lambda ni: Agreement(ni, 0, 0),
            rng,
            mock_crypto=mock,
            message_filter=filt,
        )
        for nid in range(8):
            net.input(nid, nid < 4)

        def drain():
            while net.any_busy():
                net.step()

        drain()
        # W-early complete: the classes hold different bin_values, and
        # class A has already terminated SBV on its 8-true Aux prefix
        # and decided at epoch 0
        for nid in sorted(B):
            assert net.nodes[
                nid
            ].algo.sbv_broadcast.bin_values == BoolSet.single(False)
        for nid in sorted(A):
            assert net.nodes[nid].algo.decision is True
            assert net.nodes[nid].algo.epoch == 0

        phase["n"] = 2  # full BVal delivery; Auxes still class-local
        net.release_held(lambda s, r, m: bval_msg(m, True) or bval_msg(m, False))
        drain()
        for nid in sorted(B):
            assert (
                net.nodes[nid].algo.sbv_broadcast.bin_values
                == BoolSet.both()
            )

        # release exactly TWO true-Auxes to class B: its counted set
        # becomes {6 false, 2 true} → vals = {false, true} → continue
        phase["n"] = 3
        net.release_held(
            lambda s, r, m: aux_msg(m, True) and r in B and s in {0, 1}
        )
        drain()
        phase["n"] = 4
        net.release_held()
        net.step_until(
            lambda: all(n.terminated() for n in net.nodes.values())
        )
        decisions = {nid: net.nodes[nid].algo.decision for nid in range(8)}
        epochs = {nid: net.nodes[nid].algo.epoch for nid in range(8)}
        assert set(decisions.values()) == {True}
        return decisions, epochs

    @pytest.mark.parametrize("mock", [True, False])
    def test_cross_engine_divergent_decision_epochs(self, mock):
        seed = 0xDD if mock else 0xDE
        seq_dec, seq_epochs = self._sequential(mock, seed)
        res = self._vectorized(mock, seed)
        assert res.decisions[0] is True
        # per-class deciding epochs: A at 0, B at 1 — in BOTH engines
        assert res.class_epochs[0] == (0, 1)
        assert res.epochs_used[0] == 1
        for nid in sorted(self.A):
            assert seq_epochs[nid] == 0
        for nid in sorted(self.B):
            assert seq_epochs[nid] == 1

    def test_three_view_classes(self):
        # >2 classes (the r4 unrepresentability): split B into two
        # classes with the same early wave — A decides at epoch 0 on
        # its 8-true Aux prefix; B1 and B2 each count a {6 false,
        # 2 true} prefix, continue, and decide at epoch 1 via A's five
        # Terms.  Aux availability counts ALL honest undecided nodes,
        # so the per-class prefixes stay feasible after the split.
        from hbbft_tpu.core.network_info import NetworkInfo
        from hbbft_tpu.harness.epoch import (
            ClassDirective,
            DivergentSchedule,
            VectorizedAgreement,
        )

        d0 = ClassDirective(withhold=False, aux_counted=((True, 8),))
        db = ClassDirective(
            withhold=True, aux_counted=((False, 6), (True, 2))
        )
        sched = DivergentSchedule(
            classes=(self.A, frozenset({5, 6}), frozenset({7})),
            equiv={e: (True, False, False) for e in self.EQUIV},
            equiv_aux=True,
            directives={0: (d0, db, db)},
            instances=frozenset({0}),
        )
        netinfos = NetworkInfo.generate_map(
            list(range(11)), random.Random(0xD3C), mock=True
        )
        res = VectorizedAgreement(netinfos, 0, [0]).run(
            self._est0(), div_schedule=sched
        )
        assert res.decisions[0] is True
        assert res.class_epochs[0] == (0, 1, 1)

    @pytest.mark.parametrize("mock", [True, False])
    def test_divergent_continuation_reaches_real_coin(self, mock):
        # neither class decides at epoch 0 (both count a {true, false}
        # Aux prefix), the carried state runs the uniform continuation
        # through epoch 1 (fixed-false coin, unanimous true — no
        # decision) and reaches the REAL coin at epoch 2 with the
        # still-running honest nodes as the share senders; whichever
        # way the coin lands, both classes decide true at epoch 2 or 3
        # together.  Exercises _div_round's batched-coin integration.
        from hbbft_tpu.core.network_info import NetworkInfo
        from hbbft_tpu.harness.epoch import (
            ClassDirective,
            DivergentSchedule,
            VectorizedAgreement,
        )

        sched = dataclasses.replace(
            self._schedule(),
            directives={
                0: (
                    ClassDirective(
                        withhold=False,
                        aux_counted=((True, 7), (False, 1)),
                    ),
                    ClassDirective(
                        withhold=True,
                        aux_counted=((False, 6), (True, 2)),
                    ),
                )
            },
        )
        netinfos = NetworkInfo.generate_map(
            list(range(11)), random.Random(0xDC0), mock=mock
        )
        res = VectorizedAgreement(netinfos, 0, [0]).run(
            self._est0(), div_schedule=sched
        )
        assert res.decisions[0] is True
        assert res.coin_flips >= 1
        e = res.epochs_used[0]
        assert e in (2, 3)
        assert res.class_epochs[0] == (e, e)

    def test_epoch_batches_with_divergent_timing(self):
        # a FULL epoch where two classes decide instance `p` at
        # different agreement epochs; the batch is bit-identical to
        # the uniform twin's (same est0 skeleton, no equivocation —
        # both decide TRUE, one at (0,1), one later via the coin path)
        n, p = 11, 6
        contribs = {i: [b"md-%02d" % i] for i in range(n)}
        late = {p: {0, 1, 2, 3}}
        sched = self._schedule()
        sched = dataclasses.replace(sched, instances=frozenset({p}))
        sim = VectorizedHoneyBadgerSim(n, random.Random(0xEA), mock=True)
        res = sim.run_epoch(contribs, late_subset=late, div_schedule=sched)
        assert res.accepted == sorted(range(n))
        twin = VectorizedHoneyBadgerSim(n, random.Random(0xEA), mock=True)
        res2 = twin.run_epoch(contribs, late_subset=late)
        assert res.batch.contributions == res2.batch.contributions
        assert res.accepted == res2.accepted

    def test_schedule_validation(self):
        from hbbft_tpu.core.network_info import NetworkInfo
        from hbbft_tpu.harness.epoch import (
            ClassDirective,
            DivergentSchedule,
            VectorizedAgreement,
        )

        netinfos = NetworkInfo.generate_map(
            list(range(11)), random.Random(5), mock=True
        )

        def run(**kw):
            sched = dataclasses.replace(self._schedule(), **kw)
            return VectorizedAgreement(netinfos, 0, [0]).run(
                self._est0(), div_schedule=sched
            )

        # classes must partition the correct live nodes
        with pytest.raises(ValueError, match="partition"):
            run(classes=(self.A, frozenset({5, 6})))
        # directive rows must give one entry per class
        with pytest.raises(ValueError, match="per class"):
            run(
                directives={
                    0: (ClassDirective(withhold=False),)
                }
            )
        # equivocator rows must give one value per class
        with pytest.raises(ValueError, match="per class"):
            run(equiv={8: (True,), 9: (True, False), 10: (True, False)})
        # an aux prefix below N-f cannot terminate SBV
        with pytest.raises(ValueError, match="termination threshold"):
            run(
                directives={
                    0: (
                        ClassDirective(
                            withhold=False, aux_counted=((True, 6),)
                        ),
                        ClassDirective(
                            withhold=True,
                            aux_counted=((False, 6), (True, 2)),
                        ),
                    )
                }
            )
        # a prefix wanting more senders than exist is infeasible
        with pytest.raises(ValueError, match="senders exist"):
            run(
                directives={
                    0: (
                        ClassDirective(
                            withhold=False, aux_counted=((True, 9),)
                        ),
                        ClassDirective(
                            withhold=True,
                            aux_counted=((False, 6), (True, 2)),
                        ),
                    )
                }
            )


class TestPipelinedEpochs:
    """VERDICT r3 item 7: two epochs in flight (the reference
    ``max_future_epochs`` window, ``honey_badger.rs:30-34``) — epoch
    e+1's broadcast runs on a worker thread under epoch e's decryption
    flush, with bit-identical outcomes to the sequential loop."""

    @staticmethod
    def _contribs(e, n):
        return {i: [b"pl-%d-%d" % (e, i)] for i in range(n)}

    def test_pipelined_matches_sequential_mock(self):
        n, E = 7, 4
        seq_sim = VectorizedHoneyBadgerSim(n, random.Random(120), mock=True)
        seq = [
            seq_sim.run_epoch(self._contribs(e, n)) for e in range(E)
        ]
        pipe_sim = VectorizedHoneyBadgerSim(n, random.Random(120), mock=True)
        pipe = pipe_sim.run_epochs([self._contribs(e, n) for e in range(E)])
        for a, b in zip(seq, pipe):
            assert a.batch.epoch == b.batch.epoch
            assert a.batch.contributions == b.batch.contributions
            assert a.accepted == b.accepted

    def test_pipelined_matches_sequential_real_bls(self):
        n, E = 4, 3
        seq_sim = VectorizedHoneyBadgerSim(n, random.Random(121), mock=False)
        seq = [
            seq_sim.run_epoch(self._contribs(e, n)) for e in range(E)
        ]
        pipe_sim = VectorizedHoneyBadgerSim(n, random.Random(121), mock=False)
        pipe = pipe_sim.run_epochs([self._contribs(e, n) for e in range(E)])
        for a, b in zip(seq, pipe):
            assert a.batch.contributions == b.batch.contributions
            assert a.accepted == b.accepted
            assert a.shares_verified == b.shares_verified

    def test_pipelined_with_adversaries(self):
        n, E = 7, 3
        dead, late = {6}, {2}
        seq_sim = VectorizedHoneyBadgerSim(n, random.Random(122), mock=True)
        seq = [
            seq_sim.run_epoch(self._contribs(e, n), dead=dead, late=late)
            for e in range(E)
        ]
        pipe_sim = VectorizedHoneyBadgerSim(n, random.Random(122), mock=True)
        pipe = pipe_sim.run_epochs(
            [self._contribs(e, n) for e in range(E)], dead=dead, late=late
        )
        for a, b in zip(seq, pipe):
            assert a.batch.contributions == b.batch.contributions
            assert a.accepted == b.accepted

    def test_pipeline_false_falls_back(self):
        n = 4
        sim = VectorizedHoneyBadgerSim(n, random.Random(123), mock=True)
        res = sim.run_epochs(
            [self._contribs(e, n) for e in range(2)], pipeline=False
        )
        assert [r.batch.epoch for r in res] == [0, 1]


class TestPerNodeQueues:
    """VERDICT r2 item 8: divergent per-node transaction queues in the
    vectorized queueing sim (reference normal operating mode,
    ``queueing_honey_badger.rs:188-204``)."""

    def test_uniform_stays_shared(self):
        q = VectorizedQueueingSim(4, random.Random(100), batch_size=8, mock=True)
        q.input_all([b"t%d" % i for i in range(8)])
        assert not q.diverged
        res = q.run_epoch()
        assert not q.diverged
        assert len(res.batch) > 0

    def test_divergent_injection_commits_everything(self):
        n = 4
        q = VectorizedQueueingSim(
            n, random.Random(101), batch_size=16, mock=True
        )
        q.input_all([b"shared-%d" % i for i in range(4)])
        # node 2 alone hears four extra transactions
        q.input_node(2, [b"only2-%d" % i for i in range(4)])
        assert q.diverged
        assert len(q.queues[2]) == 8 and len(q.queues[0]) == 4
        committed = set()
        for _ in range(6):
            res = q.run_epoch()
            committed.update(res.batch.tx_iter())
            if all(len(qq) == 0 for qq in q.queues.values()):
                break
        assert committed == {b"shared-%d" % i for i in range(4)} | {
            b"only2-%d" % i for i in range(4)
        }
        # committed txs drained from every node's queue
        assert all(len(qq) == 0 for qq in q.queues.values())

    def test_divergence_preserves_uniform_contents(self):
        q = VectorizedQueueingSim(3, random.Random(102), batch_size=4, mock=True)
        q.input_all([b"a", b"b"])
        q.input_node(1, [b"c"])
        assert list(q.queues[0].queue) == [b"a", b"b"]
        assert list(q.queues[1].queue) == [b"a", b"b", b"c"]
        q.input_all([b"d"])  # post-divergence uniform injection
        assert list(q.queues[2].queue) == [b"a", b"b", b"d"]


class TestRealBlsCrossEngine:
    """VERDICT r2 item 6 (first half): vectorized-vs-sequential batch
    equivalence on REAL BLS12-381 — the mock-only gap closed."""

    def test_matches_sequential_real_bls_all_live(self):
        n = 4
        contributions = {i: [b"rb-%d" % i] for i in range(n)}
        seq = sequential_first_batch(
            random.Random(103), n, 0, contributions, mock=False
        )
        sim = VectorizedHoneyBadgerSim(n, random.Random(104), mock=False)
        vec = sim.run_epoch(contributions)
        assert vec.batch.epoch == seq.epoch == 0
        assert vec.batch.contributions == seq.contributions
        assert vec.accepted == list(range(n))

    def test_matches_sequential_real_bls_f_dead(self):
        n, f = 7, 2
        dead = {5, 6}
        contributions = {i: [b"rd%d" % i] for i in range(n)}
        seq = sequential_first_batch(
            random.Random(105), n, f, contributions, mock=False
        )
        sim = VectorizedHoneyBadgerSim(n, random.Random(106), mock=False)
        vec = sim.run_epoch(
            {i: c for i, c in contributions.items() if i not in dead},
            dead=dead,
        )
        assert vec.batch.contributions == seq.contributions
        assert set(vec.accepted) == set(range(n)) - dead

    def test_matches_sequential_real_bls_late(self):
        # accepted ⊊ live on REAL crypto, identical across engines
        n, late_pid = 4, 1
        contributions = {i: [b"rl-%d" % i] for i in range(n)}
        seq = sequential_first_batch_late(
            random.Random(107), n, late_pid, contributions, mock=False
        )
        assert set(seq.contributions) == set(range(n)) - {late_pid}
        sim = VectorizedHoneyBadgerSim(n, random.Random(108), mock=False)
        vec = sim.run_epoch(contributions, late={late_pid})
        assert vec.batch.contributions == seq.contributions


class TestVirtualTime:
    """VERDICT r2 weak #6: epoch-latency statistics from the vectorized
    engine under the HwQuality model (SURVEY §5.8's batched-flush →
    virtual-time design)."""

    def test_virtual_account_present_and_sane(self):
        from hbbft_tpu.harness.simulation import HwQuality

        hw = HwQuality.from_flags(lag_ms=100, bw_kbit_s=2000, cpu_pct=100)
        sim = VectorizedHoneyBadgerSim(7, random.Random(120), mock=True, hw=hw)
        res = sim.run_epoch({i: [b"v%d" % i] for i in range(7)})
        v = res.virtual
        assert v is not None and v.total_s > 0
        assert v.network_s > 0 and v.cpu_s > 0
        assert abs(v.total_s - (v.network_s + v.cpu_s)) < 1e-9
        # at least value/echo/ready + 1 agreement epoch (2) + decshares
        assert v.rounds >= 6
        # every round pays one latency
        assert v.network_s >= v.rounds * hw.latency

    def test_virtual_time_scales_with_payload(self):
        from hbbft_tpu.harness.simulation import HwQuality

        hw = HwQuality.from_flags(lag_ms=10, bw_kbit_s=100, cpu_pct=100)
        sim = VectorizedHoneyBadgerSim(7, random.Random(121), mock=True, hw=hw)
        small = sim.run_epoch({i: [b"x"] for i in range(7)}).virtual
        big = sim.run_epoch({i: [b"y" * 4096] for i in range(7)}).virtual
        assert big.per_node_bytes > small.per_node_bytes
        assert big.network_s > small.network_s

    def test_no_hw_no_account(self):
        sim = VectorizedHoneyBadgerSim(4, random.Random(122), mock=True)
        res = sim.run_epoch({i: [b"n%d" % i] for i in range(4)})
        assert res.virtual is None


def test_kitchen_sink_adversarial_epoch():
    """Every adversarial surface at once, at a size past the sequential
    harness's comfort zone: n=25 with f=8 — 4 silent nodes, 2 withheld
    (late) live proposers, a corrupted echo shard, forged decryption
    shares, Byzantine agreement votes, and the observer lane — one
    epoch, every property at once."""
    from hbbft_tpu.crypto.mock import MockDecryptionShare

    n = 25  # f = 8
    dead = {21, 22, 23, 24}
    late = {3, 17}
    sim = VectorizedHoneyBadgerSim(n, random.Random(130), mock=True)
    contribs = {i: [b"ks-%02d" % i] for i in range(n)}
    bogus = MockDecryptionShare(b"\x00" * 32, b"\x03" * 32)
    res = sim.run_epoch(
        contribs,
        dead=dead,
        late=late,
        corrupt_shards={0: {5: b"\xff\x00"}},
        forged_dec={20: {p: bogus for p in range(4)}},
        adv_bval={1: (3, 0)},
        adv_aux={1: (3, 0)},
        observe=True,
    )
    expected = set(range(n)) - dead - late
    assert set(res.accepted) == expected
    assert res.batch.contributions == {
        i: contribs[i] for i in sorted(expected)
    }
    # attribution: the corrupt echoer and the share forger are named
    flagged = {f.node_id for f in res.fault_log}
    assert 5 in flagged and 20 in flagged
    # the observer derives the identical batch from public traffic
    assert res.observer_batch.contributions == res.batch.contributions


def test_adversarial_votes_over_f_rejected():
    """Vote injection beyond the f bound is a modeling error (more
    Byzantine voters than the protocol tolerates) and must raise, not
    silently break agreement validity."""
    sim = VectorizedHoneyBadgerSim(7, random.Random(131), mock=True)
    with pytest.raises(ValueError, match="exceeds the f="):
        sim.run_epoch(
            {i: [i] for i in range(7)}, adv_bval={1: (3, 0)}
        )
