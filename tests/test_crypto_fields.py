"""Field-tower and pairing unit tests (crypto L0)."""

import random

import pytest

from hbbft_tpu.crypto import fields as F
from hbbft_tpu.crypto.curve import G1, G2, G1_GEN, G2_GEN
from hbbft_tpu.crypto.pairing import (
    final_exponentiation,
    miller_loop,
    pairing,
    pairing_check,
    pairings_equal,
)

rng = random.Random(7)


def rand_fq2():
    return (rng.randrange(F.P), rng.randrange(F.P))


def rand_fq6():
    return (rand_fq2(), rand_fq2(), rand_fq2())


def rand_fq12():
    return (rand_fq6(), rand_fq6())


class TestFieldTower:
    def test_fq2_inverse(self):
        for _ in range(10):
            a = rand_fq2()
            assert F.fq2_mul(a, F.fq2_inv(a)) == F.FQ2_ONE

    def test_fq2_sqrt(self):
        for _ in range(10):
            a = rand_fq2()
            sq = F.fq2_sq(a)
            r = F.fq2_sqrt(sq)
            assert r is not None and F.fq2_sq(r) == sq

    def test_fq2_nonresidue_sqrt_fails_half_the_time(self):
        found_none = False
        for _ in range(20):
            if F.fq2_sqrt(rand_fq2()) is None:
                found_none = True
                break
        assert found_none

    def test_fq6_inverse(self):
        for _ in range(5):
            a = rand_fq6()
            assert F.fq6_mul(a, F.fq6_inv(a)) == F.FQ6_ONE

    def test_fq6_mul_by_v_consistent(self):
        v = (F.FQ2_ZERO, F.FQ2_ONE, F.FQ2_ZERO)
        for _ in range(5):
            a = rand_fq6()
            assert F.fq6_mul_by_v(a) == F.fq6_mul(a, v)

    def test_fq12_inverse(self):
        for _ in range(5):
            a = rand_fq12()
            assert F.fq12_mul(a, F.fq12_inv(a)) == F.FQ12_ONE

    def test_fq12_frobenius_is_p_power(self):
        a = rand_fq12()
        assert F.fq12_frobenius(a) == F.fq12_pow(a, F.P)

    def test_fq12_mul_associative_commutative(self):
        a, b, c = rand_fq12(), rand_fq12(), rand_fq12()
        assert F.fq12_mul(a, b) == F.fq12_mul(b, a)
        assert F.fq12_mul(F.fq12_mul(a, b), c) == F.fq12_mul(
            a, F.fq12_mul(b, c)
        )


class TestCurve:
    def test_generator_order(self):
        assert G1_GEN.in_subgroup()
        assert G2_GEN.in_subgroup()
        assert not (G1_GEN * 1).is_infinity()

    def test_group_laws_g1(self):
        a, b = rng.randrange(F.R), rng.randrange(F.R)
        assert G1_GEN * a + G1_GEN * b == G1_GEN * ((a + b) % F.R)
        assert (G1_GEN * a) * b == G1_GEN * (a * b % F.R)
        assert G1_GEN * a - G1_GEN * a == G1.infinity()

    def test_group_laws_g2(self):
        a, b = rng.randrange(F.R), rng.randrange(F.R)
        assert G2_GEN * a + G2_GEN * b == G2_GEN * ((a + b) % F.R)
        assert G2_GEN * a - G2_GEN * a == G2.infinity()

    def test_serde_roundtrip(self):
        for k in [1, 2, 12345, F.R - 1]:
            p = G1_GEN * k
            assert G1.from_bytes(p.to_bytes()) == p
            q = G2_GEN * k
            assert G2.from_bytes(q.to_bytes()) == q
        assert G1.from_bytes(G1.infinity().to_bytes()).is_infinity()
        assert G2.from_bytes(G2.infinity().to_bytes()).is_infinity()

    def test_serde_rejects_garbage(self):
        with pytest.raises(ValueError):
            G1.from_bytes(b"\x00" * 48)
        with pytest.raises(ValueError):
            G1.from_bytes(b"\xff" * 48)
        with pytest.raises(ValueError):
            G2.from_bytes(b"\xff" * 96)

    def test_rejects_non_subgroup_point(self):
        x = 0
        while True:
            x += 1
            y = F.fq_sqrt((x**3 + 4) % F.P)
            if y is None:
                continue
            p = G1.from_affine((x, y))
            if not p.in_subgroup():
                with pytest.raises(ValueError):
                    G1.from_bytes(p.to_bytes())
                return


class TestPairing:
    def test_bilinearity(self):
        a, b = 1234567, 7654321
        assert pairing(G1_GEN * a, G2_GEN * b) == pairing(
            G1_GEN, G2_GEN * (a * b)
        )
        assert pairing(G1_GEN * a, G2_GEN * b) == pairing(
            G1_GEN * (a * b), G2_GEN
        )

    def test_non_degenerate(self):
        assert pairing(G1_GEN, G2_GEN) != F.FQ12_ONE

    def test_infinity_pairs_to_one(self):
        assert pairing(G1.infinity(), G2_GEN) == F.FQ12_ONE
        assert pairing(G1_GEN, G2.infinity()) == F.FQ12_ONE

    def test_inverse_relation(self):
        e = pairing(G1_GEN, G2_GEN)
        e_neg = pairing(-G1_GEN, G2_GEN)
        assert F.fq12_mul(e, e_neg) == F.FQ12_ONE

    def test_pairing_check_product(self):
        a, b = 99, 313
        assert pairings_equal(G1_GEN * a, G2_GEN * b, G1_GEN * b, G2_GEN * a)
        assert not pairings_equal(
            G1_GEN * a, G2_GEN * b, G1_GEN * b, G2_GEN * (a + 1)
        )
        assert pairing_check([])

    def test_pairing_value_in_cyclotomic_subgroup(self):
        e = pairing(G1_GEN * 5, G2_GEN * 9)
        # order divides r: e^r == 1
        assert F.fq12_pow(e, F.R) == F.FQ12_ONE
