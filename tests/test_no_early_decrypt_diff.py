"""Revert-and-re-detect differential suite for ``no-early-decrypt``.

The order-then-reveal pipeline (PR 19) holds its censorship-resistance
argument on one invariant: threshold decryption starts only after
common-subset output pins the epoch's order.  Each test copies
``protocols/`` into a fixture, edits exactly one early-decryption
regression into the HoneyBadger state machine by text substitution,
runs the static gate over the edited tree, and asserts the rule
reports that precise hole.  The unedited copy is asserted clean once
up front, so a failure here means the edit (and only the edit)
re-opened it.

The dynamic twin of this gate is the ``ordered-reveal`` scenario
(``harness/scenarios.py``).
"""

import os
import shutil

from hbbft_tpu.analysis import lint_paths
from hbbft_tpu.analysis.rules.no_early_decrypt import NoEarlyDecryptRule

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PKG = os.path.join(REPO, "hbbft_tpu")

HB = "protocols/honey_badger.py"


def _copy_scope(tmp_path):
    dst = tmp_path / "hbbft_tpu"
    shutil.copytree(
        os.path.join(PKG, "protocols"),
        dst / "protocols",
        ignore=shutil.ignore_patterns("__pycache__"),
    )
    return dst


def _edit_and_lint(tmp_path, relpath, old, new):
    root = _copy_scope(tmp_path)
    target = root / relpath
    text = target.read_text()
    assert old in text, (
        f"anchor text not found in {relpath} — the differential edit "
        "needs updating alongside the protocol code"
    )
    target.write_text(text.replace(old, new))
    violations, errors = lint_paths([str(root)], [NoEarlyDecryptRule()])
    assert not errors
    return [v for v in violations if v.path == relpath]


def test_unedited_scope_copy_is_clean(tmp_path):
    root = _copy_scope(tmp_path)
    violations, errors = lint_paths([str(root)], [NoEarlyDecryptRule()])
    assert not errors
    assert violations == []


def test_eager_decrypt_at_share_arrival_redetected(tmp_path):
    # the canonical regression: decrypting the moment f+1 shares are in,
    # from the share-arrival handler — BEFORE any ACS output exists for
    # the epoch on slow nodes
    hits = _edit_and_lint(
        tmp_path,
        HB,
        "        if epoch == self.epoch or epoch in self._pending_reveals:\n"
        "            return self._try_output_batches()",
        "        if epoch == self.epoch or epoch in self._pending_reveals:\n"
        "            self._try_decrypt_proposer_contribution(proposer_id, epoch)\n"
        "            return self._try_output_batches()",
    )
    assert any(
        "_try_decrypt_proposer_contribution" in v.message
        and "_handle_decryption_share_message" in v.message
        for v in hits
    ), hits


def test_inline_combine_sink_in_handler_redetected(tmp_path):
    # a combine sink spliced straight into the message handler
    hits = _edit_and_lint(
        tmp_path,
        HB,
        "        self.received_shares.setdefault(epoch, {}).setdefault(\n"
        "            proposer_id, {}\n"
        "        )[sender_id] = share",
        "        self.received_shares.setdefault(epoch, {}).setdefault(\n"
        "            proposer_id, {}\n"
        "        )[sender_id] = share\n"
        "        if ciphertext is not None:\n"
        "            try:\n"
        "                self.netinfo.public_key_set."
        "combine_decryption_shares(\n"
        "                    {0: share}, ciphertext\n"
        "                )\n"
        "            except Exception:\n"
        "                pass",
    )
    assert any(
        "combine_decryption_shares()" in v.message
        and "_handle_decryption_share_message" in v.message
        for v in hits
    ), hits


def test_share_emission_before_acs_redetected(tmp_path):
    # emitting our decryption share from the CS message pump (i.e. on
    # every CS round, not at CS output) — caller-map violation
    hits = _edit_and_lint(
        tmp_path,
        HB,
        "        cs = self._common_subset(epoch)\n"
        "        cs_step = cs.handle_message(sender_id, cs_msg)",
        "        cs = self._common_subset(epoch)\n"
        "        for _pid, _ct in self.ciphertexts.get(epoch, {}).items():\n"
        "            self._send_decryption_share(_pid, _ct, epoch)\n"
        "        cs_step = cs.handle_message(sender_id, cs_msg)",
    )
    assert any(
        "_send_decryption_share" in v.message
        and "_handle_common_subset_message" in v.message
        for v in hits
    ), hits


def test_raw_decrypt_share_sink_outside_home_redetected(tmp_path):
    # the raw share-emission primitive used anywhere but its home
    hits = _edit_and_lint(
        tmp_path,
        HB,
        "        ciphertext = self.ciphertexts.get(epoch, {}).get(proposer_id)",
        "        ciphertext = self.ciphertexts.get(epoch, {}).get(proposer_id)\n"
        "        if ciphertext is not None:\n"
        "            self.netinfo.secret_key_share.decrypt_share_no_verify(\n"
        "                ciphertext\n"
        "            )",
    )
    assert any(
        "decrypt_share_no_verify()" in v.message for v in hits
    ), hits


def test_getattr_probe_outside_home_redetected(tmp_path):
    # getattr-probing the batch combine API from a handler counts as a
    # sink reference too (the speculative path's own idiom, misplaced)
    hits = _edit_and_lint(
        tmp_path,
        HB,
        "        ciphertext = self.ciphertexts.get(epoch, {}).get(proposer_id)",
        "        ciphertext = self.ciphertexts.get(epoch, {}).get(proposer_id)\n"
        "        combine = getattr(\n"
        "            self.netinfo.public_key_set,\n"
        '            "combine_and_check_decryption_shares",\n'
        "            None,\n"
        "        )\n"
        "        del combine",
    )
    assert any(
        "combine_and_check_decryption_shares()" in v.message for v in hits
    ), hits
