"""GF(2^16) Reed-Solomon — past the reference crate's 256-shard cap.

The reference's ``reed-solomon-erasure`` crate caps shards at 256
(``/root/reference/src/broadcast.rs:310-312``), which caps reliable
broadcast — and therefore the whole stack — at 256 validators.  The
GF(2^16) codec (``crypto/rs.py``) lifts that to 65536 with the same
systematic-Vandermonde construction; these tests gate VERDICT round-2
item 3: n=1024 codec roundtrips and a protocol-level ``Broadcast``
decision at n > 256.
"""

import random

import pytest

from hbbft_tpu.core.network_info import NetworkInfo
from hbbft_tpu.crypto.rs import (
    ReedSolomon,
    ReedSolomon16,
    gf16_inv,
    gf16_mul,
    make_codec,
)


class TestGf16:
    def test_field_axioms_sampled(self):
        rng = random.Random(7)
        for _ in range(200):
            a = rng.randrange(1, 1 << 16)
            b = rng.randrange(1, 1 << 16)
            c = rng.randrange(1 << 16)
            assert gf16_mul(a, b) == gf16_mul(b, a)
            assert gf16_mul(a, gf16_inv(a)) == 1
            # distributivity over XOR (field addition)
            assert gf16_mul(a, b ^ c) == gf16_mul(a, b) ^ gf16_mul(a, c)

    def test_mul_identity_and_zero(self):
        assert gf16_mul(0x1234, 1) == 0x1234
        assert gf16_mul(0x1234, 0) == 0
        assert gf16_mul(0, 0) == 0


class TestMakeCodec:
    def test_picks_narrowest_field(self):
        assert isinstance(make_codec(4, 2), ReedSolomon)
        assert isinstance(make_codec(200, 56), ReedSolomon)
        assert isinstance(make_codec(200, 57), ReedSolomon16)
        assert isinstance(make_codec(342, 682), ReedSolomon16)

    def test_too_many_shards_rejected(self):
        with pytest.raises(ValueError):
            ReedSolomon16(60000, 6000)


class TestReedSolomon16:
    def test_systematic_roundtrip_n1024(self):
        rng = random.Random(0xE5C)
        k, m = 342, 682  # n=1024, f=341: N-2f data + 2f parity
        codec = ReedSolomon16(k, m)
        data = [bytes(rng.randrange(256) for _ in range(8)) for _ in range(k)]
        shards = codec.encode(data)
        assert shards[:k] == data  # systematic
        slots = list(shards)
        for i in rng.sample(range(k + m), m):  # erase up to m shards
            slots[i] = None
        assert codec.reconstruct(slots) == shards

    def test_reconstruct_from_parity_only_slice(self):
        rng = random.Random(3)
        codec = ReedSolomon16(5, 300)
        data = [bytes([i]) * 4 for i in range(5)]
        shards = codec.encode(data)
        # keep only k arbitrary parity shards: all data erased
        slots = [None] * 305
        for i in rng.sample(range(5, 305), 5):
            slots[i] = shards[i]
        assert codec.reconstruct(slots) == shards

    def test_odd_shard_length_rejected(self):
        codec = ReedSolomon16(200, 60)
        data = [b"abc"] * 200  # 3 bytes: not a multiple of symbol=2
        with pytest.raises(ValueError):
            codec.encode(data)

    def test_insufficient_shards_raise(self):
        codec = ReedSolomon16(250, 10)
        shards = codec.encode([b"ab"] * 250)
        slots = [None] * 260
        slots[0] = shards[0]
        with pytest.raises(ValueError):
            codec.reconstruct(slots)

    def test_trivial_no_parity(self):
        codec = ReedSolomon16(300, 0)
        data = [b"xy"] * 300
        assert codec.encode(data) == data


class TestDeviceCodec16:
    def test_device_matches_host(self):
        from hbbft_tpu.ops.gf256_jax import ReedSolomonDevice16

        rng = random.Random(11)
        k, m = 90, 180  # n=270 > 256
        host = ReedSolomon16(k, m)
        dev = ReedSolomonDevice16(k, m)
        data = [bytes(rng.randrange(256) for _ in range(16)) for _ in range(k)]
        h = host.encode(data)
        d = dev.encode(data)
        assert h == d
        slots = list(h)
        for i in rng.sample(range(k + m), m):
            slots[i] = None
        assert dev.reconstruct(list(slots)) == h


class TestBroadcastPast256:
    """Protocol-level ``Broadcast`` decision at n=260 (> the GF(2^8)
    cap).  Drives one receiving node directly with crafted-but-honest
    Echo/Ready traffic instead of routing the O(N²) network, so the
    full Value→Echo→Ready→decode decision path runs in test time."""

    def test_broadcast_delivers_at_n260(self, rng):
        from hbbft_tpu.protocols.broadcast import (
            Broadcast,
            BroadcastEcho,
            BroadcastReady,
            BroadcastValue,
            frame_into_shards,
        )

        n = 260
        ids = list(range(n))
        netinfos = NetworkInfo.generate_map(ids, rng, mock=True)
        ni = netinfos[1]  # node 1 receives; node 0 proposes
        f = ni.num_faulty
        bc = Broadcast(ni, 0)
        assert bc.coding.symbol == 2  # GF(2^16) engaged past 256 shards

        value = bytes(rng.randrange(256) for _ in range(5000))
        data = frame_into_shards(value, bc.data_shard_num, bc.coding.symbol)
        shards = bc.coding.encode(data)
        mtree = ni.ops.merkle_tree(shards)
        root = mtree.root_hash

        step = bc.handle_message(0, BroadcastValue(mtree.proof(1)))
        assert not list(step.fault_log)  # echo sent
        # n − f echos (including our own, already handled via send loop)
        for sender in range(1, n - f):
            bc.handle_message(sender, BroadcastEcho(mtree.proof(sender)))
        out = []
        for sender in range(2 * f + 1):
            s = bc.handle_message(sender, BroadcastReady(root))
            out.extend(s.output)
        assert out == [value]
        assert bc.terminated()
