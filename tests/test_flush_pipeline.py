"""Pipelined flush engine (PR 4): batch-affine serialization,
staged async transfers, and persistent warm-start.

The contracts under test:

- ``batch_affine``/``batch_serialize`` are BIT-identical to the
  per-point inversion path (random points, identity-Z, infinity);
- the staged pipeline (``ops/staging.py``) is pure plumbing — staging
  on vs off yields identical MSM results, identical flush cache
  contents and identical fault attribution;
- finalizers expose the non-blocking ``ready()``/``poll()`` probe;
- the warm-start trio (``record_warm_shape`` → ``warm_shapes.json`` →
  ``prewarm_shapes``/``preload_exec``) round-trips executables
  disk → memory without compiling.
"""

import random
import threading

import numpy as np
import pytest

from hbbft_tpu.crypto.curve import G1, G1_GEN, G2, G2_GEN
from hbbft_tpu.ops import ec_jax, packed_msm, pallas_ec, staging


# ---------------------------------------------------------------------------
# batch-affine serialization
# ---------------------------------------------------------------------------


def _mixed_g1(rng, n):
    pts = [G1_GEN * rng.randrange(1, 1 << 64) for _ in range(n)]
    pts[0] = G1_GEN  # Z == 1: the batch-inversion shortcut edge
    if n >= 4:
        pts[2] = G1.infinity()
        pts[-1] = G1.infinity()
    return pts


def test_batch_affine_matches_per_point_g1():
    pts = _mixed_g1(random.Random(3), 9)
    affs = G1.batch_affine(pts)
    for p, aff in zip(pts, affs):
        if p.is_infinity():
            assert aff is None
        else:
            assert aff == p.affine()


def test_batch_affine_matches_per_point_g2():
    rng = random.Random(5)
    pts = [G2_GEN * rng.randrange(1, 1 << 64) for _ in range(6)]
    pts[0] = G2_GEN
    pts[3] = G2.infinity()
    affs = G2.batch_affine(pts)
    for p, aff in zip(pts, affs):
        if p.is_infinity():
            assert aff is None
        else:
            assert aff == p.affine()


def test_batch_affine_all_infinity_and_empty():
    assert G1.batch_affine([]) == []
    assert G1.batch_affine([G1.infinity()] * 3) == [None, None, None]


def test_batch_serialize_bit_identical_g1():
    from hbbft_tpu import native as NT

    base = _mixed_g1(random.Random(7), 8)
    # two memo-free copies of the same Jacobians: one serialized via
    # the batch inversion, one via the per-point path
    batch = [G1(p.jac) for p in base]
    solo = [G1(p.jac) for p in base]
    G1.batch_serialize(batch)
    for b, s in zip(batch, solo):
        assert b._cbytes == s.to_bytes()
        assert b._wire == NT.g1_wire(s)
        assert b.to_bytes() == s.to_bytes()  # memo serves the API


def test_batch_serialize_bit_identical_g2():
    from hbbft_tpu import native as NT

    rng = random.Random(11)
    base = [G2_GEN * rng.randrange(1, 1 << 64) for _ in range(5)]
    base[1] = G2.infinity()
    batch = [G2(p.jac) for p in base]
    solo = [G2(p.jac) for p in base]
    G2.batch_serialize(batch)
    for b, s in zip(batch, solo):
        assert b._cbytes == s.to_bytes()
        assert b._wire == NT.g2_wire(s)


def test_batch_serialize_skips_existing_memos():
    pts = [G1(G1_GEN.jac) for _ in range(2)]
    G1.batch_serialize(pts)
    memo = [(p._cbytes, p._wire) for p in pts]
    G1.batch_serialize(pts)  # all memoized: must be a no-op
    assert [(p._cbytes, p._wire) for p in pts] == [
        (c, w) for c, w in memo
    ]
    assert all(p._cbytes is m[0] for p, m in zip(pts, memo))


# ---------------------------------------------------------------------------
# staging machinery
# ---------------------------------------------------------------------------


def test_stager_fifo_order_and_results():
    st = staging.stager()
    order = []
    t1 = st.submit(lambda: (order.append(1), "a")[1])
    # FIFO + single worker: by the time t2 runs, t1 has completed
    t2 = st.submit(lambda: (order.append(2), t1.done())[1])
    assert t2.result() is True
    assert t1.result() == "a"
    assert order == [1, 2]


def test_stage_task_reraises_worker_error():
    def boom():
        raise RuntimeError("marshal failed")

    t = staging.stager().submit(boom)
    with pytest.raises(RuntimeError, match="marshal failed"):
        t.result()
    assert t.done() and t.failed()


def test_staging_disabled_runs_inline(monkeypatch):
    monkeypatch.setenv("HBBFT_TPU_STAGING", "0")
    ran_on = []
    t = staging.stager().submit(
        lambda: ran_on.append(threading.current_thread())
    )
    assert t.done()  # completed before submit returned
    assert ran_on == [threading.current_thread()]


def test_buffer_pool_lease_lifecycle():
    pool = staging.BufferPool()
    lease = pool.lease()
    a = lease.get((4, 3))
    b = lease.get((4, 3))
    assert a is not b  # one flush never aliases its own buffers
    assert a.dtype == np.uint8 and a.shape == (4, 3)
    a[:] = 7
    lease.retire()
    c = pool.lease().get((4, 3))
    assert c is a or c is b  # retired buffers are reused...
    assert not c.any()  # ...and handed back zeroed
    d = pool.lease().get((8, 3))
    assert d is not a and d is not b  # different shape: fresh alloc


# ---------------------------------------------------------------------------
# finalizer protocol
# ---------------------------------------------------------------------------


def test_eager_finalizer_protocol():
    from hbbft_tpu.crypto.backend import CpuBackend, EagerFinalizer

    fin = EagerFinalizer(42)
    assert fin.ready() and fin.poll()
    assert fin() == 42
    be = CpuBackend()
    pts = [G1_GEN * 3, G1_GEN * 5]
    afin = be.g1_msm_async(pts, [2, 4])
    assert afin.ready()
    assert afin() == be.g1_msm(pts, [2, 4])
    pfin = be.g1_msm_product_async(pts, [2, 4], [3], [2])
    assert pfin.ready() and pfin.poll()


def test_product_finalizer_memoizes_and_probes():
    calls = []
    fin = packed_msm.ProductFinalizer(
        lambda: (calls.append(1), "r")[1], probe=lambda: False
    )
    assert fin.ready() is False  # probe says the drain is still live
    assert fin() == "r"
    assert fin() == "r"
    assert calls == [1]  # second call is the memo
    assert fin.ready() is True  # done short-circuits the probe
    bare = packed_msm.ProductFinalizer(lambda: 1)
    assert bare.ready()  # no probe: born ready


# ---------------------------------------------------------------------------
# staging on/off determinism
# ---------------------------------------------------------------------------


def _host_windowed_tiles(pts_t, dig_t, interpret):
    # host stand-in for the Pallas windowed kernel (same as
    # test_packed.py): per-lane scalar-mul through the host curve ops
    pts_t = np.asarray(pts_t)
    dig_t = np.asarray(dig_t)
    G_, _, L, T = pts_t.shape
    out = np.zeros_like(pts_t)
    for g in range(G_):
        for t in range(T):
            pt = ec_jax.g1_from_limbs(pts_t[g, :, :, t])
            k = 0
            for d in dig_t[g, :, t]:
                k = (k << 4) | int(d)
            out[g, :, :, t] = ec_jax.g1_to_limbs([pt * k])[0]
    import jax.numpy as jnp

    return jnp.asarray(out)


def _product_case(seed=59, G_=4, n=3):
    rng = random.Random(seed)
    k = G_ * n
    pts = [G1_GEN * rng.randrange(1, 1 << 64) for _ in range(k)]
    pts[1] = G1.infinity()
    s = [rng.getrandbits(16) | 1 for _ in range(k)]
    ts = [rng.getrandbits(16) | 1 for _ in range(G_)]
    return pts, s, ts, [n] * G_


def test_product_msm_staging_on_off_identical(monkeypatch):
    from hbbft_tpu.crypto import fields as F
    from hbbft_tpu.crypto.backend import CpuBackend

    monkeypatch.setattr(pallas_ec, "_windowed_tiles", _host_windowed_tiles)
    pts, s, ts, sizes = _product_case()
    n = sizes[0]
    flat = [
        (s[g * n + i] * ts[g]) % F.R
        for g in range(len(sizes))
        for i in range(n)
    ]
    want = CpuBackend().g1_msm(pts, flat)

    monkeypatch.setenv("HBBFT_TPU_STAGING", "1")
    fin = packed_msm.g1_msm_product_async(pts, s, ts, sizes, interpret=True)
    assert fin is not None and fin() == want

    monkeypatch.setenv("HBBFT_TPU_STAGING", "0")
    fin = packed_msm.g1_msm_product_async(pts, s, ts, sizes, interpret=True)
    assert fin is not None and fin() == want


def _flush_case(seed=7):
    from hbbft_tpu.crypto import threshold as T
    from hbbft_tpu.harness.batching import DecObligation, SigObligation

    rng = random.Random(seed)
    sks = T.SecretKeySet.random(1, rng)
    pks = sks.public_keys()
    obs = []
    for m in (b"nonce-A", b"nonce-B"):
        for i in range(4):
            share = sks.secret_key_share(i).sign(m)
            obs.append(SigObligation(pks.public_key_share(i), share, m))
    ct = pks.public_key().encrypt(b"payload", rng)
    for i in range(4):
        share = sks.secret_key_share(i).decrypt_share_no_verify(ct)
        obs.append(DecObligation(pks.public_key_share(i), share, ct))
    # one forgery: staging on/off must attribute it identically
    forged = sks.secret_key_share(2).sign(b"other")
    obs[2] = SigObligation(pks.public_key_share(2), forged, b"nonce-A")
    return obs


def test_flush_cache_identical_staging_on_off(monkeypatch):
    from hbbft_tpu.harness.batching import BatchingBackend

    results = {}
    for mode in ("1", "0"):
        monkeypatch.setenv("HBBFT_TPU_STAGING", mode)
        be = BatchingBackend()
        be.prefetch(_flush_case())
        results[mode] = (
            dict(be._cache),
            be.stats.fallback_groups,
            be.stats.fallback_items,
        )
    assert results["1"] == results["0"]
    # the forgery was caught (some False in the cache) either way
    assert False in results["1"][0].values()


def test_preserialize_fills_memos_and_stamps_wall():
    from hbbft_tpu.harness.batching import BatchingBackend

    obs = _flush_case()
    be = BatchingBackend()
    be._preserialize(obs)
    assert be._preserialize_s >= 0.0
    for ob in obs:
        assert getattr(ob.pk_share.point, "_cbytes", None) is not None
        assert getattr(ob.share.point, "_wire", None) is not None
    # malformed obligations must not break the warm-up
    be._preserialize([object()])


def test_duplicate_cell_flush_stamps_phase_walls():
    # satellite 1: the independent-coefficients branch used to return
    # before stamping any wall, leaving the flush event's phases empty
    # (or a stale carryover) for exactly the double-send epochs
    from hbbft_tpu.crypto import threshold as T
    from hbbft_tpu.crypto.hashing import DST_SIG, hash_to_g1
    from hbbft_tpu.harness.batching import BatchingBackend, SigObligation

    rng = random.Random(13)
    sks = T.SecretKeySet.random(1, rng)
    pks = sks.public_keys()
    m = b"dup-nonce"
    base = hash_to_g1(m, DST_SIG)
    good = sks.secret_key_share(0).sign(m)
    delta = base * 999
    pk0 = pks.public_key_share(0)
    obs = [
        SigObligation(pk0, T.SignatureShare(good.point + delta), m),
        SigObligation(pk0, T.SignatureShare(good.point + (-delta)), m),
        *(
            SigObligation(
                pks.public_key_share(i), sks.secret_key_share(i).sign(m), m
            )
            for i in range(1, 4)
        ),
    ]
    be = BatchingBackend()
    be.prefetch(obs)
    ph = be.last_flush_phases
    for wall in ("serialize", "setup", "launch", "g2", "finalize", "pairing"):
        assert wall in ph and ph[wall] >= 0.0
    assert be.verify_sig_share(pk0, obs[0].share, m) is False
    assert be.verify_sig_share(pk0, obs[1].share, m) is False
    for i in range(1, 4):
        share = sks.secret_key_share(i).sign(m)
        assert be.verify_sig_share(pks.public_key_share(i), share, m) is True


# ---------------------------------------------------------------------------
# persistent warm-start
# ---------------------------------------------------------------------------


def test_warm_shape_record_roundtrip(monkeypatch, tmp_path):
    monkeypatch.setenv("HBBFT_TPU_EXEC_CACHE", str(tmp_path))
    monkeypatch.setattr(packed_msm, "_WARM_SEEN", set())
    packed_msm.record_warm_shape(1024, 64, False)
    packed_msm.record_warm_shape(1024, 64, True)  # sticky compressed
    packed_msm.record_warm_shape(974, 8, False)
    shapes = packed_msm._load_warm_shapes()
    assert shapes == {
        "1024:64": {"compressed": True},
        "974:8": {"compressed": False},
    }
    # dedupe: a repeat record is a no-op (no exception, same contents)
    packed_msm.record_warm_shape(1024, 64, True)
    assert packed_msm._load_warm_shapes() == shapes


def test_load_warm_shapes_tolerates_garbage(monkeypatch, tmp_path):
    monkeypatch.setenv("HBBFT_TPU_EXEC_CACHE", str(tmp_path))
    (tmp_path / "warm_shapes.json").write_text(
        '{"64:2": {"compressed": false}, "bogus": 1, "0:3": {}, "x:y": {}}'
    )
    assert packed_msm._load_warm_shapes() == {
        "64:2": {"compressed": False}
    }
    (tmp_path / "warm_shapes.json").write_text("not json")
    assert packed_msm._load_warm_shapes() == {}


def test_preload_exec_roundtrip(monkeypatch, tmp_path):
    monkeypatch.setenv("HBBFT_TPU_EXEC_CACHE", str(tmp_path))
    key_parts = (((2, 3), "int32"),)
    assert not pallas_ec.preload_exec("pwtest", key_parts)  # nothing on disk
    a = np.arange(6, dtype=np.int32).reshape(2, 3)
    out = pallas_ec.cached_compiled("pwtest", lambda x: x * 2, a)
    assert np.array_equal(np.asarray(out), a * 2)
    key = pallas_ec._exec_key("pwtest", key_parts)
    pallas_ec._EXEC_MEM.pop(key, None)
    assert pallas_ec.preload_exec("pwtest", key_parts)  # disk → memory
    assert key in pallas_ec._EXEC_MEM
    assert pallas_ec.preload_exec("pwtest", key_parts)  # already warm


def test_prewarm_shapes_loads_recorded_plan(monkeypatch, tmp_path):
    monkeypatch.setenv("HBBFT_TPU_EXEC_CACHE", str(tmp_path))
    monkeypatch.setattr(packed_msm, "_WARM_SEEN", set())
    monkeypatch.setattr(packed_msm, "_RHO_STATE", None)
    packed_msm.record_warm_shape(3, 4, False)
    # no .palexe files yet: everything stays cold, quietly
    assert packed_msm.prewarm_shapes() == 0
    # the keys prewarm probes are exactly the routing guard's
    plan = packed_msm._split_plan(12, 4)
    assert plan  # rho default 0.5 gives this shape a device share
    keys = {
        (name, parts)
        for g in plan
        for name, parts in packed_msm._product_exec_keys(g * 3, g, False)
    }
    assert any(name.startswith("gtree_g1_") for name, _ in keys)
    # v2 wire discipline: exact-row transfer, on-device bucket padding
    assert any(name == "unpack_g1_v2" for name, _ in keys)


def test_start_background_prewarm_idempotent(monkeypatch, tmp_path):
    monkeypatch.setenv("HBBFT_TPU_EXEC_CACHE", str(tmp_path))
    monkeypatch.setattr(packed_msm, "_PREWARM", None)
    th = packed_msm.start_background_prewarm()
    assert th is packed_msm.start_background_prewarm()  # one per process
    th.join(10)
    assert not th.is_alive()


# ---------------------------------------------------------------------------
# device_async trace event
# ---------------------------------------------------------------------------


def test_g1_msm_async_emits_device_async_event(monkeypatch):
    from hbbft_tpu.obs import recorder as obs_mod
    from hbbft_tpu.ops import backend_tpu

    rng = random.Random(17)
    pts = [G1_GEN * rng.randrange(1, 1 << 32) for _ in range(6)]
    scalars = [rng.getrandbits(32) | 1 for _ in range(6)]
    be = backend_tpu.TpuBackend()
    be.G1_DEVICE_MIN = 0
    be.G1_DEVICE_MAX = 1 << 62
    want = be.g1_msm(pts, scalars)

    captured = []

    class _Rec:
        def event(self, name, **fields):
            captured.append((name, fields))

        def span(self, *a, **k):
            import contextlib

            return contextlib.nullcontext()

        def observe(self, *a, **k):
            pass

        def count(self, *a, **k):
            pass

    # force the device fast path: pretend the backend is a TPU and
    # intercept the packed async entry with a host oracle
    import jax

    monkeypatch.setattr(obs_mod, "ACTIVE", _Rec())
    monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
    monkeypatch.setattr(
        packed_msm,
        "g1_msm_packed_async",
        lambda p, s, interpret=False: (lambda: want),
    )
    fin = be.g1_msm_async(pts, scalars)
    assert fin() == want
    evts = [f for n, f in captured if n == "device_op"]
    assert {
        "op": "g1_msm",
        "k": 6,
        "engine": "device_async",
    } in evts
