"""Device DKG dealing plane (``harness/dkg._run_real_device``) —
byte-identity against the host engine when both are fed the same
dealing polynomials, and self-consistency of the sampled mode."""

import random

import pytest

from hbbft_tpu.harness.dkg import VectorizedDkg


def _mk(n, t, seed):
    return VectorizedDkg(list(range(n)), t, random.Random(seed), mock=False)


def test_device_matches_host_same_coeffs():
    n, t = 7, 2
    dkg = _mk(n, t, 0xD0)
    coeffs = dkg._dealer_coeffs(random.Random(0xC0FFEE))
    host = _mk(n, t, 0xD0).run(
        verify_honest=False, coeffs=coeffs, engine="host"
    )
    dev = _mk(n, t, 0xD0).run(
        verify_honest=False, coeffs=coeffs, engine="device"
    )
    assert dev.engine == "device" and host.engine == "host"
    assert (
        dev.pk_set.public_key().to_bytes()
        == host.pk_set.public_key().to_bytes()
    )
    assert dev.pk_set.commitment.coeffs == host.pk_set.commitment.coeffs
    for i in range(n):
        assert dev.shares[i].scalar == host.shares[i].scalar
    assert dev.complete == host.complete and dev.fault_log.is_empty()


def test_device_sampled_keys_work():
    # sampled mode: self-consistent keys — a t+1 subset's signature
    # shares combine into a signature the master key verifies
    n, t = 7, 2
    res = _mk(n, t, 0xD1).run(verify_honest=False, engine="device")
    assert res.engine == "device"
    shares = {i: res.shares[i].sign(b"dev-dkg") for i in range(t + 1)}
    sig = res.pk_set.combine_signatures(shares)
    assert res.pk_set.verify_signature(sig, b"dev-dkg")
    # per-node commitment evaluation matches the dealt share scalar
    from hbbft_tpu.crypto.curve import G2_GEN

    for i in range(n):
        assert (
            res.pk_set.public_key_share(i).point.to_bytes()
            == (G2_GEN * res.shares[i].scalar).to_bytes()
        )


def test_engine_routing_defaults(monkeypatch):
    # with auto-routing pinned off, the default route is host;
    # adversarial or verified runs never take the device path
    # regardless of the engine hint
    monkeypatch.setenv("HBBFT_TPU_DKG_DEVICE", "0")
    n, t = 4, 1
    dkg = _mk(n, t, 0xD2)
    res = dkg.run(verify_honest=False)
    assert res.engine == "host"
    monkeypatch.setenv("HBBFT_TPU_DKG_DEVICE", "1")
    forced = _mk(n, t, 0xD2).run(verify_honest=False)
    assert forced.engine == "device"
    monkeypatch.delenv("HBBFT_TPU_DKG_DEVICE")
    res2 = _mk(n, t, 0xD2).run(
        verify_honest=True, engine="device"
    )
    assert res2.engine == "host"  # verified mode: full host machinery
    with_adv = _mk(n, t, 0xD2).run(
        verify_honest=False,
        wrong_row={0: {1}},
        engine="device",
    )
    assert with_adv.engine == "host"


def test_engine_routing_capability_gate(monkeypatch):
    # past the u8-matmul contraction bound (fr_jax._MAX_K) the device
    # engine would raise mid-DKG; auto AND explicit routing fall back
    # to the host engine instead (ADVICE r4 #2)
    from hbbft_tpu.ops import fr_jax

    monkeypatch.setattr(fr_jax, "_MAX_K", 1)
    res = _mk(4, 1, 0xD3).run(verify_honest=False, engine="device")
    assert res.engine == "host"
