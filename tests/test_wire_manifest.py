"""The wire manifest vs the live registry, and byte-identical
round-trips for every pinned type.

Three layers of defense for the serialization contract (signed
payloads decode across versions — ``core/serialize.py``):

1. the static lint (``wire-stability``) pins the source to
   ``hbbft_tpu/analysis/wire_manifest.json``;
2. this module cross-checks the *live* registry — every manifest type
   imports, registers under the pinned tag, and (for dataclasses)
   exposes exactly the pinned field order at runtime;
3. a curated instance of every manifest type round-trips through
   ``dumps``/``loads`` byte-identically, so the codec itself can't
   drift under a type either.

The sample factory is asserted complete against the manifest: adding a
``@wire`` type without a sample here fails, which is the point — new
wire formats ship with a pinned byte-level example.
"""

import dataclasses
import importlib
import json

import pytest

from hbbft_tpu.analysis.rules.wire_stability import DEFAULT_MANIFEST
from hbbft_tpu.core.serialize import (
    SerializationError,
    _BY_NAME,
    dumps,
    loads,
    wire,
)


def _manifest():
    with open(DEFAULT_MANIFEST) as fh:
        return json.load(fh)


def _import_manifest_modules(manifest):
    for info in manifest["types"].values():
        importlib.import_module(
            "hbbft_tpu." + info["module"][: -len(".py")].replace("/", ".")
        )


# ---------------------------------------------------------------------------
# manifest ↔ live registry
# ---------------------------------------------------------------------------


def test_manifest_matches_live_registry():
    manifest = _manifest()
    _import_manifest_modules(manifest)
    for name, info in manifest["types"].items():
        assert name in _BY_NAME, f"manifest type {name!r} not registered"
        cls = _BY_NAME[name][0]
        if info["kind"] == "dataclass":
            assert dataclasses.is_dataclass(cls)
            live = [f.name for f in dataclasses.fields(cls)]
            assert live == info["fields"], (
                f"{name}: live field order {live} != manifest "
                f"{info['fields']}"
            )


def test_manifest_pins_primitive_tag_bytes():
    from hbbft_tpu.core import serialize

    manifest = _manifest()
    assert manifest["primitive_tags"], "no primitive tags pinned"
    for tag_name, byte in manifest["primitive_tags"].items():
        live = getattr(serialize, tag_name)
        assert live == bytes([byte]), f"{tag_name}: 0x{byte:02x} != {live!r}"


# ---------------------------------------------------------------------------
# byte-identical round-trips
# ---------------------------------------------------------------------------


def _samples():
    """One representative instance per wire tag.  Nested fields use
    real wire objects where the shape matters and small scalars where
    the codec treats them opaquely."""
    manifest = _manifest()
    _import_manifest_modules(manifest)
    from hbbft_tpu.crypto.curve import G1_GEN, G2_GEN
    from hbbft_tpu.crypto.merkle import MerkleProof
    from hbbft_tpu.crypto.poly import BivarCommitment, BivarPoly, Commitment, Poly

    cls = {name: _BY_NAME[name][0] for name in manifest["types"]}

    poly = Poly([3, 1, 4, 1, 5])
    commitment = Commitment([G2_GEN, G2_GEN.double()])
    proof = MerkleProof(b"leaf", 1, (b"\x11" * 32, b"\x22" * 32), b"\x33" * 32)
    vote = cls["Vote"](cls["ChangeAdd"]("node-9", b"pk"), 2, 7)
    signed_vote = cls["SignedVote"](vote, "node-3", cls["MockSig"](b"\xaa" * 32))
    dkg_ack = cls["DkgAck"](1, {0: b"row0", 1: b"row1"})
    dkg_part = cls["DkgPart"](commitment, [b"r0", b"r1"], G1_GEN)

    samples = {
        # crypto/threshold.py (real BLS objects are curve points)
        "Sig": cls["Sig"](G1_GEN),
        "SigShare": cls["SigShare"](G1_GEN.double()),
        "DecShare": cls["DecShare"](G1_GEN),
        "Ciphertext": cls["Ciphertext"](G1_GEN, b"\x05" * 16, G2_GEN, G1_GEN),
        "PublicKey": cls["PublicKey"](G1_GEN, G2_GEN),
        "SecretKey": cls["SecretKey"](12345),
        "SecretKeyShare": cls["SecretKeyShare"](67890),
        "PublicKeyShare": cls["PublicKeyShare"](G2_GEN),
        "PublicKeySet": cls["PublicKeySet"](commitment, G1_GEN),
        "SecretKeySet": cls["SecretKeySet"](poly),
        # crypto/mock.py
        "MockSig": cls["MockSig"](b"\x01" * 32),
        "MockSigShare": cls["MockSigShare"](b"\x02" * 32, b"\x03" * 32),
        "MockDecShare": cls["MockDecShare"](b"\x04" * 32, b"\x05" * 32),
        "MockCiphertext": cls["MockCiphertext"](
            b"\x06" * 32, b"\x07" * 16, b"payload", b"\x08" * 32
        ),
        "MockPublicKey": cls["MockPublicKey"](b"\x09" * 32),
        "MockSecretKey": cls["MockSecretKey"](b"\x0a" * 32),
        "MockSecretKeyShare": cls["MockSecretKeyShare"](b"\x0b" * 32, 4),
        "MockPublicKeyShare": cls["MockPublicKeyShare"](b"\x0c" * 32, 4),
        "MockPublicKeySet": cls["MockPublicKeySet"](b"\x0d" * 32, 2),
        # crypto/poly.py + merkle + curve
        "Poly": poly,
        "Commitment": commitment,
        "BivarPoly": BivarPoly([[1, 2], [3, 4]]),
        "BivarCommitment": BivarCommitment([[G2_GEN], [G2_GEN.double()]]),
        "MerkleProof": proof,
        "G1": G1_GEN,
        "G2": G2_GEN,
        # protocols
        "BoolSet": cls["BoolSet"](2),
        "SbvBVal": cls["SbvBVal"](True),
        "SbvAux": cls["SbvAux"](False),
        "AbaSbv": cls["AbaSbv"](cls["SbvBVal"](True)),
        "AbaConf": cls["AbaConf"](cls["BoolSet"](3)),
        "AbaTerm": cls["AbaTerm"](True),
        "AbaCoin": cls["AbaCoin"](cls["CoinMsg"](cls["MockSigShare"](b"t", b"c"))),
        "AbaMsg": cls["AbaMsg"](5, cls["AbaTerm"](False)),
        "CoinMsg": cls["CoinMsg"](cls["MockSigShare"](b"t", b"c")),
        "BcValue": cls["BcValue"](proof),
        "BcEcho": cls["BcEcho"](proof),
        "BcReady": cls["BcReady"](b"\x33" * 32),
        "CsBc": cls["CsBc"]("node-1", cls["BcReady"](b"\x33" * 32)),
        "CsAba": cls["CsAba"]("node-1", cls["AbaMsg"](0, cls["AbaTerm"](True))),
        "HbBatch": cls["HbBatch"](3, {"node-1": b"contrib"}),
        "HbOrderedBatch": cls["HbOrderedBatch"](
            3, 2, b"\x44" * 32, ("node-0", "node-1")
        ),
        "HbCs": cls["HbCs"](cls["CsBc"]("node-1", cls["BcReady"](b"\x33" * 32))),
        "HbDec": cls["HbDec"]("node-2", cls["MockDecShare"](b"t", b"k")),
        "HbMsg": cls["HbMsg"](3, cls["HbDec"]("n", cls["MockDecShare"](b"t", b"k"))),
        "Vote": vote,
        "SignedVote": signed_vote,
        "ChangeAdd": cls["ChangeAdd"]("node-9", b"pk"),
        "ChangeRemove": cls["ChangeRemove"]("node-9"),
        "CsNone": cls["CsNone"](),
        "CsInProgress": cls["CsInProgress"](cls["ChangeRemove"]("node-9")),
        "CsComplete": cls["CsComplete"](cls["ChangeAdd"]("node-9", b"pk")),
        "DkgPart": dkg_part,
        "DkgAck": dkg_ack,
        "KgPart": cls["KgPart"](dkg_part),
        "KgAck": cls["KgAck"](dkg_ack),
        "SignedKgMsg": cls["SignedKgMsg"](
            1, "node-0", cls["KgAck"](dkg_ack), cls["MockSig"](b"\xbb" * 32)
        ),
        "InternalContrib": cls["InternalContrib"](
            b"user-payload", (cls["SignedKgMsg"](1, "n", cls["KgAck"](dkg_ack), None),),
            (signed_vote,),
        ),
        "DhbHb": cls["DhbHb"](0, cls["HbBatch"](0, {})),
        "DhbKeyGen": cls["DhbKeyGen"](1, cls["KgPart"](dkg_part), cls["MockSig"](b"s")),
        "DhbVote": cls["DhbVote"](signed_vote),
        "JoinPlan": cls["JoinPlan"](
            9, cls["CsNone"](), cls["MockPublicKeySet"](b"\x0d" * 32, 2),
            {"node-0": cls["MockPublicKey"](b"\x09" * 32)},
        ),
        # harness
        "DynContrib": cls["DynContrib"](b"user", (signed_vote,)),
        # serve (client wire protocol)
        "SrvHello": cls["SrvHello"](1, "tenant-0", "client-7"),
        "SrvHelloAck": cls["SrvHelloAck"](True, "ok", 262144),
        "SrvSubmit": cls["SrvSubmit"](42, b"tx-payload"),
        "SrvSubmitAck": cls["SrvSubmitAck"](42, False, 50, "tenant-full"),
        "SrvCommitAck": cls["SrvCommitAck"](42, 3),
        "SrvOrderedAck": cls["SrvOrderedAck"](3, 2, b"\x44" * 32),
        "SrvRevealNote": cls["SrvRevealNote"](3, 2, 150),
        "SrvGossip": cls["SrvGossip"]((b"tx-a", b"tx-b")),
        # transport (session resumption + state transfer + telemetry)
        "RsHello": cls["RsHello"]("127.0.0.1:7001", 5),
        "RsWelcome": cls["RsWelcome"](5),
        "RsData": cls["RsData"](7, b"payload"),
        "RsAck": cls["RsAck"](7),
        "StReq": cls["StReq"](0, 3, False),
        "StMeta": cls["StMeta"](0, 3, b"\x11" * 32, 1024, 1),
        "StChunk": cls["StChunk"](0, 0, b"chunk-bytes"),
        "StDone": cls["StDone"](3, b"\x11" * 32),
        "ObTrace": cls["ObTrace"]("127.0.0.1:7001", 42, 3),
    }
    return manifest, samples


def test_every_manifest_type_round_trips_byte_identically():
    manifest, samples = _samples()
    missing = sorted(set(manifest["types"]) - set(samples))
    assert missing == [], f"no round-trip sample for: {missing}"
    extra = sorted(set(samples) - set(manifest["types"]))
    assert extra == [], f"samples without manifest entry: {extra}"
    for name, obj in sorted(samples.items()):
        blob = dumps(obj)
        back = loads(blob)
        assert type(back) is type(obj), name
        assert dumps(back) == blob, f"{name}: re-encode changed bytes"


# ---------------------------------------------------------------------------
# wire() duplicate-registration guard
# ---------------------------------------------------------------------------


def test_wire_rejects_duplicate_tag_name():
    @wire("_TestDupA")
    @dataclasses.dataclass(frozen=True)
    class A:
        x: int

    with pytest.raises(SerializationError, match="already registered"):

        @wire("_TestDupA")
        @dataclasses.dataclass(frozen=True)
        class B:
            y: int


def test_wire_rejects_reregistering_a_class():
    @dataclasses.dataclass(frozen=True)
    class C:
        x: int

    wire("_TestDupC")(C)
    with pytest.raises(SerializationError, match="already registered as"):
        wire("_TestDupC2")(C)
