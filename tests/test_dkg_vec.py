"""Vectorized DKG + dynamic-layer tests (VERDICT r2 item 3).

Gates:
- the vectorized DKG's ``pk_set`` and every node's secret share are
  **byte-identical** to the sequential ``SyncKeyGen`` given the same
  dealing polynomials (both verification modes);
- the single fused MSM catches corrupted rows/values with the same
  fault attribution as the sequential machine;
- the vectorized churn cycle (vote → on-chain DKG → era switch)
  reaches the same semantic trajectory as the sequential
  DynamicHoneyBadger network: same membership changes completed, all
  transactions committed, and the post-churn network functional under
  its new keys.
"""

import random

import pytest

from hbbft_tpu.core.fault import FaultKind
from hbbft_tpu.crypto import threshold as T
from hbbft_tpu.crypto.poly import BivarPoly
from hbbft_tpu.harness.dkg import VectorizedDkg
from hbbft_tpu.harness.dynamic import VectorizedDynamicSim
from hbbft_tpu.protocols import change as C
from hbbft_tpu.protocols.sync_key_gen import SyncKeyGen

pytestmark = pytest.mark.skipif(
    not __import__("hbbft_tpu.native", fromlist=["available"]).available(),
    reason="vectorized real-BLS DKG requires the native library",
)


def sequential_dkg(n, t, dealer_seed):
    """Full sequential SyncKeyGen network with per-dealer aligned rngs;
    returns (per-node (pk_set, share), the dealing coefficients)."""
    ids = list(range(n))
    sec_keys = {
        i: T.SecretKey.random(random.Random(1000 + i)) for i in ids
    }
    pub_keys = {i: sec_keys[i].public_key() for i in ids}
    nodes = {
        i: SyncKeyGen(
            i, sec_keys[i], pub_keys, t, random.Random(f"{dealer_seed}-{i}")
        )
        for i in ids
    }
    for d in ids:
        part = nodes[d].our_part
        acks = {}
        for r in ids:
            ack, faults = nodes[r].handle_part(
                d, part, rng=random.Random(f"enc-{d}-{r}")
            )
            assert ack is not None and faults.is_empty()
            acks[r] = ack
        for s in ids:
            for r in ids:
                assert nodes[r].handle_ack(s, acks[s]).is_empty()
    assert all(nodes[i].is_ready() for i in ids)
    coeffs = [
        BivarPoly.random(t, random.Random(f"{dealer_seed}-{d}")).coeffs
        for d in ids
    ]
    return {i: nodes[i].generate() for i in ids}, coeffs


class TestDkgEquivalence:
    @pytest.mark.parametrize("verify_honest", [True, False])
    def test_matches_sequential_n4(self, verify_honest):
        n, t = 4, 1
        seq, coeffs = sequential_dkg(n, t, "dkg4")
        dkg = VectorizedDkg(list(range(n)), t, random.Random(9), mock=False)
        res = dkg.run(
            verify_honest=verify_honest,
            coeffs=[list(map(list, c)) for c in coeffs],
        )
        assert res.fault_log.is_empty()
        seq_pk = seq[0][0]
        assert res.pk_set.commitment == seq_pk.commitment
        assert res.pk_set.master_g1 == seq_pk.master_g1
        for i in range(n):
            assert res.shares[i].scalar == seq[i][1].scalar
        if verify_honest:
            assert res.msm_points == n * (t + 1) ** 2
            assert res.row_checks == n * n
            assert res.value_checks == n * n * n

    def test_matches_sequential_n7_verified(self):
        n, t = 7, 2
        seq, coeffs = sequential_dkg(n, t, "dkg7")
        dkg = VectorizedDkg(list(range(n)), t, random.Random(10), mock=False)
        res = dkg.run(
            verify_honest=True, coeffs=[list(map(list, c)) for c in coeffs]
        )
        assert res.fault_log.is_empty()
        assert res.pk_set.commitment == seq[0][0].commitment
        for i in range(n):
            assert res.shares[i].scalar == seq[i][1].scalar

    def test_generated_keys_function(self):
        # threshold sign + combine + threshold encrypt/decrypt round-trip
        n, t = 7, 2
        dkg = VectorizedDkg(list(range(n)), t, random.Random(13), mock=False)
        res = dkg.run(verify_honest=False)
        sig_shares = {
            i: res.shares[i].sign(b"post-dkg") for i in range(t + 1)
        }
        sig = res.pk_set.combine_signatures(sig_shares)
        assert res.pk_set.verify_signature(sig, b"post-dkg")
        ct = res.pk_set.public_key().encrypt(b"secret", random.Random(14))
        dec_shares = {
            i: res.shares[i].decrypt_share_no_verify(ct)
            for i in range(t + 1)
        }
        assert (
            res.pk_set.combine_decryption_shares(dec_shares, ct)
            == b"secret"
        )


class TestDkgAdversaries:
    @pytest.mark.parametrize("verify_honest", [True, False])
    def test_bad_row_and_value_attributed(self, verify_honest):
        n, t = 4, 1
        dkg = VectorizedDkg(list(range(n)), t, random.Random(11), mock=False)
        res = dkg.run(
            verify_honest=verify_honest,
            wrong_row={2: {0}},
            wrong_value={(1, 3): {2}},
        )
        kinds = {(f.node_id, f.kind) for f in res.fault_log}
        assert (2, FaultKind.INVALID_PART) in kinds
        assert (3, FaultKind.INVALID_ACK) in kinds
        # one refused ack (node 0 on part 2) still leaves > 2t acks
        assert set(res.complete) == set(range(n))
        # every node still reconstructs a working share (node 2
        # interpolates dealer 1's column from the other senders)
        sig_shares = {
            i: res.shares[i].sign(b"adv") for i in (0, 2)
        }
        sig = res.pk_set.combine_signatures(sig_shares)
        assert res.pk_set.verify_signature(sig, b"adv")

    def test_clean_run_no_faults(self):
        n, t = 4, 1
        dkg = VectorizedDkg(list(range(n)), t, random.Random(15), mock=False)
        res = dkg.run(verify_honest=True)
        assert res.fault_log.is_empty()
        assert set(res.complete) == set(range(n))


class TestDkgMockAndScale:
    def test_mock_run(self):
        dkg = VectorizedDkg(list(range(7)), 2, random.Random(12), mock=True)
        res = dkg.run()
        assert len(res.shares) == 7
        shares = {i: res.shares[i].sign(b"m") for i in range(3)}
        sig = res.pk_set.combine_signatures(shares)
        assert res.pk_set.verify_signature(sig, b"m")

    def test_scale_smoke_n32_elided(self):
        # the co-simulation shape: honest checks elided, full dealing +
        # generation at a size the sequential machine cannot touch in CI
        n = 32
        t = (n - 1) // 3
        dkg = VectorizedDkg(list(range(n)), t, random.Random(16), mock=False)
        res = dkg.run(verify_honest=False)
        assert len(res.shares) == n
        sig_shares = {
            i: res.shares[i].sign(b"s32") for i in range(t + 1)
        }
        sig = res.pk_set.combine_signatures(sig_shares)
        assert res.pk_set.verify_signature(sig, b"s32")


class TestVectorizedChurn:
    def _cycle(self, mock, n, seed):
        sim = VectorizedDynamicSim(n, random.Random(seed), mock=mock)
        f = (n - 1) // 3
        committed = set()
        changes = []

        def run(txs):
            res = sim.run_epoch(txs)
            committed.update(res.batch.tx_iter())
            if not isinstance(res.change, C.NoChange):
                changes.append(res.change)
            return res

        run({i: [b"tx-%d-0" % i] for i in sim.validators})
        for v in sim.validators[: f + 1]:
            sim.vote_for(v, C.Remove(n - 1))
        run({i: [b"tx-%d-1" % i] for i in sim.validators})
        assert (n - 1) not in sim.validators
        run({i: [b"tx-%d-2" % i] for i in sim.validators})
        pk = sim.register_candidate(n - 1)
        for v in sim.validators[: f + 1]:
            sim.vote_for(v, C.Add(n - 1, pk))
        run({i: [b"tx-%d-3" % i] for i in sim.validators})
        assert (n - 1) in sim.validators
        res = run({i: [b"tx-%d-4" % i] for i in sim.validators})
        return sim, committed, changes, res

    def test_churn_cycle_mock(self):
        sim, committed, changes, last = self._cycle(True, 7, 40)
        assert [type(c.change) for c in changes] == [C.Remove, C.Add]
        assert sim.era == 2
        assert last.batch.epoch == 4  # numbering continues across eras

    def test_churn_cycle_real_bls(self):
        sim, committed, changes, last = self._cycle(False, 4, 41)
        assert [type(c.change) for c in changes] == [C.Remove, C.Add]
        assert sim.era == 2
        # the post-churn era runs on DKG-generated keys, not dealt ones
        ni = sim.sim.netinfos[0]
        assert isinstance(ni.secret_key_share, T.SecretKeyShare)

    def test_matches_sequential_churn_semantics(self):
        """Cross-engine gate: the sequential DynamicHoneyBadger network
        and the vectorized dynamic sim, driven through the same
        Remove(0) → Add(0) cycle, end in the same state — both changes
        completed in order, the same transaction set committed, the
        same final validator set, and a working post-churn epoch."""
        from test_dynamic_honey_badger import _run_dhb_churn, batch_key

        net = _run_dhb_churn(88, mock=True, txs_per_node=2)
        seq_node = net.nodes[0]
        seq_committed = {tx for b in seq_node.outputs for tx in b.tx_iter()}
        seq_changes = [
            b.change
            for b in seq_node.outputs
            if isinstance(b.change, C.Complete)
        ]
        assert [type(c.change) for c in seq_changes] == [C.Remove, C.Add]
        seq_validators = sorted(
            seq_node.instance.netinfo.all_ids
        )

        n = len(net.nodes)
        sim = VectorizedDynamicSim(n, random.Random(89), mock=True)
        f = (n - 1) // 3
        txs = {
            nid: [b"tx-%d-%d" % (nid, i) for i in range(2)]
            for nid in range(n)
        }
        committed = set()
        changes = []
        for v in range(n):
            sim.vote_for(v, C.Remove(0))
        r = sim.run_epoch(txs)
        committed.update(r.batch.tx_iter())
        assert isinstance(r.change, C.Complete)
        changes.append(r.change)
        assert 0 not in sim.validators
        pk = sim.pub_keys[0]
        for v in sim.validators:
            sim.vote_for(v, C.Add(0, pk))
        r = sim.run_epoch({i: txs[i] for i in sim.validators})
        committed.update(r.batch.tx_iter())
        assert isinstance(r.change, C.Complete)
        changes.append(r.change)
        # common subset needs ≥ N−f proposers every epoch: the rest
        # propose empty contributions while node 0 catches up
        r = sim.run_epoch(
            {i: (txs[i] if i == 0 else []) for i in sim.validators}
        )
        committed.update(r.batch.tx_iter())

        assert [type(c.change) for c in changes] == [
            type(c.change) for c in seq_changes
        ]
        # same nodes changed (the Add public keys are per-run key
        # material — different dealing seeds — so compare identities)
        assert changes[0].change == seq_changes[0].change  # Remove(0)
        assert changes[1].change.node_id == seq_changes[1].change.node_id
        assert committed == seq_committed
        assert sorted(sim.validators) == seq_validators

    def test_stale_era_votes_dropped(self):
        """A vote cast before an era switch by a node that was dead for
        the switching epoch must NOT ride into the next era (era-scoped
        pending votes, ``votes.rs:64-85``) — it would be flagged as an
        invalid-era vote against an honest node."""
        n = 7
        sim = VectorizedDynamicSim(n, random.Random(42), mock=True)
        sim.vote_for(3, C.Remove(0))  # goes stale: 3 is dead this epoch
        for v in (1, 2, 4):
            sim.vote_for(v, C.Remove(6))
        r = sim.run_epoch(
            {i: [b"a%d" % i] for i in range(n) if i != 3}, dead={3}
        )
        assert isinstance(r.change, C.Complete) and sim.era == 1
        r = sim.run_epoch({i: [b"b%d" % i] for i in sim.validators})
        assert r.fault_log.is_empty(), list(r.fault_log)
        assert isinstance(r.change, C.NoChange)


class TestDynamicQueueing:
    """QHB = DHB + queue in the vectorized stack (VERDICT r2 missing
    #1: the round-2 driver's 'QHB' wrapped the static HB sim)."""

    def test_queueing_with_churn_mock(self):
        from hbbft_tpu.harness.dynamic import VectorizedDynamicQueueingSim

        n = 7
        q = VectorizedDynamicQueueingSim(
            n, random.Random(50), batch_size=16, mock=True
        )
        q.input_all([b"t-%02d" % i for i in range(16)])
        f = (n - 1) // 3
        committed = set()
        r = q.run_epoch()
        committed.update(r.batch.tx_iter())
        # vote to remove the last node mid-stream
        for v in q.validators[: f + 1]:
            q.vote_for(v, C.Remove(n - 1))
        r = q.run_epoch()
        committed.update(r.batch.tx_iter())
        assert isinstance(r.change, C.Complete) and q.era == 1
        assert (n - 1) not in q.validators
        # drain the queue under the new era's keys
        guard = 0
        while any(len(qq) for qq in q.queues.values()):
            guard += 1
            assert guard < 20
            r = q.run_epoch()
            committed.update(r.batch.tx_iter())
        assert committed == {b"t-%02d" % i for i in range(16)}

    def test_queueing_divergent_injection(self):
        from hbbft_tpu.harness.dynamic import VectorizedDynamicQueueingSim

        q = VectorizedDynamicQueueingSim(
            4, random.Random(51), batch_size=8, mock=True
        )
        q.input_all([b"s1", b"s2"])
        q.input_node(2, [b"only2"])
        assert q.diverged
        committed = set()
        for _ in range(4):
            committed.update(q.run_epoch().batch.tx_iter())
            if all(len(qq) == 0 for qq in q.queues.values()):
                break
        assert committed == {b"s1", b"s2", b"only2"}

    def test_queueing_real_bls_churn(self):
        from hbbft_tpu.harness.dynamic import VectorizedDynamicQueueingSim

        n = 4
        q = VectorizedDynamicQueueingSim(
            n, random.Random(52), batch_size=8, mock=False
        )
        q.input_all([b"r-%d" % i for i in range(8)])
        for v in q.validators[:2]:
            q.vote_for(v, C.Remove(n - 1))
        committed = set()
        r = q.run_epoch()
        committed.update(r.batch.tx_iter())
        assert isinstance(r.change, C.Complete)
        guard = 0
        while any(len(qq) for qq in q.queues.values()):
            guard += 1
            assert guard < 20
            committed.update(q.run_epoch().batch.tx_iter())
        assert committed == {b"r-%d" % i for i in range(8)}


class TestDynamicVirtualTime:
    def test_era_switch_epoch_accounts_dkg(self):
        from hbbft_tpu.harness.simulation import HwQuality

        hw = HwQuality.from_flags(lag_ms=50, bw_kbit_s=10_000, cpu_pct=100)
        sim = VectorizedDynamicSim(7, random.Random(60), mock=True, hw=hw)
        plain = sim.run_epoch({i: [b"p%d" % i] for i in range(7)})
        assert "dkg-part" not in plain.inner.virtual.breakdown
        for v in range(3):
            sim.vote_for(v, C.Remove(6))
        churn = sim.run_epoch({i: [b"q%d" % i] for i in range(7)})
        assert isinstance(churn.change, C.Complete)
        v = churn.inner.virtual
        assert "dkg-part" in v.breakdown and "dkg-ack" in v.breakdown
        assert "cpu:dkg" in v.breakdown
        # the DKG traffic makes the switching epoch strictly costlier
        assert v.total_s > plain.inner.virtual.total_s
        assert abs(v.total_s - (v.network_s + v.cpu_s)) < 1e-9


class TestJoinPlan:
    def test_join_plan_tracks_era_and_observer_verifies(self):
        """The vectorized dynamic layer's join plan (reference
        ``mod.rs:136-145``): a fresh observer hydrated from the plan
        holds the CURRENT era's keys — including after a DKG era
        switch — and can verify a threshold signature made by the new
        validators."""
        sim = VectorizedDynamicSim(4, random.Random(70), mock=False)
        p0 = sim.join_plan()
        assert sorted(p0.pub_keys) == [0, 1, 2, 3]
        for v in (0, 1):
            sim.vote_for(v, C.Remove(3))
        r = sim.run_epoch({i: [b"j%d" % i] for i in range(4)})
        assert isinstance(r.change, C.Complete)
        p1 = sim.join_plan()
        assert sorted(p1.pub_keys) == [0, 1, 2]
        # the plan carries the change that produced this era
        assert isinstance(p1.change, C.Complete)
        assert p1.change.change == C.Remove(3)
        assert p1.epoch == sim.epoch and p1.pub_key_set is sim.sim.pk_set
        obs = sim.observer_from_plan(p1)
        assert not obs.is_validator
        # the observer's view verifies a signature under the NEW keys
        ni0 = sim.sim.netinfos[0]
        shares = {
            i: sim.sim.netinfos[i].secret_key_share.sign(b"post-churn")
            for i in (0, 1)
        }
        sig = ni0.public_key_set.combine_signatures(shares)
        assert obs.public_key_set.verify_signature(sig, b"post-churn")
        # and an epoch run with observe=True still matches (public lane)
        r2 = sim.run_epoch(
            {i: [b"k%d" % i] for i in sim.validators}, observe=True
        )
        assert (
            r2.inner.observer_batch.contributions
            == r2.inner.batch.contributions
        )
