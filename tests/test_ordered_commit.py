"""Order-then-reveal commit pipeline (PR 19).

Covers the split at every layer:

- protocol: ``reveal_mode="ordered"`` emits an :class:`OrderedBatch`
  at ACS completion (contiguous seqs, cross-node digest agreement)
  and the plaintext :class:`Batch` afterwards, byte-identical to the
  inline pipeline's
- vectorized harness: ordered runs produce bit-identical batches and
  identical fault attribution (deferred to the reveal) vs inline
- gateway: the epoch-scoped ``OrderedAck`` / ``RevealNote`` fan-out is
  at-most-once / exactly-once per (connection, epoch), ages under GC,
  and its wire validators are total
"""

import random

import pytest

from hbbft_tpu.crypto.mock import MockDecryptionShare
from hbbft_tpu.harness.epoch import VectorizedHoneyBadgerSim
from hbbft_tpu.harness.network import (
    MessageScheduler,
    SilentAdversary,
    TestNetwork,
)
from hbbft_tpu.protocols.honey_badger import (
    Batch,
    HoneyBadger,
    HoneyBadgerBuilder,
    OrderedBatch,
    default_reveal_mode,
    ordered_batch_digest,
)
from hbbft_tpu.serve.gateway import GatewayCore
from hbbft_tpu.serve.protocol import (
    ClientHello,
    OrderedAck,
    RevealNote,
    SubmitTx,
    frame,
    loads,
    validate_ordered_ack,
    validate_reveal_note,
)

# -- protocol plane ----------------------------------------------------------


def _run_net(reveal_mode, seed=7, n=4, epochs=3):
    """Drive an n-node mock-crypto TestNetwork for ``epochs`` proposals
    per node → per-node output lists."""
    rng = random.Random(seed)
    net = TestNetwork(
        n,
        0,
        lambda adv: SilentAdversary(
            MessageScheduler(MessageScheduler.RANDOM, rng)
        ),
        lambda ni: HoneyBadger(
            ni,
            rng=random.Random(f"oc-{ni.our_id}-{seed}"),
            reveal_mode=reveal_mode,
        ),
        rng,
        mock_crypto=True,
    )
    guard = 0
    while True:
        guard += 1
        assert guard < 100_000, "network failed to quiesce"
        proposed = False
        for nid in sorted(net.nodes):
            node = net.nodes[nid]
            algo = node.instance
            if algo.epoch < epochs and not algo.has_input():
                node.handle_input([b"oc-%d-%03d" % (algo.epoch, nid)])
                msgs = list(node.messages)
                node.messages.clear()
                net.dispatch_messages(nid, msgs)
                proposed = True
        if net.any_busy():
            net.step()
        elif not proposed:
            break
    return {nid: list(node.outputs) for nid, node in net.nodes.items()}


def test_ordered_protocol_interleaves_order_and_reveal():
    epochs = 3
    ordered_out = _run_net("ordered", epochs=epochs)
    inline_out = _run_net("inline", epochs=epochs)
    for nid, outs in ordered_out.items():
        obs = [o for o in outs if isinstance(o, OrderedBatch)]
        batches = [o for o in outs if isinstance(o, Batch)]
        assert len(obs) == epochs and len(batches) == epochs
        # contiguous node-local commit sequence, epochs in log order
        assert [o.seq for o in obs] == list(range(epochs))
        assert [o.epoch for o in obs] == list(range(epochs))
        assert [b.epoch for b in batches] == list(range(epochs))
        # the order is pinned before the plaintext exists
        for e in range(epochs):
            assert outs.index(obs[e]) < outs.index(batches[e])
        # plaintext identical to the inline pipeline's
        inline_batches = [o for o in inline_out[nid] if isinstance(o, Batch)]
        assert [(b.epoch, b.contributions) for b in batches] == [
            (b.epoch, b.contributions) for b in inline_batches
        ]
    # every correct node pins the same digest per epoch
    for e in range(epochs):
        digests = {
            next(
                o for o in outs if isinstance(o, OrderedBatch) and o.epoch == e
            ).digest
            for outs in ordered_out.values()
        }
        assert len(digests) == 1


def test_ordered_batch_digest_canonical():
    cts = {1: b"ct-one", 0: b"ct-zero", 2: b"ct-two"}
    permuted = {2: b"ct-two", 0: b"ct-zero", 1: b"ct-one"}
    assert ordered_batch_digest(5, cts) == ordered_batch_digest(5, permuted)
    assert ordered_batch_digest(5, cts) != ordered_batch_digest(6, cts)
    assert ordered_batch_digest(5, cts) != ordered_batch_digest(
        5, {**cts, 2: b"ct-other"}
    )
    assert len(ordered_batch_digest(5, cts)) == 32


def test_reveal_mode_validation_and_env_default(monkeypatch):
    rng = random.Random(11)
    from hbbft_tpu.core.network_info import NetworkInfo

    netinfos = NetworkInfo.generate_map(list(range(4)), rng, mock=True)
    ni = netinfos[0]
    with pytest.raises(ValueError):
        HoneyBadger(ni, reveal_mode="weird")
    # the backpressure bound clamps to >= 1
    hb = HoneyBadger(ni, reveal_mode="ordered", max_outstanding_reveals=0)
    assert hb.max_outstanding_reveals == 1
    assert hb._pending_reveals == {}
    monkeypatch.delenv("HBBFT_TPU_ORDERED_COMMIT", raising=False)
    assert default_reveal_mode() == "inline"
    monkeypatch.setenv("HBBFT_TPU_ORDERED_COMMIT", "1")
    assert default_reveal_mode() == "ordered"
    assert HoneyBadgerBuilder(ni).build().reveal_mode == "ordered"


# -- vectorized harness ------------------------------------------------------


def _contribs(n, tag):
    return {i: [b"%s-%03d" % (tag, i)] for i in range(n)}


def test_vectorized_ordered_byte_identical_to_inline():
    n, epochs = 4, 3
    seq = [_contribs(n, b"vo%d" % e) for e in range(epochs)]
    inline = VectorizedHoneyBadgerSim(n, random.Random(0x0C), mock=True)
    ordered = VectorizedHoneyBadgerSim(
        n, random.Random(0x0C), mock=True, reveal_mode="ordered"
    )
    rows_in = inline.run_epochs(seq, pipeline=False)
    rows_or = ordered.run_epochs(seq, pipeline=False)
    for e, (ri, ro) in enumerate(zip(rows_in, rows_or)):
        # run_epochs flushed the ordered reveals in place
        assert ro.batch is not None, f"epoch {e} never revealed"
        assert ro.batch.contributions == ri.batch.contributions
        assert ro.fault_log.is_empty()


def test_vectorized_ordered_defers_bad_share_attribution():
    n, epochs, forger = 4, 3, 1
    bogus = MockDecryptionShare(b"\xab" * 32, b"\xcd" * 32)
    forged = {forger: {p: bogus for p in range(n)}}
    seq = [_contribs(n, b"vb%d" % e) for e in range(epochs)]
    twin = VectorizedHoneyBadgerSim(n, random.Random(0x0D), mock=True)
    ordered = VectorizedHoneyBadgerSim(
        n,
        random.Random(0x0D),
        mock=True,
        reveal_mode="ordered",
        max_outstanding_reveals=epochs,
    )
    rows_ref = twin.run_epochs(seq, pipeline=False)
    rows_or = ordered.run_epochs(seq, pipeline=False, forged_dec=forged)
    for rr, ro in zip(rows_ref, rows_or):
        assert ro.batch is not None
        assert ro.batch.contributions == rr.batch.contributions
        # decryption faults surface at reveal time, same attribution
        assert {fl.node_id for fl in ro.fault_log} == {forger}


# -- gateway ack split -------------------------------------------------------


def _core_with_pending(conns=("ca", "cb")):
    core = GatewayCore()
    for i, conn in enumerate(conns):
        replies, dropped = core.on_hello(
            conn, ClientHello(1, "t%d" % i, "c%d" % i)
        )
        assert not dropped and replies[0].ok
        replies, dropped = core.on_submit(
            conn, SubmitTx(0, b"payload-%d" % i), 1.0
        )
        assert not dropped and replies[0].admitted
    return core


def test_gateway_ordered_ack_fanout_at_most_once():
    core = _core_with_pending()
    digest = b"\x11" * 32
    acks = core.on_ordered(4, 2, digest, 2.0)
    assert [c for c, _ in acks] == ["ca", "cb"]
    assert all(a == OrderedAck(4, 2, digest) for _, a in acks)
    assert all(validate_ordered_ack(a) for _, a in acks)
    # duplicate epoch → nothing; hostile values → nothing, no throw
    assert core.on_ordered(4, 3, digest, 2.5) == []
    assert core.on_ordered(-1, 0, digest, 2.5) == []
    assert core.on_ordered("e", 0, digest, 2.5) == []
    assert core.on_ordered(5, 0, "not-bytes", 2.5) == []


def test_gateway_reveal_note_exactly_once():
    core = _core_with_pending()
    core.on_ordered(4, 2, b"\x22" * 32, 2.0)
    notes = core.on_revealed(4, 2.75)
    assert [c for c, _ in notes] == ["ca", "cb"]
    assert all(n == RevealNote(4, 2, 750) for _, n in notes)
    assert all(validate_reveal_note(n) for _, n in notes)
    # exactly once: the notified list was popped
    assert core.on_revealed(4, 3.0) == []
    # inline-pipeline epochs (never ordered) produce no notes
    assert core.on_revealed(5, 3.0) == []
    assert core.on_revealed(None, 3.0) == []


def test_gateway_gc_ages_ordered_window():
    core = _core_with_pending(conns=("ca",))
    for e in range(6):
        core.on_ordered(e, e, b"\x33" * 32, float(e))
    core.gc_epochs(20, keep=8)
    assert core.ordered_log == {}
    assert core.on_revealed(3, 21.0) == []


def test_ordered_wire_validators_total_and_roundtrip():
    good_ack = OrderedAck(3, 2, b"\x44" * 32)
    good_note = RevealNote(3, 2, 150)
    assert validate_ordered_ack(good_ack)
    assert validate_reveal_note(good_note)
    assert loads(frame(good_ack)[4:]) == good_ack
    assert loads(frame(good_note)[4:]) == good_note
    for bad in (
        None,
        good_note,
        OrderedAck(True, 2, b"\x44" * 32),
        OrderedAck(-1, 2, b"\x44" * 32),
        OrderedAck(3, "2", b"\x44" * 32),
        OrderedAck(3, 2, b"\x44" * 31),
        OrderedAck(3, 2, "digest"),
    ):
        assert validate_ordered_ack(bad) is False
    for bad in (
        None,
        good_ack,
        RevealNote(True, 2, 150),
        RevealNote(3, -1, 150),
        RevealNote(3, 2, -5),
        RevealNote(3, 2, 2**31),
        RevealNote(3, 2, 1.5),
    ):
        assert validate_reveal_note(bad) is False
