"""DynamicHoneyBadger tests (mirrors ``tests/dynamic_honey_badger.rs``):
a full Remove(0) → Add(0) membership cycle while transactions are being
committed, with prefix-equality of batch sequences across nodes."""

import random

import pytest

from hbbft_tpu.harness.network import (
    MessageScheduler,
    SilentAdversary,
    TestNetwork,
)
from hbbft_tpu.protocols import change as C
from hbbft_tpu.protocols.dynamic_honey_badger import (
    ChangeInput,
    DynamicHoneyBadger,
    DynamicHoneyBadgerBuilder,
    UserInput,
)


def batch_key(batch):
    return (
        batch.epoch,
        tuple(
            sorted(
                (str(k), tuple(v)) for k, v in batch.contributions.items()
            )
        ),
        repr(batch.change),
    )


def _run_dhb_churn(seed, mock=True, ops=None, txs_per_node=4):
    """The full Remove(0) → Add(0) membership cycle with transactions
    in flight (reference ``tests/dynamic_honey_badger.rs:35-105``) —
    parameterized so the riskiest composite path (on-chain DKG → era
    switch → signing under the new keys) also runs with REAL BLS12-381
    (VERDICT r2 item 5)."""
    rng = random.Random(seed)
    size = 4
    net = TestNetwork(
        size,
        0,
        lambda adv: SilentAdversary(
            MessageScheduler(MessageScheduler.RANDOM, rng)
        ),
        lambda ni: DynamicHoneyBadger(
            ni, rng=random.Random(f"dhb-{ni.our_id}")
        ),
        rng,
        mock_crypto=mock,
        ops=ops,
    )
    queues = {
        nid: [b"tx-%d-%d" % (nid, i) for i in range(txs_per_node)]
        for nid in net.nodes
    }
    all_txs = {tx for q in queues.values() for tx in q}
    node0_pk = net.nodes[0].instance.netinfo.public_key(0)

    # Phase 1: everyone votes to remove node 0
    for nid in sorted(net.nodes):
        net.input(nid, ChangeInput(C.Remove(0)))

    state = {"removed": False, "added": False}

    def committed(node):
        return {tx for b in node.outputs for tx in b.tx_iter()}

    def changes_seen(node):
        return [
            b.change
            for b in node.outputs
            if not isinstance(b.change, C.NoChange)
        ]

    def done():
        if not state["added"]:
            return False
        return all(committed(n) >= all_txs for n in net.nodes.values())

    guard = 0
    while not done():
        guard += 1
        assert guard < 200_000, (
            "DHB churn test did not complete; "
            f"state={state}, outputs={[len(n.outputs) for n in net.nodes.values()]}"
        )
        # when the removal completes at every node, vote to add node 0 back
        if not state["removed"] and all(
            any(
                isinstance(ch, C.Complete) and isinstance(ch.change, C.Remove)
                for ch in changes_seen(n)
            )
            for n in net.nodes.values()
        ):
            state["removed"] = True
            for nid in sorted(net.nodes):
                if net.nodes[nid].instance.netinfo.is_validator:
                    net.input(nid, ChangeInput(C.Add(0, node0_pk)))
        if not state["added"] and all(
            any(
                isinstance(ch, C.Complete) and isinstance(ch.change, C.Add)
                for ch in changes_seen(n)
            )
            for n in net.nodes.values()
        ):
            state["added"] = True

        # propose pending txs on free validators
        if rng.random() < 0.2 or not net.any_busy():
            nid = rng.choice(sorted(net.nodes))
            node = net.nodes[nid]
            inst = node.instance
            if inst.netinfo.is_validator and not inst.has_input():
                remaining = [
                    tx for tx in queues[nid] if tx not in committed(node)
                ][:2]
                node.handle_input(UserInput(remaining))
                msgs = list(node.messages)
                node.messages.clear()
                net.dispatch_messages(nid, msgs)
                continue
        if net.any_busy():
            net.step()

    # prefix equality of batch sequences
    seqs = [
        [batch_key(b) for b in n.outputs] for n in net.nodes.values()
    ]
    min_len = min(len(s) for s in seqs)
    for s in seqs[1:]:
        assert s[:min_len] == seqs[0][:min_len], "batch sequences diverged"
    # the membership cycle actually happened
    assert state["removed"] and state["added"]
    return net


def test_dynamic_honey_badger_remove_then_add():
    _run_dhb_churn(80, mock=True)


def test_dhb_churn_real_bls():
    """Remove(0) → Add(0) with mock=False: real threshold encryption,
    real vote signatures, real on-chain Pedersen DKG, an era switch,
    and batches committed under the NEW keys — runtime kept sane by the
    batching façade's fused share-verification flushes."""
    from hbbft_tpu.harness.batching import BatchingBackend

    _run_dhb_churn(84, mock=False, ops=BatchingBackend(), txs_per_node=2)


def test_dhb_join_plan_roundtrip():
    """A change-bearing batch yields a JoinPlan a fresh node can join from."""
    rng = random.Random(81)
    builder = DynamicHoneyBadgerBuilder()
    dhb = builder.build_first_node("solo", mock=True)
    assert dhb.netinfo.num_nodes == 1
    step = dhb.handle_input(UserInput([b"t1"]))
    batches = [o for o in step.output]
    assert batches and b"t1" in set(batches[0].tx_iter())


def test_vote_counter_supersede_and_winner():
    from hbbft_tpu.core.network_info import NetworkInfo
    from hbbft_tpu.protocols.votes import VoteCounter

    rng = random.Random(82)
    nis = NetworkInfo.generate_map(range(4), rng, mock=True)
    counters = {i: VoteCounter(nis[i], 0) for i in range(4)}
    # node 0 votes remove(3), then changes its mind to remove(2)
    sv1 = counters[0].sign_vote_for(C.Remove(3))
    sv2 = counters[0].sign_vote_for(C.Remove(2))
    assert sv2.vote.num > sv1.vote.num
    c = counters[1]
    assert c.add_pending_vote(0, sv1).is_empty()
    assert c.add_pending_vote(0, sv2).is_empty()
    pend = list(c.pending_votes())
    assert len(pend) == 1 and pend[0].vote.change == C.Remove(2)
    # commit votes from f+1 = 2 voters for the same change -> winner
    svx = counters[2].sign_vote_for(C.Remove(2))
    assert c.add_committed_vote(1, sv2).is_empty()
    assert c.compute_winner() is None
    assert c.add_committed_vote(1, svx).is_empty()
    assert c.compute_winner() == C.Remove(2)


def test_vote_counter_rejects_bad_signature():
    from hbbft_tpu.core.network_info import NetworkInfo
    from hbbft_tpu.protocols.votes import SignedVote, Vote, VoteCounter

    rng = random.Random(83)
    nis = NetworkInfo.generate_map(range(4), rng, mock=True)
    counter = VoteCounter(nis[0], 0)
    legit = VoteCounter(nis[1], 0).sign_vote_for(C.Remove(3))
    forged = SignedVote(Vote(C.Remove(2), 0, 5), legit.voter, legit.sig)
    faults = counter.add_pending_vote(1, forged)
    assert not faults.is_empty()
