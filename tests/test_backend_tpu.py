"""End-to-end protocol runs over the TPU ops backend.

Kernel-level bit-identity (hashes, Merkle levels, RS shards, MSMs,
batched verification) is covered in ``tests/test_ops.py``; these tests
close the loop at the *protocol* layer: full multi-node runs where
every backend-routed operation executes on the device path
(``ops/backend_tpu.py``), alone and composed with the batching façade
(``harness/batching.py``) — the production stack of the TPU
co-simulation north star.

Runs on the virtual 8-device CPU mesh (see ``conftest.py``); the same
code paths hit real TPU hardware via ``bench.py``.
"""

import random

import pytest

from hbbft_tpu.harness.batching import BatchingBackend
from hbbft_tpu.harness.network import (
    MessageScheduler,
    SilentAdversary,
    TestNetwork,
)
from hbbft_tpu.ops.backend_tpu import TpuBackend
from hbbft_tpu.protocols.broadcast import Broadcast


def _run_broadcast(rng, ops, payload):
    net = TestNetwork(
        6,
        2,
        lambda adv: SilentAdversary(
            MessageScheduler(MessageScheduler.RANDOM, rng)
        ),
        lambda ni: Broadcast(ni, 0),
        rng,
        ops=ops,
    )
    net.input(0, payload)
    net.step_until(
        lambda: all(n.terminated() for n in net.nodes.values())
    )
    outs = [n.outputs for n in net.nodes.values()]
    assert all(o == [payload] for o in outs), outs
    return net


def test_broadcast_over_tpu_backend(rng):
    """Reliable broadcast where RS coding and the Merkle tree run on
    the device (payload > shard threshold so kernels actually engage)."""
    payload = bytes(rng.randrange(256) for _ in range(4096))
    _run_broadcast(random.Random(5), TpuBackend(), payload)


def test_broadcast_cpu_tpu_same_transcript(rng):
    """Same seed, CPU vs TPU ops backend → identical outputs and fault
    logs (bit-identity surfaced at the protocol layer)."""
    payload = bytes(rng.randrange(256) for _ in range(1024))
    net_cpu = _run_broadcast(random.Random(6), None, payload)
    net_tpu = _run_broadcast(random.Random(6), TpuBackend(), payload)
    for nid in net_cpu.nodes:
        assert (
            net_cpu.nodes[nid].outputs == net_tpu.nodes[nid].outputs
        )
        assert [
            (f.node_id, f.kind) for f in net_cpu.nodes[nid].faults
        ] == [(f.node_id, f.kind) for f in net_tpu.nodes[nid].faults]


def test_honey_badger_batching_over_tpu_backend():
    """The full production stack: HoneyBadger on real BLS12-381 with
    the batching façade wrapping the TPU backend — prefetched share
    verifications run their MSMs through the device kernels."""
    from test_honey_badger import run_honey_badger

    be = BatchingBackend(inner=TpuBackend())
    run_honey_badger(
        random.Random(43), 4, txs_per_node=2, batch_contrib=2,
        mock=False, ops=be,
    )
    assert be.stats.prefetched > 0
    assert be.stats.cache_hits > 0
