"""Bit-identity tests: C++ native host library vs pure-Python oracle.

The native library (``native/hbbft_native.cpp``) replaces the
reference's native host crates (``ring`` SHA-256, ``merkle``,
``reed-solomon-erasure`` — SURVEY.md §2.4).  Every exported function
must agree byte-for-byte with the pure-Python implementations in
``hbbft_tpu/crypto``; randomized inputs sweep shapes including the odd
corners (empty messages, odd leaf counts, singular submatrices)."""

import hashlib
import random

import numpy as np
import pytest

from hbbft_tpu import native as N
from hbbft_tpu.crypto import rs as RS
from hbbft_tpu.crypto.merkle import MerkleTree, leaf_hash, node_hash

pytestmark = pytest.mark.skipif(
    not N.available(), reason="native library unavailable"
)


def test_sha256_many_matches_hashlib():
    rng = random.Random(1)
    msgs = [b"", b"x", b"a" * 63, b"b" * 64, b"c" * 65] + [
        bytes(rng.randrange(256) for _ in range(rng.randrange(0, 300)))
        for _ in range(50)
    ]
    assert N.sha256_many(msgs) == [hashlib.sha256(m).digest() for m in msgs]


def _python_levels(values):
    level = [leaf_hash(i, v) for i, v in enumerate(values)]
    levels = [level]
    while len(level) > 1:
        if len(level) & 1:
            level = level + [level[-1]]
            levels[-1] = level
        nxt = [node_hash(level[i], level[i + 1]) for i in range(0, len(level), 2)]
        levels.append(nxt)
        level = nxt
    return levels


@pytest.mark.parametrize("n", [1, 2, 3, 4, 5, 6, 7, 8, 9, 15, 16, 17, 33])
def test_merkle_levels_match_python(n):
    rng = random.Random(n)
    values = [
        bytes(rng.randrange(256) for _ in range(rng.randrange(1, 40)))
        for _ in range(n)
    ]
    assert N.merkle_levels(values) == _python_levels(values)


def test_merkle_tree_uses_native_and_proofs_validate():
    values = [bytes([i]) * 10 for i in range(13)]
    tree = MerkleTree(values)
    for i in range(13):
        assert tree.proof(i).validate(13)


def test_gf_matmul_matches_numpy():
    rng = np.random.RandomState(7)
    for _ in range(10):
        m, k, n = rng.randint(1, 20, size=3)
        a = rng.randint(0, 256, (m, k)).astype(np.uint8)
        b = rng.randint(0, 256, (k, n)).astype(np.uint8)
        assert (N.gf_matmul(a, b) == RS.gf_matmul(a, b)).all()


def test_gf_mat_inv_matches_python_and_detects_singular():
    rng = np.random.RandomState(9)
    for n in (1, 2, 5, 11):
        # systematic RS submatrices are guaranteed invertible
        mat = RS._systematic_matrix(n, 2 * n + 1)
        rows = sorted(rng.choice(2 * n + 1, size=n, replace=False))
        sub = mat[rows, :]
        inv_native = N.gf_mat_inv(sub)
        inv_py = RS._gf_mat_inv(sub.copy())
        assert (inv_native == inv_py).all()
    singular = np.zeros((3, 3), dtype=np.uint8)
    with pytest.raises(ValueError):
        N.gf_mat_inv(singular)


def test_no_native_env_flag_switches_paths(monkeypatch):
    values = [bytes([i]) * 8 for i in range(9)]
    native_tree = MerkleTree(values)
    monkeypatch.setenv("HBBFT_TPU_NO_NATIVE", "1")
    assert not N.available()
    pure_tree = MerkleTree(values)
    assert native_tree.levels == pure_tree.levels


def test_rs_codec_native_roundtrip():
    codec = RS.ReedSolomon(5, 4)
    rng = random.Random(11)
    data = [
        bytes(rng.randrange(256) for _ in range(64)) for _ in range(5)
    ]
    shards = codec.encode(data)
    lossy = list(shards)
    for i in (0, 3, 6, 8):
        lossy[i] = None
    assert codec.reconstruct(lossy) == shards
