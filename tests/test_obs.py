"""Tests for the observability layer (``hbbft_tpu/obs/``): recorder
span semantics, JSONL round-trip of the event schema, no-op-mode
silence, fault telemetry stability, and the simulation → trace →
report-CLI pipeline end to end."""

import json
import random

import pytest

from hbbft_tpu.core.fault import Fault, FaultKind, FaultLog
from hbbft_tpu.core.step import Step
from hbbft_tpu.obs import recorder as obs
from hbbft_tpu.obs import report


@pytest.fixture(autouse=True)
def _no_leaked_recorder():
    """Every test starts and ends with tracing off."""
    obs.disable()
    yield
    obs.disable()


def _events(rec, ev=None):
    if ev is None:
        return rec.events
    return [e for e in rec.events if e["ev"] == ev]


# ---------------------------------------------------------------------------
# Recorder core
# ---------------------------------------------------------------------------


def test_span_nesting_and_timing_monotonicity():
    rec = obs.enable()
    with rec.span("outer", tag="a") as outer:
        t_mid = rec.now()
        with rec.span("inner") as inner:
            pass
    obs.disable()

    spans = {e["name"]: e for e in _events(rec, "span")}
    assert set(spans) == {"outer", "inner"}
    assert spans["outer"]["depth"] == 0
    assert spans["inner"]["depth"] == 1
    # attrs survive
    assert spans["outer"]["tag"] == "a"
    # nesting: inner starts after outer, inner duration fits inside
    assert spans["inner"]["t"] >= spans["outer"]["t"]
    assert inner.dur <= outer.dur
    assert 0.0 <= spans["outer"]["t"] <= t_mid
    # event stream timestamps are monotone for sequential events
    e1 = rec.event("a")
    e2 = rec.event("b")
    assert e1["t"] <= e2["t"]
    # durations are non-negative and spans completed inner-first
    names_in_order = [e["name"] for e in _events(rec, "span")]
    assert names_in_order == ["inner", "outer"]


def test_traced_decorator_on_and_off():
    calls = []

    @obs.traced("decorated.fn", layer="test")
    def fn(x):
        calls.append(x)
        return x * 2

    assert fn(3) == 6  # tracing off: passes straight through
    rec = obs.enable()
    assert fn(4) == 8
    obs.disable()
    assert calls == [3, 4]
    (span,) = _events(rec, "span")
    assert span["name"] == "decorated.fn" and span["layer"] == "test"


def test_counters_and_histograms_summarized_on_close(tmp_path):
    path = tmp_path / "t.jsonl"
    rec = obs.enable(str(path))
    rec.count("widgets")
    rec.count("widgets", 2)
    for v in (1.0, 2.0, 3.0, 4.0):
        rec.observe("lat", v)
    obs.disable()

    events = report.load_events(str(path))
    counters = [e for e in events if e["ev"] == "counter"]
    assert counters == [
        {"ev": "counter", "t": counters[0]["t"], "name": "widgets", "value": 3}
    ]
    (hist,) = [e for e in events if e["ev"] == "hist"]
    assert hist["name"] == "lat"
    assert hist["count"] == 4
    assert hist["min"] == 1.0 and hist["max"] == 4.0 and hist["sum"] == 10.0
    assert events[-1]["ev"] == "trace_end"


def test_jsonl_roundtrip_every_event_type(tmp_path):
    """One event of every schema type goes through the file and comes
    back with its fields and types intact."""
    path = tmp_path / "all.jsonl"
    rec = obs.enable(str(path))
    with rec.span("s", k=5):
        pass
    rec.event("msg_send", src=0, size=17, vt=0.25, kind="all")
    rec.event("msg_deliver", src=0, dst=1, size=17, vt=0.25, kind="all")
    rec.event("msg_handle", node=1, vt=0.5, wall=0.001, size=17)
    rec.event("epoch_start", epoch=0, vt=0.1)
    rec.event("epoch_decide", epoch=0, node=1, vt=0.9)
    rec.event(
        "epoch",
        epoch=0,
        min_time=0.5,
        max_time=0.9,
        txs=10,
        msgs_per_node=4,
        bytes_per_node=256,
    )
    rec.event("epoch_phases", epoch=0, phases={"rbc": 0.5}, shares=12)
    rec.event(
        "flush",
        queued=10,
        shipped=8,
        real=8,
        inline=0,
        occupancy=0.8,
        groups=2,
        dur=0.01,
        fallback_groups=0,
        phases={"ship": 0.002},
    )
    rec.event("device_op", op="g1_msm", k=4096, engine="device")
    rec.event("fault", fault="1:INVALID_PROOF", node=1, kind="INVALID_PROOF")
    # fleet-telemetry plane (schema v2)
    rec.event("wal_append", records=7, kind=1, path="/tmp/x.wal")
    rec.event("trace_link", node="127.0.0.1:2", peer="127.0.0.1:1", seq=9, epoch=1)
    rec.event("gossip_relay", txs=3, depth=12)
    rec.event("acs_done", node="127.0.0.1:2", epoch=1, proposers=4)
    rec.event("node_commit", node="127.0.0.1:2", epoch=1, txs=3)
    rec.event("flight_dump", reason="fault", events=64, dropped=0, path="/tmp/f")
    rec.event("metrics_scrape", node="n0", up=True, families=12, wall=0.004)
    # non-JSON-native values are coerced, not fatal
    rec.event("weird", blob=b"\x00\x01", obj=object(), seq=(1, 2))
    rec.count("c")
    rec.observe("h", 1.5)
    obs.disable()

    events = report.load_events(str(path))
    by_ev = {e["ev"]: e for e in events}
    expected = {
        "trace_start",
        "span",
        "msg_send",
        "msg_deliver",
        "msg_handle",
        "epoch_start",
        "epoch_decide",
        "epoch",
        "epoch_phases",
        "flush",
        "device_op",
        "fault",
        "wal_append",
        "trace_link",
        "gossip_relay",
        "acs_done",
        "node_commit",
        "flight_dump",
        "metrics_scrape",
        "weird",
        "counter",
        "hist",
        "trace_end",
    }
    assert expected <= set(by_ev)
    assert by_ev["trace_start"]["schema"] == obs.SCHEMA_VERSION
    assert by_ev["epoch"]["txs"] == 10 and by_ev["epoch"]["max_time"] == 0.9
    assert by_ev["flush"]["phases"] == {"ship": 0.002}
    assert by_ev["weird"]["blob"] == "0001"  # bytes → hex
    assert by_ev["weird"]["seq"] == [1, 2]
    assert isinstance(by_ev["weird"]["obj"], str)  # repr fallback
    # every line in the file is valid standalone JSON
    with open(path) as f:
        for line in f:
            assert isinstance(json.loads(line), dict)
    # summarize() accepts the full schema without error
    s = report.summarize(events)
    assert s["epochs"]["count"] == 1
    assert s["flushes"]["occupancy"] == 0.8
    assert s["faults"]["by_kind"] == {"INVALID_PROOF": 1}
    assert s["device_ops"]["g1_msm/device"]["count"] == 1


def test_noop_mode_adds_zero_events():
    """With no recorder installed, instrumented code paths run normally
    and record nothing anywhere."""
    from hbbft_tpu.harness.simulation import simulate_queueing_honey_badger

    assert obs.active() is None
    bystander = obs.Recorder()  # constructed but NOT installed
    baseline = len(bystander.events)
    stats, _, _ = simulate_queueing_honey_badger(
        num_nodes=4, num_txs=8, batch_size=4, rng=random.Random(7)
    )
    assert stats.rows  # the run did real work
    fl = FaultLog.init("x", FaultKind.MULTIPLE_ECHOS)  # fault path, untraced
    assert len(fl) == 1
    assert obs.active() is None
    assert len(bystander.events) == baseline
    # module-level span helper is the shared null span when off
    with obs.span("nothing") as sp:
        pass
    assert sp.dur == 0.0 and len(bystander.events) == baseline


# ---------------------------------------------------------------------------
# Fault telemetry
# ---------------------------------------------------------------------------


def test_fault_repr_single_stable_compact_form():
    f = Fault("a", FaultKind.INVALID_PROOF)
    assert f.compact() == "'a':INVALID_PROOF"
    assert repr(f) == "Fault('a':INVALID_PROOF)"
    assert repr(FaultKind.INVALID_PROOF) == "FaultKind.INVALID_PROOF"
    # int node ids too — byte-stable either way
    assert Fault(3, FaultKind.DUPLICATE_BVAL).compact() == "3:DUPLICATE_BVAL"


def test_fault_events_from_every_creation_path():
    rec = obs.enable()
    FaultLog.init(1, FaultKind.INVALID_PROOF)
    Step.from_fault(2, FaultKind.MULTIPLE_ECHOS)
    Step().add_fault(3, FaultKind.DUPLICATE_AUX)
    log = FaultLog()
    log.add(4, FaultKind.INVALID_MESSAGE)
    obs.disable()

    faults = _events(rec, "fault")
    assert [e["fault"] for e in faults] == [
        "1:INVALID_PROOF",
        "2:MULTIPLE_ECHOS",
        "3:DUPLICATE_AUX",
        "4:INVALID_MESSAGE",
    ]
    assert rec.counters["fault.INVALID_PROOF"] == 1
    # merge moves already-recorded faults without double-counting
    rec2 = obs.enable()
    merged = FaultLog()
    merged.merge(FaultLog.init(9, FaultKind.DUPLICATE_CONF))
    obs.disable()
    assert len(_events(rec2, "fault")) == 1


# ---------------------------------------------------------------------------
# Instrumented subsystems end to end
# ---------------------------------------------------------------------------


def _mock_obligations(n=6):
    from hbbft_tpu.crypto.mock import MockSecretKeySet
    from hbbft_tpu.harness.batching import SigObligation

    sks = MockSecretKeySet.random(1, random.Random(5))
    pks = sks.public_keys()
    msg = b"obs-flush"
    return [
        SigObligation(pks.public_key_share(i), sks.secret_key_share(i).sign(msg), msg)
        for i in range(n)
    ]


def test_flush_event_occupancy_and_cache():
    from hbbft_tpu.harness.batching import BatchingBackend

    rec = obs.enable()
    be = BatchingBackend()
    obligations = _mock_obligations(6)
    be.prefetch(obligations)
    be.prefetch(obligations)  # second flush: everything cached
    obs.disable()

    first, second = _events(rec, "flush")
    assert first["queued"] == 6 and first["shipped"] == 6
    assert first["occupancy"] == 1.0 and first["inline"] == 6
    assert second["queued"] == 6 and second["shipped"] == 0
    assert rec.counters["flush.count"] == 1  # only the real flush counts


def test_epoch_stats_structured_rows():
    """format_row consumes the structured dict row and renders the same
    bytes as the dataclass form."""
    from hbbft_tpu.harness.simulation import EpochRow, EpochStats

    row = EpochRow(3, 0.5123, 1.25, 100, 42, 9000)
    d = row.as_dict()
    assert d == {
        "epoch": 3,
        "min_time": 0.5123,
        "max_time": 1.25,
        "txs": 100,
        "msgs_per_node": 42,
        "bytes_per_node": 9000,
    }
    stats = object.__new__(EpochStats)  # formatting needs no network
    text_from_row = stats.format_row(row)
    text_from_dict = stats.format_row(d)
    assert text_from_row == text_from_dict
    assert text_from_row == (
        "    3     512ms    1250ms   100        42      9000B"
    )
    header = stats.header()
    assert header.split() == [
        "Epoch", "MinTime", "MaxTime", "Txs", "Msgs/Node", "Size/Node",
    ]


def test_simulation_smoke_trace_and_report_cli(tmp_path, capsys):
    """A small simulation run emits epoch/message/flush events the
    report CLI can parse and summarize."""
    from hbbft_tpu.harness.batching import BatchingBackend
    from hbbft_tpu.harness.simulation import simulate_queueing_honey_badger

    path = tmp_path / "trace.jsonl"
    obs.enable(str(path))
    stats, _, _ = simulate_queueing_honey_badger(
        num_nodes=4, num_txs=12, batch_size=6, rng=random.Random(0)
    )
    # mock crypto keeps the façade out of the sim loop; drive one flush
    # directly so the trace carries the crypto-batching surface too
    BatchingBackend().prefetch(_mock_obligations(8))
    obs.disable()

    events = report.load_events(str(path))
    kinds = {e["ev"] for e in events}
    assert {"msg_send", "msg_deliver", "msg_handle", "epoch_start",
            "epoch_decide", "epoch", "flush"} <= kinds

    s = report.summarize(events)
    assert s["epochs"]["count"] == len(stats.rows) >= 1
    # trace rows match the in-process structured rows exactly
    assert s["epochs"]["rows"][0]["txs"] == stats.rows[0].txs
    assert s["messages"]["delivered"] > 0
    assert set(s["messages"]["per_node"]) == {"0", "1", "2", "3"}
    assert s["flushes"]["count"] == 1 and s["flushes"]["shipped"] == 8

    # the CLI renders it (text and --json modes)
    assert report.main([str(path)]) == 0
    out = capsys.readouterr().out
    for needle in ("Epoch latency", "Messages", "Crypto flushes", "trace:"):
        assert needle in out, out
    assert report.main([str(path), "--json"]) == 0
    js = json.loads(capsys.readouterr().out)
    assert js["epochs"]["count"] == len(stats.rows)


def test_trace_survives_torn_final_line(tmp_path):
    path = tmp_path / "torn.jsonl"
    rec = obs.enable(str(path))
    rec.event("msg_send", src=0, size=1, vt=0.0, kind="node")
    obs.disable()
    with open(path, "a") as f:
        f.write('{"ev": "msg_send", "src": 1, ')  # killed mid-write
    events = report.load_events(str(path))
    assert any(e["ev"] == "msg_send" for e in events)
    assert any(e["ev"] == "_parse_errors" for e in events)
    report.summarize(events)  # no crash
