"""The adversarial scenario suite (``hbbft_tpu/harness/scenarios.py``)
and the wire-format fuzzer (``hbbft_tpu/harness/fuzz.py``).

Three layers:

- each scenario of the matrix runs green at tier-1 sizes, with its
  guarantee-equivalent-baseline bit-identity assertions active, and a
  deliberately broken configuration FAILS (the matrix is a real check,
  not a rubber stamp);
- the fuzzer's pinned-seed corpus completes over all three surfaces
  (codec, TCP framing, ``handle_*``) with zero crashes / hangs /
  unlogged failures;
- regression tests for every malformed-but-deserializable input path
  hardened for this suite: a crash found by the fuzzer must stay fixed.
"""

import asyncio
import random

import pytest

from hbbft_tpu.core.fault import Fault, FaultKind
from hbbft_tpu.core.network_info import NetworkInfo
from hbbft_tpu.core.serialize import SerializationError, dumps, loads
from hbbft_tpu.core.step import Step
from hbbft_tpu.harness import fuzz, scenarios
from hbbft_tpu.harness.scenarios import ScenarioConfig, run_scenario

SMALL = ScenarioConfig(n=7, epochs=1, seed=0xA5C, fuzz_cases=60)


def _netinfos(n=4, seed=0x51):
    return NetworkInfo.generate_map(list(range(n)), random.Random(seed), mock=True)


# ---------------------------------------------------------------------------
# The scenario matrix
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("name", sorted(scenarios.SCENARIOS))
def test_scenario_green(name):
    res = run_scenario(name, SMALL)
    assert res.ok, f"{name}: {res.detail}"
    assert res.name == name


def test_scenario_byzantine_faults_are_attributed():
    # the Byzantine rows must observe injected faults in the FaultLog
    for name in ("bad-share", "corrupt-echo"):
        res = run_scenario(name, SMALL)
        assert res.ok and res.faults > 0, (name, res.detail)


def test_scenario_failure_is_reported_not_raised():
    # n=3 has f=0: the silent scenario's precondition check must fail
    # as a ScenarioResult row, never as an exception
    res = run_scenario("silent", ScenarioConfig(n=3, epochs=1, seed=1))
    assert not res.ok
    assert "f=0" in res.detail


def test_scenario_assertions_bite(monkeypatch):
    # corrupt the twin comparison: tamper with the sim so the bad-share
    # batch really diverges, and the scenario must go red
    real = scenarios.VectorizedHoneyBadgerSim

    class Tampered(real):
        def run_epoch(self, contributions, **kw):
            if "forged_dec" in kw:
                contributions = dict(contributions)
                contributions.pop(sorted(contributions)[0])
            return real.run_epoch(self, contributions, **kw)

    monkeypatch.setattr(scenarios, "VectorizedHoneyBadgerSim", Tampered)
    res = run_scenario("bad-share", SMALL)
    assert not res.ok
    assert "diverges" in res.detail or "crashed" in res.detail


def test_scenario_events_emitted_when_tracing():
    from hbbft_tpu.obs import recorder as obs

    obs.enable()
    try:
        res = run_scenario("silent", SMALL)
        rows = [e for e in obs.active().events if e["ev"] == "scenario"]
    finally:
        obs.disable()
    assert res.ok
    assert len(rows) == 1
    assert rows[0]["name"] == "silent" and rows[0]["ok"] is True


def test_fuzz_summary_events_emitted_when_tracing():
    from hbbft_tpu.obs import recorder as obs

    obs.enable()
    try:
        res = run_scenario(
            "fuzz", ScenarioConfig(n=4, epochs=1, seed=3, fuzz_cases=40)
        )
        rows = [e for e in obs.active().events if e["ev"] == "fuzz_summary"]
    finally:
        obs.disable()
    assert res.ok, res.detail
    assert {r["surface"] for r in rows} == {
        "codec",
        "frames",
        "handlers",
        "gateway",
    }


def test_cli_list_and_run(capsys):
    assert scenarios.main(["--list"]) == 0
    out = capsys.readouterr().out
    assert "partition-heal" in out and "fuzz" in out
    rc = scenarios.main(
        ["--only", "silent", "--only", "delay", "--n", "7", "--epochs", "1"]
    )
    out = capsys.readouterr().out
    assert rc == 0
    assert out.count("PASS") == 2 and "2/2 scenarios green" in out


def test_cli_json_rows(capsys):
    import json as _json

    rc = scenarios.main(
        ["--only", "corrupt-echo", "--n", "7", "--epochs", "1", "--json"]
    )
    out = capsys.readouterr().out
    assert rc == 0
    row = _json.loads(out.strip())
    assert row["name"] == "corrupt-echo" and row["ok"] is True


def test_cli_unknown_scenario(capsys):
    assert scenarios.main(["--only", "nope"]) == 2
    assert "unknown scenario" in capsys.readouterr().err


@pytest.mark.slow
def test_churn_soak_n256():
    """Membership churn through the vectorized harness at n=256: a full
    Remove -> Add cycle with on-chain DKG era switches (the scale the
    paper's co-simulation targets)."""
    res = run_scenario(
        "churn", ScenarioConfig(n=256, epochs=3, seed=0x256, fuzz_cases=0)
    )
    assert res.ok, res.detail


# ---------------------------------------------------------------------------
# The fuzzer corpus (pinned seeds)
# ---------------------------------------------------------------------------


def test_fuzz_codec_pinned_corpus():
    rep = fuzz.fuzz_codec(0xF0227, 200)
    assert rep.ok, rep.failures[:3]
    assert rep.surface == "codec"
    # both outcomes must actually occur, or the fuzzer tests nothing
    assert rep.decoded > 0 and rep.rejected > 0


def test_fuzz_frames_pinned_corpus():
    rep = fuzz.fuzz_frames(0xF0228, 40)
    assert rep.ok, rep.failures[:3]
    assert rep.delivered > 0


def test_fuzz_handlers_pinned_corpus():
    rep = fuzz.fuzz_handlers(0xF0229, 150)
    assert rep.ok, rep.failures[:3]
    # malformed-but-deserializable messages must surface as Step faults
    assert rep.faults > 0


def test_fuzz_corpus_smoke():
    reports = fuzz.run_corpus(
        seed=0xBEE, codec_cases=80, frame_cases=12, handler_cases=40
    )
    assert [r.surface for r in reports] == [
        "codec",
        "frames",
        "handlers",
        "gateway",
    ]
    assert all(r.ok for r in reports), [
        f for r in reports for f in r.failures[:2]
    ]


def test_fuzz_is_deterministic_per_seed():
    a = fuzz.fuzz_codec(0xD5, 120)
    b = fuzz.fuzz_codec(0xD5, 120)
    assert (a.decoded, a.rejected, a.cases) == (b.decoded, b.rejected, b.cases)


# ---------------------------------------------------------------------------
# Codec hardening regressions (fuzzer findings stay fixed)
# ---------------------------------------------------------------------------


def test_loads_rejects_deep_nesting():
    deep = b"\x07\x01" * 500 + b"\x00"  # 500 nested single-item lists
    with pytest.raises(SerializationError, match="nesting"):
        loads(deep)


def test_loads_normalizes_internal_errors():
    # frames that used to escape as IndexError / struct.error /
    # UnicodeDecodeError / OverflowError must all be SerializationError
    for frame in (
        b"\x03",  # int tag with no magnitude
        b"\xff" * 16,  # nonsense tag soup
        b"\x06\x04\xff\xfe\x80\x81",  # str tag, invalid UTF-8
        b"\x07\xff" + (2**62).to_bytes(8, "big"),  # huge list header
        b"\x05\x08ab",  # bytes tag, truncated payload
    ):
        with pytest.raises(SerializationError):
            loads(frame)


def test_loads_rejects_trailing_bytes():
    with pytest.raises(SerializationError, match="trailing"):
        loads(dumps(7) + b"\x00")


def test_roundtrip_still_exact():
    vals = [None, True, -(2**70), b"x" * 40, "str", [1, [2, [3]]], {"k": (1, 2)}]
    for v in vals:
        assert loads(dumps(v)) == v


# ---------------------------------------------------------------------------
# handle_* hardening regressions
# ---------------------------------------------------------------------------


def _is_invalid_msg_fault(step):
    assert isinstance(step, Step)
    kinds = [f.kind for f in step.fault_log]
    assert kinds and all(
        k
        in (
            FaultKind.INVALID_MESSAGE,
            FaultKind.UNEXPECTED_PROPOSER,
        )
        for k in kinds
    ), kinds
    return True


def test_honey_badger_rejects_non_int_epoch():
    from hbbft_tpu.protocols.honey_badger import HoneyBadger, HoneyBadgerMessage

    hb = HoneyBadger(_netinfos()[0])
    for bad_epoch in ("7", None, True, 1.5, [2]):
        step = hb.handle_message(1, HoneyBadgerMessage(bad_epoch, "x"))
        _is_invalid_msg_fault(step)


def test_honey_badger_rejects_unhashable_and_unknown_proposer():
    from hbbft_tpu.protocols.honey_badger import (
        HbDecryptionShare,
        HoneyBadger,
        HoneyBadgerMessage,
    )

    hb = HoneyBadger(_netinfos()[0])
    for proposer in ([1, 2], {}, "ghost", 99):
        step = hb.handle_message(
            1, HoneyBadgerMessage(0, HbDecryptionShare(proposer, b"s"))
        )
        _is_invalid_msg_fault(step)


def test_agreement_rejects_non_int_epoch_and_confused_contents():
    from hbbft_tpu.protocols.agreement import (
        Agreement,
        AgreementMessage,
        ConfContent,
        TermContent,
    )

    ag = Agreement(_netinfos()[0], 0, 1)
    _is_invalid_msg_fault(ag.handle_message(1, AgreementMessage(False, "x")))
    _is_invalid_msg_fault(
        ag.handle_message(1, AgreementMessage(0, ConfContent("not-a-boolset")))
    )
    _is_invalid_msg_fault(
        ag.handle_message(1, AgreementMessage(0, TermContent("not-a-bool")))
    )


def test_sbv_broadcast_rejects_non_bool_votes():
    from hbbft_tpu.protocols.sbv_broadcast import Aux, BVal, SbvBroadcast

    for content in (BVal(2), BVal("t"), Aux(None), Aux([True])):
        sbv = SbvBroadcast(_netinfos()[0])
        _is_invalid_msg_fault(sbv.handle_message(1, content))


def test_common_subset_rejects_bad_proposers():
    from hbbft_tpu.protocols.agreement import AgreementMessage, TermContent
    from hbbft_tpu.protocols.common_subset import (
        CommonSubset,
        CsAgreement,
        CsBroadcast,
    )

    cs = CommonSubset(_netinfos()[0], 0)
    for proposer in ([1], {"a": 1}, "ghost", 42):
        _is_invalid_msg_fault(
            cs.handle_message(1, CsBroadcast(proposer, "m"))
        )
        _is_invalid_msg_fault(
            cs.handle_message(
                1, CsAgreement(proposer, AgreementMessage(0, TermContent(True)))
            )
        )


def test_merkle_proof_validate_survives_type_confusion():
    from hbbft_tpu.crypto.merkle import MerkleProof, MerkleTree

    tree = MerkleTree([b"a", b"b", b"c", b"d"])
    good = tree.proof(1)
    assert good.validate(4)
    for bad in (
        MerkleProof(value=None, index=1, lemma=good.lemma, root_hash=good.root_hash),
        MerkleProof(value=b"b", index="1", lemma=good.lemma, root_hash=good.root_hash),
        MerkleProof(value=b"b", index=True, lemma=good.lemma, root_hash=good.root_hash),
        MerkleProof(value=b"b", index=1, lemma=b"xx", root_hash=good.root_hash),
        MerkleProof(value=b"b", index=1, lemma=good.lemma, root_hash=7),
    ):
        assert bad.validate(4) is False


def test_vote_counter_rejects_malformed_signed_votes():
    from hbbft_tpu.protocols.votes import SignedVote, Vote, VoteCounter
    from hbbft_tpu.protocols.change import Remove

    ni = _netinfos()[0]
    vc = VoteCounter(ni, 0)
    malformed = [
        "not-a-vote",
        SignedVote(vote="junk", voter=1, sig=b""),
        SignedVote(vote=Vote(change="junk", era=0, num=0), voter=1, sig=b""),
        SignedVote(vote=Vote(change=Remove(0), era="0", num=0), voter=1, sig=b""),
        SignedVote(vote=Vote(change=Remove(0), era=0, num=True), voter=1, sig=b""),
        SignedVote(vote=Vote(change=Remove(0), era=0, num=0), voter=[1], sig=b""),
    ]
    for sv in malformed:
        # malformed votes are attributed (INVALID_VOTE_SIGNATURE — the
        # counter's own fault kind), never raised
        faults = vc.add_pending_vote(1, sv)
        assert [f.kind for f in faults] == [FaultKind.INVALID_VOTE_SIGNATURE]
        faults = vc.add_committed_vote(1, sv)
        assert [f.kind for f in faults] == [FaultKind.INVALID_VOTE_SIGNATURE]


def test_dynamic_hb_rejects_non_int_era():
    from hbbft_tpu.protocols.dynamic_honey_badger import (
        DhbSignedVote,
        DynamicHoneyBadgerBuilder,
        _message_era,
    )

    assert _message_era("garbage") is None
    assert _message_era(DhbSignedVote(signed_vote="junk")) is None
    dhb = DynamicHoneyBadgerBuilder().build(_netinfos()[0])
    step = dhb.handle_message(1, DhbSignedVote(signed_vote="junk"))
    _is_invalid_msg_fault(step)


def test_tcp_run_logs_handler_crash_as_fault():
    from hbbft_tpu.transport.tcp import TcpNode

    class Boom:
        def handle_message(self, sender, message):
            raise RuntimeError("handler bug")

        def handle_input(self, value):
            return Step()

        def terminated(self):
            return False

    node = TcpNode(
        "127.0.0.1:1",
        ["127.0.0.1:1", "127.0.0.1:2"],
        lambda ni: Boom(),
    )
    node._inbox.put_nowait(("127.0.0.1:2", "malformed-but-deserializable"))

    async def drive():
        await node.run(until=lambda nd: len(nd.faults) > 0, timeout=10.0)

    asyncio.run(drive())
    assert node.faults == [Fault("127.0.0.1:2", FaultKind.INVALID_MESSAGE)]
