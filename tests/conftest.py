"""Test configuration.

JAX must be forced onto a virtual 8-device CPU mesh *before* it is
imported anywhere, so multi-chip sharding tests (``tests/test_parallel.py``,
``__graft_entry__.dryrun_multichip``) can validate pjit/shard_map layouts
without TPU hardware.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import random

import pytest


@pytest.fixture
def rng():
    return random.Random(0x4242)
