"""Test configuration.

JAX must be forced onto a virtual 8-device CPU mesh *before* it is
imported anywhere, so multi-chip sharding tests (``tests/test_parallel.py``,
``__graft_entry__.dryrun_multichip``) can validate pjit/shard_map layouts
without TPU hardware.
"""

import os

# HBBFT_TPU_HW=1 opts into the real-hardware smoke suite
# (tests/test_hw_smoke.py): the process then keeps the real TPU
# platform.  Everything else runs on the virtual 8-device CPU mesh.
_HW = bool(os.environ.get("HBBFT_TPU_HW"))

if not _HW:
    os.environ["JAX_PLATFORMS"] = "cpu"
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8"
        ).strip()

# Some environments inject a TPU plugin via sitecustomize that calls
# ``jax.config.update("jax_platforms", ...)`` — which silently outranks
# the env var.  Re-assert CPU *after* importing jax so the virtual
# 8-device CPU mesh is what tests actually run on.
import jax  # noqa: E402

if not _HW:
    jax.config.update("jax_platforms", "cpu")

# Persistent compilation cache: the EC scalar-mul scans are large XLA
# programs (minutes to compile cold); cache them across test runs.
# Repo-local so it survives across driver rounds (git-ignored).
_CACHE = os.path.join(os.path.dirname(os.path.dirname(__file__)), ".xla_cache")
os.makedirs(_CACHE, exist_ok=True)
jax.config.update("jax_compilation_cache_dir", _CACHE)
jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

import random  # noqa: E402

import pytest  # noqa: E402


def pytest_addoption(parser):
    parser.addoption(
        "--racecheck",
        action="store_true",
        default=False,
        help="run every test under the Eraser-style lockset checker "
        "(hbbft_tpu.analysis.racecheck); candidate races fail the test "
        "and append to $HBBFT_TPU_RACECHECK_OUT when set",
    )
    parser.addoption(
        "--rangecheck",
        action="store_true",
        default=False,
        help="run every test under the arbitrary-precision shadow "
        "sanitizer (hbbft_tpu.analysis.rangeshadow); device/shadow "
        "divergences (overflow witnesses) fail the test and append to "
        "$HBBFT_TPU_RANGECHECK_OUT when set",
    )
    parser.addoption(
        "--stallcheck",
        action="store_true",
        default=False,
        help="run every test under the event-loop stall sanitizer "
        "(hbbft_tpu.analysis.stallcheck); callbacks blocking the loop "
        "past $HBBFT_TPU_STALLCHECK_BUDGET seconds fail the test and "
        "append to $HBBFT_TPU_STALLCHECK_OUT when set",
    )


@pytest.fixture(autouse=True)
def _racecheck_guard(request):
    """With ``--racecheck``, bracket every test with the runtime
    lockset checker.  Reports surface twice: as a test failure here and
    as JSONL in ``$HBBFT_TPU_RACECHECK_OUT`` for the
    ``python -m hbbft_tpu.analysis --racecheck`` driver."""
    if not request.config.getoption("--racecheck"):
        yield
        return
    from hbbft_tpu.analysis import racecheck

    racecheck.enable()
    yield
    reports = racecheck.disable()
    if reports:
        pytest.fail(
            "racecheck: "
            + "; ".join(
                f"{r.path}:{r.line}: {r.message()}" for r in reports
            ),
            pytrace=False,
        )


@pytest.fixture(autouse=True)
def _rangecheck_guard(request):
    """With ``--rangecheck``, bracket every test with the exact-shadow
    overflow sanitizer.  Reports surface twice: as a test failure here
    and as JSONL in ``$HBBFT_TPU_RANGECHECK_OUT`` for the
    ``python -m hbbft_tpu.analysis --rangecheck`` driver."""
    if not request.config.getoption("--rangecheck"):
        yield
        return
    from hbbft_tpu.analysis import rangeshadow

    rangeshadow.enable()
    yield
    reports = rangeshadow.disable()
    if reports:
        pytest.fail(
            "rangecheck: "
            + "; ".join(
                f"{r.path}:{r.line}: {r.message()}" for r in reports
            ),
            pytrace=False,
        )


@pytest.fixture(autouse=True)
def _stallcheck_guard(request):
    """With ``--stallcheck``, bracket every test with the event-loop
    stall sanitizer.  Reports surface twice: as a test failure here and
    as JSONL in ``$HBBFT_TPU_STALLCHECK_OUT`` for the
    ``python -m hbbft_tpu.analysis --stallcheck`` driver."""
    if not request.config.getoption("--stallcheck"):
        yield
        return
    from hbbft_tpu.analysis import stallcheck

    stallcheck.enable()
    yield
    reports = stallcheck.disable()
    if reports:
        pytest.fail(
            "stallcheck: "
            + "; ".join(
                f"{r.path}:{r.line}: {r.message()}" for r in reports
            ),
            pytrace=False,
        )


@pytest.fixture
def rng():
    return random.Random(0x4242)
