"""Canonical state fingerprints (``core/digest.py``) — the dedup
backbone of badgermc.  Two behaviourally identical states must hash
identically no matter which delivery schedule built them; any real
state difference must change the hash."""

import collections
import random

import pytest

from hbbft_tpu.core.digest import DigestError, fingerprint, restore, snapshot
from hbbft_tpu.core.fault import FaultKind
from hbbft_tpu.core.network_info import NetworkInfo


def _netinfo(seed=0x11):
    return NetworkInfo.generate_map(
        list(range(4)), random.Random(seed), mock=True
    )[0]


# -- canonical encoding -----------------------------------------------------


def test_dict_and_set_insertion_order_is_invisible():
    a = {"x": 1, "y": 2, "z": 3}
    b = {"z": 3, "x": 1, "y": 2}
    assert fingerprint(a) == fingerprint(b)
    assert fingerprint({3, 1, 2}) == fingerprint({2, 3, 1})
    # nested: a schedule-dependent dict inside a list
    assert fingerprint([{"p": {1, 2}}, None]) == fingerprint([{"p": {2, 1}}, None])


def test_sequence_order_is_real_state():
    assert fingerprint([1, 2]) != fingerprint([2, 1])
    assert fingerprint(collections.deque([1, 2])) != fingerprint(
        collections.deque([2, 1])
    )


def test_value_mutation_changes_fingerprint():
    base = {"epoch": 3, "vals": [True, False], "peers": {0, 1, 2}}
    assert fingerprint(base) != fingerprint({**base, "epoch": 4})
    assert fingerprint(base) != fingerprint({**base, "vals": [True, True]})
    assert fingerprint(base) != fingerprint({**base, "peers": {0, 1, 3}})


def test_container_type_and_primitive_tags_distinguish():
    assert fingerprint((1, 2)) != fingerprint([1, 2])
    assert fingerprint(1) != fingerprint(1.0)
    assert fingerprint(True) != fingerprint(1)
    assert fingerprint(b"ab") != fingerprint("ab")


def test_enum_members_encode_by_identity():
    # the default __getstate__ walk would drag in the enum class
    # mappingproxy; the canonical form is (class, member name)
    f1 = fingerprint(FaultKind.INVALID_MESSAGE)
    assert f1 == fingerprint(FaultKind.INVALID_MESSAGE)
    assert f1 != fingerprint(FaultKind.INVALID_DECRYPTION_SHARE)
    assert fingerprint({"k": FaultKind.INVALID_MESSAGE}) == fingerprint(
        {"k": FaultKind.INVALID_MESSAGE}
    )


def test_rng_state_is_part_of_the_fingerprint():
    r1, r2 = random.Random(5), random.Random(5)
    assert fingerprint(r1) == fingerprint(r2)
    r1.random()
    assert fingerprint(r1) != fingerprint(r2)


def test_shared_subobject_equals_independent_copies():
    # the in-memory run shares one object across two slots; a replayed
    # run deserializes two equal but distinct objects — same bytes
    shared = {"v": 1}
    assert fingerprint([shared, shared]) == fingerprint([{"v": 1}, {"v": 1}])


def test_cycle_raises_digest_error():
    loop = []
    loop.append(loop)
    with pytest.raises(DigestError):
        fingerprint(loop)


# -- the DistAlgorithm hooks ------------------------------------------------


def test_protocol_state_digest_tracks_messages():
    from hbbft_tpu.protocols.sbv_broadcast import BVal, SbvBroadcast

    ni = _netinfo()
    a, b = SbvBroadcast(ni), SbvBroadcast(ni)
    assert a.state_digest() == b.state_digest()
    a.handle_message(1, BVal(True))
    assert a.state_digest() != b.state_digest()
    b.handle_message(1, BVal(True))
    assert a.state_digest() == b.state_digest()


def test_snapshot_restore_roundtrip_preserves_digest():
    from hbbft_tpu.protocols.sbv_broadcast import BVal, SbvBroadcast

    sbv = SbvBroadcast(_netinfo())
    sbv.handle_message(2, BVal(False))
    clone = sbv.restore(snapshot(sbv))
    assert clone.state_digest() == sbv.state_digest()
    # the clone is independent: stepping it diverges, the original stays
    before = sbv.state_digest()
    clone.handle_message(1, BVal(True))
    assert clone.state_digest() != before
    assert sbv.state_digest() == before
