"""Crash-recovery tests — durable WAL, checkpoint + replay restore,
TCP session resumption, and the degradation ladder.

The recovery contract under test, plane by plane:

- **WAL** (``recover/wal.py``): CRC-framed append-only records survive
  torn tails; ``read_records`` stops cleanly at the first bad record.
- **Restore** (``recover/node.py``): last snapshot + deterministic
  replay reconstructs the pre-crash state exactly, and a crash-restart
  run commits batches identical to an uninterrupted same-seed twin —
  at n=4 and n=13, mock and real threshold crypto.
- **Transport** (``transport/tcp.py``): a mid-epoch SIGKILL-sim over
  real sockets, restored via ``recover.driver``; session resumption
  replays only the missed frames, inbound dedup drops duplicates, and
  acks reflect the *applied* (WAL-logged) high-water mark — never the
  merely-delivered one.
- **Serving** (``serve/gateway.py``): every committed transaction is
  acked exactly once across the restart, zero duplicates or losses.
- **Degradation** (``ops/staging.py``, ``ops/backend_tpu.py``): a dead
  stager worker or a faulting device degrades to the host path with a
  single ``degrade`` obs event and byte-identical results — never a
  process death.
"""

import asyncio
import random

import pytest

from hbbft_tpu.harness import checkpoint as ckpt
from hbbft_tpu.harness.network import (
    MessageScheduler,
    SilentAdversary,
    TestNetwork,
)
from hbbft_tpu.harness.scenarios import _hb_batch_key, _state_eq
from hbbft_tpu.protocols.broadcast import Broadcast
from hbbft_tpu.protocols.honey_badger import HoneyBadger
from hbbft_tpu.recover import WalWriter, recover
from hbbft_tpu.recover import wal as wal_mod
from hbbft_tpu.recover.node import DurableAlgo, RecoveryError
from hbbft_tpu.transport.tcp import TcpNode


def _free_ports(n):
    import socket

    socks, ports = [], []
    for _ in range(n):
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        socks.append(s)
        ports.append(s.getsockname()[1])
    for s in socks:
        s.close()
    return ports


def _addrs(n):
    return sorted(f"127.0.0.1:{p}" for p in _free_ports(n))


# -- WAL framing ---------------------------------------------------------


def test_wal_roundtrip(tmp_path):
    p = str(tmp_path / "a.wal")
    with WalWriter(p, fsync="always") as w:
        w.append_input([b"tx-1"])
        w.append_message("peer-0", ("m", 1))
        w.append_checkpoint(b"state-bytes", {"send_seqs": {"p": 3}})
    records, clean = wal_mod.read_records(p)
    assert clean
    assert [r.kind for r in records] == [
        wal_mod.INPUT,
        wal_mod.MESSAGE,
        wal_mod.CHECKPOINT,
    ]
    assert wal_mod.decode_input(records[0].payload) == [b"tx-1"]
    assert wal_mod.decode_message(records[1].payload) == ("peer-0", ("m", 1))
    assert wal_mod.decode_checkpoint(records[2].payload) == (
        b"state-bytes",
        {"send_seqs": {"p": 3}},
    )


def test_wal_reopen_appends(tmp_path):
    p = str(tmp_path / "a.wal")
    with WalWriter(p, fsync="off") as w:
        w.append_input(1)
    with WalWriter(p, fsync="off") as w:  # reopen: no second magic
        w.append_input(2)
    records, clean = wal_mod.read_records(p)
    assert clean
    assert [wal_mod.decode_input(r.payload) for r in records] == [1, 2]


def test_wal_truncated_tail_tolerated(tmp_path):
    p = str(tmp_path / "a.wal")
    with WalWriter(p, fsync="off") as w:
        w.append_input(1)
        w.append_input(2)
    # crash mid-append: a torn header, then a torn payload
    for tail in (b"\x02\x00\x00", bytes([wal_mod.INPUT]) +
                 (100).to_bytes(4, "big") + b"\x00" * 4 + b"short"):
        with open(p, "ab") as f:
            f.write(tail)
        records, clean = wal_mod.read_records(p)
        assert not clean
        assert [wal_mod.decode_input(r.payload) for r in records] == [1, 2]
        with open(p, "rb") as f:
            data = f.read()
        with open(p, "wb") as f:
            f.write(data[: len(data) - len(tail)])


def test_wal_crc_corruption_stops_scan(tmp_path):
    p = str(tmp_path / "a.wal")
    with WalWriter(p, fsync="off") as w:
        for i in range(3):
            w.append_input(i)
    with open(p, "rb") as f:
        data = f.read()
    with open(p, "wb") as f:  # flip the last payload byte
        f.write(data[:-1] + bytes([data[-1] ^ 0xFF]))
    records, clean = wal_mod.read_records(p)
    assert not clean
    assert [wal_mod.decode_input(r.payload) for r in records] == [0, 1]


def test_wal_missing_empty_and_junk_files(tmp_path):
    assert wal_mod.read_records(str(tmp_path / "nope.wal")) == ([], True)
    empty = tmp_path / "empty.wal"
    empty.write_bytes(b"")
    assert wal_mod.read_records(str(empty)) == ([], True)
    junk = tmp_path / "junk.wal"
    junk.write_bytes(b"not a wal file")
    assert wal_mod.read_records(str(junk)) == ([], False)


def test_wal_guard_rails(tmp_path):
    p = str(tmp_path / "a.wal")
    with pytest.raises(ValueError):
        WalWriter(p, fsync="nope")
    w = WalWriter(p, fsync="off")
    with pytest.raises(wal_mod.WalError):
        w.append(7, b"")
    w.close()
    w.close()  # idempotent
    with pytest.raises(wal_mod.WalError):
        w.append_input(1)


def test_wal_interval_fsync(tmp_path):
    p = str(tmp_path / "a.wal")
    w = WalWriter(p, fsync="interval", fsync_interval_s=0.01)
    for i in range(10):
        w.append_input(i)
    w.sync()
    w.close()
    records, clean = wal_mod.read_records(p)
    assert clean and len(records) == 10


def test_recover_requires_checkpoint(tmp_path):
    p = str(tmp_path / "a.wal")
    with WalWriter(p, fsync="off") as w:
        w.append_input(b"x")
    with pytest.raises(RecoveryError):
        recover(p)


# -- checkpoint + WAL restore ≡ uninterrupted run ------------------------


def _crash_restore_run(n, mock, seed, wal_path, kill_at):
    """One HoneyBadger epoch in TestNetwork; when ``wal_path`` is set,
    node 1 is durable and is SIGKILL-simmed at step ``kill_at``, then
    restored from checkpoint + WAL and rejoined.  Returns per-node
    batch keys (sorted by node id)."""
    victim = 1
    rng = random.Random(seed)

    def new_algo(ni):
        algo = HoneyBadger(ni, rng=random.Random(f"rcv-{ni.our_id}-{seed}"))
        if wal_path is not None and ni.our_id == victim:
            return DurableAlgo(
                algo, WalWriter(wal_path, fsync="off"), checkpoint_every=1
            )
        return algo

    net = TestNetwork(
        n,
        0,
        lambda adv: SilentAdversary(
            MessageScheduler(MessageScheduler.RANDOM, rng)
        ),
        new_algo,
        rng,
        mock_crypto=mock,
    )
    for nid in sorted(net.nodes):
        node = net.nodes[nid]
        node.handle_input([b"rc-%03d" % nid])
        msgs = list(node.messages)
        node.messages.clear()
        net.dispatch_messages(nid, msgs)
    steps = 0
    resumed = None
    try:
        while not all(nd.outputs for nd in net.nodes.values()):
            assert net.any_busy(), "network quiesced before batches"
            net.step()
            steps += 1
            assert steps < 400_000, "crash-restore epoch stalled"
            if wal_path is not None and steps == kill_at:
                killed = net.kill(victim)
                assert not killed.outputs, "victim output before the kill"
                pre = ckpt.load(ckpt.save(killed.algo.algo))
                killed.algo.wal.close()
                rec = recover(wal_path)
                assert _state_eq(rec.algo, pre), (
                    "recovered state diverges from pre-crash state"
                )
                resumed = WalWriter(wal_path, fsync="off")
                net.restart(victim, rec.resume(resumed))
        assert all(not nd.faults for nd in net.nodes.values())
        return [
            _hb_batch_key(nd.outputs[0])
            for _, nd in sorted(net.nodes.items())
        ]
    finally:
        if resumed is not None:
            resumed.close()


@pytest.mark.parametrize(
    "n,mock",
    [(4, True), (13, True), (4, False)],
    ids=["n4-mock", "n13-mock", "n4-real-bls"],
)
def test_restore_equals_uninterrupted(n, mock, tmp_path):
    seed = 1000 + n + (0 if mock else 1)
    keys = _crash_restore_run(n, mock, seed, str(tmp_path / "v.wal"), 25)
    twin = _crash_restore_run(n, mock, seed, None, 0)
    assert keys == twin, "batches diverge from the no-crash twin"
    assert len(set(keys)) == 1, "validators disagree on the batch"


@pytest.mark.slow
def test_restore_equals_uninterrupted_n13_real_bls(tmp_path):
    keys = _crash_restore_run(13, False, 77, str(tmp_path / "v.wal"), 25)
    twin = _crash_restore_run(13, False, 77, None, 0)
    assert keys == twin
    assert len(set(keys)) == 1


# -- TCP session resumption: dedup + applied-not-delivered acks ----------


class _CaptureWriter:
    def __init__(self):
        self.buf = b""

    def write(self, data):
        self.buf += data


def test_resume_dedup_under_duplicate_frame_replay():
    """Feeding a resume replay stream twice delivers each frame exactly
    once, in order, and counts the duplicates."""
    from hbbft_tpu.core.step import Step, Target
    from hbbft_tpu.obs import recorder as obs

    async def run():
        a, b = "127.0.0.1:1", "127.0.0.1:2"
        sender = TcpNode(a, [b], lambda ni: Broadcast(ni, a))
        receiver = TcpNode(b, [a], lambda ni: Broadcast(ni, b))
        payloads = [b"seg-%d" % i for i in range(6)]
        for p in payloads:
            await sender._route(Step(messages=[Target.all().message(p)]))
        w = _CaptureWriter()
        sender._resume_link(b, 0, w)
        reader = asyncio.StreamReader()
        reader.feed_data(w.buf + w.buf)  # duplicated delivery
        reader.feed_eof()
        await receiver._recv_loop(a, reader)
        got = []
        while not receiver._inbox.empty():
            got.append(receiver._inbox.get_nowait())
        assert [m for _, m in got] == payloads
        assert all(s == a for s, _ in got)
        assert receiver._recv_seq[a] == len(payloads)

    rec = obs.enable()
    try:
        asyncio.run(run())
        assert rec.counters.get("wire.dup_frames", 0) == 6
    finally:
        obs.disable()


class _NullAlgo:
    """Minimal sans-IO algorithm: absorbs everything, never outputs."""

    def __init__(self, ni):
        pass

    def handle_input(self, value):
        from hbbft_tpu.core.step import Step

        return Step()

    def handle_message(self, sender, message):
        from hbbft_tpu.core.step import Step

        return Step()

    def terminated(self):
        return False


def test_ack_reflects_applied_not_delivered():
    """The resume ack must advance only as frames are *applied* by the
    pump (and therefore WAL-logged by a durable algorithm) — an ack at
    delivery time would let the peer trim frames that a crash between
    delivery and apply would then lose forever."""
    from hbbft_tpu.core.serialize import loads
    from hbbft_tpu.core.step import Step, Target
    from hbbft_tpu.transport import tcp as tcp_mod

    async def run():
        a, b = "127.0.0.1:1", "127.0.0.1:2"
        sender = TcpNode(a, [b], _NullAlgo)
        receiver = TcpNode(b, [a], _NullAlgo)
        n = tcp_mod._ACK_EVERY
        for i in range(n):
            await sender._route(
                Step(messages=[Target.all().message(b"m-%d" % i)])
            )
        w = _CaptureWriter()
        sender._resume_link(b, 0, w)
        back = _CaptureWriter()
        receiver._writers[a] = back
        reader = asyncio.StreamReader()
        reader.feed_data(w.buf)
        reader.feed_eof()
        await receiver._recv_loop(a, reader)
        # all frames delivered, none applied: no ack may have left
        assert receiver._inbox.qsize() == n
        assert back.buf == b""
        calls = {"n": 0}

        def done(nd):
            calls["n"] += 1
            return calls["n"] > n

        await receiver.run(until=done)
        acks = []
        buf = back.buf
        while buf:
            ln = int.from_bytes(buf[:4], "big")
            acks.append(loads(buf[4 : 4 + ln]))
            buf = buf[4 + ln :]
        assert [x.seq for x in acks] == [n]
        assert all(isinstance(x, tcp_mod.ResumeAck) for x in acks)

    asyncio.run(run())


# -- mid-epoch kill/restart over real TCP + exactly-once gateway acks ----


def test_tcp_kill_restart_exactly_once(tmp_path):
    """SIGKILL-sim a durable validator mid-epoch over real sockets,
    restore it from checkpoint + WAL, rejoin via session resumption:
    every node commits the same batch, and the serving gateway acks
    every committed transaction exactly once — zero duplicates, zero
    losses."""
    from hbbft_tpu.recover.driver import (
        durable_tcp_node,
        prime_replay,
        restart_tcp_node,
    )
    from hbbft_tpu.serve.gateway import AdmissionQueues, GatewayCore
    from hbbft_tpu.serve.protocol import ClientHello, SubmitTx

    core = GatewayCore(
        AdmissionQueues(per_tenant_limit=64, global_limit=128)
    )
    _, dropped = core.on_hello("c0", ClientHello(1, "alpha", "c0"))
    assert not dropped
    for s in range(4):
        replies, dropped = core.on_submit(
            "c0", SubmitTx(s, b"gw-tx-%d" % s), float(s)
        )
        assert not dropped and replies and replies[0].admitted
    txs = list(core.drain(16))
    assert len(txs) == 4
    wal_path = str(tmp_path / "victim.wal")

    def new_algo(ni):
        return HoneyBadger(ni, rng=random.Random(f"tcpcr-{ni.our_id}"))

    async def run():
        addrs = _addrs(4)
        victim_addr = addrs[0]  # smallest address: dials all peers,
        # so the restarted process re-establishes the mesh itself
        nodes = {}
        for a in addrs:
            others = [x for x in addrs if x != a]
            if a == victim_addr:
                nodes[a] = durable_tcp_node(
                    a, others, new_algo, wal_path, fsync="off"
                )
            else:
                nodes[a] = TcpNode(a, others, new_algo)
        await asyncio.gather(
            *(nd.start(mesh_timeout=15) for nd in nodes.values())
        )
        for i, a in enumerate(addrs):
            await nodes[a].input([txs[i]])
        other_tasks = [
            asyncio.ensure_future(
                nodes[a].run(
                    until=lambda nd: len(nd.outputs) >= 1, timeout=120
                )
            )
            for a in addrs
            if a != victim_addr
        ]
        # SIGKILL-sim: stop the pump mid-epoch (12 applied messages is
        # far short of an epoch at n=4), dropping the unapplied inbox
        calls = {"n": 0}

        def kill_when(nd):
            calls["n"] += 1
            return calls["n"] > 12

        victim = nodes[victim_addr]
        await victim.run(until=kill_when, timeout=60)
        assert not victim.outputs, "victim output before the kill point"
        await victim.close()
        victim.algo.wal.close()

        node2, recovery = restart_tcp_node(
            victim_addr,
            [x for x in addrs if x != victim_addr],
            wal_path,
            fsync="off",
        )
        # replay the regenerated steps into the transport so the resume
        # handshake can re-send (identically renumbered) missed frames
        await prime_replay(node2, recovery.steps)
        await node2.start(mesh_timeout=15)
        out2 = await node2.run(
            until=lambda nd: len(nd.outputs) >= 1, timeout=120
        )
        await asyncio.gather(*other_tasks)
        results = [out2[0]] + [
            nodes[a].outputs[0] for a in addrs if a != victim_addr
        ]
        node2.algo.wal.close()
        await node2.close()
        await asyncio.gather(
            *(nodes[a].close() for a in addrs if a != victim_addr)
        )
        return results

    batches = asyncio.run(run())
    keys = [_hb_batch_key(b) for b in batches]
    assert len(set(keys)) == 1, keys
    batch = batches[0]
    committed = [
        tx for _, c in sorted(batch.contributions.items()) for tx in c
    ]
    assert set(committed) <= set(txs)
    assert len(committed) >= 3  # at least n - f contributions commit
    acks = [core.on_committed(tx, batch.epoch, 10.0) for tx in committed]
    assert all(a is not None for a in acks), "committed tx never acked"
    # exactly-once: replaying the same committed batch acks nothing new
    assert all(
        core.on_committed(tx, batch.epoch, 11.0) is None
        for tx in committed
    )


# -- graceful degradation ------------------------------------------------


def test_stager_worker_death_degrades_to_inline():
    """A dead staging worker degrades to inline execution: results stay
    correct, one ``degrade`` event is emitted (sticky — never again),
    and the process survives."""
    from hbbft_tpu.obs import recorder as obs
    from hbbft_tpu.ops import staging

    st = staging.Stager()
    assert st.submit(lambda: 7).result() == 7
    assert not st.degraded()
    # simulate the worker thread dying (the poison pill makes _loop
    # return, exactly like an uncaught thread death would)
    st._q.put(None)
    st._thread.join(timeout=5)
    assert not st._thread.is_alive()
    rec = obs.enable()
    try:
        t = st.submit(lambda: 6 * 7)
        assert t.done() and t.result() == 42  # ran inline
        assert st.degraded()
        evs = [e for e in rec.events if e["ev"] == "degrade"]
        assert st.submit(lambda: 1).result() == 1
        evs2 = [e for e in rec.events if e["ev"] == "degrade"]
    finally:
        obs.disable()
    assert len(evs) == 1
    assert evs[0]["plane"] == "stager"
    assert evs[0]["reason"] == "worker-died"
    assert len(evs2) == 1  # degrade is sticky and reported once


def test_device_error_degrades_to_host(monkeypatch):
    """An induced device fault mid-call falls back to the host path
    with byte-identical results, one ``degrade`` event, and permanent
    host routing afterwards — never a crash."""
    from hbbft_tpu.crypto.backend import CpuBackend
    from hbbft_tpu.obs import recorder as obs
    from hbbft_tpu.ops import backend_tpu

    be = backend_tpu.TpuBackend()
    # the native host path would short-circuit the device path; force
    # the device route so the injected fault is actually hit
    monkeypatch.setattr(be, "_native_host", lambda: False)

    def boom(items):
        raise RuntimeError("injected device fault")

    monkeypatch.setattr(backend_tpu.sha256_jax, "sha256_many", boom)
    items = [bytes([i]) * 32 for i in range(16)]
    rec = obs.enable()
    try:
        out = be.sha256_many(items)
        evs = [e for e in rec.events if e["ev"] == "degrade"]
        out2 = be.sha256_many(items)  # host-routed, no second event
        evs2 = [e for e in rec.events if e["ev"] == "degrade"]
    finally:
        obs.disable()
    expected = CpuBackend().sha256_many(items)
    assert out == expected and out2 == expected
    assert be.degraded()
    assert len(evs) == 1
    assert evs[0]["plane"] == "device"
    assert evs[0]["reason"] == "sha256:RuntimeError"
    assert len(evs2) == 1
