"""HoneyBadger integration tests (mirrors ``tests/honey_badger.rs``).

Key invariant (reference ``verify_output_sequence``, ``:163-186``):
every correct node (and the observer) outputs the *identical sequence
of batches*, and all input transactions are eventually committed."""

import random

import pytest

from hbbft_tpu.core.step import Target
from hbbft_tpu.harness.network import (
    Adversary,
    MessageScheduler,
    MessageWithSender,
    SilentAdversary,
    TestNetwork,
)
from hbbft_tpu.protocols.honey_badger import (
    Batch,
    HbDecryptionShare,
    HoneyBadger,
    HoneyBadgerMessage,
)


def new_hb(netinfo, seed=0):
    return HoneyBadger(netinfo, rng=random.Random(f"{netinfo.our_id}-{seed}"))


class FaultyShareAdversary(Adversary):
    """Sends well-formed but wrong decryption shares for every proposer
    in early epochs (reference ``tests/honey_badger.rs:38-124``)."""

    def __init__(self, scheduler, rng, num_epochs=2):
        self.scheduler = scheduler
        self.rng = rng
        self.num_epochs = num_epochs
        self.adv_ids = []
        self.all_ids = []
        self.sent = False

    def init(self, all_nodes, adv_netinfos):
        self.adv_ids = sorted(adv_netinfos)
        self.all_ids = sorted(all_nodes)
        self.adv_netinfos = adv_netinfos

    def pick_node(self, nodes):
        return self.scheduler.pick_node(nodes)

    def push_message(self, sender_id, tm):
        pass

    def step(self):
        if self.sent or not self.adv_ids:
            return []
        self.sent = True
        out = []
        for adv_id in self.adv_ids:
            ni = self.adv_netinfos[adv_id]
            # craft syntactically valid but cryptographically wrong shares
            from hbbft_tpu.crypto.mock import MockDecryptionShare

            for epoch in range(self.num_epochs):
                for proposer in self.all_ids:
                    bogus = MockDecryptionShare(
                        self.rng.randrange(2**256).to_bytes(32, "big"),
                        self.rng.randrange(2**256).to_bytes(32, "big"),
                    )
                    msg = HoneyBadgerMessage(
                        epoch, HbDecryptionShare(proposer, bogus)
                    )
                    out.append(
                        MessageWithSender(adv_id, Target.all().message(msg))
                    )
        return out


def run_honey_badger(
    rng,
    size,
    txs_per_node=6,
    batch_contrib=2,
    adversary_factory=None,
    mock=True,
    max_batches=50,
    ops=None,
):
    f = (size - 1) // 3
    good = size - f
    if adversary_factory is None:
        adversary_factory = lambda adv: SilentAdversary(
            MessageScheduler(MessageScheduler.RANDOM, rng)
        )
    net = TestNetwork(
        good, f, adversary_factory, lambda ni: new_hb(ni), rng,
        mock_crypto=mock, ops=ops,
    )
    # per-node transaction queues
    queues = {
        nid: [b"tx-%d-%d" % (nid, i) for i in range(txs_per_node)]
        for nid in net.nodes
    }
    all_txs = {tx for q in queues.values() for tx in q}

    def committed(node):
        return {
            tx for batch in node.outputs for tx in batch.tx_iter()
        }

    def done():
        return all(committed(n) >= all_txs for n in net.nodes.values())

    guard = 0
    while not done():
        guard += 1
        assert guard < 100_000, "HoneyBadger failed to commit all txs"
        # randomly interleave proposing and stepping
        if rng.random() < 0.1 or not net.any_busy():
            nid = rng.choice(sorted(net.nodes))
            node = net.nodes[nid]
            if not node.instance.has_input():
                q = queues[nid]
                contrib = [tx for tx in q if tx not in committed(node)][
                    :batch_contrib
                ]
                node.handle_input(contrib)
                msgs = list(node.messages)
                node.messages.clear()
                net.dispatch_messages(nid, msgs)
                continue
        if net.any_busy():
            net.step()

    # identical batch sequences at all nodes (common prefix)
    seqs = [
        [(b.epoch, tuple(sorted((k, tuple(v)) for k, v in b.contributions.items())))
         for b in n.outputs]
        for n in net.nodes.values()
    ]
    min_len = min(len(s) for s in seqs)
    assert min_len > 0
    for s in seqs[1:]:
        assert s[:min_len] == seqs[0][:min_len], "batch sequences diverged"
    # observer sees the same sequence prefix
    obs_seq = [
        (b.epoch, tuple(sorted((k, tuple(v)) for k, v in b.contributions.items())))
        for b in net.observer.outputs
    ]
    k = min(len(obs_seq), min_len)
    assert obs_seq[:k] == seqs[0][:k]
    return net


def test_honey_badger_silent_sizes():
    rng = random.Random(40)
    for size in (1, 2, 4, 7):
        run_honey_badger(rng, size, txs_per_node=4)


def test_honey_badger_first_scheduler():
    rng = random.Random(41)
    run_honey_badger(
        rng,
        4,
        adversary_factory=lambda adv: SilentAdversary(
            MessageScheduler(MessageScheduler.FIRST, rng)
        ),
    )


def test_honey_badger_faulty_shares():
    rng = random.Random(42)
    net = run_honey_badger(
        rng,
        7,
        adversary_factory=lambda adv: FaultyShareAdversary(
            MessageScheduler(MessageScheduler.RANDOM, rng), rng
        ),
    )
    # bogus shares must be attributed to adversarial senders
    flagged = {
        f.node_id for n in net.nodes.values() for f in n.faults
    }
    assert flagged <= {5, 6}, flagged


def test_honey_badger_real_bls():
    rng = random.Random(43)
    run_honey_badger(rng, 4, txs_per_node=2, batch_contrib=2, mock=False)


def test_share_verification_fault_order_is_arrival_independent():
    """badgermc regression: the fault log emitted while auditing
    pending decryption shares must not depend on share-arrival order
    (the canonical walk in ``_verify_pending_decryption_shares``)."""
    from hbbft_tpu.core.network_info import NetworkInfo

    nis = NetworkInfo.generate_map(
        list(range(4)), random.Random(0x5EED), mock=True
    )
    runs = []
    for order in ([0, 1, 2, 3], [2, 0, 3, 1]):
        hb = HoneyBadger(nis[0])
        shares = {}
        for sid in order:  # insertion order == arrival order
            shares[sid] = b"bogus"
        hb.received_shares[0] = {1: shares}
        incorrect, faults = hb._verify_pending_decryption_shares(
            1, b"ciphertext", 0
        )
        assert incorrect == {0, 1, 2, 3}
        runs.append([f.node_id for f in faults])
    assert runs[0] == runs[1] == [0, 1, 2, 3]
