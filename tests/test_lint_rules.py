"""Per-rule fixture tests for badgerlint (``hbbft_tpu/analysis/``).

Each rule is demonstrated by a minimal source fixture that trips it
under a pretend package-relative path, plus a near-identical clean
variant that does not — so a rule that silently stops firing (or
starts over-firing) fails here, not in a production trace.  The
suppression comment, the baseline round-trip, and the CLI surface are
exercised the same way.
"""

import json
import subprocess
import sys
import textwrap

import pytest

from hbbft_tpu.analysis import (
    Baseline,
    Violation,
    all_rules,
    lint_source,
)
from hbbft_tpu.analysis.cli import main as cli_main

RULES = all_rules()


def _lint(source, relpath, select=None):
    rules = RULES
    if select is not None:
        rules = [r for r in RULES if r.name == select]
        assert rules, f"no such rule: {select}"
    return lint_source(textwrap.dedent(source), relpath, rules)


def _names(violations):
    return sorted({v.rule for v in violations})


# ---------------------------------------------------------------------------
# determinism
# ---------------------------------------------------------------------------


def test_determinism_flags_unseeded_rng_and_clocks():
    src = """
        import random, time, os, uuid

        class Algo:
            def __init__(self):
                self.rng = random.Random()

            def handle_message(self, sender, msg):
                now = time.time()
                tag = uuid.uuid4()
                noise = os.urandom(8)
                key = id(msg)
                return now, tag, noise, key
    """
    vs = _lint(src, "protocols/fixture.py", select="determinism")
    msgs = "\n".join(v.message for v in vs)
    assert len(vs) == 5
    assert "unseeded random.Random()" in msgs
    assert "time.time" in msgs
    assert "uuid.uuid4" in msgs
    assert "os.urandom" in msgs
    assert "id() is address-derived" in msgs


def test_determinism_allows_seeded_and_injected_rng():
    src = """
        import random

        class Algo:
            def __init__(self, netinfo, rng=None):
                self.rng = rng or netinfo.default_rng("algo")
                self.aux = random.Random(42)
    """
    assert _lint(src, "protocols/fixture.py", select="determinism") == []


def test_determinism_flags_global_random_helpers():
    src = """
        import random

        def pick(xs):
            return random.choice(xs)
    """
    vs = _lint(src, "core/fixture.py", select="determinism")
    assert len(vs) == 1
    assert "ambient-seeded global RNG" in vs[0].message


def test_determinism_scope_excludes_harness():
    src = "import time\nx = time.time()\n"
    assert _lint(src, "harness/fixture.py", select="determinism") == []


# ---------------------------------------------------------------------------
# ordered-iter
# ---------------------------------------------------------------------------


def test_ordered_iter_flags_bare_set_iteration():
    src = """
        class Algo:
            def __init__(self):
                self.pending = set()

            def flush(self, step):
                for nid in self.pending:
                    step.send_to(nid, "x")
    """
    vs = _lint(src, "protocols/fixture.py", select="ordered-iter")
    assert len(vs) == 1
    assert "set-typed 'self.pending'" in vs[0].message
    assert "emitting path" in vs[0].message


def test_ordered_iter_sorted_wrapper_is_clean():
    src = """
        class Algo:
            def __init__(self):
                self.pending = set()

            def flush(self, step):
                for nid in sorted(self.pending):
                    step.send_to(nid, "x")
    """
    assert _lint(src, "protocols/fixture.py", select="ordered-iter") == []


def test_ordered_iter_dict_keys_only_on_emitting_paths():
    src = """
        def tally(counts):
            return [counts[k] for k in counts.keys()]

        def emit(counts, step):
            for k in counts.keys():
                step.send_all(k)
    """
    vs = _lint(src, "protocols/fixture.py", select="ordered-iter")
    assert len(vs) == 1
    assert "dict.keys()" in vs[0].message
    assert vs[0].line > 4  # the emitting function, not the tally


def test_ordered_iter_scope_excludes_ops():
    src = "def f(s: set):\n    return [x for x in s]\n"
    assert _lint(src, "ops/fixture.py", select="ordered-iter") == []


# ---------------------------------------------------------------------------
# device-sync
# ---------------------------------------------------------------------------


def test_device_sync_flags_sync_inside_decorated_jit():
    src = """
        import jax
        import numpy as np

        @jax.jit
        def kernel(x):
            n = int(x)
            h = np.asarray(x)
            return x.sum().item() + n + h
    """
    vs = _lint(src, "ops/fixture.py", select="device-sync")
    msgs = "\n".join(v.message for v in vs)
    assert len(vs) == 3
    assert ".item() forces a device sync" in msgs
    assert "np.asarray materializes" in msgs
    assert "int() on a (possibly traced) value" in msgs


def test_device_sync_finds_jit_wrap_sites():
    src = """
        import jax

        def kernel(x):
            return float(x)

        kernel_j = jax.jit(kernel)
    """
    vs = _lint(src, "harness/fixture.py", select="device-sync")
    assert len(vs) == 1
    assert "float()" in vs[0].message


def test_device_sync_allows_shape_arithmetic_and_plain_functions():
    src = """
        import jax

        @jax.jit
        def kernel(x):
            n = int(x.shape[0])
            m = float(len(x.shape))
            return x * n * m

        def host_helper(x):
            return int(x)  # not a jit region
    """
    assert _lint(src, "ops/fixture.py", select="device-sync") == []


def test_device_sync_staging_module_checked_outside_jit():
    # ops/staging is the flush pipeline's overlap window: blocking /
    # materializing calls are flagged MODULE-WIDE, not just in @jit
    src = """
        import jax
        import numpy as np

        def marshal(task, dev):
            dev.block_until_ready()
            host = np.asarray(dev)
            got = jax.device_get(dev)
            return host, got
    """
    vs = _lint(src, "ops/staging.py", select="device-sync")
    msgs = "\n".join(v.message for v in vs)
    assert len(vs) == 3
    assert ".block_until_ready() blocks the staging overlap window" in msgs
    assert "np.asarray materializes a device value" in msgs
    assert "jax.device_get blocks the staging overlap window" in msgs
    # the same source outside ops/staging has no jit region: clean
    assert _lint(src, "ops/fixture.py", select="device-sync") == []


def test_device_sync_staging_module_allows_host_arithmetic():
    # int()/float() on concrete numpy are ordinary host arithmetic in
    # the staging module — no concretization hazard without tracing
    src = """
        import numpy as np

        def lease_shape(shape, k):
            rows = int(np.prod(shape))
            return rows, float(k)
    """
    assert _lint(src, "ops/staging.py", select="device-sync") == []


def test_device_sync_flags_host_gather_in_shard_map_body():
    # a shard_map body (the mesh flush plane) must never pull shard
    # values through the host — that is exactly the gather the mesh
    # engine removes
    src = """
        import functools
        import jax
        import numpy as np
        from jax.sharding import PartitionSpec as P

        @functools.partial(shard_map, mesh=mesh, in_specs=(P("s"),), out_specs=P())
        def _sharded(wires):
            local = reduce_local(wires)
            host = jax.device_get(local)
            back = np.asarray(host)
            return back
    """
    vs = _lint(src, "parallel/fixture.py", select="device-sync")
    msgs = "\n".join(v.message for v in vs)
    assert len(vs) == 2
    assert "host gather of per-shard values" in msgs
    assert "breaks the mesh overlap window" in msgs
    assert "shard_map body" in msgs


def test_device_sync_shard_map_wrap_site_beats_jit_diagnosis():
    # jax.jit(shard_map(f)) is the normal mesh stack: f must get the
    # shard_map diagnosis (the more specific one), found via the
    # wrap-site form and a dotted re-export spelling
    src = """
        import jax
        from hbbft_tpu.parallel import mesh as M

        def _body(x):
            return x.sum().item()

        _sharded = M.shard_map(_body, mesh=mesh, in_specs=(P("s"),), out_specs=P())
        runner = jax.jit(_sharded)
    """
    vs = _lint(src, "parallel/fixture.py", select="device-sync")
    assert len(vs) == 1
    assert "inside a shard_map body" in vs[0].message
    assert "per-shard host sync" in vs[0].message


def test_device_sync_shard_map_allows_collectives_and_shapes():
    # on-device collectives (all_gather / ppermute / the Pallas remote
    # copy ring) and shape arithmetic are the legal moves in a
    # shard_map body
    src = """
        import functools
        import jax

        @functools.partial(shard_map, mesh=mesh, in_specs=(P("s"),), out_specs=P())
        def _sharded(pts):
            local = kern.tree_sum(kern.scalar_mul(pts, int(pts.shape[0])))
            partials = jax.lax.all_gather(local, "s")
            rolled = jax.lax.ppermute(local, "s", perm)
            return kern.tree_sum(partials) + rolled
    """
    assert _lint(src, "parallel/fixture.py", select="device-sync") == []


def test_device_sync_donation_flags_undonated_jit_on_staged_buffers():
    # a flush-path launcher that leases pool buffers / device_puts and
    # then wraps the program with bare jax.jit keeps two device copies
    # of every staged operand alive — the donation pass gates this
    src = """
        import jax

        def launch_chunk(wires, sc, lease):
            buf = lease.get((128, 96))
            buf[: wires.shape[0]] = wires
            dev = jax.device_put(buf)
            dev_sc = jax.device_put(sc)
            run = jax.jit(_unpack_and_sum)
            return run(dev, dev_sc)
    """
    vs = _lint(src, "ops/fixture.py", select="device-sync")
    assert len(vs) == 1
    assert "donate_argnums" in vs[0].message


def test_device_sync_donation_allows_donated_and_unstaged_sites():
    # donate_argnums at the wrap site (or routing through
    # cached_compiled's donate=) satisfies the pass; jit wrappers in
    # functions that never touch staged buffers are out of scope
    src = """
        import functools
        import jax

        def launch_chunk(wires, sc, lease):
            dev = jax.device_put(lease.get((128, 96)))
            dev_sc = jax.device_put(sc)
            run = jax.jit(_unpack_and_sum, donate_argnums=(0, 1))
            return run(dev, dev_sc)

        def launch_cached(dev, dev_sc):
            jax.device_put(dev)
            return pallas_ec.cached_compiled(
                "prog", _unpack_and_sum, dev, dev_sc, donate=(0, 1)
            )

        @functools.lru_cache(maxsize=None)
        def _cpu_fallback_jit():
            return jax.jit(_unpack_and_sum)
    """
    assert _lint(src, "ops/fixture.py", select="device-sync") == []


def test_device_sync_donation_suppressible_inline():
    src = """
        import jax

        def launch_chunk(wires, lease):
            dev = jax.device_put(lease.get((128, 96)))
            run = jax.jit(_sum)  # lint: ok(device-sync) operand reused by later launch
            return run(dev)
    """
    assert _lint(src, "ops/fixture.py", select="device-sync") == []


# ---------------------------------------------------------------------------
# dtype-width
# ---------------------------------------------------------------------------


def test_dtype_width_requires_preferred_element_type():
    src = """
        import jax.numpy as jnp
        from jax import lax

        def mul(a, b):
            good = lax.dot_general(
                a, b, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.int32,
            )
            bad = lax.dot_general(a, b, (((1,), (0,)), ((), ())))
            worse = jnp.einsum("ij,jk->ik", a, b)
            return good, bad, worse
    """
    vs = _lint(src, "ops/limbs.py", select="dtype-width")
    assert len(vs) == 2
    assert all("preferred_element_type" in v.message for v in vs)


def test_dtype_width_flags_narrow_product_and_overflowing_constant():
    src = """
        import jax.numpy as jnp
        import numpy as np

        def f(a, b):
            wraps = a.astype(jnp.uint8) * b.astype(jnp.uint8)
            mask = np.int8(300)
            ok = jnp.array(255, dtype=jnp.uint8)
            neg = jnp.array(-128, dtype=jnp.int8)
            return wraps, mask, ok, neg
    """
    vs = _lint(src, "ops/fr_jax.py", select="dtype-width")
    msgs = "\n".join(v.message for v in vs)
    assert len(vs) == 2
    assert "uint8×uint8 narrow casts" in msgs
    assert "constant 300 does not fit declared dtype int8" in msgs


def test_dtype_width_scope_is_limb_modules_only():
    src = "import jax.numpy as jnp\nx = jnp.einsum('ij,jk->ik', 1, 2)\n"
    assert _lint(src, "harness/fixture.py", select="dtype-width") == []


# ---------------------------------------------------------------------------
# layering
# ---------------------------------------------------------------------------


def test_layering_flags_upward_imports():
    src = """
        from ..harness import batching
        from hbbft_tpu.transport import tcp
    """
    vs = _lint(src, "ops/fixture.py", select="layering")
    assert len(vs) == 2
    assert "must not import layer 'harness'" in vs[0].message
    assert "must not import layer 'transport'" in vs[1].message


def test_layering_resolves_relative_imports():
    src = """
        from ..core.step import Step
        from ..crypto import threshold
        from . import agreement
    """
    # legal from protocols/ (core + crypto + self are all allowed)
    assert _lint(src, "protocols/fixture.py", select="layering") == []
    # the SAME source under obs/ trips twice: obs imports nothing
    vs = _lint(src, "obs/fixture.py", select="layering")
    assert len(vs) == 2
    assert all("must not import layer" in v.message for v in vs)


def test_layering_root_package_from_import_uses_alias_names():
    src = "from .. import harness\n"
    vs = _lint(src, "protocols/fixture.py", select="layering")
    assert len(vs) == 1
    assert "'harness'" in vs[0].message


def test_layering_external_imports_unconstrained():
    src = "import numpy\nfrom typing import Any\n"
    assert _lint(src, "obs/fixture.py", select="layering") == []


# ---------------------------------------------------------------------------
# obs-schema
# ---------------------------------------------------------------------------


def test_obs_schema_flags_unknown_event_and_fields():
    src = """
        def f(rec):
            rec.event("no_such_event", x=1)
            rec.event("epoch_start", epoch=1, vt=0.5, bogus=2)
            rec.event("epoch_start", epoch=1)  # vt missing
    """
    vs = _lint(src, "harness/fixture.py", select="obs-schema")
    msgs = "\n".join(v.message for v in vs)
    assert len(vs) == 3
    assert "unknown event type 'no_such_event'" in msgs
    assert "field 'bogus' is not in the schema" in msgs
    assert "missing required field(s) vt" in msgs


def test_obs_schema_flags_reserved_trace_fields():
    """``tn``/``ts``/``te`` are stamped by the Recorder itself — an
    emit site passing one explicitly would collide with (or spoof) the
    trace context."""
    src = """
        def f(rec):
            rec.event("epoch_start", epoch=1, vt=0.5, tn="spoof")
            rec.event("span", name="x", dur=0.1, depth=0, ts=9, te=2)
    """
    vs = _lint(src, "harness/fixture.py", select="obs-schema")
    msgs = "\n".join(v.message for v in vs)
    assert len(vs) == 3
    for field in ("tn", "ts", "te"):
        assert f"field '{field}' is a reserved trace-context field" in msgs


def test_obs_schema_accepts_valid_and_open_events():
    src = """
        def f(rec, extra):
            rec.event("epoch_start", epoch=1, vt=0.5)
            rec.event("span", name="x", dur=0.1, depth=0, anything="goes")
            rec.event("flush", queued=1, shipped=1, real=1, inline=0, dur=0.2)
            rec.event("epoch", **extra)  # splat: named subset only
    """
    assert _lint(src, "harness/fixture.py", select="obs-schema") == []


# ---------------------------------------------------------------------------
# step-purity
# ---------------------------------------------------------------------------


def test_step_purity_flags_impure_handler_effects():
    src = """
        CACHE = {}

        class Algo(DistAlgorithm):
            def handle_message(self, sender_id, msg):
                msg.seen = True
                msg.votes.append(sender_id)
                CACHE[sender_id] = msg
                print("got", msg)
                return None
    """
    vs = _lint(src, "protocols/fixture.py", select="step-purity")
    msgs = "\n".join(v.message for v in vs)
    assert len(vs) == 5
    assert "writes through argument-derived 'msg'" in msgs
    assert "mutates argument-derived 'msg' via .append()" in msgs
    assert "writes module-level state 'CACHE'" in msgs
    assert "calls print()" in msgs
    assert "returns None" in msgs


def test_step_purity_flags_transport_calls_and_aliased_mutation():
    src = """
        import socket
        from ..transport.tcp import send_frame

        class Algo(DistAlgorithm):
            def handle_message(self, sender_id, msg):
                votes = msg.votes
                votes.append(sender_id)
                send_frame(sender_id, msg)
                sock = socket.socket()
                sock.sendall(b"x")
                return Step()
    """
    vs = _lint(src, "protocols/fixture.py", select="step-purity")
    msgs = "\n".join(v.message for v in vs)
    assert len(vs) == 4
    assert "mutates argument-derived 'votes'" in msgs
    assert "transport API 'send_frame'" in msgs
    assert "transport API 'socket.socket'" in msgs
    assert "socket-style 'sock.sendall'" in msgs


def test_step_purity_clean_handler_and_combinators():
    src = """
        class Algo(DistAlgorithm):
            def handle_message(self, sender_id, msg):
                if msg.bad:
                    return Step.from_fault(sender_id, FaultKind.INVALID_MESSAGE)
                self.received[sender_id] = msg
                votes = list(msg.votes)
                step: Step = Step()
                step.send_all(msg)
                step.extend(self._flush(votes))
                return step

            def handle_input(self, value):
                return self._propose(value)

            def _flush(self, votes):
                return Step()
    """
    assert _lint(src, "protocols/fixture.py", select="step-purity") == []


def test_step_purity_scope_is_dist_algorithms_only():
    """SyncKeyGen-style helpers keep their out-parameter convention."""
    src = """
        class SyncKeyGen:
            def handle_part(self, sender_id, part, faults):
                faults.append(sender_id)
                return None
    """
    assert _lint(src, "protocols/fixture.py", select="step-purity") == []
    # and the same class IS flagged once it claims to be a DistAlgorithm
    src2 = src.replace("class SyncKeyGen:", "class SyncKeyGen(DistAlgorithm):")
    assert len(_lint(src2, "protocols/fixture.py", select="step-purity")) == 2


def test_step_purity_suppression_and_baseline():
    src = """
        class Algo(DistAlgorithm):
            def handle_message(self, sender_id, msg):
                msg.seen = True  # lint: ok(step-purity)
                return Step()
    """
    assert _lint(src, "protocols/fixture.py", select="step-purity") == []
    flagged = _lint(
        src.replace("  # lint: ok(step-purity)", ""),
        "protocols/fixture.py",
        select="step-purity",
    )
    assert len(flagged) == 1
    bl = Baseline.from_violations(flagged, "legacy handler, tracked")
    assert bl.split(flagged) == ([], flagged)


# ---------------------------------------------------------------------------
# wire-stability
# ---------------------------------------------------------------------------


WIRE_SRC = """
    import dataclasses
    from ..core.serialize import wire

    @wire("Vote")
    @dataclasses.dataclass(frozen=True)
    class Vote:
        change: object
        era: int
        num: int
"""


def _wire_manifest(fields=("change", "era", "num"), types=None):
    all_types = {
        "Vote": {
            "module": "protocols/fixture.py",
            "kind": "dataclass",
            "fields": list(fields),
        }
    }
    if types is not None:
        all_types = types
    return {
        "version": 1,
        "serialize_module": "core/serialize.py",
        "primitive_tags": {"_TAG_NONE": 0, "_TAG_STR": 6},
        "types": all_types,
    }


def _wire_lint(src, relpath, manifest):
    from hbbft_tpu.analysis.rules.wire_stability import WireStabilityRule

    return lint_source(
        textwrap.dedent(src), relpath, [WireStabilityRule(manifest=manifest)]
    )


def test_wire_stability_matching_manifest_is_clean():
    assert _wire_lint(WIRE_SRC, "protocols/fixture.py", _wire_manifest()) == []


def test_wire_stability_flags_reorder_and_append():
    reordered = _wire_lint(
        WIRE_SRC, "protocols/fixture.py", _wire_manifest(("era", "change", "num"))
    )
    assert len(reordered) == 1
    assert "field order changed incompatibly" in reordered[0].message

    appended = _wire_lint(
        WIRE_SRC, "protocols/fixture.py", _wire_manifest(("change", "era"))
    )
    assert len(appended) == 1
    assert "appended field(s) num" in appended[0].message
    assert "--write-wire-manifest" in appended[0].message


def test_wire_stability_flags_type_deleted_from_manifest():
    """Deleting a tag from the manifest (or adding a type without
    regenerating) fails the lint."""
    vs = _wire_lint(WIRE_SRC, "protocols/fixture.py", _wire_manifest(types={}))
    assert len(vs) == 1
    assert "not in wire_manifest.json" in vs[0].message


def test_wire_stability_flags_removed_type_via_finish_run():
    manifest = _wire_manifest(
        types={
            "Gone": {
                "module": "protocols/fixture.py",
                "kind": "dataclass",
                "fields": ["x"],
            }
        }
    )
    vs = _wire_lint("x = 1\n", "protocols/fixture.py", manifest)
    assert len(vs) == 1
    assert "'Gone' removed or renamed" in vs[0].message
    # a module the run never scanned stays un-flagged
    assert _wire_lint("x = 1\n", "protocols/other.py", manifest) == []


def test_wire_stability_primitive_tag_table_append_only():
    src = """
        _TAG_NONE = b"\\x01"
        _TAG_LIST = b"\\x07"
    """
    vs = _wire_lint(src, "core/serialize.py", _wire_manifest(types={}))
    msgs = "\n".join(v.message for v in vs)
    assert len(vs) == 2
    assert "renumbered 0x00" in msgs  # _TAG_NONE moved
    assert "_TAG_STR (byte 0x06) removed" in msgs
    clean = """
        _TAG_NONE = b"\\x00"
        _TAG_STR = b"\\x06"
        _TAG_NEW = b"\\x0b"
    """
    assert _wire_lint(clean, "core/serialize.py", _wire_manifest(types={})) == []


def test_wire_stability_checked_in_manifest_matches_tree():
    """The default rule instance (checked-in manifest) over the real
    package: regeneration drift fails here before CI's tree gate."""
    import os

    from hbbft_tpu.analysis.cli import DEFAULT_BASELINE
    from hbbft_tpu.analysis.rules.wire_stability import (
        DEFAULT_MANIFEST,
        build_manifest,
    )

    assert os.path.exists(DEFAULT_MANIFEST)
    pkg = os.path.dirname(DEFAULT_BASELINE).rsplit(os.sep, 1)[0]
    built = build_manifest([pkg])
    with open(DEFAULT_MANIFEST) as fh:
        assert json.load(fh) == built


# ---------------------------------------------------------------------------
# pallas-shape
# ---------------------------------------------------------------------------


def _pallas_src(block="(2, 128)", grid="(4,)", out="(8, 128)"):
    return f"""
        import jax
        import jax.numpy as jnp
        from jax.experimental import pallas as pl

        def run(kernel, x):
            block = {block}
            return pl.pallas_call(
                kernel,
                out_shape=jax.ShapeDtypeStruct({out}, jnp.int32),
                grid={grid},
                in_specs=[pl.BlockSpec(block, lambda g: (g, 0))],
                out_specs=pl.BlockSpec(block, lambda g: (g, 0)),
            )(x)
    """


def test_pallas_shape_exact_tiling_is_clean():
    assert _lint(_pallas_src(), "ops/fixture.py", select="pallas-shape") == []


def test_pallas_shape_flags_shrunk_block_and_non_divisor():
    # grid 4 × block 1 covers only 4 of the 8 output rows
    vs = _lint(
        _pallas_src(block="(1, 128)"), "ops/fixture.py", select="pallas-shape"
    )
    assert len(vs) == 1
    assert "4×1=4) does not tile array dim 8" in vs[0].message

    # block 3 does not divide dim 8 at all
    vs = _lint(
        _pallas_src(block="(3, 128)", grid="(2,)"),
        "ops/fixture.py",
        select="pallas-shape",
    )
    assert len(vs) == 1
    assert "block dim 3 does not divide array dim 8" in vs[0].message


def test_pallas_shape_flags_arity_and_missing_grid():
    src = _pallas_src().replace("lambda g:", "lambda g, h:")
    vs = _lint(src, "ops/fixture.py", select="pallas-shape")
    assert len(vs) == 2  # both specs
    assert all("takes 2 arg(s) but the grid has rank 1" in v.message for v in vs)

    src = """
        from jax.experimental import pallas as pl

        def run(kernel, x):
            return pl.pallas_call(kernel, out_shape=None)(x)
    """
    vs = _lint(src, "ops/fixture.py", select="pallas-shape")
    assert len(vs) == 1
    assert "without grid=" in vs[0].message


def test_pallas_shape_resolves_spec_helper_functions():
    """The ``spec()`` closure idiom from ops/pallas_ec.py, fully
    concrete: the tiled index map evaluates through the helper."""
    src = """
        import jax
        import jax.numpy as jnp
        from jax.experimental import pallas as pl

        def run(kernel, x):
            G = 2
            T = 128
            block = (1, T)

            def spec(blk, tiled=True):
                index_map = (
                    (lambda g: (g,) + (0,) * (len(blk) - 1))
                    if tiled
                    else (lambda g: (0,) * len(blk))
                )
                return pl.BlockSpec(blk, index_map)

            return pl.pallas_call(
                kernel,
                out_shape=jax.ShapeDtypeStruct((4, T), jnp.int32),
                grid=(G,),
                in_specs=[spec(block)],
                out_specs=spec(block),
            )(x)
    """
    vs = _lint(src, "ops/fixture.py", select="pallas-shape")
    assert len(vs) == 1  # out_spec: 2×1 covers 2 of 4 rows
    assert "2×1=2) does not tile array dim 4" in vs[0].message
    assert _lint(src.replace("G = 2", "G = 4"), "ops/fixture.py",
                 select="pallas-shape") == []


def test_pallas_shape_scope_and_suppression():
    bad = _pallas_src(block="(1, 128)")
    assert _lint(bad, "protocols/fixture.py", select="pallas-shape") == []
    suppressed = bad.replace(
        "out_specs=pl.BlockSpec(block, lambda g: (g, 0)),",
        "out_specs=pl.BlockSpec(block, lambda g: (g, 0)),  # lint: ok(pallas-shape)",
    )
    # suppression anchors on the pallas_call line; the violation node
    # is the out_specs expression — comment goes on its line
    vs = _lint(suppressed, "ops/fixture.py", select="pallas-shape")
    assert vs == []


# ---------------------------------------------------------------------------
# thread-shared-state
# ---------------------------------------------------------------------------

_SHARED_STATE_SRC = """
    import threading

    CACHE = {{}}
    _LOCK = threading.Lock()

    def _worker():
        {worker_write}

    def start():
        t = threading.Thread(target=_worker, name="hbbft-w", daemon=True)
        t.start()
        return t

    def lookup(key):
        {main_write}
        return CACHE.get(key)
"""


def test_thread_shared_state_flags_unguarded_writes():
    src = _SHARED_STATE_SRC.format(
        worker_write='CACHE["w"] = 1',
        main_write='CACHE[key] = 2',
    )
    vs = _lint(src, "ops/fixture.py", select="thread-shared-state")
    assert len(vs) == 2  # both the worker's and the main path's write
    assert all("unguarded write to 'ops/fixture.CACHE'" in v.message for v in vs)
    assert all("_worker" in v.message for v in vs)  # names the thread side


def test_thread_shared_state_locked_writes_are_clean():
    src = _SHARED_STATE_SRC.format(
        worker_write='with _LOCK:\n            CACHE["w"] = 1',
        main_write="with _LOCK:\n            CACHE[key] = 2",
    )
    assert _lint(src, "ops/fixture.py", select="thread-shared-state") == []


def test_thread_shared_state_no_spawn_no_sharing():
    # same writes, but nothing ever runs on a thread — not shared
    src = """
        CACHE = {}

        def put(k):
            CACHE[k] = 1
    """
    assert _lint(src, "ops/fixture.py", select="thread-shared-state") == []


def test_thread_shared_state_suppression_survives_finish_run():
    # cross-file rules report at finish_run, after the per-file
    # suppression filter has run — the flag must be honored anyway
    src = _SHARED_STATE_SRC.format(
        worker_write='CACHE["w"] = 1  # lint: ok(thread-shared-state)',
        main_write='CACHE[key] = 2  # lint: ok(thread-shared-state)',
    )
    assert _lint(src, "ops/fixture.py", select="thread-shared-state") == []


def test_thread_shared_state_flags_anonymous_threads():
    src = """
        import threading
        from concurrent.futures import ThreadPoolExecutor

        def _work():
            return 1

        def bad():
            threading.Thread(target=_work, daemon=True).start()
            threading.Thread(target=_work, name="waiter").start()
            with ThreadPoolExecutor(max_workers=1) as ex:
                ex.submit(_work)

        def good():
            threading.Thread(target=_work, name="hbbft-x").start()
            with ThreadPoolExecutor(
                max_workers=1, thread_name_prefix="hbbft-y"
            ) as ex:
                ex.submit(_work)
    """
    vs = _lint(src, "harness/fixture.py", select="thread-shared-state")
    assert len(vs) == 3
    msgs = "\n".join(v.message for v in vs)
    assert msgs.count("threading.Thread without a stable") == 2
    assert msgs.count("ThreadPoolExecutor without") == 1


_QUEUE_HANDOFF_SRC = """
    import queue
    import threading

    _Q = None

    def _chan():
        global _Q
        if _Q is None:
            _Q = {ctor}
        return _Q

    def _worker():
        _chan().put(1)

    def start():
        threading.Thread(target=_worker, name="hbbft-q", daemon=True).start()

    def drain():
        return _chan().get()
"""


def test_thread_shared_state_queue_handoff_is_safe():
    # a lazily-bound queue.* global is an internally-locked handoff
    # channel: neither the shared-state pass nor atomic-cache flags it,
    # with no suppression comment needed
    for ctor in ("queue.SimpleQueue()", "queue.Queue(maxsize=8)"):
        src = _QUEUE_HANDOFF_SRC.format(ctor=ctor)
        assert _lint(src, "ops/fixture.py", select="thread-shared-state") == []
        assert _lint(src, "ops/fixture.py", select="atomic-cache") == []


def test_thread_shared_state_queue_exemption_is_narrow():
    # the identical shape with a plain container still flags under both
    # rules — the exemption keys on the constructor, not the idiom
    src = _QUEUE_HANDOFF_SRC.format(ctor="[]")
    vs = _lint(src, "ops/fixture.py", select="thread-shared-state")
    assert len(vs) == 1 and "unguarded write to 'ops/fixture._Q'" in vs[0].message
    assert _lint(src, "ops/fixture.py", select="atomic-cache") != []
    # one rebind to a non-queue value demotes the name even when
    # another rebind is a queue
    mixed = _QUEUE_HANDOFF_SRC.format(ctor="queue.SimpleQueue()") + (
        "\n    def reset():\n        global _Q\n        _Q = []\n"
    )
    vs = _lint(mixed, "ops/fixture.py", select="thread-shared-state")
    assert len(vs) >= 1


# ---------------------------------------------------------------------------
# lock-order
# ---------------------------------------------------------------------------


def test_lock_order_flags_cycle_with_thread_note():
    src = """
        import threading

        A_LOCK = threading.Lock()
        B_LOCK = threading.Lock()

        def daemon_path():
            with A_LOCK:
                with B_LOCK:
                    pass

        def start():
            threading.Thread(
                target=daemon_path, name="hbbft-d", daemon=True
            ).start()

        def main_path():
            with B_LOCK:
                with A_LOCK:
                    pass
    """
    vs = _lint(src, "ops/fixture.py", select="lock-order")
    assert len(vs) == 2  # one per edge of the 2-cycle
    msgs = "\n".join(v.message for v in vs)
    assert "completes a lock-order cycle" in msgs
    assert "daemon and the main path disagree" in msgs


def test_lock_order_consistent_order_is_clean():
    src = """
        import threading

        A_LOCK = threading.Lock()
        B_LOCK = threading.Lock()

        def one():
            with A_LOCK:
                with B_LOCK:
                    pass

        def two():
            with A_LOCK:
                with B_LOCK:
                    pass
    """
    assert _lint(src, "ops/fixture.py", select="lock-order") == []


def test_lock_order_interprocedural_edge():
    # with A: helper() where helper takes B, plus a direct B→A nesting
    src = """
        import threading

        A_LOCK = threading.Lock()
        B_LOCK = threading.Lock()

        def helper():
            with B_LOCK:
                pass

        def one():
            with A_LOCK:
                helper()

        def two():
            with B_LOCK:
                with A_LOCK:
                    pass
    """
    vs = _lint(src, "ops/fixture.py", select="lock-order")
    assert vs, "call-through acquisition must close the cycle"
    assert any("cycle" in v.message for v in vs)


def test_lock_order_self_deadlock_on_plain_lock_only():
    plain = """
        import threading

        MY_LOCK = threading.Lock()

        def reenter():
            with MY_LOCK:
                with MY_LOCK:
                    pass
    """
    vs = _lint(plain, "ops/fixture.py", select="lock-order")
    assert len(vs) == 1
    assert "non-reentrant lock" in vs[0].message

    reentrant = plain.replace("threading.Lock()", "threading.RLock()")
    assert _lint(reentrant, "ops/fixture.py", select="lock-order") == []


# ---------------------------------------------------------------------------
# atomic-cache
# ---------------------------------------------------------------------------

_ATOMIC_SRC = """
    import threading

    CACHE = {{}}
    _STATE = None
    _LOCK = threading.Lock()

    def _bg():
        return 1

    def start():
        threading.Thread(target=_bg, name="hbbft-bg", daemon=True).start()

    {body}
"""


def test_atomic_cache_flags_membership_guard():
    src = _ATOMIC_SRC.format(
        body="""
    def get(k):
        if k not in CACHE:
            CACHE[k] = object()
        return CACHE[k]
    """
    )
    vs = _lint(src, "ops/fixture.py", select="atomic-cache")
    assert len(vs) == 1
    assert "check-then-act on 'ops/fixture.CACHE'" in vs[0].message


def test_atomic_cache_flags_lazy_init():
    src = _ATOMIC_SRC.format(
        body="""
    def state():
        global _STATE
        if _STATE is None:
            _STATE = {}
        return _STATE
    """
    )
    vs = _lint(src, "ops/fixture.py", select="atomic-cache")
    assert len(vs) == 1
    assert "lazy init" in vs[0].message


def test_atomic_cache_double_checked_locking_is_legal():
    src = _ATOMIC_SRC.format(
        body="""
    def state():
        global _STATE
        if _STATE is None:
            with _LOCK:
                if _STATE is None:
                    _STATE = {}
        return _STATE
    """
    )
    assert _lint(src, "ops/fixture.py", select="atomic-cache") == []


def test_atomic_cache_ignores_single_threaded_modules():
    # identical idiom, but the module never spawns a thread
    src = """
        CACHE = {}

        def get(k):
            if k not in CACHE:
                CACHE[k] = object()
            return CACHE[k]
    """
    assert _lint(src, "ops/fixture.py", select="atomic-cache") == []


# ---------------------------------------------------------------------------
# suppression + baseline
# ---------------------------------------------------------------------------


def test_suppression_comment_silences_one_rule():
    flagged = "import time\nx = time.time()\n"
    same_line = "import time\nx = time.time()  # lint: ok(determinism)\n"
    line_above = (
        "import time\n# lint: ok(determinism)\nx = time.time()\n"
    )
    wildcard = "import time\nx = time.time()  # lint: ok(*)\n"
    other_rule = "import time\nx = time.time()  # lint: ok(layering)\n"
    rel = "protocols/fixture.py"
    assert len(_lint(flagged, rel, select="determinism")) == 1
    assert _lint(same_line, rel, select="determinism") == []
    assert _lint(line_above, rel, select="determinism") == []
    assert _lint(wildcard, rel, select="determinism") == []
    assert len(_lint(other_rule, rel, select="determinism")) == 1


def test_baseline_round_trip(tmp_path):
    v1 = Violation("determinism", "protocols/a.py", 3, 0, "msg one")
    v2 = Violation("layering", "ops/b.py", 9, 4, "msg two")
    bl = Baseline.from_violations([v1, v2], "legacy, tracked in ROADMAP")
    path = tmp_path / "baseline.json"
    bl.save(str(path))
    loaded = Baseline.load(str(path))
    assert loaded.covers(v1) and loaded.covers(v2)
    # line/col excluded from identity: a moved violation stays covered
    moved = Violation("determinism", "protocols/a.py", 77, 8, "msg one")
    assert loaded.covers(moved)
    new, old = loaded.split([moved, Violation("x", "y.py", 1, 0, "fresh")])
    assert [v.message for v in new] == ["fresh"]
    assert [v.message for v in old] == ["msg one"]


def test_baseline_rejects_missing_justification(tmp_path):
    path = tmp_path / "baseline.json"
    path.write_text(
        json.dumps(
            {
                "version": 1,
                "entries": [
                    {"rule": "r", "path": "p.py", "message": "m", "justification": ""}
                ],
            }
        )
    )
    with pytest.raises(ValueError, match="justification"):
        Baseline.load(str(path))


# ---------------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------------


def _write_pkg_file(tmp_path, rel, source):
    """Materialize a fixture under a fake hbbft_tpu/ package root so
    the CLI's path → package-relative mapping applies the scoped rules."""
    f = tmp_path / "hbbft_tpu" / rel
    f.parent.mkdir(parents=True, exist_ok=True)
    f.write_text(textwrap.dedent(source))
    return f


def test_cli_exit_codes_and_json(tmp_path, capsys):
    dirty = _write_pkg_file(
        tmp_path, "protocols/fixture.py", "import time\nx = time.time()\n"
    )
    rc = cli_main(["--json", "--no-baseline", str(dirty)])
    out = json.loads(capsys.readouterr().out)
    assert rc == 1
    assert out["ok"] is False
    assert out["counts"] == {"determinism": 1}
    assert out["violations"][0]["path"] == "protocols/fixture.py"

    clean = _write_pkg_file(tmp_path, "protocols/clean.py", "x = 1\n")
    assert cli_main(["--json", "--no-baseline", str(clean)]) == 0
    assert json.loads(capsys.readouterr().out)["ok"] is True


def test_cli_baseline_write_then_pass(tmp_path, capsys):
    dirty = _write_pkg_file(
        tmp_path, "protocols/fixture.py", "import time\nx = time.time()\n"
    )
    bl = tmp_path / "baseline.json"
    assert (
        cli_main(
            [
                "--write-baseline",
                "known legacy clock read",
                "--baseline",
                str(bl),
                str(dirty),
            ]
        )
        == 0
    )
    capsys.readouterr()
    # with the baseline: clean exit; without: violation again
    assert cli_main(["--baseline", str(bl), str(dirty)]) == 0
    assert "1 baselined" in capsys.readouterr().out
    assert cli_main(["--no-baseline", str(dirty)]) == 1
    capsys.readouterr()


def test_cli_select_unknown_rule_is_usage_error(tmp_path, capsys):
    f = _write_pkg_file(tmp_path, "core/x.py", "x = 1\n")
    assert cli_main(["--select", "nope", str(f)]) == 2
    capsys.readouterr()


def test_cli_sarif_format(tmp_path, capsys):
    dirty = _write_pkg_file(
        tmp_path, "protocols/fixture.py", "import time\nx = time.time()\n"
    )
    rc = cli_main(["--format", "sarif", "--no-baseline", str(dirty)])
    out = json.loads(capsys.readouterr().out)
    assert rc == 1
    assert out["version"] == "2.1.0"
    run = out["runs"][0]
    assert run["tool"]["driver"]["name"] == "badgerlint"
    assert {r["id"] for r in run["tool"]["driver"]["rules"]} == {
        r.name for r in RULES
    }
    (result,) = run["results"]
    assert result["ruleId"] == "determinism"
    loc = result["locations"][0]["physicalLocation"]
    assert loc["artifactLocation"]["uri"] == "protocols/fixture.py"
    assert loc["region"]["startLine"] == 2

    clean = _write_pkg_file(tmp_path, "protocols/clean.py", "x = 1\n")
    assert cli_main(["--format", "sarif", "--no-baseline", str(clean)]) == 0
    assert json.loads(capsys.readouterr().out)["runs"][0]["results"] == []


def test_cli_write_wire_manifest_and_stability_gate(tmp_path, capsys):
    src = """
        import dataclasses
        from ..core.serialize import wire

        @wire("Thing")
        @dataclasses.dataclass(frozen=True)
        class Thing:
            a: int
            b: bytes
    """
    f = _write_pkg_file(tmp_path, "protocols/things.py", src)
    manifest = tmp_path / "wire_manifest.json"
    assert (
        cli_main(
            ["--write-wire-manifest", "--manifest", str(manifest), str(f)]
        )
        == 0
    )
    capsys.readouterr()
    data = json.loads(manifest.read_text())
    assert data["types"]["Thing"] == {
        "module": "protocols/things.py",
        "kind": "dataclass",
        "fields": ["a", "b"],
    }

    # in sync → clean; reorder the dataclass fields → lint fails
    assert (
        cli_main(
            ["--no-baseline", "--manifest", str(manifest),
             "--select", "wire-stability", str(f)]
        )
        == 0
    )
    capsys.readouterr()
    _write_pkg_file(
        tmp_path,
        "protocols/things.py",
        """
        import dataclasses
        from ..core.serialize import wire

        @wire("Thing")
        @dataclasses.dataclass(frozen=True)
        class Thing:
            b: bytes
            a: int
        """,
    )
    assert (
        cli_main(
            ["--no-baseline", "--manifest", str(manifest),
             "--select", "wire-stability", str(f)]
        )
        == 1
    )
    assert "field order changed incompatibly" in capsys.readouterr().out


def test_cli_module_entry_point():
    """``python -m hbbft_tpu.analysis --list-rules`` works end to end."""
    proc = subprocess.run(
        [sys.executable, "-m", "hbbft_tpu.analysis", "--list-rules"],
        capture_output=True,
        text=True,
    )
    assert proc.returncode == 0
    for rule in RULES:
        assert rule.name in proc.stdout


# ---------------------------------------------------------------------------
# wire-taint
# ---------------------------------------------------------------------------


def _wt(source, relpath="protocols/taintfix.py"):
    return _lint(source, relpath, select="wire-taint")


def test_wire_taint_flags_dict_key_sink():
    out = _wt(
        """
        class Proto:
            def handle_message(self, sender_id, message):
                self.seen[message.epoch] = True
                return None
        """
    )
    assert _names(out) == ["wire-taint"]
    assert "container key" in out[0].message


def test_wire_taint_sender_param_is_not_tainted():
    out = _wt(
        """
        class Proto:
            def handle_message(self, sender_id, message):
                self.seen[sender_id] = True
                return None
        """
    )
    assert out == []


def test_wire_taint_handle_bval_is_not_a_root():
    # handle_bval receives already-validated values from within the
    # protocol — only handle_message/handle_part/handle_ack are entry
    # points
    out = _wt(
        """
        class Proto:
            def handle_bval(self, sender_id, value):
                self.votes[value] = True
                return None
        """
    )
    assert out == []


def test_wire_taint_handle_part_is_a_root():
    out = _wt(
        """
        class KeyGen:
            def handle_part(self, sender_idx, part):
                self.parts[part.idx] = part
                return None
        """
    )
    assert _names(out) == ["wire-taint"]


def test_wire_taint_isinstance_int_sanitizes_key():
    out = _wt(
        """
        class Proto:
            def handle_message(self, sender_id, message):
                epoch = message.epoch
                if not isinstance(epoch, int) or isinstance(epoch, bool):
                    return None
                self.seen[epoch] = True
                return None
        """
    )
    assert out == []


def test_wire_taint_ordering_compare_flags():
    out = _wt(
        """
        class Proto:
            def handle_message(self, sender_id, message):
                if message.epoch < self.epoch:
                    return None
                return None
        """
    )
    assert _names(out) == ["wire-taint"]
    assert "ordering comparison" in out[0].message


def test_wire_taint_ordering_after_isinstance_clean():
    out = _wt(
        """
        class Proto:
            def handle_message(self, sender_id, message):
                if not isinstance(message.epoch, int):
                    return None
                if message.epoch < self.epoch:
                    return None
                return None
        """
    )
    assert out == []


def test_wire_taint_membership_unguarded_flags():
    out = _wt(
        """
        class Proto:
            def handle_message(self, sender_id, message):
                if message.pid in self.instances:
                    return None
                return None
        """
    )
    assert _names(out) == ["wire-taint"]
    assert "membership-tested" in out[0].message


def test_wire_taint_membership_in_try_clean():
    out = _wt(
        """
        class Proto:
            def handle_message(self, sender_id, message):
                try:
                    if message.pid in self.instances:
                        return None
                except TypeError:
                    return None
                return None
        """
    )
    assert out == []


def test_wire_taint_validator_witness_sanitizes():
    # the common_subset pattern: branch on the boolean result of a
    # guarded membership probe, then key state with the probed value
    out = _wt(
        """
        class Proto:
            def handle_message(self, sender_id, message):
                try:
                    known = message.pid in self.instances
                except TypeError:
                    return None
                if not known:
                    return None
                self.instances[message.pid].deliver()
                return None
        """
    )
    assert out == []


def test_wire_taint_chained_get_key_flags():
    # `d.get(e, {}).get(k)` has no dotted name — the keyed sink must
    # still see the trailing .get()
    out = _wt(
        """
        class Proto:
            def handle_message(self, sender_id, message):
                return self.cts.get(0, {}).get(message.pid)
        """
    )
    assert _names(out) == ["wire-taint"]
    assert ".get() key" in out[0].message


def test_wire_taint_setdefault_key_flags():
    out = _wt(
        """
        class Proto:
            def handle_message(self, sender_id, message):
                self.queue.setdefault(message.epoch, []).append(sender_id)
                return None
        """
    )
    assert _names(out) == ["wire-taint"]
    assert ".setdefault() key" in out[0].message


def test_wire_taint_hash_sink_flags():
    out = _wt(
        """
        class Proto:
            def handle_message(self, sender_id, message):
                return hash(message.payload)
        """
    )
    assert _names(out) == ["wire-taint"]
    assert "hashed" in out[0].message


def test_wire_taint_to_bytes_sink_flags():
    out = _wt(
        """
        class Proto:
            def handle_message(self, sender_id, message):
                return message.length.to_bytes(4, "big")
        """
    )
    assert _names(out) == ["wire-taint"]
    assert ".to_bytes()" in out[0].message


def test_wire_taint_int_shaped_key_is_hashable():
    # int.from_bytes narrows to int-shaped taint: hashable and
    # comparable, so keying is fine (magnitude hazards are the alloc
    # sink's job)
    out = _wt(
        """
        class Proto:
            def handle_message(self, sender_id, message):
                n = int.from_bytes(message.raw, "big")
                return self.parts.get(n)
        """
    )
    assert out == []


def test_wire_taint_crypto_sink_flags():
    out = _wt(
        """
        class Proto:
            def handle_message(self, sender_id, message):
                return self.pk_set.combine_signatures(message.shares)
        """
    )
    assert _names(out) == ["wire-taint"]
    assert "crypto sink combine_signatures()" in out[0].message


def test_wire_taint_crypto_sink_guarded_clean():
    out = _wt(
        """
        class Proto:
            def handle_message(self, sender_id, message):
                try:
                    return self.pk_set.combine_signatures(message.shares)
                except Exception:
                    return None
        """
    )
    assert out == []


def test_wire_taint_rng_seed_flags():
    out = _wt(
        """
        import random

        class Proto:
            def handle_message(self, sender_id, message):
                self.rng = random.Random(message.seed)
                return None
        """
    )
    assert _names(out) == ["wire-taint"]
    assert "seeds an RNG" in out[0].message


def test_wire_taint_alloc_fires_even_inside_try(tmp_path):
    # resource exhaustion happens before any except clause runs, so
    # try/except earns no credit at alloc sinks
    out = _wt(
        """
        async def pump(reader):
            header = await reader.readexactly(4)
            n = int.from_bytes(header, "big")
            try:
                return bytearray(n)
            except MemoryError:
                return None
        """,
        relpath="transport/pumpfix.py",
    )
    assert _names(out) == ["wire-taint"]
    assert "size reaches bytearray()" in out[0].message


def test_wire_taint_bounds_check_clears_alloc():
    out = _wt(
        """
        async def pump(reader):
            header = await reader.readexactly(4)
            n = int.from_bytes(header, "big")
            if n > 65536:
                raise ValueError("oversized")
            return bytearray(n)
        """,
        relpath="transport/pumpfix.py",
    )
    assert out == []


def test_wire_taint_loads_result_tainted_in_harness():
    out = _wt(
        """
        from ..core.serialize import loads

        def replay(frame, table):
            msg = loads(frame)
            table[msg] = 1
            return msg
        """,
        relpath="harness/replayfix.py",
    )
    assert _names(out) == ["wire-taint"]
    assert "container key" in out[0].message


def test_wire_taint_socket_read_membership_flags():
    out = _wt(
        """
        async def accept(reader, registry):
            peer = await reader.readexactly(16)
            if peer in registry:
                return None
            return peer
        """,
        relpath="transport/acceptfix.py",
    )
    assert _names(out) == ["wire-taint"]
    assert "membership-tested" in out[0].message


def test_wire_taint_recursion_unguarded_flags():
    out = _wt(
        """
        from ..core.serialize import loads

        def walk(node):
            for child in node:
                walk(child)
            return node

        def pump(frame):
            return walk(loads(frame))
        """,
        relpath="harness/walkfix.py",
    )
    assert _names(out) == ["wire-taint"]
    assert "recursion on attacker-controlled input" in out[0].message


def test_wire_taint_recursion_depth_guard_clean():
    out = _wt(
        """
        from ..core.serialize import loads

        def walk(node, depth=0):
            if depth > 64:
                raise ValueError("too deep")
            for child in node:
                walk(child, depth + 1)
            return node

        def pump(frame):
            return walk(loads(frame))
        """,
        relpath="harness/walkfix.py",
    )
    assert out == []


def test_wire_taint_dispatch_outside_protocols_flags():
    out = _wt(
        """
        from ..core.serialize import loads

        def pump(algo, frame):
            msg = loads(frame)
            return algo.handle_message("peer", msg)
        """,
        relpath="transport/dispatchfix.py",
    )
    assert _names(out) == ["wire-taint"]
    assert "dispatched" in out[0].message


def test_wire_taint_dispatch_guarded_clean():
    out = _wt(
        """
        from ..core.serialize import loads

        def pump(algo, frame):
            msg = loads(frame)
            try:
                return algo.handle_message("peer", msg)
            except Exception:
                return None
        """,
        relpath="transport/dispatchfix.py",
    )
    assert out == []


def test_wire_taint_wire_class_methods_are_roots():
    # a @wire class's own fields are attacker data inside its methods
    out = _wt(
        """
        import dataclasses
        from ..core.serialize import wire

        @wire("FixProofX")
        @dataclasses.dataclass(frozen=True)
        class FixProofX:
            index: int

            def check(self, n):
                return 0 <= self.index < n
        """
    )
    assert _names(out) == ["wire-taint"]
    assert "ordering comparison" in out[0].message
    assert any("FixProofX" in note for _, _, note in out[0].flow)


def test_wire_taint_isinstance_wire_class_keeps_fields_tainted():
    # isinstance(message, WireCls) proves the *shape*, not the fields:
    # every manifest field is still attacker-chosen
    out = _wt(
        """
        import dataclasses
        from ..core.serialize import wire

        @wire("FixMsgX")
        @dataclasses.dataclass(frozen=True)
        class FixMsgX:
            epoch: int

        class Proto:
            def handle_message(self, sender_id, message):
                if not isinstance(message, FixMsgX):
                    return None
                self.queue[message.epoch] = 1
                return None
        """
    )
    assert _names(out) == ["wire-taint"]
    assert "container key" in out[0].message


def test_wire_taint_interprocedural_flow_through_helper():
    out = _wt(
        """
        class Proto:
            def handle_message(self, sender_id, message):
                return self._queue(message.epoch)

            def _queue(self, epoch):
                self.pending[epoch] = 1
                return None
        """
    )
    assert _names(out) == ["wire-taint"]
    v = out[0]
    # the finding lands in the helper but the flow starts at the
    # handler boundary
    assert "_queue" in v.message
    assert v.flow is not None and len(v.flow) >= 3
    assert "handle_message" in v.flow[0][2]
    assert "sink:" in v.flow[-1][2]


def test_wire_taint_flow_hops_name_real_lines():
    out = _wt(
        """
        class Proto:
            def handle_message(self, sender_id, message):
                self.seen[message.epoch] = True
                return None
        """
    )
    (v,) = out
    for path, line, note in v.flow:
        assert path == "protocols/taintfix.py"
        assert line > 0
        assert note


def test_wire_taint_suppression_comment():
    out = _wt(
        """
        class Proto:
            def handle_message(self, sender_id, message):
                self.seen[message.epoch] = True  # lint: ok(wire-taint)
                return None
        """
    )
    assert out == []


# ---------------------------------------------------------------------------
# wire-taint CLI surface: flow in --json / SARIF, --changed widening,
# --trace lint_run
# ---------------------------------------------------------------------------

_WT_CLI_FIXTURE = """
class Proto:
    def handle_message(self, sender_id, message):
        self.seen[message.epoch] = True
        return None
"""


def test_cli_json_carries_flow(tmp_path, capsys):
    f = _write_pkg_file(tmp_path, "protocols/taintfix.py", _WT_CLI_FIXTURE)
    rc = cli_main(
        ["--json", "--no-baseline", "--select", "wire-taint", str(f)]
    )
    out = json.loads(capsys.readouterr().out)
    assert rc == 1
    (v,) = out["violations"]
    assert v["rule"] == "wire-taint"
    assert isinstance(v["flow"], list) and len(v["flow"]) >= 2
    for hop in v["flow"]:
        assert set(hop) == {"path", "line", "note"}
    assert "handle_message" in v["flow"][0]["note"]


def test_cli_json_omits_flow_when_absent(tmp_path, capsys):
    f = _write_pkg_file(
        tmp_path, "protocols/fixture.py", "import time\nx = time.time()\n"
    )
    cli_main(["--json", "--no-baseline", "--select", "determinism", str(f)])
    out = json.loads(capsys.readouterr().out)
    assert all("flow" not in v for v in out["violations"])


def test_cli_sarif_code_flows(tmp_path, capsys):
    f = _write_pkg_file(tmp_path, "protocols/taintfix.py", _WT_CLI_FIXTURE)
    rc = cli_main(
        ["--format", "sarif", "--no-baseline", "--select", "wire-taint",
         str(f)]
    )
    sarif = json.loads(capsys.readouterr().out)
    assert rc == 1
    (result,) = sarif["runs"][0]["results"]
    (thread_flow,) = result["codeFlows"][0]["threadFlows"]
    locs = thread_flow["locations"]
    assert len(locs) >= 2
    for loc in locs:
        phys = loc["location"]["physicalLocation"]
        assert phys["artifactLocation"]["uri"] == "protocols/taintfix.py"
        assert loc["location"]["message"]["text"]


def test_changed_widening_covers_whole_project_domains():
    from hbbft_tpu.analysis.cli import _widening_rules

    # a protocols file is in the wire-taint (and wire-stability) domain
    widened = _widening_rules(
        ["/x/hbbft_tpu/protocols/agreement.py"], RULES
    )
    assert "wire-taint" in widened
    assert "wire-stability" in widened
    # an ops kernel is outside wire-taint's scope
    widened = _widening_rules(["/x/hbbft_tpu/ops/pallas_ec.py"], RULES)
    assert "wire-taint" not in widened
    # a file outside the package is in no rule's domain
    assert _widening_rules(["/x/tests/test_foo.py"], RULES) == []
    # only whole-project rules ever widen
    per_file = [r for r in RULES if not getattr(r, "whole_project", False)]
    assert _widening_rules(
        ["/x/hbbft_tpu/protocols/agreement.py"], per_file
    ) == []


def test_cli_trace_emits_lint_run_event(tmp_path, capsys):
    from hbbft_tpu.obs.schema import EVENTS

    assert "lint_run" in EVENTS

    f = _write_pkg_file(tmp_path, "protocols/taintfix.py", _WT_CLI_FIXTURE)
    trace = tmp_path / "trace.jsonl"
    rc = cli_main(
        ["--no-baseline", "--select", "wire-taint", "--trace", str(trace),
         str(f)]
    )
    capsys.readouterr()
    assert rc == 1
    events = [json.loads(l) for l in trace.read_text().splitlines()]
    (run,) = [e for e in events if e.get("ev") == "lint_run"]
    assert run["rules"] == 1
    assert run["violations"] == 1
    assert run["wall"] > 0
    assert run["counts"] == {"wire-taint": 1}
    assert run["changed"] is False


# ---------------------------------------------------------------------------
# async-blocking
# ---------------------------------------------------------------------------


def _ab(source, relpath="transport/fixfile.py"):
    return _lint(source, relpath, select="async-blocking")


def test_async_blocking_flags_direct_blocking_call():
    out = _ab(
        """
        import time

        async def pump():
            time.sleep(0.1)
        """
    )
    (v,) = out
    assert "pump()" in v.message
    assert "time.sleep" in v.message
    assert "stalls every socket" in v.message
    # the flow walks root coroutine → blocking sink
    assert v.flow is not None and len(v.flow) == 2
    assert "event loop" in v.flow[0][2]
    assert "blocking" in v.flow[-1][2]


def test_async_blocking_executor_hop_is_clean():
    # the sanctioned form: the offloaded callee is an *argument*, not a
    # call, so the chain is broken by construction
    out = _ab(
        """
        import asyncio
        import time

        async def pump(loop):
            await loop.run_in_executor(None, time.sleep, 0.1)
            await asyncio.to_thread(time.sleep, 0.1)
        """
    )
    assert out == []


def test_async_blocking_interprocedural_chain():
    out = _ab(
        """
        import os

        def flush(fd):
            os.fsync(fd)

        def persist(fd):
            flush(fd)

        async def run(fd):
            persist(fd)
        """
    )
    (v,) = out
    assert "os.fsync" in v.message
    assert "via flush()" in v.message
    notes = [note for _, _, note in v.flow]
    assert any("calls persist()" in n for n in notes)
    assert any("calls flush()" in n for n in notes)
    assert "blocking" in notes[-1]
    # the finding anchors at the call the chain leaves the root through
    assert v.line == v.flow[1][1]


def test_async_blocking_dynamic_seam_bridges_unresolvable_call():
    # `self.algo.handle_message(...)` cannot be resolved statically; the
    # seam table bridges it to every `handle_message` in the index
    out = _ab(
        """
        import os

        class Algo:
            def handle_message(self, sender, msg):
                os.fsync(3)

        class Node:
            async def pump(self):
                step = self.algo.handle_message(1, 2)
        """
    )
    (v,) = out
    assert "pump()" in v.message
    assert "os.fsync" in v.message
    assert "handle_message" in v.message


def test_async_blocking_roots_only_in_serving_planes():
    # a blocking coroutine in protocols/ is not a *root*; it only
    # matters if a serving-plane coroutine reaches it
    out = _lint(
        """
        import time

        async def helper():
            time.sleep(0.1)
        """,
        "protocols/fixfile.py",
        select="async-blocking",
    )
    assert out == []


def test_async_blocking_suppression_at_anchor():
    out = _ab(
        """
        import time

        async def pump():
            time.sleep(0.1)  # lint: ok(async-blocking)
        """
    )
    assert out == []


def test_async_blocking_baseline_identity_ignores_flow_and_line():
    src = """
        import time

        async def pump():
            time.sleep(0.1)
    """
    (v,) = _ab(src)
    bl = Baseline.from_violations([v], "legacy stall, tracked")
    # same chain shifted down a line: line and flow move, the
    # (rule, path, message) identity — and so baseline coverage — holds
    (v2,) = _ab("\n" + src)
    assert v2.line != v.line or v2.flow != v.flow
    assert bl.covers(v2)


# ---------------------------------------------------------------------------
# task-leak
# ---------------------------------------------------------------------------


def _tl(source, relpath="serve/fixfile.py"):
    return _lint(source, relpath, select="task-leak")


def test_task_leak_flags_fire_and_forget():
    out = _tl(
        """
        import asyncio

        async def serve(conn):
            asyncio.create_task(handle(conn))
        """
    )
    (v,) = out
    assert "fire-and-forget create_task()" in v.message
    assert "serve()" in v.message


def test_task_leak_flags_local_assigned_never_read():
    out = _tl(
        """
        import asyncio

        async def serve(conn):
            t = asyncio.ensure_future(handle(conn))
            await drain(conn)
        """
    )
    (v,) = out
    assert "assigned to 't'" in v.message
    assert "never read again" in v.message


def test_task_leak_flags_self_attr_never_read():
    out = _tl(
        """
        import asyncio

        class Node:
            def start(self):
                self._pump = asyncio.create_task(self.pump())
        """
    )
    (v,) = out
    assert "self._pump" in v.message
    assert "Node" in v.message


def test_task_leak_clean_when_retained_and_settled():
    out = _tl(
        """
        import asyncio

        class Node:
            def start(self):
                self._pump = asyncio.create_task(self.pump())

            async def close(self):
                self._pump.cancel()

        async def once():
            t = asyncio.create_task(work())
            await t

        async def grouped(conns):
            # nested in a wider expression: the reference is retained
            # by construction
            await asyncio.gather(*[asyncio.create_task(h(c)) for c in conns])
        """
    )
    assert out == []


# ---------------------------------------------------------------------------
# await-holding-lock
# ---------------------------------------------------------------------------


def _ahl(source, relpath="transport/fixfile.py"):
    return _lint(source, relpath, select="await-holding-lock")


def test_await_holding_lock_flags_await_under_threading_lock():
    out = _ahl(
        """
        class Node:
            async def flush(self):
                with self._lock:
                    await self._drain()
        """
    )
    (v,) = out
    assert "await while holding threading lock 'self._lock'" in v.message
    assert "flush()" in v.message


def test_await_holding_lock_flags_blocking_under_asyncio_lock():
    out = _ahl(
        """
        import os

        class Node:
            async def flush(self):
                async with self._algo_lock:
                    os.fsync(self.fd)
        """
    )
    (v,) = out
    assert "blocking os.fsync" in v.message
    assert "asyncio lock 'self._algo_lock'" in v.message


def test_await_holding_lock_executor_hop_under_asyncio_lock_is_clean():
    # the sanctioned form the serving planes use: hold the asyncio lock
    # across the run_in_executor hop — the loop keeps running
    out = _ahl(
        """
        class Node:
            async def flush(self, loop):
                async with self._algo_lock:
                    step = await loop.run_in_executor(None, self._sync_flush)
        """
    )
    assert out == []


def test_await_holding_lock_ignores_non_lock_contexts():
    out = _ahl(
        """
        class Node:
            async def flush(self):
                with self._session:
                    await self._drain()
        """
    )
    assert out == []


# ---------------------------------------------------------------------------
# cancellation-safety
# ---------------------------------------------------------------------------


def _cs(source, relpath="transport/fixfile.py"):
    return _lint(source, relpath, select="cancellation-safety")


def test_cancellation_safety_flags_bare_except_around_await():
    out = _cs(
        """
        class Node:
            async def pump(self):
                try:
                    await self._inbox.get()
                except:
                    pass
        """
    )
    (v,) = out
    assert "bare except" in v.message
    assert "swallows" in v.message


def test_cancellation_safety_flags_base_exception_and_explicit_catch():
    out = _cs(
        """
        import asyncio

        async def pump(q):
            try:
                await q.get()
            except BaseException:
                log()

        async def drain(q):
            try:
                await q.get()
            except asyncio.CancelledError:
                log()
        """
    )
    assert len(out) == 2
    msgs = "\n".join(v.message for v in out)
    assert "BaseException" in msgs
    assert "CancelledError" in msgs


def test_cancellation_safety_allows_exception_and_reraise():
    # CancelledError derives from BaseException since py3.8, so plain
    # `except Exception` does not swallow it; an explicit catch with a
    # bare `raise` propagates
    out = _cs(
        """
        import asyncio

        async def pump(q):
            try:
                await q.get()
            except Exception:
                log()

        async def drain(q):
            try:
                await q.get()
            except asyncio.CancelledError:
                cleanup()
                raise
        """
    )
    assert out == []


def test_cancellation_safety_sync_try_body_not_flagged():
    # a body that never awaits cannot observe cancellation
    out = _cs(
        """
        async def pump(q):
            try:
                q.get_nowait()
            except:
                pass
        """
    )
    assert out == []


def test_cancellation_safety_flags_unshielded_await_in_finally():
    out = _cs(
        """
        async def serve(writer):
            try:
                await handle(writer)
            finally:
                await writer.wait_closed()
        """
    )
    (v,) = out
    assert "un-shielded await in a finally block" in v.message
    assert "serve()" in v.message


def test_cancellation_safety_shielded_finally_is_clean():
    out = _cs(
        """
        import asyncio

        async def serve(writer):
            try:
                await handle(writer)
            finally:
                await asyncio.shield(writer.wait_closed())
        """
    )
    assert out == []


# ---------------------------------------------------------------------------
# async rules on the CLI surface: --changed widening, lint_run counts
# ---------------------------------------------------------------------------


def test_changed_widening_includes_async_blocking_everywhere():
    from hbbft_tpu.analysis.cli import _widening_rules

    # async-blocking's scope is empty on purpose — the call graph spans
    # the package, so any package edit widens it (the blocking bodies
    # live in recover/ and crypto/, far from the coroutine roots)
    widened = _widening_rules(["/x/hbbft_tpu/ops/pallas_ec.py"], RULES)
    assert "async-blocking" in widened
    widened = _widening_rules(["/x/hbbft_tpu/transport/tcp.py"], RULES)
    assert "async-blocking" in widened
    # but not for files outside the package
    assert "async-blocking" not in _widening_rules(
        ["/x/tests/test_foo.py"], RULES
    )


def test_cli_trace_counts_async_rules(tmp_path, capsys):
    f = _write_pkg_file(
        tmp_path,
        "transport/fixfile.py",
        "import time\n\n\nasync def pump():\n    time.sleep(0.1)\n",
    )
    trace = tmp_path / "trace.jsonl"
    rc = cli_main(
        ["--no-baseline", "--select", "async-blocking", "--trace",
         str(trace), str(f)]
    )
    capsys.readouterr()
    assert rc == 1
    events = [json.loads(l) for l in trace.read_text().splitlines()]
    (run,) = [e for e in events if e.get("ev") == "lint_run"]
    assert run["counts"] == {"async-blocking": 1}


def test_async_rules_registered():
    names = {r.name for r in RULES}
    assert {
        "async-blocking",
        "task-leak",
        "await-holding-lock",
        "cancellation-safety",
    } <= names
    assert len(RULES) == 20


# ---------------------------------------------------------------------------
# bounded-state
# ---------------------------------------------------------------------------


def test_bounded_state_flags_unbounded_wire_growth():
    src = """
        class Algo:
            def __init__(self):
                self.queue = []
                self.table = {}

            def handle_message(self, sender_id, msg):
                self.queue.append(msg)
                self.table[msg.epoch] = msg
    """
    vs = _lint(src, "protocols/fixture.py", select="bounded-state")
    assert len(vs) == 2
    assert {v.line for v in vs} == {8, 9}
    assert "remotely drivable unbounded growth" in vs[0].message


def test_bounded_state_witnesses_are_clean():
    # one class per witness form: eviction, len() bound, validator-set
    # key, swap-drain re-assignment, and a set-add of a node identity
    src = """
        class Evicts:
            def handle_message(self, sender_id, msg):
                self.table[msg.epoch] = msg
                self.table.pop(msg.epoch - 2, None)

        class Bounds:
            def handle_message(self, sender_id, msg):
                if len(self.queue) < 64:
                    self.queue.append(msg)

        class IdKeyed:
            def handle_message(self, sender_id, msg):
                self.shares[sender_id] = msg
                self.parts[msg.sender_idx] = msg

        class SwapDrains:
            def handle_message(self, sender_id, msg):
                self.queue.append(msg)

            def _advance(self):
                drained, self.queue = self.queue, []
                return drained

        class SetAddsId:
            def handle_message(self, sender_id, msg):
                self.votes[msg.value].add(sender_id)
    """
    assert _lint(src, "protocols/fixture.py", select="bounded-state") == []


def test_bounded_state_scope_wire_fed_classes_only():
    # no handle_* entry point in protocols/ -> not wire-fed, not flagged
    src = """
        class Helper:
            def note(self, msg):
                self.log.append(msg)
    """
    assert _lint(src, "protocols/fixture.py", select="bounded-state") == []
    # the same class in transport/ IS wire-fed by definition
    vs = _lint(src, "transport/fixture.py", select="bounded-state")
    assert len(vs) == 1
    assert "Helper.log" in vs[0].message
    # and harness/ is out of scope entirely
    assert _lint(src, "harness/fixture.py", select="bounded-state") == []


def test_bounded_state_suppression():
    src = """
        class Algo:
            def handle_message(self, sender_id, msg):
                # capped by the protocol's batch bound, not visible
                # to the AST  # lint: ok(bounded-state)
                self.queue.append(msg)
    """
    assert _lint(src, "protocols/fixture.py", select="bounded-state") == []
