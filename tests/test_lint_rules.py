"""Per-rule fixture tests for badgerlint (``hbbft_tpu/analysis/``).

Each rule is demonstrated by a minimal source fixture that trips it
under a pretend package-relative path, plus a near-identical clean
variant that does not — so a rule that silently stops firing (or
starts over-firing) fails here, not in a production trace.  The
suppression comment, the baseline round-trip, and the CLI surface are
exercised the same way.
"""

import json
import subprocess
import sys
import textwrap

import pytest

from hbbft_tpu.analysis import (
    Baseline,
    Violation,
    all_rules,
    lint_source,
)
from hbbft_tpu.analysis.cli import main as cli_main

RULES = all_rules()


def _lint(source, relpath, select=None):
    rules = RULES
    if select is not None:
        rules = [r for r in RULES if r.name == select]
        assert rules, f"no such rule: {select}"
    return lint_source(textwrap.dedent(source), relpath, rules)


def _names(violations):
    return sorted({v.rule for v in violations})


# ---------------------------------------------------------------------------
# determinism
# ---------------------------------------------------------------------------


def test_determinism_flags_unseeded_rng_and_clocks():
    src = """
        import random, time, os, uuid

        class Algo:
            def __init__(self):
                self.rng = random.Random()

            def handle_message(self, sender, msg):
                now = time.time()
                tag = uuid.uuid4()
                noise = os.urandom(8)
                key = id(msg)
                return now, tag, noise, key
    """
    vs = _lint(src, "protocols/fixture.py", select="determinism")
    msgs = "\n".join(v.message for v in vs)
    assert len(vs) == 5
    assert "unseeded random.Random()" in msgs
    assert "time.time" in msgs
    assert "uuid.uuid4" in msgs
    assert "os.urandom" in msgs
    assert "id() is address-derived" in msgs


def test_determinism_allows_seeded_and_injected_rng():
    src = """
        import random

        class Algo:
            def __init__(self, netinfo, rng=None):
                self.rng = rng or netinfo.default_rng("algo")
                self.aux = random.Random(42)
    """
    assert _lint(src, "protocols/fixture.py", select="determinism") == []


def test_determinism_flags_global_random_helpers():
    src = """
        import random

        def pick(xs):
            return random.choice(xs)
    """
    vs = _lint(src, "core/fixture.py", select="determinism")
    assert len(vs) == 1
    assert "ambient-seeded global RNG" in vs[0].message


def test_determinism_scope_excludes_harness():
    src = "import time\nx = time.time()\n"
    assert _lint(src, "harness/fixture.py", select="determinism") == []


# ---------------------------------------------------------------------------
# ordered-iter
# ---------------------------------------------------------------------------


def test_ordered_iter_flags_bare_set_iteration():
    src = """
        class Algo:
            def __init__(self):
                self.pending = set()

            def flush(self, step):
                for nid in self.pending:
                    step.send_to(nid, "x")
    """
    vs = _lint(src, "protocols/fixture.py", select="ordered-iter")
    assert len(vs) == 1
    assert "set-typed 'self.pending'" in vs[0].message
    assert "emitting path" in vs[0].message


def test_ordered_iter_sorted_wrapper_is_clean():
    src = """
        class Algo:
            def __init__(self):
                self.pending = set()

            def flush(self, step):
                for nid in sorted(self.pending):
                    step.send_to(nid, "x")
    """
    assert _lint(src, "protocols/fixture.py", select="ordered-iter") == []


def test_ordered_iter_dict_keys_only_on_emitting_paths():
    src = """
        def tally(counts):
            return [counts[k] for k in counts.keys()]

        def emit(counts, step):
            for k in counts.keys():
                step.send_all(k)
    """
    vs = _lint(src, "protocols/fixture.py", select="ordered-iter")
    assert len(vs) == 1
    assert "dict.keys()" in vs[0].message
    assert vs[0].line > 4  # the emitting function, not the tally


def test_ordered_iter_scope_excludes_ops():
    src = "def f(s: set):\n    return [x for x in s]\n"
    assert _lint(src, "ops/fixture.py", select="ordered-iter") == []


# ---------------------------------------------------------------------------
# device-sync
# ---------------------------------------------------------------------------


def test_device_sync_flags_sync_inside_decorated_jit():
    src = """
        import jax
        import numpy as np

        @jax.jit
        def kernel(x):
            n = int(x)
            h = np.asarray(x)
            return x.sum().item() + n + h
    """
    vs = _lint(src, "ops/fixture.py", select="device-sync")
    msgs = "\n".join(v.message for v in vs)
    assert len(vs) == 3
    assert ".item() forces a device sync" in msgs
    assert "np.asarray materializes" in msgs
    assert "int() on a (possibly traced) value" in msgs


def test_device_sync_finds_jit_wrap_sites():
    src = """
        import jax

        def kernel(x):
            return float(x)

        kernel_j = jax.jit(kernel)
    """
    vs = _lint(src, "harness/fixture.py", select="device-sync")
    assert len(vs) == 1
    assert "float()" in vs[0].message


def test_device_sync_allows_shape_arithmetic_and_plain_functions():
    src = """
        import jax

        @jax.jit
        def kernel(x):
            n = int(x.shape[0])
            m = float(len(x.shape))
            return x * n * m

        def host_helper(x):
            return int(x)  # not a jit region
    """
    assert _lint(src, "ops/fixture.py", select="device-sync") == []


# ---------------------------------------------------------------------------
# dtype-width
# ---------------------------------------------------------------------------


def test_dtype_width_requires_preferred_element_type():
    src = """
        import jax.numpy as jnp
        from jax import lax

        def mul(a, b):
            good = lax.dot_general(
                a, b, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.int32,
            )
            bad = lax.dot_general(a, b, (((1,), (0,)), ((), ())))
            worse = jnp.einsum("ij,jk->ik", a, b)
            return good, bad, worse
    """
    vs = _lint(src, "ops/limbs.py", select="dtype-width")
    assert len(vs) == 2
    assert all("preferred_element_type" in v.message for v in vs)


def test_dtype_width_flags_narrow_product_and_overflowing_constant():
    src = """
        import jax.numpy as jnp
        import numpy as np

        def f(a, b):
            wraps = a.astype(jnp.uint8) * b.astype(jnp.uint8)
            mask = np.int8(300)
            ok = jnp.array(255, dtype=jnp.uint8)
            neg = jnp.array(-128, dtype=jnp.int8)
            return wraps, mask, ok, neg
    """
    vs = _lint(src, "ops/fr_jax.py", select="dtype-width")
    msgs = "\n".join(v.message for v in vs)
    assert len(vs) == 2
    assert "uint8×uint8 narrow casts" in msgs
    assert "constant 300 does not fit declared dtype int8" in msgs


def test_dtype_width_scope_is_limb_modules_only():
    src = "import jax.numpy as jnp\nx = jnp.einsum('ij,jk->ik', 1, 2)\n"
    assert _lint(src, "harness/fixture.py", select="dtype-width") == []


# ---------------------------------------------------------------------------
# layering
# ---------------------------------------------------------------------------


def test_layering_flags_upward_imports():
    src = """
        from ..harness import batching
        from hbbft_tpu.transport import tcp
    """
    vs = _lint(src, "ops/fixture.py", select="layering")
    assert len(vs) == 2
    assert "must not import layer 'harness'" in vs[0].message
    assert "must not import layer 'transport'" in vs[1].message


def test_layering_resolves_relative_imports():
    src = """
        from ..core.step import Step
        from ..crypto import threshold
        from . import agreement
    """
    # legal from protocols/ (core + crypto + self are all allowed)
    assert _lint(src, "protocols/fixture.py", select="layering") == []
    # the SAME source under obs/ trips twice: obs imports nothing
    vs = _lint(src, "obs/fixture.py", select="layering")
    assert len(vs) == 2
    assert all("must not import layer" in v.message for v in vs)


def test_layering_root_package_from_import_uses_alias_names():
    src = "from .. import harness\n"
    vs = _lint(src, "protocols/fixture.py", select="layering")
    assert len(vs) == 1
    assert "'harness'" in vs[0].message


def test_layering_external_imports_unconstrained():
    src = "import numpy\nfrom typing import Any\n"
    assert _lint(src, "obs/fixture.py", select="layering") == []


# ---------------------------------------------------------------------------
# obs-schema
# ---------------------------------------------------------------------------


def test_obs_schema_flags_unknown_event_and_fields():
    src = """
        def f(rec):
            rec.event("no_such_event", x=1)
            rec.event("epoch_start", epoch=1, vt=0.5, bogus=2)
            rec.event("epoch_start", epoch=1)  # vt missing
    """
    vs = _lint(src, "harness/fixture.py", select="obs-schema")
    msgs = "\n".join(v.message for v in vs)
    assert len(vs) == 3
    assert "unknown event type 'no_such_event'" in msgs
    assert "field 'bogus' is not in the schema" in msgs
    assert "missing required field(s) vt" in msgs


def test_obs_schema_accepts_valid_and_open_events():
    src = """
        def f(rec, extra):
            rec.event("epoch_start", epoch=1, vt=0.5)
            rec.event("span", name="x", dur=0.1, depth=0, anything="goes")
            rec.event("flush", queued=1, shipped=1, real=1, inline=0, dur=0.2)
            rec.event("epoch", **extra)  # splat: named subset only
    """
    assert _lint(src, "harness/fixture.py", select="obs-schema") == []


# ---------------------------------------------------------------------------
# suppression + baseline
# ---------------------------------------------------------------------------


def test_suppression_comment_silences_one_rule():
    flagged = "import time\nx = time.time()\n"
    same_line = "import time\nx = time.time()  # lint: ok(determinism)\n"
    line_above = (
        "import time\n# lint: ok(determinism)\nx = time.time()\n"
    )
    wildcard = "import time\nx = time.time()  # lint: ok(*)\n"
    other_rule = "import time\nx = time.time()  # lint: ok(layering)\n"
    rel = "protocols/fixture.py"
    assert len(_lint(flagged, rel, select="determinism")) == 1
    assert _lint(same_line, rel, select="determinism") == []
    assert _lint(line_above, rel, select="determinism") == []
    assert _lint(wildcard, rel, select="determinism") == []
    assert len(_lint(other_rule, rel, select="determinism")) == 1


def test_baseline_round_trip(tmp_path):
    v1 = Violation("determinism", "protocols/a.py", 3, 0, "msg one")
    v2 = Violation("layering", "ops/b.py", 9, 4, "msg two")
    bl = Baseline.from_violations([v1, v2], "legacy, tracked in ROADMAP")
    path = tmp_path / "baseline.json"
    bl.save(str(path))
    loaded = Baseline.load(str(path))
    assert loaded.covers(v1) and loaded.covers(v2)
    # line/col excluded from identity: a moved violation stays covered
    moved = Violation("determinism", "protocols/a.py", 77, 8, "msg one")
    assert loaded.covers(moved)
    new, old = loaded.split([moved, Violation("x", "y.py", 1, 0, "fresh")])
    assert [v.message for v in new] == ["fresh"]
    assert [v.message for v in old] == ["msg one"]


def test_baseline_rejects_missing_justification(tmp_path):
    path = tmp_path / "baseline.json"
    path.write_text(
        json.dumps(
            {
                "version": 1,
                "entries": [
                    {"rule": "r", "path": "p.py", "message": "m", "justification": ""}
                ],
            }
        )
    )
    with pytest.raises(ValueError, match="justification"):
        Baseline.load(str(path))


# ---------------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------------


def _write_pkg_file(tmp_path, rel, source):
    """Materialize a fixture under a fake hbbft_tpu/ package root so
    the CLI's path → package-relative mapping applies the scoped rules."""
    f = tmp_path / "hbbft_tpu" / rel
    f.parent.mkdir(parents=True, exist_ok=True)
    f.write_text(textwrap.dedent(source))
    return f


def test_cli_exit_codes_and_json(tmp_path, capsys):
    dirty = _write_pkg_file(
        tmp_path, "protocols/fixture.py", "import time\nx = time.time()\n"
    )
    rc = cli_main(["--json", "--no-baseline", str(dirty)])
    out = json.loads(capsys.readouterr().out)
    assert rc == 1
    assert out["ok"] is False
    assert out["counts"] == {"determinism": 1}
    assert out["violations"][0]["path"] == "protocols/fixture.py"

    clean = _write_pkg_file(tmp_path, "protocols/clean.py", "x = 1\n")
    assert cli_main(["--json", "--no-baseline", str(clean)]) == 0
    assert json.loads(capsys.readouterr().out)["ok"] is True


def test_cli_baseline_write_then_pass(tmp_path, capsys):
    dirty = _write_pkg_file(
        tmp_path, "protocols/fixture.py", "import time\nx = time.time()\n"
    )
    bl = tmp_path / "baseline.json"
    assert (
        cli_main(
            [
                "--write-baseline",
                "known legacy clock read",
                "--baseline",
                str(bl),
                str(dirty),
            ]
        )
        == 0
    )
    capsys.readouterr()
    # with the baseline: clean exit; without: violation again
    assert cli_main(["--baseline", str(bl), str(dirty)]) == 0
    assert "1 baselined" in capsys.readouterr().out
    assert cli_main(["--no-baseline", str(dirty)]) == 1
    capsys.readouterr()


def test_cli_select_unknown_rule_is_usage_error(tmp_path, capsys):
    f = _write_pkg_file(tmp_path, "core/x.py", "x = 1\n")
    assert cli_main(["--select", "nope", str(f)]) == 2
    capsys.readouterr()


def test_cli_module_entry_point():
    """``python -m hbbft_tpu.analysis --list-rules`` works end to end."""
    proc = subprocess.run(
        [sys.executable, "-m", "hbbft_tpu.analysis", "--list-rules"],
        capture_output=True,
        text=True,
    )
    assert proc.returncode == 0
    for rule in RULES:
        assert rule.name in proc.stdout
