"""Pallas G1 kernel tests (interpret mode on the CPU mesh).

Bit-identity contract: the Pallas scalar-mul must produce exactly the
same canonical points as the XLA kernel (``ec_jax``) and the host path
(``crypto/curve.py``) — same limb algebra, same complete formulas.
"""

import random

import numpy as np
import pytest

from hbbft_tpu.crypto.curve import G1, G1_GEN, g1_multi_exp
from hbbft_tpu.ops import ec_jax as EC
from hbbft_tpu.ops import limbs as LB
from hbbft_tpu.ops import pallas_ec as PE


@pytest.fixture(scope="module")
def points(rng=None):
    r = random.Random(0xA11)
    return [G1_GEN * r.randrange(1, 1 << 64) for _ in range(6)] + [
        G1.infinity()
    ]


def test_scalar_mul_matches_host(points):
    r = random.Random(0xA12)
    ks = [r.randrange(0, 1 << 64) for _ in points]
    pts = EC.g1_to_limbs(points)
    bits = LB.scalars_to_bits(ks, 64)
    out = np.asarray(PE.scalar_mul_pallas(pts, bits, interpret=True))
    for i, (p, k) in enumerate(zip(points, ks)):
        assert EC.g1_from_limbs(out[i]) == p * k


def test_scalar_mul_bit_identical_to_xla(points):
    """Not just the same group elements — the same limb vectors."""
    r = random.Random(0xA13)
    ks = [r.randrange(0, 1 << 48) for _ in points]
    pts = EC.g1_to_limbs(points)
    bits = LB.scalars_to_bits(ks, 48)
    out_pl = np.asarray(PE.scalar_mul_pallas(pts, bits, interpret=True))
    out_xla = np.asarray(
        EC.g1_kernel().scalar_mul(np.asarray(pts), np.asarray(bits))
    )
    assert (out_pl == out_xla).all()


def test_msm_matches_host(points):
    # 96-bit scalars: full-width (255-bit) interpret-mode compiles take
    # tens of minutes on CPU XLA; full-width correctness is verified on
    # real TPU hardware (BASELINE.md) and the windowed digit path is
    # width-agnostic.
    r = random.Random(0xA14)
    ks = [r.randrange(1, 1 << 96) for _ in points]
    got = PE.g1_msm_pallas(points, ks, nbits=96)
    assert got == g1_multi_exp(points, ks)


def test_windowed_matches_host(points):
    """The 4-bit fixed-window kernel: canonically equal to the host
    path for every point including the identity."""
    r = random.Random(0xA16)
    ks = [r.randrange(0, 1 << 64) for _ in points]
    pts = EC.g1_to_limbs(points)
    bits = LB.scalars_to_bits(ks, 64)
    out = np.asarray(PE.scalar_mul_windowed(pts, bits, interpret=True))
    for i, (p, k) in enumerate(zip(points, ks)):
        assert EC.g1_from_limbs(out[i]) == p * k


def test_bits_to_digits():
    r = random.Random(0xA17)
    ks = [r.randrange(0, 1 << 61) for _ in range(5)]  # 61 bits: short top window
    bits = LB.scalars_to_bits(ks, 61)
    digits = PE.bits_to_digits(bits)
    assert digits.shape == (5, 16)
    for k, row in zip(ks, digits):
        got = 0
        for d in row:
            got = (got << 4) | int(d)
        assert got == k % LB.R


def test_g2_windowed_matches_host():
    """The Fq2 windowed kernel: canonically equal to the host G2 path,
    including the identity."""
    from hbbft_tpu.crypto.curve import G2, G2_GEN, g2_multi_exp

    r = random.Random(0xA18)
    points = [G2_GEN * r.randrange(1, 1 << 64) for _ in range(4)] + [
        G2.infinity()
    ]
    # 16-bit scalars: the Fq2 kernel is ~3× the G1 program and the
    # interpret-mode compile scales with window count; 4 windows
    # exercise table build, doubling chain, and select completely.
    # (Full-width correctness is verified on real TPU hardware — see
    # BASELINE.md.)
    ks = [r.randrange(0, 1 << 16) for _ in points]
    pts = EC.g2_to_limbs(points)
    bits = LB.scalars_to_bits(ks, 16)
    out = np.asarray(PE.scalar_mul_windowed_g2(pts, bits, interpret=True))
    for i, (p, k) in enumerate(zip(points, ks)):
        assert EC.g2_from_limbs(out[i]) == p * k
    # full MSM through the same path
    got = PE.g2_msm_pallas(points, ks, nbits=16, interpret=True)
    assert got == g2_multi_exp(points, ks)


def test_padding_beyond_tile():
    """K not a multiple of the 128-lane tile pads with identities."""
    r = random.Random(0xA15)
    points = [G1_GEN * r.randrange(1, 1 << 32) for _ in range(3)]
    ks = [r.randrange(1, 1 << 32) for _ in range(3)]
    pts = EC.g1_to_limbs(points)
    bits = LB.scalars_to_bits(ks, 32)
    out = np.asarray(PE.scalar_mul_pallas(pts, bits, interpret=True))
    assert out.shape[0] == 3
    for i in range(3):
        assert EC.g1_from_limbs(out[i]) == points[i] * ks[i]
