"""limbprove (:mod:`hbbft_tpu.analysis.rangecheck`) + the exact-shadow
sanitizer (:mod:`hbbft_tpu.analysis.rangeshadow`).

Three layers:

- per-primitive transfer functions — tiny lambdas traced to jaxprs,
  exact interval propagation asserted per primitive;
- the clean-tree gate — every registered crypto kernel proves every
  obligation, and the live obligations match the pinned
  ``range_manifest.json`` byte-for-byte (this is the same check the
  ``limb-range`` badgerlint rule runs tree-wide);
- the runtime dual — the shadow sanitizer catches a planted int32 wrap
  through the public ``wrap()`` seam and stays silent on the real
  device kernels.

``verify_all()`` is memoized per process, so the clean-tree gate and
the manifest gate pay the jaxpr tracing cost once between them (and
share it with the lint-clean tests when run in the same process).
"""

import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import hbbft_tpu
from hbbft_tpu.analysis import rangecheck as rc
from hbbft_tpu.analysis import rangeshadow as rsh
from hbbft_tpu.analysis.rules.dtype_width import LIMBPROVE_COVERED

PACKAGE_DIR = os.path.dirname(os.path.abspath(hbbft_tpu.__file__))


def _interp(fn, *specs):
    """Trace ``fn`` over symbolic args and abstract-interpret it."""
    closed = jax.make_jaxpr(fn)(
        *[jax.ShapeDtypeStruct(s.shape, np.dtype(s.dtype)) for s in specs]
    )
    an = rc.Analyzer("unit")
    outs = an.interpret(closed, [s.aval() for s in specs])
    return outs, an


def _iv(outs):
    iv = outs[0].iv
    assert iv is not None
    return (iv.lo, iv.hi)


# ---------------------------------------------------------------------------
# per-primitive transfer functions
# ---------------------------------------------------------------------------


class TestTransfer:
    def test_add(self):
        outs, _ = _interp(
            lambda x, y: x + y,
            rc.arg((4,), "int32", 0, 10),
            rc.arg((4,), "int32", -3, 5),
        )
        assert _iv(outs) == (-3, 15)

    def test_sub(self):
        outs, _ = _interp(
            lambda x, y: x - y,
            rc.arg((4,), "int32", 0, 10),
            rc.arg((4,), "int32", -3, 5),
        )
        assert _iv(outs) == (-5, 13)

    def test_mul_signed_corners(self):
        outs, _ = _interp(
            lambda x, y: x * y,
            rc.arg((4,), "int32", -4, 3),
            rc.arg((4,), "int32", 2, 5),
        )
        assert _iv(outs) == (-20, 15)

    def test_dot_general_accumulates_contraction(self):
        """u8×u8 over k=3: the peak is k·255², attributed to int32."""
        outs, an = _interp(
            lambda A, B: jax.lax.dot_general(
                A, B, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.int32,
            ),
            rc.arg((2, 3), "uint8", 0, 255),
            rc.arg((3, 2), "uint8", 0, 255),
        )
        assert _iv(outs) == (0, 3 * 255 * 255)
        peak, _eqn = an.peaks["int32"]
        assert peak == 3 * 255 * 255

    def test_shift_left(self):
        outs, _ = _interp(lambda x: x << 4, rc.arg((4,), "int32", 0, 7))
        assert _iv(outs) == (0, 112)

    def test_shift_right_logical(self):
        outs, _ = _interp(
            lambda x: jax.lax.shift_right_logical(x, jnp.int32(4)),
            rc.arg((4,), "int32", 0, 255),
        )
        assert _iv(outs) == (0, 15)

    def test_and_mask_bounds(self):
        outs, _ = _interp(
            lambda x: x & 0xFF, rc.arg((4,), "int32", 0, 100000)
        )
        assert _iv(outs) == (0, 255)

    def test_select_n_unions_branches(self):
        outs, _ = _interp(
            lambda c, x, y: jnp.where(c, x, y),
            rc.arg((4,), "bool", 0, 1),
            rc.arg((4,), "int32", 0, 10),
            rc.arg((4,), "int32", -7, 3),
        )
        assert _iv(outs) == (-7, 10)

    def test_concatenate_unions_pieces(self):
        outs, _ = _interp(
            lambda x, y: jnp.concatenate([x, y]),
            rc.arg((4,), "int32", 0, 10),
            rc.arg((4,), "int32", -7, 3),
        )
        assert _iv(outs) == (-7, 10)

    def test_rem_bounds_by_divisor(self):
        outs, _ = _interp(lambda x: x % 13, rc.arg((4,), "int32", 0, 1000))
        lo, hi = _iv(outs)
        assert lo == 0 and 12 <= hi <= 25  # sound; conservatively ≤ 2·|d|−1

    def test_convert_keeps_fitting_interval(self):
        outs, _ = _interp(
            lambda x: x.astype(jnp.uint8), rc.arg((4,), "int32", 0, 200)
        )
        assert _iv(outs) == (0, 200)

    def test_scan_clamped_carry_converges(self):
        """A masked carry reaches a tight fixpoint (no widening):
        out ≤ 15, intermediate peak = 15 + 3 before the mask."""

        def body(c, x):
            c = (c + x) & 0xF
            return c, c

        outs, an = _interp(
            lambda xs: jax.lax.scan(body, jnp.int32(0), xs)[1],
            rc.arg((5,), "int32", 0, 3),
        )
        assert _iv(outs) == (0, 15)
        peak, _eqn = an.peaks["int32"]
        assert peak == 18

    def test_scan_growing_carry_widens_soundly(self):
        """An unbounded carry widens to the dtype range — conservative,
        never unsound."""

        def body(c, x):
            return c + x, c

        outs, _ = _interp(
            lambda xs: jax.lax.scan(body, jnp.int32(0), xs)[0],
            rc.arg((5,), "int32", 0, 3),
        )
        lo, hi = _iv(outs)
        assert lo <= 0 and hi >= 15  # must contain the concrete range

    def test_const_gather_is_exact(self):
        """Indexing a known table propagates the exact element, not the
        table-wide bound."""
        tbl = rc.const_arg(np.arange(8, dtype=np.int32))
        outs, _ = _interp(
            lambda t, i: t[2] * i, tbl, rc.arg((), "int32", 0, 10)
        )
        assert _iv(outs) == (0, 20)


# ---------------------------------------------------------------------------
# clean-tree gate + manifest pin
# ---------------------------------------------------------------------------


def test_every_kernel_proves_every_obligation():
    result = rc.verify_all()
    bad = [o for o in result.obligations if not o.proved]
    assert not bad, "; ".join(
        f"{o.key}: peak {o.peak} vs capacity {o.capacity} "
        f"({o.message or 'bound exceeded'})"
        for o in bad
    )
    # Every registered module contributed at least one kernel report.
    assert {r.kernel.split(".")[0] for r in result.reports} >= {
        "limbs", "fr", "gf", "sha", "ec", "packed", "pallas",
    }


def test_manifest_matches_live_tree():
    manifest = rc.load_manifest()
    assert manifest is not None, "range_manifest.json missing"
    diffs = rc.diff_manifest(manifest, rc.verify_all())
    assert not diffs, "; ".join(msg for msg, _ob in diffs)


def test_manifest_file_is_sorted_and_stringly():
    """The pinned file stays diffable: sorted keys, decimal-string
    peaks (peaks exceed 2^53 — JSON numbers would lose digits)."""
    path = os.path.join(PACKAGE_DIR, "analysis", rc.MANIFEST_NAME)
    with open(path) as fh:
        manifest = json.load(fh)
    keys = [e["key"] for e in manifest["obligations"]]
    assert keys == sorted(keys)
    for e in manifest["obligations"]:
        assert isinstance(e["peak"], str) and e["peak"].isdigit()
        assert isinstance(e["capacity"], str)
        assert e["proved"] is True


def test_disk_cache_roundtrips_obligations(tmp_path, monkeypatch):
    """The source-hashed disk cache replays byte-identical obligations
    (peaks > 2^53 survive as decimal strings, sites and flows intact)
    and refuses a stale fingerprint."""
    result = rc.verify_all()
    monkeypatch.setattr(rc, "DISK_CACHE", str(tmp_path / "cache.json"))
    rc._disk_cache_store("fp-1", result.reports)
    replayed = rc._disk_cache_load("fp-1")
    assert replayed is not None
    live = {o.key: o for r in result.reports for o in r.obligations}
    back = {o.key: o for r in replayed for o in r.obligations}
    assert live.keys() == back.keys()
    for key, o in live.items():
        b = back[key]
        assert (o.peak, o.capacity, o.proved, o.site, o.flow) == (
            b.peak, b.capacity, b.proved, b.site, b.flow,
        ), key
    assert rc._disk_cache_load("fp-other") is None
    monkeypatch.setenv(rc.DISK_CACHE_ENV, "0")
    assert rc._disk_cache_load("fp-1") is None


def test_source_fingerprint_tracks_kernel_sources():
    fp = rc._source_fingerprint()
    assert fp == rc._source_fingerprint()  # deterministic
    assert len(fp) == 64


def test_dtype_width_deferral_matches_registry():
    """The lint-side LIMBPROVE_COVERED table must mirror the live
    ``covers`` declarations, or the dtype-width rule would exempt
    functions limbprove no longer proves."""
    live = {k: v for k, v in rc.covered_functions().items() if v}
    assert LIMBPROVE_COVERED == live


def test_baseline_carries_no_range_debt():
    """limb-range starts (and stays) baseline-free: pinned bounds are
    regenerated, never grandfathered."""
    path = os.path.join(PACKAGE_DIR, "analysis", "baseline.json")
    with open(path) as fh:
        baseline = json.load(fh)
    assert not [
        e
        for e in baseline["entries"]
        if e.get("rule") in ("limb-range", "dtype-width")
    ]


# ---------------------------------------------------------------------------
# shadow sanitizer: planted overflow + real kernels clean
# ---------------------------------------------------------------------------


def _square_shadow(args, out):
    """Exact oracle for the planted fixture: (x²)·65536 in Python ints."""
    x = np.asarray(args[0]).astype(object)
    want = (x * x) * 65536
    got = np.asarray(out).astype(object)
    return [
        ((int(i),), int(want[i]), int(got[i]))
        for i in range(x.shape[0])
        if want[i] != got[i]
    ]


def test_shadow_catches_planted_int32_wrap():
    @jax.jit
    def square_scaled(x):
        y = x.astype(jnp.int32)
        return (y * y) * 65536  # wraps for |x| ≥ 2^7.5·...; 70000² ≫ 2³¹

    wrapped = rsh.wrap("fixture.square", square_scaled, _square_shadow)
    x = np.array([3, 70000], dtype=np.int64)
    rsh.enable()
    try:
        wrapped(x)
    finally:
        reports = rsh.disable()
    assert len(reports) == 1
    rep = reports[0]
    assert rep.kernel == "fixture.square"
    assert rep.index == (1,)
    assert rep.expected != rep.actual
    assert rep.path.endswith("test_rangecheck.py")
    v = rep.as_violation()
    assert v.rule == "rangecheck"
    assert "fixture.square" in v.message


def test_shadow_oracle_error_is_reported_not_raised():
    """A crashing oracle must degrade to a <shadow-error> report, never
    take the product call down with it."""

    def bad_oracle(args, out):
        raise RuntimeError("oracle exploded")

    wrapped = rsh.wrap("fixture.bad", lambda x: x, bad_oracle)
    rsh.enable()
    try:
        wrapped(np.zeros(2, dtype=np.int32))
    finally:
        reports = rsh.disable()
    assert len(reports) == 1
    assert "<shadow-error>" in reports[0].message()
    assert "oracle exploded" in reports[0].message()


def test_shadow_clean_on_real_kernels(rng):
    """fr matmul/add, SHA-256, and GF(2⁸) RS encode run shadowed with
    zero divergence — the kernels really do stay inside their proved
    ranges."""
    from hbbft_tpu.ops import fr_jax, gf256_jax, sha256_jax

    rsh.enable()
    try:
        # fr matmul on random scalars
        vals = [rng.randrange(1 << 252) for _ in range(6)]
        a = fr_jax.fr_to_limbs(vals[:4]).reshape(2, 2, fr_jax.FR_LIMBS)
        b = fr_jax.fr_to_limbs(vals[2:]).reshape(2, 2, fr_jax.FR_LIMBS)
        np.asarray(fr_jax.fr_matmul_device(a, b))
        # sha256 on uniform-length messages
        msgs = [bytes(rng.randrange(256) for _ in range(55)) for _ in range(3)]
        np.asarray(sha256_jax.sha256_device(jnp.asarray(
            sha256_jax.pad_messages(msgs)
        )))
        # GF(2^8) Reed-Solomon encode
        dev = gf256_jax.ReedSolomonDevice(4, 2)
        data = [bytes(rng.randrange(256) for _ in range(32)) for _ in range(4)]
        dev.encode(data)
    finally:
        reports = rsh.disable()
    assert reports == [], "; ".join(r.message() for r in reports)
