"""Structured tracing + metrics for the hbbft_tpu stack — the fleet
telemetry plane.

The observability layer, per node:

- :mod:`hbbft_tpu.obs.recorder` — a near-zero-overhead recorder with
  span timers (context manager + decorator), counters and histograms.
  No-op by default: instrumented hot paths pay exactly one module
  attribute check (``recorder.ACTIVE is None``) when tracing is off.
  Schema v2 stamps every row with the cross-node trace context
  (``tn``/``ts``/``te``) when a node identity is set.
- :mod:`hbbft_tpu.obs.flight` — the bounded black box: a ring of the
  last K event rows, force-dumped (atomic, crash-safe) on faults,
  degrades and SIGTERM; persist mode survives SIGKILL.
- :mod:`hbbft_tpu.obs.metrics` — sans-IO Prometheus-style text
  exposition of the live counters/hists + the tiny asyncio endpoint.

And across the fleet:

- :mod:`hbbft_tpu.obs.fleet` — the poller scraping N exporters into
  one fleet JSONL.
- :mod:`hbbft_tpu.obs.report` — the single-summary CLI::

      python -m hbbft_tpu.obs.report n0.jsonl n1.jsonl

- :mod:`hbbft_tpu.obs.timeline` — the post-mortem: merges multi-node
  traces by trace context into a per-epoch commit timeline with
  admit→gossip→ACS→decrypt→ack hop walls and a declarative SLO/health
  pass::

      python -m hbbft_tpu.obs.timeline run/*.jsonl

Enable tracing programmatically::

    from hbbft_tpu import obs
    obs.enable("trace.jsonl", node="n0")
    ...   # run simulations / flushes / epochs
    obs.disable()

or pass ``--trace trace.jsonl`` to ``bench.py`` /
``examples/simulation.py``.  ``enable(..., jax_annotations=True)`` (or
``HBBFT_TPU_TRACE_JAX=1``) additionally wraps every span in a
``jax.profiler.TraceAnnotation`` so TPU profiles carry protocol-level
span names.
"""

from .recorder import (  # noqa: F401
    Recorder,
    SCHEMA_VERSION,
    active,
    disable,
    enable,
    span,
    traced,
)

__all__ = [
    "Recorder",
    "SCHEMA_VERSION",
    "active",
    "disable",
    "enable",
    "span",
    "traced",
]
