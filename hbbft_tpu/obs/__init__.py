"""Structured tracing + metrics for the hbbft_tpu stack.

The observability layer has three parts:

- :mod:`hbbft_tpu.obs.recorder` — a near-zero-overhead recorder with
  span timers (context manager + decorator), counters and histograms.
  No-op by default: instrumented hot paths pay exactly one module
  attribute check (``recorder.ACTIVE is None``) when tracing is off.
- Structured JSONL trace export with a stable event schema (epoch
  start/decide, message send/deliver, crypto flush spans with batch
  occupancy, fault telemetry, device-op routing decisions).
- :mod:`hbbft_tpu.obs.report` — the trace summarizer CLI::

      python -m hbbft_tpu.obs.report trace.jsonl

Enable tracing programmatically::

    from hbbft_tpu import obs
    obs.enable("trace.jsonl")
    ...   # run simulations / flushes / epochs
    obs.disable()

or pass ``--trace trace.jsonl`` to ``bench.py`` /
``examples/simulation.py``.  ``enable(..., jax_annotations=True)`` (or
``HBBFT_TPU_TRACE_JAX=1``) additionally wraps every span in a
``jax.profiler.TraceAnnotation`` so TPU profiles carry protocol-level
span names.
"""

from .recorder import (  # noqa: F401
    Recorder,
    SCHEMA_VERSION,
    active,
    disable,
    enable,
    span,
    traced,
)

__all__ = [
    "Recorder",
    "SCHEMA_VERSION",
    "active",
    "disable",
    "enable",
    "span",
    "traced",
]
