"""Post-mortem timeline — merge multi-node traces into one causally
ordered per-epoch commit timeline, then run the SLO/health rules.

    python -m hbbft_tpu.obs.timeline n0.jsonl n1.jsonl flight-n2.jsonl
    python -m hbbft_tpu.obs.timeline run/*.jsonl --json --rules slo.rules

Inputs are any mix of per-node recorder traces, flight-recorder dumps
and fleet scrape JSONLs (all the same schema-v2 row format).  Each
file's rows are aligned onto one wall clock via its ``trace_start``
``wall_unix`` anchor, then joined three ways:

- **wire joins** — a ``wire_send`` on node A (``node``, ``peer``,
  ``seq``) joins the matching ``wire_recv`` on node B; the join
  fraction is a health signal (un-joinable contexts mean a node's
  trace is missing or its clock anchor is lying).
- **tx chains** — ``gateway_admit`` (client, seq) → committed epoch
  (``client_commit_latency``) → ``node_commit`` rows for that epoch:
  a *complete* chain shows the tx entering the gateway, the fleet
  committing its epoch, and the ack leaving — the admit→ack arc.
- **per-epoch hops** — admit → gossip (``gossip_relay``) → ACS
  (``acs_done``) → decrypt/commit (``node_commit``) → ack walls, one
  line per epoch.  Under order-then-reveal (``ordered_commit`` rows
  present) the commit hop splits: ``acs_to_ordered_commit`` (the
  commit critical path — agreement + digest only) and
  ``ordered_commit_to_reveal`` (the off-path decryption lag).

Alert rules are declarative ``name selector op threshold`` tuples
(see :data:`DEFAULT_RULES`); selectors address merged counters
(``counter:wire.seq_gap``), event-field sums
(``event_sum:spec_combine:misses``), histogram summary stats
(``hist:gateway.commit_latency_s:p90``), and the derived chain/join
fractions (``chain:complete_frac``, ``join:frac``).  A selector whose
subject never appears in the traces *passes* (absent ≠ violated —
rules are forward declarations over future planes too).  Any violated
rule makes the CLI exit non-zero: CI runs this over scenario traces.
"""

from __future__ import annotations

import argparse
import json
import sys
from collections import defaultdict
from typing import Any, Dict, List, Optional, Tuple

from .report import _dist, load_events

#: ``(name, selector, op, threshold)`` — the built-in SLO/health pass.
#: ``reveal.lag_s`` is a forward declaration for the order-then-reveal
#: arc; it passes while the histogram doesn't exist.
DEFAULT_RULES: List[Tuple[str, str, str, float]] = [
    ("wire-seq-gap", "counter:wire.seq_gap", "<=", 0),
    ("wire-replay-evicted", "counter:wire.replay_evicted", "<=", 0),
    ("wire-bad-obtrace", "counter:wire.bad_obtrace", "<=", 0),
    ("wire-handler-errors", "counter:wire.handler_errors", "<=", 0),
    ("spec-combine-misses", "event_sum:spec_combine:misses", "<=", 0),
    ("gateway-rejects", "counter:gateway.rejected", "<=", 0),
    ("reveal-lag-p90", "hist:reveal.lag_s:p90", "<=", 1.0),
    ("reveal-lag-p99", "hist:reveal.lag_s:p99", "<=", 2.0),
    ("chain-complete", "chain:complete_frac", ">=", 0.99),
    ("trace-joins", "join:frac", ">=", 0.99),
]

_OPS = {
    "<=": lambda a, b: a <= b,
    ">=": lambda a, b: a >= b,
    "<": lambda a, b: a < b,
    ">": lambda a, b: a > b,
    "==": lambda a, b: a == b,
}


# ---------------------------------------------------------------------------
# merge + align
# ---------------------------------------------------------------------------


def merge(paths: List[str]) -> List[Dict[str, Any]]:
    """Load every file, stamp each row with ``_wall`` (its file's
    ``trace_start`` wall anchor + ``t``) and ``_src``, and return all
    rows sorted by wall time.

    Flight dumps have no ``trace_start`` row: the ring mirrors a live
    recorder, so its rows reuse the recorder's relative ``t`` but the
    dump itself carries no wall anchor.  A file without an anchor
    borrows the anchor of any anchored file holding the same
    ``(tn, ts)`` row — mixing raw and anchored clocks in one hop would
    otherwise put ~the unix epoch into a wall diff.  The same
    ``(tn, ts)`` identity dedupes mirrored copies, so a row present in
    both a node's trace and its flight dump is counted once.
    """
    files = []
    for path in paths:
        events = load_events(path)
        anchor: Any = None
        for e in events:
            if e.get("ev") == "trace_start" and "wall_unix" in e:
                anchor = float(e["wall_unix"])
                break
        files.append((path, events, anchor))
    anchored_keys: Dict[Any, float] = {}
    for path, events, anchor in files:
        if anchor is None:
            continue
        for e in events:
            if "tn" in e and "ts" in e:
                anchored_keys[(e["tn"], e["ts"], e.get("ev"))] = anchor
    rows: List[Dict[str, Any]] = []
    seen = set()
    for path, events, anchor in files:
        if anchor is None:
            for e in events:
                key = (e["tn"], e["ts"], e.get("ev")) if "tn" in e and "ts" in e else None
                if key is not None and key in anchored_keys:
                    anchor = anchored_keys[key]
                    break
        base = 0.0 if anchor is None else anchor
        for e in events:
            key = (e["tn"], e["ts"], e.get("ev")) if "tn" in e and "ts" in e else None
            if key is not None:
                if key in seen:
                    continue
                seen.add(key)
            e["_wall"] = base + float(e.get("t", 0.0))
            e["_src"] = path
            rows.append(e)
    rows.sort(key=lambda e: e["_wall"])
    return rows


# ---------------------------------------------------------------------------
# joins
# ---------------------------------------------------------------------------


def wire_joins(rows: List[Dict[str, Any]]) -> Dict[str, Any]:
    """Join v2 ``wire_send`` rows to their ``wire_recv`` on the far
    node.  Only sends carrying the causal fields participate (v1 rows
    have no ``node``/``seq``)."""
    recvs = set()
    for e in rows:
        if e.get("ev") == "wire_recv" and "node" in e and "seq" in e:
            recvs.add((str(e["node"]), str(e["peer"]), int(e["seq"])))
    sends = 0
    joined = 0
    for e in rows:
        if e.get("ev") == "wire_send" and "node" in e and "seq" in e:
            sends += 1
            if (str(e["peer"]), str(e["node"]), int(e["seq"])) in recvs:
                joined += 1
    links = sum(1 for e in rows if e.get("ev") == "trace_link")
    return {
        "sends": sends,
        "joined": joined,
        "frac": (joined / sends) if sends else None,
        "trace_links": links,
    }


def tx_chains(rows: List[Dict[str, Any]]) -> Dict[str, Any]:
    """The admit→ack chain per committed tx.  A chain is *complete*
    when its commit ack (``client_commit_latency`` with client+seq)
    joins back to a ``gateway_admit`` AND its epoch shows at least one
    ``node_commit`` — i.e. the tx is traceable across the gateway, the
    mesh, and back out."""
    admits: Dict[Tuple[str, int], Dict[str, Any]] = {}
    committed_epochs = set()
    for e in rows:
        ev = e.get("ev")
        if ev == "gateway_admit" and "client" in e and "seq" in e:
            admits.setdefault((str(e["client"]), int(e["seq"])), e)
        elif ev == "node_commit":
            committed_epochs.add(e.get("epoch"))
    total = complete = 0
    missing: List[Dict[str, Any]] = []
    for e in rows:
        if e.get("ev") != "client_commit_latency":
            continue
        total += 1
        key = (str(e.get("client")), int(e.get("seq", -1)))
        has_admit = key in admits
        has_commit = e.get("epoch") in committed_epochs
        if has_admit and has_commit:
            complete += 1
        elif len(missing) < 16:
            missing.append(
                {
                    "client": e.get("client"),
                    "seq": e.get("seq"),
                    "epoch": e.get("epoch"),
                    "admit": has_admit,
                    "node_commit": has_commit,
                }
            )
    return {
        "committed": total,
        "complete": complete,
        "complete_frac": (complete / total) if total else None,
        "incomplete_sample": missing,
    }


# ---------------------------------------------------------------------------
# per-epoch hop walls
# ---------------------------------------------------------------------------


def epoch_timeline(rows: List[Dict[str, Any]]) -> List[Dict[str, Any]]:
    """One entry per committed epoch, causally ordered, with the
    admit→gossip→ACS→decrypt→ack hop walls that can be established
    from the merged rows (a hop whose endpoints are missing is simply
    omitted — partial traces still produce a timeline)."""
    admits: Dict[Tuple[str, int], float] = {}
    gossip_walls: List[float] = []
    acs: Dict[int, List[float]] = defaultdict(list)
    ordered: Dict[int, List[float]] = defaultdict(list)
    commits: Dict[int, List[Dict[str, Any]]] = defaultdict(list)
    acks: Dict[int, List[Dict[str, Any]]] = defaultdict(list)
    for e in rows:
        ev = e.get("ev")
        if ev == "gateway_admit" and "client" in e and "seq" in e:
            admits.setdefault((str(e["client"]), int(e["seq"])), e["_wall"])
        elif ev == "gossip_relay":
            gossip_walls.append(e["_wall"])
        elif ev == "acs_done" and isinstance(e.get("epoch"), int):
            acs[e["epoch"]].append(e["_wall"])
        elif ev == "ordered_commit" and isinstance(e.get("epoch"), int):
            ordered[e["epoch"]].append(e["_wall"])
        elif ev == "node_commit" and isinstance(e.get("epoch"), int):
            commits[e["epoch"]].append(e)
        elif ev == "client_commit_latency" and isinstance(e.get("epoch"), int):
            acks[e["epoch"]].append(e)
    gossip_walls.sort()

    out: List[Dict[str, Any]] = []
    epochs = sorted(set(commits) | set(acks) | set(acs))
    for epoch in epochs:
        entry: Dict[str, Any] = {"epoch": epoch}
        ack_rows = acks.get(epoch, [])
        commit_rows = commits.get(epoch, [])
        entry["commit_nodes"] = len({str(c.get("node")) for c in commit_rows})
        entry["txs"] = max(
            [int(c["txs"]) for c in commit_rows if "txs" in c], default=len(ack_rows)
        )
        admit_walls = sorted(
            admits[(str(a.get("client")), int(a.get("seq", -1)))]
            for a in ack_rows
            if (str(a.get("client")), int(a.get("seq", -1))) in admits
        )
        hops: Dict[str, float] = {}
        t_admit = admit_walls[0] if admit_walls else None
        t_gossip = None
        if t_admit is not None:
            later = [w for w in gossip_walls if w >= t_admit]
            if later:
                t_gossip = later[0]
                hops["admit_to_gossip"] = t_gossip - t_admit
        t_acs = min(acs[epoch]) if acs.get(epoch) else None
        if t_acs is not None and t_gossip is not None:
            hops["gossip_to_acs"] = max(0.0, t_acs - t_gossip)
        t_commit = (
            max(c["_wall"] for c in commit_rows) if commit_rows else None
        )
        if t_commit is not None and t_acs is not None:
            hops["acs_to_commit"] = max(0.0, t_commit - t_acs)
        # order-then-reveal: the commit hop splits at the ordered
        # commit — agreement+digest on the critical path, decryption
        # as observable reveal lag behind it
        t_ordered = max(ordered[epoch]) if ordered.get(epoch) else None
        if t_ordered is not None:
            if t_acs is not None:
                hops["acs_to_ordered_commit"] = max(0.0, t_ordered - t_acs)
            if t_commit is not None:
                hops["ordered_commit_to_reveal"] = max(
                    0.0, t_commit - t_ordered
                )
        if ack_rows and t_commit is not None:
            hops["commit_to_ack"] = max(
                0.0, max(a["_wall"] for a in ack_rows) - t_commit
            )
        if ack_rows:
            entry["admit_to_ack"] = _dist(
                [float(a.get("latency_s", 0.0)) for a in ack_rows]
            )
        entry["hops"] = hops
        out.append(entry)
    return out


# ---------------------------------------------------------------------------
# health rules
# ---------------------------------------------------------------------------


def _merged_counters(rows: List[Dict[str, Any]]) -> Dict[str, float]:
    out: Dict[str, float] = defaultdict(float)
    for e in rows:
        if e.get("ev") == "counter":
            out[str(e.get("name"))] += float(e.get("value", 0))
    return dict(out)


def _merged_hists(rows: List[Dict[str, Any]]) -> Dict[str, Dict[str, float]]:
    """Per-name worst-case merge of ``hist`` summary rows (max for
    order stats, sum for count/sum) — the conservative view for SLOs."""
    out: Dict[str, Dict[str, float]] = {}
    for e in rows:
        if e.get("ev") != "hist":
            continue
        name = str(e.get("name"))
        cur = out.setdefault(name, defaultdict(float))
        for stat in ("min", "p50", "p90", "p99", "max"):
            if stat in e:
                cur[stat] = max(cur.get(stat, float("-inf")), float(e[stat]))
        for stat in ("count", "sum"):
            if stat in e:
                cur[stat] += float(e[stat])
    return {k: dict(v) for k, v in out.items()}


def select(
    selector: str,
    rows: List[Dict[str, Any]],
    derived: Dict[str, Any],
) -> Optional[float]:
    """Resolve one rule selector against the merged rows; ``None``
    means the subject is absent from these traces."""
    kind, _, rest = selector.partition(":")
    if kind == "counter":
        return _merged_counters(rows).get(rest)
    if kind == "event_sum":
        ev, _, field = rest.partition(":")
        vals = [
            float(e[field])
            for e in rows
            if e.get("ev") == ev and isinstance(e.get(field), (int, float))
        ]
        return sum(vals) if vals else None
    if kind == "event_count":
        n = sum(1 for e in rows if e.get("ev") == rest)
        return float(n) if n else None
    if kind == "hist":
        name, _, stat = rest.rpartition(":")
        h = _merged_hists(rows).get(name)
        return None if h is None else h.get(stat)
    if kind == "chain":
        return derived["chains"].get(rest)
    if kind == "join":
        return derived["joins"].get(rest)
    raise ValueError("unknown selector kind: %r" % selector)


def evaluate_rules(
    rules: List[Tuple[str, str, str, float]],
    rows: List[Dict[str, Any]],
    derived: Dict[str, Any],
) -> List[Dict[str, Any]]:
    results = []
    for name, selector, op, threshold in rules:
        value = select(selector, rows, derived)
        if value is None:
            status = "absent"
        elif _OPS[op](value, threshold):
            status = "pass"
        else:
            status = "FAIL"
        results.append(
            {
                "rule": name,
                "selector": selector,
                "op": op,
                "threshold": threshold,
                "value": value,
                "status": status,
            }
        )
    return results


def parse_rules(path: str) -> List[Tuple[str, str, str, float]]:
    """One rule per line: ``name selector op threshold`` (``#``
    comments and blank lines skipped)."""
    rules = []
    with open(path) as fh:
        for ln, line in enumerate(fh, 1):
            line = line.strip()
            if not line or line.startswith("#"):
                continue
            parts = line.split()
            if len(parts) != 4 or parts[2] not in _OPS:
                raise ValueError("%s:%d: bad rule line: %r" % (path, ln, line))
            rules.append((parts[0], parts[1], parts[2], float(parts[3])))
    return rules


# ---------------------------------------------------------------------------
# top level
# ---------------------------------------------------------------------------


def build(
    paths: List[str],
    rules: Optional[List[Tuple[str, str, str, float]]] = None,
) -> Dict[str, Any]:
    """The full post-mortem: merged rows → joins, chains, per-epoch
    timeline, health results."""
    rows = merge(paths)
    nodes = sorted(
        {str(e["tn"]) for e in rows if "tn" in e}
        | {str(e["node"]) for e in rows if e.get("ev") == "node_commit"}
    )
    derived = {"joins": wire_joins(rows), "chains": tx_chains(rows)}
    health = evaluate_rules(
        DEFAULT_RULES if rules is None else rules, rows, derived
    )
    return {
        "files": len(paths),
        "events": len(rows),
        "nodes": nodes,
        "joins": derived["joins"],
        "chains": derived["chains"],
        "epochs": epoch_timeline(rows),
        "health": health,
        "ok": all(r["status"] != "FAIL" for r in health),
    }


def render(tl: Dict[str, Any]) -> str:
    lines: List[str] = []
    add = lines.append
    add(
        "timeline: %d events from %d file(s), nodes: %s"
        % (tl["events"], tl["files"], ", ".join(tl["nodes"]) or "(none)")
    )
    j = tl["joins"]
    if j["sends"]:
        add(
            "wire joins: %d/%d sends joined (%.2f%%), %d trace_link rows"
            % (j["joined"], j["sends"], 100.0 * j["frac"], j["trace_links"])
        )
    c = tl["chains"]
    if c["committed"]:
        add(
            "tx chains: %d/%d committed txs with complete admit->ack chain (%.2f%%)"
            % (c["complete"], c["committed"], 100.0 * c["complete_frac"])
        )
    if tl["epochs"]:
        add("")
        add("epoch  nodes  txs  hop walls (ms)")
        for e in tl["epochs"]:
            hops = "  ".join(
                "%s %.1f" % (k.replace("_to_", ">"), v * 1000.0)
                for k, v in e["hops"].items()
            )
            a2a = e.get("admit_to_ack")
            if a2a:
                hops += "  admit>ack p50 %.1f max %.1f" % (
                    a2a["p50"] * 1000.0,
                    a2a["max"] * 1000.0,
                )
            add(
                "%5d  %5d  %3d  %s"
                % (e["epoch"], e["commit_nodes"], e["txs"], hops or "(no hops)")
            )
    add("")
    add("health:")
    for r in tl["health"]:
        val = "absent" if r["value"] is None else "%g" % r["value"]
        add(
            "  [%-6s] %-22s %s %s %g (value: %s)"
            % (r["status"], r["rule"], r["selector"], r["op"], r["threshold"], val)
        )
    add("overall: %s" % ("OK" if tl["ok"] else "VIOLATIONS"))
    return "\n".join(lines)


def main(argv: Optional[List[str]] = None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m hbbft_tpu.obs.timeline", description=__doc__
    )
    p.add_argument("trace", nargs="+", help="trace/flight/fleet JSONL files")
    p.add_argument("--json", action="store_true")
    p.add_argument(
        "--rules", default=None, help="rule file (default: built-in SLO set)"
    )
    p.add_argument(
        "--min-join",
        type=float,
        default=None,
        metavar="FRAC",
        help="fail unless the wire-join fraction reaches FRAC "
        "(unlike the rule, absent joins also fail)",
    )
    args = p.parse_args(argv)
    rules = parse_rules(args.rules) if args.rules else None
    tl = build(args.trace, rules)
    if args.min_join is not None:
        frac = tl["joins"]["frac"]
        if frac is None or frac < args.min_join:
            tl["ok"] = False
            tl["health"].append(
                {
                    "rule": "min-join(cli)",
                    "selector": "join:frac",
                    "op": ">=",
                    "threshold": args.min_join,
                    "value": frac,
                    "status": "FAIL",
                }
            )
    try:
        if args.json:
            print(json.dumps(tl, indent=2, sort_keys=True))
        else:
            print(render(tl))
    except BrokenPipeError:
        sys.stderr.close()
    return 0 if tl["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
