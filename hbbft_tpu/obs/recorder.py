"""The trace recorder — span timers, counters, histograms, JSONL export.

Design constraints (ISSUE 1 tentpole):

- **Near-zero overhead when off.**  Instrumented call sites read the
  module global ``ACTIVE`` once and branch on ``is None`` — no object
  allocation, no dict lookup, no context-manager machinery on the
  no-op path.  Protocol hot loops (``SimNode.handle_message``,
  ``SimNetwork._dispatch``, ``FaultLog.append``) stay within noise of
  the untraced build.
- **Stable event schema.**  Every event is one JSON object per line
  with at least ``{"ev": <type>, "t": <seconds since trace start>}``.
  The authoritative per-type field table lives in
  :mod:`hbbft_tpu.obs.schema` and is enforced over every call site by
  the ``obs-schema`` badgerlint rule (``python -m hbbft_tpu.analysis``).
  Event types in use across the stack (consumed by
  :mod:`hbbft_tpu.obs.report`):

  ==================  =====================================================
  ``trace_start``     schema version + wall-clock anchor
  ``span``            named timed region: ``name, t, dur, depth`` + attrs
  ``msg_send``        simulator dispatch: ``src, size, vt, kind`` (all/node)
  ``msg_deliver``     per-recipient enqueue: ``src, dst, size, vt, kind``
  ``msg_handle``      one handled message: ``node, vt, wall, size``
  ``epoch_start``     first batch output seen for an epoch: ``epoch, vt``
  ``epoch_decide``    one node's batch for an epoch: ``epoch, node, vt``
  ``epoch``           completed epoch row (all live nodes decided):
                      ``epoch, min_time, max_time, txs, msgs_per_node,
                      bytes_per_node``
  ``epoch_phases``    vectorized epoch driver wall-clock breakdown:
                      ``epoch, phases{...}, shares, coin_flips, faults``
  ``flush``           one crypto batch flush: ``queued, shipped, real,
                      inline`` (+ ``occupancy, dur, groups,
                      fallback_groups, phases`` on non-cached rounds)
  ``device_op``       one MSM routing decision: ``op, k, engine``
  ``fault``           one attributed Byzantine fault: ``fault`` (the
                      stable compact form ``<node!r>:<KIND>``), ``node,
                      kind``
  ``wire_send``       one frame written to a TCP peer link: ``peer,
                      size`` (+ ``kind``: ``all``/``node``; v2 adds
                      ``node, seq`` for the cross-node causal join)
  ``wire_recv``       one frame read off a TCP peer link: ``peer, size``
                      (+ ``node, seq``)
  ``counter``         final counter values (emitted on close)
  ``hist``            histogram summaries (emitted on close)
  ``trace_end``       total event count + duration
  ==================  =====================================================

- **Cross-node trace context (schema v2).**  A recorder given a node
  identity (``enable(..., node=...)`` or :meth:`Recorder.set_node`)
  stamps every row with ``tn`` (node id), ``ts`` (a per-recorder
  monotonic event sequence number) and — once :meth:`set_epoch` has
  been called — ``te`` (the current consensus epoch).  The triple is
  the compact trace context ``obs.timeline`` merges multi-node traces
  by; it is stamped by :meth:`event` itself, never by call sites.
- **Flight recorder.**  :meth:`attach_flight` mirrors every event row
  into a bounded :class:`~hbbft_tpu.obs.flight.FlightRecorder` ring
  and force-dumps it on any ``fault`` or ``degrade`` event — the
  built-in black box for crashes and attributions.

- **Streaming JSONL.**  With a ``path``, events are written as they
  happen (line-buffered), so a crashed run still leaves a readable
  trace.  Events are also kept in ``Recorder.events`` for in-process
  inspection (tests, bench).
"""

from __future__ import annotations

import functools
import json
import os
import threading
import time as _time
from typing import Any, Callable, Dict, IO, List, Optional

from .schema import SCHEMA_VERSION

# THE hot-path gate: instrumented modules do
#     rec = _obs.ACTIVE
#     if rec is not None: rec.event(...)
# Rebinding happens only in enable()/disable().
ACTIVE: Optional["Recorder"] = None


def _jsonable(v: Any) -> Any:
    """Coerce arbitrary attribute values to JSON-safe ones: primitives
    pass through, bytes hex-encode, containers recurse, anything else
    becomes its ``repr`` (node ids in this codebase are ints/strs, but
    the schema must never crash on an exotic one)."""
    if v is None or isinstance(v, (bool, int, float, str)):
        return v
    if isinstance(v, bytes):
        return v.hex()
    if isinstance(v, dict):
        return {str(k): _jsonable(x) for k, x in v.items()}
    if isinstance(v, (list, tuple)):
        return [_jsonable(x) for x in v]
    return repr(v)


def _pct(sorted_vals: List[float], q: float) -> float:
    """Nearest-rank percentile of a pre-sorted non-empty list."""
    i = min(len(sorted_vals) - 1, max(0, int(q * len(sorted_vals))))
    return sorted_vals[i]


class Span:
    """A named timed region.  Context manager; ``dur`` holds the
    elapsed seconds after exit (used by bench to keep its medians while
    the same timing lands in the trace)."""

    __slots__ = ("rec", "name", "attrs", "t0", "dur", "depth", "_ann")

    def __init__(self, rec: "Recorder", name: str, attrs: Dict[str, Any]):
        self.rec = rec
        self.name = name
        self.attrs = attrs
        self.t0 = 0.0
        self.dur = 0.0
        self.depth = 0
        self._ann = None

    def __enter__(self) -> "Span":
        self.depth = self.rec._enter_span()
        if self.rec._jax:
            try:
                from jax.profiler import TraceAnnotation

                self._ann = TraceAnnotation(self.name)
                self._ann.__enter__()
            except Exception:
                self._ann = None
        self.t0 = self.rec.now()
        return self

    def __exit__(self, et, ev, tb) -> bool:
        self.dur = self.rec.now() - self.t0
        if self._ann is not None:
            try:
                self._ann.__exit__(et, ev, tb)
            except Exception:
                pass
        self.rec._exit_span()
        self.rec.event(
            "span",
            t=self.t0,
            name=self.name,
            dur=round(self.dur, 9),
            depth=self.depth,
            **self.attrs,
        )
        return False


class _NullSpan:
    """Shared no-op span returned by the module-level :func:`span` when
    tracing is off (``dur`` stays 0.0 — callers that need wall time
    regardless hold their own :class:`Recorder`)."""

    dur = 0.0

    def __enter__(self) -> "_NullSpan":
        return self

    def __exit__(self, *exc) -> bool:
        return False


_NULL_SPAN = _NullSpan()


class Recorder:
    """Collects events, counters and histogram samples; optionally
    streams events to a JSONL file as they are recorded.

    Thread-safe: the batching backend's async MSM finalizers run on
    waiter threads, so event append takes a lock (only paid when
    tracing is on)."""

    def __init__(
        self,
        path: Optional[str] = None,
        *,
        jax_annotations: bool = False,
        clock: Optional[Callable[[], float]] = None,
        node: Optional[str] = None,
    ):
        self._clock = clock or _time.perf_counter
        self._t0 = self._clock()
        self._lock = threading.Lock()
        self._tls = threading.local()
        self.events: List[Dict[str, Any]] = []
        self.counters: Dict[str, int] = {}
        self._hists: Dict[str, List[float]] = {}
        self.path = path
        self._sink: Optional[IO[str]] = (
            open(path, "w", buffering=1) if path else None
        )
        self._jax = jax_annotations or bool(
            os.environ.get("HBBFT_TPU_TRACE_JAX")
        )
        self._closed = False
        # cross-node trace context (schema v2): stamped on every row
        # when a node identity is set — tn/ts/te are reserved fields
        self._node: Optional[str] = None if node is None else str(node)
        self._trace_seq = 0
        self._epoch: Optional[int] = None
        # flight-recorder mirror (attach_flight): every row is echoed
        # into the ring; fault/degrade events trigger a forced dump
        self._flight: Optional[Any] = None
        self.event(
            "trace_start", schema=SCHEMA_VERSION, wall_unix=round(_time.time(), 3)
        )

    # -- time ---------------------------------------------------------------

    def now(self) -> float:
        return self._clock() - self._t0

    # -- events -------------------------------------------------------------

    def event(self, ev: str, *, t: Optional[float] = None, **fields) -> Dict[str, Any]:
        row: Dict[str, Any] = {
            "ev": ev,
            "t": round(self.now() if t is None else t, 9),
        }
        for k, v in fields.items():
            row[k] = _jsonable(v)
        with self._lock:
            # the trace-context stamp lives under the lock so ts is a
            # strictly monotonic per-recorder sequence even with
            # waiter/stager threads emitting concurrently
            if self._node is not None:
                self._trace_seq += 1
                row["tn"] = self._node
                row["ts"] = self._trace_seq
                if self._epoch is not None:
                    row["te"] = self._epoch
            self.events.append(row)
            if self._sink is not None:
                self._sink.write(json.dumps(row, separators=(",", ":")) + "\n")
            flight = self._flight
        # the flight mirror runs OUTSIDE _lock: dumps do file I/O and
        # may emit a flight_dump marker row back through event(), so
        # holding the non-reentrant lock here would self-deadlock (the
        # lock-order rule)
        if flight is not None:
            flight.record(row)
            if ev in ("fault", "degrade"):
                flight.maybe_dump(ev)
        return row

    # -- trace context (schema v2) ------------------------------------------

    def set_node(self, node: Any) -> None:
        """Bind this recorder to a node identity: every subsequent row
        is stamped with the ``tn``/``ts`` (/``te``) trace context."""
        with self._lock:
            self._node = str(node)

    def set_epoch(self, epoch: int) -> None:
        """Update the epoch component of the trace context (stamped as
        ``te`` on subsequent rows; ignored until :meth:`set_node`)."""
        if type(epoch) is int:
            with self._lock:
                self._epoch = epoch

    @property
    def node(self) -> Optional[str]:
        return self._node

    def attach_flight(self, flight: Any) -> None:
        """Mirror every event row into ``flight`` (a
        :class:`~hbbft_tpu.obs.flight.FlightRecorder`); ``fault`` and
        ``degrade`` events force a dump.  Pass ``None`` to detach."""
        with self._lock:
            self._flight = flight

    # -- counters / histograms ---------------------------------------------

    def count(self, name: str, n: int = 1) -> None:
        with self._lock:
            self.counters[name] = self.counters.get(name, 0) + n

    def observe(self, name: str, value: float) -> None:
        """Record one histogram sample (summarized on :meth:`close`)."""
        with self._lock:
            self._hists.setdefault(name, []).append(float(value))

    def counters_snapshot(self) -> Dict[str, int]:
        """A consistent copy of the live counters (metrics export reads
        this mid-run; :meth:`close` emits the final values as rows)."""
        with self._lock:
            return dict(self.counters)

    def hists_summary(self) -> Dict[str, Dict[str, float]]:
        """Live histogram summaries keyed by name — same statistics the
        ``hist`` close-time rows carry (count/min/p50/p90/p99/max/
        sum)."""
        with self._lock:
            hists = {k: list(v) for k, v in self._hists.items()}
        out: Dict[str, Dict[str, float]] = {}
        for name, vals in hists.items():
            vals.sort()
            out[name] = {
                "count": len(vals),
                "min": vals[0],
                "p50": _pct(vals, 0.50),
                "p90": _pct(vals, 0.90),
                "p99": _pct(vals, 0.99),
                "max": vals[-1],
                "sum": sum(vals),
            }
        return out

    # -- spans --------------------------------------------------------------

    def span(self, name: str, **attrs) -> Span:
        return Span(self, name, attrs)

    def snapshot(self) -> List[Dict[str, Any]]:
        """A consistent copy of the events recorded so far.  In-process
        readers (tests, bench, report) iterate THIS, not ``events``,
        while waiter/stager threads may still be appending — list
        append is atomic under the GIL but iterating a list being
        appended to is not a stable view."""
        with self._lock:
            return list(self.events)

    def _enter_span(self) -> int:
        d = getattr(self._tls, "depth", 0)
        self._tls.depth = d + 1
        return d

    def _exit_span(self) -> None:
        self._tls.depth = max(0, getattr(self._tls, "depth", 1) - 1)

    # -- lifecycle ----------------------------------------------------------

    def close(self) -> None:
        """Emit counter + histogram summaries and ``trace_end``, then
        close the sink.  Idempotent — the closed-check and the flag
        flip happen under ``_lock`` so two racing closers (e.g. a
        waiter thread finishing while ``disable()`` runs) emit the
        summaries exactly once.  The summaries themselves are emitted
        AFTER releasing the lock: ``event()`` re-takes the
        non-reentrant ``_lock``, so emitting while holding it would
        self-deadlock (the ``lock-order`` badgerlint rule catches
        exactly this shape)."""
        with self._lock:
            if self._closed:
                return
            self._closed = True
            counters = dict(self.counters)
            hists = {k: list(v) for k, v in self._hists.items()}
        for name in sorted(counters):
            self.event("counter", name=name, value=counters[name])
        for name in sorted(hists):
            vals = sorted(hists[name])
            self.event(
                "hist",
                name=name,
                count=len(vals),
                min=round(vals[0], 9),
                p50=round(_pct(vals, 0.50), 9),
                p90=round(_pct(vals, 0.90), 9),
                p99=round(_pct(vals, 0.99), 9),
                max=round(vals[-1], 9),
                sum=round(sum(vals), 9),
            )
        with self._lock:
            n_events = len(self.events) + 1
        self.event("trace_end", events=n_events, dur=round(self.now(), 9))
        with self._lock:
            if self._sink is not None:
                self._sink.close()
                self._sink = None


# ---------------------------------------------------------------------------
# Module-level switchboard
# ---------------------------------------------------------------------------

# Guards the ACTIVE swap in enable()/disable() — hot-path READERS stay
# lock-free (one global load + is-None branch; a stale read during a
# swap only routes one event to the outgoing recorder, which is still
# open until close() runs).  close() is called under this lock from
# enable(), giving the one-directional _SWITCH_LOCK → Recorder._lock
# edge; nothing acquires them in the other order.
_SWITCH_LOCK = threading.Lock()


def active() -> Optional[Recorder]:
    """The installed recorder, or None when tracing is off."""
    return ACTIVE


def enable(
    path: Optional[str] = None,
    *,
    jax_annotations: bool = False,
    clock: Optional[Callable[[], float]] = None,
    node: Optional[str] = None,
) -> Recorder:
    """Install a recorder as the process-wide trace sink.  A previously
    installed recorder is closed first.  With ``node``, every row is
    stamped with the cross-node trace context (schema v2)."""
    global ACTIVE
    with _SWITCH_LOCK:
        if ACTIVE is not None:
            ACTIVE.close()
        ACTIVE = Recorder(
            path, jax_annotations=jax_annotations, clock=clock, node=node
        )
        return ACTIVE


def disable() -> Optional[Recorder]:
    """Uninstall and close the active recorder; returns it (its
    in-memory ``events`` stay readable after close)."""
    global ACTIVE
    with _SWITCH_LOCK:
        rec, ACTIVE = ACTIVE, None
    if rec is not None:
        rec.close()
    return rec


def span(name: str, **attrs):
    """Module-level span helper: a real span when tracing is on, a
    shared no-op context manager otherwise."""
    rec = ACTIVE
    return rec.span(name, **attrs) if rec is not None else _NULL_SPAN


def traced(name: Optional[str] = None, **attrs):
    """Decorator form of :func:`span`: times every call of the wrapped
    function when tracing is on; passes straight through otherwise."""

    def deco(fn):
        label = name or fn.__qualname__

        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            rec = ACTIVE
            if rec is None:
                return fn(*args, **kwargs)
            with rec.span(label, **attrs):
                return fn(*args, **kwargs)

        return wrapper

    return deco
