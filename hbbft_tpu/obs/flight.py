"""The flight recorder — a bounded per-node black box.

A :class:`FlightRecorder` keeps the last ``capacity`` event rows in a
ring and force-dumps them to disk when something goes wrong: the
recorder mirror (:meth:`Recorder.attach_flight`) triggers a dump on
every ``fault`` and ``degrade`` event, and :func:`install_sigterm`
hooks process termination.  Dumps are crash-safe the same way the WAL
is — written to a temp file, fsynced, then atomically renamed — so a
reader never sees a torn dump.

For crashes that never reach a dump trigger (SIGKILL, power loss) the
ring can run in *persist* mode: every row is written through to an
append-only JSONL file as it is recorded, line-buffered, so the file
on disk always holds the tail of the event stream up to the last
completed write.  The file is compacted back down to the ring bound
with the same atomic temp+rename dance once it grows past a few times
``capacity``, keeping long runs at bounded disk cost.

Dump files are plain JSONL in the schema-v2 row format, prefixed by
one ``flight_dump`` meta row, so ``obs.timeline`` and ``obs.report``
ingest them exactly like live traces.
"""

from __future__ import annotations

import collections
import json
import os
import signal
import threading
import time as _time
from typing import Any, Callable, Deque, Dict, List, Optional, Tuple

DEFAULT_CAPACITY = 512

#: Minimum seconds between trigger-driven dumps (a fault storm must
#: not turn the black box into an fsync storm).
DUMP_INTERVAL_S = 1.0


class FlightRecorder:
    """Bounded ring of the last ``capacity`` event rows.

    Thread-safe; never raises out of :meth:`record`/:meth:`maybe_dump`
    (a broken black box must not take the node down with it).

    :param path: where :meth:`dump` writes (atomic temp+rename).
    :param capacity: ring bound (rows).
    :param node: node identity stamped on the ``flight_dump`` meta row.
    :param persist: optional append-only JSONL path written through on
        every :meth:`record` — the SIGKILL-survivable mode.
    """

    def __init__(
        self,
        path: str,
        *,
        capacity: int = DEFAULT_CAPACITY,
        node: Optional[str] = None,
        persist: Optional[str] = None,
        clock: Optional[Callable[[], float]] = None,
    ):
        self.path = path
        self.capacity = max(1, int(capacity))
        self.node = None if node is None else str(node)
        self._clock = clock or _time.monotonic
        self._lock = threading.Lock()
        self._ring: Deque[Dict[str, Any]] = collections.deque(
            maxlen=self.capacity
        )
        self._total = 0
        self._last_dump = float("-inf")
        self.dumps = 0
        self._persist_path = persist
        self._persist_rows = 0
        self._persist_fh = None
        if persist is not None:
            self._persist_fh = open(persist, "a", buffering=1)

    # -- recording ----------------------------------------------------------

    def record(self, row: Dict[str, Any]) -> None:
        """Append one event row to the ring (and the persist file when
        enabled).  Swallows I/O errors — see class docstring."""
        try:
            with self._lock:
                self._ring.append(row)
                self._total += 1
                if self._persist_fh is not None:
                    self._persist_fh.write(
                        json.dumps(row, separators=(",", ":")) + "\n"
                    )
                    self._persist_rows += 1
                    if self._persist_rows > 4 * self.capacity:
                        self._compact_persist_locked()
        except Exception:
            pass

    def _compact_persist_locked(self) -> None:
        """Rewrite the persist file down to the current ring contents
        (atomic temp+rename), then reopen the append handle.  Called
        with ``_lock`` held."""
        path = self._persist_path
        self._persist_fh.close()
        tmp = path + ".tmp"
        with open(tmp, "w") as fh:
            for row in self._ring:
                fh.write(json.dumps(row, separators=(",", ":")) + "\n")
            fh.flush()
            os.fsync(fh.fileno())
        os.replace(tmp, path)
        self._persist_fh = open(path, "a", buffering=1)
        self._persist_rows = len(self._ring)

    # -- dumping ------------------------------------------------------------

    def maybe_dump(self, reason: str) -> Optional[str]:
        """Trigger-driven dump, rate-limited to one per
        :data:`DUMP_INTERVAL_S`.  Returns the dump path or ``None``."""
        with self._lock:
            now = self._clock()
            if now - self._last_dump < DUMP_INTERVAL_S:
                return None
            self._last_dump = now
        return self.dump(reason)

    def dump(self, reason: str) -> Optional[str]:
        """Force-dump the ring to ``self.path``: one ``flight_dump``
        meta row, then the buffered rows, via atomic temp+rename with
        an fsync before the rename (torn dumps are impossible; a crash
        mid-dump leaves the previous dump intact)."""
        try:
            with self._lock:
                rows = list(self._ring)
                dropped = self._total - len(rows)
            meta = {
                "ev": "flight_dump",
                "t": round(_time.time(), 3),
                "reason": reason,
                "events": len(rows),
                "dropped": dropped,
                "path": self.path,
            }
            if self.node is not None:
                meta["node"] = self.node
            tmp = self.path + ".tmp"
            with open(tmp, "w") as fh:
                fh.write(json.dumps(meta, separators=(",", ":")) + "\n")
                for row in rows:
                    fh.write(json.dumps(row, separators=(",", ":")) + "\n")
                fh.flush()
                os.fsync(fh.fileno())
            os.replace(tmp, self.path)
            self.dumps += 1
            # a marker row in the *live* trace too, so a merged
            # timeline shows when/why each black box fired.  No locks
            # are held here, and "flight_dump" is not a dump trigger,
            # so the mirror back through event() cannot recurse.
            from . import recorder as _obs

            rec = _obs.ACTIVE
            if rec is not None:
                rec.event(
                    "flight_dump",
                    reason=reason,
                    events=len(rows),
                    dropped=dropped,
                    path=self.path,
                    node=self.node,
                )
            return self.path
        except Exception:
            return None

    def close(self) -> None:
        with self._lock:
            if self._persist_fh is not None:
                try:
                    self._persist_fh.close()
                except Exception:
                    pass
                self._persist_fh = None


def load(path: str) -> Tuple[List[Dict[str, Any]], Optional[Dict[str, Any]]]:
    """Read a dump (or persist) file: ``(rows, meta)`` where ``meta``
    is the leading ``flight_dump`` row when present (dumps have one,
    persist files don't).  Torn trailing lines — expected after a hard
    kill mid-write — are silently dropped, like ``report.load_events``."""
    rows: List[Dict[str, Any]] = []
    meta: Optional[Dict[str, Any]] = None
    with open(path) as fh:
        for i, line in enumerate(fh):
            line = line.strip()
            if not line:
                continue
            try:
                row = json.loads(line)
            except json.JSONDecodeError:
                continue
            if i == 0 and row.get("ev") == "flight_dump":
                meta = row
            else:
                rows.append(row)
    return rows, meta


def install_sigterm(flight: FlightRecorder) -> None:
    """Dump ``flight`` on SIGTERM, chaining any previously installed
    handler (and the default terminate behaviour).  Main-thread only —
    signal handlers can't be set elsewhere; callers off the main
    thread get a no-op."""
    try:
        prev = signal.getsignal(signal.SIGTERM)

        def _on_term(signum, frame):
            flight.dump("sigterm")
            if callable(prev):
                prev(signum, frame)
            elif prev == signal.SIG_DFL:
                signal.signal(signal.SIGTERM, signal.SIG_DFL)
                signal.raise_signal(signal.SIGTERM)

        signal.signal(signal.SIGTERM, _on_term)
    except ValueError:
        pass
