"""The fleet poller — scrape N per-node exporters into one JSONL.

:class:`FleetPoller` periodically GETs every node's ``/metrics``
endpoint (:mod:`hbbft_tpu.obs.metrics`), parses the exposition back
into series, and appends one scrape row per node per round to a
single fleet JSONL file::

    {"ev": "metrics_scrape", "node": "n0", "up": true,
     "wall": 1754650000.123, "families": {"hbbft_wire_seq_gap_total": 0.0, ...}}

Rows use the schema-v2 ``metrics_scrape`` event shape (plus the
``families`` payload), so the fleet file feeds straight into
``obs.timeline`` / ``obs.report`` alongside per-node traces.  A node
that refuses connections or times out produces an ``up: false`` row —
the fleet file records outages, it doesn't skip them.

CLI::

    python -m hbbft_tpu.obs.fleet --target n0=127.0.0.1:9100 \
        --target n1=127.0.0.1:9101 --out fleet.jsonl --rounds 3
"""

from __future__ import annotations

import argparse
import asyncio
import json
import time as _time
from typing import Any, Dict, List, Optional, Tuple

from . import recorder as _obs
from .metrics import parse, scrape


class FleetPoller:
    """Scrapes ``targets`` (``{node_name: (host, port)}``) into
    ``out_path`` (append-mode JSONL; ``None`` keeps rows in memory
    only — they're always available via :attr:`rows`)."""

    def __init__(
        self,
        targets: Dict[str, Tuple[str, int]],
        out_path: Optional[str] = None,
        *,
        interval_s: float = 1.0,
        timeout_s: float = 5.0,
    ):
        self.targets = dict(targets)
        self.out_path = out_path
        self.interval_s = interval_s
        self.timeout_s = timeout_s
        self.rows: List[Dict[str, Any]] = []

    async def _scrape_one(self, name: str, host: str, port: int) -> Dict[str, Any]:
        t0 = _time.perf_counter()
        row: Dict[str, Any] = {
            "ev": "metrics_scrape",
            "node": name,
            "wall": round(_time.time(), 3),
        }
        try:
            body = await scrape(host, port, timeout=self.timeout_s)
            row["up"] = True
            row["families"] = parse(body)
        except (OSError, asyncio.TimeoutError, ConnectionError) as exc:
            row["up"] = False
            row["error"] = type(exc).__name__
        rec = _obs.ACTIVE
        if rec is not None:
            rec.event(
                "metrics_scrape",
                node=name,
                up=row["up"],
                families=len(row.get("families", ())),
                wall=round(_time.perf_counter() - t0, 6),
            )
        return row

    def _append_rows(self, rows: List[Dict[str, Any]]) -> None:
        """Sync JSONL append — runs on an executor thread, never on the
        event loop (the poller often shares its loop with the nodes it
        scrapes; a slow disk must not stall their sockets)."""
        assert self.out_path is not None
        with open(self.out_path, "a") as fh:
            for row in rows:
                fh.write(json.dumps(row, separators=(",", ":")) + "\n")

    async def poll_once(self) -> List[Dict[str, Any]]:
        """One scrape round across every target, concurrently."""
        rows = await asyncio.gather(
            *(
                self._scrape_one(name, host, port)
                for name, (host, port) in sorted(self.targets.items())
            )
        )
        self.rows.extend(rows)
        if self.out_path is not None:
            loop = asyncio.get_event_loop()
            await loop.run_in_executor(None, self._append_rows, rows)
        return list(rows)

    async def run(self, rounds: int) -> List[Dict[str, Any]]:
        """``rounds`` scrape rounds, ``interval_s`` apart."""
        for i in range(rounds):
            await self.poll_once()
            if i + 1 < rounds:
                await asyncio.sleep(self.interval_s)
        return list(self.rows)


def aggregate(rows: List[Dict[str, Any]]) -> Dict[str, Any]:
    """Fleet summary over scrape rows: the latest ``up`` state per
    node and, over each node's *latest* successful scrape, the
    fleet-wide sum per counter series (label sets stripped)."""
    latest: Dict[str, Dict[str, Any]] = {}
    for row in rows:
        latest[row["node"]] = row
    totals: Dict[str, float] = {}
    for row in latest.values():
        for series, value in (row.get("families") or {}).items():
            name = series.split("{", 1)[0]
            if name.endswith("_total"):
                totals[name] = totals.get(name, 0.0) + value
    return {
        "nodes": len(latest),
        "up": sum(1 for r in latest.values() if r.get("up")),
        "totals": {k: totals[k] for k in sorted(totals)},
    }


def _parse_target(spec: str) -> Tuple[str, Tuple[str, int]]:
    name, _, addr = spec.partition("=")
    if not addr:
        name, addr = addr or spec, spec
    host, _, port = addr.rpartition(":")
    return name, (host or "127.0.0.1", int(port))


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m hbbft_tpu.obs.fleet",
        description="Scrape per-node metrics endpoints into one fleet JSONL.",
    )
    ap.add_argument(
        "--target",
        action="append",
        required=True,
        metavar="NAME=HOST:PORT",
        help="one exporter endpoint (repeatable)",
    )
    ap.add_argument("--out", default=None, help="fleet JSONL path (append)")
    ap.add_argument("--rounds", type=int, default=1)
    ap.add_argument("--interval", type=float, default=1.0)
    ap.add_argument("--timeout", type=float, default=5.0)
    args = ap.parse_args(argv)

    targets = dict(_parse_target(s) for s in args.target)
    poller = FleetPoller(
        targets, args.out, interval_s=args.interval, timeout_s=args.timeout
    )
    rows = asyncio.run(poller.run(args.rounds))
    summary = aggregate(rows)
    print(json.dumps(summary, indent=2, sort_keys=True))
    return 0 if summary["up"] == summary["nodes"] else 1


if __name__ == "__main__":
    raise SystemExit(main())
