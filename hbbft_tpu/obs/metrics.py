"""Live metrics export — Prometheus-style text exposition per node.

Split sans-IO, like every protocol core in this codebase:

- :class:`MetricsCore` holds no sockets.  It snapshots a recorder's
  live counters and histogram summaries (thread-safe reads — see
  :meth:`Recorder.counters_snapshot`) and renders them as text
  exposition format 0.0.4.  Dotted internal names map to Prometheus
  conventions: counter ``wire.seq_gap`` becomes
  ``hbbft_wire_seq_gap_total``, histogram ``gateway.commit_latency_s``
  becomes ``hbbft_gateway_commit_latency_s{stat="p50"}`` summary
  series.  A ``node`` label carries the trace-context node id.
- :class:`MetricsExporter` is the tiny asyncio shell beside the
  gateway: a one-request HTTP/1.0 server answering ``GET /metrics``
  with the core's rendering (and ``/healthz`` with ``ok``).  One
  read, one write, close — no keep-alive, no framing edge cases.

:func:`parse` is the matching reader used by the fleet poller
(:mod:`hbbft_tpu.obs.fleet`) and tests: exposition text back into a
``{series: value}`` dict.
"""

from __future__ import annotations

import asyncio
import re
from typing import Any, Dict, Optional, Tuple

from . import recorder as _obs

_NAME_RE = re.compile(r"[^a-zA-Z0-9_]")

#: Histogram summary statistics exported per hist, in exposition order.
HIST_STATS = ("count", "min", "p50", "p90", "max", "sum")


def _metric_name(name: str) -> str:
    return "hbbft_" + _NAME_RE.sub("_", name)


class MetricsCore:
    """Sans-IO renderer of one recorder's live counters/hists.

    :param node: node label on every series (defaults to the
        recorder's trace-context node at render time).
    :param recorder: pin a specific recorder; defaults to the
        process-wide active one at each render.
    """

    def __init__(
        self,
        node: Optional[str] = None,
        recorder: Optional["_obs.Recorder"] = None,
    ):
        self.node = None if node is None else str(node)
        self._recorder = recorder

    def _rec(self) -> Optional["_obs.Recorder"]:
        return self._recorder if self._recorder is not None else _obs.ACTIVE

    def render(self) -> str:
        """The exposition body.  Always valid (possibly empty of
        samples) even with tracing off."""
        rec = self._rec()
        lines = []
        node = self.node
        if node is None and rec is not None:
            node = rec.node
        label = "" if node is None else '{node="%s"}' % node
        if rec is None:
            lines.append("# hbbft-tpu metrics: tracing off")
            return "\n".join(lines) + "\n"
        counters = rec.counters_snapshot()
        hists = rec.hists_summary()
        lines.append("hbbft_obs_events_total%s %d" % (label, len(rec.events)))
        for name in sorted(counters):
            metric = _metric_name(name) + "_total"
            lines.append("# TYPE %s counter" % metric)
            lines.append("%s%s %d" % (metric, label, counters[name]))
        for name in sorted(hists):
            metric = _metric_name(name)
            lines.append("# TYPE %s summary" % metric)
            stats = hists[name]
            for stat in HIST_STATS:
                if node is None:
                    slabel = '{stat="%s"}' % stat
                else:
                    slabel = '{node="%s",stat="%s"}' % (node, stat)
                lines.append("%s%s %.9g" % (metric, slabel, stats[stat]))
        return "\n".join(lines) + "\n"


def parse(text: str) -> Dict[str, float]:
    """Exposition text → ``{series: value}`` (series includes its
    label set verbatim).  Comment and blank lines are skipped;
    malformed lines are dropped, not raised — the poller must survive
    a half-written or newer-format body."""
    out: Dict[str, float] = {}
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        parts = line.rsplit(None, 1)
        if len(parts) != 2:
            continue
        try:
            out[parts[0]] = float(parts[1])
        except ValueError:
            continue
    return out


class MetricsExporter:
    """The asyncio endpoint serving one :class:`MetricsCore`."""

    def __init__(self, core: MetricsCore, host: str = "127.0.0.1", port: int = 0):
        self.core = core
        self.host = host
        self.port = port
        self._server: Optional[asyncio.AbstractServer] = None

    @property
    def addr(self) -> Tuple[str, int]:
        """The bound (host, port) — meaningful after :meth:`start`
        (port 0 binds an ephemeral port)."""
        return (self.host, self.port)

    async def start(self) -> "MetricsExporter":
        self._server = await asyncio.start_server(
            self._handle, self.host, self.port
        )
        self.port = self._server.sockets[0].getsockname()[1]
        return self

    async def stop(self) -> None:
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()
            self._server = None

    async def _handle(
        self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter
    ) -> None:
        try:
            try:
                req = await asyncio.wait_for(reader.readline(), timeout=5.0)
            except asyncio.TimeoutError:
                return
            parts = req.decode("latin-1", "replace").split()
            path = parts[1] if len(parts) >= 2 else "/"
            if path.startswith("/healthz"):
                status, body = "200 OK", "ok\n"
            elif path.startswith("/metrics"):
                status, body = "200 OK", self.core.render()
            else:
                status, body = "404 Not Found", "not found\n"
            payload = body.encode()
            writer.write(
                (
                    "HTTP/1.0 %s\r\n"
                    "Content-Type: text/plain; version=0.0.4\r\n"
                    "Content-Length: %d\r\n"
                    "Connection: close\r\n\r\n" % (status, len(payload))
                ).encode()
                + payload
            )
            await writer.drain()
        except ConnectionError:
            # A client that hangs up mid-response is routine.  But
            # CancelledError must propagate: the server's close() path
            # cancels these handler tasks and relies on the unwind —
            # swallowing it would turn shutdown into a hang.
            pass
        finally:
            try:
                writer.close()
            except Exception:
                pass


async def scrape(host: str, port: int, timeout: float = 5.0) -> str:
    """One GET /metrics against an exporter; returns the raw body."""
    reader, writer = await asyncio.open_connection(host, port)
    try:
        writer.write(b"GET /metrics HTTP/1.0\r\n\r\n")
        await writer.drain()
        raw = await asyncio.wait_for(reader.read(), timeout=timeout)
    finally:
        writer.close()
    head, _, body = raw.partition(b"\r\n\r\n")
    if b" 200 " not in head.split(b"\r\n", 1)[0]:
        raise ConnectionError(
            "scrape %s:%d: %s" % (host, port, head.split(b"\r\n", 1)[0].decode())
        )
    return body.decode()
