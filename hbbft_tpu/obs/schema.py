"""The stable JSONL event schema — one authoritative field table.

Schema v2: v1 (PR 1) plus the additive fleet-telemetry extensions —
cross-node trace context, the causal wire-join fields, the flight
recorder, and the per-hop commit-timeline events.  Consumed by
:mod:`hbbft_tpu.obs.report` and :mod:`hbbft_tpu.obs.timeline` (field
access), by :mod:`hbbft_tpu.analysis.rules.obs_schema` (call-site
lint), and by tests.

Every event row carries ``ev`` (the type) and ``t`` (seconds since
trace start) — those are added by :meth:`Recorder.event` itself and
are not listed per type.  A recorder with a node context additionally
stamps the trace-context triple on every row (:data:`TRACE_FIELDS`):
``tn`` (node id), ``ts`` (per-recorder monotonic event seq), ``te``
(current epoch, when known).  Those are reserved — emit sites must
never pass them explicitly (the ``obs-schema`` lint enforces it).

``required`` fields must appear at every emit site; ``optional``
fields may.  Event types marked ``open`` accept arbitrary extra
attributes (spans carry caller attrs).  Schema *minors* are additive:
consumers must tolerate unknown event types and unknown optional
fields from newer traces.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, FrozenSet

SCHEMA_VERSION = 2

#: Trace-context fields stamped by the Recorder itself (never by emit
#: sites): node id, monotonic event seq, current epoch.
TRACE_FIELDS: FrozenSet[str] = frozenset({"tn", "ts", "te"})


@dataclasses.dataclass(frozen=True)
class EventSpec:
    required: FrozenSet[str]
    optional: FrozenSet[str] = frozenset()
    open: bool = False  # arbitrary extra fields allowed

    @property
    def allowed(self) -> FrozenSet[str]:
        return self.required | self.optional


def _spec(required, optional=(), open=False) -> EventSpec:
    return EventSpec(frozenset(required), frozenset(optional), open)


EVENTS: Dict[str, EventSpec] = {
    # lifecycle (emitted by the Recorder itself)
    "trace_start": _spec({"schema", "wall_unix"}),
    "trace_end": _spec({"events", "dur"}),
    "counter": _spec({"name", "value"}),
    "hist": _spec(
        {"name", "count", "min", "p50", "p90", "max", "sum"}, {"p99"}
    ),
    # spans carry caller attributes — open by design
    "span": _spec({"name", "dur", "depth"}, open=True),
    # simulator message plane
    "msg_send": _spec({"src", "size", "vt", "kind"}),
    "msg_deliver": _spec({"src", "dst", "size", "vt", "kind"}),
    "msg_handle": _spec({"node", "vt", "wall", "size"}),
    # epoch rows
    "epoch_start": _spec({"epoch", "vt"}),
    "epoch_decide": _spec({"epoch", "node", "vt"}),
    "epoch": _spec(
        {"epoch", "min_time", "max_time", "txs", "msgs_per_node", "bytes_per_node"}
    ),
    "epoch_phases": _spec({"epoch", "phases", "shares", "coin_flips", "faults"}),
    # commit-latency arc (additive): speculative combine-first
    # decryption counters (hits = combined-check successes, misses =
    # fallbacks to per-share verification) and the per-epoch commit
    # latency the pipelined driver measures
    "spec_combine": _spec({"hits", "misses"}, {"epoch", "fallback_items"}),
    "commit_latency": _spec({"epoch", "latency_s"}, {"mode"}),
    # order-then-reveal (additive): the two observable commit events.
    # ``ordered_commit`` fires the moment ACS output pins the epoch's
    # ciphertext batch (seq = node-local commit sequence, outstanding =
    # ordered-but-unrevealed epochs incl. this one); ``reveal_lag``
    # fires when the plaintext batch finally reveals — ``lag_epochs``
    # is the deterministic epoch distance, ``lag_s`` the wall lag where
    # a driver can measure it
    "ordered_commit": _spec(
        {"node", "epoch"}, {"seq", "outstanding", "proposers"}
    ),
    "reveal_lag": _spec(
        {"epoch"}, {"lag_s", "lag_epochs", "node", "outstanding", "mode"}
    ),
    # crypto batching / device routing
    "flush": _spec(
        {"queued", "shipped", "real", "inline"},
        {"occupancy", "dur", "groups", "fallback_groups", "phases", "plane"},
    ),
    "device_op": _spec({"op", "k", "engine"}),
    # one XLA/Mosaic compile paid by the executable cache (a primed
    # ``.palexe`` cache run emits ZERO of these — the AOT acceptance
    # gate greps the trace for them)
    "compile": _spec({"name", "key", "wall"}),
    # fault attribution
    "fault": _spec({"fault", "node", "kind"}),
    # real TCP mesh wire plane (additive).  v2: ``node`` (the emitting
    # endpoint) + ``seq`` (the link sequence number) make a send on
    # node A joinable to the matching recv on node B even when both
    # stamp rows into one in-process recorder.
    "wire_send": _spec({"peer", "size"}, {"kind", "node", "seq"}),
    "wire_recv": _spec({"peer", "size"}, {"node", "seq"}),
    # adversarial scenario matrix (additive): one row per scenario run,
    # and one per completed fuzz surface
    "scenario": _spec(
        {"name", "ok", "n", "faults"}, {"epochs", "detail", "seed"}
    ),
    "fuzz_summary": _spec(
        {"surface", "cases", "failures"},
        {"decoded", "rejected", "delivered", "faults"},
    ),
    # static-analysis runs (additive): one row per badgerlint CLI run,
    # so lint results land on the same tracing plane as scenario /
    # fuzz_summary rows
    "lint_run": _spec(
        {"rules", "violations", "wall"},
        {"baselined", "errors", "counts", "paths", "changed"},
    ),
    # limbprove (additive): one row per kernel-range verification run —
    # proof obligations checked, how many proved, and the wall cost of
    # the jaxpr abstract interpretation
    "range_check": _spec({"obligations", "proved", "wall"}),
    # badgermc (additive): one row per bounded model-checking run —
    # states explored / deduplicated / DPOR-pruned, the exact naive
    # enumeration size the reduction is measured against, and the wall
    # cost of the schedule-space search
    "mc_run": _spec(
        {"explored", "deduped", "dpor_pruned", "wall"},
        {
            "naive",
            "reduction",
            "truncated",
            "probe_runs",
            "probe_actions",
            "shrink_replays",
            "config",
            "violation",
            "repro_path",
        },
    ),
    # serving gateway (additive): admission decisions, the client-side
    # commit-latency arc, and periodic queue-depth snapshots
    "gateway_admit": _spec({"tenant", "depth"}, {"client", "seq"}),
    "gateway_reject": _spec(
        {"tenant", "reason"}, {"client", "seq", "retry_after_ms"}
    ),
    "client_commit_latency": _spec(
        {"latency_s"}, {"tenant", "epoch", "client", "seq"}
    ),
    "queue_depth": _spec({"depth"}, {"pending"}),
    # 100k co-simulation (additive): one row per packed-sim epoch, and
    # one per WAN model bound to a network size
    "cosim_epoch": _spec(
        {"n", "epochs_per_s", "peak_rss"},
        {"epoch", "accepted", "coin_flips", "mesh_devices", "bytes_per_validator"},
    ),
    "wan_model": _spec({"distribution", "seed"}, {"zones", "n"}),
    # crash-recovery (additive): one row per resumed TCP link (how many
    # buffered frames were replayed vs dropped as already-delivered),
    # and one per plane that degraded to its fallback path (stager →
    # inline, device → host) — emitted at most once per degradation
    "wire_resume": _spec({"peer", "replayed", "dropped"}, {"recv_seq"}),
    "degrade": _spec({"plane", "reason"}, {"detail"}),
    # state-transfer (additive): one row per installed snapshot, one
    # per rejected provider/abort, one per future-epoch flood drop
    # burst, and one per live WAL compaction
    "st_transfer": _spec(
        {"peer", "from_epoch", "upto_epoch", "bytes"}, {"chunks", "retries"}
    ),
    "st_reject": _spec({"peer", "reason"}, {"epoch"}),
    "hb_future_drop": _spec({"node", "epoch"}, {"drops"}),
    "wal_compact": _spec({"dropped", "kept", "bytes"}),
    # fleet telemetry plane (schema v2, all additive) ------------------
    # one row per WAL record append — ``records`` is the log's
    # high-water mark, which the flight-recorder crash test joins
    # against the on-disk WAL after a SIGKILL
    "wal_append": _spec({"records"}, {"kind", "path"}),
    # one row per validated ObTrace piggyback received: the local
    # node's view of the peer's trace context (peer node id, peer
    # trace seq, peer epoch) — the cross-process causal join points
    "trace_link": _spec({"node", "peer", "seq"}, {"epoch"}),
    # per-hop commit timeline: gossip relay into the mesh, ACS
    # completion (decryption begins), and one node's committed batch
    "gossip_relay": _spec({"txs"}, {"depth", "node"}),
    "acs_done": _spec({"node", "epoch"}, {"proposers"}),
    "node_commit": _spec({"node", "epoch"}, {"txs"}),
    # flight recorder: one marker row per forced dump (written into
    # the dump file AND the live trace)
    "flight_dump": _spec({"reason", "events"}, {"node", "path", "dropped"}),
    # fleet metrics poller: one row per scrape attempt per node
    "metrics_scrape": _spec({"node", "up"}, {"families", "wall"}),
}
