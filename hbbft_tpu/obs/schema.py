"""The stable JSONL event schema — one authoritative field table.

Schema v1 (PR 1) with the additive v1 extensions from the static
-analysis PR (``wire_send`` / ``wire_recv`` for the real TCP mesh).
Consumed by :mod:`hbbft_tpu.obs.report` (field access), by
:mod:`hbbft_tpu.analysis.rules.obs_schema` (call-site lint), and by
tests.

Every event row carries ``ev`` (the type) and ``t`` (seconds since
trace start) — those are added by :meth:`Recorder.event` itself and
are not listed per type.  ``required`` fields must appear at every
emit site; ``optional`` fields may.  Event types marked ``open``
accept arbitrary extra attributes (spans carry caller attrs).
"""

from __future__ import annotations

import dataclasses
from typing import Dict, FrozenSet

SCHEMA_VERSION = 1


@dataclasses.dataclass(frozen=True)
class EventSpec:
    required: FrozenSet[str]
    optional: FrozenSet[str] = frozenset()
    open: bool = False  # arbitrary extra fields allowed

    @property
    def allowed(self) -> FrozenSet[str]:
        return self.required | self.optional


def _spec(required, optional=(), open=False) -> EventSpec:
    return EventSpec(frozenset(required), frozenset(optional), open)


EVENTS: Dict[str, EventSpec] = {
    # lifecycle (emitted by the Recorder itself)
    "trace_start": _spec({"schema", "wall_unix"}),
    "trace_end": _spec({"events", "dur"}),
    "counter": _spec({"name", "value"}),
    "hist": _spec({"name", "count", "min", "p50", "p90", "max", "sum"}),
    # spans carry caller attributes — open by design
    "span": _spec({"name", "dur", "depth"}, open=True),
    # simulator message plane
    "msg_send": _spec({"src", "size", "vt", "kind"}),
    "msg_deliver": _spec({"src", "dst", "size", "vt", "kind"}),
    "msg_handle": _spec({"node", "vt", "wall", "size"}),
    # epoch rows
    "epoch_start": _spec({"epoch", "vt"}),
    "epoch_decide": _spec({"epoch", "node", "vt"}),
    "epoch": _spec(
        {"epoch", "min_time", "max_time", "txs", "msgs_per_node", "bytes_per_node"}
    ),
    "epoch_phases": _spec({"epoch", "phases", "shares", "coin_flips", "faults"}),
    # commit-latency arc (additive): speculative combine-first
    # decryption counters (hits = combined-check successes, misses =
    # fallbacks to per-share verification) and the per-epoch commit
    # latency the pipelined driver measures
    "spec_combine": _spec({"hits", "misses"}, {"epoch", "fallback_items"}),
    "commit_latency": _spec({"epoch", "latency_s"}, {"mode"}),
    # crypto batching / device routing
    "flush": _spec(
        {"queued", "shipped", "real", "inline"},
        {"occupancy", "dur", "groups", "fallback_groups", "phases", "plane"},
    ),
    "device_op": _spec({"op", "k", "engine"}),
    # one XLA/Mosaic compile paid by the executable cache (a primed
    # ``.palexe`` cache run emits ZERO of these — the AOT acceptance
    # gate greps the trace for them)
    "compile": _spec({"name", "key", "wall"}),
    # fault attribution
    "fault": _spec({"fault", "node", "kind"}),
    # real TCP mesh wire plane (additive)
    "wire_send": _spec({"peer", "size"}, {"kind"}),
    "wire_recv": _spec({"peer", "size"}),
    # adversarial scenario matrix (additive): one row per scenario run,
    # and one per completed fuzz surface
    "scenario": _spec(
        {"name", "ok", "n", "faults"}, {"epochs", "detail", "seed"}
    ),
    "fuzz_summary": _spec(
        {"surface", "cases", "failures"},
        {"decoded", "rejected", "delivered", "faults"},
    ),
    # static-analysis runs (additive): one row per badgerlint CLI run,
    # so lint results land on the same tracing plane as scenario /
    # fuzz_summary rows
    "lint_run": _spec(
        {"rules", "violations", "wall"},
        {"baselined", "errors", "counts", "paths", "changed"},
    ),
    # serving gateway (additive): admission decisions, the client-side
    # commit-latency arc, and periodic queue-depth snapshots
    "gateway_admit": _spec({"tenant", "depth"}, {"client", "seq"}),
    "gateway_reject": _spec(
        {"tenant", "reason"}, {"client", "seq", "retry_after_ms"}
    ),
    "client_commit_latency": _spec({"latency_s"}, {"tenant", "epoch"}),
    "queue_depth": _spec({"depth"}, {"pending"}),
    # 100k co-simulation (additive): one row per packed-sim epoch, and
    # one per WAN model bound to a network size
    "cosim_epoch": _spec(
        {"n", "epochs_per_s", "peak_rss"},
        {"epoch", "accepted", "coin_flips", "mesh_devices", "bytes_per_validator"},
    ),
    "wan_model": _spec({"distribution", "seed"}, {"zones", "n"}),
    # crash-recovery (additive): one row per resumed TCP link (how many
    # buffered frames were replayed vs dropped as already-delivered),
    # and one per plane that degraded to its fallback path (stager →
    # inline, device → host) — emitted at most once per degradation
    "wire_resume": _spec({"peer", "replayed", "dropped"}, {"recv_seq"}),
    "degrade": _spec({"plane", "reason"}, {"detail"}),
    # state-transfer (additive): one row per installed snapshot, one
    # per rejected provider/abort, one per future-epoch flood drop
    # burst, and one per live WAL compaction
    "st_transfer": _spec(
        {"peer", "from_epoch", "upto_epoch", "bytes"}, {"chunks", "retries"}
    ),
    "st_reject": _spec({"peer", "reason"}, {"epoch"}),
    "hb_future_drop": _spec({"node", "epoch"}, {"drops"}),
    "wal_compact": _spec({"dropped", "kept", "bytes"}),
}
