"""Trace report CLI — summarize JSONL traces written by the recorder.

    python -m hbbft_tpu.obs.report trace.jsonl
    python -m hbbft_tpu.obs.report node0.jsonl node1.jsonl --json

Multiple trace files (one per node, flight dumps, fleet JSONL) are
merged into one summary.  Unknown event types — traces from a newer
schema minor — are tolerated and surfaced under ``unknown_events``,
never raised on.

Prints, from the stable event schema (:mod:`hbbft_tpu.obs.recorder`):

- epoch-latency distribution (the reference table's Min/MaxTime,
  aggregated),
- per-node message histograms (deliveries and bytes),
- crypto-batch occupancy (queued vs shipped per flush, phase walls),
- device-op routing counts (which engine each MSM size landed on),
- fault summaries per kind and per node,
- span aggregates and final counter/histogram values.

``--json`` emits the same summary as one machine-readable JSON object
(what the tests consume).
"""

from __future__ import annotations

import argparse
import json
import sys
from collections import defaultdict
from typing import Any, Dict, List

from .recorder import _pct


def load_events(path: str) -> List[Dict[str, Any]]:
    """Parse a JSONL trace; unparsable lines are counted, not fatal (a
    killed run may leave a torn final line)."""
    events: List[Dict[str, Any]] = []
    bad = 0
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                ev = json.loads(line)
            except ValueError:
                bad += 1
                continue
            if isinstance(ev, dict) and "ev" in ev:
                events.append(ev)
            else:
                bad += 1
    if bad:
        events.append({"ev": "_parse_errors", "t": 0.0, "count": bad})
    return events


def load_many(paths: List[str]) -> List[Dict[str, Any]]:
    """Concatenate several traces (per-node files, flight dumps) into
    one event list for :func:`summarize`."""
    events: List[Dict[str, Any]] = []
    for path in paths:
        events.extend(load_events(path))
    return events


def _dist(vals: List[float]) -> Dict[str, float]:
    vals = sorted(vals)
    if not vals:
        # a trace can legitimately carry rows missing an optional
        # field — an empty distribution must summarize, not raise
        return {"count": 0, "min": 0.0, "p50": 0.0, "p90": 0.0, "max": 0.0, "mean": 0.0}
    return {
        "count": len(vals),
        "min": vals[0],
        "p50": _pct(vals, 0.50),
        "p90": _pct(vals, 0.90),
        "max": vals[-1],
        "mean": sum(vals) / len(vals),
    }


def summarize(events: List[Dict[str, Any]]) -> Dict[str, Any]:
    """Aggregate a parsed event list into the report structure."""
    by_ev: Dict[str, List[Dict[str, Any]]] = defaultdict(list)
    for e in events:
        by_ev[e["ev"]].append(e)

    out: Dict[str, Any] = {
        "schema": (by_ev["trace_start"][0].get("schema") if by_ev["trace_start"] else None),
        "events": len(events),
        "duration_s": (by_ev["trace_end"][-1].get("dur") if by_ev["trace_end"] else None),
    }

    # -- forward compatibility ---------------------------------------------
    # schema minors are additive: a newer trace may carry event types
    # this reader doesn't know — count them, don't choke on them
    from .schema import EVENTS as _KNOWN

    unknown = {
        ev: len(rows)
        for ev, rows in by_ev.items()
        if ev not in _KNOWN and not ev.startswith("_")
    }
    if unknown:
        out["unknown_events"] = dict(sorted(unknown.items()))

    # -- epochs -------------------------------------------------------------
    rows = by_ev["epoch"]
    if rows:
        out["epochs"] = {
            "count": len(rows),
            "txs": sum(r.get("txs", 0) for r in rows),
            "latency": _dist([r["max_time"] for r in rows if "max_time" in r]),
            "min_latency": _dist([r["min_time"] for r in rows if "min_time" in r]),
            "rows": rows,
        }
    phases_rows = by_ev["epoch_phases"]
    if phases_rows:
        totals: Dict[str, float] = defaultdict(float)
        for r in phases_rows:
            for k, v in (r.get("phases") or {}).items():
                totals[k] += float(v)
        out["epoch_phases"] = {
            "count": len(phases_rows),
            "phase_totals_s": dict(sorted(totals.items())),
        }

    # -- messages -----------------------------------------------------------
    sends = by_ev["msg_send"]
    delivers = by_ev["msg_deliver"]
    handles = by_ev["msg_handle"]
    if sends or delivers or handles:
        per_node: Dict[str, Dict[str, int]] = defaultdict(
            lambda: {"msgs": 0, "bytes": 0}
        )
        for d in delivers:
            node = per_node[str(d.get("dst"))]
            node["msgs"] += 1
            node["bytes"] += int(d.get("size", 0))
        out["messages"] = {
            "sends": len(sends),
            "broadcast_sends": sum(1 for s in sends if s.get("kind") == "all"),
            "delivered": len(delivers),
            "handled": len(handles),
            "bytes_sent": sum(int(s.get("size", 0)) for s in sends),
            "bytes_delivered": sum(int(d.get("size", 0)) for d in delivers),
            "per_node": dict(sorted(per_node.items())),
        }
        if handles:
            out["messages"]["handle_wall"] = _dist(
                [float(h.get("wall", 0.0)) for h in handles]
            )

    # -- crypto flushes -----------------------------------------------------
    flushes = by_ev["flush"]
    if flushes:
        queued = sum(int(f.get("queued", 0)) for f in flushes)
        shipped = sum(int(f.get("shipped", 0)) for f in flushes)
        phase_totals: Dict[str, float] = defaultdict(float)
        for f in flushes:
            for k, v in (f.get("phases") or {}).items():
                phase_totals[k] += float(v)
        out["flushes"] = {
            "count": len(flushes),
            "queued": queued,
            "shipped": shipped,
            "occupancy": round(shipped / queued, 4) if queued else None,
            "batch": _dist([float(f.get("shipped", 0)) for f in flushes]),
            "dur": _dist([float(f.get("dur", 0.0)) for f in flushes]),
            "phase_totals_s": dict(sorted(phase_totals.items())),
        }

    # -- device ops ---------------------------------------------------------
    ops = by_ev["device_op"]
    if ops:
        per: Dict[str, Dict[str, Any]] = {}
        for o in ops:
            key = "%s/%s" % (o.get("op"), o.get("engine"))
            slot = per.setdefault(key, {"count": 0, "k": []})
            slot["count"] += 1
            slot["k"].append(int(o.get("k", 0)))
        out["device_ops"] = {
            key: {"count": s["count"], "k": _dist([float(x) for x in s["k"]])}
            for key, s in sorted(per.items())
        }

    # -- faults -------------------------------------------------------------
    faults = by_ev["fault"]
    if faults:
        by_kind: Dict[str, int] = defaultdict(int)
        by_node: Dict[str, int] = defaultdict(int)
        for f in faults:
            by_kind[str(f.get("kind"))] += 1
            by_node[str(f.get("node"))] += 1
        out["faults"] = {
            "count": len(faults),
            "by_kind": dict(sorted(by_kind.items())),
            "by_node": dict(sorted(by_node.items())),
        }

    # -- spans / counters / hists ------------------------------------------
    spans = by_ev["span"]
    if spans:
        agg: Dict[str, List[float]] = defaultdict(list)
        for s in spans:
            agg[str(s.get("name"))].append(float(s.get("dur", 0.0)))
        out["spans"] = {
            name: {"count": len(durs), "total_s": sum(durs), "dur": _dist(durs)}
            for name, durs in sorted(agg.items())
        }
    if by_ev["counter"]:
        out["counters"] = {
            str(c.get("name")): c.get("value") for c in by_ev["counter"]
        }
    if by_ev["hist"]:
        out["hists"] = {
            str(h.get("name")): {
                k: h.get(k) for k in ("count", "min", "p50", "p90", "max", "sum")
            }
            for h in by_ev["hist"]
        }
    return out


# ---------------------------------------------------------------------------
# Text rendering
# ---------------------------------------------------------------------------


def _fmt_dist(d: Dict[str, float], scale: float = 1.0, unit: str = "") -> str:
    return "min %.3f%s  p50 %.3f%s  p90 %.3f%s  max %.3f%s" % (
        d["min"] * scale,
        unit,
        d["p50"] * scale,
        unit,
        d["p90"] * scale,
        unit,
        d["max"] * scale,
        unit,
    )


def _bar(n: int, peak: int, width: int = 24) -> str:
    if peak <= 0:
        return ""
    return "#" * max(1, round(width * n / peak)) if n else ""


def render(s: Dict[str, Any]) -> str:
    lines: List[str] = []
    add = lines.append
    add(
        "trace: %d events%s (schema v%s)"
        % (
            s.get("events", 0),
            (" over %.3fs" % s["duration_s"]) if s.get("duration_s") else "",
            s.get("schema"),
        )
    )
    if s.get("unknown_events"):
        add(
            "  unknown event types (newer schema minor): "
            + ", ".join(
                "%s x%d" % (ev, n) for ev, n in s["unknown_events"].items()
            )
        )

    ep = s.get("epochs")
    if ep:
        add("")
        add("Epoch latency (%d epochs, %d txs)" % (ep["count"], ep["txs"]))
        add("  max_time: " + _fmt_dist(ep["latency"], 1000.0, "ms"))
        add("  min_time: " + _fmt_dist(ep["min_latency"], 1000.0, "ms"))
    eph = s.get("epoch_phases")
    if eph:
        add("")
        add("Epoch phases (%d epochs, wall seconds, summed)" % eph["count"])
        for k, v in sorted(
            eph["phase_totals_s"].items(), key=lambda kv: -kv[1]
        )[:12]:
            add("  %-24s %8.3fs" % (k, v))

    msg = s.get("messages")
    if msg:
        add("")
        add(
            "Messages: %d sent (%d broadcast), %d delivered, %d handled, %d B delivered"
            % (
                msg["sends"],
                msg["broadcast_sends"],
                msg["delivered"],
                msg["handled"],
                msg["bytes_delivered"],
            )
        )
        per = msg["per_node"]
        if per:
            peak = max(v["msgs"] for v in per.values())
            add("  per-node deliveries:")
            for node, v in per.items():
                add(
                    "    %-8s %7d msgs %10d B  %s"
                    % (node, v["msgs"], v["bytes"], _bar(v["msgs"], peak))
                )

    fl = s.get("flushes")
    if fl:
        add("")
        add(
            "Crypto flushes: %d flushes, %d/%d shipped/queued (occupancy %s)"
            % (
                fl["count"],
                fl["shipped"],
                fl["queued"],
                ("%.1f%%" % (100 * fl["occupancy"])) if fl["occupancy"] is not None else "n/a",
            )
        )
        add("  batch size: " + _fmt_dist(fl["batch"]))
        add("  flush wall: " + _fmt_dist(fl["dur"], 1000.0, "ms"))
        if fl["phase_totals_s"]:
            add("  phase walls (summed):")
            for k, v in sorted(
                fl["phase_totals_s"].items(), key=lambda kv: -kv[1]
            ):
                add("    %-12s %8.3fs" % (k, v))

    dev = s.get("device_ops")
    if dev:
        add("")
        add("Device ops (op/engine):")
        for key, v in dev.items():
            add(
                "  %-24s %6d calls  k p50 %d"
                % (key, v["count"], int(v["k"]["p50"]))
            )

    fa = s.get("faults")
    if fa:
        add("")
        add("Faults: %d attributed" % fa["count"])
        for kind, n in sorted(fa["by_kind"].items(), key=lambda kv: -kv[1]):
            add("  %-40s %6d" % (kind, n))
        add("  by node: " + ", ".join(
            "%s: %d" % (node, n) for node, n in fa["by_node"].items()
        ))

    sp = s.get("spans")
    if sp:
        add("")
        add("Spans:")
        for name, v in sorted(sp.items(), key=lambda kv: -kv[1]["total_s"])[:16]:
            add(
                "  %-32s %6d calls %9.3fs total  p50 %.3fms"
                % (name, v["count"], v["total_s"], v["dur"]["p50"] * 1000)
            )

    if s.get("counters"):
        add("")
        add("Counters:")
        for name, v in s["counters"].items():
            add("  %-40s %10s" % (name, v))
    if s.get("hists"):
        add("")
        add("Histograms:")
        for name, h in s["hists"].items():
            add(
                "  %-32s n=%-6d min %.4g  p50 %.4g  p90 %.4g  max %.4g"
                % (name, h["count"], h["min"], h["p50"], h["p90"], h["max"])
            )
    return "\n".join(lines)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m hbbft_tpu.obs.report", description=__doc__
    )
    p.add_argument(
        "trace",
        nargs="+",
        help="JSONL trace file(s) written by the recorder (merged)",
    )
    p.add_argument(
        "--json", action="store_true", help="emit the summary as one JSON object"
    )
    args = p.parse_args(argv)
    events = load_many(args.trace)
    summary = summarize(events)
    try:
        if args.json:
            # rows are bulky; the JSON consumer can re-derive them from
            # the trace
            summary.get("epochs", {}).pop("rows", None)
            print(json.dumps(summary, indent=2, sort_keys=True))
        else:
            print(render(summary))
    except BrokenPipeError:
        # `report trace.jsonl | head` is a normal way to skim a summary
        sys.stderr.close()
    return 0


if __name__ == "__main__":
    sys.exit(main())
