"""Fixed-prime big-integer limb arithmetic as JAX kernels.

The device-side equivalent of the base-field layer of the reference's
``pairing`` crate (``Cargo.toml:22``) — the foundation every batched
BLS12-381 kernel builds on (share verify/combine MSMs of
``common_coin.rs:142-207`` and ``honey_badger.rs:422-444``).

Representation (chosen for the TPU's int32 vector lanes):

- an element is a vector of ``L = 38`` limbs of ``LIMB_BITS = 11`` bits,
  little-endian, stored in ``int32`` — 418 bits of capacity, 37 bits of
  headroom above the 381-bit prime;
- limbs are kept *redundant*: the invariant is ``limb < 2^12`` (one
  slack bit), so a 38-term schoolbook convolution sum is bounded by
  ``38·(2^12)^2 < 2^29.3 < 2^31`` — no multiplication or accumulation
  ever overflows int32, and no double-width accumulator is needed
  (TPUs have no 64-bit integer datapath);
- values are *lazily reduced*: a limb vector represents a value
  ``< 2^408`` merely congruent to the canonical residue mod p.
  Reduction folds every limb at index ≥ B = 37 back via a precomputed
  ``2^(11·(B+i)) mod p`` table (a tiny matmul).  The fold boundary sits
  26 bits above p, so one (parallel-carry, fold) round already lands
  any product back under ``2^408``, and the topmost limb of the stored
  38-limb form is provably zero — fully branchless, scan-free,
  batch-friendly reduction with no data-dependent control flow.
- ``canon()`` produces the unique canonical form (for equality tests
  and host export) via a fixed conditional-subtraction ladder; it is
  off the hot path.

Everything is shape-polymorphic over leading batch dimensions: all ops
take ``[..., L]`` int32 arrays and broadcast, so ``vmap`` is never
required (but composes fine).
"""

from __future__ import annotations

import functools
from typing import List, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

LIMB_BITS = 11
LIMB_MASK = (1 << LIMB_BITS) - 1


def int_to_limbs(x: int, nlimbs: int) -> np.ndarray:
    """Host-side: python int → little-endian limb vector."""
    if x < 0:
        raise ValueError("negative value")
    out = np.zeros(nlimbs, dtype=np.int32)
    for i in range(nlimbs):
        out[i] = x & LIMB_MASK
        x >>= LIMB_BITS
    if x:
        raise ValueError("value does not fit in limbs")
    return out


def limbs_to_int(limbs) -> int:
    """Host-side: limb vector → python int (limbs may be unnormalised)."""
    arr = np.asarray(limbs)
    acc = 0
    for i in range(arr.shape[-1] - 1, -1, -1):
        acc = (acc << LIMB_BITS) + int(arr[..., i])
    return acc


def _carry_round(x: jnp.ndarray) -> jnp.ndarray:
    """One parallel carry round: [..., W] → [..., W+1].

    Works on negative limbs too (arithmetic right shift = floor
    division), as needed transiently inside subtraction.
    """
    lo = jnp.bitwise_and(x, LIMB_MASK)
    hi = jnp.right_shift(x, LIMB_BITS)
    zpad = jnp.zeros(x.shape[:-1] + (1,), dtype=x.dtype)
    return jnp.concatenate([lo, zpad], axis=-1) + jnp.concatenate(
        [zpad, hi], axis=-1
    )


def _conv(a: jnp.ndarray, b: jnp.ndarray, L: int) -> jnp.ndarray:
    """Schoolbook polynomial product [..., L]×[..., L] → [..., 2L−1]
    as L statically shifted multiply-adds (XLA fuses the stack+sum)."""
    rows = a[..., :, None] * b[..., None, :]  # [..., L, L] ≤ 2^24 each
    shifted = [
        jnp.pad(rows[..., i, :], [(0, 0)] * (rows.ndim - 2) + [(i, L - 1 - i)])
        for i in range(L)
    ]
    return sum(shifted)


class ModField:
    """Limb-vector arithmetic mod a fixed prime ``p``.

    One instance per prime (``fq()`` for the BLS12-381 base field); all
    methods are pure jnp functions suitable for use inside ``jit``.
    """

    def __init__(self, p: int, nlimbs: int):
        self.p = p
        self.L = L = nlimbs
        self.B = B = nlimbs - 1  # fold boundary: stored value < 2^(11·B+1)
        self.bits = LIMB_BITS * nlimbs
        if p.bit_length() > LIMB_BITS * B - 24:
            raise ValueError("need ≥24 bits of headroom above p for lazy fold")

        # fold[i] = limbs of 2^(11·(B+i)) mod p — reduces limb B+i.
        # Sized for the widest intermediate (2L−1 product + carry limbs).
        # All constants are HOST numpy arrays on purpose: captured jnp
        # device arrays become hidden const-inputs of any jit that
        # closes over them, which breaks executable serialization (the
        # reloaded executable expects inputs the caller no longer has —
        # measured r4 on the tree-reduction cache).  np constants are
        # inlined into the HLO at trace time instead, making every
        # compiled program self-contained.
        nfold = L + 5
        self.fold = np.stack(
            [
                int_to_limbs(pow(2, LIMB_BITS * (B + i), p), B)
                for i in range(nfold)
            ]
        )  # [nfold, B]
        # Subtraction pad: smallest multiple of p ≥ 2^(11·B+2), covering
        # any invariant-respecting minuend; a + pad − b is non-negative.
        pad = ((1 << (LIMB_BITS * B + 2)) // p + 1) * p
        self.sub_pad = int_to_limbs(pad, L + 1)
        # canon(): conditional subtraction of (2^k)·p, largest k first.
        ks: List[int] = []
        k = 1
        while k * p < (1 << (self.bits + 2)):
            ks.append(k)
            k <<= 1
        self.canon_steps = np.stack(
            [int_to_limbs(k * p, L + 1) for k in reversed(ks)]
        )  # [n_steps, L+1]
        self.zero = np.zeros(L, dtype=np.int32)
        self.one = int_to_limbs(1, L)

    # -- host conversion ---------------------------------------------------

    def to_limbs(self, x: int) -> np.ndarray:
        return int_to_limbs(x % self.p, self.L)

    def to_limbs_batch(self, xs: Sequence[int]) -> np.ndarray:
        return np.stack([self.to_limbs(x) for x in xs]) if len(xs) else np.zeros(
            (0, self.L), dtype=np.int32
        )

    def from_limbs(self, limbs) -> int:
        return limbs_to_int(limbs) % self.p

    # -- normalisation -----------------------------------------------------

    def _fold_high(self, x: jnp.ndarray) -> jnp.ndarray:
        """[..., W] (W > B, limbs < 2^12) → [..., B]: fold every limb at
        index ≥ B back via its 2^(11·(B+i)) mod p table row."""
        W = x.shape[-1]
        high = x[..., self.B :]
        folded = jnp.einsum(
            "...h,hl->...l",
            high,
            self.fold[: W - self.B],
            preferred_element_type=jnp.int32,
        )
        return x[..., : self.B] + folded

    def normalize(self, wide: jnp.ndarray, rounds: int = 2) -> jnp.ndarray:
        """[..., W] limbs (W ≥ B, any int32 magnitudes, non-negative
        value) → [..., L] limbs < 2^12 each, value < 2^408.

        Each round: two parallel carry passes (limbs → < 2^12 + ε) then
        a fold of every limb ≥ B = L−1.  The low part is ≤ 1.02·2^407
        and the fold adds ≤ (#high)·2^12·p < 2^399, so a single round
        already lands under 2^408; the second is safety margin.  The
        final two carry passes then provably cannot ripple past limb
        L−1 (a limb at index L would imply value ≥ 2^418), making the
        closing slice exact.
        """
        x = wide
        for _ in range(rounds):
            x = _carry_round(_carry_round(x))
            if x.shape[-1] > self.B:
                x = self._fold_high(x)
        x = _carry_round(_carry_round(x))
        return x[..., : self.L]

    # -- ring ops ----------------------------------------------------------

    def add(self, a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
        return self.normalize(a + b)

    def sub(self, a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
        a, b = jnp.broadcast_arrays(a, b)
        zpad = jnp.zeros(a.shape[:-1] + (1,), dtype=jnp.int32)
        wide = (
            jnp.concatenate([a, zpad], axis=-1)
            + self.sub_pad
            - jnp.concatenate([b, zpad], axis=-1)
        )
        return self.normalize(wide)

    def neg(self, a: jnp.ndarray) -> jnp.ndarray:
        return self.sub(jnp.broadcast_to(self.zero, a.shape), a)

    def mul(self, a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
        a, b = jnp.broadcast_arrays(a, b)
        return self.normalize(_conv(a, b, self.L))

    def sq(self, a: jnp.ndarray) -> jnp.ndarray:
        return self.mul(a, a)

    def mul_small(self, a: jnp.ndarray, k: int) -> jnp.ndarray:
        """Multiply by a small non-negative int (k·2^12 must stay well
        inside int32, i.e. k ≤ ~2^17)."""
        return self.normalize(a * k)

    # -- canonical form (off the hot path) ---------------------------------

    def canon(self, a: jnp.ndarray) -> jnp.ndarray:
        """Unique canonical residue in [0, p): conditional-subtraction
        ladder over (2^k)·p, largest first.  Needs exact borrow
        propagation, done with a ``lax.scan`` along the limb axis."""
        zpad = jnp.zeros(a.shape[:-1] + (1,), dtype=jnp.int32)
        x = jnp.concatenate([a, zpad], axis=-1)  # [..., L+1]

        def cond_sub(x, kp):
            diff = jnp.moveaxis(x - kp, -1, 0)

            def step(borrow, d):
                t = d + borrow
                return t >> LIMB_BITS, t & LIMB_MASK

            borrow, limbs = jax.lax.scan(
                step, jnp.zeros_like(diff[0]), diff
            )
            limbs = jnp.moveaxis(limbs, 0, -1)
            keep = (borrow < 0)[..., None]  # underflow → keep x
            return jnp.where(keep, x, limbs), None

        # First make limbs exact (the ladder compares bit patterns).
        x = jnp.moveaxis(x, -1, 0)

        def carry_step(c, xi):
            t = xi + c
            return t >> LIMB_BITS, t & LIMB_MASK

        _, xex = jax.lax.scan(carry_step, jnp.zeros_like(x[0]), x)
        x = jnp.moveaxis(xex, 0, -1)
        x, _ = jax.lax.scan(cond_sub, x, self.canon_steps)
        return x[..., : self.L]

    def eq(self, a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
        """Batched equality mod p → bool[...]."""
        return jnp.all(self.canon(a) == self.canon(b), axis=-1)

    def is_zero(self, a: jnp.ndarray) -> jnp.ndarray:
        return jnp.all(self.canon(a) == 0, axis=-1)


# ---------------------------------------------------------------------------
# The BLS12-381 base field instance
# ---------------------------------------------------------------------------

P = 0x1A0111EA397FE69A4B1BA7B6434BACD764774B84F38512BF6730D2A0F6B0F6241EABFFFEB153FFFFB9FEFFFFFFFFAAAB
R = 0x73EDA753299D7D483339D80809A1D80553BDA402FFFE5BFEFFFFFFFF00000001

FQ_LIMBS = 38  # 418-bit capacity for the 381-bit field (37 bits headroom)


@functools.lru_cache(maxsize=None)
def fq() -> ModField:
    return ModField(P, FQ_LIMBS)


def scalar_to_bits(k: int, nbits: int = 255) -> np.ndarray:
    """Host-side: scalar (reduced mod r) → msb-first bit vector for the
    fixed-length double-and-add scan (protocol scalars live in Fr)."""
    k %= R
    return np.asarray(
        [(k >> (nbits - 1 - i)) & 1 for i in range(nbits)], dtype=np.int32
    )


def scalars_to_be_bytes(ks: Sequence[int], nbytes: int) -> np.ndarray:
    """[K, nbytes] uint8, big-endian, reduced mod r — the one home for
    scalar byte marshalling (shared by the bit decomposition below and
    the packed-wire transfer path, ``packed_msm.py``)."""
    if not len(ks):
        return np.zeros((0, nbytes), dtype=np.uint8)
    return np.frombuffer(
        b"".join((int(k) % R).to_bytes(nbytes, "big") for k in ks),
        dtype=np.uint8,
    ).reshape(len(ks), nbytes)


def scalars_to_bits(ks: Sequence[int], nbits: int = 255) -> np.ndarray:
    """Vectorized batch of :func:`scalar_to_bits`: ``to_bytes`` (C) +
    one ``np.unpackbits`` instead of a Python loop per bit — the per-bit
    loop was ~40% of a 262k-point flush's wall clock."""
    if not len(ks):
        return np.zeros((0, nbits), dtype=np.int32)
    nbytes = (nbits + 7) // 8
    buf = scalars_to_be_bytes(ks, nbytes)
    bits = np.unpackbits(buf, axis=1)  # msb-first
    return bits[:, nbytes * 8 - nbits :].astype(np.int32)


def ints_to_limbs_batch(xs: Sequence[int], nlimbs: int) -> np.ndarray:
    """Vectorized :func:`int_to_limbs` over a batch: little-endian
    bytes (C) + one ``np.unpackbits`` + a bit-weight matmul."""
    n = len(xs)
    if not n:
        return np.zeros((0, nlimbs), dtype=np.int32)
    nbytes = (nlimbs * LIMB_BITS + 7) // 8
    buf = np.frombuffer(
        b"".join(int(x).to_bytes(nbytes, "little") for x in xs),
        dtype=np.uint8,
    ).reshape(n, nbytes)
    bits = np.unpackbits(buf, axis=1, bitorder="little")[
        :, : nlimbs * LIMB_BITS
    ]
    w = (1 << np.arange(LIMB_BITS, dtype=np.int32)).astype(np.int32)
    return (
        bits.reshape(n, nlimbs, LIMB_BITS).astype(np.int32) @ w
    ).astype(np.int32)


# ---------------------------------------------------------------------------
# limbprove registry.  analysis/rangecheck.py traces these entry points
# and proves the redundant-limb invariant (|limb| <= 2**(LIMB_BITS+1)-1
# on every boundary, no int32 intermediate overflow).  ops must not
# import analysis (layering), so the builder receives the rangecheck
# module as its toolbox argument.


def _range_specs(rc):
    f = fq()
    L = FQ_LIMBS
    # Magnitude bound on limbs at op boundaries.  sub() goes through a
    # transiently-negative representation, so the invariant is
    # symmetric: |limb| <= 2**(LIMB_BITS+1) - 1.  Expressed through
    # LIMB_BITS so a width change re-derives every obligation.
    bound = (1 << (LIMB_BITS + 1)) - 1
    el = rc.arg((2, L), "int32", -bound, bound)
    inv = dict(out_lo=-bound, out_hi=bound)
    return [
        rc.KernelSpec("limbs.add", lambda a, b: f.add(a, b), (el, el), **inv),
        rc.KernelSpec("limbs.sub", lambda a, b: f.sub(a, b), (el, el), **inv),
        rc.KernelSpec("limbs.neg", lambda a: f.neg(a), (el,), **inv),
        rc.KernelSpec("limbs.mul", lambda a, b: f.mul(a, b), (el, el), **inv),
        rc.KernelSpec("limbs.sq", lambda a: f.sq(a), (el,), **inv),
        rc.KernelSpec(
            "limbs.mul_small",
            lambda a: f.mul_small(a, (1 << 17) - 1),
            (el,),
            **inv,
        ),
        rc.KernelSpec(
            "limbs.canon",
            lambda a: f.canon(a),
            (rc.arg((2, L), "int32", 0, bound),),
            out_lo=0,
            out_hi=LIMB_MASK,
        ),
    ]


RANGE_SPECS = dict(
    module="ops/limbs.py",
    covers=("_fold_high",),
    specs=_range_specs,
)
