"""Batched BLS12-381 group arithmetic — branchless, jit-compatible.

Device-side counterpart of ``hbbft_tpu/crypto/curve.py`` (which replaces
the group layer of the reference's ``pairing`` crate, ``Cargo.toml:22``).
These kernels execute the MSMs at the heart of every protocol round:
share-verify random linear combinations (``common_coin.rs:149-161``,
``honey_badger.rs:422-444``) and Lagrange share combining
(``common_coin.rs:183-207``, ``honey_badger.rs:340``).

Design choices for TPU:

- **Complete addition formulas** (Renes–Costello–Batina 2015, Alg. 7
  for a = 0) in homogeneous projective coordinates: one formula valid
  for *all* inputs — doubling, mixed, identity — so scalar-mul scans
  and MSM trees need no branches, no equality tests, no special cases.
  Identity is (0 : 1 : 0).
- **One generic template** instantiated over Fq (G1) and Fq2 (G2), the
  same structure as the host path's ``_jacobian_ops`` — the two groups
  cannot drift apart.
- Points are int32 limb tensors: G1 ``[..., 3, L]``, G2 ``[..., 3, 2, L]``
  (X, Y, Z along axis −2); all ops broadcast over leading batch dims.
- Scalar multiplication is a fixed 255-iteration left-to-right
  double-and-add ``lax.scan`` with `where`-masked adds (no
  data-dependent control flow); MSM reduces the batch with a log₂ tree
  of complete adds, padding with the identity.

Bit-identity with the host path is exact: both reduce to the same
canonical affine coordinates.
"""

from __future__ import annotations

import functools
from typing import Any, Callable, NamedTuple, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from . import limbs as LB

# ---------------------------------------------------------------------------
# Field adaptors: Fq and Fq2 over limb tensors
# ---------------------------------------------------------------------------


class _FieldOps(NamedTuple):
    """Minimal field interface the point template needs."""

    add: Callable
    sub: Callable
    mul: Callable
    mul_b3: Callable  # multiply by 3·b of the curve
    zero: Callable[[], jnp.ndarray]
    one: Callable[[], jnp.ndarray]
    # element axes count (1 for Fq → [L]; 2 for Fq2 → [2, L])
    el_ndim: int


def _fq_ops() -> _FieldOps:
    f = LB.fq()
    return _FieldOps(
        add=f.add,
        sub=f.sub,
        mul=f.mul,
        mul_b3=lambda a: f.mul_small(a, 12),  # 3·b, b = 4
        zero=lambda: f.zero,
        one=lambda: f.one,
        el_ndim=1,
    )


def _fq2_ops() -> _FieldOps:
    """Fq2 = Fq[u]/(u²+1); elements are [..., 2, L] limb tensors."""
    f = LB.fq()

    def add(a, b):
        return f.add(a, b)  # limb add broadcasts over the u-axis

    def sub(a, b):
        return f.sub(a, b)

    def mul(a, b):
        a0, a1 = a[..., 0, :], a[..., 1, :]
        b0, b1 = b[..., 0, :], b[..., 1, :]
        t0 = f.mul(a0, b0)
        t1 = f.mul(a1, b1)
        # Karatsuba: a0b1 + a1b0 = (a0+a1)(b0+b1) − t0 − t1
        cross = f.sub(f.sub(f.mul(f.add(a0, a1), f.add(b0, b1)), t0), t1)
        return jnp.stack([f.sub(t0, t1), cross], axis=-2)

    def mul_b3(a):
        # 3·b with b = 4(1+u): 12·(a0 − a1) + 12·(a0 + a1)·u
        a0, a1 = a[..., 0, :], a[..., 1, :]
        return jnp.stack(
            [f.mul_small(f.sub(a0, a1), 12), f.mul_small(f.add(a0, a1), 12)],
            axis=-2,
        )

    def zero():
        return jnp.stack([f.zero, f.zero])

    def one():
        return jnp.stack([f.one, f.zero])

    return _FieldOps(add=add, sub=sub, mul=mul, mul_b3=mul_b3, zero=zero, one=one, el_ndim=2)


# ---------------------------------------------------------------------------
# Complete point addition (Renes–Costello–Batina Alg. 7, a = 0)
# ---------------------------------------------------------------------------


class PointKernel:
    """Branchless projective point ops over an abstract field."""

    def __init__(self, field: _FieldOps):
        self.f = field

    # points: [..., 3, *el] with X = p[..., 0, ...], etc.

    def identity(self, batch_shape: Tuple[int, ...] = ()) -> jnp.ndarray:
        pt = jnp.stack([self.f.zero(), self.f.one(), self.f.zero()])
        return jnp.broadcast_to(pt, batch_shape + pt.shape)

    def add(self, p: jnp.ndarray, q: jnp.ndarray) -> jnp.ndarray:
        """Complete addition: valid for every (p, q) incl. p == q and
        identities.  RCB 2015 Algorithm 7 (a = 0, b3 = 3·b)."""
        f = self.f
        ax = -1 - f.el_ndim  # the X/Y/Z axis
        X1, Y1, Z1 = (
            jnp.take(p, 0, axis=ax),
            jnp.take(p, 1, axis=ax),
            jnp.take(p, 2, axis=ax),
        )
        X2, Y2, Z2 = (
            jnp.take(q, 0, axis=ax),
            jnp.take(q, 1, axis=ax),
            jnp.take(q, 2, axis=ax),
        )
        t0 = f.mul(X1, X2)
        t1 = f.mul(Y1, Y2)
        t2 = f.mul(Z1, Z2)
        t3 = f.mul(f.add(X1, Y1), f.add(X2, Y2))
        t3 = f.sub(t3, f.add(t0, t1))
        t4 = f.mul(f.add(Y1, Z1), f.add(Y2, Z2))
        t4 = f.sub(t4, f.add(t1, t2))
        X3 = f.mul(f.add(X1, Z1), f.add(X2, Z2))
        Y3 = f.sub(X3, f.add(t0, t2))
        X3 = f.add(t0, t0)
        t0 = f.add(X3, t0)
        t2 = f.mul_b3(t2)
        Z3 = f.add(t1, t2)
        t1 = f.sub(t1, t2)
        Y3 = f.mul_b3(Y3)
        X3 = f.sub(f.mul(t3, t1), f.mul(t4, Y3))
        Y3 = f.add(f.mul(t1, Z3), f.mul(Y3, t0))
        Z3 = f.add(f.mul(Z3, t4), f.mul(t0, t3))
        return jnp.stack([X3, Y3, Z3], axis=ax)

    def double(self, p: jnp.ndarray) -> jnp.ndarray:
        return self.add(p, p)

    def select(self, mask: jnp.ndarray, a: jnp.ndarray, b: jnp.ndarray):
        """where(mask, a, b) with mask broadcast over point axes."""
        extra = 1 + self.f.el_ndim
        m = mask.reshape(mask.shape + (1,) * extra)
        return jnp.where(m, a, b)

    def scalar_mul(self, p: jnp.ndarray, bits: jnp.ndarray) -> jnp.ndarray:
        """[..., 3, *el] × [..., nbits] (msb-first 0/1) → [..., 3, *el].

        Fixed-trip-count left-to-right double-and-add as a ``lax.scan``
        — the complete formulas make every iteration branch-free.
        """
        bits_t = jnp.moveaxis(bits, -1, 0)  # [nbits, ...]
        # Initial accumulator = identity, built *from the inputs* (p·0 +
        # bits·0) so it inherits their batch shape and — under
        # shard_map — their device-varying axes (a plain constant would
        # fail lax.scan's carry typing inside a sharded region).
        extra = 1 + self.f.el_ndim  # X/Y/Z axis + field element axes
        bz = (jnp.sum(bits, axis=-1) * 0).reshape(
            bits.shape[:-1] + (1,) * extra
        )
        pt = jnp.stack([self.f.zero(), self.f.one(), self.f.zero()])
        acc0 = p * 0 + bz + pt

        def step(acc, b):
            acc = self.add(acc, acc)
            with_p = self.add(acc, p)
            return self.select(b.astype(bool), with_p, acc), None

        acc, _ = jax.lax.scan(step, acc0, bits_t)
        return acc

    def tree_sum(self, pts: jnp.ndarray) -> jnp.ndarray:
        """Σ over the leading axis via a log₂ tree of complete adds."""
        n = pts.shape[0]
        if n == 0:
            return self.identity()
        while n > 1:
            if n % 2:
                pts = jnp.concatenate(
                    [pts, self.identity((1,))], axis=0
                )
                n += 1
            pts = self.add(pts[: n // 2], pts[n // 2 :])
            n = pts.shape[0]
        return pts[0]

    def msm(self, pts: jnp.ndarray, bits: jnp.ndarray) -> jnp.ndarray:
        """Multi-scalar multiplication: Σᵢ kᵢ·Pᵢ.

        pts [k, 3, *el], bits [k, nbits] → [3, *el].  The per-point
        scalar muls run batched (the k axis rides the vector lanes);
        the final reduction is a log₂(k) tree.
        """
        return self.tree_sum(self.scalar_mul(pts, bits))


@functools.lru_cache(maxsize=None)
def g1_kernel() -> PointKernel:
    return PointKernel(_fq_ops())


@functools.lru_cache(maxsize=None)
def g2_kernel() -> PointKernel:
    return PointKernel(_fq2_ops())


# ---------------------------------------------------------------------------
# Host ↔ device conversion (canonical at the boundary)
# ---------------------------------------------------------------------------


def g1_batch_affine(points: Sequence[Any]) -> List[Any]:
    """Host G1 points → ``[(x, y) | None]`` (None = infinity), with ONE
    Montgomery batch inversion shared across every non-infinity point.
    Delegates to the shared normalization home in :mod:`crypto.curve`
    (``G1.batch_affine`` / ``_jacobian_ops``'s ``batch_to_affine``) so
    limb marshalling, packed-wire marshalling, and the serialization
    memos all flow from the same batch."""
    from ..crypto.curve import G1

    return G1.batch_affine(points)


def g1_to_limbs(points: Sequence[Any]) -> np.ndarray:
    """Host G1 points (crypto.curve.G1) → [k, 3, L] projective limbs.

    Batched: one shared batch inversion (``g1_batch_affine``); limb
    decomposition is one vectorized ``unpackbits`` pass — a 262k-point
    flush spent more time in the per-point Python loop than on the
    device before this.
    """
    f = LB.fq()
    n = len(points)
    xs = [0] * n
    ys = [0] * n
    zs = np.zeros(n, dtype=np.int32)
    for i, aff in enumerate(g1_batch_affine(points)):
        if aff is None:
            ys[i] = 1  # infinity encoded (0 : 1 : 0)
        else:
            xs[i], ys[i], zs[i] = aff[0], aff[1], 1
    out = np.zeros((n, 3, f.L), dtype=np.int32)
    out[:, 0, :] = LB.ints_to_limbs_batch(xs, f.L)
    out[:, 1, :] = LB.ints_to_limbs_batch(ys, f.L)
    out[:, 2, 0] = zs
    return out


def g2_to_limbs(points: Sequence[Any]) -> np.ndarray:
    """Host G2 points → [k, 3, 2, L] projective limbs (one shared
    Fq2 batch inversion, not one ``fq2_inv`` per point)."""
    from ..crypto.curve import G2

    f = LB.fq()
    out = np.zeros((len(points), 3, 2, f.L), dtype=np.int32)
    for i, aff in enumerate(G2.batch_affine(points)):
        if aff is None:
            out[i, 1, 0] = f.to_limbs(1)
        else:
            (x0, x1), (y0, y1) = aff
            out[i, 0, 0] = f.to_limbs(x0)
            out[i, 0, 1] = f.to_limbs(x1)
            out[i, 1, 0] = f.to_limbs(y0)
            out[i, 1, 1] = f.to_limbs(y1)
            out[i, 2, 0] = f.to_limbs(1)
    return out


def g1_from_limbs(arr) -> Any:
    """[3, L] projective limbs → host G1 point (exact, canonical)."""
    from ..crypto.curve import G1
    from ..crypto import fields as F

    f = LB.fq()
    arr = np.asarray(arr)
    X, Y, Z = (f.from_limbs(arr[i]) for i in range(3))
    if Z == 0:
        return G1.infinity()
    zinv = pow(Z, -1, F.P)
    return G1.from_affine((X * zinv % F.P, Y * zinv % F.P))


def g2_from_limbs(arr) -> Any:
    """[3, 2, L] projective limbs → host G2 point (exact, canonical)."""
    from ..crypto.curve import G2
    from ..crypto import fields as F

    f = LB.fq()
    arr = np.asarray(arr)
    X = (f.from_limbs(arr[0, 0]), f.from_limbs(arr[0, 1]))
    Y = (f.from_limbs(arr[1, 0]), f.from_limbs(arr[1, 1]))
    Z = (f.from_limbs(arr[2, 0]), f.from_limbs(arr[2, 1]))
    if Z == (0, 0):
        return G2.infinity()
    zinv = F.fq2_inv(Z)
    return G2.from_affine((F.fq2_mul(X, zinv), F.fq2_mul(Y, zinv)))


# ---------------------------------------------------------------------------
# Jitted entry points (shapes: k points, 255-bit scalars)
# ---------------------------------------------------------------------------


@functools.partial(jax.jit, static_argnums=())
def g1_msm_device(pts: jnp.ndarray, bits: jnp.ndarray) -> jnp.ndarray:
    return g1_kernel().msm(pts, bits)


@functools.partial(jax.jit, static_argnums=())
def g2_msm_device(pts: jnp.ndarray, bits: jnp.ndarray) -> jnp.ndarray:
    return g2_kernel().msm(pts, bits)


@jax.jit
def g1_scalar_mul_device(pts: jnp.ndarray, bits: jnp.ndarray) -> jnp.ndarray:
    return g1_kernel().scalar_mul(pts, bits)


def _width(scalars: Sequence[int], nbits: Optional[int]) -> int:
    """Scan depth for a scalar batch.  The kernels are latency-bound by
    the bit-serial scan, so shorter known-width scalars (128-bit RLC
    coefficients vs full 255-bit Fr) halve the MSM latency.  Widths are
    bucketed to keep the number of compiled variants small."""
    if nbits is not None:
        return nbits
    m = max((s.bit_length() for s in scalars), default=1)
    for w in (128, 160, 192, 255):  # 192: product-form RLC coefficients
        if m <= w:
            return w
    raise ValueError(f"scalar wider than the group order: {m} bits")


def _use_pallas(k: int) -> bool:
    """The Pallas VMEM-resident scalar-mul kernel wins beyond ~512
    points on real TPU hardware (the XLA scan goes HBM-bound; measured
    3.6× at K=64k) and compiles ~5× faster.  Interpret mode on CPU is
    only for correctness tests, so stay with XLA there."""
    import os

    if os.environ.get("HBBFT_TPU_NO_PALLAS"):
        return False
    return k >= 512 and jax.default_backend() == "tpu"


def g1_msm(
    points: Sequence[Any], scalars: Sequence[int], nbits: Optional[int] = None
) -> Any:
    """Host-facing MSM: G1 points × Fr scalars → G1 (device compute)."""
    if not points:
        from ..crypto.curve import G1

        return G1.infinity()
    w = _width(scalars, nbits)
    if _use_pallas(len(points)):
        from . import pallas_ec

        return pallas_ec.g1_msm_pallas(points, scalars, nbits=w, interpret=False)
    pts = jnp.asarray(g1_to_limbs(points))
    bits = jnp.asarray(LB.scalars_to_bits(scalars, w))
    return g1_from_limbs(g1_msm_device(pts, bits))


def g2_msm(
    points: Sequence[Any], scalars: Sequence[int], nbits: Optional[int] = None
) -> Any:
    if not points:
        from ..crypto.curve import G2

        return G2.infinity()
    w = _width(scalars, nbits)
    if _use_pallas(len(points)):
        from . import pallas_ec

        return pallas_ec.g2_msm_pallas(points, scalars, nbits=w, interpret=False)
    pts = jnp.asarray(g2_to_limbs(points))
    bits = jnp.asarray(LB.scalars_to_bits(scalars, w))
    return g2_from_limbs(g2_msm_device(pts, bits))


# ---------------------------------------------------------------------------
# limbprove registry (see ops/limbs.py for the convention).  One scan
# body of the bit-serial MSM is the inductive step: inputs within the
# redundant-limb bound come out within it, so the whole ladder stays
# bounded.  The engine verifies the scan via carry-join fixpoint.


def _range_specs(rc):
    bound = (1 << (LB.LIMB_BITS + 1)) - 1
    L = LB.FQ_LIMBS
    inv = dict(out_lo=-bound, out_hi=bound)
    bits = rc.arg((2, 16), "int32", 0, 1)
    return [
        rc.KernelSpec(
            "ec.g1_msm",
            lambda p, b: g1_kernel().msm(p, b),
            (rc.arg((2, 3, L), "int32", -bound, bound), bits),
            **inv,
        ),
        rc.KernelSpec(
            "ec.g2_msm",
            lambda p, b: g2_kernel().msm(p, b),
            (rc.arg((2, 3, 2, L), "int32", -bound, bound), bits),
            **inv,
        ),
        rc.KernelSpec(
            "ec.g1_scalar_mul",
            lambda p, b: g1_kernel().scalar_mul(p, b),
            (rc.arg((2, 3, L), "int32", -bound, bound), bits),
            **inv,
        ),
    ]


RANGE_SPECS = dict(
    module="ops/ec_jax.py",
    covers=(),
    specs=_range_specs,
)
