"""Device kernels: batched crypto math as JAX/TPU programs.

Modules
-------
- ``limbs``       — 381-bit modular arithmetic on int32 limb vectors
- ``ec_jax``      — complete-formula G1/G2 point ops, scalar mul, MSM
- ``sha256_jax``  — batched SHA-256 + level-parallel Merkle builds
- ``gf256_jax``   — bit-sliced GF(2^8) matmuls, Reed-Solomon codec
- ``backend_tpu`` — the ``CryptoBackend`` implementation wiring these
  into the protocol stack (``NetworkInfo.ops``)

Import of heavy deps is lazy at module granularity: importing
``hbbft_tpu`` never pulls in jax; importing ``hbbft_tpu.ops.*`` does.
"""
