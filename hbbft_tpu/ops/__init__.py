"""hbbft_tpu.ops subpackage."""
