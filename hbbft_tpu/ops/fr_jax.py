"""Device Fr (BLS12-381 scalar field) matrix products on the MXU.

The DKG's dealing plane is matrix multiplication over Fr: row grids
``ROWS_d = POW·C_d`` and value grids ``VAL_d = ROWS_d·POWᵀ``
(``harness/dkg.py``, the vectorized form of the per-node evaluation
work in ``sync_key_gen.rs:268-299``).  At N=1024 (degree-341 bivariate
polynomials) that is ~2·10¹¹ Fr multiplications — hours on the native
single-core host path (measured: the N=1024 DKG exceeds 2 h).  This
module maps the same algebra onto the TPU's systolic array:

- Fr elements are **8-bit limb vectors** (``FR_LIMBS = 33`` limbs,
  little-endian, a redundant representation closed under the fold:
  any 33-limb value < 2^264, congruent mod r).  8-bit limbs are the
  MXU's native int8 operand width.
- An [m,k]×[k,p] Fr product becomes ONE ``dot_general`` over u8 limbs
  with int32 accumulation — ``P[m,a,p,b] = Σ_k A[m,k,a]·B[k,p,b]`` —
  i.e. an (m·33)×k×(p·33) int8 matmul the MXU tiles natively,
  followed by cheap vector work: diagonal-sum into convolution
  positions, a carry sweep to base-256 digits, and a fold of the
  digits above position 32 through precomputed ``2^(8j) mod r``
  tables back into 33 limbs.
- Exactness: products ≤ 255², accumulated over ≤ k·33 terms — int32
  holds for k ≤ 971 (asserted; the DKG contracts k = t+1 ≤ 342).
  Every step is integer-exact; the representation is reduced to
  canonical form (``% r``) only at the host boundary.

Fold-bound argument (why 33 limbs is a fixed point): after the carry
sweep the product has ≤ 70 base-256 digits.  Folding every digit at
position ≥ 32 through ``K_j = 2^(8(32+j)) mod r < 2^255`` leaves
``lo < 2^256`` plus ≤ 38 terms ≤ 255·2^255 each → < 2^269 (34
digits); a second fold (≤ 2 terms) → < 2^256 + 2^264; a third fold
(terms d₃₂ ≤ 255, d₃₃ ≤ 1) → < 2^256 + 256·2^255 < 2^264 — closed at
33 limbs.  Three post-carry folds therefore suffice for ANY input
pair, and a fourth is never needed.

No Pallas: everything is plain XLA (fast server-side compiles, runs
on the CPU backend for tests).  The matmul is where the FLOPs are and
XLA tiles it onto the MXU; hand-scheduling the rest would fight the
compiler for the ~2% that is vector work.
"""

from __future__ import annotations

import functools
from typing import List, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..crypto import fields as F

R = F.R
FR_LIMBS = 33  # 8-bit limbs; values < 2^264, congruent mod r
# int32 accumulation bound, INCLUDING the carry-sweep addend (the
# running carry ≈ max_digit/255 is added to a digit before its shift):
# 255² · k · 33 · (1 + 1/255) < 2^31 — at k=971 the worst case is
# ≈ 2.092e9, ~2.6% under the ceiling (ADVICE r4 #3: the carry
# headroom is part of the invariant a future k-bound edit must check)
_MAX_K = 971


def _fold_table(offset: int, count: int) -> np.ndarray:
    """[count, FR_LIMBS] u8 — row j holds ``2^(8·(offset+j)) mod r``
    as little-endian bytes (canonical, so the top limb is 0)."""
    out = np.zeros((count, FR_LIMBS), dtype=np.uint8)
    for j in range(count):
        k = pow(2, 8 * (offset + j), R)
        out[j] = np.frombuffer(
            k.to_bytes(FR_LIMBS, "little"), dtype=np.uint8
        )
    return out


# ---------------------------------------------------------------------------
# Host ↔ limb conversions
# ---------------------------------------------------------------------------


def fr_to_limbs(vals: Sequence[int]) -> np.ndarray:
    """Python ints (any size; reduced mod r) → [n, FR_LIMBS] u8."""
    out = np.empty((len(vals), FR_LIMBS), dtype=np.uint8)
    for i, v in enumerate(vals):
        out[i] = np.frombuffer(
            int(v % R).to_bytes(FR_LIMBS, "little"), dtype=np.uint8
        )
    return out


def limbs_to_fr(arr: np.ndarray) -> List[int]:
    """[..., FR_LIMBS] u8 → canonical ints mod r (host reduction)."""
    flat = np.asarray(arr, dtype=np.uint8).reshape(-1, FR_LIMBS)
    raw = flat.tobytes()
    step = FR_LIMBS
    return [
        int.from_bytes(raw[i * step : (i + 1) * step], "little") % R
        for i in range(flat.shape[0])
    ]


def be32_to_limbs(buf: np.ndarray) -> np.ndarray:
    """The native layout ([n·32] u8, 32-byte big-endian words —
    ``harness/dkg._fr_bytes``) → [n, FR_LIMBS] u8 little-endian."""
    b = np.asarray(buf, dtype=np.uint8).reshape(-1, 32)
    le = b[:, ::-1]
    out = np.zeros((le.shape[0], FR_LIMBS), dtype=np.uint8)
    out[:, :32] = le
    return out


def limbs_to_be32(arr: np.ndarray) -> np.ndarray:
    """[..., FR_LIMBS] u8 → [n·32] u8 of canonical 32-byte big-endian
    words (the native ``fr_matmul`` buffer layout)."""
    vals = limbs_to_fr(arr)
    return np.frombuffer(
        b"".join(v.to_bytes(32, "big") for v in vals), dtype=np.uint8
    ).copy()


# ---------------------------------------------------------------------------
# Device kernels (plain XLA)
# ---------------------------------------------------------------------------


def _carry_sweep(digits: jnp.ndarray) -> jnp.ndarray:
    """[..., D] int32 (non-negative) → [..., D+4] u8 base-256 digits.
    The running carry after any position is ≤ (max term)/255 ≈ 2^23,
    so 4 extra digits always absorb it."""

    def step(carry, d):
        tot = carry + d
        return tot >> 8, (tot & 0xFF).astype(jnp.uint8)

    xs = jnp.moveaxis(digits, -1, 0)
    carry, ys = jax.lax.scan(step, jnp.zeros(digits.shape[:-1], jnp.int32), xs)
    out = jnp.moveaxis(ys, 0, -1)
    tail = []
    for _ in range(4):
        tail.append((carry & 0xFF).astype(jnp.uint8))
        carry = carry >> 8
    return jnp.concatenate([out] + [t[..., None] for t in tail], axis=-1)


def _fold_once(digits: jnp.ndarray) -> jnp.ndarray:
    """One fold+carry: digits [..., D] u8 (D > FR_LIMBS) →
    [..., ≤ max(FR_LIMBS, D-?)+] u8 with every position ≥ 32 folded
    through ``2^(8j) mod r``.  Preserves the value mod r."""
    D = digits.shape[-1]
    hi_n = D - 32
    lo = digits[..., :32].astype(jnp.int32)
    hi = digits[..., 32:]
    table = jnp.asarray(_fold_table(32, hi_n))  # [hi_n, FR_LIMBS]
    folded = jax.lax.dot_general(
        hi.astype(jnp.int32),
        table.astype(jnp.int32),
        (((hi.ndim - 1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32,
    )  # [..., FR_LIMBS]
    summed = folded.at[..., :32].add(lo)
    return _carry_sweep(summed)


def _reduce_digits(digits: jnp.ndarray) -> jnp.ndarray:
    """int32 convolution limbs → [..., FR_LIMBS] u8 (< 2^264, ≡ mod r).
    Carry sweep then three folds (see the module-doc bound: three
    always suffice); trailing guaranteed-zero digits are sliced off."""
    d = _carry_sweep(digits)
    for _ in range(3):
        d = _fold_once(d)
    return d[..., :FR_LIMBS]


def _matmul_limbs(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """[m, k, L] u8 × [k, p, L] u8 → [m, p, L] u8 (≡ product mod r).

    The dot_general is the MXU part: contracting k with free limb
    axes is an (m·L)×k×(p·L) int8 matmul."""
    k = a.shape[1]
    if k > _MAX_K:
        raise ValueError("contraction %d exceeds int32-safe bound" % k)
    prod = jax.lax.dot_general(
        a.astype(jnp.uint8),
        b.astype(jnp.uint8),
        (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32,
    )  # [m, La, p, Lb]
    m, L, p = prod.shape[0], prod.shape[1], prod.shape[2]
    conv = jnp.zeros((m, p, 2 * L - 1), jnp.int32)
    for sh in range(L):  # limb a=sh contributes at positions sh+b
        conv = conv.at[..., sh : sh + L].add(prod[:, sh, :, :])
    return _reduce_digits(conv)


@functools.lru_cache(maxsize=None)
def _matmul_jit():
    return jax.jit(_matmul_limbs)


def fr_matmul_device(a: np.ndarray, b: np.ndarray) -> jnp.ndarray:
    """Device Fr matmul on limb arrays ([m,k,L] × [k,p,L] u8); returns
    the device array ([m,p,L] u8, values < 2^264 ≡ mod r)."""
    return _matmul_jit()(jnp.asarray(a), jnp.asarray(b))


def _add_limbs(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """Elementwise Fr addition of limb tensors (fold keeps 33 limbs)."""
    s = a.astype(jnp.int32) + b.astype(jnp.int32)
    return _reduce_digits(s)


@functools.lru_cache(maxsize=None)
def _add_jit():
    return jax.jit(_add_limbs)


def fr_add_device(a, b) -> jnp.ndarray:
    return _add_jit()(jnp.asarray(a), jnp.asarray(b))


# ---------------------------------------------------------------------------
# Uniform sampling mod r (for on-device dealing at scale)
# ---------------------------------------------------------------------------


def _sample_limbs(key, shape) -> jnp.ndarray:
    """Uniform Fr samples: 48 random bytes folded mod r (statistical
    distance < 2^-129 from uniform), as [..., FR_LIMBS] u8."""
    raw = jax.random.randint(
        key, tuple(shape) + (48,), 0, 256, dtype=jnp.int32
    )
    return _reduce_digits(raw)


@functools.lru_cache(maxsize=None)
def _sample_jit():
    return jax.jit(_sample_limbs, static_argnums=(1,))


def sample_fr_device(key, shape) -> jnp.ndarray:
    return _sample_jit()(key, tuple(shape))


# ---------------------------------------------------------------------------
# limbprove registry (see ops/limbs.py for the convention).  The
# carry-sweep peak here is the "~2.6% under the ceiling" comment made
# checkable: the engine re-derives it from _MAX_K and the fold table.


def _range_specs(rc):
    k = _MAX_K
    byte = (0, 255)
    return [
        rc.KernelSpec(
            "fr.matmul",
            lambda a, b: _matmul_limbs(a, b),
            (
                rc.arg((2, k, FR_LIMBS), "uint8", *byte),
                rc.arg((k, 2, FR_LIMBS), "uint8", *byte),
            ),
            out_lo=0,
            out_hi=255,
            final_slice_exact=True,
        ),
        rc.KernelSpec(
            "fr.add",
            lambda a, b: _add_limbs(a, b),
            (
                rc.arg((4, FR_LIMBS), "uint8", *byte),
                rc.arg((4, FR_LIMBS), "uint8", *byte),
            ),
            out_lo=0,
            out_hi=255,
            final_slice_exact=True,
        ),
        rc.KernelSpec(
            "fr.sample",
            lambda key: _sample_limbs(key, (3,)),
            (rc.arg((2,), "uint32", 0, (1 << 32) - 1),),
            out_lo=0,
            out_hi=255,
            final_slice_exact=True,
        ),
    ]


RANGE_SPECS = dict(
    module="ops/fr_jax.py",
    covers=("_fold_once", "_matmul_limbs"),
    specs=_range_specs,
)
