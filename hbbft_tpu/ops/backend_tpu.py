"""The TPU ops backend — drop-in for ``crypto.backend.CpuBackend``.

Implements the ``CryptoBackend`` seam (SURVEY §7 architecture stance)
with batched device kernels:

- SHA-256 / Merkle levels  → ``ops/sha256_jax.py`` (uniform batches);
- Reed-Solomon coding      → ``ops/gf256_jax.py`` (bit-sliced matmul);
- share-verification MSMs  → ``ops/ec_jax.py`` (complete-formula EC);
- Lagrange combining MSMs  → same EC kernels.

Only the two final pairings of a batch verification stay host-side
(they are O(1) per *batch*, not per share — the random-linear-
combination trick of ``crypto.threshold.batch_verify_shares``).

Everything returns bit-identical results to the CPU backend; the
protocols cannot tell which backend they run on (asserted in
``tests/test_backend_tpu.py``).
"""

from __future__ import annotations

import os

from typing import List, Sequence

import numpy as np

from ..crypto import backend as _backend
from ..crypto.backend import CpuBackend
from ..crypto.curve import G1, G2, G1_GEN, G2_GEN
from ..obs import recorder as _obs
from ..crypto.hashing import sha256
from ..crypto.merkle import MerkleTree
from ..crypto.pairing import pairing_check
from ..crypto import threshold as T
from . import ec_jax, gf256_jax, sha256_jax

# Below this many leaves/shards the device round-trip costs more than
# the host hash; stay on CPU (same results either way).
_MIN_DEVICE_BATCH = 8


def _mesh_from_env():
    """Resolve the backend's default mesh from ``HBBFT_TPU_MESH``:
    unset → auto (a real multi-device TPU host meshes itself over all
    its chips); ``"0"`` / empty → explicitly off; an integer N → a
    forced N-device mesh (the virtual-device path used by the tier-1
    mesh tests and ``bench.py --mesh`` children)."""
    env = os.environ.get("HBBFT_TPU_MESH")
    try:
        from ..parallel import mesh as M

        if env is not None:
            env = env.strip()
            if not env or env == "0":
                return None
            n = int(env)
            return M.make_mesh(n) if n > 1 else None
        import jax

        if jax.default_backend() == "tpu" and len(jax.devices()) > 1:
            return M.make_mesh()
    except Exception:
        pass  # a broken mesh config must not break construction
    return None


class _DeviceMerkleTree(MerkleTree):
    """MerkleTree whose levels were hashed on device (same layout)."""

    def __init__(self, values: List[bytes], levels: List[List[bytes]]):
        self.values = list(values)
        self.levels = levels


class _GuardedFinalizer:
    """Wraps a device finalizer for the degradation ladder: an
    exception surfacing at finalize time degrades the backend (one
    ``degrade`` obs event, device routing off for the process) and
    recomputes the value on the host path — byte-identical by the
    backend contract, so callers never see the failure."""

    def __init__(self, backend: "TpuBackend", fin, recompute):
        self._backend = backend
        self._fin = fin
        self._recompute = recompute

    def __call__(self):
        try:
            return self._fin()
        except Exception as exc:
            self._backend._degrade(f"finalize:{type(exc).__name__}")
            return self._recompute()

    def __getattr__(self, name):
        # ready/poll/start_drain finalizer-protocol passthrough for the
        # epoch driver's drain overlap
        return getattr(self._fin, name)


class TpuBackend(CpuBackend):
    """Batched JAX/TPU ops backend (bit-identical to ``CpuBackend``).

    ``mesh``: an optional ``jax.sharding.Mesh`` — G1 MSMs beyond the
    device threshold then shard over the validator axis with the
    all-gather + tree reduction of ``parallel/mesh.py`` (multi-chip
    scale-out; validated on the virtual CPU mesh in
    ``tests/test_parallel.py`` and by the driver's multi-chip dry run).
    """

    name = "tpu"

    def __init__(self, mesh=None):
        self.mesh = mesh if mesh is not None else _mesh_from_env()
        self._sharded_g1 = None
        # Degradation ladder: the first device/mesh error flips this
        # sticky flag — every later call routes host-side (identical
        # results, the process stays alive) and the failure is
        # attributed exactly once via the ``degrade`` obs event.
        self._degraded = False
        # env overrides are read here (not at import) so operators and
        # tests can set them after the module loads
        # G2_DEVICE_MIN joined the tunable set with the batched coin
        # plane: cross-instance coin flushes spend their host half in
        # per-sender-class G2 MSMs, so operators balancing that plane
        # need the same override the G1 bands have
        for attr in (
            "G1_DEVICE_MIN",
            "G1_DEVICE_MAX",
            "G1_FLAT_MAX",
            "G1_MESH_MIN",
            "G2_DEVICE_MIN",
        ):
            env = os.environ.get("HBBFT_TPU_" + attr)
            if env is not None:
                setattr(self, attr, int(env))
        # warm start: begin deserializing the last run's flush-shape
        # executables (disk → memory, no compiling) while the caller
        # runs DKG/setup — the first flush then skips the per-
        # executable load wall that dominated the r05 cold flush
        try:
            from . import packed_msm, pallas_ec

            if pallas_ec.exec_cache_active():
                packed_msm.start_background_prewarm()
        except Exception:
            pass  # prewarm is an optimization; never block construction

    def degraded(self) -> bool:
        return self._degraded

    def _degrade(self, reason: str) -> None:
        """Flip to host-only routing, attributing the failure once."""
        if self._degraded:
            return
        self._degraded = True
        rec = _obs.ACTIVE
        if rec is not None:
            rec.event("degrade", plane="device", reason=reason)
            rec.count("degrade.device")

    def _mesh_flush_active(self) -> bool:
        """Whether product flushes route to the sharded mesh engine:
        a >1-device mesh on a backend the engine supports (real TPU,
        or a virtual CPU mesh under ``HBBFT_TPU_MESH_CPU=1``)."""
        if self._degraded:
            return False
        if self.mesh is None or self.mesh.devices.size < 2:
            return False
        from . import packed_msm

        return packed_msm._mesh_backend_ok()

    # -- hashing / merkle -------------------------------------------------
    # Like the MSMs, routed by measured capability: the native C++ host
    # path (SHA-NI, table-driven GF(2⁸)) beats the device kernels for
    # single-instance protocol work — a 64-node 1 MB broadcast runs
    # 1.3 s native vs 53 s via per-decode device round-trips (each
    # erasure pattern is a fresh shape → recompiles).  The device
    # kernels earn their keep on *uniform batches* (co-simulation
    # flushes); without the native library they also beat the
    # pure-Python fallback.

    def sha256_many(self, items: Sequence[bytes]) -> List[bytes]:
        items = list(items)
        if (
            not self._degraded
            and not self._native_host()
            and len(items) >= _MIN_DEVICE_BATCH
            and len({len(i) for i in items}) == 1
        ):
            try:
                return sha256_jax.sha256_many(items)
            except Exception as exc:
                self._degrade(f"sha256:{type(exc).__name__}")
        return super().sha256_many(items)

    def merkle_tree(self, values: List[bytes]) -> MerkleTree:
        vals = list(values)
        if (
            self._degraded
            or self._native_host()
            or len(vals) < _MIN_DEVICE_BATCH
            or len({len(v) for v in vals}) != 1
        ):
            return MerkleTree(vals)
        try:
            levels = sha256_jax.merkle_levels_device(vals)
        except Exception as exc:
            self._degrade(f"merkle:{type(exc).__name__}")
            return MerkleTree(vals)
        return _DeviceMerkleTree(vals, levels)

    # -- erasure coding ---------------------------------------------------

    def rs_codec(self, data_shards: int, parity_shards: int):
        if parity_shards == 0 or self._degraded or self._native_host():
            return super().rs_codec(data_shards, parity_shards)
        if data_shards + parity_shards > 256:
            return gf256_jax.ReedSolomonDevice16(data_shards, parity_shards)
        return gf256_jax.ReedSolomonDevice(data_shards, parity_shards)

    # -- group MSMs --------------------------------------------------------
    # Routing is by measured capability (TPU v5e, see BASELINE.md):
    # the VMEM-resident windowed Pallas kernel scales nearly free with
    # batch width (45.7k pts/s at K=8k, 67.5k at K=64k — past the
    # native C++ Pippenger host path's ~40k) while small MSMs are
    # dominated by launch latency, where the host wins.  Without the
    # native library the host fallback is pure Python (~100× slower),
    # so the device takes everything it can.  All paths are exact.

    # G1 MSM routing band [G1_DEVICE_MIN, G1_DEVICE_MAX] — outside it
    # the native host Pippenger runs.  Re-measured r4 END-TO-END after
    # the packed-wire redesign (48-96 B/point transfer with on-device
    # unpack, factored 96-bit product scalars, executable disk cache)
    # at the fused-flush shape K=65,536:
    #
    #   - idle host: device ≈ 2.7-3.5 s/MSM vs host Pippenger
    #     ≈ 2.7-3.8 s — parity (the r3 expanded-limb path lost 3-15×;
    #     see git history for the old table);
    #   - loaded host (anything sharing the single CPU core): device
    #     4.1-4.9 s/flush vs host 5.0-7.0 s — device wins;
    #   - the SHIPPING flush splits the factored product across BOTH
    #     engines concurrently at the measured balance point
    #     (packed_msm.learned_fraction / _adapt — a rate-balance
    #     controller solves per-shape for the split where the device
    #     half finishes just as the host half does, from EMA rate
    #     estimates), so it tracks the better split under either
    #     regime instead of pinning a compile-time constant.
    #
    # Small MSMs stay launch-latency-bound, so the band opens at 16k.
    # A shape inside the band still falls back to host unless its
    # executables are warm (``_device_g1_msm`` → None): production
    # paths never pay a cold multi-minute Mosaic compile; warming
    # entry points (bench, hardware smoke) set HBBFT_TPU_WARM=1.
    G1_DEVICE_MIN = 1 << 14
    G1_DEVICE_MAX = 1 << 62
    # FLAT (ungrouped) MSMs above this stay host-side: past ~2^17 the
    # chunked flat path's transfer + per-chunk trees lose to native
    # Pippenger (r4 measured — hb_1024_real's 948k-point flushes ran
    # 4× 262k flat chunks and lost).  Product-form flushes are NOT
    # capped here: their hybrid split sizes its own device share
    # (``packed_msm._split_plan``).
    G1_FLAT_MAX = 1 << 17
    # a mesh-configured backend shards MSMs at or above this size;
    # smaller ones stay on the fast host path (a tiny MSM should not
    # pay a shard_map dispatch over the interconnect)
    G1_MESH_MIN = 8192
    # Device G2 (windowed Fq2 Pallas, exec-cached so the 18-min Mosaic
    # compile is paid once ever) measured 2026-07-30: ~3k pts/s at
    # K=1024 and K=8192 vs native host Pippenger ~6-12k pts/s — it
    # loses at every size.  More importantly the product-form fused
    # check (harness/batching.py) reduced every flush's pk-half to ONE
    # N-point G2 MSM (~85 ms at N=1024 on host), so G2 is no longer a
    # bottleneck anywhere; routing stays host-side by measurement.
    G2_DEVICE_MIN = 1 << 30

    def _native_host(self) -> bool:
        from .. import native as _native

        return _native.available()

    def g1_msm(self, points: Sequence[G1], scalars: Sequence[int]) -> G1:
        points, scalars = list(points), list(scalars)
        rec = _obs.ACTIVE
        # Mesh path first: an explicitly mesh-configured backend shards
        # its G1 MSMs — the 4-bit windowed Pallas kernel under
        # shard_map (parallel/mesh.sharded_windowed_msm_fn); per-chip
        # throughput is the single-chip windowed rate and only the
        # [3, L] partial sums cross ICI, so the mesh scales it by
        # device count (ADVICE r1 item 3 / VERDICT r2 item 5).
        if (
            not self._degraded
            and self.mesh is not None
            and len(points) >= self.G1_MESH_MIN
        ):
            try:
                from ..parallel import mesh as M
                from . import packed_msm

                if rec is not None:
                    rec.event(
                        "device_op", op="g1_msm", k=len(points), engine="mesh"
                    )
                if self._sharded_g1 is None:
                    # r5: the mesh path ships the PACKED wire (96 B/point
                    # + scalar bytes, on-device unpack per shard) — the r4
                    # single-chip transfer win, inherited multi-chip
                    # (VERDICT r4 weak #5); the expanded limb+digit layout
                    # (~650 B/point) is gone from this branch
                    self._sharded_g1 = M.sharded_packed_msm_fn(self.mesh)
                w = ec_jax._width(scalars, None)
                wires = packed_msm.g1_wires_batch(points)
                sc = packed_msm.scalar_bytes_batch(scalars, -(-w // 8))
                return ec_jax.g1_from_limbs(self._sharded_g1(wires, sc))
            except Exception as exc:
                self._degrade(f"mesh:{type(exc).__name__}")
        if not self._g1_in_device_band(len(points), flat=True):
            if rec is not None:
                rec.event("device_op", op="g1_msm", k=len(points), engine="host")
            return super().g1_msm(points, scalars)
        try:
            fin = self._device_g1_msm(points, scalars)
        except Exception as exc:
            self._degrade(f"launch:{type(exc).__name__}")
            fin = None
        if fin is None:  # no warm executables for this shape (or degraded)
            if rec is not None:
                rec.event(
                    "device_op", op="g1_msm", k=len(points), engine="host_cold"
                )
            return super().g1_msm(points, scalars)
        if rec is not None:
            rec.event("device_op", op="g1_msm", k=len(points), engine="device")
        return _GuardedFinalizer(
            self, fin, lambda: CpuBackend.g1_msm(self, points, scalars)
        )()

    def _g1_in_device_band(self, k: int, flat: bool = False) -> bool:
        """One home for the host/device G1 routing decision (shared by
        the sync and async entries so they can never drift): the device
        takes a batch when no native host path exists, or when k falls
        inside the measured routing band.  ``flat`` applies the extra
        upper cap of the ungrouped chunked path (``G1_FLAT_MAX``).  A
        degraded backend never routes to the device again."""
        if self._degraded:
            return False
        if not self._native_host():
            return True
        if flat and k > self.G1_FLAT_MAX:
            return False
        return self.G1_DEVICE_MIN <= k <= self.G1_DEVICE_MAX

    @staticmethod
    def _device_g1_msm(points, scalars):
        """Launch the device G1 MSM, returning a finalizer — or None
        when the shape has no warm executables (cold Mosaic compiles
        are minutes each; the caller falls back to the host path, and
        warming entry points — ``HBBFT_TPU_WARM=1`` — compile new
        shapes).  On real TPU — or any backend running the AOT
        executable cache (``HBBFT_TPU_AOT=1``) — this is the
        packed-wire path (``ops/packed_msm.py``), whose per-chunk
        executables load from ``.palexe`` instead of paying the
        module-level ``ec_jax.g1_msm`` XLA compile (minutes cold on
        CPU — the r05 wall).  On plain CPU (tests, interpret mode) the
        XLA limb path keeps its fast compiles."""
        import jax

        from . import pallas_ec

        if jax.default_backend() == "tpu" or pallas_ec.exec_cache_active():
            from . import packed_msm

            fin = packed_msm.g1_msm_packed_async(points, scalars)
            if fin is None:
                return None
            # uniform finalizer protocol (ready/poll/start_drain) for
            # the epoch driver's drain overlap
            return packed_msm.ProductFinalizer(fin)
        result = ec_jax.g1_msm(points, scalars)
        return _backend.EagerFinalizer(result)

    def g1_msm_async(self, points, scalars):
        """Async G1 MSM: device-routed batches overlap the tunnel
        transfer + kernel with the caller's host work (the fused
        flush's G2 MSMs and transcript pairings — VERDICT r3 item 1).

        A mesh-configured backend has no async seam (shard_map blocks
        until the partial sums cross ICI) — it degrades to the sync
        :meth:`g1_msm`, whose own ``device_op`` event (``engine=
        "mesh"``) keeps the trace honest about the degradation."""
        points, scalars = list(points), list(scalars)
        if (
            self.mesh is None
            and points
            and self._g1_in_device_band(len(points), flat=True)
        ):
            try:
                fin = self._device_g1_msm(points, scalars)
            except Exception as exc:
                self._degrade(f"launch:{type(exc).__name__}")
                fin = None
            if fin is not None:
                fin = _GuardedFinalizer(
                    self, fin, lambda: CpuBackend.g1_msm(self, points, scalars)
                )
                # the sync path stamps every route it takes; the async
                # fast path was the ONE silent branch — device MSMs in
                # flight were invisible in traces (ISSUE 4 satellite)
                rec = _obs.ACTIVE
                if rec is not None:
                    rec.event(
                        "device_op",
                        op="g1_msm",
                        k=len(points),
                        engine="device_async",
                    )
                return fin
        result = self.g1_msm(points, scalars)
        return _backend.EagerFinalizer(result)

    def g2_msm(self, points: Sequence[G2], scalars: Sequence[int]) -> G2:
        points, scalars = list(points), list(scalars)
        rec = _obs.ACTIVE
        if self._degraded or (
            self._native_host() and len(points) < self.G2_DEVICE_MIN
        ):
            if rec is not None:
                rec.event("device_op", op="g2_msm", k=len(points), engine="host")
            return super().g2_msm(points, scalars)
        if rec is not None:
            rec.event("device_op", op="g2_msm", k=len(points), engine="device")
        try:
            return ec_jax.g2_msm(points, scalars)
        except Exception as exc:
            self._degrade(f"g2:{type(exc).__name__}")
            return super().g2_msm(points, scalars)

    # -- product-form MSM ---------------------------------------------------

    def g1_ship(self, points, group_sizes=None):
        """Start the packed-wire transfer early (overlaps the caller's
        transcript hashing — the flush ships points the moment they are
        serialized).  Falls through to the plain list when the batch
        would not route to the device anyway."""
        points = list(points)
        if (
            self._mesh_flush_active()
            and len(points) >= self.G1_MESH_MIN
        ):
            from . import packed_msm

            # the sharded marshal: per-shard blocks staged on the FIFO
            # worker; ShippedPoints records the mesh so the product
            # launch below routes to the same engine
            return packed_msm.ship_points(
                points, group_sizes, mesh=self.mesh
            )
        if (
            self.mesh is None
            and points
            and self._g1_in_device_band(len(points))
        ):
            import jax

            from . import pallas_ec

            if (
                jax.default_backend() == "tpu"
                or pallas_ec.exec_cache_active()
            ):
                from . import packed_msm

                return packed_msm.ship_points(points, group_sizes)
        return points

    def g1_msm_product_async(self, points, s_coeffs, t_coeffs, group_sizes):
        from . import packed_msm

        pts_list = (
            points.points
            if isinstance(points, packed_msm.ShippedPoints)
            else list(points)
        )
        rec = _obs.ACTIVE

        def _host_product():
            # degrade recompute target: the exact host path, finalized
            return CpuBackend.g1_msm_product_async(
                self, pts_list, s_coeffs, t_coeffs, group_sizes
            )()

        if (
            self._mesh_flush_active()
            and pts_list
            and len(pts_list) >= self.G1_MESH_MIN
        ):
            try:
                fin = packed_msm.g1_msm_product_async(
                    points, s_coeffs, t_coeffs, group_sizes, mesh=self.mesh
                )
            except Exception as exc:
                self._degrade(f"mesh-flush:{type(exc).__name__}")
                fin = None
            if fin is not None:
                if rec is not None:
                    rec.event(
                        "device_op",
                        op="g1_msm_product",
                        k=len(pts_list),
                        engine="mesh",
                    )
                return _GuardedFinalizer(self, fin, _host_product)
            # the mesh declined (no warm shard executable / zero device
            # share) or degraded: fall through to the host product path
        if (
            self.mesh is None
            and pts_list
            and self._g1_in_device_band(len(pts_list))
        ):
            import jax

            from . import pallas_ec

            if (
                jax.default_backend() == "tpu"
                or pallas_ec.exec_cache_active()
            ):
                try:
                    fin = packed_msm.g1_msm_product_async(
                        points, s_coeffs, t_coeffs, group_sizes
                    )
                except Exception as exc:
                    self._degrade(f"fused-flush:{type(exc).__name__}")
                    fin = None
                if fin is not None:
                    if rec is not None:
                        rec.event(
                            "device_op",
                            op="g1_msm_product",
                            k=len(pts_list),
                            engine="device",
                        )
                    return _GuardedFinalizer(self, fin, _host_product)
        if rec is not None:
            rec.event(
                "device_op", op="g1_msm_product", k=len(pts_list), engine="host"
            )
        return super().g1_msm_product_async(
            pts_list, s_coeffs, t_coeffs, group_sizes
        )

    # -- batched share verification ---------------------------------------

    def batch_verify_shares(
        self,
        shares: Sequence[G1],
        pks: Sequence[G2],
        base: G1,
        context: bytes = b"",
    ) -> bool:
        """Identical math to ``threshold.batch_verify_shares`` with the
        two MSMs on device: e(Σrᵢ·σᵢ, P₂)·e(−base, Σrᵢ·pkᵢ) == 1."""
        shares = list(shares)
        pks = list(pks)
        if not shares:
            return True
        coeffs = T._rlc_coeffs(
            context,
            [s.to_bytes() for s in shares] + [p.to_bytes() for p in pks],
        )[: len(shares)]  # one rᵢ per (shareᵢ, pkᵢ) pair, as on CPU
        agg_share_fin = self.g1_msm_async(shares, coeffs)
        u_pks, u_coeffs = T.aggregate_by_point(pks, coeffs)
        agg_pk = self.g2_msm(u_pks, u_coeffs)  # overlaps the device leg
        return pairing_check([(agg_share_fin(), G2_GEN), (-base, agg_pk)])


_DEFAULT_TPU = None


def tpu_backend() -> TpuBackend:
    global _DEFAULT_TPU
    if _DEFAULT_TPU is None:
        _DEFAULT_TPU = TpuBackend()
    return _DEFAULT_TPU
