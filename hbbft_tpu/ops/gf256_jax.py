"""GF(2^8) Reed-Solomon coding as JAX kernels.

Device-side counterpart of ``hbbft_tpu/crypto/rs.py`` (which replaces
the ``reed-solomon-erasure`` crate, ``Cargo.toml:26``; encode at
``broadcast.rs:365-367``, reconstruct at ``:643-656``).

Two execution strategies, picked by matrix size:

- **bit-sliced GF(2) matmul** (the TPU-native path): multiplication by
  a *constant* GF(2^8) matrix is GF(2)-linear in the input bits, so an
  (m×k) GF(256) matmul lowers to an (8m×8k) binary matrix times the
  unpacked input bits — an integer matmul + parity, which is exactly
  the dense-matmul shape the MXU/VPU likes.  The binary expansion of
  the coding matrix is precomputed host-side once per (k, n).
- **log/exp table gathers** for tiny shard counts where matmul setup
  dominates.

Shard payloads ride the second axis ``[shards, shard_len]`` so the
batched dimension is long and contiguous.
"""

from __future__ import annotations

import functools
import threading
from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..crypto import rs as _host_rs

# ---------------------------------------------------------------------------
# Binary expansion of a constant GF(2^8) matrix
# ---------------------------------------------------------------------------


def _gf_mul_table_bits(c: int) -> np.ndarray:
    """8×8 GF(2) matrix M with bits(c·x) = M @ bits(x) (poly 0x11d)."""
    cols = []
    for bit in range(8):
        prod = _host_rs.gf_mul(c, 1 << bit)
        cols.append([(prod >> r) & 1 for r in range(8)])
    return np.array(cols, dtype=np.int8).T  # [out_bit, in_bit]


@functools.lru_cache(maxsize=256)  # decode matrices vary per erasure pattern
def _binary_matrix(key: Tuple[int, int, bytes]) -> np.ndarray:
    """GF(256) matrix (m, k) → binary matrix (8m, 8k) int8."""
    m, k, raw = key
    mat = np.frombuffer(raw, dtype=np.uint8).reshape(m, k)
    out = np.zeros((8 * m, 8 * k), dtype=np.int8)
    for i in range(m):
        for j in range(k):
            out[8 * i : 8 * i + 8, 8 * j : 8 * j + 8] = _gf_mul_table_bits(
                int(mat[i, j])
            )
    return out


def _unpack_bits(x: jnp.ndarray) -> jnp.ndarray:
    """[k, n] uint8 → [8k, n] int8 bit planes (lsb-first per byte)."""
    shifts = jnp.arange(8, dtype=jnp.uint8)
    bits = (x[:, None, :] >> shifts[None, :, None]) & 1  # [k, 8, n]
    return bits.reshape(-1, x.shape[-1]).astype(jnp.int8)


def _pack_bits(bits: jnp.ndarray) -> jnp.ndarray:
    """[8m, n] int32 bit planes → [m, n] uint8."""
    m8 = bits.shape[0]
    b = bits.reshape(m8 // 8, 8, -1).astype(jnp.uint8)
    shifts = jnp.arange(8, dtype=jnp.uint8)
    return jnp.sum(b << shifts[None, :, None], axis=1).astype(jnp.uint8)


@functools.partial(jax.jit, static_argnums=())
def _bitsliced_matmul(binmat: jnp.ndarray, data: jnp.ndarray) -> jnp.ndarray:
    """GF(256) matmul via binary matmul + parity.

    binmat [8m, 8k] int8, data [k, n] uint8 → [m, n] uint8.
    The int8×int8→int32 matmul is the MXU-friendly inner loop; the
    mod-2 keeps only the XOR parity.
    """
    bits = _unpack_bits(data)  # [8k, n]
    acc = jnp.matmul(
        binmat.astype(jnp.int32), bits.astype(jnp.int32)
    )  # XOR-as-integer-sum; parity below
    return _pack_bits(acc & 1)


def gf_matmul_device(mat: np.ndarray, data: jnp.ndarray) -> jnp.ndarray:
    """Constant GF(256) matrix × byte matrix on device.

    mat: host-side (m, k) uint8; data: [k, n] uint8 on device.
    """
    m, k = mat.shape
    binmat = jnp.asarray(
        _binary_matrix((m, k, np.ascontiguousarray(mat, dtype=np.uint8).tobytes()))
    )
    return _bitsliced_matmul(binmat, data)


# ---------------------------------------------------------------------------
# Reed-Solomon codec (device-accelerated, host-orchestrated)
# ---------------------------------------------------------------------------


class ReedSolomonDevice:
    """Same semantics as ``crypto.rs.ReedSolomon`` with the shard-payload
    matmuls on device.  Matrix algebra over the (tiny) shard-index
    dimension — systematic-matrix construction, submatrix inversion on
    reconstruct — stays host-side where it is O(k³) on k ≤ 256 bytes.
    """

    def __init__(self, data_shards: int, parity_shards: int):
        self._host = _host_rs.ReedSolomon(data_shards, parity_shards)
        self.k = self._host.k
        self.m = self._host.m
        self.n = self._host.n

    def encode(self, data: Sequence[bytes]) -> List[bytes]:
        if len(data) != self.k:
            raise ValueError(f"expected {self.k} data shards")
        if self.m == 0:
            return list(data)
        arr = jnp.asarray(
            np.frombuffer(b"".join(data), dtype=np.uint8).reshape(self.k, -1)
        )
        parity = gf_matmul_device(self._host.matrix[self.k :], arr)
        parity_np = np.asarray(parity)
        return list(data) + [p.tobytes() for p in parity_np]

    def reconstruct(self, shards: List[Optional[bytes]]) -> List[bytes]:
        if len(shards) != self.n:
            raise ValueError(f"expected {self.n} shard slots")
        present = [i for i, s in enumerate(shards) if s is not None]
        if len(present) < self.k:
            raise ValueError("not enough shards to reconstruct")
        if self.m == 0:
            return [s for s in shards]  # type: ignore[misc]
        use = present[: self.k]
        dec = self.decode_matrix(use)
        avail = jnp.asarray(
            np.stack([np.frombuffer(shards[i], dtype=np.uint8) for i in use])
        )
        data = gf_matmul_device(dec, avail)
        # Only recompute the missing shards (device matmul over the
        # erased rows); present shards pass through untouched.
        missing = [i for i, s in enumerate(shards) if s is None]
        out: List[Optional[bytes]] = list(shards)
        if missing:
            rows = self._host.matrix[missing, :]
            rec = np.asarray(gf_matmul_device(rows, data))
            for j, i in enumerate(missing):
                out[i] = rec[j].tobytes()
        return out  # type: ignore[return-value]


# ---------------------------------------------------------------------------
# GF(2^16) generalization — the north-star n=1024 broadcast path
# ---------------------------------------------------------------------------
# Identical strategy with 16-bit symbols: multiplication by a constant
# c ∈ GF(2^16) is GF(2)-linear, a 16×16 binary matrix, so an (m×k)
# GF(2^16) matmul lowers to a (16m×16k) binary matmul + parity.

_BITS16: Optional[np.ndarray] = None  # [65536, 16, 16] uint8
_BITS16_LOCK = threading.Lock()  # stage worker + main thread both decode


def _bits16_table() -> np.ndarray:
    """M[c] with bits16(c·x) = M[c] @ bits16(x), for every constant c
    (built vectorised from the host log/antilog tables, ~16 MB)."""
    global _BITS16
    if _BITS16 is None:
        with _BITS16_LOCK:
            if _BITS16 is None:
                _host_rs._build_tables16()
                exp, log = _host_rs._EXP16, _host_rs._LOG16
                cs = np.arange(65536, dtype=np.int64)
                table = np.zeros((65536, 16, 16), dtype=np.uint8)
                for bit in range(16):
                    prod = np.where(
                        cs == 0, 0, exp[log[cs] + int(log[1 << bit])]
                    )
                    for r in range(16):
                        table[:, r, bit] = (prod >> r) & 1
                _BITS16 = table
    return _BITS16


@functools.lru_cache(maxsize=8)  # ~30-60 MB each; decode patterns vary
def _binary_matrix16(key: Tuple[int, int, bytes]) -> np.ndarray:
    """GF(2^16) matrix (m, k) → binary matrix (16m, 16k) int8."""
    m, k, raw = key
    mat = np.frombuffer(raw, dtype=np.uint16).reshape(m, k)
    blocks = _bits16_table()[mat]  # [m, k, 16, 16]
    return (
        blocks.transpose(0, 2, 1, 3).reshape(16 * m, 16 * k).astype(np.int8)
    )


def _unpack_bits16(x: jnp.ndarray) -> jnp.ndarray:
    """[k, n] uint16 → [16k, n] int8 bit planes (lsb-first)."""
    shifts = jnp.arange(16, dtype=jnp.uint16)
    bits = (x[:, None, :] >> shifts[None, :, None]) & 1
    return bits.reshape(-1, x.shape[-1]).astype(jnp.int8)


def _pack_bits16(bits: jnp.ndarray) -> jnp.ndarray:
    """[16m, n] int32 bit planes → [m, n] uint16."""
    m16 = bits.shape[0]
    b = bits.reshape(m16 // 16, 16, -1).astype(jnp.uint16)
    shifts = jnp.arange(16, dtype=jnp.uint16)
    return jnp.sum(b << shifts[None, :, None], axis=1).astype(jnp.uint16)


@jax.jit
def _bitsliced_matmul16(binmat: jnp.ndarray, data: jnp.ndarray) -> jnp.ndarray:
    bits = _unpack_bits16(data)  # [16k, n]
    acc = jnp.matmul(binmat.astype(jnp.int32), bits.astype(jnp.int32))
    return _pack_bits16(acc & 1)


def gf16_matmul_device(mat: np.ndarray, data: jnp.ndarray) -> jnp.ndarray:
    """Constant GF(2^16) matrix × uint16 symbol matrix on device."""
    m, k = mat.shape
    binmat = jnp.asarray(
        _binary_matrix16(
            (m, k, np.ascontiguousarray(mat, dtype=np.uint16).tobytes())
        )
    )
    return _bitsliced_matmul16(binmat, data)


class ReedSolomonDevice16:
    """Device-accelerated GF(2^16) codec (semantics of
    ``crypto.rs.ReedSolomon16``) — lifts the reference crate's 256-shard
    cap (``/root/reference/src/broadcast.rs:310-312``) to 65536 with the
    payload matmuls on the MXU."""

    symbol = 2

    def __init__(self, data_shards: int, parity_shards: int):
        self._host = _host_rs.ReedSolomon16(data_shards, parity_shards)
        self.k = self._host.k
        self.m = self._host.m
        self.n = self._host.n

    def _to_syms(self, shard: bytes) -> np.ndarray:
        return self._host._to_syms(shard)

    def encode(self, data: Sequence[bytes]) -> List[bytes]:
        if len(data) != self.k:
            raise ValueError(f"expected {self.k} data shards")
        if self.m == 0:
            return list(data)
        arr = jnp.asarray(np.stack([self._to_syms(s) for s in data]))
        parity = np.asarray(
            gf16_matmul_device(self._host.matrix[self.k :], arr)
        )
        return list(data) + [p.astype("<u2").tobytes() for p in parity]

    def reconstruct(self, shards: List[Optional[bytes]]) -> List[bytes]:
        if len(shards) != self.n:
            raise ValueError(f"expected {self.n} shard slots")
        present = [i for i, s in enumerate(shards) if s is not None]
        if len(present) < self.k:
            raise ValueError("not enough shards to reconstruct")
        if self.m == 0:
            return [s for s in shards]  # type: ignore[misc]
        use = present[: self.k]
        dec = self.decode_matrix(use)
        avail = jnp.asarray(np.stack([self._to_syms(shards[i]) for i in use]))
        data = gf16_matmul_device(dec, avail)
        missing = [i for i, s in enumerate(shards) if s is None]
        out: List[Optional[bytes]] = list(shards)
        if missing:
            rec = np.asarray(
                gf16_matmul_device(self._host.matrix[missing, :], data)
            )
            for j, i in enumerate(missing):
                out[i] = rec[j].astype("<u2").tobytes()
        return out  # type: ignore[return-value]


def _delegate_decode_matrix(cls):
    """Device codecs delegate decode-matrix construction (tiny O(k³)
    host algebra, cached per erasure pattern) to their host twin so
    batched callers (``harness/epoch.py``) treat host and device codecs
    uniformly."""

    def decode_matrix(self, use):
        return self._host.decode_matrix(use)

    cls.decode_matrix = decode_matrix


_delegate_decode_matrix(ReedSolomonDevice)
_delegate_decode_matrix(ReedSolomonDevice16)


# ---------------------------------------------------------------------------
# limbprove registry (see ops/limbs.py for the convention).  The
# bitsliced matmuls accumulate 0/1 products in int32: the peak is the
# contraction length, which the engine bounds exactly.


def _range_specs(rc):
    bit8 = rc.arg((32, 48), "int8", 0, 1)  # [8m, 8k] binary planes
    bit16 = rc.arg((32, 48), "int8", 0, 1)  # [16m, 16k] binary planes
    return [
        rc.KernelSpec(
            "gf.matmul",
            lambda m, d: _bitsliced_matmul(m, d),
            (bit8, rc.arg((6, 7), "uint8", 0, 255)),
            out_lo=0,
            out_hi=255,
        ),
        rc.KernelSpec(
            "gf.matmul16",
            lambda m, d: _bitsliced_matmul16(m, d),
            (bit16, rc.arg((3, 5), "uint16", 0, (1 << 16) - 1)),
            out_lo=0,
            out_hi=(1 << 16) - 1,
        ),
    ]


RANGE_SPECS = dict(
    module="ops/gf256_jax.py",
    covers=(),
    specs=_range_specs,
)
