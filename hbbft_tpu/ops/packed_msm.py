"""Packed-wire G1 MSMs: minimum-byte tunnel transfer, on-device unpack.

The round-3 finding (VERDICT r3, What's missing #1): the windowed
Pallas MSM kernel's *compute* beats native host Pippenger beyond ~6k
points, yet the device leg lost end-to-end at every shipped shape
because points crossed the remote tunnel as expanded limb+digit arrays
— ``[K, 3, 38]`` int32 limbs plus ``[K, nwin]`` int32 digits, ~650+
bytes per point against a measured ~5-8 MB/s link.  This module ships
the *wire bytes* instead:

- points as the 96-byte uncompressed affine encoding (``x‖y``,
  big-endian — exactly ``native.g1_wire``'s layout, so the memoized
  ``_wire`` attribute of deserialized/native-built shares is reused
  byte-for-byte, and the all-zero encoding is the point at infinity);
- scalars as width-bucketed big-endian bytes (24 B for the 192-bit
  product-form RLC coefficients of ``harness/batching.py``).

120 B/point instead of ~650 — the tunnel term drops ~5.4×.  A small
XLA program (``_unpack_jit``) expands bytes → 11-bit limbs → the
tile-transposed ``[G, 3, L, 128]`` kernel layout *on device*, then the
existing cached ``win_g1`` Pallas executable and the XLA tree
reduction run unchanged (three dispatches, all intermediate arrays
device-resident; only the final ``[3, L]`` sum returns to host).

The entry points are **async**: ``g1_msm_packed_async`` returns a
zero-arg finalizer after enqueueing the transfers + compute, so the
caller overlaps the device MSM with host-side work (the fused flush
runs its G2 MSMs and transcript pairings while the device leg is in
flight — ``harness/batching.py``).

Replaces the hot path of the reference's per-share loop
(``honey_badger.rs:422-444``) at co-simulation scale; same results,
bit-identical to the host path (asserted in ``tests/test_packed.py``).
"""

from __future__ import annotations

import functools
import os
import threading
from typing import Any, Callable, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from . import limbs as LB
from . import pallas_ec
from . import staging

# Scalars ship as ceil(width/8) big-endian bytes; ec_jax._width's
# buckets (128/160/192/255 bits) keep the set of compiled kernel
# shapes small (4-bit windows → nwin = 2·nbytes per bucket).

# Largest point count one unpack+reduce program spans (the tree
# reduction's first levels materialize [K/2, 38, 38] int32
# intermediates — ~9.5 GB at 512k with tiling padding, measured HBM
# OOM on v5e).  Bigger batches run in equal-shape chunks whose
# executables are shared and whose transfers/computes overlap via
# async dispatch.
_MAX_CHUNK = 1 << 18


def _bucket_rows(k: int) -> int:
    """Round K up to a power-of-two multiple of the 128-lane tile."""
    return pallas_ec._bucket_tiles(max(1, -(-k // pallas_ec.TILE))) * pallas_ec.TILE


def _allow_compile() -> bool:
    """Cold Mosaic/XLA compiles are minutes each on this class of host;
    production routing only uses shapes with warm executables unless a
    warming entry point (bench, hardware smoke) sets HBBFT_TPU_WARM=1."""
    return os.environ.get("HBBFT_TPU_WARM", "0") == "1"


def _product_engine() -> str:
    """The device engine a product/flat flush uses on THIS backend:

    - ``"pallas"`` — real TPU: the cached windowed Pallas kernel.
    - ``"xla"`` — the executable cache is the compile authority but
      there is no TPU (CPU AOT runs, ``HBBFT_TPU_AOT=1``): fused XLA
      programs through the same ``.palexe`` cache + cold-guard, so a
      restarted CPU host gets the identical never-compile-on-the-flush
      property (the r05-class multi-minute XLA scan compiles were the
      CPU cold wall).
    - ``"interp"`` — plain CPU (tests, default): eager jit paths,
      behavior unchanged from before the AOT work.
    """
    if jax.default_backend() == "tpu":
        return "pallas"
    if pallas_ec.exec_cache_active():
        return "xla"
    return "interp"


def _tree_parts(kp: int, g2: bool = False):
    """The executable-cache keys the tree reduction will need — one
    home for both groups (the shapes differ only in the Fq2 axis and
    the chunk constant)."""
    L = LB.FQ_LIMBS
    chunk = pallas_ec._TREE_CHUNK_G2 if g2 else pallas_ec._TREE_CHUNK_G1
    name = "tree_g2" if g2 else "tree_g1"
    mid = (3, 2, L) if g2 else (3, L)
    if kp <= chunk:
        return [(name, (((kp,) + mid, "int32"),))]
    return [
        (name, (((chunk,) + mid, "int32"),)),
        (name, (((kp // chunk,) + mid, "int32"),)),
    ]


def _flat_exec_keys(
    kp: int, nb: int, g2: bool = False, engine: str = "pallas"
):
    """The ``(name, key_parts)`` of every executable one flat packed
    chunk needs on the given engine (G1 or G2 — the guard keys mirror
    exactly what the device path will build, so the two groups share
    one home and cannot drift separately).  The XLA engine fuses
    unpack + scalar-mul + tree into ONE cached program per chunk shape;
    interpret mode needs none."""
    L = LB.FQ_LIMBS
    T = pallas_ec.TILE
    G = kp // T
    if engine == "xla":
        return [
            (
                "flat_g2_xla" if g2 else "flat_g1_xla",
                (((kp, 192 if g2 else 96), "uint8"), ((kp, nb), "uint8")),
            )
        ]
    if engine != "pallas":
        return []
    if g2:
        checks = [
            ("unpack_g2_v1", (((kp, 192), "uint8"), ((kp, nb), "uint8"))),
            ("win_g2", ((G, 3, 2, L, T), (G, nb * 2, T))),
        ]
    else:
        checks = [
            ("unpack_g1_v1", (((kp, 96), "uint8"), ((kp, nb), "uint8"))),
            ("win_g1", ((G, 3, L, T), (G, nb * 2, T))),
        ]
    return checks + _tree_parts(kp, g2)


def _flat_ready(
    kp: int, nb: int, g2: bool = False, engine: str = "pallas"
) -> bool:
    """All executables of one flat packed chunk are warm."""
    return all(
        pallas_ec.exec_available(n, p)
        for n, p in _flat_exec_keys(kp, nb, g2, engine)
    )


def _product_exec_keys(
    kd: int, n_groups: int, compressed: bool, engine: str = "pallas"
):
    """The ``(name, key_parts)`` of every executable ONE
    factored-product device chunk needs — the ONE home shared by the
    warm-routing guard (:func:`_product_ready`) and the warm-start
    prewarmer (:func:`prewarm_plan`), so what the prewarmer loads can
    never drift from what routing requires.

    ``kd`` is the chunk's true point count (``n_groups`` × group size).
    The v2 unpack programs are keyed on the EXACT ``kd`` — the tunnel
    ships kd rows and the bucket padding to ``kp`` happens on device
    inside the unpack program (the v1 programs padded on host); the
    key space stays bounded because ``_split_plan`` quantizes kd.  The
    XLA engine (CPU AOT) fuses unpack + scalar-mul + group-tree into
    ONE cached program per chunk; interpret mode needs none."""
    L = LB.FQ_LIMBS
    T = pallas_ec.TILE
    kp = _bucket_rows(kd)
    G = kp // T
    nb = _S_BITS // 8
    if engine == "xla":
        # the XLA engine always ships the uncompressed 96-byte wire:
        # the compressed path's on-device sqrt exists to trade tunnel
        # bytes for TPU compute, a trade that has no meaning on-host
        return [
            (
                "prod_g1_xla_%d" % n_groups,
                (((kd, 96), "uint8"), ((kd, nb), "uint8")),
            )
        ]
    if engine != "pallas":
        return []
    if compressed:
        unpack = (
            "unpack_g1c_v2",
            (
                ((kd, 48), "uint8"),
                ((kd,), "uint8"),
                ((kd, nb), "uint8"),
            ),
        )
    else:
        unpack = (
            "unpack_g1_v2",
            (((kd, 96), "uint8"), ((kd, nb), "uint8")),
        )
    return [
        unpack,
        ("win_g1", ((G, 3, L, T), (G, nb * 2, T))),
        ("gtree_g1_%d" % n_groups, (((kd, 3, L), "int32"),)),
    ]


def _product_ready(
    kd: int, n_groups: int, compressed: bool, engine: str = "pallas"
) -> bool:
    """All executables of ONE factored-product device chunk are warm."""
    return all(
        pallas_ec.exec_available(n, p)
        for n, p in _product_exec_keys(kd, n_groups, compressed, engine)
    )


# ---------------------------------------------------------------------------
# Host-side marshalling: points/scalars → packed wire bytes
# ---------------------------------------------------------------------------


def g1_wires_batch(points: Sequence[Any]) -> np.ndarray:
    """[K, 96] uint8 of uncompressed affine encodings.

    Points deserialized from the network or built by the native ops
    carry a memoized ``_wire`` (``native.g1_wire``) and cost one dict
    lookup each.  The rest are normalized together through
    ``crypto.curve.G1.batch_affine`` (one shared Montgomery batch
    inversion, not a Python ``pow`` per point); both the wire and the
    compressed ``to_bytes`` memos are filled from that single
    normalized batch (``G1.batch_serialize``), so later cache keying
    never re-inverts the same points.
    """
    from ..crypto.curve import G1

    n = len(points)
    out = np.empty((n, 96), dtype=np.uint8)
    slow: List[int] = []
    for i, pt in enumerate(points):
        w = getattr(pt, "_wire", None)
        if w is not None and len(w) == 96:
            out[i] = np.frombuffer(w, dtype=np.uint8)
        else:
            slow.append(i)
    if slow:
        slow_pts = [points[i] for i in slow]
        affs = G1.batch_affine(slow_pts)
        for i, pt, aff in zip(slow, slow_pts, affs):
            w = G1._wire_affine(aff)  # infinity = native's all-zero row
            out[i] = np.frombuffer(w, dtype=np.uint8)
            # memoize both encodings for the next flush / cache keying
            try:
                pt._wire = w
                if getattr(pt, "_cbytes", None) is None:
                    pt._cbytes = G1._encode_affine(aff)
            except AttributeError:
                pass
    return out


def scalar_bytes_batch(scalars: Sequence[int], nbytes: int) -> np.ndarray:
    """[K, nbytes] uint8, big-endian, reduced mod r (one marshalling
    home shared with the host bit path — ``limbs.scalars_to_be_bytes``)."""
    return LB.scalars_to_be_bytes(scalars, nbytes)


# ---------------------------------------------------------------------------
# Device-side unpack (XLA; no Pallas — compiles in seconds, cached)
# ---------------------------------------------------------------------------


def _bytes_to_bits_msb(x: jnp.ndarray) -> jnp.ndarray:
    """[..., B] int32 bytes → [..., B*8] bits, msb-first."""
    shifts = jnp.arange(7, -1, -1, dtype=jnp.int32)
    bits = jnp.bitwise_and(
        jnp.right_shift(x[..., None], shifts), jnp.int32(1)
    )
    return bits.reshape(x.shape[:-1] + (x.shape[-1] * 8,))


def _le_bits_to_limbs(le_bits: jnp.ndarray) -> jnp.ndarray:
    """[K, 384] little-endian bits → [K, L] 11-bit limbs (int32)."""
    L = LB.FQ_LIMBS
    K = le_bits.shape[0]
    pad = L * LB.LIMB_BITS - le_bits.shape[1]
    p = jnp.pad(le_bits, ((0, 0), (0, pad)))
    p = p.reshape(K, L, LB.LIMB_BITS)
    w = jnp.left_shift(jnp.int32(1), jnp.arange(LB.LIMB_BITS, dtype=jnp.int32))
    return jnp.sum(p * w, axis=-1, dtype=jnp.int32)


def _assemble_points(
    xl: jnp.ndarray, yl: jnp.ndarray, ident: jnp.ndarray
) -> jnp.ndarray:
    """(x, y) coordinate limbs ([Kp, L] for G1, [Kp, 2, L] for G2) +
    identity mask → [Kp, 3, (2,) L] projective points, with flagged
    rows (infinity encodings, bucket padding) set to the projective
    identity (0 : 1 : 0) — the ONE home for that encoding across the
    uncompressed, compressed, and G2 unpack paths."""
    Kp = xl.shape[0]
    coord = xl.shape[1:]
    one = jnp.zeros(coord, jnp.int32)
    one = one.at[(0,) * len(coord)].set(1)
    mask = ident.reshape((Kp,) + (1,) * len(coord))
    yl = jnp.where(mask, one[None], yl)
    xl = jnp.where(mask, jnp.int32(0), xl)
    zl = jnp.zeros_like(xl)
    zl = zl.at[(slice(None),) + (0,) * len(coord)].set(
        jnp.where(ident, 0, 1).astype(jnp.int32)
    )
    return jnp.stack([xl, yl, zl], axis=1)


def _scalar_digits(sc_u8: jnp.ndarray) -> jnp.ndarray:
    """[Kp, nb] scalar bytes → [Kp, 2·nb] 4-bit window digits."""
    Kp, nb = sc_u8.shape
    sbits = _bytes_to_bits_msb(sc_u8.astype(jnp.int32))
    d = sbits.reshape(Kp, nb * 2, 4)
    return (
        (d[..., 0] << 3) | (d[..., 1] << 2) | (d[..., 2] << 1) | d[..., 3]
    )


def _tile_layout(pts: jnp.ndarray, dig: jnp.ndarray):
    """[Kp, 3, (2,) L] + [Kp, nwin] → the kernel's tile-transposed
    ([G, 3, (2,) L, T], [G, nwin, T]) layout, G1 and G2 alike."""
    T = pallas_ec.TILE
    Kp = pts.shape[0]
    mid = pts.shape[1:]
    nwin = dig.shape[1]
    G = Kp // T
    perm = (0,) + tuple(range(2, 2 + len(mid))) + (1,)
    pts_t = pts.reshape((G, T) + mid).transpose(perm)
    dig_t = dig.reshape(G, T, nwin).transpose(0, 2, 1)
    return pts_t, dig_t


def _sqrt_chain(w: jnp.ndarray) -> jnp.ndarray:
    """Batched square root in Fq: w^((p+1)/4) over [..., L] limbs
    (valid because p ≡ 3 mod 4; w must be a quadratic residue, which
    every x³+4 of an on-curve point is).  A fixed 379-bit
    square-and-multiply — ~570 field muls, fully data-independent."""
    f = LB.fq()
    e = (LB.P + 1) // 4
    bits = bin(e)[2:]  # msb-first, leading bit 1
    acc = w
    for b in bits[1:]:
        acc = f.mul(acc, acc)
        if b == "1":
            acc = f.mul(acc, w)
    return acc


def _unpack_compressed_core(
    x_u8: jnp.ndarray,
    parity: jnp.ndarray,
    ident: jnp.ndarray,
    sc_u8: jnp.ndarray,
):
    """Shared body of the compressed unpack programs: [Kp, 48] x-bytes
    + [Kp] parity bits + [Kp] identity mask + [Kp, nb] scalar bytes →
    the kernel's (pts_t, dig_t) layout.

    y is RECOVERED on device (y = sqrt(x³+4), sign-corrected against
    the parity bit) — the tunnel ships ~49 bytes per point instead of
    96, and the sqrt chain costs a fraction of the windowed kernel's
    scan (measured r4).  Only points this process serialized itself
    are shipped compressed (always on-curve), so the root always
    exists."""
    L = LB.FQ_LIMBS
    f = LB.fq()

    xb = _bytes_to_bits_msb(x_u8.astype(jnp.int32))  # [Kp, 384]
    xl = _le_bits_to_limbs(jnp.flip(xb, axis=1))

    four = jnp.zeros((L,), jnp.int32).at[0].set(4)
    w = f.add(f.mul(f.mul(xl, xl), xl), four[None, :])
    yl = _sqrt_chain(w)
    # canonicalize to read the true parity bit, negate where it differs
    y_canon = f.canon(yl)
    neg = (y_canon[:, 0] & 1) != parity
    yl = jnp.where(neg[:, None], f.neg(y_canon), y_canon)
    pts = _assemble_points(xl, yl, ident)
    return _tile_layout(pts, _scalar_digits(sc_u8))


def _unpack_fn_compressed(
    x_u8: jnp.ndarray, meta_u8: jnp.ndarray, sc_u8: jnp.ndarray
):
    """v1 compressed-wire unpack: [Kp, 48] x-bytes (HOST-padded to the
    tile bucket) + [2, Kp/8] packed meta bits (row 0: y parity, row 1:
    infinity/padding flag) + [Kp, nb] scalar bytes."""
    Kp = x_u8.shape[0]
    meta_bits = _bytes_to_bits_msb(meta_u8.astype(jnp.int32))  # [2, Kp]
    parity = meta_bits[0, :Kp]
    ident = meta_bits[1, :Kp].astype(bool)
    return _unpack_compressed_core(x_u8, parity, ident, sc_u8)


def _unpack_fn_compressed_v2(
    x_u8: jnp.ndarray, meta_u8: jnp.ndarray, sc_u8: jnp.ndarray
):
    """v2 compressed-wire unpack: EXACT [kd, 48] x-bytes + [kd] meta
    bytes (bit 0: y parity, bit 1: infinity flag) + [kd, nb] scalar
    bytes.  The tile-bucket padding happens HERE, on device — the
    tunnel carries only real rows, and the host marshal is one column
    copy plus one vectorized meta-byte expression (the remaining
    byte-wrangling of the v1 ``compress_rows`` — pad buffers, packbits
    — moved into this program)."""
    kd = x_u8.shape[0]
    kp = _bucket_rows(kd)
    x_u8 = jnp.pad(x_u8, ((0, kp - kd), (0, 0)))
    # pad meta = 2: the infinity flag, so pad rows become the identity
    meta = jnp.pad(
        meta_u8.astype(jnp.int32), (0, kp - kd), constant_values=2
    )
    sc_u8 = jnp.pad(sc_u8, ((0, kp - kd), (0, 0)))
    parity = jnp.bitwise_and(meta, 1)
    ident = jnp.bitwise_and(jnp.right_shift(meta, 1), 1).astype(bool)
    return _unpack_compressed_core(x_u8, parity, ident, sc_u8)


def _unpack_fn(pts_u8: jnp.ndarray, sc_u8: jnp.ndarray):
    """[Kp, 96] u8 + [Kp, nb] u8 → (pts_t [G, 3, L, T], dig_t [G, nwin, T]).

    All-zero point rows (the ``native.g1_wire`` infinity encoding, and
    the bucket padding) become the projective identity (0 : 1 : 0).
    """
    pts = _wire_points_g1(pts_u8)
    return _tile_layout(pts, _scalar_digits(sc_u8))


def _wire_points_g1(pts_u8: jnp.ndarray) -> jnp.ndarray:
    """[K, 96] u8 wires → [K, 3, L] projective point limbs (all-zero
    rows → identity) — the unpack math shared by the tile-layout
    programs, the mesh shard body, and the fused XLA engine."""
    b = _bytes_to_bits_msb(pts_u8.astype(jnp.int32))  # [K, 768]
    xl = _le_bits_to_limbs(jnp.flip(b[:, :384], axis=1))
    yl = _le_bits_to_limbs(jnp.flip(b[:, 384:], axis=1))
    ident = jnp.all(pts_u8 == 0, axis=1)
    return _assemble_points(xl, yl, ident)


def _unpack_fn_v2(pts_u8: jnp.ndarray, sc_u8: jnp.ndarray):
    """v2 uncompressed unpack: EXACT [kd, 96] wire rows + [kd, nb]
    scalar bytes, tile-bucket padding ON DEVICE (a zero wire row is
    the infinity encoding and a zero scalar contributes nothing, so
    zero-padding is absorbing by construction).  Kills the host-side
    pad-buffer copy of the v1 marshal: ``ship`` is the raw transfer."""
    kd = pts_u8.shape[0]
    kp = _bucket_rows(kd)
    pts_u8 = jnp.pad(pts_u8, ((0, kp - kd), (0, 0)))
    sc_u8 = jnp.pad(sc_u8, ((0, kp - kd), (0, 0)))
    return _unpack_fn(pts_u8, sc_u8)


@functools.lru_cache(maxsize=None)
def _unpack_jit():
    return jax.jit(_unpack_fn)


def _unpack_device(dev_pts, dev_sc):
    if jax.default_backend() == "tpu":
        return pallas_ec.cached_compiled(
            "unpack_g1_v1", _unpack_fn, dev_pts, dev_sc
        )
    return _unpack_jit()(dev_pts, dev_sc)


@functools.lru_cache(maxsize=None)
def _unpack_compressed_jit():
    return jax.jit(_unpack_fn_compressed)


def _unpack_compressed_device(dev_x, dev_meta, dev_sc):
    if jax.default_backend() == "tpu":
        return pallas_ec.cached_compiled(
            "unpack_g1c_v1", _unpack_fn_compressed, dev_x, dev_meta, dev_sc
        )
    return _unpack_compressed_jit()(dev_x, dev_meta, dev_sc)


@functools.lru_cache(maxsize=None)
def _unpack_jit_v2():
    return jax.jit(_unpack_fn_v2)


def _unpack_device_v2(dev_pts, dev_sc):
    """The product flush's uncompressed unpack (exact rows, device-side
    pad).  Donates the staged wire/scalar buffers: the unpack consumes
    them in one pass and the lease protocol guarantees the host never
    touches them again before ``retire()``."""
    if jax.default_backend() == "tpu":
        return pallas_ec.cached_compiled(
            "unpack_g1_v2", _unpack_fn_v2, dev_pts, dev_sc, donate=(0, 1)
        )
    return _unpack_jit_v2()(dev_pts, dev_sc)


@functools.lru_cache(maxsize=None)
def _unpack_compressed_jit_v2():
    return jax.jit(_unpack_fn_compressed_v2)


def _unpack_compressed_device_v2(dev_x, dev_meta, dev_sc):
    if jax.default_backend() == "tpu":
        return pallas_ec.cached_compiled(
            "unpack_g1c_v2",
            _unpack_fn_compressed_v2,
            dev_x,
            dev_meta,
            dev_sc,
            donate=(0, 1, 2),
        )
    return _unpack_compressed_jit_v2()(dev_x, dev_meta, dev_sc)


# ---------------------------------------------------------------------------
# Fused XLA engine programs (CPU AOT, HBBFT_TPU_AOT=1) — one cached
# executable per chunk shape, so a restarted CPU host never compiles
# on the flush path either (the multi-minute XLA scan compile of
# ``ec_jax.g1_msm_device`` was the measured r05-class CPU cold wall).
# ---------------------------------------------------------------------------


def _prod_xla_fn(n_groups: int):
    """Build the fused product-chunk program: [kd, 96] wires +
    [kd, nb] scalars → [n_groups, 3, L] group sums (unpack →
    bit-serial scalar-mul scan → per-group tree, one program)."""
    from . import ec_jax

    def fn(pts_u8, sc_u8):
        pts = _wire_points_g1(pts_u8)
        bits = _bytes_to_bits_msb(sc_u8.astype(jnp.int32))
        prods = ec_jax.g1_kernel().scalar_mul(pts, bits)
        return _group_tree(prods, n_groups)

    return fn


def _flat_xla_fn(g2: bool):
    """Build the fused flat-chunk program: [kp, 96|192] wires +
    [kp, nb] scalars → one [3, (2,) L] partial sum."""
    from . import ec_jax

    def fn(pts_u8, sc_u8):
        if g2:
            pts = _wire_points_g2(pts_u8)
            kern = ec_jax.g2_kernel()
        else:
            pts = _wire_points_g1(pts_u8)
            kern = ec_jax.g1_kernel()
        bits = _bytes_to_bits_msb(sc_u8.astype(jnp.int32))
        return kern.msm(pts, bits)

    return fn


def _flat_msm_xla(pts_u8: np.ndarray, sc_u8: np.ndarray, g2: bool):
    """One flat chunk through the fused XLA engine (cached)."""
    dev_pts = jax.device_put(pts_u8)
    dev_sc = jax.device_put(sc_u8)
    return pallas_ec.cached_compiled(
        "flat_g2_xla" if g2 else "flat_g1_xla",
        _flat_xla_fn(g2),
        dev_pts,
        dev_sc,
        donate=(0, 1),
    )


def _msm_chunk_device(pts_u8, sc_u8, interpret: bool):
    """One chunk: packed bytes (host numpy) → device [3, L] partial sum.

    Three async dispatches — unpack (XLA), windowed Pallas kernel
    (cached executable), tree reduction (XLA) — with every
    intermediate device-resident.  Returns without blocking.
    """
    dev_pts = jax.device_put(pts_u8)  # async H2D
    dev_sc = jax.device_put(sc_u8)
    pts_t, dig_t = _unpack_device(dev_pts, dev_sc)
    out_t = pallas_ec._windowed_tiles(pts_t, dig_t, interpret)
    Kp = pts_u8.shape[0]
    prods = pallas_ec._untile(out_t, Kp, Kp)
    return pallas_ec._tree_sum_chunked(prods, g2=False)


def g1_msm_packed_async(
    points: Sequence[Any],
    scalars: Sequence[int],
    nbits: Optional[int] = None,
    interpret: Optional[bool] = None,
) -> Optional[Callable[[], Any]]:
    """Enqueue the MSM on device and return a zero-arg finalizer.

    The finalizer blocks on the device result and returns the host G1
    point.  Everything before it — marshalling, H2D transfers, the
    three device dispatches — is issued eagerly, so host work between
    call and finalize overlaps the tunnel transfer and device compute.
    """
    from ..crypto.curve import G1
    from . import ec_jax

    if not points:
        return lambda: G1.infinity()
    if interpret is None:
        engine = _product_engine()
    else:
        engine = "interp" if interpret else "pallas"
    interpret = engine != "pallas"
    w = ec_jax._width(scalars, nbits)
    nb = -(-w // 8)
    k = len(points)
    if engine != "interp" and not _allow_compile():
        # cold-compile guard: every chunk shape must be warm
        for lo in range(0, k, _MAX_CHUNK):
            kc = min(_MAX_CHUNK, k - lo)
            if not _flat_ready(_bucket_rows(kc), nb, engine=engine):
                return None
    wires = g1_wires_batch(points)
    sc = scalar_bytes_batch(scalars, nb)

    partials = []
    for lo in range(0, k, _MAX_CHUNK):
        chunk = wires[lo : lo + _MAX_CHUNK]
        sc_chunk = sc[lo : lo + _MAX_CHUNK]
        kc = chunk.shape[0]
        kp = _bucket_rows(kc)
        if kp != kc:
            chunk = np.concatenate(
                [chunk, np.zeros((kp - kc, 96), dtype=np.uint8)]
            )
            sc_chunk = np.concatenate(
                [sc_chunk, np.zeros((kp - kc, nb), dtype=np.uint8)]
            )
        if engine != "interp":
            record_flat_shape(kp, nb, g2=False)
        if engine == "xla":
            partials.append(_flat_msm_xla(chunk, sc_chunk, g2=False))
        else:
            partials.append(_msm_chunk_device(chunk, sc_chunk, interpret))

    def finalize():
        acc = ec_jax.g1_from_limbs(partials[0])
        for part in partials[1:]:
            acc = acc + ec_jax.g1_from_limbs(part)
        return acc

    return finalize


def g1_msm_packed(
    points: Sequence[Any],
    scalars: Sequence[int],
    nbits: Optional[int] = None,
    interpret: Optional[bool] = None,
) -> Any:
    """Blocking wrapper around :func:`g1_msm_packed_async`."""
    fin = g1_msm_packed_async(points, scalars, nbits, interpret)
    if fin is None:
        raise RuntimeError(
            "packed MSM executables are cold for this shape — warm "
            "them with HBBFT_TPU_WARM=1 or route to the host path"
        )
    return fin()


# ---------------------------------------------------------------------------
# Packed-wire G2 MSM (flat) — the DKG verification plane's shape
# ---------------------------------------------------------------------------
# The fused trilinear-RLC check (``harness/dkg.py``) settles every
# row/value cell of a verified DKG in ONE huge G2 MSM over commitment
# entries it already holds as 192-byte wires.  r4 routed G2 host-side
# by a measurement that PREDATES the packed-wire transfer (the device
# lost on ~1.3 KB/point expanded limbs); this path re-runs that
# decision with the same treatment G1 got: wire bytes across the
# tunnel (192 B/point + 32 B scalars), on-device unpack to the
# windowed Fq2 kernel's tile layout, per-chunk tree reductions.

# [K, 3, 2, L] int32 ≈ 912 B/point on device plus ~3× tree
# intermediates: 2^17-point chunks stay comfortably inside HBM and
# keep the per-chunk tunnel floor amortized over ~25 MB transfers.
_MAX_CHUNK_G2 = 1 << 17


def _wire_points_g2(pts_u8: jnp.ndarray) -> jnp.ndarray:
    """[K, 192] u8 wires (x.c0‖x.c1‖y.c0‖y.c1, big-endian — exactly
    ``native.g2_wire``) → [K, 3, 2, L] projective point limbs;
    all-zero rows (infinity encoding, chunk padding) become the
    projective identity via the shared ``_assemble_points`` home."""
    b = _bytes_to_bits_msb(pts_u8.astype(jnp.int32))  # [K, 1536]
    comps = [
        _le_bits_to_limbs(jnp.flip(b[:, i * 384 : (i + 1) * 384], axis=1))
        for i in range(4)
    ]
    x = jnp.stack([comps[0], comps[1]], axis=1)  # [K, 2, L]
    y = jnp.stack([comps[2], comps[3]], axis=1)
    ident = jnp.all(pts_u8 == 0, axis=1)
    return _assemble_points(x, y, ident)


def _unpack_fn_g2(pts_u8: jnp.ndarray, sc_u8: jnp.ndarray):
    """[Kp, 192] u8 + [Kp, nb] u8 scalars → the G2 kernel's
    ([G, 3, 2, L, T], [G, nwin, T]) layout."""
    pts = _wire_points_g2(pts_u8)  # [Kp, 3, 2, L]
    return _tile_layout(pts, _scalar_digits(sc_u8))


@functools.lru_cache(maxsize=None)
def _unpack_g2_jit():
    return jax.jit(_unpack_fn_g2)


def _unpack_g2_device(dev_pts, dev_sc):
    if jax.default_backend() == "tpu":
        return pallas_ec.cached_compiled(
            "unpack_g2_v1", _unpack_fn_g2, dev_pts, dev_sc
        )
    return _unpack_g2_jit()(dev_pts, dev_sc)


def g2_msm_packed_wires_async(
    wires: Sequence[bytes],
    scalars: Sequence[int],
    interpret: Optional[bool] = None,
    nbits: int = 255,
) -> Optional[Callable[[], bytes]]:
    """Enqueue a flat G2 MSM over raw 192-byte wire encodings and
    return a finalizer yielding the result as a wire (the DKG plane
    keeps everything as buffers).  Returns ``None`` when executables
    are cold outside warming mode — the caller stays host-side.
    ``nbits`` defaults to full-width Fr (the trilinear-RLC products);
    tests narrow it to keep CPU interpret mode tractable."""
    from . import ec_jax

    k = len(wires)
    if k == 0:
        return lambda: b"\x00" * 192
    if interpret is None:
        engine = _product_engine()
    else:
        engine = "interp" if interpret else "pallas"
    interpret = engine != "pallas"
    nb = -(-nbits // 8)
    if engine != "interp" and not _allow_compile():
        for lo in range(0, k, _MAX_CHUNK_G2):
            kc = min(_MAX_CHUNK_G2, k - lo)
            if not _flat_ready(_bucket_rows(kc), nb, g2=True, engine=engine):
                return None
    pts_u8 = np.frombuffer(b"".join(wires), dtype=np.uint8).reshape(
        k, 192
    )
    sc = LB.scalars_to_be_bytes(list(scalars), nb)

    partials = []
    for lo in range(0, k, _MAX_CHUNK_G2):
        chunk = pts_u8[lo : lo + _MAX_CHUNK_G2]
        sc_chunk = sc[lo : lo + _MAX_CHUNK_G2]
        kc = chunk.shape[0]
        kp = _bucket_rows(kc)
        if kp != kc:
            chunk = np.concatenate(
                [chunk, np.zeros((kp - kc, 192), dtype=np.uint8)]
            )
            sc_chunk = np.concatenate(
                [sc_chunk, np.zeros((kp - kc, nb), dtype=np.uint8)]
            )
        if engine != "interp":
            record_flat_shape(kp, nb, g2=True)
        if engine == "xla":
            partials.append(_flat_msm_xla(chunk, sc_chunk, g2=True))
            continue
        dev_pts = jax.device_put(chunk)
        dev_sc = jax.device_put(sc_chunk)
        pts_t, dig_t = _unpack_g2_device(dev_pts, dev_sc)
        out_t = pallas_ec._windowed_g2_tiles(pts_t, dig_t, interpret)
        prods = pallas_ec._untile(out_t, kp, kp)
        partials.append(pallas_ec._tree_sum_chunked(prods, g2=True))

    def finalize() -> bytes:
        from .. import native as NT

        acc = ec_jax.g2_from_limbs(partials[0])
        for part in partials[1:]:
            acc = acc + ec_jax.g2_from_limbs(part)
        return NT.g2_wire(acc)  # pure-Python wire encode (no lib call)

    return finalize


# ---------------------------------------------------------------------------
# Factored product-form MSM: Σ_g t_g · (Σ_{i∈g} sᵢ·Pᵢ)
# ---------------------------------------------------------------------------
# The fused flush's aggregate (``backend.g1_msm_product_async``
# contract).  The device evaluates the factored form directly: one
# 96-bit-scalar kernel pass (24 windows — HALF the 192-bit product
# width), a per-group tree reduction on device, then the tiny t-MSM
# over the G group sums on host.  A scan kernel pays per-point
# doublings per window, so halving the window count halves its
# dominant cost — structure host Pippenger cannot exploit.

_S_BITS = 96  # product-form sender coefficients (batching.py coeff())


def _compress_env() -> Optional[bool]:
    """Operator override for the compressed 48-byte-x transfer with
    on-device y recovery: ``HBBFT_TPU_COMPRESS=1`` forces it on, ``0``
    forces it off, unset lets the controller choose per shape from
    measured rates (:func:`_choose_compressed`)."""
    env = os.environ.get("HBBFT_TPU_COMPRESS")
    if env is None:
        return None
    return env == "1"


def _use_compressed() -> bool:
    """Back-compat predicate: forced-on only (plan-shape warm checks
    use the uncompressed executables unless compression is forced)."""
    return _compress_env() is True


# flushes between compressed-transfer trials: the controller keeps a
# separate device-rate EMA for the compressed wire ("dc") and ships
# whichever mode measures faster — the 48-byte path exists for
# link-bound regimes (loaded tunnel), and this probe is how the regime
# is DETECTED instead of the path shipping dark behind an env switch
# (VERDICT r4 weak #7 / next-8)
_COMPRESS_PROBE_IV = 16


def _choose_compressed(n: int, n_groups: int, plan: List[int]) -> bool:
    """Per-flush transfer-mode decision for the device chunks."""
    env = _compress_env()
    if env is not None:
        return env
    if jax.default_backend() != "tpu":
        return False
    st = _rho_state().get("%d:%d" % (n, n_groups))
    if not isinstance(st, dict):
        return False
    kpn = n  # group size = points per group
    warm = _allow_compile() or all(
        _product_ready(g * kpn, g, True) for g in plan
    )
    d, dc = st.get("d"), st.get("dc")
    if dc is None or st.get("cage", 0) >= _COMPRESS_PROBE_IV:
        return warm  # compressed trial (skipped while executables cold)
    if st.get("dage", 0) >= _COMPRESS_PROBE_IV:
        # symmetric staleness: a compressed-winning streak must not
        # pin the UNCOMPRESSED rate forever (the tunnel idling again
        # would otherwise never be detected) — probe the 96-byte wire
        return False
    return bool(warm and d and dc > d)


def _env_fraction() -> Optional[float]:
    """Operator override for the device share of a product flush
    (HBBFT_TPU_DEVICE_FRACTION, 0 = all host, 1 = all device).  When
    set it pins EVERY shape and disables the measured controller below
    — the bench uses it to force the pure-engine comparison legs."""
    import math

    env = os.environ.get("HBBFT_TPU_DEVICE_FRACTION")
    if env is None:
        return None
    try:
        rho = float(env)
    except ValueError:
        return None  # malformed override: fall back to the controller
    return rho if math.isfinite(rho) else None


# Measured host/device balance, per flush shape ("n:n_groups" →
# {"rho", "d", "h", "hage"}).  The finalizer's controller (``_adapt``)
# keeps EMA estimates of each engine's end-to-end rate (points/s) and
# solves for the split where the device half (which also covers the
# caller's overlapped G2/pairing work) finishes just as the host half
# does — the split then tracks the *actual* load regime (idle vs
# contended CPU, tunnel weather) instead of a compile-time constant,
# and the hybrid flush stays ≥ the better single engine in either
# regime.  Persisted next to the executable cache so a fresh process
# starts from the last measured balance instead of 0.5.
#
# r5 redesign (VERDICT r4 missing #1): the r4 controller could only
# measure the device rate when the device half *straggled* past an
# RPC-floor deadband — a small share almost never straggles, early
# finishes yielded useless lower bounds, and the probe ratchet backed
# off, so the estimate froze 5.6× low and the shipping flush lost to
# its own device-only leg.  Now a waiter thread stamps the wall at
# which the device group sums actually materialize, so EVERY flush
# yields an exact device-rate sample and the probe/ratchet machinery
# is gone.  The bench's forced single-engine legs additionally seed
# the state directly (``seed_rates``) instead of being thrown away.
_RHO_DEFAULT = 0.5
_RHO_STATE: Optional[dict] = None

# One lock for ALL controller/warm-shape state in this module
# (_RHO_STATE and its nested per-shape dicts, _WARM_SEEN, _PREWARM,
# the warm_shapes.json read-merge-replace): the finalizer's waiter
# thread, the prewarm daemon and the main flush path all touch it.
# RLock because the guarded helpers nest (_adapt → _shape_state →
# _rho_state → _save_rho).  Never held across a pallas_ec call that
# takes _EXEC_LOCK — the two stay unordered.
_STATE_LOCK = threading.RLock()

# flushes between forced host-rate refreshes once the solved split
# covers every group (an all-device plan has no host tail to measure,
# and a stale ``h`` could otherwise freeze the split at full-device
# through a host-side regime change)
_HOST_PROBE_IV = 8


def _rho_path() -> str:
    from . import pallas_ec

    return os.path.join(pallas_ec._exec_cache_dir(), "device_fraction.json")


def _rho_state() -> dict:
    global _RHO_STATE
    if _RHO_STATE is None:
        with _STATE_LOCK:
            if _RHO_STATE is None:
                import json

                state: dict = {}
                try:
                    with open(_rho_path()) as fh:
                        raw = json.load(fh)
                except Exception:
                    raw = {}
                for k, v in raw.items() if isinstance(raw, dict) else ():
                    try:  # per-entry: one malformed entry must not drop
                        # the rest
                        if isinstance(v, dict):
                            if 0.0 < float(v.get("rho", -1)) <= 1.0:
                                state[str(k)] = {
                                    "rho": float(v["rho"]),
                                    "d": float(v["d"]) if v.get("d") else None,
                                    "h": float(v["h"]) if v.get("h") else None,
                                    "hage": int(v.get("hage", 0)),
                                    "dc": float(v["dc"]) if v.get("dc") else None,
                                    "cage": int(v.get("cage", 0)),
                                    "dage": int(v.get("dage", 0)),
                                }
                        elif 0.0 < float(v) < 1.0:  # legacy bare-rho entries
                            state[str(k)] = {
                                "rho": float(v), "d": None, "h": None
                            }
                    except (TypeError, ValueError):
                        continue
                _RHO_STATE = state
    return _RHO_STATE


def _save_rho() -> None:
    import json

    try:
        path = _rho_path()
        tmp = path + ".tmp.%d" % os.getpid()
        with _STATE_LOCK:  # snapshot while no flush/waiter mutates it
            payload = json.dumps(_rho_state())
        with open(tmp, "w") as fh:
            fh.write(payload)
        os.replace(tmp, path)
    except Exception:
        pass  # best-effort: losing the hint only costs re-convergence


def _shape_key(n: int, n_groups: int, mesh_dev: int = 0) -> str:
    """Controller state key of one flush shape.  A mesh flush learns
    its OWN balance per device count (``…:mD``) — the sharded engine's
    rate has nothing to do with the single-device rate, and the bench's
    per-device-count children must not poison each other's EMAs."""
    if mesh_dev > 1:
        return "%d:%d:m%d" % (n, n_groups, mesh_dev)
    return "%d:%d" % (n, n_groups)


def learned_fraction(n: int, n_groups: int, mesh_dev: int = 0) -> float:
    """The device fraction a flush of ``n_groups`` groups of ``n``
    points would use right now (env override or learned balance)."""
    env = _env_fraction()
    if env is not None:
        return env
    with _STATE_LOCK:
        v = _rho_state().get(_shape_key(n, n_groups, mesh_dev))
        if v is None:
            return _RHO_DEFAULT
        if isinstance(v, dict):
            return v.get("rho", _RHO_DEFAULT)
        return float(v)


def _shape_state(n: int, n_groups: int, mesh_dev: int = 0) -> dict:
    key = _shape_key(n, n_groups, mesh_dev)
    with _STATE_LOCK:
        state = _rho_state()
        st = state.get(key)
        if not isinstance(st, dict):
            st = {"rho": st if isinstance(st, float) else _RHO_DEFAULT,
                  "d": None, "h": None, "hage": 0, "dc": None, "cage": 0}
            state[key] = st
        return st


def _solve_rho(st: dict, K: float, t_caller: float) -> None:
    """Re-solve the split from the current rate estimates:

        rho·K/d  =  t_caller + (1-rho)·K/h

    (the device half finishes just as the host half does, the device
    covering the caller's overlapped G2/pairing work for free), i.e.
    ``rho* = (t_caller + K/h) / (K/d + K/h)``.  ``d`` is the better of
    the two transfer modes' measured rates (the mode the next flush
    will ship)."""
    d = max((r for r in (st.get("d"), st.get("dc")) if r), default=None)
    h = st.get("h")
    if d and h and K:
        rho = (t_caller + K / h) / (K / d + K / h)
        st["rho"] = min(1.0, max(0.02, rho))


def _adapt(
    n: int,
    n_groups: int,
    k_dev: int,
    k_host: int,
    t_caller: float,
    t_host: float,
    t_dev: float,
    compressed: bool = False,
    mesh_dev: int = 0,
) -> None:
    """One rate-balance step from one hybrid flush's measurements.

    ``t_caller`` is the launch→finalize gap (the caller's G2 MSMs +
    pairings that the device half overlaps), ``t_host`` the finalizer's
    host-Pippenger wall, ``t_dev`` the EXACT device wall — launch →
    the device group sums materializing on host, stamped by the
    finalizer's waiter thread.  Both engine rates are therefore exact
    samples every flush (the r4 controller could only measure ``d``
    when the device half straggled, which a small share never does —
    the estimate froze 5.6× low and the shipping flush lost to its own
    device-only leg; VERDICT r4 missing #1).  EMAs smooth tunnel/load
    noise; a slew-rate clip bounds one pathological flush's damage to
    3×; the solved split converges in a couple of flushes and
    re-converges when the load regime shifts."""
    with _STATE_LOCK:  # one balance step is atomic vs waiter/prewarm
        st = _shape_state(n, n_groups, mesh_dev)
        if k_host > 0:
            h_obs = k_host / max(t_host, 1e-6)
            if st["h"] is None:
                st["h"] = h_obs
            else:
                h_obs = min(max(h_obs, st["h"] / 3.0), st["h"] * 3.0)
                st["h"] = 0.5 * st["h"] + 0.5 * h_obs
            st["hage"] = 0
        else:
            # all-device plan: the host rate went unmeasured — count the
            # staleness so _split_plan can reserve a probe chunk
            st["hage"] = st.get("hage", 0) + 1
        if k_dev > 0:
            # the compressed and uncompressed transfers keep SEPARATE
            # device-rate EMAs ("dc" / "d"); the shipping mode is whichever
            # measures faster, re-probed every _COMPRESS_PROBE_IV flushes
            slot = "dc" if compressed else "d"
            d_obs = k_dev / max(t_dev, 1e-6)
            if st.get(slot) is None:
                st[slot] = d_obs
            else:
                d_obs = min(max(d_obs, st[slot] / 3.0), st[slot] * 3.0)
                st[slot] = 0.5 * st[slot] + 0.5 * d_obs
            # mode-staleness counters, symmetric: each mode's counter
            # resets on its own sample and grows on the other's
            st["cage"] = 0 if compressed else st.get("cage", 0) + 1
            st["dage"] = st.get("dage", 0) + 1 if compressed else 0
        _solve_rho(st, float(k_dev + k_host), t_caller)
        _save_rho()


def seed_rates(
    n: int,
    n_groups: int,
    d: Optional[float] = None,
    h: Optional[float] = None,
    mesh_dev: int = 0,
) -> None:
    """Write exact single-engine rates (points/s) into the controller
    state and re-solve the split.

    The bench's forced device-only and host-only legs measure the
    rates the controller estimates, every round — feeding their
    medians here (instead of discarding them, the r4 defect) means the
    shipping flush starts a capture at the measured balance rather
    than converging across its first flushes.  Leg medians are
    END-TO-END walls (serialize + transcript + pairings included), so
    they are LOWER BOUNDS on the engine-only rates the controller's
    EMAs track — a seed therefore only ever RAISES an estimate, never
    overwrites a converged (higher) one."""
    with _STATE_LOCK:
        st = _shape_state(n, n_groups, mesh_dev)
        if d:
            st["d"] = max(st.get("d") or 0.0, float(d))
        if h:
            st["h"] = max(st.get("h") or 0.0, float(h))
            st["hage"] = 0
        # t_caller unknown here: solve the pure rate balance (the caller
        # term only nudges the split further device-ward; the first real
        # flush re-solves with it measured)
        _solve_rho(st, 1.0, 0.0)
        _save_rho()


# Largest device share of one product flush: the per-group tree is a
# single unrolled program (no chunking), so its row count stays at the
# scale proven on hardware — 2^16 rows compiles in ~2 min and fits
# HBM comfortably; 2^18 is the 197 s / ~GB-intermediates regime the
# flat path chunks at 2^14 to avoid.
_MAX_GTREE = 1 << 16


# Chunk-size ladder, as multiples of the split quantum ``q``,
# largest-first.  The r5 A/B at the headline shape measured the
# per-chunk tunnel cost directly: 16×4-group chunks 2.24 s, 8×8 1.3-1.7,
# 2×32 0.58-1.2, 1×64 1.3 s — fewest-chunks wins until a single chunk
# loses the transfer/compute overlap between chunks.  {8q, 2q, q}
# decomposes any quantum count into ≤ ~5 chunks while every headline-
# shape size stays on warm executables.
_CHUNK_LADDER = (8, 2, 1)


def _split_plan(k: int, n_groups: int) -> List[int]:
    """Group-counts of the device chunks of a uniform-group product
    flush (the LEADING ``sum(plan)`` groups run on device, the rest on
    host).  The device share moves in whole quanta ``q`` (shape-only,
    ≥16 steps of resolution — r4's //8 left the measured optimum
    ρ*≈0.54 unrepresentable), then the chosen quanta are packed into
    the FEWEST chunks via the ``_CHUNK_LADDER`` sizes: each chunk pays
    a tunnel RPC floor, so chunk count — not chunk size — dominated
    the r5 device-leg A/B.  Each chunk stays within the proven
    per-group-tree scale (``_MAX_GTREE`` rows); its transfer/kernel
    rows are bucket-padded and the padding sliced off before the tree,
    so group sizes need NOT land on a tile bucket (the r4
    `hb_1024_real` finding: 974-point groups never do).  On a real
    TPU outside warming mode, ladder sizes without warm executables
    are skipped (smaller warm chunks take their place) so production
    never pays a cold multi-minute Mosaic compile.
    [] = no device share."""
    if n_groups <= 0 or k % n_groups:
        return []
    n = k // n_groups
    cap = _MAX_GTREE // n
    if cap == 0:
        return []  # a single group alone exceeds the proven tree scale
    rho = learned_fraction(n, n_groups)
    if rho <= 0.0:
        return []
    q = min(cap, max(1, n_groups // 16))
    m_max = n_groups // q
    if _env_fraction() is None:
        # adaptive mode: keep one device chunk at the bottom (an
        # all-host plan never reaches the finalizer's measurement at
        # all).  Full-device plans ARE allowed — the waiter thread
        # stamps the device wall directly, so the controller no longer
        # needs a host tail to infer the device rate from (the r4
        # reserved-host-chunk rule capped the share at 87.5% and the
        # headline shipped below its own device-only leg).  Only the
        # HOST rate goes unmeasured under a full plan; once it is
        # _HOST_PROBE_IV flushes stale, hand one quantum back to host
        # to refresh it.
        m = max(1, min(int(round(n_groups * min(rho, 1.0) / q)), m_max))
        if q * m >= n_groups:
            if m < 2:
                # a single-chunk plan covering everything (one group,
                # or one quantum spanning all groups) can neither be
                # balanced nor host-probed: stay host-side
                return []
            with _STATE_LOCK:
                st = _rho_state().get("%d:%d" % (n, n_groups))
                hage = st.get("hage", 0) if isinstance(st, dict) else 0
            if hage >= _HOST_PROBE_IV:
                m -= 1
    else:
        m = min(int(round(n_groups * min(rho, 1.0) / q)), m_max)
    if m <= 0:
        return []
    # pack the m quanta into the fewest available chunks, largest-first
    sizes = []
    engine = _product_engine()
    check_warm = engine != "interp" and not _allow_compile()
    compressed = _use_compressed() and engine == "pallas"
    for mult in _CHUNK_LADDER:
        c = q * mult
        if c > cap or c > m * q:
            continue
        if check_warm and not _product_ready(c * n, c, compressed, engine):
            continue
        sizes.append(c)
    if not sizes:
        sizes = [q]
    plan: List[int] = []
    rem = m * q
    for c in sizes:
        while rem >= c:
            plan.append(c)
            rem -= c
    # rem only stays nonzero when warm-filtering dropped the quantum
    # size itself; the caller's readiness check then routes host-side
    return plan


# ---------------------------------------------------------------------------
# Mesh (multi-chip) product plane — ISSUE 7 tentpole
# ---------------------------------------------------------------------------
# A mesh-configured backend shards the device share of a product flush
# across the 1-D named-axis mesh (``parallel/mesh.sharded_product_msm_fn``)
# instead of running single-device chunks: the point axis splits
# WITHIN every group, each shard computes its slice of every group's
# inner sum, and the [G, 3, L] partials ring-reduce on device.  The
# chunk ladder disappears (ONE sharded launch — the sharded
# ``device_put`` pays the tunnel once and PJRT splits it per device);
# everything else — staging leases, the rho controller, warm-shape
# prewarm, the waiter/finalizer protocol — is the same machinery,
# threaded through, not forked.


def _mesh_backend_ok() -> bool:
    """The sharded flush engages on a real TPU mesh, or on a virtual
    CPU mesh when ``HBBFT_TPU_MESH_CPU=1`` (tier-1 mesh tests and the
    bench's per-device-count scaling children; plain CPU runs keep the
    single-device path so default behavior is unchanged)."""
    return (
        jax.default_backend() == "tpu"
        or os.environ.get("HBBFT_TPU_MESH_CPU", "0") == "1"
    )


def _mesh_engine() -> str:
    """Per-shard compute engine: the cached windowed Pallas kernel on
    real TPUs, the XLA bit-serial scan on CPU meshes (interpret-mode
    Pallas is orders slower and the XLA scan compiles in seconds —
    results are byte-identical either way)."""
    return "pallas" if jax.default_backend() == "tpu" else "xla"


def _mesh_shard_rows(n: int, g_dev: int, n_dev: int, engine: str):
    """(n_shard, kd_shard, kp_shard) of ONE shard's block: ``g_dev``
    groups × ``ceil(n/n_dev)`` rows each, bucket-padded to the tile
    grid for the Pallas engine (the XLA scan takes any row count)."""
    n_shard = -(-n // n_dev)
    kd_shard = g_dev * n_shard
    kp_shard = _bucket_rows(kd_shard) if engine == "pallas" else kd_shard
    return n_shard, kd_shard, kp_shard


def _mesh_exec_keys(n: int, g_dev: int, n_dev: int, engine: str):
    """``(name, key_parts)`` of the ONE sharded executable a mesh flush
    of ``g_dev`` groups needs — shared by the warm-routing guard
    (:func:`_mesh_ready`) and :func:`prewarm_shapes`, mirroring the
    ``_product_exec_keys`` one-home rule for the single-device path."""
    nb = _S_BITS // 8
    _, _, kp_shard = _mesh_shard_rows(n, g_dev, n_dev, engine)
    rows = n_dev * kp_shard
    return [
        (
            "mesh_prod_g1_%dg_%dd" % (g_dev, n_dev),
            (((rows, 96), "uint8"), ((rows, nb), "uint8")),
        )
    ]


def _mesh_ready(n: int, g_dev: int, n_dev: int, engine: str) -> bool:
    if engine != "pallas" and not pallas_ec.exec_cache_active():
        return True  # plain-CPU XLA engine: no exec-cache gate
    return all(
        pallas_ec.exec_available(nm, p)
        for nm, p in _mesh_exec_keys(n, g_dev, n_dev, engine)
    )


def _mesh_plan(
    k: int, n_groups: int, n_dev: int, engine: str, assume_warm: bool = False
) -> int:
    """How many LEADING groups of a uniform product flush run on the
    mesh (the rest host-side) — the mesh analogue of
    :func:`_split_plan`.  The device share is ONE sharded launch; the
    single-device chunk ladder existed to balance per-chunk tunnel
    RPCs, which the sharded transfer pays exactly once.  The rho
    controller's balance is learned per device count
    (``_shape_key(..., mesh_dev)``); the per-SHARD group tree must stay
    within the proven ``_MAX_GTREE`` row scale.  0 = no mesh share.
    ``assume_warm`` skips the cold-executable guard — the prewarm plan
    enumerates what routing WILL demand once warm, so it must see the
    pick even before the first ``.palexe`` lands on disk."""
    if n_groups <= 0 or k % n_groups:
        return 0
    n = k // n_groups
    n_shard = -(-n // n_dev)
    cap = _MAX_GTREE // max(1, n_shard)
    if cap == 0:
        return 0  # one group's shard slice alone exceeds the tree scale
    rho = learned_fraction(n, n_groups, mesh_dev=n_dev)
    if rho <= 0.0:
        return 0
    g_dev = min(n_groups, cap, max(1, int(round(n_groups * min(rho, 1.0)))))
    if _env_fraction() is None and g_dev >= n_groups and n_groups > 1:
        # full-mesh plan: the host rate goes unmeasured — once stale,
        # hand one group back to host to refresh it (same probe rule
        # as the single-device planner)
        with _STATE_LOCK:
            st = _rho_state().get(_shape_key(n, n_groups, n_dev))
            hage = st.get("hage", 0) if isinstance(st, dict) else 0
        if hage >= _HOST_PROBE_IV:
            g_dev -= 1
    if (
        not assume_warm
        and not _allow_compile()
        and not _mesh_ready(n, g_dev, n_dev, engine)
    ):
        return 0  # cold sharded executable: flush runs host-side
    return g_dev


def _put_shard_blocks(
    rows: np.ndarray,
    n: int,
    g_dev: int,
    n_dev: int,
    engine: str,
    mesh,
    lease: Optional[staging.Lease] = None,
    width: int = 96,
):
    """Group-major ``[g_dev·n, width]`` u8 rows → the sharded block
    layout of ``parallel.mesh.sharded_product_msm_fn``: shard j holds
    rows ``[j·n_shard, (j+1)·n_shard)`` of every group (group-major
    within the shard), zero rows padding both the group remainder and
    the Pallas tile bucket (all-zero wire = infinity, zero scalar = 0 —
    absorbing either way).  One sharded ``device_put`` starts the
    transfer; PJRT splits it per device.  With a ``lease`` the block
    buffer comes zeroed from the staging pool and is retired by the
    finalizer once the device results materialize — the same
    provably-safe reuse protocol as the single-device chunks."""
    from jax.sharding import NamedSharding, PartitionSpec

    from ..parallel import mesh as M

    n_shard, kd_shard, kp_shard = _mesh_shard_rows(n, g_dev, n_dev, engine)
    shape = (n_dev * kp_shard, width)
    buf = (
        lease.get(shape)
        if lease is not None
        else np.zeros(shape, dtype=np.uint8)
    )
    src = rows.reshape(g_dev, n, width)
    for j in range(n_dev):
        lo = j * n_shard
        cnt = min(n_shard, n - lo)
        if cnt <= 0:
            break  # trailing shards hold only padding (n < n_dev)
        dst = buf[j * kp_shard : j * kp_shard + kd_shard].reshape(
            g_dev, n_shard, width
        )
        dst[:, :cnt] = src[:, lo : lo + cnt]
    return jax.device_put(buf, NamedSharding(mesh, PartitionSpec(M.AXIS)))


# ---------------------------------------------------------------------------
# Persistent warm-start: flush-shape memory + background prewarm
# ---------------------------------------------------------------------------
# The controller persists the learned split (device_fraction.json) and
# the executables persist as .palexe files — but a fresh process still
# paid the deserialize + device-load wall for EVERY executable inside
# its first flush (the r05 32.8 s cold flush vs the 1.42 s warm
# median).  So also persist the SET of flush shapes that actually
# shipped a device plan, and let the backend prewarm their executables
# on a background thread during DKG/setup: the first flush then starts
# at the converged split AND with warm executables.

_WARM_SEEN: set = set()  # shapes recorded this process (dedupe disk writes)
_PREWARM: Optional[Any] = None  # the background prewarm thread, once kicked


def _warm_shapes_path() -> str:
    return os.path.join(pallas_ec._exec_cache_dir(), "warm_shapes.json")


# warm_shapes.json schema: version 2 wraps the per-shape dict in
# {"version": 2, "shapes": {...}, "flat": [[kp, nb, "g1"|"g2"], ...]}
# so the flat MSM paths (batch_verify_shares, DKG G2) prewarm too.
# Version-1 files (a bare shapes dict) load transparently; entries
# whose key/format predates the PR-7 mesh keys parse per-entry
# tolerant and are PRUNED on the next rewrite (stale keys used to
# linger forever and bloat the prewarm plan).
_WARM_SCHEMA = 2


def _load_warm_file() -> dict:
    """The full warm-shapes document, normalized to the v2 schema —
    per-entry tolerant, like ``_rho_state`` (one malformed entry must
    not drop the rest; a malformed entry is also GONE after the next
    ``_write_warm``, which is the tolerate-and-prune half)."""
    import json

    doc: dict = {"version": _WARM_SCHEMA, "shapes": {}, "flat": []}
    try:
        with open(_warm_shapes_path()) as fh:
            raw = json.load(fh)
    except Exception:
        return doc
    if not isinstance(raw, dict):
        return doc
    shapes = raw.get("shapes") if "shapes" in raw else raw  # v1: bare dict
    for k, v in shapes.items() if isinstance(shapes, dict) else ():
        try:
            n, g = (int(x) for x in str(k).split(":"))
            if n > 0 and g > 0:
                mesh: List[int] = []
                if isinstance(v, dict):
                    for d in v.get("mesh") or ():
                        if int(d) > 1:
                            mesh.append(int(d))
                ent = {
                    "compressed": bool(v.get("compressed"))
                    if isinstance(v, dict)
                    else False,
                }
                if mesh:  # absent = single-device only: the seed's
                    ent["mesh"] = sorted(set(mesh))  # format, unchanged
                doc["shapes"]["%d:%d" % (n, g)] = ent
        except (TypeError, ValueError):
            continue
    for ent in raw.get("flat") or ():
        try:
            kp, nb, grp = int(ent[0]), int(ent[1]), str(ent[2])
            if kp > 0 and nb > 0 and grp in ("g1", "g2"):
                row = [kp, nb, grp]
                if row not in doc["flat"]:
                    doc["flat"].append(row)
        except (TypeError, ValueError, IndexError):
            continue
    return doc


def _write_warm(doc: dict) -> None:
    """Atomic v2-format rewrite (call under ``_STATE_LOCK``)."""
    import json

    doc = dict(doc)
    doc["version"] = _WARM_SCHEMA
    path = _warm_shapes_path()
    tmp = path + ".tmp.%d" % os.getpid()
    with open(tmp, "w") as fh:
        json.dump(doc, fh)
    os.replace(tmp, path)


def _load_warm_shapes() -> dict:
    """``{"n:n_groups": {"compressed": bool, "mesh": [n_dev, …]}}`` —
    the product-shape half of the warm file (the historical return
    shape; flat shapes ride :func:`_load_warm_file`)."""
    return _load_warm_file()["shapes"]


def record_warm_shape(
    n: int, n_groups: int, compressed: bool, mesh_dev: int = 0
) -> None:
    """Remember that shape ``(n, n_groups)`` shipped a device plan, so
    the NEXT process can prewarm its executables before its first
    flush.  Read-merge-replace keeps other processes' entries; a
    compressed sighting is sticky (both transfer modes get prewarmed
    once a shape has probed compression), and so is a mesh device
    count (a mesh deployment keeps its per-device-count sharded
    executable warm across restarts).  Best-effort throughout —
    losing the hint only costs one cold-start first flush.  The whole
    dedupe + read-merge-replace runs under ``_STATE_LOCK`` so two
    concurrent flushes can't interleave their merges and drop each
    other's entries."""
    seen_key = ("%d:%d" % (n, n_groups), bool(compressed), int(mesh_dev))
    with _STATE_LOCK:
        if seen_key in _WARM_SEEN:
            return
        _WARM_SEEN.add(seen_key)
        try:
            doc = _load_warm_file()
            ent = doc["shapes"].setdefault(seen_key[0], {"compressed": False})
            ent["compressed"] = bool(ent.get("compressed")) or bool(compressed)
            if mesh_dev > 1:
                ent["mesh"] = sorted(set(ent.get("mesh") or []) | {mesh_dev})
            _write_warm(doc)
        except Exception:
            pass


def record_flat_shape(kp: int, nb: int, g2: bool = False) -> None:
    """Remember one FLAT chunk shape that shipped to the device
    (``batch_verify_shares``/epoch aggregation G1, the DKG plane's G2)
    so the prewarm plan covers it — flat shapes used to be invisible
    to the prewarmer and recompiled cold every process (the CPU-AOT
    cold wall's biggest term, and a real TPU restart's unpack/tree
    reload wall)."""
    seen_key = ("flat", int(kp), int(nb), bool(g2))
    with _STATE_LOCK:
        if seen_key in _WARM_SEEN:
            return
        _WARM_SEEN.add(seen_key)
        try:
            doc = _load_warm_file()
            row = [int(kp), int(nb), "g2" if g2 else "g1"]
            if row not in doc["flat"]:
                doc["flat"].append(row)
                _write_warm(doc)
        except Exception:
            pass


def prewarm_plan() -> list:
    """Every ``(name, key_parts)`` the recorded warm state implies for
    the CURRENT backend — the ONE enumeration shared by
    :func:`prewarm_shapes` (which preloads each entry and GCs the rest)
    and the tier-1 completeness test (which asserts every shape the
    epoch driver can emit appears here), so a future shape addition
    that skips the plan fails a test instead of silently reintroducing
    a cold compile.

    Covers, per recorded product shape: the chunk plan at the
    PERSISTED split (``device_fraction.json``) via the same
    ``_split_plan`` routing uses, BOTH transfer modes when the shape
    has probed compression (the controller's periodic mode probe can
    flip at any flush), and the per-device-count mesh exec keys; plus
    every recorded flat chunk shape (G1 and the DKG plane's G2)."""
    engine = _product_engine()
    doc = _load_warm_file()
    keys: list = []
    for skey, ent in sorted(doc["shapes"].items()):
        try:
            n, n_groups = (int(x) for x in skey.split(":"))
        except ValueError:
            continue
        plan = _split_plan(n * n_groups, n_groups)
        modes = (
            {False, bool(ent.get("compressed"))}
            if engine == "pallas"
            else {False}
        )
        for g in plan:
            for compressed in sorted(modes):
                keys.extend(
                    _product_exec_keys(g * n, g, compressed, engine)
                )
        # mesh deployments: the per-device-count sharded executables at
        # the g_dev the planner would pick today (the _mesh_exec_keys
        # one home keeps this exactly what routing will require)
        m_engine = _mesh_engine()
        for n_dev in ent.get("mesh") or ():
            g_dev = _mesh_plan(
                n * n_groups, n_groups, n_dev, m_engine, assume_warm=True
            )
            if not g_dev:
                continue  # rho=0 or over tree scale: nothing routable
            keys.extend(_mesh_exec_keys(n, g_dev, n_dev, m_engine))
    for kp, nb, grp in doc["flat"]:
        keys.extend(_flat_exec_keys(kp, nb, grp == "g2", engine))
    seen: set = set()
    out: list = []
    for name, parts in keys:
        if (name, parts) not in seen:
            seen.add((name, parts))
            out.append((name, parts))
    return out


# ``.palexe`` families OWNED by the prewarm plan — eligible for GC
# when no longer reachable from it.  Shared families (win_*, tree_*,
# scan_*) serve non-flush MSM paths too and are never touched.
_GC_FAMILIES = (
    "unpack_g1_v1-",
    "unpack_g1_v2-",
    "unpack_g1c_v1-",
    "unpack_g1c_v2-",
    "unpack_g2_v1-",
    "prod_g1_xla_",
    "flat_g1_xla-",
    "flat_g2_xla-",
    "mesh_prod_g1_",
    "gtree_g1_",
)


def _gc_palexe(reachable_fnames) -> int:
    """Garbage-collect ``.palexe`` files no longer reachable from the
    prewarm plan (stale shapes, pre-PR-7 key formats, renamed
    programs).  Deliberately narrow: only files whose key suffix
    matches THIS process (jax version + device kind — other backends'
    caches are not ours to judge) and whose name family the plan owns
    (``_GC_FAMILIES``).  Best-effort; returns how many were removed."""
    tail = (
        "-".join(
            str(p)
            for p in (jax.__version__, jax.devices()[0].device_kind)
        ).replace(" ", "").replace("/", "_")
        + ".palexe"
    )
    reach = set(reachable_fnames)
    removed = 0
    try:
        d = pallas_ec._exec_cache_dir()
        for fname in os.listdir(d):
            if not fname.endswith(tail) or fname in reach:
                continue
            if not fname.startswith(_GC_FAMILIES):
                continue
            try:
                os.remove(os.path.join(d, fname))
                removed += 1
            except OSError:
                pass
    except Exception:
        pass
    return removed


def prewarm_shapes() -> int:
    """Bring every planned executable disk → memory, WITHOUT compiling
    (``pallas_ec.preload_exec``), then GC the unreachable ``.palexe``
    files of the plan-owned families.  The plan is
    :func:`prewarm_plan` — exactly what the first flush will route, by
    construction.  Returns how many executables are warm in memory
    afterwards; a missing ``.palexe`` simply stays cold and routing
    falls back exactly as before."""
    warm = 0
    reachable = []
    for name, parts in prewarm_plan():
        reachable.append(
            pallas_ec._exec_fname(pallas_ec._exec_key(name, parts))
        )
        if pallas_ec.preload_exec(name, parts):
            warm += 1
    _gc_palexe(reachable)
    return warm


def start_background_prewarm() -> Optional[Any]:
    """Kick ONE daemon thread per process deserializing the recorded
    shapes' executables while DKG/setup runs on the main thread (the
    natural dead time before the first flush).  Idempotent; returns
    the thread (or the one already started).  Safe to race with the
    first flush: ``preload_exec`` and ``cached_compiled`` both write
    ``_EXEC_MEM`` under ``pallas_ec._EXEC_LOCK`` and a duplicate load
    is only wasted work, never a wrong result."""
    global _PREWARM
    if _PREWARM is not None:
        return _PREWARM
    with _STATE_LOCK:
        if _PREWARM is not None:
            return _PREWARM
        th = threading.Thread(
            target=prewarm_shapes, name="hbbft-prewarm", daemon=True
        )
        _PREWARM = th
    th.start()
    return th


class ShippedPoints:
    """Points being marshalled and (asynchronously) shipped to the
    device — ``backend.g1_ship``'s handle.  Keeps the host list so any
    fallback path can still reach the original objects.

    The plan/transfer-mode/warm-executable ROUTING decisions are made
    synchronously (cheap, and callers key the host-vs-device decision
    off ``self.plan``); the marshalling itself — batch-affine wire
    encoding plus per-chunk pad/compress/``device_put`` — runs as a
    staged task on the flush pipeline's FIFO worker, overlapping the
    caller's transcript/serialization work instead of walling the
    flush (the r05 7.5 s ``ship`` wall).  ``g1_msm_product_async``
    resolves the task inside its own staged launch (FIFO ⇒ the ship
    task has completed by then); marshalling errors re-raise at the
    finalizer, exactly where the sequential path surfaced them.

    In compressed mode only the x coordinates cross the tunnel, plus
    two packed bit-rows (y parity, infinity flag); y is recovered on
    device.  The transfer starts ONLY for the device chunks of the
    factored product plan (uniform groups, warm executables) — each
    chunk ships bucket-padded exactly as the product path will consume
    it, so no byte crosses the tunnel twice."""

    def __init__(
        self,
        points: List[Any],
        group_sizes: Optional[Sequence[int]] = None,
        mesh=None,
    ):
        self.points = points
        self.compressed = False
        self.plan: List[int] = []
        self.task: Optional[staging.StageTask] = None  # → [(g, kd, dev, dev_meta)]
        self.lease = staging.buffers().lease()
        self.g_dev = 0
        self.k_dev = 0
        self.mesh = None  # set iff the mesh plan took this flush
        self.mesh_engine: Optional[str] = None
        k = len(points)
        uniform = bool(group_sizes) and len(set(group_sizes)) == 1
        mesh_dev = mesh.devices.size if mesh is not None else 0
        if mesh_dev > 1 and _mesh_backend_ok() and uniform:
            # mesh plan: the device share ships as per-shard blocks in
            # ONE sharded transfer (always the uncompressed 96-byte
            # wire — the sharded program keeps one executable per
            # device count instead of two)
            n = k // len(group_sizes)
            engine = _mesh_engine()
            g_dev = _mesh_plan(k, len(group_sizes), mesh_dev, engine)
            if g_dev:
                self.mesh = mesh
                self.mesh_engine = engine
                self.g_dev = g_dev
                self.k_dev = g_dev * n
                k_dev, lease = self.k_dev, self.lease

                def _marshal_mesh():
                    return _put_shard_blocks(
                        g1_wires_batch(points[:k_dev]),
                        n, g_dev, mesh_dev, engine, mesh, lease,
                    )

                self.task = staging.stager().submit(_marshal_mesh)
                return
            # no mesh share (cold executable / rho=0): fall through to
            # the single-device plan below, which on a CPU mesh stays
            # empty (backend guard) — the flush runs host-side
        engine = _product_engine()
        if engine == "interp" or not uniform:
            return
        n = k // len(group_sizes)
        plan = _split_plan(k, len(group_sizes))
        if not plan:
            return
        # transfer mode: measured per shape (controller "d" vs "dc"
        # EMAs, periodic trial) unless HBBFT_TPU_COMPRESS pins it.
        # The XLA engine always ships the 96-byte wire (its fused
        # program unpacks uncompressed; compression is a TPU
        # tunnel-bandwidth trade).
        self.compressed = engine == "pallas" and _choose_compressed(
            n, len(group_sizes), plan
        )
        if not _allow_compile() and not all(
            _product_ready(g * n, g, self.compressed, engine)
            for g in plan
        ):
            return  # cold shapes — the flush will run host-side
        self.plan = plan
        self.g_dev = sum(plan)
        self.k_dev = self.g_dev * n
        k_dev, compressed, lease = self.k_dev, self.compressed, self.lease

        def _marshal():
            # only the device prefix is marshalled: the host tail goes
            # through native Pippenger's own (memoized) wire encoding,
            # so serializing it here would be pure wasted flush time
            wires = g1_wires_batch(points[:k_dev])
            chunks = []
            lo = 0
            for g in plan:
                kd = g * n
                dev, dev_meta = _put_chunk(
                    wires[lo : lo + kd], kd, compressed, lease
                )
                chunks.append((g, kd, dev, dev_meta))
                lo += kd
            return chunks

        self.task = staging.stager().submit(_marshal)


def _put_chunk(
    wires: np.ndarray,
    kd: int,
    compressed: bool,
    lease: Optional[staging.Lease] = None,
):
    """Start one device chunk's transfer — (dev, dev_meta); the ONE
    home for the compress/ship step shared by the eager
    (``ShippedPoints``) and lazy (``g1_msm_product_async`` fallback)
    marshalling paths.  v2 wire discipline: the transfer carries
    EXACTLY the ``kd`` live rows — bucket padding to ``kp`` happens ON
    DEVICE inside the v2 unpack programs (``_unpack_fn_v2`` /
    ``_unpack_fn_compressed_v2``), so the tunnel never ships padding
    bytes and the host never touches a pad buffer.  With a ``lease``
    the compressed x-block comes preallocated from the staging pool
    (retired by the finalizer once the device results materialize —
    i.e. once the transfer provably completed)."""
    if compressed:
        x, meta = compress_rows_v2(wires, lease)
        return jax.device_put(x), jax.device_put(meta)
    return jax.device_put(wires), None


def compress_rows_v2(
    wires: np.ndarray, lease: Optional[staging.Lease] = None
) -> tuple:
    """[k, 96] wires → ([k, 48] x bytes, [k] meta bytes).  Meta bit 0
    is y parity (last wire byte & 1), bit 1 the infinity flag
    (all-zero wire — ``native.g1_wire``'s encoding).  Unlike the v1
    ``compress_rows`` there is no bucket padding and no host packbits:
    exact rows cross the tunnel and the device pads with meta value 2
    (infinity) in ``_unpack_fn_compressed_v2``."""
    k = wires.shape[0]
    x = (
        lease.get((k, 48))
        if lease is not None
        else np.empty((k, 48), dtype=np.uint8)
    )
    x[:] = wires[:, :48]
    meta = (wires[:, 95] & 1) | (
        (wires == 0).all(axis=1).astype(np.uint8) << 1
    )
    return x, meta


def compress_rows(
    wires: np.ndarray, kp: int, lease: Optional[staging.Lease] = None
) -> tuple:
    """[k, 96] wires → ([kp, 48] x bytes, [2, kp/8] packed meta bits).
    Padding rows (k..kp) are flagged infinity.  Meta row 0 is y parity
    (last wire byte & 1), row 1 the infinity/padding flag (all-zero
    wire — ``native.g1_wire``'s encoding)."""
    k = wires.shape[0]
    x = (
        lease.get((kp, 48))
        if lease is not None
        else np.zeros((kp, 48), dtype=np.uint8)
    )
    x[:k] = wires[:, :48]
    parity = np.zeros(kp, dtype=np.uint8)
    parity[:k] = wires[:, 95] & 1
    inf = np.ones(kp, dtype=np.uint8)
    inf[:k] = (wires == 0).all(axis=1)
    meta = np.stack([np.packbits(parity), np.packbits(inf)])
    return x, meta


def ship_points(
    points: Sequence[Any],
    group_sizes: Optional[Sequence[int]] = None,
    mesh=None,
) -> ShippedPoints:
    return ShippedPoints(list(points), group_sizes, mesh=mesh)


class ProductFinalizer:
    """Callable finalizer handle with a non-blocking readiness probe
    and a double-buffering drain.

    ``fin()`` blocks exactly like the plain closure it replaces (host
    Pippenger tail, then the device drain); ``fin.ready()`` /
    ``fin.poll()`` report — without blocking — whether the device
    results have already materialized, so a driver can interleave
    other work (serializing the next round's obligations, the epoch
    pipeline's staging) until the drain completes instead of sitting
    inside ``agg_share_fin()``.

    ``fin.start_drain()`` moves the whole finalizer body — host
    Pippenger tail AND the materializing device fetch — onto a daemon
    thread, so flush k's finalize overlaps flush k+1's launch instead
    of serializing behind it (the r05 11.7 s cold ``finalize`` wall).
    A later ``fin()`` just joins the drain.  Idempotent and memoizing
    either way: the body runs exactly once; a failure re-raises at
    EVERY subsequent call (same surfacing point as the synchronous
    path, never swallowed by the thread)."""

    __slots__ = ("_fn", "_probe", "_done", "_result", "_err", "_lock", "_drain")

    def __init__(self, fn: Callable[[], Any], probe: Optional[Callable[[], bool]] = None):
        self._fn = fn
        self._probe = probe
        self._done = False
        self._result: Any = None
        self._err: Optional[BaseException] = None
        self._lock = threading.Lock()
        self._drain: Optional[threading.Thread] = None

    def _run(self):
        # sole writer of the memo: _run executes only on the one drain
        # thread start_drain creates under its lock, so two bodies can
        # never race
        try:
            res = self._fn()
        except BaseException as e:
            self._err = e
            self._done = True
            return
        self._result = res
        self._done = True

    def start_drain(self) -> "ProductFinalizer":
        """Begin (or adopt) the background drain; returns self."""
        with self._lock:
            if self._done or self._drain is not None:
                return self
            th = threading.Thread(
                target=self._run, name="hbbft-msm-drain", daemon=True
            )
            self._drain = th
        th.start()
        return self

    def __call__(self):
        th = self.start_drain()._drain
        if th is not None:
            th.join()
        if self._err is not None:
            raise self._err
        return self._result

    def ready(self) -> bool:
        if self._done:
            return True
        return bool(self._probe()) if self._probe is not None else True

    poll = ready


def _group_tree(prods: jnp.ndarray, n_groups: int) -> jnp.ndarray:
    """[K, 3, L] (group-major, uniform group size) → [G, 3, L]: one
    log₂ tree per group, all groups in parallel (the group axis rides
    the kernel's batch dims)."""
    from . import ec_jax

    K = prods.shape[0]
    n = K // n_groups
    kern = ec_jax.g1_kernel()
    x = jnp.swapaxes(
        prods.reshape(n_groups, n, *prods.shape[1:]), 0, 1
    )  # [n, G, 3, L]
    m = 1
    while m < n:
        m <<= 1
    if m != n:
        x = jnp.concatenate(
            [x, kern.identity((m - n, n_groups))], axis=0
        )
    while x.shape[0] > 1:
        h = x.shape[0] // 2
        x = kern.add(x[:h], x[h:])
    return x[0]


@functools.lru_cache(maxsize=None)
def _group_tree_jit():
    return jax.jit(_group_tree, static_argnums=(1,))


def _group_tree_device(prods, n_groups: int):
    if jax.default_backend() == "tpu":
        return pallas_ec.cached_compiled(
            "gtree_g1_%d" % n_groups,
            functools.partial(_group_tree, n_groups=n_groups),
            prods,
        )
    return _group_tree_jit()(prods, n_groups)


def g1_msm_product_async(
    points,
    s_coeffs: Sequence[int],
    t_coeffs: Sequence[int],
    group_sizes: Sequence[int],
    interpret: Optional[bool] = None,
    mesh=None,
) -> Optional[Callable[[], Any]]:
    """Factored-form HYBRID MSM (``backend.g1_msm_product_async``
    semantics): the leading ``sum(plan)`` groups run on the device in
    uniform-shape chunks (packed transfer → windowed kernel →
    bucket-padding slice → per-group trees), the rest run native host
    Pippenger INSIDE the finalizer while the device chunks are in
    flight — both engines busy simultaneously, split at the measured
    balance point (``learned_fraction`` / ``_adapt``).
    Returns ``None`` when no conforming device share exists
    (non-uniform group sizes, a single group past the tree scale, cold
    executables) and the caller falls back to the flat/host path.

    Exactness: equal to the flat ``Σ (sᵢ·t_g mod r)·Pᵢ`` on r-torsion
    points (scalars act mod r there); see the backend docstring for
    the off-subgroup discussion."""
    from ..crypto.backend import CpuBackend
    from ..crypto import fields as F
    from . import ec_jax

    shipped = points if isinstance(points, ShippedPoints) else None
    pts_list = shipped.points if shipped else list(points)
    k = len(pts_list)
    sizes = set(group_sizes)
    if not pts_list or len(sizes) != 1:
        return None
    n = sizes.pop()
    n_groups = len(group_sizes)
    if n * n_groups != k:
        return None
    if interpret is None:
        engine = _product_engine()
    else:
        # explicit override (tests, hardware smoke): True pins the
        # interpreter, False pins the Pallas engine
        engine = "interp" if interpret else "pallas"
    interpret = engine != "pallas"

    mesh_dev = 0
    mesh_engine: Optional[str] = None
    if shipped is not None:
        # routing off the synchronously-computed plan: the staged
        # marshal may still be in flight, and must not be waited on
        # here — the launch below resolves it on the FIFO worker
        if shipped.mesh is not None:
            mesh = shipped.mesh
            mesh_dev = mesh.devices.size
            mesh_engine = shipped.mesh_engine
            g_dev = shipped.g_dev
            plan = []
            compressed = False  # the sharded transfer is always 96-byte
            ship_task = shipped.task
        else:
            plan = shipped.plan
            compressed = shipped.compressed
            ship_task = shipped.task
            if not plan:
                return None
            g_dev = sum(plan)
    elif (
        mesh is not None and mesh.devices.size > 1 and _mesh_backend_ok()
    ):
        mesh_dev = mesh.devices.size
        mesh_engine = _mesh_engine()
        g_dev = _mesh_plan(k, n_groups, mesh_dev, mesh_engine)
        if not g_dev:
            return None
        plan = []
        compressed = False
        ship_task = None
    else:
        plan = _split_plan(k, n_groups)
        if not plan:
            return None
        compressed = engine == "pallas" and _choose_compressed(
            n, n_groups, plan
        )
        if (
            engine != "interp"
            and not _allow_compile()
            and not all(
                _product_ready(g * n, g, compressed, engine)
                for g in plan
            )
        ):
            return None
        ship_task = None
        g_dev = sum(plan)

    nb = _S_BITS // 8
    k_dev = g_dev * n
    # snapshots against caller mutation: the marshalling below runs on
    # the staging worker after this call returns
    s_head = list(s_coeffs[:k_dev])
    s_tail = list(s_coeffs[k_dev:])
    t_list = list(t_coeffs)
    host_pts = pts_list[k_dev:]
    lease = staging.buffers().lease()

    if engine != "interp":
        # this shape shipped a real device plan: remember it so the
        # next process can prewarm its executables during setup
        record_warm_shape(n, n_groups, compressed, mesh_dev=mesh_dev)

    import time

    t_call = time.perf_counter()

    def _launch():
        # Staged dispatch: scalar marshalling, pad-to-bucket, and the
        # non-blocking device_puts all run on the pipeline's FIFO
        # worker, overlapping the caller's G2 MSMs/transcript work —
        # the r05 12.7 s ``launch`` wall.  FIFO ⇒ a ShippedPoints
        # marshal submitted earlier has completed; ``result()``
        # re-raises its errors here, which the waiter carries to the
        # finalizer (same surfacing point as the sequential path).
        if mesh_dev:
            # sharded engine: ONE launch over the whole device share —
            # the sharded device_put pays the transfer once and PJRT
            # splits it per shard, so there is no chunk ladder here
            from ..parallel import mesh as M

            dev_wires = (
                ship_task.result()
                if ship_task is not None
                else _put_shard_blocks(
                    g1_wires_batch(pts_list[:k_dev]),
                    n, g_dev, mesh_dev, mesh_engine, mesh, lease,
                )
            )
            sc = scalar_bytes_batch(s_head, nb)
            dev_sc = _put_shard_blocks(
                sc, n, g_dev, mesh_dev, mesh_engine, mesh, lease,
                width=nb,
            )
            _, kd_shard, _ = _mesh_shard_rows(
                n, g_dev, mesh_dev, mesh_engine
            )
            run = M.sharded_product_msm_fn(
                mesh, g_dev, kd_shard, nb, mesh_engine
            )
            return [run(dev_wires, dev_sc)], time.perf_counter()
        chunks = (
            ship_task.result()
            if ship_task is not None
            else [(g, g * n, None, None) for g in plan]
        )
        sc = scalar_bytes_batch(s_head, nb)
        gsums = []
        lo = 0
        for g, kd, dev, dev_meta in chunks:
            # v2 wire discipline: EXACT kd scalar rows cross the
            # tunnel too — the device unpack pads both operands to the
            # kp bucket (zero scalar rows contribute identity)
            dev_sc = jax.device_put(sc[lo : lo + kd])
            if dev is None:  # lazy marshalling (no ShippedPoints handle)
                dev, dev_meta = _put_chunk(
                    g1_wires_batch(pts_list[lo : lo + kd]),
                    kd, compressed, lease,
                )
            if engine == "xla":
                # ONE fused program per chunk: device-side unpack →
                # scalar ladder → per-group trees, no tile round-trip
                gsums.append(
                    pallas_ec.cached_compiled(
                        "prod_g1_xla_%d" % g,
                        _prod_xla_fn(g),
                        dev,
                        dev_sc,
                        donate=(0, 1),
                    )
                )
                lo += kd
                continue
            kp = _bucket_rows(kd)
            # _put_chunk returns meta iff compressed, on both paths
            if dev_meta is not None:
                pts_t, dig_t = _unpack_compressed_device_v2(
                    dev, dev_meta, dev_sc
                )
            else:
                pts_t, dig_t = _unpack_device_v2(dev, dev_sc)
            out_t = pallas_ec._windowed_tiles(pts_t, dig_t, interpret)
            prods = pallas_ec._untile(out_t, kd, kp)  # slice the padding
            gsums.append(_group_tree_device(prods, g))
            lo += kd
        # dispatch-end stamp: t_dev below keeps the same semantics as
        # the sequential path (dispatch done → group sums materialize)
        return gsums, time.perf_counter()

    launch_task = staging.stager().submit(_launch)

    # Waiter thread: stamp the wall at which the device group sums
    # actually materialize on host.  The fetched arrays are tiny
    # ([G, 3, L] int32 per chunk) and the main thread spends the same
    # window in native Pippenger (ctypes releases the GIL), so the
    # fetch runs genuinely concurrently.  This is the controller's
    # exact device-rate sample — through the tunnel,
    # ``block_until_ready`` is a no-op and only a materializing fetch
    # observes completion, so the stamp lives on its own thread instead
    # of gating the finalizer.
    waiter: dict = {"arrs": None, "t": None, "t_disp": None, "err": None}

    def _wait():
        try:
            gsums, t_disp = launch_task.result()
            waiter["t_disp"] = t_disp
            waiter["arrs"] = [np.asarray(gs) for gs in gsums]
        except BaseException as e:  # re-raised on the finalizer below
            waiter["err"] = e
        waiter["t"] = time.perf_counter()

    th = threading.Thread(target=_wait, name="hbbft-msm-wait", daemon=True)
    th.start()

    def finalize():
        # host half FIRST: native Pippenger runs while the device
        # chunks are still in flight; only then block on their results.
        # The flat coefficient products are built HERE, not at launch —
        # launch-time work delays the caller's G2 MSMs/pairings, the
        # exact overlap the async contract exists to provide.
        t_caller = time.perf_counter() - t_call
        t0 = time.perf_counter()
        host_sum = None
        if host_pts:
            host_flat = [
                (s_tail[i] * t_list[g_dev + i // n]) % F.R
                for i in range(k - k_dev)
            ]
            host_sum = CpuBackend().g1_msm(host_pts, host_flat)
        t_host = time.perf_counter() - t0
        th.join()
        # the device results materialized (or failed): every staged
        # transfer has been consumed, so the pad buffers can go back
        # to the pool for the next flush
        lease.retire()
        if shipped is not None:
            shipped.lease.retire()
        if waiter["err"] is not None:
            # surface the device failure to the flush caller with its
            # real traceback; no rate sample is recorded from a
            # failed fetch (it would poison the persisted estimate)
            raise waiter["err"]
        arrs = waiter["arrs"]
        t_dev = (waiter["t"] or time.perf_counter()) - (
            waiter["t_disp"] or t_call
        )
        if engine != "interp" and _env_fraction() is None:
            _adapt(
                n,
                n_groups,
                k_dev,
                k - k_dev,
                t_caller,
                t_host,
                t_dev,
                compressed=compressed,
                mesh_dev=mesh_dev,
            )
        group_pts = []
        for arr in arrs:
            group_pts.extend(
                ec_jax.g1_from_limbs(arr[i]) for i in range(arr.shape[0])
            )
        dev_sum = CpuBackend().g1_msm(group_pts, t_list[:g_dev])
        return dev_sum + host_sum if host_sum is not None else dev_sum

    # ready() = the device drain is over; the epoch driver uses it to
    # keep serializing the next round's obligations until the drain
    # completes instead of blocking inside the finalizer
    return ProductFinalizer(finalize, probe=lambda: not th.is_alive())


# ---------------------------------------------------------------------------
# limbprove registry (see ops/limbs.py for the convention).  These are
# the same entry points prewarm_plan() enumerates: the unpack family,
# the fused XLA product/flat programs (the Mosaic win_*/tree_*
# families are covered by the pallas_ec core specs, the scan_* family
# by the ec_jax specs — see rangecheck._PLAN_PREFIXES).


def _range_specs(rc):
    bound = (1 << (LB.LIMB_BITS + 1)) - 1
    nb = _S_BITS // 8
    kp = _bucket_rows(1)  # the smallest tile bucket (128 rows)
    kd = 4  # v2 entry points pad to the bucket on device
    byte = (0, 255)
    inv = dict(out_lo=-bound, out_hi=bound)
    return [
        rc.KernelSpec(
            "packed.unpack_g1_v1",
            _unpack_fn,
            (rc.arg((kp, 96), "uint8", *byte), rc.arg((kp, nb), "uint8", *byte)),
            **inv,
        ),
        rc.KernelSpec(
            "packed.unpack_g1_v2",
            _unpack_fn_v2,
            (rc.arg((kd, 96), "uint8", *byte), rc.arg((kd, nb), "uint8", *byte)),
            **inv,
        ),
        rc.KernelSpec(
            "packed.unpack_g1c_v1",
            _unpack_fn_compressed,
            (
                rc.arg((kp, 48), "uint8", *byte),
                rc.arg((2, kp // 8), "uint8", *byte),
                rc.arg((kp, nb), "uint8", *byte),
            ),
            **inv,
        ),
        rc.KernelSpec(
            "packed.unpack_g1c_v2",
            _unpack_fn_compressed_v2,
            (
                rc.arg((kd, 48), "uint8", *byte),
                rc.arg((kd,), "uint8", *byte),
                rc.arg((kd, nb), "uint8", *byte),
            ),
            **inv,
        ),
        rc.KernelSpec(
            "packed.unpack_g2_v1",
            _unpack_fn_g2,
            (rc.arg((kp, 192), "uint8", *byte), rc.arg((kp, nb), "uint8", *byte)),
            **inv,
        ),
        rc.KernelSpec(
            "packed.prod_g1_xla",
            _prod_xla_fn(2),
            (rc.arg((kd, 96), "uint8", *byte), rc.arg((kd, nb), "uint8", *byte)),
            **inv,
        ),
        rc.KernelSpec(
            "packed.flat_g1_xla",
            _flat_xla_fn(False),
            (rc.arg((kd, 96), "uint8", *byte), rc.arg((kd, nb), "uint8", *byte)),
            **inv,
        ),
        rc.KernelSpec(
            "packed.flat_g2_xla",
            _flat_xla_fn(True),
            (rc.arg((kd, 192), "uint8", *byte), rc.arg((kd, nb), "uint8", *byte)),
            **inv,
        ),
    ]


RANGE_SPECS = dict(
    module="ops/packed_msm.py",
    covers=(),
    specs=_range_specs,
)
